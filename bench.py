#!/usr/bin/env python
"""Headline benchmark: sim-cycle accuracy vs silicon.

Runs a correlation suite on the local TPU chip — compute-bound,
bandwidth-bound, and mixed workloads — comparing the timing engine's
estimate of each captured HLO program against fenced wall-clock measurement
of the same program on the device (the framework's whole point; north-star
from BASELINE.md: <=15% cycle error).

Prints ONE json line:
  metric       "sim_cycle_error_pct"  (mean |error| across the suite)
  value        mean absolute percent error, lower is better
  unit         "%"
  vs_baseline  value / 15.0  (the reference north-star bound; <1.0 beats it)

Robustness contract (round-2 fix; VERDICT.md "What's weak" #2): the parent
process NEVER imports jax — on this image a down axon tunnel can make
backend init hang or raise, which round 1 turned into rc=1 with no JSON.
Instead the suite runs in a subprocess (``--child``) with a hard timeout,
retried with backoff; if the live chip stays unreachable, bench falls back
to replaying committed silicon fixtures (``reports/silicon/``) through the
pure-Python engine — real measured device times, no jax import at all.  In
every terminal state exactly one JSON line goes to stdout.  The reference
bar: CI that always reports (``travis.sh:1-24``, ``util/job_launching/
monitor_func_test.py:66-75``).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent
FIXTURE_DIR = Path(
    os.environ.get("TPUSIM_BENCH_FIXTURES", REPO_ROOT / "reports" / "silicon")
)

# (workload name, build overrides, scan steps) — small programs get
# more steps so tunnel RPC jitter amortizes away
SUITE = [
    ("matmul_chain", {"m": 2048, "k": 2048, "depth": 4}, 16),   # MXU-bound
    ("elementwise_stream", {"elems": 32 * 1024 * 1024}, 16),    # HBM-bound
    ("reduction", {"rows": 4096, "cols": 4096}, 64),            # VPU+HBM
    ("mlp_train_step", {"batch": 256, "width": 1024, "depth": 2}, 64),  # mixed
    ("attention_1chip",
     {"batch": 4, "seq": 1024, "heads": 8, "head_dim": 128}, 16),
    ("conv2d", {"batch": 16, "hw": 56, "cin": 64, "cout": 64}, 16),
    ("embedding_lookup",
     {"vocab": 131072, "dim": 1024, "lookups": 8192}, 16),
    ("transcendental", {"elems": 8 * 1024 * 1024}, 16),
    ("lstm_layer", {"batch": 64, "hidden": 1024, "seq": 64}, 8),
    # the inference-serving regime: batch-small matmuls + HBM-bound
    # KV-cache attention + in-place DUS appends
    ("decode_step",
     {"batch": 8, "seq_cache": 1024, "heads": 8, "head_dim": 128,
      "layers": 2, "pos": 512}, 16),
    # mechanism-isolating ubenches (round-4 calibration): narrow-minor-dim
    # VPU lane occupancy and relayouting-copy pricing get their own
    # silicon truth instead of hiding inside mixed workloads
    ("softmax_narrow", {"batch": 8, "seq": 1024, "heads": 8}, 32),
    ("relayout_copy", {"rows": 4096, "cols": 4096}, 32),
    # quantized serving: first silicon measurement of the s8 dtype_mult
    ("matmul_int8", {"m": 4096, "n": 4096, "k": 4096}, 16),
    # the two reduce regimes decode_step exposed (round-5): wide-lane
    # tree combine (extrapolated, never measured) and major-dim serial
    # accumulation (reads -56% inside decode's context fusion)
    ("reduce_lane_wide", {"rows": 65536, "cols": 1024}, 32),
    ("reduce_major_acc", {"rows": 1024, "cols": 8192}, 32),
]

# FULL-MODEL steps, measured and reported but NEVER given to the refiner
# (VERDICT r4 #2: the reference tunes on ubenches and validates on
# applications; these are the applications).  Their manifest entries
# carry held_out=true; refine/loo exclude them, the headline includes
# them — out-of-sample by construction.
HELDOUT_SUITE = [
    ("resnet50", {"batch": 16}, 4),
    ("llama_tiny_train", {"batch": 4}, 8),
]

ATTEMPTS = int(os.environ.get("TPUSIM_BENCH_ATTEMPTS", "3"))
BACKOFF_S = (0, 30, 90)
# the child runs the tuner fits, per-workload device-time profiling, the
# replay refiner, and the 15-workload correlation suite (incl. the two
# held-out full-model steps — resnet50's cold compile is the long pole);
# sized for a cold XLA compile of every program (first compile ~20-60s)
CHILD_TIMEOUT_S = int(os.environ.get("TPUSIM_BENCH_TIMEOUT", "3000"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)



def refine_and_validate(
    tuned_info: dict | None,
    fixture_entries: list[dict],
    device_kind: str,
    fixture_dir: Path | None = None,
) -> list | None:
    """Refine the microbench overlay on the captured fixtures, then
    self-validate the result against the preset — the unattended tail of
    the live bench, factored jax-free so the round-end plumbing is
    testable offline.

    Refine: coordinate descent on the very objective the headline
    reports, so the committed overlay improves on the seed by
    construction (round-4 fix — a jointly-worse single-knob fit shipped
    once and was only caught by rejection).  Validate: replay with tuned
    vs preset config; a tuned overlay that WORSENS correlation is
    renamed ``*.rejected`` (the reference only ships tuner output as
    tested-cfgs after re-validation).  A refined overlay the validation
    never confirmed is reverted to its seed.  Returns the replay rows
    the headline must be computed from (the surviving config), or None
    to keep the live-suite points."""
    if not (tuned_info and fixture_entries):
        return None
    fixture_dir = fixture_dir or FIXTURE_DIR
    from tpusim.timing.arch import detect_arch

    arch_name = detect_arch(device_kind).name

    refine_seed_text = None
    try:
        from tpusim.harness.refine import (
            load_per_op_rows, refine_arch_on_fixtures, split_held_out,
        )

        overlay_path = REPO_ROOT / tuned_info["overlay"]
        refine_seed_text = overlay_path.read_text()
        # joint objective: e2e totals + the committed artifact's per-op
        # device durations (ten totals cannot constrain fifteen knobs;
        # the ~120 matched per-op durations can — VERDICT r4 #3).
        # Held-out full-model steps are measured and reported but NEVER
        # train the fit — not their totals, not their per-op rows
        train_entries, per_op_rows, _ = split_held_out(
            fixture_entries,
            load_per_op_rows(REPO_ROOT / "reports" / "correl_ops.json"),
        )
        rr = refine_arch_on_fixtures(
            arch_name, train_entries, fixture_dir,
            base_overlays=[overlay_path],
            per_op_rows=per_op_rows,
            # physical-prior regularization: leave-one-out measured
            # 17.7% mean held-out error unanchored vs 11.6% anchored
            # (reports/loo.json)
            anchor_weight=1.0,
        )
        if not math.isfinite(rr.final_err_pct):
            # final <= start, so an infinite FINAL means nothing ever
            # replayed (an infinite start with finite final is a
            # crash-at-seed the descent recovered from — keep that)
            raise RuntimeError(
                "no fixture workload replayed; refusing to label "
                "preset values as a fit"
            )
        # merge: refined knobs + the tuner-only fits the refiner
        # doesn't touch (host_bandwidth, ici.link_bandwidth)
        keep = [
            ln for ln in refine_seed_text.splitlines()
            if ln.startswith("-") and not any(
                ln.startswith(f"-arch.{k} ") for k in rr.values
            )
        ]
        lines = rr.overlay_lines(device_kind) + keep
        overlay_path.write_text("\n".join(lines) + "\n")
        tuned_info["refined"] = {
            "replay_err_pct": {
                "seed": round(rr.start_err_pct, 2),
                "final": round(rr.final_err_pct, 2),
            },
            **({"parts": rr.parts} if rr.parts else {}),
            "changed": {
                k: float(f"{v:.6g}") for k, v in rr.changed.items()
            },
            "evals": rr.evals,
        }
        log(f"bench: replay-refined overlay: {rr.start_err_pct:.2f}% "
            f"-> {rr.final_err_pct:.2f}% ({rr.evals} evals)")
    except Exception as e:
        log(f"bench: replay refinement FAILED (microbench fit kept): "
            f"{type(e).__name__}: {e}")

    headline_rows = None
    try:
        from tpusim.timing.config import load_config
        from tpusim.timing.engine import Engine

        means = {}
        rows_by = {}
        for label, tuned_flag in (("tuned", True), ("preset", False)):
            eng = Engine(load_config(arch=arch_name, tuned=tuned_flag))
            rows = replay_fixture_errors(
                eng, fixture_entries, fixture_dir,
            )
            if rows:
                rows_by[label] = rows
        if "tuned" in rows_by and "preset" in rows_by:
            # compare over the INTERSECTION of successfully replayed
            # workloads: pathological tuned parameters that crash the
            # replay of the worst workload must not win by averaging
            # over an easier subset
            common = (
                {r[0] for r in rows_by["tuned"]}
                & {r[0] for r in rows_by["preset"]}
            )
            for label, rows in rows_by.items():
                kept = [r for r in rows if r[0] in common]
                if kept:
                    means[label] = (
                        sum(abs(r[3]) for r in kept) / len(kept)
                    )
            dropped_t = len(rows_by["tuned"]) - len(common)
            dropped_p = len(rows_by["preset"]) - len(common)
            if dropped_t or dropped_p or not common:
                log(
                    f"bench: overlay validation subset: "
                    f"{len(common)} common workloads "
                    f"(tuned dropped {dropped_t}, preset dropped "
                    f"{dropped_p})"
                )
        else:
            log("bench: overlay validation skipped — one side "
                "returned no replayable rows")
        if "tuned" in means and "preset" in means:
            tuned_info["replay_mean_abs_err_pct"] = {
                k: round(v, 2) for k, v in means.items()
            }
            if means["tuned"] > means["preset"] + 1.0:
                op = Path(REPO_ROOT / tuned_info["overlay"])
                rejected_path = op.with_suffix(op.suffix + ".rejected")
                op.rename(rejected_path)
                tuned_info["rejected"] = True
                tuned_info["overlay"] = os.path.relpath(
                    rejected_path, REPO_ROOT
                )
                # the suite's points were simulated WITH the bad
                # overlay; the headline must reflect the config that
                # survives (the preset replay, same silicon truths)
                headline_rows = rows_by["preset"]
                log(
                    f"bench: tuned overlay REJECTED (replay "
                    f"{means['tuned']:.1f}% vs preset "
                    f"{means['preset']:.1f}%); kept as {op}.rejected"
                )
            else:
                if tuned_info.get("refined"):
                    # the suite's live sims predate the refinement;
                    # the headline must reflect the overlay that is
                    # actually committed (same engine, same truths)
                    headline_rows = rows_by["tuned"]
                log(
                    f"bench: tuned overlay validated (replay "
                    f"{means['tuned']:.1f}% vs preset "
                    f"{means['preset']:.1f}%)"
                )
    except Exception as e:
        log(f"bench: overlay self-validation FAILED: "
            f"{type(e).__name__}: {e}")

    if (
        tuned_info is not None
        and tuned_info.get("refined")
        and headline_rows is None
        and not tuned_info.get("rejected")
        and refine_seed_text is not None
    ):
        # the refiner rewrote the overlay but the self-validation never
        # confirmed it (skipped or raised): an unvalidated fit must not
        # become the committed config while the headline reflects the
        # seed — restore the seed overlay so artifact and number agree
        try:
            (REPO_ROOT / tuned_info["overlay"]).write_text(refine_seed_text)
            tuned_info["refined"]["reverted"] = "validation did not run"
            log("bench: refined overlay REVERTED to seed "
                "(self-validation did not confirm it)")
        except Exception as e:
            log(f"bench: refined-overlay revert FAILED: "
                f"{type(e).__name__}: {e}")
    return headline_rows



# --------------------------------------------------------------------------
# child: live-silicon correlation (runs in a subprocess, may import jax)
# --------------------------------------------------------------------------

def child_main() -> int:
    import jax

    from tpusim.harness.correlate import correlate_workload
    from tpusim.models import get_workload

    dev = jax.devices()[0]
    log(f"bench: device={dev.device_kind} platform={dev.platform}")

    # close the tuner loop (VERDICT r3 #3): fit the arch parameters on the
    # live chip FIRST and write the overlay that load_config applies by
    # default, so the correlation below runs against tuned values — the
    # reference's tuner -> tested-cfgs -> CI pipeline (util/tuner/tuner.py)
    # attempt real power telemetry (VERDICT r3 #6) — best-effort; the
    # probe result is committed evidence either way (a measured sample,
    # or exactly why none exists on this VM)
    power_probe = None
    try:
        from tpusim.power.telemetry import probe_power_sources

        power_probe = probe_power_sources()
        log(f"bench: power probe: watts={power_probe['watts']} "
            f"tried={power_probe['tried']}")
    except Exception as e:
        log(f"bench: power probe FAILED: {type(e).__name__}: {e}")
        power_probe = {"error": f"{type(e).__name__}: {e}"}
    # re-fit coefficients ONLY with a real TPU measurement (a laptop's
    # hwmon battery rail must not overwrite committed TPU coefficients),
    # and never let a fit failure destroy the probe evidence above
    if (
        power_probe and power_probe.get("watts") is not None
        and dev.platform == "tpu"
        and os.environ.get("TPUSIM_BENCH_TUNE", "1") != "0"
    ):
        try:
            from tpusim.harness.tuner import tune_power
            from tpusim.timing.arch import detect_arch

            fitted = tune_power(
                detect_arch(dev.device_kind).name, probe=power_probe,
            )
            log(f"bench: power coefficients re-fit with measured sample: "
                f"{fitted}")
        except Exception as e:
            log(f"bench: power re-fit FAILED (probe evidence kept): "
                f"{type(e).__name__}: {e}")

    tuned_info = None
    if os.environ.get("TPUSIM_BENCH_TUNE", "1") != "0" and dev.platform == "tpu":
        try:
            from tpusim.harness.tuner import tune, write_overlay

            tr = tune()
            overlay_path = (
                REPO_ROOT / "configs" / f"{tr.base_arch}.tuned.flags"
            )
            overlay_path.parent.mkdir(parents=True, exist_ok=True)
            write_overlay(tr, overlay_path)
            tuned_info = {
                "overlay": str(overlay_path.relative_to(REPO_ROOT)),
                "fit": {
                    ln.split()[0].lstrip("-"): ln.split()[1]
                    for ln in tr.overlay_lines() if ln.startswith("-")
                },
                "details": tr.details,
            }
            log(f"bench: tuner overlay written to {overlay_path}")
        except Exception as e:  # presets still work; the fit is additive
            log(f"bench: tune FAILED (continuing with presets): "
                f"{type(e).__name__}: {e}")

    # every successful live run refreshes the committed silicon fixtures
    # (trace + measured per-step seconds per workload) so later offline
    # runs can still produce a real-silicon-anchored number
    sf = os.environ.get("TPUSIM_BENCH_SAVE_FIXTURES", "1")
    save_fixtures = sf == "force" or (sf != "0" and dev.platform == "tpu")
    fixture_entries = []

    points = []
    op_profiles: list[tuple[str, dict]] = []
    suite = [(n, o, s, False) for n, o, s in SUITE] + [
        (n, o, s, True) for n, o, s in HELDOUT_SUITE
    ]
    for name, overrides, n_steps, held_out in suite:
        try:
            fn, args = get_workload(name).build(**overrides)
            prof: dict = {}
            pt = correlate_workload(
                fn, args, name=name, n_steps=n_steps, iters=3,
                fixture_dir=FIXTURE_DIR if save_fixtures else None,
                op_profile_out=prof,
            )
            points.append(pt)
            if prof.get("ops"):
                # one device trace serves both the truth and the per-op
                # correlation — no second profiling pass per workload
                op_profiles.append((name, prof))
            if save_fixtures:
                fixture_entries.append({
                    "name": name, "trace": name, "n_steps": n_steps,
                    "real_seconds": pt.real_seconds,
                    "real_source": pt.real_source,
                    **({"held_out": True} if held_out else {}),
                })
            log(
                f"bench: {name:24s} sim={pt.sim_seconds * 1e6:9.1f}us "
                f"real={pt.real_seconds * 1e6:9.1f}us "
                f"err={pt.error_pct:+7.2f}%"
            )
        except Exception as e:  # keep the suite alive; report what ran
            log(f"bench: {name} FAILED: {type(e).__name__}: {e}")

    headline_rows = refine_and_validate(
        tuned_info, fixture_entries, dev.device_kind,
    )

    if save_fixtures and fixture_entries:
        try:
            from tpusim.timing.arch import detect_arch

            FIXTURE_DIR.mkdir(parents=True, exist_ok=True)
            (FIXTURE_DIR / "manifest.json").write_text(json.dumps({
                "arch": detect_arch(dev.device_kind).name,
                "device_kind": dev.device_kind,
                "captured": time.strftime("%Y-%m-%d %H:%M:%S"),
                "power_probe": power_probe,
                "workloads": fixture_entries,
            }, indent=2))
            log(f"bench: silicon fixtures refreshed under {FIXTURE_DIR}")
        except Exception as e:
            log(f"bench: fixture save FAILED: {type(e).__name__}: {e}")

    if not points:
        emit({
            "metric": "sim_cycle_error_pct", "value": None, "unit": "%",
            "vs_baseline": None, "error": "no workloads completed",
        })
        return 1

    if headline_rows is not None:
        # the headline AND the committed report reflect the SURVIVING
        # config — the refined overlay when it validated, the preset when
        # the overlay was rejected — replayed against the same silicon
        # truths: the artifact must substantiate the number it backs
        from tpusim.harness.correlate import CorrelationPoint

        points = [
            CorrelationPoint(
                name=r[0], sim_seconds=r[1], real_seconds=r[2],
                sim_cycles=0.0, flops=r[5], hbm_bytes=r[6],
                real_source=r[4],
            )
            for r in headline_rows
        ]
        held = {
            e["name"] for e in fixture_entries if e.get("held_out")
        }
        mean_abs = sum(abs(r[3]) for r in headline_rows) / len(headline_rows)
        detail = {
            r[0]: {
                "sim_us": round(r[1] * 1e6, 1),
                "real_us": round(r[2] * 1e6, 1),
                "err_pct": round(r[3], 2),
                "real_source": r[4],
                **({"held_out": True} if r[0] in held else {}),
            }
            for r in headline_rows
        }
        n_workloads = len(headline_rows)
    else:
        held = {
            e["name"] for e in fixture_entries if e.get("held_out")
        }
        mean_abs = sum(p.abs_error_pct for p in points) / len(points)
        detail = {
            p.name: {
                "sim_us": round(p.sim_seconds * 1e6, 1),
                "real_us": round(p.real_seconds * 1e6, 1),
                "err_pct": round(p.error_pct, 2),
                **({"held_out": True} if p.name in held else {}),
            }
            for p in points
        }
        n_workloads = len(points)
    out = {
        "metric": "sim_cycle_error_pct",
        "value": round(mean_abs, 3),
        "unit": "%",
        "vs_baseline": round(mean_abs / 15.0, 4),
        "source": "live",
        "detail": detail,
        "device": dev.device_kind,
        "workloads": n_workloads,
        "real_source": sorted({p.real_source for p in points}),
        **({"tuned": tuned_info} if tuned_info else {}),
    }

    # reports land under reports/ by default so a round-end live run
    # commits a reproducible artifact behind the README accuracy claim
    report_dir = os.environ.get(
        "TPUSIM_BENCH_REPORT",
        str(REPO_ROOT / "reports") if save_fixtures else "",
    )
    if report_dir:
        try:
            from tpusim.harness.plots import write_correlation_report

            path = write_correlation_report(points, report_dir)
            log(f"bench: correlation report written to {path}")
        except Exception as e:  # cosmetic step must not eat the result
            log(f"bench: report FAILED: {type(e).__name__}: {e}")
        try:
            from tpusim.harness.correl_ops import (
                correlate_counters, correlate_ops, write_correl_ops,
                xla_op_estimates,
            )

            # assembled from the SAME device traces that produced the
            # headline truths — no second profiling pass over the suite
            op_corrs = []
            for name, prof in op_profiles:
                try:
                    corr = correlate_ops(
                        prof["engine_result"], prof["ops"],
                        clock_hz=prof["clock_hz"], workload=name,
                        real_iters=prof["iters"],
                        xla_estimates=(
                            xla_op_estimates(prof["module"])
                            if prof.get("module") is not None else None
                        ),
                    )
                    corr.counters = correlate_counters(
                        prof["engine_result"], prof["ops"],
                        clock_hz=prof["clock_hz"], arch=prof["arch"],
                    )
                    op_corrs.append(corr)
                except Exception as e:
                    log(f"bench: correl_ops {name} FAILED: "
                        f"{type(e).__name__}: {e}")
            if op_corrs:
                p = write_correl_ops(
                    op_corrs, Path(report_dir) / "correl_ops.json"
                )
                log(f"bench: per-op correlation written to {p} "
                    f"({len(op_corrs)} workloads)")
                # the suite's engine results predate the refit two
                # blocks up; re-correlate the FINAL model against the
                # fresh device rows so the committed artifact carries
                # the current model_version (round-4's staleness, now a
                # fast-tier test failure — test_correl_artifact.py)
                try:
                    from tpusim.harness.correl_ops import (
                        regenerate_offline,
                    )
                    from tpusim.timing.arch import detect_arch

                    doc = regenerate_offline(
                        p, fixture_dir=FIXTURE_DIR, out_path=p,
                        arch=detect_arch(dev.device_kind).name,
                    )
                    log(
                        f"bench: per-op artifact re-correlated under the "
                        f"final model "
                        f"({doc['mean_sync_weighted_abs_error_pct']}% "
                        f"sync weighted, model "
                        f"{doc['model_version']})"
                    )
                except Exception as e:
                    log(f"bench: per-op regen FAILED (artifact may be "
                        f"stale): {type(e).__name__}: {e}")
            else:
                log("bench: no per-op profiles collected (device "
                    "profiling unavailable?); correl_ops.json not "
                    "refreshed")
        except Exception as e:
            log(f"bench: correl_ops FAILED: {type(e).__name__}: {e}")

    emit(out)
    return 0


# --------------------------------------------------------------------------
# fallback: committed silicon fixtures (pure sim — NO jax import)
# --------------------------------------------------------------------------

def replay_fixture_errors(
    engine, entries: list[dict], fixture_dir: Path,
    modules: dict | None = None,
) -> list[tuple[str, float, float, float, str, float, float, float]]:
    """Replay fixture traces through one engine; returns
    (name, sim_s, real_s, signed_err_pct, real_source, flops_per_step,
    hbm_bytes_per_step, op_count) per entry that replays successfully.
    Shared by
    the offline fallback and the live child's tuned-overlay
    self-validation.  ``modules`` (optional) caches loaded modules by
    trace key across calls — the warm-replay pass prices the SAME
    module objects the cold pass parsed, so its wall clock measures
    pricing alone (the steady-state sweep/serve regime)."""
    from tpusim.trace.format import load_trace, select_module

    out = []
    for entry in entries:
        name = entry["name"]
        try:
            mkey = f"{entry['trace']}::{entry.get('module')}"
            mod = modules.get(mkey) if modules is not None else None
            if mod is None:
                td = load_trace(fixture_dir / entry["trace"])
                mod = select_module(td, entry.get("module"))
                if modules is not None:
                    modules[mkey] = mod
            res = engine.run(mod)
            n_steps = float(entry.get("n_steps", 1))
            sim_s = res.seconds / n_steps
            real_s = float(entry["real_seconds"])
            err = 100.0 * (sim_s - real_s) / real_s
            out.append((
                name, sim_s, real_s, err,
                entry.get("real_source", "wall"),
                res.flops / n_steps, res.hbm_bytes / n_steps,
                res.op_count,
            ))
        except Exception as e:
            log(f"bench(replay): {name} FAILED: {type(e).__name__}: {e}")
    return out


def _durable_cold_replay(
    entries: list[dict], fixture_dir: Path, arch: str,
) -> tuple[float, dict]:
    """Wall seconds (best of 3) for the full cold composition — trace
    load + pricing — against a warm durable compile store, plus the
    store's counters.  Each trial clears the in-memory compiled tier
    and reloads every trace (parse deferred), so only the disk columns
    carry state between trials: this is what a fresh serve worker or
    campaign process pays."""
    import shutil
    import tempfile

    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.perf.cache import clear_compiled_cache
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace, select_module

    cfg = load_config(arch=arch)
    store_dir = tempfile.mkdtemp(prefix="tpusim-bench-cmod-")
    try:
        # populate: one pricing pass with the store mounted persists
        # the columns the earlier passes already compiled in memory
        set_compile_store(CompileStore(store_dir))
        for entry in entries:
            td = load_trace(fixture_dir / entry["trace"])
            Engine(cfg).run(select_module(td, entry.get("module")))
        best = None
        stats: dict = {}
        for _ in range(3):
            clear_compiled_cache()
            store = CompileStore(store_dir)
            set_compile_store(store)
            t0 = time.perf_counter()
            for entry in entries:
                td = load_trace(fixture_dir / entry["trace"])
                Engine(cfg).run(select_module(td, entry.get("module")))
            wall = time.perf_counter() - t0
            if best is None or wall < best:
                best = wall
                stats = store.stats_dict()
        return best, stats
    finally:
        set_compile_store(None)
        clear_compiled_cache()
        shutil.rmtree(store_dir, ignore_errors=True)


def _scenario_batch_sweep(lanes: int = 48) -> dict | None:
    """Scenario-batched pricing headline (PR 19): price a 48-scenario
    degradation sweep of the llama_tiny_tp2dp2 fixture per-state
    through the fastpath vs one lane-axis batch pass, best-of-3 each.
    The batch contract is byte-identity (CI-pinned by check_golden
    --fastpath-parity and tests/test_batch_price.py), so this leg only
    measures speed: ``scenario_batch_kops_s`` is (module ops x lanes)
    per batched host-second, ``speedup`` the honest ratio against the
    SAME fastpath backend walked one state at a time."""
    import timeit

    from tpusim.fastpath import (
        price_module_batch, resolve_backend, resolve_batch_backend,
    )
    from tpusim.fastpath.price import price_module
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace

    trace_dir = (REPO_ROOT / "tests" / "fixtures" / "traces"
                 / "llama_tiny_tp2dp2")
    if not trace_dir.is_dir():
        return None
    backend = resolve_backend(None)
    batch_backend = resolve_batch_backend(None)
    if backend == "serial" or batch_backend == "serial":
        return None  # no numpy column math: nothing to batch
    pod = load_trace(trace_dir)
    cfg = load_config(arch="v5p")
    mod = next(iter(pod.modules.values()))
    # the campaign-style launch classes: per-lane clock/HBM derates
    engines = [
        Engine(cfg, clock_scale=1.0 - 0.005 * (s % 16),
               hbm_scale=1.0 - 0.007 * (s % 12))
        for s in range(lanes)
    ]
    # compile once up front so both passes measure pricing alone
    ref = price_module(engines[0], mod, backend)
    price_module_batch(mod, engines)

    def per_state():
        for e in engines:
            price_module(e, mod, backend)

    def batched():
        price_module_batch(mod, engines)

    # the preceding bench legs leave allocator/GC pressure behind;
    # collect first so best-of-N measures pricing, not their garbage
    import gc

    gc.collect()
    # interleave the trials so co-tenant noise windows hit both sides
    # equally; the batched pass is ~5x shorter, so give it 3 single-run
    # samples per round — min() needs single runs (not averages) on
    # both sides to find the same quiet-window floor
    t_per = t_bat = float("inf")
    for _ in range(5):
        t_per = min(t_per, timeit.timeit(per_state, number=1))
        t_bat = min(t_bat, *timeit.repeat(batched, number=1, repeat=3))
    if t_bat <= 0 or t_per <= 0:
        return None
    return {
        "scenario_batch_kops_s": round(
            ref.op_count * lanes / t_bat / 1e3, 1),
        "lanes": lanes,
        "backend": batch_backend,
        "per_state_ms": round(t_per * 1e3, 2),
        "batched_ms": round(t_bat * 1e3, 2),
        "speedup": round(t_per / t_bat, 2),
    }


def _multislice_ar_leg(arch: str = "v5p") -> dict | None:
    """Multi-slice fabric micro-headline (PR 20): the modeled time of a
    64 MiB all-reduce spanning a 2-slice, 8-chip system over the
    tpusim.dcn fabric — hierarchical in-slice reduce-scatter, cross-slice
    all-reduce on the NIC-derived bandwidth, in-slice all-gather — with
    the flat scalar-DCN model it must beat riding as detail.  Pure model
    evaluation: deterministic, no silicon, byte-pinned by the CI dcn
    smoke (ci/check_golden --dcn-smoke) and tests/test_dcn.py."""
    from tpusim.ici.collectives import CollectiveModel
    from tpusim.ici.topology import torus_for
    from tpusim.timing.config import load_config

    def _ici(overlay):
        return load_config(
            arch=arch, overlays=[{"arch": {"ici": overlay}}],
        ).arch.ici

    payload = float(64 << 20)
    n = 8
    topo = torus_for(n, arch)
    flat = CollectiveModel(topo, _ici({"chips_per_slice": 4}))
    fab = CollectiveModel(topo, _ici({
        "chips_per_slice": 4, "dcn_nics_per_slice": 4,
        "dcn_hop_bandwidth": 25e9, "dcn_hop_latency": 1e-5,
    }))
    hier_s = fab.allreduce_seconds(payload, n)
    flat_s = flat.allreduce_seconds(payload, n)
    if not (hier_s > 0 and math.isfinite(hier_s)):
        return None
    return {
        "multislice_ar_us": round(hier_s * 1e6, 1),
        "payload_mib": 64,
        "slices": 2,
        "chips": n,
        "flat_dcn_us": round(flat_s * 1e6, 1),
        "hier_speedup": round(flat_s / hier_s, 2),
    }


def fixture_main(fixture_dir: Path = FIXTURE_DIR) -> int | None:
    """Replay committed TPU traces against their committed measured times.

    Returns an exit code, or None when no fixture set is available."""
    manifest_path = fixture_dir / "manifest.json"
    if not manifest_path.exists():
        return None

    from tpusim.perf.cache import CachedEngine, ResultCache
    from tpusim.timing.config import load_config

    manifest = json.loads(manifest_path.read_text())
    arch = manifest.get("arch", "v5e")
    # replay through the tpusim.perf cache so the emitted detail block
    # tracks cache effectiveness alongside accuracy (hit/miss counts);
    # in-memory tier only — the bench must price today's model, never a
    # stale disk record
    cache = ResultCache()
    engine = CachedEngine(load_config(arch=arch), result_cache=cache)

    try:
        from tpusim.harness.correl_ops import (
            load_known_outliers, match_known_outlier,
        )

        known_outliers = load_known_outliers()
    except Exception as e:
        log(f"bench(fixture): known-outlier load FAILED: "
            f"{type(e).__name__}: {e}")
        known_outliers, match_known_outlier = [], None

    detail = {}
    errs = []
    by_name = {e["name"]: e for e in manifest.get("workloads", [])}
    modules: dict = {}
    replay_t0 = time.perf_counter()
    rows = replay_fixture_errors(
        engine, manifest.get("workloads", []), fixture_dir,
        modules=modules,
    )
    replay_wall = time.perf_counter() - replay_t0
    # warm pass: a FRESH uncached engine re-prices the already-parsed
    # modules through the fastpath's compiled columns — real pricing
    # work (zero result-cache hits), measuring the steady-state regime
    # every sweep/serve/campaign replay after the first runs in.  The
    # tpusim.fastpath parity contract makes its rows byte-identical to
    # the cold pass, so accuracy numbers are unaffected.
    from tpusim.fastpath import resolve_backend
    from tpusim.timing.engine import Engine

    warm_engine = Engine(load_config(arch=arch))
    warm_t0 = time.perf_counter()
    warm_rows = replay_fixture_errors(
        warm_engine, manifest.get("workloads", []), fixture_dir,
        modules=modules,
    )
    warm_wall = time.perf_counter() - warm_t0
    pricing_backend = resolve_backend(None)
    # durable-cold pass: the fresh-process-with-a-warm-disk-tier regime
    # the PR 12 compile store exists for.  Populate a throwaway store
    # from the already-compiled modules, then replay the full cold
    # composition (trace load INCLUDED, parse deferred) with the
    # in-memory compiled tier cleared — pricing runs from mmapped
    # columns with zero IR construction.  Best-of-3 (the serve-bench
    # discipline: co-tenant noise halves absolutes in bad windows).
    durable_wall = None
    durable_stats = None
    try:
        durable_wall, durable_stats = _durable_cold_replay(
            manifest.get("workloads", []), fixture_dir, arch,
        )
    except Exception as e:
        log(f"bench(fixture): durable-cold leg FAILED: "
            f"{type(e).__name__}: {e}")
    # scenario-batched pricing leg (PR 19): the campaign/fleet regime —
    # one module priced under a sweep of degradation launch classes
    scenario_batch = None
    try:
        scenario_batch = _scenario_batch_sweep()
        if scenario_batch is not None:
            log(f"bench(fixture): scenario-batch x{scenario_batch['lanes']} "
                f"per-state={scenario_batch['per_state_ms']:.1f}ms "
                f"batched={scenario_batch['batched_ms']:.1f}ms "
                f"speedup={scenario_batch['speedup']:.2f}x "
                f"({scenario_batch['backend']})")
    except Exception as e:
        log(f"bench(fixture): scenario-batch leg FAILED: "
            f"{type(e).__name__}: {e}")
    # multi-slice fabric leg (PR 20): the modeled hierarchical AR over
    # the DCN fabric vs the flat scalar model it degenerates to
    multislice = None
    try:
        multislice = _multislice_ar_leg(arch)
        if multislice is not None:
            log(f"bench(fixture): multislice-ar 64MiB x{multislice['slices']} "
                f"slices hier={multislice['multislice_ar_us']:.1f}us "
                f"flat={multislice['flat_dcn_us']:.1f}us "
                f"speedup={multislice['hier_speedup']:.2f}x")
    except Exception as e:
        log(f"bench(fixture): multislice-ar leg FAILED: "
            f"{type(e).__name__}: {e}")
    for name, sim_s, real_s, err, src, _fl, _hb, _ops in rows:
        # ground-truth provenance: entries captured before the
        # device-timeline change (or where the profiler failed) hold
        # wall-clock times inflated by per-launch dispatch gaps
        errs.append(abs(err))
        entry = by_name.get(name, {})
        detail[name] = {
            "sim_us": round(sim_s * 1e6, 1),
            "real_us": round(real_s * 1e6, 1),
            "err_pct": round(err, 2),
            "real_source": src,
            **({"held_out": True} if entry.get("held_out") else {}),
        }
        if known_outliers and match_known_outlier is not None:
            reason = match_known_outlier(
                known_outliers, name, abs_error_pct=abs(err),
            )
            if reason is not None:
                detail[name]["known_outlier"] = reason
        log(f"bench(fixture): {name:24s} sim={sim_s * 1e6:9.1f}us "
            f"real={real_s * 1e6:9.1f}us err={err:+7.2f}%"
            + ("  [wall-sourced truth]" if src != "device" else ""))

    if not errs:
        return None
    mean_abs = sum(errs) / len(errs)
    emit({
        "metric": "sim_cycle_error_pct",
        "value": round(mean_abs, 3),
        "unit": "%",
        "vs_baseline": round(mean_abs / 15.0, 4),
        "source": "silicon_fixture",
        "fixture_device": manifest.get("device_kind", "unknown"),
        "fixture_captured": manifest.get("captured", "unknown"),
        "detail": detail,
        "workloads": len(errs),
        # gpgpu_simulation_rate analogue: ops simulated per host-second
        # (pinned by tests/test_sim_throughput.py).  Since the fastpath
        # PR this is the WARM rate — pure pricing over parsed modules,
        # the regime every replay after a process's first runs in;
        # sim_rate_kops_cold keeps the old parse-included composition
        # so BENCH_r06+ records the full speedup trajectory.
        "sim_rate_kops": round(
            sum(r[7] for r in warm_rows) / warm_wall / 1e3, 1
        ) if warm_wall > 0 and warm_rows else None,
        "sim_rate_kops_cold": round(
            sum(r[7] for r in rows) / replay_wall / 1e3, 1
        ) if replay_wall > 0 and rows else None,
        # the durable-tier cold rate: same composition as _cold (trace
        # load + pricing, fresh in-memory state) but against a warm
        # disk compile store — the first-touch rate a fleet process
        # actually pays once any peer has compiled the module (PR 12)
        "sim_rate_kops_cold_durable": round(
            sum(r[7] for r in rows) / durable_wall / 1e3, 1
        ) if durable_wall and rows else None,
        "compile_store": durable_stats,
        # scenario-batched pricing (PR 19): kops/s through one lane-axis
        # pass over the 48-scenario degradation sweep, with the honest
        # per-state-fastpath baseline and speedup riding as detail
        "scenario_batch_kops_s": (
            scenario_batch["scenario_batch_kops_s"]
            if scenario_batch else None),
        "scenario_batch": scenario_batch,
        # multi-slice fabric micro-headline (PR 20): modeled 64 MiB
        # hierarchical all-reduce over a 2-slice tpusim.dcn fabric,
        # with the flat scalar model and speedup riding as detail
        "multislice_ar_us": (
            multislice["multislice_ar_us"] if multislice else None),
        "multislice_ar": multislice,
        # which tpusim.fastpath backend priced (serial/vectorized/native)
        "pricing_backend": pricing_backend,
        # simulator throughput + cache effectiveness ride the artifact
        # (tpusim.perf): sim_wall_s is the whole-suite cold replay wall,
        # cache counts show how much pricing the suite deduplicated
        "sim_wall_s": round(replay_wall, 3),
        "sim_wall_warm_s": round(warm_wall, 3),
        "cache": {"hits": cache.hits, "misses": cache.misses},
    })
    return 0


# --------------------------------------------------------------------------
# parent: orchestrate with retry/backoff; never imports jax
# --------------------------------------------------------------------------

def _backend_probe(timeout_s: int = 90) -> bool:
    """Cheap check that the live backend is reachable (bounded; a down
    axon tunnel makes ``import jax`` hang, which round 1 turned into a
    full-timeout rc=124)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s, cwd=REPO_ROOT,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    last_tail = ""
    for attempt in range(ATTEMPTS):
        wait = BACKOFF_S[min(attempt, len(BACKOFF_S) - 1)]
        if wait:
            log(f"bench: retrying in {wait}s (attempt {attempt + 1}/{ATTEMPTS})")
            time.sleep(wait)
        if not _backend_probe():
            last_tail = "backend probe failed (tunnel down?)"
            log(f"bench: attempt {attempt + 1}: {last_tail}")
            continue
        try:
            proc = subprocess.run(
                [sys.executable, str(Path(__file__).resolve()), "--child"],
                capture_output=True, text=True, timeout=CHILD_TIMEOUT_S,
                cwd=REPO_ROOT,
            )
        except subprocess.TimeoutExpired:
            last_tail = f"child timed out after {CHILD_TIMEOUT_S}s"
            log(f"bench: {last_tail}")
            continue
        if proc.stderr:
            sys.stderr.write(proc.stderr[-8000:])
            sys.stderr.flush()
        line = _last_json_line(proc.stdout)
        if proc.returncode == 0 and line is not None:
            print(line, flush=True)
            return 0
        last_tail = (proc.stderr or proc.stdout or "")[-2000:]
        log(f"bench: child attempt {attempt + 1} failed (rc={proc.returncode})")

    log("bench: live chip unreachable; trying committed silicon fixtures")
    try:
        rc = fixture_main()
        if rc is not None:
            return rc
    except Exception as e:
        log(f"bench: fixture fallback FAILED: {type(e).__name__}: {e}")

    emit({
        "metric": "sim_cycle_error_pct", "value": None, "unit": "%",
        "vs_baseline": None,
        "error": f"live TPU unreachable after {ATTEMPTS} attempts and no "
                 f"silicon fixture present; last: {last_tail[-300:]}",
    })
    return 1


def _last_json_line(stdout: str) -> str | None:
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
                return line
            except ValueError:
                continue
    return None


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child_main())
    sys.exit(main())
