#!/usr/bin/env python
"""Headline benchmark: sim-cycle accuracy vs silicon.

Runs a small correlation suite on the local TPU chip — compute-bound,
bandwidth-bound, and mixed workloads — comparing the timing engine's
estimate of each captured HLO program against fenced wall-clock measurement
of the same program on the device (the framework's whole point; north-star
from BASELINE.md: <=15% cycle error).

Prints ONE json line:
  metric       "sim_cycle_error_pct"  (mean |error| across the suite)
  value        mean absolute percent error, lower is better
  unit         "%"
  vs_baseline  value / 15.0  (the reference north-star bound; <1.0 beats it)

Extra per-workload detail goes to stderr so stdout stays one line.
"""

from __future__ import annotations

import json
import sys


SUITE = [
    # (workload name, build overrides, scan steps) — small programs get
    # more steps so tunnel RPC jitter amortizes away
    ("matmul_chain", {"m": 2048, "k": 2048, "depth": 4}, 16),   # MXU-bound
    ("elementwise_stream", {"elems": 32 * 1024 * 1024}, 16),    # HBM-bound
    ("reduction", {"rows": 4096, "cols": 4096}, 64),            # VPU+HBM
    ("mlp_train_step", {"batch": 256, "width": 1024, "depth": 2}, 64),  # mixed
]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    import jax

    from tpusim.harness.correlate import correlate_workload
    from tpusim.models import get_workload

    dev = jax.devices()[0]
    log(f"bench: device={dev.device_kind} platform={dev.platform}")

    points = []
    for name, overrides, n_steps in SUITE:
        try:
            fn, args = get_workload(name).build(**overrides)
            pt = correlate_workload(
                fn, args, name=name, n_steps=n_steps, iters=3
            )
            points.append(pt)
            log(
                f"bench: {name:24s} sim={pt.sim_seconds * 1e6:9.1f}us "
                f"real={pt.real_seconds * 1e6:9.1f}us "
                f"err={pt.error_pct:+7.2f}%"
            )
        except Exception as e:  # keep the suite alive; report what ran
            log(f"bench: {name} FAILED: {type(e).__name__}: {e}")

    if not points:
        print(json.dumps({
            "metric": "sim_cycle_error_pct", "value": None, "unit": "%",
            "vs_baseline": None, "error": "no workloads completed",
        }))
        return 1

    mean_abs = sum(p.abs_error_pct for p in points) / len(points)
    out = {
        "metric": "sim_cycle_error_pct",
        "value": round(mean_abs, 3),
        "unit": "%",
        "vs_baseline": round(mean_abs / 15.0, 4),
        "detail": {
            p.name: {
                "sim_us": round(p.sim_seconds * 1e6, 1),
                "real_us": round(p.real_seconds * 1e6, 1),
                "err_pct": round(p.error_pct, 2),
            }
            for p in points
        },
        "device": dev.device_kind,
        "workloads": len(points),
    }

    import os

    report_dir = os.environ.get("TPUSIM_BENCH_REPORT")
    if report_dir:
        try:
            from tpusim.harness.plots import write_correlation_report

            path = write_correlation_report(points, report_dir)
            log(f"bench: correlation report written to {path}")
        except Exception as e:  # cosmetic step must not eat the result
            log(f"bench: report FAILED: {type(e).__name__}: {e}")

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
