#!/usr/bin/env python3
"""Golden-stat regression check — the CI tier the reference runs as
``travis.sh`` + ``Jenkinsfile`` (simulate pre-recorded traces, then gate on
scraped stats; ``util/job_launching/get_stats.py`` success sentinel).

Simulates every fixture trace under a matrix of configs and compares the
scraped stats against ``ci/golden/<name>.json``.  The simulator is
deterministic, so the default comparison is exact for counter stats and
tight-relative for derived floats; any diff means the timing model changed
— rerun with ``--update`` when the change is intended.

Usage:
    python ci/check_golden.py                 # check stats
    python ci/check_golden.py --update        # regenerate goldens
    python ci/check_golden.py --obs-smoke     # obs-export schema smoke
    python ci/check_golden.py --faults-smoke  # degraded-pod schema smoke
    python ci/check_golden.py --serve-smoke   # HTTP daemon determinism
    python ci/check_golden.py --fastpath-parity  # pricing-backend parity
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

GOLDEN_DIR = REPO / "ci" / "golden"
FIXTURES = REPO / "tests" / "fixtures" / "traces"

#: (fixture dir name, arch, overlay dicts) — the QV100/RTX2060/RTX3070
#: config-matrix idea at TPU scale
MATRIX = [
    ("matmul_512", "v5e", []),
    ("matmul_512", "v5p", []),
    ("llama_tiny_tp2dp2", "v5p", []),
    ("llama_tiny_tp2dp2", "v5p",
     [{"arch": {"ici": {"network_mode": "detailed"}}}]),
    ("llama_tiny_tp2dp2", "v6e", [{"power_enabled": True}]),
]

#: host-dependent stats excluded from comparison
VOLATILE = {"simulation_rate_kops", "wall_seconds", "silicon_slowdown"}
#: relative tolerance for derived float stats
RTOL = 1e-9


def _overlay_tag(overlays: list[dict]) -> str:
    """Filename tag encoding overlay keys AND values, so matrix rows
    differing only in values cannot collide."""
    parts: list[str] = []

    def walk(prefix: str, d: dict) -> None:
        for k, v in sorted(d.items()):
            if isinstance(v, dict):
                walk(f"{prefix}{k}.", v)
            else:
                parts.append(f"{prefix}{k}={v}")

    for o in overlays:
        walk("", o)
    return "_".join(parts).replace("/", "-").replace(" ", "")


def run_matrix() -> dict[str, dict[str, float]]:
    from tpusim.sim.driver import simulate_trace

    out: dict[str, dict[str, float]] = {}
    for fixture, arch, overlays in MATRIX:
        name = f"{fixture}__{arch}"
        tag = _overlay_tag(overlays)
        if tag:
            name += "__" + tag
        # tuned=False: goldens are a MODEL regression gate; they must not
        # shift when a live run refreshes configs/<arch>.tuned.flags
        report = simulate_trace(
            FIXTURES / fixture, arch=arch, overlays=list(overlays),
            tuned=False,
        )
        stats = {
            k: v for k, v in json.loads(report.stats.to_json()).items()
            if k not in VOLATILE
        }
        out[name] = stats
    return out


def compare(
    got: dict[str, dict[str, float]],
) -> list[str]:
    errors: list[str] = []
    for name, stats in got.items():
        path = GOLDEN_DIR / f"{name}.json"
        if not path.exists():
            errors.append(f"{name}: no golden file {path} (run --update)")
            continue
        golden = json.loads(path.read_text())
        for key in sorted(set(golden) | set(stats)):
            if key in VOLATILE:
                continue
            if key not in golden:
                errors.append(f"{name}: NEW stat {key} = {stats[key]}")
                continue
            if key not in stats:
                errors.append(f"{name}: MISSING stat {key}")
                continue
            g, s = golden[key], stats[key]
            if isinstance(g, (int, float)) and isinstance(s, (int, float)):
                tol = RTOL * max(abs(g), abs(s), 1e-30)
                if abs(g - s) > tol:
                    errors.append(
                        f"{name}: {key} changed {g!r} -> {s!r}"
                    )
            elif g != s:
                errors.append(f"{name}: {key} changed {g!r} -> {s!r}")
    return errors


#: the obs smoke fixture: the multi-device golden trace, replayed with
#: the observability layer on and its exports schema-checked
OBS_SMOKE_FIXTURE = "llama_tiny_tp2dp2"
OBS_SCHEMA = REPO / "ci" / "obs_schema.json"


def obs_smoke(out_dir: Path | None = None) -> dict:
    """Simulate one golden fixture with ``--obs-out`` semantics and
    validate the emitted JSONL/trace/prometheus set against the
    checked-in schema (``ci/obs_schema.json``).  Raises on violation."""
    import tempfile

    from tpusim.obs import Instrumentation, validate_obs_dir, write_obs_dir
    from tpusim.sim.driver import simulate_trace

    schema = json.loads(OBS_SCHEMA.read_text())
    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="tpusim_obs_smoke_")
        out_dir = Path(tmp.name)
    try:
        obs = Instrumentation()
        report = simulate_trace(
            FIXTURES / OBS_SMOKE_FIXTURE, arch="v5p", tuned=False, obs=obs,
        )
        write_obs_dir(out_dir, report, obs=obs)
        summary = validate_obs_dir(out_dir, schema)
        # the self-profiling side must have seen the pipeline phases
        for phase in ("parse", "simulate", "simulate/engine"):
            if phase not in obs.spans:
                raise ValueError(f"obs smoke: span {phase!r} not recorded")
        return summary
    finally:
        if tmp is not None:
            tmp.cleanup()


#: the faults smoke fixture: the multi-device golden trace on a tiny v5p
#: slice, replayed healthy and with one dead ICI link
FAULTS_SMOKE_FIXTURE = "llama_tiny_tp2dp2"
FAULTS_SCHEMA = REPO / "ci" / "faults_schema.json"


def faults_smoke() -> dict:
    """Degraded-pod contract smoke (mirrors the PR 1 obs-smoke pattern):

    1. the kinds table in ``ci/faults_schema.json`` must match the
       loader's (``tpusim.faults.FAULT_KINDS``) and every example
       schedule must round-trip through it;
    2. a tiny v5p slice replayed with one dead link must run strictly
       slower than the healthy baseline and stamp every
       ``stats_required_when_active`` key;
    3. the healthy replay must stamp NONE of them (no-op default).
    Raises on violation."""
    from tpusim.faults import (
        FAULT_KINDS, link_down_schedule, load_fault_schedule,
    )
    from tpusim.ici.topology import torus_for
    from tpusim.sim.driver import simulate_trace

    schema = json.loads(FAULTS_SCHEMA.read_text())
    schema_kinds = set(schema["fault_kinds"])
    if schema_kinds != set(FAULT_KINDS):
        raise ValueError(
            f"faults smoke: schema kinds {sorted(schema_kinds)} != "
            f"loader kinds {sorted(FAULT_KINDS)}"
        )
    for kind, doc in schema.get("example_schedules", {}).items():
        sched = load_fault_schedule(doc)
        if not sched.faults or sched.faults[0].kind != kind:
            raise ValueError(
                f"faults smoke: example schedule for {kind!r} did not "
                f"round-trip"
            )

    healthy = simulate_trace(
        FIXTURES / FAULTS_SMOKE_FIXTURE, arch="v5p", tuned=False,
    )
    leaked = [
        k for k in healthy.stats.values if k.startswith("faults_")
    ]
    if leaked:
        raise ValueError(
            f"faults smoke: healthy run leaked fault stats {leaked}"
        )
    topo = torus_for(healthy.num_devices, "v5p")
    a, b = topo.undirected_links()[0]
    sched = link_down_schedule(topo, a, b)
    faulted = simulate_trace(
        FIXTURES / FAULTS_SMOKE_FIXTURE, arch="v5p", tuned=False,
        faults=sched, topology=topo,
    )
    missing = [
        k for k in schema["stats_required_when_active"]
        if k not in faulted.stats.values
    ]
    if missing:
        raise ValueError(f"faults smoke: missing stats keys {missing}")
    h_coll = healthy.stats.get("tot_collective_cycles", 0.0)
    f_coll = faulted.stats.get("tot_collective_cycles", 0.0)
    if not f_coll > h_coll:
        raise ValueError(
            f"faults smoke: dead link did not inflate collective cycles "
            f"({h_coll} -> {f_coll})"
        )
    if not faulted.cycles > healthy.cycles:
        raise ValueError(
            f"faults smoke: dead link did not inflate step time "
            f"({healthy.cycles} -> {faulted.cycles})"
        )
    return {
        "kinds": sorted(schema_kinds),
        "dead_link": f"{list(topo.coords(a))}->{list(topo.coords(b))}",
        "step_inflation": faulted.cycles / healthy.cycles,
        "collective_inflation": f_coll / h_coll if h_coll else float("inf"),
        "stats_keys": schema["stats_required_when_active"],
    }


def lint_smoke() -> dict:
    """Static-analyzer contract smoke (``tpusim lint`` over everything
    checked in):

    1. every fixture trace under the golden matrix's arches must lint
       with ZERO error-level diagnostics (warnings allowed — CPU-backend
       capture quirks are warnings by design);
    2. every committed overlay flag file must compose onto its arch and
       pass the config passes clean;
    3. every example schedule in ``ci/faults_schema.json`` must pass the
       schedule passes against a 4x4x4 v5p torus;
    4. the repo-wide stats-key audit must be clean;
    5. ``--list-codes`` must agree with the registry (docs/CI sync).
    Raises on violation."""
    from tpusim.analysis import (
        CODES, Diagnostics, analyze_stats_keys, list_code_lines,
    )
    from tpusim.analysis.runner import analyze_config, analyze_schedule
    from tpusim.analysis.trace_passes import (
        load_parsed_trace, run_trace_passes,
    )
    from tpusim.ici.topology import torus_for
    from tpusim.timing.config import load_config

    checked: list[str] = []

    def _require_clean(diags, what: str) -> None:
        if diags.has_errors:
            lines = "\n".join(d.text() for d in diags.errors)
            raise ValueError(
                f"lint smoke: {what} has error-level diagnostics:\n"
                f"{lines}"
            )
        checked.append(what)

    fixtures = sorted({m[0] for m in MATRIX})
    arches = sorted({m[1] for m in MATRIX})
    for fixture in fixtures:
        # trace passes are arch-independent: parse + lint the artifacts
        # once, then rerun only the config passes per matrix arch
        pt = load_parsed_trace(FIXTURES / fixture)
        diags = Diagnostics()
        run_trace_passes(pt, diags, lenient=False)
        _require_clean(diags, f"trace {fixture}")
        for arch in arches:
            cfg = load_config(arch=arch, tuned=False)
            _require_clean(
                analyze_config(cfg, trace_meta=pt.meta),
                f"config passes {fixture} @ {arch}",
            )

    for flags in sorted((REPO / "configs").glob("*.flags")):
        arch = flags.name.split(".", 1)[0]
        cfg = load_config(arch=arch, overlays=[flags], tuned=False)
        _require_clean(
            analyze_config(cfg, file=f"configs/{flags.name}"),
            f"config {flags.name}",
        )

    schema = json.loads(FAULTS_SCHEMA.read_text())
    topo = torus_for(64, "v5p")
    for kind, doc in sorted(schema.get("example_schedules", {}).items()):
        _require_clean(
            analyze_schedule(doc, topo),
            f"schedule example {kind}",
        )

    _require_clean(analyze_stats_keys(), "stats-key audit")

    lines = list_code_lines()
    code_lines = [ln for ln in lines if ln.startswith("TL")]
    if len(code_lines) != len(CODES):
        raise ValueError(
            f"lint smoke: --list-codes prints {len(code_lines)} code "
            f"lines but the registry has {len(CODES)} codes"
        )
    if not any(ln.startswith("[") for ln in lines):
        raise ValueError(
            "lint smoke: --list-codes lost its family grouping headers"
        )
    return {"artifacts": checked, "codes": len(CODES)}


def dataflow_smoke() -> dict:
    """Dataflow / deadlock / self-audit contract smoke (`tpusim.analysis`
    v2):

    1. every committed fixture trace + golden-matrix arch lints with
       ZERO TL4xx (memory) and TL41x (collective-matching) errors —
       the new semantic passes must not refuse a healthy workload;
    2. the liveness pass AGREES with the engine: per-module static
       vmem residency and peak-live bytes equal the engine's own
       capacity-model walk on the full fixture + silicon corpus;
    3. a seeded two-device mismatched-collective trace is REFUSED:
       ``tpusim lint`` reports a TL41x error and ``simulate
       --validate`` raises instead of pricing a trace that can never
       complete;
    4. the TL35x determinism/durability self-audit over the repo's own
       sources is green.
    Raises on violation."""
    import tempfile

    from tpusim.analysis import analyze_self_audit, analyze_trace_dir
    from tpusim.analysis.dataflow import analyze_module
    from tpusim.timing.engine import (
        _vmem_peak_live_bytes, _vmem_resident_bytes,
    )
    from tpusim.trace.format import load_trace

    fixtures = sorted({m[0] for m in MATRIX})
    arches = sorted({m[1] for m in MATRIX})
    new_families = ("TL4",)
    checked = 0
    for fixture in fixtures:
        for arch in arches:
            diags = analyze_trace_dir(
                FIXTURES / fixture, arch=arch, tuned=False,
            )
            bad = [
                d for d in diags.errors
                if d.code.startswith(new_families)
            ]
            if bad:
                raise ValueError(
                    f"dataflow smoke: {fixture}@{arch} has TL4xx/TL41x "
                    f"errors on a healthy trace:\n"
                    + "\n".join(d.text() for d in bad)
                )
            checked += 1

    # 2. liveness == engine on the corpus
    corpus = [FIXTURES / f for f in fixtures]
    silicon = REPO / "reports" / "silicon"
    if silicon.is_dir():
        corpus += sorted(
            d for d in silicon.iterdir() if (d / "modules").is_dir()
        )
    agreed = 0
    for trace_dir in corpus:
        pod = load_trace(trace_dir)
        for name, module in pod.modules.items():
            df = analyze_module(module)
            want_resident = _vmem_resident_bytes(module)
            want_peak = _vmem_peak_live_bytes(module)
            if df.alloc_total("vmem") != want_resident or \
                    df.peak_live("vmem") != want_peak:
                raise ValueError(
                    f"dataflow smoke: liveness disagrees with the "
                    f"engine on {trace_dir.name}/{name}: "
                    f"resident {df.alloc_total('vmem')} vs "
                    f"{want_resident}, peak {df.peak_live('vmem')} "
                    f"vs {want_peak}"
                )
            agreed += 1

    # 3. the seeded two-device mismatched-collective trace is refused
    hlo = (
        "HloModule tiny, num_partitions=4\n\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  ROOT %r = f32[8]{0} negate(%p0)\n"
        "}\n"
    )
    with tempfile.TemporaryDirectory() as td:
        trace = Path(td) / "deadlock"
        (trace / "modules").mkdir(parents=True)
        (trace / "modules" / "tiny.hlo").write_text(hlo)
        (trace / "meta.json").write_text(json.dumps(
            {"num_devices": 4, "device_kind": "cpu"}
        ))
        cmds = [
            {"kind": "kernel_launch", "module": "tiny", "device": 0},
            {"kind": "kernel_launch", "module": "tiny", "device": 1},
            {"kind": "collective", "device": 0, "bytes": 1024,
             "collective": {"kind": "all-reduce",
                            "replica_groups": [[0, 1]]}},
            {"kind": "collective", "device": 1, "bytes": 1024,
             "collective": {"kind": "all-gather",
                            "replica_groups": [[0, 1]]}},
        ]
        (trace / "commandlist.jsonl").write_text(
            "\n".join(json.dumps(c) for c in cmds) + "\n"
        )
        diags = analyze_trace_dir(trace, arch="v5p", tuned=False)
        deadlock = [
            d for d in diags.errors if d.code.startswith("TL41")
        ]
        if not deadlock:
            raise ValueError(
                "dataflow smoke: the seeded mismatched-collective "
                "trace was NOT flagged:\n"
                + "\n".join(diags.text_lines())
            )
        from tpusim.analysis import ValidationError
        from tpusim.sim.driver import simulate_trace

        try:
            simulate_trace(trace, arch="v5p", tuned=False,
                           validate="on")
        except ValidationError as e:
            if "TL41" not in str(e):
                raise ValueError(
                    f"dataflow smoke: --validate refused for the "
                    f"wrong reason: {e}"
                )
        else:
            raise ValueError(
                "dataflow smoke: simulate --validate priced the "
                "deadlocked trace instead of refusing it"
            )

    # 4. the self-audit over the repo itself
    audit = analyze_self_audit()
    if audit.items:
        raise ValueError(
            "dataflow smoke: TL35x self-audit is not clean:\n"
            + "\n".join(audit.text_lines())
        )
    return {
        "lint_cells": checked,
        "modules_agreed": agreed,
        "deadlock_code": deadlock[0].code,
    }


#: the TL501 seed: an async all-reduce that is ~100% exposed while an
#: independent 1024^3 dot sits after the join — the engineered defect
#: the perf-lint smoke requires BOTH front doors (lint --perf and
#: perf-report) to flag
PERF_LINT_TL501_HLO = """HloModule seeded501, is_scheduled=true, num_partitions=4

%r (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (p0: f32[2097152], p1: f32[1024,1024]) -> f32[2097152] {
  %p0 = f32[2097152]{0} parameter(0)
  %p1 = f32[1024,1024]{1,0} parameter(1)
  %st = f32[2097152]{0} all-reduce-start(%p0), channel_id=1, replica_groups={{0,1,2,3}}, to_apply=%r
  %dn = f32[2097152]{0} all-reduce-done(%st)
  %dot = f32[1024,1024]{1,0} dot(%p1, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %out = f32[2097152]{0} add(%dn, %dn)
}
"""


def perf_lint_smoke() -> dict:
    """Perf-lint contract smoke (`tpusim.analysis` v3, the TL5xx
    family):

    1. every committed fixture trace + golden-matrix arch runs
       ``lint --perf`` emitting the TL500 summary with ZERO TL5xx
       errors — the opt-in passes must not refuse a healthy workload;
    2. the three-way inequality holds per module per arch across the
       full fixture + silicon corpus: critical path <= engine total
       cycles <= serial op-cost sum, priced with the SAME composed
       config, and every collective's exposed cycles <= its priced
       cycles;
    3. the seeded exposed-collective module trips TL501 through BOTH
       front doors: ``analyze_trace_dir(perf=True)`` (what
       ``lint --perf`` runs) and the ``tpusim perf-report`` CLI;
    4. ``serve --strict-lint`` passes TL5xx findings through as
       warnings — a verdict whose only warnings are TL5xx ADMITS the
       trace;
    5. the TL35x self-audit (now including the TL353 lock-across-fork
       check) over the repo's own sources stays green.
    Raises on violation."""
    import subprocess
    import tempfile

    from tpusim.analysis import analyze_self_audit, analyze_trace_dir
    from tpusim.analysis.critpath import analyze_module_perf
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace

    fixtures = sorted({m[0] for m in MATRIX})
    arches = sorted({m[1] for m in MATRIX})

    # 1. healthy fixtures lint clean under --perf, with the summary
    checked = 0
    for fixture in fixtures:
        for arch in arches:
            diags = analyze_trace_dir(
                FIXTURES / fixture, arch=arch, tuned=False, perf=True,
            )
            if "TL500" not in diags.codes():
                raise ValueError(
                    f"perf-lint smoke: {fixture}@{arch} emitted no "
                    f"TL500 critical-path summary"
                )
            bad = [
                d for d in diags.errors if d.code.startswith("TL5")
            ]
            if bad:
                raise ValueError(
                    f"perf-lint smoke: {fixture}@{arch} has TL5xx "
                    f"errors on a healthy trace:\n"
                    + "\n".join(d.text() for d in bad)
                )
            checked += 1

    # 2. the inequality pin over the full corpus x matrix arches
    corpus = [FIXTURES / f for f in fixtures]
    silicon = REPO / "reports" / "silicon"
    if silicon.is_dir():
        corpus += sorted(
            d for d in silicon.iterdir() if (d / "modules").is_dir()
        )
    bracketed = 0
    for trace_dir in corpus:
        pod = load_trace(trace_dir)
        for arch in arches:
            cfg = load_config(arch=arch, tuned=False)
            for name, module in sorted(pod.modules.items()):
                mp = analyze_module_perf(module, cfg)
                eng = Engine(cfg).run(module).cycles
                tol = 1e-6 * max(eng, 1.0)
                if not (mp.critical_path_cycles <= eng + tol
                        <= mp.serial_cycles + 2 * tol):
                    raise ValueError(
                        f"perf-lint smoke: inequality violated on "
                        f"{trace_dir.name}/{name}@{arch}: critical "
                        f"{mp.critical_path_cycles} vs engine {eng} "
                        f"vs serial {mp.serial_cycles}"
                    )
                for cp in mp.comps.values():
                    for e in cp.exposures:
                        if e.exposed_cycles > e.priced_cycles + tol:
                            raise ValueError(
                                f"perf-lint smoke: {trace_dir.name}/"
                                f"{name}@{arch} collective {e.op}: "
                                f"exposed {e.exposed_cycles} > priced "
                                f"{e.priced_cycles}"
                            )
                bracketed += 1

    # 3. the seeded TL501 module trips through both front doors
    with tempfile.TemporaryDirectory() as td:
        trace = Path(td) / "seeded501"
        (trace / "modules").mkdir(parents=True)
        (trace / "modules" / "seeded501.hlo").write_text(
            PERF_LINT_TL501_HLO
        )
        (trace / "meta.json").write_text(json.dumps(
            {"num_devices": 4, "device_kind": "cpu"}
        ))
        (trace / "commandlist.jsonl").write_text(json.dumps(
            {"kind": "kernel_launch", "module": "seeded501",
             "device": 0}
        ) + "\n")
        diags = analyze_trace_dir(
            trace, arch="v5e", tuned=False, perf=True,
        )
        if "TL501" not in diags.codes():
            raise ValueError(
                "perf-lint smoke: lint --perf missed the seeded "
                "exposed collective:\n" + "\n".join(diags.text_lines())
            )
        proc = subprocess.run(
            [sys.executable, "-m", "tpusim", "perf-report",
             str(trace), "--arch", "v5e"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            raise ValueError(
                f"perf-lint smoke: perf-report exited "
                f"{proc.returncode} (warnings must not fail it): "
                f"{proc.stderr[-1500:]}"
            )
        if "TL501" not in proc.stdout:
            raise ValueError(
                "perf-lint smoke: perf-report did not surface the "
                "seeded TL501:\n" + proc.stdout[-1500:]
            )

    # 4. strict-lint serve admits TL5xx-only verdicts
    from tpusim.serve.daemon import ServeDaemon
    from tpusim.serve.client import ServeClient

    with ServeDaemon(trace_root=FIXTURES, strict_lint=True) as d:
        orig = d.worker.registry.trace_diagnostics

        def fake(entry):
            ds = orig(entry)
            ds.emit("TL500", "critical path summary (synthetic)")
            ds.emit("TL501", "collective 90% exposed (synthetic)")
            return ds
        d.worker.registry.trace_diagnostics = fake
        r = ServeClient(d.url).simulate(trace="matmul_512", arch="v5e")
        if not r.stats.get("sim_cycle", 0) > 0:
            raise ValueError(
                "perf-lint smoke: strict-lint serve failed to price "
                "a trace whose only findings are TL5xx"
            )
        refused = d.worker.stats_dict()["strict_lint_refused_total"]
        if refused:
            raise ValueError(
                f"perf-lint smoke: strict-lint serve refused "
                f"{refused} TL5xx-only request(s) — TL5xx must pass "
                f"through as warnings"
            )

    # 5. the self-audit (incl. TL353 lock-across-fork) stays green
    audit = analyze_self_audit()
    if audit.items:
        raise ValueError(
            "perf-lint smoke: TL35x self-audit is not clean:\n"
            + "\n".join(audit.text_lines())
        )
    return {
        "lint_cells": checked,
        "modules_bracketed": bracketed,
    }


#: stats the perf/guard layers add only when active — stripped before
#: golden comparison (the determinism contract covers the simulation
#: stats, not the layers' own accounting)
PERF_KEY_PREFIXES = ("cache_", "pool_", "guard_", "fastpath_")


def perf_smoke() -> dict:
    """Performance-layer determinism contract (tpusim.perf):

    1. the full golden matrix replayed with ``--workers 4`` and an
       on-disk result cache must reproduce the committed serial goldens
       byte-for-byte (modulo the layer's own ``cache_*``/``pool_*``
       accounting keys);
    2. a warm-cache second pass over the matrix must execute ZERO engine
       pricing walks — every module result comes from the cache;
    3. warm stats must equal cold stats exactly.
    Raises on violation."""
    import tempfile
    import time

    from tpusim.sim.driver import simulate_trace
    from tpusim.timing.engine import Engine

    runs = {"n": 0}
    orig_run = Engine.run

    def counting_run(self, module):
        runs["n"] += 1
        return orig_run(self, module)

    def run_matrix_perf(cache_dir: str, workers: int):
        out = {}
        for fixture, arch, overlays in MATRIX:
            name = f"{fixture}__{arch}"
            tag = _overlay_tag(overlays)
            if tag:
                name += "__" + tag
            report = simulate_trace(
                FIXTURES / fixture, arch=arch, overlays=list(overlays),
                tuned=False, workers=workers, result_cache=cache_dir,
            )
            out[name] = {
                k: v for k, v in json.loads(report.stats.to_json()).items()
                if k not in VOLATILE
                and not k.startswith(PERF_KEY_PREFIXES)
            }
        return out

    Engine.run = counting_run
    try:
        with tempfile.TemporaryDirectory(prefix="tpusim_perf_smoke_") as td:
            t0 = time.perf_counter()
            cold = run_matrix_perf(td, workers=4)
            cold_s = time.perf_counter() - t0
            errors = compare(cold)
            if errors:
                raise ValueError(
                    "parallel+cached matrix diverged from committed "
                    "serial goldens:\n  " + "\n  ".join(errors)
                )
            runs["n"] = 0
            t0 = time.perf_counter()
            warm = run_matrix_perf(td, workers=4)
            warm_s = time.perf_counter() - t0
            if runs["n"] != 0:
                raise ValueError(
                    f"warm-cache matrix still executed {runs['n']} "
                    f"engine pricing walks (expected 0)"
                )
            if warm != cold:
                diff = [
                    n for n in cold
                    if warm.get(n) != cold[n]
                ]
                raise ValueError(
                    f"warm-cache stats diverged from cold for {diff}"
                )
    finally:
        Engine.run = orig_run
    return {
        "configs": len(cold),
        "cold_s": cold_s,
        "warm_s": warm_s,
    }


def _serve_golden_bytes(name: str) -> str:
    """One committed golden artifact, raw bytes-as-text — the anchor
    both serve smokes compare served responses against."""
    path = GOLDEN_DIR / f"{name}.json"
    if not path.exists():
        raise ValueError(f"no golden file {path} (run --update)")
    return path.read_text()


def _serve_served_bytes(stats: dict) -> str:
    """A served stats doc rendered EXACTLY as the golden writer renders
    the CLI's (volatile + perf-accounting keys stripped, same dumps
    args) — the one canon both serve smokes must enforce."""
    doc = {
        k: v for k, v in stats.items()
        if k not in VOLATILE and not k.startswith(PERF_KEY_PREFIXES)
    }
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def serve_smoke(serve_workers: int = 0) -> dict:
    """Serving-layer determinism contract (tpusim.serve):

    1. a daemon booted on a free loopback port, serving the committed
       fixture traces, must answer every golden-matrix request with a
       stats doc BYTE-IDENTICAL to the committed CLI golden (same
       JSON serialization, volatile + perf-accounting keys stripped);
    2. a warm second pass over the same requests must serve every
       response from the result cache: ``cache_hit`` true on each and
       ZERO engine pricing walks anywhere in the process;
    3. ``/metrics`` must parse as Prometheus text and carry the serve
       counters; ``/healthz`` must be ok; the drain must complete.

    ``serve_workers > 0`` runs the same contract through the serve v2
    supervised pre-forked pool — the byte-identity claim across 1..N
    workers.  There the engine-walk counter guards the PARENT process
    over BOTH passes (every request must be priced by the pool, never
    the in-process fallback) and the warm pass must be all ``cache_hit``
    (content-hash affinity lands repeats on the worker whose L1 is warm;
    a cache hit is by construction a request priced with zero walks).
    Raises on violation."""
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon
    from tpusim.timing.engine import Engine

    runs = {"n": 0}
    orig_run = Engine.run

    def counting_run(self, module):
        runs["n"] += 1
        return orig_run(self, module)

    golden_bytes = _serve_golden_bytes
    served_bytes = _serve_served_bytes

    def run_pass(client) -> list[tuple[str, dict, bool]]:
        out = []
        for fixture, arch, overlays in MATRIX:
            name = f"{fixture}__{arch}"
            tag = _overlay_tag(overlays)
            if tag:
                name += "__" + tag
            r = client.simulate(
                trace=fixture, arch=arch, overlays=list(overlays),
                tuned=False,
            )
            out.append((name, r.stats, r.cache_hit))
        return out

    daemon = ServeDaemon(
        trace_root=FIXTURES, max_inflight=4,
        serve_workers=max(int(serve_workers), 0),
    )
    if serve_workers > 0:
        # the pool prices in child processes; the parent must price
        # NOTHING in either pass — count from before the cold pass
        Engine.run = counting_run
    daemon.start()
    try:
        client = ServeClient(daemon.url)
        health = client.healthz()
        if health.get("status") != "ok":
            raise ValueError(f"healthz not ok: {health}")
        if serve_workers > 0:
            if health.get("workers_alive") != serve_workers:
                raise ValueError(
                    f"expected {serve_workers} live workers, healthz "
                    f"reports {health.get('workers_alive')}"
                )

        cold = run_pass(client)
        for name, stats, _hit in cold:
            got = served_bytes(stats)
            want = golden_bytes(name)
            if got != want:
                raise ValueError(
                    f"served stats for {name} diverged from the "
                    f"committed CLI golden (byte comparison failed, "
                    f"serve_workers={serve_workers})"
                )

        if serve_workers == 0:
            Engine.run = counting_run
        try:
            warm = run_pass(client)
        finally:
            Engine.run = orig_run
        if runs["n"] != 0:
            what = (
                "the parent process still executed"
                if serve_workers > 0 else "warm pass still executed"
            )
            raise ValueError(
                f"{what} {runs['n']} engine pricing walks (expected 0: "
                f"every request must be served from the "
                f"{'pool' if serve_workers > 0 else 'shared result cache'})"
            )
        missed = [name for name, _s, hit in warm if not hit]
        if missed:
            raise ValueError(
                f"warm pass responses did not report cache_hit: {missed}"
            )
        for (name, cold_stats, _h1), (_n2, warm_stats, _h2) in zip(
            cold, warm
        ):
            if served_bytes(cold_stats) != served_bytes(warm_stats):
                raise ValueError(
                    f"warm served stats diverged from cold for {name}"
                )

        prom = client.metrics_text()
        gauges = 0
        for line in prom.splitlines():
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"bad prometheus line: {line!r}")
            float(parts[1])
            gauges += 1
        required = ["serve_requests_total", "serve_cache_hits"]
        if serve_workers > 0:
            required += [
                "serve_workers_alive", "serve_worker_restarts_total",
                "serve_worker_kills_total", "serve_quarantine_size",
                "serve_shed_503_total",
            ]
        for key in required:
            if f"tpusim_{key} " not in prom:
                raise ValueError(f"/metrics missing {key}")
    finally:
        Engine.run = orig_run
        if not daemon.drain_and_stop():
            raise ValueError("daemon did not drain cleanly")
    return {
        "configs": len(cold),
        "warm_cache_hits": len(warm),
        "gauges": gauges,
        "serve_workers": max(int(serve_workers), 0),
    }


def serve_chaos_smoke(serve_workers: int = 2) -> dict:
    """Serve v2 survivability contract: SIGKILL a worker while the
    golden matrix is in flight and the run must still finish green —

    1. ZERO failed requests: the killed worker's request is retried on
       a fresh worker and every response (including the retried one)
       stays byte-identical to the committed CLI goldens;
    2. at least one worker restart is recorded by the supervisor (the
       kill really landed, the fleet really healed);
    3. the daemon drains cleanly afterwards.
    Raises on violation."""
    import threading

    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    golden_bytes = _serve_golden_bytes
    served_bytes = _serve_served_bytes

    daemon = ServeDaemon(
        trace_root=FIXTURES, max_inflight=4, serve_workers=serve_workers,
    )
    daemon.start()
    sup = daemon.supervisor
    stop_chaos = threading.Event()
    killed = {"pid": None}

    def chaos():
        # wait for a request to be mid-flight on some worker, then
        # SIGKILL that worker exactly once — the worst-timed crash
        while not stop_chaos.is_set():
            for slot in sup.slots:
                if slot.busy and slot.pid is not None:
                    killed["pid"] = slot.pid
                    sup.kill_worker(slot.index)
                    return
            stop_chaos.wait(0.002)

    chaos_thread = threading.Thread(target=chaos, daemon=True)
    try:
        client = ServeClient(daemon.url)
        chaos_thread.start()
        failures = []
        passes = 2  # enough traffic that the kill lands mid-pass
        for _ in range(passes):
            for fixture, arch, overlays in MATRIX:
                name = f"{fixture}__{arch}"
                tag = _overlay_tag(overlays)
                if tag:
                    name += "__" + tag
                try:
                    r = client.simulate(
                        trace=fixture, arch=arch,
                        overlays=list(overlays), tuned=False,
                    )
                except Exception as e:  # noqa: BLE001 - the contract
                    failures.append(f"{name}: {type(e).__name__}: {e}")
                    continue
                if served_bytes(r.stats) != golden_bytes(name):
                    failures.append(f"{name}: stats diverged from golden")
        stop_chaos.set()
        chaos_thread.join(timeout=5.0)
        if killed["pid"] is None:
            raise ValueError(
                "chaos kill never landed (no worker was ever observed "
                "busy — did the pool serve anything?)"
            )
        if failures:
            raise ValueError(
                f"{len(failures)} request(s) failed after the worker "
                f"kill: {failures[:4]}"
            )
        restarts = sum(s.restarts for s in sup.slots)
        if restarts < 1:
            raise ValueError(
                "worker was killed but the supervisor recorded no restart"
            )
    finally:
        stop_chaos.set()
        if not daemon.drain_and_stop():
            raise ValueError("daemon did not drain cleanly after chaos")
    return {
        "configs": len(MATRIX) * passes,
        "killed_pid": killed["pid"],
        "restarts": restarts,
        "retries": sup.retried,
    }


#: the campaign smoke: a fixed-seed 16-scenario Monte-Carlo campaign on
#: the llama_tiny fixture whose report must be BYTE-identical to the
#: committed golden.  Seed 3 was picked to exercise every outcome class:
#: partitioned scenarios (correlated axis bundles on dim-2 axes), a
#: spread of compound-fault inflations, and a non-null capacity answer
#: selecting the smallest candidate slice.  tuned=False like every
#: golden: the report must not shift when a live run refreshes the fit.
CAMPAIGN_SMOKE_FIXTURE = "llama_tiny_tp2dp2"
CAMPAIGN_SMOKE_GOLDEN = GOLDEN_DIR / "campaign_smoke.json"
CAMPAIGN_SMOKE_SPEC = {
    "name": "ci-campaign-smoke",
    "seed": 3,
    "scenarios": 16,
    "arch": "v5p",
    "chips": 8,
    "tuned": False,
    "faults": {
        "count": {"dist": "uniform", "min": 0, "max": 3},
        "kinds": {"link_down": 1.0, "link_degraded": 1.0,
                  "chip_straggler": 0.5, "hbm_throttle": 0.5},
        "scale": {"min": 0.4, "max": 0.9},
    },
    "correlated_groups": [
        {"name": "cable-bundle-y", "prob": 0.06, "axis": 1},
        {"name": "cable-bundle-z", "prob": 0.06, "axis": 2},
    ],
    "slo": {"step_time_ms": 0.55, "percentile": 90},
    "candidate_slices": [{"arch": "v5p", "chips": 4},
                         {"arch": "v5p", "chips": 16}],
}


def campaign_smoke(update: bool = False) -> dict:
    """Campaign-layer determinism contract (tpusim.campaign):

    1. the fixed-seed campaign's report document must be byte-identical
       to the committed golden (regen with ``--campaign-smoke
       --update`` after an intended model/report change);
    2. the report must carry every contract piece: inflation
       p50/p95/p99/max, a nonzero partition rate, per-scenario energy
       deltas, and a capacity table with a non-null smallest meeting
       slice (watts joined from power/model.py);
    3. the healthy-path golden matrix must stay byte-identical as
       always — a campaign run must not perturb healthy pricing.
    Raises on violation."""
    from tpusim.campaign import run_campaign

    res = run_campaign(
        CAMPAIGN_SMOKE_SPEC,
        trace_path=FIXTURES / CAMPAIGN_SMOKE_FIXTURE,
    )
    got = json.dumps(res.doc, indent=1, sort_keys=True) + "\n"
    if update:
        GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
        CAMPAIGN_SMOKE_GOLDEN.write_text(got)
    if not CAMPAIGN_SMOKE_GOLDEN.exists():
        raise ValueError(
            f"no campaign golden {CAMPAIGN_SMOKE_GOLDEN} "
            f"(run --campaign-smoke --update)"
        )
    want = CAMPAIGN_SMOKE_GOLDEN.read_text()
    if got != want:
        raise ValueError(
            "campaign smoke: fixed-seed report diverged from the "
            "committed golden (byte comparison failed) — a timing-model "
            "or campaign-report change must regen with "
            "--campaign-smoke --update"
        )

    doc = res.doc
    primary = doc["slices"][0]
    for key in ("p50", "p95", "p99", "max"):
        if not isinstance(primary["inflation"].get(key), float):
            raise ValueError(f"campaign smoke: inflation.{key} missing")
    if not any(s["partition_rate"] > 0 for s in doc["slices"]):
        raise ValueError(
            "campaign smoke: no slice saw a partitioned scenario "
            "(the seed was chosen to produce them)"
        )
    cap = doc.get("capacity")
    if not cap or cap.get("smallest_meeting_slice") is None:
        raise ValueError("campaign smoke: capacity answer missing/null")
    if not all(
        isinstance(r.get("healthy_watts"), float) for r in cap["table"]
    ):
        raise ValueError(
            "campaign smoke: capacity table rows missing watts"
        )
    stats = res.stats.stats_dict()
    if stats["campaign_partitioned_total"] < 1:
        raise ValueError("campaign smoke: campaign_partitioned_total=0")

    errors = compare(run_matrix())
    if errors:
        raise ValueError(
            "campaign smoke: healthy-path golden matrix diverged:\n  "
            + "\n  ".join(errors)
        )
    return {
        "scenarios": stats["campaign_scenarios_total"],
        "partitioned": stats["campaign_partitioned_total"],
        "capacity": cap["smallest_meeting_slice"],
        "matrix_configs": len(MATRIX),
    }


#: the dcn smoke: a fixed-seed campaign over a 2-slice system with a
#: modeled DCN fabric and slice-targeted fault kinds.  Seed 7 on a
#: 4-chip / 2-slice spec was picked so the sampler lands both
#: slice-loss scenarios (the "how many slices survive" answer) and
#: surviving-fabric scenarios in 8 draws.  tuned=False like every
#: golden: the report must not shift when a live run refreshes the fit.
DCN_SMOKE_FIXTURE = "llama_tiny_tp2dp2"
DCN_SMOKE_GOLDEN = GOLDEN_DIR / "dcn_smoke.json"
DCN_SMOKE_SPEC = {
    "name": "ci-dcn-smoke",
    "seed": 7,
    "scenarios": 8,
    "arch": "v5p",
    "chips": 4,
    "tuned": False,
    "dcn": {
        "num_slices": 2,
        "nics_per_slice": 2,
        "nic_bandwidth": 25e9,
        "hop_latency": 1e-5,
    },
    "faults": {
        "count": {"dist": "uniform", "min": 1, "max": 2},
        "kinds": {"slice_down": 2.0, "dcn_link_down": 1.0,
                  "link_degraded": 0.5},
        "scale": {"min": 0.4, "max": 0.9},
    },
}


def dcn_smoke(update: bool = False) -> dict:
    """Multi-slice fabric contract (tpusim.dcn):

    1. the fixed-seed DCN campaign's report must be byte-identical to
       the committed golden (regen with ``--dcn-smoke --update``);
    2. the report must answer slice survival: a ``dcn`` section with at
       least one slice-loss scenario, a survival histogram covering
       every scenario, and slice-loss rows attributed as partitions;
    3. the hierarchical decomposition must actually engage — a
       slice-spanning all-reduce over the fabric prices strictly
       cheaper than the flat scalar model at a bandwidth-bound payload;
    4. an UNCONFIGURED fabric must price byte-identically to the flat
       model (the back-compat degeneration contract).
    Raises on violation."""
    from tpusim.campaign import run_campaign
    from tpusim.ici.collectives import CollectiveModel
    from tpusim.ici.topology import torus_for
    from tpusim.timing.config import load_config

    res = run_campaign(
        DCN_SMOKE_SPEC, trace_path=FIXTURES / DCN_SMOKE_FIXTURE,
    )
    got = json.dumps(res.doc, indent=1, sort_keys=True) + "\n"
    if update:
        GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
        DCN_SMOKE_GOLDEN.write_text(got)
    if not DCN_SMOKE_GOLDEN.exists():
        raise ValueError(
            f"no dcn golden {DCN_SMOKE_GOLDEN} (run --dcn-smoke --update)"
        )
    if got != DCN_SMOKE_GOLDEN.read_text():
        raise ValueError(
            "dcn smoke: fixed-seed report diverged from the committed "
            "golden (byte comparison failed) — a fabric-model or "
            "campaign-report change must regen with --dcn-smoke --update"
        )

    sl = res.doc["slices"][0]
    dcn = sl.get("dcn")
    if not dcn or dcn["slice_loss_scenarios"] < 1:
        raise ValueError(
            "dcn smoke: no slice-loss scenario landed (the seed was "
            "chosen to produce them)"
        )
    if sum(dcn["slices_ok_hist"].values()) != sl["scenarios"]:
        raise ValueError(
            "dcn smoke: survival histogram does not cover every scenario"
        )
    for row in res.doc["rows"]:
        if row["dcn"]["slices_lost"] > 0 and \
                row.get("status") != "partitioned":
            raise ValueError(
                f"dcn smoke: slice-loss row {row['index']} not "
                f"attributed as a partition"
            )

    def _ici(overlay):
        return load_config(
            arch="v5p", overlays=[{"arch": {"ici": overlay}}],
            tuned=False,
        ).arch.ici

    topo = torus_for(8, "v5p")
    payload = float(64 << 20)
    flat = CollectiveModel(topo, _ici({"chips_per_slice": 4}))
    fab = CollectiveModel(topo, _ici({
        "chips_per_slice": 4, "dcn_nics_per_slice": 4,
        "dcn_hop_bandwidth": 25e9, "dcn_hop_latency": 1e-5,
    }))
    flat_s = flat.allreduce_seconds(payload, 8)
    hier_s = fab.allreduce_seconds(payload, 8)
    if not hier_s < flat_s:
        raise ValueError(
            f"dcn smoke: hierarchical all-reduce did not beat the flat "
            f"model ({hier_s} vs {flat_s})"
        )
    unconfigured = CollectiveModel(topo, _ici({
        "chips_per_slice": 4, "dcn_hop_bandwidth": 25e9,
    }))
    if unconfigured.allreduce_seconds(payload, 8) != flat_s:
        raise ValueError(
            "dcn smoke: NIC-less config did not degenerate "
            "byte-identically to the flat scalar model"
        )
    return {
        "scenarios": sl["scenarios"],
        "slice_losses": dcn["slice_loss_scenarios"],
        "min_slices_ok": dcn["min_slices_ok"],
        "hier_speedup": flat_s / hier_s,
    }


#: the fleet smoke: a fixed-seed fleet digital-twin run on the
#: llama_tiny fixture whose report must be BYTE-identical to the
#: committed golden.  Seed 3 + pod_loss prob 0.9 was picked to exercise
#: every contract piece at once: both pods crash (restart windows +
#: elastic-recovery rows), the 30 req/s load point overruns the
#: 4-deep queue (a real shedding window), and the 12 req/s frontier
#: target lands a non-null pods-needed answer inside max_pods.
#: tuned=False like every golden: the report must not shift when a
#: live run refreshes the fit.
FLEET_SMOKE_FIXTURE = "llama_tiny_tp2dp2"
FLEET_SMOKE_GOLDEN = GOLDEN_DIR / "fleet_smoke.json"
FLEET_SMOKE_SPEC = {
    "name": "ci-fleet-smoke",
    "seed": 3,
    "pods": 2,
    "arch": "v5p",
    "chips": 8,
    "tuned": False,
    "horizon_s": 30.0,
    "traffic": {
        "shape": "bursty",
        "load_points": [5.0, 30.0],
        "burst": {"factor": 4.0, "fraction": 0.1, "period_s": 20.0},
        "mix": [{"name": "chat", "weight": 3.0, "steps": 100},
                {"name": "batch", "weight": 1.0, "steps": 400}],
    },
    "faults": {
        "count": {"dist": "uniform", "min": 0, "max": 2},
        "kinds": {"link_down": 1.0, "hbm_throttle": 1.0},
        "scale": {"min": 0.4, "max": 0.9},
        "window": {"min_s": 10.0, "max_s": 30.0},
        "pod_loss": {"prob": 0.9},
    },
    "policies": {
        "max_inflight": 1,
        "queue_depth": 4,
        "deadline_s": 0.5,
        "restart_backoff_s": 5.0,
    },
    "slo": {"latency_ms": 400.0, "percentile": 95},
    "frontier": {"target_rps": [12.0], "max_pods": 4},
}


def fleet_smoke(update: bool = False) -> dict:
    """Fleet-twin determinism contract (tpusim.fleet):

    1. the fixed-seed fleet run's report document must be byte-identical
       to the committed golden (regen with ``--fleet-smoke --update``
       after an intended model/report change);
    2. the report must carry every contract piece: a goodput/p99 curve
       with latency percentiles, per-policy loss attribution with a
       LIVE shedding bucket, at least one pod loss with an
       elastic-recovery row, energy per served request, and a non-null
       capacity-frontier answer;
    3. the healthy-path golden matrix must stay byte-identical as
       always — a fleet run must not perturb healthy pricing.
    Raises on violation."""
    from tpusim.fleet import run_fleet

    res = run_fleet(
        FLEET_SMOKE_SPEC,
        trace_path=FIXTURES / FLEET_SMOKE_FIXTURE,
    )
    got = json.dumps(res.doc, indent=1, sort_keys=True) + "\n"
    if update:
        GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
        FLEET_SMOKE_GOLDEN.write_text(got)
    if not FLEET_SMOKE_GOLDEN.exists():
        raise ValueError(
            f"no fleet golden {FLEET_SMOKE_GOLDEN} "
            f"(run --fleet-smoke --update)"
        )
    want = FLEET_SMOKE_GOLDEN.read_text()
    if got != want:
        raise ValueError(
            "fleet smoke: fixed-seed report diverged from the "
            "committed golden (byte comparison failed) — a timing-model "
            "or fleet-report change must regen with "
            "--fleet-smoke --update"
        )

    doc = res.doc
    stats = res.stats.stats_dict()
    for row in doc["curve"]:
        lat = row["latency_ms"]
        if lat is None or not all(
            isinstance(lat.get(k), float) for k in ("p50", "p99")
        ):
            raise ValueError("fleet smoke: curve latency dist missing")
        if row["served"] and row["energy_per_request_j"] is None:
            raise ValueError(
                "fleet smoke: energy per request missing on a serving "
                "curve row"
            )
    if stats["fleet_lost_shed_total"] < 1:
        raise ValueError(
            "fleet smoke: no shedding losses (the overload load point "
            "was chosen to produce them)"
        )
    if stats["fleet_pod_losses_total"] < 1 or not doc["recovery"]:
        raise ValueError(
            "fleet smoke: no pod loss / recovery row (the seed was "
            "chosen to produce them)"
        )
    for rec in doc["recovery"]:
        if rec["time_to_recover_s"] <= 0:
            raise ValueError("fleet smoke: non-positive time-to-recover")
    table = doc["frontier"]["table"]
    if not table or table[0]["pods_needed"] is None:
        raise ValueError("fleet smoke: capacity frontier answer is null")

    errors = compare(run_matrix())
    if errors:
        raise ValueError(
            "fleet smoke: healthy-path golden matrix diverged:\n  "
            + "\n  ".join(errors)
        )
    return {
        "requests": stats["fleet_requests_total"],
        "served": stats["fleet_served_total"],
        "shed": stats["fleet_lost_shed_total"],
        "pod_losses": stats["fleet_pod_losses_total"],
        "pods_needed": table[0]["pods_needed"],
        "matrix_configs": len(MATRIX),
    }


#: the advise smoke: a fixed-spec strategy sweep on the llama_tiny
#: fixture whose ranked report must be BYTE-identical to the committed
#: golden.  The spec covers every synthesizable family (dp, tp, every
#: dp x tp factorization, ring-attention sp, pipeline pp, one pinned
#: composite mesh) on two slice types — 14 cells, comfortably past the
#: 12-cell acceptance floor — and the dp=4 x tp=2 cell's per-chip
#: collective count must equal MULTICHIP_r05's 14.  tuned=False like
#: every golden: the report must not shift when a live run refreshes
#: the fit.
ADVISE_SMOKE_FIXTURE = "llama_tiny_tp2dp2"
ADVISE_SMOKE_GOLDEN = GOLDEN_DIR / "advise_smoke.json"
ADVISE_SMOKE_SPEC = {
    "name": "ci-advise-smoke",
    "strategies": ["dp", "tp", "dp_tp", "sp", "pp"],
    "slices": [{"arch": "v5p", "chips": 8},
               {"arch": "v5e", "chips": 8}],
    "meshes": [{"dp": 2, "tp": 2, "pp": 2}],
    "tuned": False,
    "slo": {"step_time_ms": 1.0},
}


def advise_smoke(update: bool = False) -> dict:
    """Sharding-advisor determinism contract (tpusim.advise):

    1. the fixed-spec sweep's ranked report must be byte-identical to
       the committed golden (regen with ``--advise-smoke --update``
       after an intended model/transform change);
    2. a warm second pass through the same shared result cache must
       execute ZERO engine pricing walks and reproduce the report
       byte-for-byte;
    3. the report must carry the contract columns (step_ms, ici_bytes,
       hbm_resident_gib, watts, slo_ok) on >= 12 ranked cells with a
       non-null recommendation, and the dp=4 x tp=2 cell must
       synthesize the 14-collective step MULTICHIP_r05 measured;
    4. the healthy-path golden matrix must stay byte-identical — an
       advise sweep must not perturb healthy pricing.
    Raises on violation."""
    from tpusim.advise import run_advise
    from tpusim.perf.cache import ResultCache
    from tpusim.timing.engine import Engine

    cache = ResultCache()
    res = run_advise(
        ADVISE_SMOKE_SPEC,
        trace_path=FIXTURES / ADVISE_SMOKE_FIXTURE,
        result_cache=cache,
    )
    got = json.dumps(res.doc, indent=1, sort_keys=True) + "\n"
    if update:
        GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
        ADVISE_SMOKE_GOLDEN.write_text(got)
    if not ADVISE_SMOKE_GOLDEN.exists():
        raise ValueError(
            f"no advise golden {ADVISE_SMOKE_GOLDEN} "
            f"(run --advise-smoke --update)"
        )
    want = ADVISE_SMOKE_GOLDEN.read_text()
    if got != want:
        raise ValueError(
            "advise smoke: fixed-spec report diverged from the "
            "committed golden (byte comparison failed) — a timing-"
            "model or transform change must regen with "
            "--advise-smoke --update"
        )

    runs = {"n": 0}
    orig_run = Engine.run

    def counting_run(self, module):
        runs["n"] += 1
        return orig_run(self, module)

    Engine.run = counting_run
    try:
        warm = run_advise(
            ADVISE_SMOKE_SPEC,
            trace_path=FIXTURES / ADVISE_SMOKE_FIXTURE,
            result_cache=cache,
        )
    finally:
        Engine.run = orig_run
    if runs["n"] != 0:
        raise ValueError(
            f"advise smoke: warm pass still executed {runs['n']} "
            f"engine pricing walks (expected 0: every cell's compute "
            f"module must come from the shared cache)"
        )
    if json.dumps(warm.doc, indent=1, sort_keys=True) + "\n" != got:
        raise ValueError(
            "advise smoke: warm report diverged from cold"
        )

    doc = res.doc
    cells = doc["cells"]
    if len(cells) < 12:
        raise ValueError(
            f"advise smoke: only {len(cells)} ranked cells (>= 12 "
            f"required by the acceptance contract)"
        )
    for col in ("step_ms", "ici_bytes", "hbm_resident_gib", "watts",
                "slo_ok", "collectives_per_chip"):
        if any(col not in r for r in cells):
            raise ValueError(f"advise smoke: cell column {col!r} missing")
    dp4tp2 = [r for r in cells if r["mesh"] == {"dp": 4, "tp": 2}]
    if not dp4tp2 or dp4tp2[0]["collectives_per_chip"] != 14:
        raise ValueError(
            "advise smoke: dp=4 x tp=2 cell does not synthesize the "
            "14-collective step MULTICHIP_r05 measured "
            f"(got {dp4tp2[0]['collectives_per_chip'] if dp4tp2 else 'no cell'})"
        )
    if doc["recommendation"] is None:
        raise ValueError("advise smoke: recommendation is null")

    errors = compare(run_matrix())
    if errors:
        raise ValueError(
            "advise smoke: healthy-path golden matrix diverged:\n  "
            + "\n  ".join(errors)
        )
    return {
        "cells": len(cells),
        "feasible": sum(1 for r in cells if r["feasible"]),
        "recommendation": doc["recommendation"]["cell"],
        "matrix_configs": len(MATRIX),
    }


def fastpath_smoke() -> dict:
    """CI tier: pricing-backend parity (the tpusim.fastpath contract).

    1. every golden-matrix config must produce BYTE-identical stats
       docs through the serial reference walk, the NumPy-vectorized
       fastpath, and (when built) the native kernel — modulo the
       opt-in ``fastpath_*`` accounting block;
    2. the serial doc must still match the committed golden, so the
       parity chain is anchored to the committed model, not merely
       self-consistent;
    3. a streaming leg re-runs the matrix with every module file-backed
       (``TPUSIM_STREAM_THRESHOLD=0``) and must match the committed
       goldens too — bounded-RSS pricing is not allowed to change a
       single stat;
    4. a durable leg persists compiled columns to a throwaway store,
       clears the in-memory compiled tier, and re-runs the matrix
       through DISK-loaded columns (traces reloaded with deferred
       parsing): byte-identical to the goldens, zero recompiles, the
       store provably hit.  (The cold-serve half of the tier lives in
       :func:`cold_serve_smoke`.)"""
    import os

    from tpusim.fastpath import native_price_available, numpy_available
    from tpusim.sim.driver import simulate_trace

    backends = ["serial"]
    if numpy_available():
        backends.append("vectorized")
    if native_price_available():
        backends.append("native")
    if backends == ["serial"]:
        raise ValueError(
            "fastpath parity needs at least the vectorized backend "
            "(numpy not importable)"
        )

    def run_row(fixture: str, arch: str, overlays: list, backend):
        report = simulate_trace(
            FIXTURES / fixture, arch=arch, overlays=list(overlays),
            tuned=False, pricing_backend=backend,
        )
        return {
            k: v for k, v in json.loads(report.stats.to_json()).items()
            if k not in VOLATILE and not k.startswith("fastpath_")
        }

    serial_docs: dict[str, dict] = {}
    for fixture, arch, overlays in MATRIX:
        name = f"{fixture}__{arch}"
        tag = _overlay_tag(overlays)
        if tag:
            name += "__" + tag
        docs = {
            b: run_row(fixture, arch, overlays, b) for b in backends
        }
        blobs = {
            b: json.dumps(d, sort_keys=True) for b, d in docs.items()
        }
        if len(set(blobs.values())) != 1:
            diverged = [b for b in backends[1:]
                        if blobs[b] != blobs["serial"]]
            raise ValueError(
                f"{name}: pricing backends diverged from the serial "
                f"walk: {diverged} — the fastpath byte-identity "
                f"contract is broken"
            )
        serial_docs[name] = docs["serial"]
    errors = compare(serial_docs)
    if errors:
        raise ValueError(
            "fastpath parity: serial anchor diverged from committed "
            "goldens:\n  " + "\n  ".join(errors)
        )

    # streaming leg: every module file-backed, default (auto) backend
    prev = os.environ.get("TPUSIM_STREAM_THRESHOLD")
    os.environ["TPUSIM_STREAM_THRESHOLD"] = "0"
    try:
        streamed = run_matrix()
    finally:
        if prev is None:
            os.environ.pop("TPUSIM_STREAM_THRESHOLD", None)
        else:
            os.environ["TPUSIM_STREAM_THRESHOLD"] = prev
    errors = compare(streamed)
    if errors:
        raise ValueError(
            "fastpath parity: streaming (file-backed) replay diverged "
            "from committed goldens:\n  " + "\n  ".join(errors)
        )

    # durable leg (tpusim.fastpath.store): compiled columns persisted
    # to a throwaway store must serve a fresh-process-equivalent replay
    # (in-memory compiled tier cleared, traces reloaded with deferred
    # parsing) byte-identically, with zero recompiles and the store
    # provably hit
    import shutil
    import tempfile

    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.perf.cache import clear_compiled_cache, compiled_cache_stats

    store_dir = tempfile.mkdtemp(prefix="tpusim-ci-cmod-")
    try:
        set_compile_store(CompileStore(store_dir))
        run_matrix()  # populate: pricing persists columns post-walk
        clear_compiled_cache()
        store = CompileStore(store_dir)
        set_compile_store(store)
        misses_before = compiled_cache_stats()["compile_misses"]
        disk_docs = {
            name: {
                k: v for k, v in doc.items()
                if not k.startswith(PERF_KEY_PREFIXES)
            }
            for name, doc in run_matrix().items()
        }
        misses_after = compiled_cache_stats()["compile_misses"]
        errors = compare(disk_docs)
        if errors:
            raise ValueError(
                "fastpath parity: disk-loaded compiled replay diverged "
                "from committed goldens:\n  " + "\n  ".join(errors)
            )
        if misses_after != misses_before:
            raise ValueError(
                f"durable pass recompiled "
                f"{misses_after - misses_before} module(s) despite a "
                f"warm compile store (expected 0)"
            )
        if store.hits <= 0:
            raise ValueError(
                "durable pass never hit the compile store"
            )
        store_hits = store.hits
    finally:
        set_compile_store(None)
        clear_compiled_cache()
        shutil.rmtree(store_dir, ignore_errors=True)
    return {
        "configs": len(serial_docs),
        "backends": backends,
        "streamed_configs": len(streamed),
        "durable_configs": len(disk_docs),
        "durable_store_hits": store_hits,
    }


def scenario_batch_smoke() -> dict:
    """BATCHED leg of the fastpath-parity tier (PR 19): a 48-scenario
    compound-fault campaign priced through the scenario-batched
    lane-axis warm pass must produce a report document BYTE-identical
    to the pure per-state walk, with the batch pass provably engaged
    (``batch_stats.states`` — the ``fastpath_batched_states`` counter
    — strictly positive).  The batching contract is "faster, not
    different": the pass is only allowed to pre-fill the shared result
    cache under the same per-state keys the serial walk mints."""
    from tpusim.campaign import run_campaign

    spec = {
        "name": "ci-batch", "seed": 19, "scenarios": 48,
        "arch": "v5p", "chips": 8, "tuned": False,
        "faults": {
            "count": {"dist": "uniform", "min": 0, "max": 2},
            "kinds": {"link_down": 1.0, "link_degraded": 1.0,
                      "chip_straggler": 0.5, "hbm_throttle": 0.5},
            "scale": {"min": 0.4, "max": 0.9},
        },
    }
    trace = FIXTURES / "llama_tiny_tp2dp2"
    batched = run_campaign(dict(spec), trace_path=trace)
    per_state = run_campaign(dict(spec), trace_path=trace,
                             scenario_batch=False)
    b_blob = json.dumps(batched.doc, sort_keys=True)
    s_blob = json.dumps(per_state.doc, sort_keys=True)
    if b_blob != s_blob:
        raise ValueError(
            "scenario-batched campaign report diverged from the "
            "per-state walk — the batch byte-identity contract is "
            "broken"
        )
    bs = batched.batch_stats
    if bs is None or bs.states <= 0:
        raise ValueError(
            "batched campaign never engaged the lane-axis pass "
            "(fastpath_batched_states == 0): the parity leg proved "
            "nothing"
        )
    if per_state.batch_stats is not None:
        raise ValueError(
            "scenario_batch=False still constructed batch accounting"
        )
    return {
        "scenarios": spec["scenarios"],
        "batched_states": bs.states,
        "batch_groups": bs.groups,
    }


def cold_serve_smoke() -> dict:
    """The durable tier's cold-path contract, end to end: a FRESH
    daemon process booted against a warm disk compile store must price
    its first request with **zero Python IR construction** — no parse,
    no span index, no computation objects; just mmapped columns.

    Proven over the process boundary via the stats the driver stamps
    when the store is active: ``fastpath_ir_ops_built`` (the
    process-wide op-construction counter) must be 0 and
    ``fastpath_store_hits`` >= 1 on the response, and ``/metrics`` must
    expose the compile-cache counters.  The first-request wall time is
    reported (the ~660 ms -> <70 ms trajectory lives in BENCH/serve
    bench artifacts; a CI container's absolute latency is not a
    contract)."""
    import shutil
    import signal
    import subprocess
    import tempfile
    import time
    import urllib.request

    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.perf.cache import clear_compiled_cache
    from tpusim.sim.driver import simulate_trace

    fixture, arch, _ = MATRIX[2]  # llama_tiny_tp2dp2 @ v5p (collectives)
    store_dir = tempfile.mkdtemp(prefix="tpusim-ci-coldserve-")
    proc = None
    try:
        store = CompileStore(store_dir)
        set_compile_store(store)
        try:
            simulate_trace(FIXTURES / fixture, arch=arch, tuned=False)
        finally:
            set_compile_store(None)
            clear_compiled_cache()
        if store.stores <= 0:
            raise ValueError("warm-up persisted no compiled records")

        proc = subprocess.Popen(
            [sys.executable, "-m", "tpusim", "serve", "--port", "0",
             "--trace-root", str(FIXTURES), "--compile-cache", store_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        # the bound-port line is the documented startup contract; a
        # watchdog kills a daemon that hangs WITHOUT printing it —
        # readline() alone would block past any deadline check
        import threading

        boot_watchdog = threading.Timer(60, proc.kill)
        boot_watchdog.start()
        port = None
        try:
            while True:
                line = proc.stdout.readline()
                if not line:
                    raise ValueError(
                        f"daemon exited (or was killed at the 60s boot "
                        f"deadline) before binding (rc={proc.poll()})"
                    )
                if "listening on http://" in line:
                    hostport = (
                        line.split("listening on http://", 1)[1]
                        .split()[0].rstrip("/")
                    )
                    port = int(hostport.rsplit(":", 1)[1])
                    break
        finally:
            boot_watchdog.cancel()

        body = json.dumps({
            "trace": fixture, "arch": arch, "tuned": False,
            "validate": False,
        }).encode()
        t0 = time.perf_counter()
        resp = urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/simulate", data=body,
                headers={"Content-Type": "application/json"},
            ),
            timeout=120,
        )
        doc = json.loads(resp.read())
        cold_ms = (time.perf_counter() - t0) * 1e3
        stats = doc.get("stats") or {}
        built = stats.get("fastpath_ir_ops_built")
        if built != 0:
            raise ValueError(
                f"cold first request built {built} IR op(s) despite a "
                f"warm compile store (expected 0: the request must "
                f"price from mmapped columns alone)"
            )
        if stats.get("fastpath_store_hits", 0) < 1:
            raise ValueError(
                "cold first request never hit the compile store "
                f"(fastpath_store_hits="
                f"{stats.get('fastpath_store_hits')})"
            )
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).read().decode()
        for needle in ("fastpath_store_hits", "fastpath_compile_hits"):
            if needle not in metrics:
                raise ValueError(
                    f"/metrics missing compile-cache counter {needle!r}"
                )
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        if proc.returncode != 0:
            raise ValueError(
                f"daemon drain exited rc={proc.returncode}"
            )
        proc = None
        return {
            "cold_first_request_ms": round(cold_ms, 1),
            "store_records": store.stores,
        }
    finally:
        if proc is not None:
            proc.kill()
            proc.wait(timeout=30)
        shutil.rmtree(store_dir, ignore_errors=True)


#: --guard-smoke store quota: above the largest single matrix record
#: (~52KB) so GC never deletes the record just published, below the
#: matrix total (~159KB) so the quota provably engages
GUARD_SMOKE_QUOTA_BYTES = 64 * 1024


def guard_smoke(serve_workers: int = 2) -> dict:
    """Resource-governance contract (tpusim.guard):

    1. the golden matrix priced under a deliberately small
       ``--cache-quota`` must stay byte-identical to the committed
       goldens (quota/GC change WHETHER records persist, never the
       arithmetic — ``guard_*``/``cache_*`` accounting keys stripped
       like every perf-layer smoke), the store must sit at or under the
       quota after every run, and the GC must have actually engaged;
    2. a served request that outlives its deadline must 504 through
       cooperative IN-PROCESS cancellation: the worker that priced it
       survives (zero restarts, zero kills, same pids), answers the
       next request from its warm caches, and the coop-cancel counter
       lands on /metrics.
    Raises on violation."""
    import tempfile

    from tpusim.guard.store import store_bytes
    from tpusim.perf.cache import ResultCache
    from tpusim.sim.driver import simulate_trace

    quota = GUARD_SMOKE_QUOTA_BYTES
    with tempfile.TemporaryDirectory(prefix="tpusim_guard_smoke_") as td:
        cache_dir = Path(td) / "cache"
        cache = ResultCache(disk_dir=cache_dir, quota_bytes=quota)
        got = {}
        for fixture, arch, overlays in MATRIX:
            name = f"{fixture}__{arch}"
            tag = _overlay_tag(overlays)
            if tag:
                name += "__" + tag
            report = simulate_trace(
                FIXTURES / fixture, arch=arch, overlays=list(overlays),
                tuned=False, result_cache=cache,
            )
            got[name] = {
                k: v for k, v in json.loads(report.stats.to_json()).items()
                if k not in VOLATILE
                and not k.startswith(PERF_KEY_PREFIXES)
            }
            on_disk = store_bytes(cache_dir)
            if on_disk > quota:
                raise ValueError(
                    f"guard smoke: store at {on_disk} bytes after "
                    f"{name}, over the {quota}-byte quota"
                )
        errors = compare(got)
        if errors:
            raise ValueError(
                "quota-governed matrix diverged from committed "
                "goldens:\n  " + "\n  ".join(errors)
            )
        if cache.gc_runs == 0:
            raise ValueError(
                "guard smoke: the quota never engaged (zero GC runs) — "
                "the matrix shrank or the quota grew; retune "
                "GUARD_SMOKE_QUOTA_BYTES so the bound is actually "
                "exercised"
            )
        gc_runs, gc_deleted = cache.gc_runs, cache.gc_deleted

    # -- part 2: cooperative deadline cancel through the worker pool ----
    from tpusim.serve.client import ServeClient, ServeError
    from tpusim.serve.daemon import ServeDaemon

    daemon = ServeDaemon(
        trace_root=FIXTURES, serve_workers=serve_workers,
        chaos_hooks=True,
    ).start()
    try:
        client = ServeClient(daemon.url)
        warmup = client.simulate(trace="matmul_512", arch="v5e",
                                 tuned=False)
        pids_before = [
            w["pid"] for w in client.healthz()["workers"]
        ]
        # a cancel-aware stand-in for slow pricing (the chaos spin hook
        # checks its CancelToken at pricing grain), far past a 400ms
        # deadline: the worker must cancel in-process, never be killed
        try:
            r, _ = client._raw("POST", "/v1/simulate", {
                "trace": "matmul_512", "arch": "v5e", "tuned": False,
                "_chaos_spin_s": 10, "deadline_ms": 400,
            })
            payload = json.loads(_)
            status = r.status
        except ServeError as e:  # pragma: no cover - transport failure
            raise ValueError(f"guard smoke: coop-cancel request died "
                             f"in transport: {e}")
        if status != 504 or "cooperative" not in str(
            payload.get("detail", "")
        ):
            raise ValueError(
                f"guard smoke: expected an in-process-cancel 504, got "
                f"{status} {payload.get('detail')!r}"
            )
        health = client.healthz()
        pids_after = [w["pid"] for w in health["workers"]]
        restarts = sum(w["restarts"] for w in health["workers"])
        kills = sum(w["kills"] for w in health["workers"])
        if (
            health["workers_alive"] != serve_workers
            or restarts != 0 or kills != 0
            or pids_after != pids_before
        ):
            raise ValueError(
                f"guard smoke: the cooperative cancel cost a worker "
                f"(alive={health['workers_alive']}, restarts={restarts},"
                f" kills={kills}, pids {pids_before}->{pids_after})"
            )
        prom = client.metrics_text()
        if "tpusim_serve_worker_coop_cancels_total 1" not in prom:
            raise ValueError(
                "guard smoke: /metrics is missing the coop-cancel "
                "counter"
            )
        # the surviving worker's caches are warm: the repeat request is
        # a cache hit priced by the same pid that was just cancelled
        repeat = client.simulate(trace="matmul_512", arch="v5e",
                                 tuned=False)
        if not repeat.cache_hit:
            raise ValueError(
                "guard smoke: post-cancel repeat was not a cache hit — "
                "the worker's warm state did not survive"
            )
        if _serve_served_bytes(repeat.stats) != _serve_served_bytes(
            warmup.stats
        ):
            raise ValueError(
                "guard smoke: post-cancel repeat stats diverged"
            )
    finally:
        if not daemon.drain_and_stop():
            raise ValueError("guard smoke: daemon did not drain cleanly")
    return {
        "configs": len(got),
        "quota_bytes": quota,
        "gc_runs": gc_runs,
        "gc_deleted": gc_deleted,
        "serve_workers": serve_workers,
    }


def front_smoke(serve_workers: int = 1) -> dict:
    """serve v3 front-tier contract (multi-acceptor + hot cache):

    1. **byte-identity across every topology**: the golden matrix served
       through acceptors=1 and acceptors=2, each with and without the
       shared mmap hot-response cache, must answer every request
       byte-identical to the committed CLI goldens;
    2. **the hot tier really serves**: on the hot legs the warm second
       pass must be answered entirely from the mmap (``cache_hit`` on
       every response, ``serve_hot_hits_total`` >= the matrix size, and
       ``serve_priced_total`` frozen at the cold pass — zero worker
       dispatches);
    3. **chaos**: an acceptor SIGKILLed mid-matrix costs zero failed
       requests (the client's idempotent-retry discipline reconnects
       onto a surviving acceptor) and the front supervisor heals the
       fleet;
    4. **guard semantics hold multi-acceptor**: a request past its
       deadline still 504s through cooperative in-process cancel, and a
       poison request quarantined behind one acceptor is refused by the
       OTHER acceptor immediately (shared quarantine state) without
       costing it any worker deaths.
    Raises on violation."""
    import tempfile
    import threading
    import time

    from tpusim.serve.client import ServeClient, ServeError
    from tpusim.serve.front import FrontSupervisor

    golden_bytes = _serve_golden_bytes
    served_bytes = _serve_served_bytes

    def matrix_names():
        out = []
        for fixture, arch, overlays in MATRIX:
            name = f"{fixture}__{arch}"
            tag = _overlay_tag(overlays)
            if tag:
                name += "__" + tag
            out.append((name, fixture, arch, overlays))
        return out

    def matrix_pass(url, fresh_conns: bool):
        """One pass; fresh_conns opens a new connection per request so
        the kernel's reuseport hashing spreads them over acceptors."""
        out = []
        client = None
        for name, fixture, arch, overlays in matrix_names():
            if client is None or fresh_conns:
                client = ServeClient(url, retries=3)
            r = client.simulate(
                trace=fixture, arch=arch, overlays=list(overlays),
                tuned=False,
            )
            if served_bytes(r.stats) != golden_bytes(name):
                raise ValueError(
                    f"front smoke: served stats for {name} diverged "
                    f"from the committed CLI golden"
                )
            out.append((name, r))
        return out

    def metric(client, key) -> float:
        for line in client.metrics_text().splitlines():
            if line.startswith(f"tpusim_{key} "):
                return float(line.split()[1])
        return 0.0

    legs = []
    n = len(MATRIX)
    for acceptors, hot in ((1, False), (2, False), (1, True), (2, True)):
        with tempfile.TemporaryDirectory(
            prefix="tpusim_front_smoke_"
        ) as td:
            front = FrontSupervisor(
                settings={
                    "trace_root": str(FIXTURES), "max_inflight": 4,
                    "hot_cache": f"{td}/hot" if hot else None,
                },
                num_acceptors=acceptors,
            ).start()
            try:
                client = ServeClient(front.url, retries=3)
                matrix_pass(front.url, fresh_conns=acceptors > 1)
                if hot:
                    # every unique request publishes once; wait for the
                    # async post-response publishes to land before the
                    # warm pass claims to be served from the map
                    deadline = time.monotonic() + 5.0
                    while (
                        metric(client, "serve_hot_publishes_total") < n
                        and time.monotonic() < deadline
                    ):
                        time.sleep(0.05)
                warm = matrix_pass(front.url, fresh_conns=acceptors > 1)
                if hot:
                    missed = [nm for nm, r in warm if not r.cache_hit]
                    if missed:
                        raise ValueError(
                            f"front smoke: hot-leg warm responses "
                            f"without cache_hit: {missed}"
                        )
                    hot_hits = metric(client, "serve_hot_hits_total")
                    priced = metric(client, "serve_priced_total")
                    if hot_hits < n:
                        raise ValueError(
                            f"front smoke: warm pass recorded only "
                            f"{hot_hits:.0f} hot hits (expected >= {n})"
                        )
                    if priced > n:
                        raise ValueError(
                            f"front smoke: {priced:.0f} requests were "
                            f"priced (expected {n}: the warm pass must "
                            f"dispatch ZERO work past the mmap tier)"
                        )
                legs.append({
                    "acceptors": acceptors, "hot": hot, "configs": n,
                })
            finally:
                if not front.stop():
                    raise ValueError(
                        f"front smoke: fleet (acceptors={acceptors}, "
                        f"hot={hot}) did not drain cleanly"
                    )

    # -- chaos: SIGKILL an acceptor mid-matrix ------------------------------
    with tempfile.TemporaryDirectory(prefix="tpusim_front_chaos_") as td:
        front = FrontSupervisor(
            settings={
                "trace_root": str(FIXTURES), "max_inflight": 4,
                "hot_cache": f"{td}/hot",
            },
            num_acceptors=2, restart_backoff_s=0.1,
        ).start()
        try:
            matrix_pass(front.url, fresh_conns=True)  # warm + publish
            killed = {"pid": None}

            def chaos():
                time.sleep(0.05)
                killed["pid"] = front.slots[1].pid
                front.kill_acceptor(1)

            t = threading.Thread(target=chaos, daemon=True)
            t.start()
            for _ in range(3):
                matrix_pass(front.url, fresh_conns=True)
            t.join(timeout=5.0)
            if killed["pid"] is None:
                raise ValueError("front smoke: chaos kill never landed")
            # wait for the RESTART, not mere aliveness: the alive flag
            # only drops once the monitor notices the death, so an
            # alive-count poll can win the race and see nothing
            deadline = time.monotonic() + 20.0
            while (
                front.slots[1].restarts < 1 or not front.slots[1].alive
            ) and time.monotonic() < deadline:
                time.sleep(0.1)
            if front.slots[1].restarts < 1 or not front.slots[1].alive:
                raise ValueError(
                    "front smoke: fleet did not heal after the "
                    "acceptor SIGKILL (no restart recorded)"
                )
            chaos_restarts = front.slots[1].restarts
            # the healed fleet still serves golden bytes
            matrix_pass(front.url, fresh_conns=True)
        finally:
            front.stop()

    # -- guard semantics across acceptors -----------------------------------
    with tempfile.TemporaryDirectory(prefix="tpusim_front_guard_") as td:
        # 2+ workers per acceptor: the poison retry must find a LIVE
        # second worker (one alive worker would shed Degraded instead
        # of spending the retry budget)
        front = FrontSupervisor(
            settings={
                "trace_root": str(FIXTURES), "max_inflight": 4,
                "workers_per_acceptor": max(serve_workers, 2),
                "chaos_hooks": True,
                "quarantine_dir": f"{td}/quarantine",
            },
            num_acceptors=2,
        ).start()
        try:
            client = ServeClient(front.url, retries=3)
            client.simulate(trace="matmul_512", arch="v5e", tuned=False)
            # deadline past the budget: in-process cooperative cancel,
            # exactly the single-daemon guard contract
            resp, payload = client._raw("POST", "/v1/simulate", {
                "trace": "matmul_512", "arch": "v5e", "tuned": False,
                "_chaos_spin_s": 10, "deadline_ms": 400,
            })
            doc = json.loads(payload)
            if resp.status != 504 or "cooperative" not in str(
                doc.get("detail", "")
            ):
                raise ValueError(
                    f"front smoke: expected in-process-cancel 504 "
                    f"through the front tier, got {resp.status} "
                    f"{doc.get('detail')!r}"
                )
            # poison: kills its worker past the retry budget -> 422
            poison_body = {
                "trace": "matmul_512", "arch": "v5e", "tuned": False,
                "_chaos_exit": True,
            }
            status = None
            try:
                resp, payload = client._raw(
                    "POST", "/v1/simulate", poison_body,
                )
                status = resp.status
            except ServeError:
                pass
            if status != 422:
                raise ValueError(
                    f"front smoke: poison request answered {status}, "
                    f"expected 422 after the retry budget"
                )
            # find the victim acceptor and the innocent one
            health = client.healthz()
            victims, innocents = [], []
            for acc in health.get("acceptors", []):
                crashes = sum(
                    w.get("crashes", 0) for w in acc.get("workers", [])
                )
                (victims if crashes else innocents).append(acc)
            if not victims or not innocents:
                raise ValueError(
                    f"front smoke: could not identify poison victim/"
                    f"innocent acceptors in {health}"
                )
            innocent = innocents[0]
            direct = ServeClient(
                f"http://127.0.0.1:{innocent['direct_port']}"
            )
            try:
                resp, payload = direct._raw(
                    "POST", "/v1/simulate", poison_body,
                )
                status2 = resp.status
            except ServeError:
                status2 = None
            if status2 != 422:
                raise ValueError(
                    f"front smoke: the innocent acceptor answered "
                    f"{status2} for the quarantined body (expected an "
                    f"immediate 422 from the SHARED quarantine)"
                )
            after = direct.healthz(timeout_s=10)
            crashes_after = sum(
                w.get("crashes", 0) for w in (
                    next(
                        a for a in after.get("acceptors", [])
                        if a.get("acceptor_index")
                        == innocent.get("acceptor_index")
                    ).get("workers", [])
                )
            )
            if crashes_after:
                raise ValueError(
                    "front smoke: the shared quarantine did not refuse "
                    "the poison body before it killed the innocent "
                    "acceptor's worker"
                )
        finally:
            if not front.stop():
                raise ValueError(
                    "front smoke: guard fleet did not drain cleanly"
                )

    return {
        "legs": legs,
        "configs": n,
        "chaos_restarts": chaos_restarts,
        "serve_workers": max(serve_workers, 1),
    }


def reqtrace_smoke() -> dict:
    """Request-tracing contract (tpusim.obs.reqtrace, L24) over a
    2-acceptor front:

    1. **off is zero-overhead**: a tracing-off fleet answers the golden
       matrix byte-identical to the committed CLI goldens, stamps no
       ``X-Tpusim-Trace`` header, exposes no ``tpusim_reqtrace_*``
       series, and 404s the debug routes;
    2. **on never touches a body**: with ``--trace-requests`` the same
       matrix stays byte-identical to the goldens while every response
       carries a well-formed trace ID (an inbound pinned ID echoed
       verbatim);
    3. **histograms account for every request**: the fleet ``/metrics``
       view renders real ``# TYPE ... histogram`` series whose per-route
       ``+Inf`` bucket counts sum EXACTLY to
       ``tpusim_serve_requests_total``;
    4. **the flight recorder answers**: the slowest recorded trace is
       fetched by ID through whichever acceptor the client lands on
       (fleet fan-out), its top-level phase spans sum within the
       recorded total, and the ``?format=chrome`` export parses as a
       Perfetto/Chrome ``traceEvents`` document;
    5. **the access log lands**: per-acceptor JSONL files parse with
       route/status/latency/trace-id fields.
    Raises on violation."""
    import json as _json
    import tempfile

    from tpusim.obs.reqtrace import TRACE_HEADER, valid_trace_id
    from tpusim.serve.client import ServeClient
    from tpusim.serve.front import FrontSupervisor

    def matrix_pass(client) -> tuple[list[str], list[str | None]]:
        """Serve the golden matrix via raw calls (the typed client
        hides headers); returns (served canonical docs, trace ids)."""
        served, tids = [], []
        for fixture, arch, overlays in MATRIX:
            name = f"{fixture}__{arch}"
            tag = _overlay_tag(overlays)
            if tag:
                name += "__" + tag
            body = {"trace": fixture, "arch": arch, "tuned": False,
                    "validate": True}
            if overlays:
                body["overlays"] = list(overlays)
            resp, payload = client._raw(
                "POST", "/v1/simulate", body, idempotent=True,
            )
            if resp.status != 200:
                raise ValueError(
                    f"reqtrace smoke: {name} answered {resp.status}"
                )
            stats = _json.loads(payload)["stats"]
            if _serve_served_bytes(stats) != _serve_golden_bytes(name):
                raise ValueError(
                    f"reqtrace smoke: served stats for {name} diverged "
                    f"from the committed CLI golden"
                )
            served.append(name)
            tids.append(resp.getheader(TRACE_HEADER))
        return served, tids

    # -- pass 1: tracing off (the default) ---------------------------------
    front = FrontSupervisor(
        settings={"trace_root": str(FIXTURES), "max_inflight": 4},
        num_acceptors=2,
    ).start()
    try:
        client = ServeClient(front.url, retries=3)
        _, tids = matrix_pass(client)
        stamped = [t for t in tids if t is not None]
        if stamped:
            raise ValueError(
                f"reqtrace smoke: tracing-off responses carried trace "
                f"headers: {stamped}"
            )
        text = client.metrics_text()
        if "tpusim_reqtrace" in text:
            raise ValueError(
                "reqtrace smoke: tracing-off /metrics grew reqtrace "
                "series"
            )
        resp, _ = client._raw("GET", "/v1/debug/traces")
        if resp.status != 404:
            raise ValueError(
                f"reqtrace smoke: tracing-off debug route answered "
                f"{resp.status}, expected 404"
            )
    finally:
        if not front.stop():
            raise ValueError(
                "reqtrace smoke: tracing-off fleet did not drain"
            )

    # -- pass 2: tracing on -------------------------------------------------
    with tempfile.TemporaryDirectory(prefix="tpusim_reqtrace_") as td:
        front = FrontSupervisor(
            settings={
                "trace_root": str(FIXTURES), "max_inflight": 4,
                "trace_requests": True,
                "access_log": f"{td}/access.jsonl",
            },
            num_acceptors=2,
        ).start()
        try:
            client = ServeClient(front.url, retries=3)
            _, tids = matrix_pass(client)
            bad = [t for t in tids if not (t and valid_trace_id(t))]
            if bad:
                raise ValueError(
                    f"reqtrace smoke: malformed/missing trace ids: {bad}"
                )

            # an inbound pinned ID must be echoed verbatim
            import http.client as _http

            conn = _http.HTTPConnection(front.host, front.port,
                                        timeout=30)
            try:
                conn.request(
                    "POST", "/v1/simulate",
                    body=_json.dumps({
                        "trace": MATRIX[0][0], "arch": MATRIX[0][1],
                        "tuned": False, "validate": True,
                    }).encode(),
                    headers={"Content-Type": "application/json",
                             TRACE_HEADER: "deadbeef01234567"},
                )
                resp = conn.getresponse()
                resp.read()
                echoed = resp.getheader(TRACE_HEADER)
            finally:
                conn.close()
            if echoed != "deadbeef01234567":
                raise ValueError(
                    f"reqtrace smoke: pinned inbound trace id came "
                    f"back as {echoed!r}"
                )

            # fleet histogram accounting: +Inf bucket counts == counter
            text = client.metrics_text()
            if "# TYPE tpusim_reqtrace_route_ms histogram" not in text:
                raise ValueError(
                    "reqtrace smoke: /metrics lacks the route "
                    "histogram TYPE line"
                )
            bucket_total = sum(
                float(ln.split()[1]) for ln in text.splitlines()
                if ln.startswith("tpusim_reqtrace_route_ms_bucket")
                and 'le="+Inf"' in ln
            )
            counter = next(
                (float(ln.split()[1]) for ln in text.splitlines()
                 if ln.startswith("tpusim_serve_requests_total ")),
                None,
            )
            if counter is None or bucket_total != counter:
                raise ValueError(
                    f"reqtrace smoke: histogram buckets account for "
                    f"{bucket_total} requests, counter says {counter}"
                )
            for ln in text.splitlines():
                if ln.startswith("#") or not ln.strip():
                    continue
                parts = ln.split()
                if len(parts) != 2:
                    raise ValueError(
                        f"reqtrace smoke: unparseable sample line "
                        f"{ln!r}"
                    )
                float(parts[1])

            # the slowest recorded trace, fetched by ID fleet-wide
            recent = client.recent_traces()
            if not recent:
                raise ValueError(
                    "reqtrace smoke: flight recorder is empty after "
                    "the matrix"
                )
            slowest = recent[0]["trace_id"]
            doc = client.trace_detail(slowest)
            if doc.get("trace_id") != slowest:
                raise ValueError(
                    f"reqtrace smoke: trace {slowest} not retrievable "
                    f"by id"
                )
            top_ms = sum(
                s["dur_ms"] for s in doc["spans"]
                if "/" not in s["path"]
            )
            if top_ms > doc["total_ms"] + 0.5:
                raise ValueError(
                    f"reqtrace smoke: top-level spans sum to "
                    f"{top_ms:.3f}ms, exceeding the recorded total "
                    f"{doc['total_ms']:.3f}ms"
                )
            chrome = client.trace_detail(slowest, chrome=True)
            events = chrome.get("traceEvents")
            if not events or not any(
                e.get("ph") == "X" for e in events
            ):
                raise ValueError(
                    "reqtrace smoke: chrome export lacks duration "
                    "events"
                )

            n_traced = len(tids) + 1
        finally:
            if not front.stop():
                raise ValueError(
                    "reqtrace smoke: tracing-on fleet did not drain"
                )

        # access log: per-acceptor JSONL files with the full field set
        log_lines = 0
        log_files = sorted(Path(td).glob("access*.jsonl*"))
        for p in log_files:
            for ln in p.read_text().splitlines():
                rec = _json.loads(ln)
                if not {"route", "status", "latency_ms", "trace_id",
                        "ts_s"} <= set(rec):
                    raise ValueError(
                        f"reqtrace smoke: access-log record missing "
                        f"fields: {rec}"
                    )
                log_lines += 1
        if log_lines < n_traced:
            raise ValueError(
                f"reqtrace smoke: access logs hold {log_lines} lines "
                f"for {n_traced}+ served requests"
            )

    return {
        "configs": len(MATRIX),
        "traced": n_traced,
        "bucket_total": bucket_total,
        "access_log_lines": log_lines,
        "access_log_files": len(log_files),
    }


#: --cluster-smoke campaign spec: small enough to run three times in
#: one CI tier, big enough (2 slices x 6 scenarios) that a shard child
#: is reliably mid-run when the chaos leg SIGKILLs it
CLUSTER_CAMPAIGN_SPEC = {
    "name": "ci-cluster-smoke",
    "seed": 3,
    "scenarios": 6,
    "arch": "v5p",
    "chips": 8,
    "tuned": False,
    "faults": {
        "count": {"dist": "uniform", "min": 0, "max": 3},
        "kinds": {"link_down": 1.0, "link_degraded": 1.0,
                  "chip_straggler": 0.5, "hbm_throttle": 0.5},
        "scale": {"min": 0.4, "max": 0.9},
    },
    "candidate_slices": [{"arch": "v5p", "chips": 4}],
}


def _shard_journal_sigs(out_dir) -> tuple[int, int]:
    """(distinct scenario signatures, duplicate appends) across every
    shard journal under ``<out>/shards/`` — duplicates == 0 is the
    zero-re-priced-scenarios proof."""
    from tpusim.campaign.journal import Journal

    seen: set[tuple[str, int]] = set()
    dup = 0
    shards = Path(out_dir) / "shards"
    for d in sorted(shards.iterdir()) if shards.is_dir() else []:
        if not (d / "journal.jsonl").is_file():
            continue
        for rec in Journal(d).iter_records():
            if rec.get("kind") != "scenario":
                continue
            sig = (rec["slice"], rec["index"])
            if sig in seen:
                dup += 1
            seen.add(sig)
    return len(seen), dup


def cluster_smoke() -> dict:
    """Multi-node cluster contract (serve --join + campaign --nodes):

    1. **byte-identity across fleet sizes**: the golden matrix served
       single-node, then through BOTH nodes of a 2-node localhost
       cluster (hot + compiled tiers engaged, membership live,
       consistent-hash forwarding in play), answers every request
       byte-identical to the committed CLI goldens;
    2. **node loss under traffic**: the second node SIGKILLed
       mid-matrix costs ZERO failed requests (client failover + the
       survivor's forward-fallback) and the primary records the heal
       (a death, an epoch bump, nodes_alive back to 1);
    3. **distributed campaign chaos**: ``--nodes 2`` sharded campaigns
       merge to a report byte-identical to the uninterrupted
       single-node run — with a shard child SIGKILLed mid-run (its
       remaining scenarios resume on the survivor) and with the whole
       coordinator killed then ``--resume``d — and in every case the
       union of shard journals holds each scenario signature exactly
       once: zero re-priced scenarios.
    Raises on violation."""
    import os
    import shutil
    import signal
    import subprocess
    import tempfile
    import threading
    import time

    from tpusim.campaign import run_campaign, run_sharded_campaign
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    golden_bytes = _serve_golden_bytes
    served_bytes = _serve_served_bytes

    def matrix_names():
        out = []
        for fixture, arch, overlays in MATRIX:
            name = f"{fixture}__{arch}"
            tag = _overlay_tag(overlays)
            if tag:
                name += "__" + tag
            out.append((name, fixture, arch, overlays))
        return out

    def matrix_pass(client, leg: str) -> int:
        for name, fixture, arch, overlays in matrix_names():
            r = client.simulate(
                trace=fixture, arch=arch, overlays=list(overlays),
                tuned=False,
            )
            if served_bytes(r.stats) != golden_bytes(name):
                raise ValueError(
                    f"cluster smoke [{leg}]: served stats for {name} "
                    f"diverged from the committed CLI golden"
                )
        return len(MATRIX)

    td = tempfile.mkdtemp(prefix="tpusim-ci-cluster-")
    node_b = None
    summary: dict = {}
    try:
        # -- leg 1: 2-node serve fleet, byte-identity + kill + heal ---
        a = ServeDaemon(
            trace_root=FIXTURES, max_inflight=4,
            hot_cache=f"{td}/hot_a", compile_cache=f"{td}/cc_a",
        ).start()
        try:
            client_a = ServeClient(a.url, retries=3)
            configs = matrix_pass(client_a, "single-node")

            node_b = subprocess.Popen(
                [sys.executable, "-m", "tpusim", "serve", "--port", "0",
                 "--trace-root", str(FIXTURES),
                 "--join", f"{a.host}:{a.port}",
                 "--hot-cache", f"{td}/hot_b",
                 "--compile-cache", f"{td}/cc_b"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=REPO,
            )
            boot_watchdog = threading.Timer(120, node_b.kill)
            boot_watchdog.start()
            url_b = None
            try:
                while True:
                    line = node_b.stdout.readline()
                    if not line:
                        raise ValueError(
                            f"node B exited before binding "
                            f"(rc={node_b.poll()})"
                        )
                    if "listening on http://" in line:
                        url_b = (
                            "http://" +
                            line.split("listening on http://", 1)[1]
                            .split()[0].rstrip("/")
                        )
                        break
            finally:
                boot_watchdog.cancel()

            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if a.cluster is not None and len([
                    m for m in a.cluster.view()["members"] if m["alive"]
                ]) == 2:
                    break
                time.sleep(0.1)
            else:
                raise ValueError(
                    "node B never joined the primary's registry"
                )

            # both nodes serve the matrix with membership live:
            # consistent-hash forwarding routes some requests across
            # the wire, and every byte still matches the goldens
            matrix_pass(ServeClient(a.url, retries=3), "2-node via A")
            matrix_pass(ServeClient(url_b, retries=3), "2-node via B")

            # chaos: kill node B mid-matrix; the failover client must
            # finish the pass with zero failed requests
            failover = ServeClient(url_b, retries=3, members=[a.url])
            killed = False
            for i, (name, fixture, arch, overlays) in enumerate(
                matrix_names()
            ):
                if i == 1:
                    node_b.send_signal(signal.SIGKILL)
                    node_b.wait(timeout=30)
                    killed = True
                r = failover.simulate(
                    trace=fixture, arch=arch, overlays=list(overlays),
                    tuned=False,
                )
                if served_bytes(r.stats) != golden_bytes(name):
                    raise ValueError(
                        f"cluster smoke [node-kill]: served stats for "
                        f"{name} diverged after failover"
                    )
            if not killed:
                raise ValueError("cluster smoke: kill leg never killed")

            # the heal must be RECORDED: the reaper marks B dead, bumps
            # the epoch, and the fleet gauges settle at one alive node
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                stats = a.cluster.stats_dict()
                if (
                    stats["cluster_deaths_total"] >= 1
                    and stats["cluster_nodes_alive"] == 1
                ):
                    break
                time.sleep(0.2)
            else:
                raise ValueError(
                    f"cluster smoke: node B's death was never recorded "
                    f"({a.cluster.stats_dict()})"
                )
            summary.update({
                "configs": configs,
                "heal_epoch": a.cluster.epoch,
                "deaths": a.cluster.stats_dict()["cluster_deaths_total"],
            })
        finally:
            if not a.drain_and_stop():
                raise ValueError("node A did not drain cleanly")

        # -- leg 2: sharded campaign, chaos + resume, byte-identity ---
        single = run_campaign(
            CLUSTER_CAMPAIGN_SPEC,
            trace_path=FIXTURES / CAMPAIGN_SMOKE_FIXTURE,
            out_dir=f"{td}/single",
        )
        single_bytes = Path(f"{td}/single/report.json").read_text()

        def kill_one_shard(procs):
            """Watch the busiest shard's journal; SIGKILL its process
            the moment a scenario record lands — mid-run by
            construction, since its remaining scenarios are unpriced."""
            if len(procs) < 2:
                return

            def watch(node, proc):
                path = (
                    Path(td) / "chaos" / "shards" / f"n{node}"
                    / "journal.jsonl"
                )
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline and proc.is_alive():
                    if path.is_file() and sum(
                        1 for ln in path.read_bytes().splitlines()
                        if b'"scenario"' in ln
                    ) >= 1:
                        os.kill(proc.pid, signal.SIGKILL)
                        return
                    time.sleep(0.05)

            node = sorted(procs)[0]
            threading.Thread(
                target=watch, args=(node, procs[node]), daemon=True,
            ).start()

        heals: list[str] = []
        chaos = run_sharded_campaign(
            CLUSTER_CAMPAIGN_SPEC,
            trace_path=FIXTURES / CAMPAIGN_SMOKE_FIXTURE,
            out_dir=f"{td}/chaos",
            nodes=2,
            progress=heals.append,
            on_spawn=kill_one_shard,
        )
        chaos_bytes = Path(f"{td}/chaos/report.json").read_text()
        if chaos_bytes != single_bytes:
            raise ValueError(
                "cluster smoke: shard-killed campaign report is not "
                "byte-identical to the uninterrupted single-node run"
            )
        sigs, dup = _shard_journal_sigs(f"{td}/chaos")
        if dup != 0:
            raise ValueError(
                f"cluster smoke: {dup} scenario(s) were re-priced "
                f"after the shard kill (expected 0)"
            )
        if not any("died" in msg for msg in heals):
            raise ValueError(
                "cluster smoke: the shard SIGKILL was never observed "
                "as a node death (kill landed after the shard "
                "finished?)"
            )

        # coordinator killed mid-run, then --resume: the surviving
        # journals are the durable record and nothing re-prices
        spec_path = Path(td) / "spec.json"
        spec_path.write_text(json.dumps(CLUSTER_CAMPAIGN_SPEC))
        coord = subprocess.Popen(
            [sys.executable, "-m", "tpusim", "campaign", str(spec_path),
             "--trace", str(FIXTURES / CAMPAIGN_SMOKE_FIXTURE),
             "--out", f"{td}/resume", "--nodes", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        deadline = time.monotonic() + 300.0
        journaled = 0
        while time.monotonic() < deadline and coord.poll() is None:
            journaled, _ = _shard_journal_sigs(f"{td}/resume")
            if journaled >= 1:
                coord.send_signal(signal.SIGKILL)
                break
            time.sleep(0.05)
        coord.wait(timeout=60)
        done_before, _ = _shard_journal_sigs(f"{td}/resume")
        if done_before < 1:
            raise ValueError(
                "cluster smoke: coordinator finished before the kill "
                "landed — resume leg never exercised"
            )
        resumed = run_sharded_campaign(
            CLUSTER_CAMPAIGN_SPEC,
            trace_path=FIXTURES / CAMPAIGN_SMOKE_FIXTURE,
            out_dir=f"{td}/resume",
            nodes=2,
            resume=True,
        )
        if Path(f"{td}/resume/report.json").read_text() != single_bytes:
            raise ValueError(
                "cluster smoke: resumed campaign report is not "
                "byte-identical to the uninterrupted single-node run"
            )
        _, dup = _shard_journal_sigs(f"{td}/resume")
        if dup != 0:
            raise ValueError(
                f"cluster smoke: --resume re-priced {dup} journaled "
                f"scenario(s) (expected 0)"
            )
        rs = resumed.stats
        if rs.resumed != done_before:
            raise ValueError(
                f"cluster smoke: resume restored {rs.resumed} "
                f"scenario(s) from the shard journals, expected "
                f"{done_before}"
            )
        summary.update({
            "scenarios": chaos.stats.scenarios,
            "shard_sigs": sigs,
            "resumed": rs.resumed,
        })
        return summary
    finally:
        if node_b is not None and node_b.poll() is None:
            node_b.kill()
            node_b.wait(timeout=30)
        shutil.rmtree(td, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", action="store_true",
                    help="rewrite ci/golden/ from the current model")
    ap.add_argument("--obs-smoke", action="store_true",
                    help="validate the obs export set against "
                         "ci/obs_schema.json instead of checking stats")
    ap.add_argument("--faults-smoke", action="store_true",
                    help="validate the fault-schedule contract against "
                         "ci/faults_schema.json: one-dead-link replay "
                         "of a tiny v5p slice + stats-key check")
    ap.add_argument("--lint-smoke", action="store_true",
                    help="run tpusim lint over every checked-in golden "
                         "trace/config/fault-schedule and require zero "
                         "error-level diagnostics")
    ap.add_argument("--dataflow-smoke", action="store_true",
                    help="dataflow-analysis contract: committed "
                         "fixtures lint clean of TL4xx/TL41x errors, "
                         "the liveness pass agrees with the engine's "
                         "residency walk on the fixture corpus, a "
                         "seeded two-device mismatched-collective "
                         "trace is refused, and the TL35x self-audit "
                         "over tpusim/ is green")
    ap.add_argument("--perf-lint-smoke", action="store_true",
                    help="perf-lint (TL5xx) contract: healthy fixtures "
                         "emit the TL500 summary with no TL5xx errors, "
                         "critical path <= engine <= serial sum holds "
                         "per module per arch over the full corpus, "
                         "the seeded exposed-collective trips TL501 "
                         "from both lint --perf and perf-report, "
                         "strict-lint serve admits TL5xx-only "
                         "verdicts, and the TL35x self-audit stays "
                         "green")
    ap.add_argument("--perf-smoke", action="store_true",
                    help="replay the golden matrix with --workers 4 and "
                         "an on-disk result cache: must match the "
                         "committed serial goldens byte-for-byte, and a "
                         "warm-cache second pass must run zero engine "
                         "pricing walks")
    ap.add_argument("--serve-smoke", action="store_true",
                    help="boot the serve daemon on a free port, replay "
                         "the golden-matrix requests over HTTP: stats "
                         "docs must be byte-identical to the committed "
                         "CLI goldens, and a warm second pass must "
                         "report cache_hit with zero engine walks; runs "
                         "both the single-process daemon and the serve "
                         "v2 supervised multi-worker pool")
    ap.add_argument("--serve-chaos-smoke", action="store_true",
                    help="SIGKILL a supervised worker while the golden "
                         "matrix is in flight: the run must finish with "
                         "zero failed requests, every response still "
                         "byte-identical to the committed goldens, and "
                         "at least one recorded worker restart")
    ap.add_argument("--serve-workers", type=int, default=2, metavar="N",
                    help="worker count for the multi-worker serve legs "
                         "(default 2)")
    ap.add_argument("--front-smoke", action="store_true",
                    help="serve v3 front-tier contract: the golden "
                         "matrix byte-identical across acceptors=1 and "
                         "acceptors=2 (with and without the shared mmap "
                         "hot-response cache), the warm pass served "
                         "from the mmap tier with zero worker "
                         "dispatches, an acceptor SIGKILLed mid-matrix "
                         "costing zero failed requests, and guard "
                         "deadline-504 / shared-quarantine semantics "
                         "holding across acceptors")
    ap.add_argument("--cluster-smoke", action="store_true",
                    help="multi-node cluster contract: the golden "
                         "matrix byte-identical served single-node vs "
                         "through both nodes of a 2-node localhost "
                         "--join fleet (hot/compiled tiers engaged), "
                         "one node SIGKILLed mid-matrix costing zero "
                         "failed requests with the heal recorded, and "
                         "--nodes 2 sharded campaigns (shard-killed "
                         "and coordinator-killed-then-resumed) merging "
                         "byte-identical to the single-node report "
                         "with zero re-priced scenarios")
    ap.add_argument("--reqtrace-smoke", action="store_true",
                    help="request-tracing contract over a 2-acceptor "
                         "front: tracing off = byte-identical goldens "
                         "with zero new surface; tracing on = the same "
                         "bytes plus X-Tpusim-Trace on every response, "
                         "fleet /metrics histograms whose bucket "
                         "counts sum to serve_requests_total, the "
                         "slowest trace fetched by id with a valid "
                         "Perfetto export, and parseable per-acceptor "
                         "JSONL access logs")
    ap.add_argument("--advise-smoke", action="store_true",
                    help="run the fixed-spec sharding-advisor sweep on "
                         "the llama_tiny fixture: the ranked report "
                         "must be byte-identical to the committed "
                         "golden, a warm pass through the shared cache "
                         "must run zero engine walks, and the "
                         "dp=4 x tp=2 cell must synthesize the "
                         "14-collective MULTICHIP_r05 step")
    ap.add_argument("--fastpath-parity", action="store_true",
                    help="price the golden matrix through every "
                         "available pricing backend (serial reference "
                         "walk, NumPy-vectorized, native kernel) plus "
                         "a file-backed streaming leg: all docs must "
                         "be byte-identical and match the committed "
                         "goldens")
    ap.add_argument("--guard-smoke", action="store_true",
                    help="resource-governance contract: the golden "
                         "matrix under a small --cache-quota must stay "
                         "byte-identical while the cache dir never "
                         "exceeds the quota (GC provably engaged), and "
                         "a served request past its deadline must 504 "
                         "via cooperative in-process cancel with zero "
                         "worker restarts")
    ap.add_argument("--campaign-smoke", action="store_true",
                    help="run the fixed-seed 16-scenario Monte-Carlo "
                         "campaign on the llama_tiny fixture: the "
                         "report must be byte-identical to the "
                         "committed golden (partition rate, inflation "
                         "percentiles, capacity table included) and "
                         "the healthy golden matrix must be untouched")
    ap.add_argument("--dcn-smoke", action="store_true",
                    help="run the fixed-seed DCN campaign on a 2-slice "
                         "4-chip system: the report must be "
                         "byte-identical to the committed golden, "
                         "answer slice survival (loss scenarios + "
                         "histogram, partition attribution), the "
                         "hierarchical all-reduce must beat the flat "
                         "scalar model, and an unconfigured fabric "
                         "must degenerate byte-identically")
    ap.add_argument("--fleet-smoke", action="store_true",
                    help="run the fixed-seed fleet digital twin on the "
                         "llama_tiny fixture: the report must be "
                         "byte-identical to the committed golden "
                         "(goodput/p99 curve, loss attribution with a "
                         "live shedding window, a pod loss with its "
                         "recovery row, a non-null capacity frontier) "
                         "and the healthy golden matrix must be "
                         "untouched")
    args = ap.parse_args(argv)

    if args.cluster_smoke:
        try:
            summary = cluster_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --cluster-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --cluster-smoke: OK "
              f"({summary['configs']} configs byte-identical to CLI "
              f"goldens single-node AND through both nodes of the "
              f"2-node fleet; node SIGKILL mid-matrix cost zero failed "
              f"requests, heal recorded at epoch "
              f"{summary['heal_epoch']} with {summary['deaths']:.0f} "
              f"death(s); sharded campaigns "
              f"({summary['scenarios']:.0f} scenarios, "
              f"{summary['shard_sigs']} journal signatures) stayed "
              f"byte-identical to the single-node report through a "
              f"shard kill and a coordinator kill + --resume "
              f"({summary['resumed']} restored), zero re-priced "
              f"scenarios)")
        return 0

    if args.reqtrace_smoke:
        try:
            summary = reqtrace_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --reqtrace-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --reqtrace-smoke: OK "
              f"({summary['configs']} configs byte-identical to the "
              f"goldens with tracing off AND on, "
              f"{summary['traced']} traced requests, fleet histogram "
              f"buckets account for {summary['bucket_total']:.0f} "
              f"requests exactly, slowest trace fetched by id with a "
              f"valid chrome export, {summary['access_log_lines']} "
              f"access-log lines across "
              f"{summary['access_log_files']} per-acceptor files)")
        return 0

    if args.dataflow_smoke:
        try:
            summary = dataflow_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --dataflow-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --dataflow-smoke: OK "
              f"({summary['lint_cells']} fixture/arch cells clean of "
              f"TL4xx/TL41x errors, liveness==engine on "
              f"{summary['modules_agreed']} corpus modules, seeded "
              f"deadlock refused with {summary['deadlock_code']}, "
              f"TL35x self-audit green)")
        return 0

    if args.perf_lint_smoke:
        try:
            summary = perf_lint_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --perf-lint-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --perf-lint-smoke: OK "
              f"({summary['lint_cells']} fixture/arch cells clean of "
              f"TL5xx errors with TL500 summaries, critical path <= "
              f"engine <= serial sum on "
              f"{summary['modules_bracketed']} module/arch cells, "
              f"seeded TL501 flagged by lint --perf AND perf-report, "
              f"strict-lint serve admits TL5xx-only verdicts, "
              f"self-audit green)")
        return 0

    if args.fleet_smoke:
        try:
            summary = fleet_smoke(update=args.update)
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --fleet-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --fleet-smoke: OK "
              f"({summary['served']:.0f}/{summary['requests']:.0f} "
              f"requests served byte-identically to the committed "
              f"report, {summary['shed']:.0f} shed, "
              f"{summary['pod_losses']:.0f} pod loss(es) with recovery "
              f"rows, frontier answer {summary['pods_needed']} pod(s), "
              f"healthy matrix unchanged across "
              f"{summary['matrix_configs']} configs)")
        return 0

    if args.fastpath_parity:
        try:
            summary = fastpath_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --fastpath-parity: FAILED: {e}")
            return 1
        try:
            cold = cold_serve_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --fastpath-parity [cold-serve]: "
                  f"FAILED: {e}")
            return 1
        try:
            batch = scenario_batch_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --fastpath-parity [batched]: "
                  f"FAILED: {e}")
            return 1
        print(f"ci/check_golden --fastpath-parity: OK "
              f"({summary['configs']} configs byte-identical across "
              f"backends {summary['backends']}; "
              f"{summary['streamed_configs']} streamed configs match "
              f"the committed goldens; "
              f"{summary['durable_configs']} disk-loaded configs match "
              f"with {summary['durable_store_hits']} store hits and "
              f"zero recompiles; cold-serve first request priced with "
              f"zero IR construction in "
              f"{cold['cold_first_request_ms']:.0f}ms; "
              f"{batch['scenarios']}-scenario campaign byte-identical "
              f"batched vs per-state with "
              f"{batch['batched_states']} lane(s) batch-priced in "
              f"{batch['batch_groups']} group(s))")
        return 0

    if args.advise_smoke:
        try:
            summary = advise_smoke(update=args.update)
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --advise-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --advise-smoke: OK "
              f"({summary['cells']} ranked cells byte-identical to the "
              f"committed report, {summary['feasible']} feasible, "
              f"recommendation {summary['recommendation']!r}, warm "
              f"pass zero engine walks, healthy matrix unchanged "
              f"across {summary['matrix_configs']} configs)")
        return 0

    if args.guard_smoke:
        try:
            summary = guard_smoke(
                serve_workers=max(args.serve_workers, 1)
            )
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --guard-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --guard-smoke: OK "
              f"({summary['configs']} configs byte-identical under a "
              f"{summary['quota_bytes']}-byte quota, "
              f"{summary['gc_runs']} GC run(s) deleting "
              f"{summary['gc_deleted']} record(s), store never over "
              f"quota; deadline 504 via in-process cancel with zero "
              f"restarts across {summary['serve_workers']} workers)")
        return 0

    if args.dcn_smoke:
        try:
            summary = dcn_smoke(update=args.update)
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --dcn-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --dcn-smoke: OK "
              f"({summary['scenarios']:.0f} scenarios byte-identical, "
              f"{summary['slice_losses']:.0f} slice-loss outcomes, "
              f"min {summary['min_slices_ok']:.0f} slice(s) survive, "
              f"hierarchical all-reduce "
              f"{summary['hier_speedup']:.2f}x over flat)")
        return 0

    if args.campaign_smoke:
        try:
            summary = campaign_smoke(update=args.update)
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --campaign-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --campaign-smoke: OK "
              f"({summary['scenarios']:.0f} scenarios byte-identical "
              f"to the committed report, "
              f"{summary['partitioned']:.0f} partitioned outcomes, "
              f"capacity answer {summary['capacity']!r}, healthy "
              f"matrix unchanged across {summary['matrix_configs']} "
              f"configs)")
        return 0

    if args.front_smoke:
        try:
            summary = front_smoke(
                serve_workers=max(args.serve_workers - 1, 1)
            )
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --front-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --front-smoke: OK "
              f"({len(summary['legs'])} topologies x "
              f"{summary['configs']} configs byte-identical to CLI "
              f"goldens; hot warm passes served from the mmap tier "
              f"with zero dispatches; acceptor SIGKILL healed with "
              f"{summary['chaos_restarts']} restart(s) and zero failed "
              f"requests; coop-504 + shared quarantine held across "
              f"acceptors)")
        return 0

    if args.serve_smoke:
        # both daemon shapes carry the same contract: the PR 5
        # single-process path, then the serve v2 supervised pool
        for workers in (0, max(args.serve_workers, 1)):
            leg = f"serve_workers={workers}"
            try:
                summary = serve_smoke(serve_workers=workers)
            except (ValueError, OSError, KeyError) as e:
                print(f"ci/check_golden --serve-smoke [{leg}]: "
                      f"FAILED: {e}")
                return 1
            print(f"ci/check_golden --serve-smoke [{leg}]: OK "
                  f"({summary['configs']} served configs byte-identical "
                  f"to CLI goldens; warm pass "
                  f"{summary['warm_cache_hits']}/{summary['configs']} "
                  f"cache_hit with zero engine walks; "
                  f"{summary['gauges']} prometheus gauges)")
        return 0

    if args.serve_chaos_smoke:
        try:
            summary = serve_chaos_smoke(
                serve_workers=max(args.serve_workers, 2),
            )
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --serve-chaos-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --serve-chaos-smoke: OK "
              f"({summary['configs']} requests green through a "
              f"mid-run SIGKILL of worker pid {summary['killed_pid']}; "
              f"{summary['restarts']} worker restart(s), "
              f"{summary['retries']} request retry(ies))")
        return 0

    if args.perf_smoke:
        try:
            summary = perf_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --perf-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --perf-smoke: OK ({summary['configs']} "
              f"configs bit-identical under workers=4 + cache; "
              f"cold {summary['cold_s']:.2f}s -> warm "
              f"{summary['warm_s']:.2f}s, zero warm engine runs)")
        return 0

    if args.lint_smoke:
        try:
            summary = lint_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --lint-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --lint-smoke: OK "
              f"({len(summary['artifacts'])} artifacts lint clean, "
              f"{summary['codes']} diagnostic codes registered)")
        return 0

    if args.faults_smoke:
        try:
            summary = faults_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --faults-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --faults-smoke: OK (dead link "
              f"{summary['dead_link']}, step inflation "
              f"{summary['step_inflation']:.3f}x, collective inflation "
              f"{summary['collective_inflation']:.3f}x, "
              f"{len(summary['stats_keys'])} stats keys)")
        return 0

    if args.obs_smoke:
        try:
            summary = obs_smoke()
        except (ValueError, OSError, KeyError) as e:
            print(f"ci/check_golden --obs-smoke: FAILED: {e}")
            return 1
        print(f"ci/check_golden --obs-smoke: OK ({summary['windows']} "
              f"windows, counter tracks {summary['counter_tracks']}, "
              f"{summary['gauges']} prometheus gauges)")
        return 0

    got = run_matrix()
    if args.update:
        GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
        for name, stats in got.items():
            (GOLDEN_DIR / f"{name}.json").write_text(
                json.dumps(stats, indent=1, sort_keys=True) + "\n"
            )
        print(f"updated {len(got)} golden files in {GOLDEN_DIR}")
        return 0

    errors = compare(got)
    for e in errors:
        print(f"GOLDEN MISMATCH: {e}")
    if errors:
        print(f"ci/check_golden: FAILED ({len(errors)} diffs)")
        return 1
    print(f"ci/check_golden: OK ({len(got)} configs, all stats match)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
