#!/usr/bin/env python3
"""Repo-wide static analysis gate (CI tier 2).

Runs ``ruff check .`` against the ``pyproject.toml`` config when ruff is
installed.  Containers without ruff (the jax_graft image bakes no
linters) fall back to a stdlib AST/tokenize checker implementing the
core of the same rule set — the codes CI actually gates on stay
identical, so a ruff-less box and a ruff-ful box agree:

    E999  syntax error
    E501  line longer than the configured limit
    F401  module-level import never used
    W291  trailing whitespace (W293 on blank lines)
    W292  missing newline at end of file

The fallback is deliberately conservative: ``__init__.py`` re-exports,
``__graft_entry__.py`` side-effect imports, ``__future__`` imports, and
imports guarded by try/except are never flagged (matching the
per-file-ignores in pyproject.toml).

Usage:
    python ci/lint_repo.py            # lint the repo, nonzero on findings
    python ci/lint_repo.py --list     # show which backend would run
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: wall-clock start of the lint run, read by ``_json_doc`` so the
#: ``--json`` report can carry how long the gate took (``duration_s``)
_T0 = time.perf_counter()

#: directories never linted (vendored/native/artifacts)
EXCLUDE_DIRS = {
    "native", "reports", "related", "__pycache__", ".git",
    ".claude", "runs",
}

#: files whose module-level imports exist for side effects / re-export
F401_EXEMPT_FILES = {"__init__.py", "__graft_entry__.py"}


def _line_length_limit() -> int:
    """The single source of truth is pyproject's [tool.ruff] line-length;
    the fallback reads it so the two backends can't drift."""
    m = re.search(
        r"^line-length\s*=\s*(\d+)",
        (REPO / "pyproject.toml").read_text(),
        re.MULTILINE,
    )
    return int(m.group(1)) if m else 99


def _per_file_ignores() -> dict[str, set[str]]:
    """Parse pyproject's [tool.ruff.lint.per-file-ignores] table (glob ->
    ignored codes) so the fallback honors the same exemptions ruff
    would — embedded HLO fixtures, __init__ re-exports."""
    text = (REPO / "pyproject.toml").read_text()
    m = re.search(
        r"^\[tool\.ruff\.lint\.per-file-ignores\]\n(.*?)(?:^\[|\Z)",
        text, re.MULTILINE | re.DOTALL,
    )
    out: dict[str, set[str]] = {}
    if not m:
        return out
    for pat, codes in re.findall(
        r'^"([^"]+)"\s*=\s*\[([^\]]*)\]', m.group(1), re.MULTILINE
    ):
        out[pat] = set(re.findall(r"[EWF]\d+", codes))
    return out


def _ignored_codes(rel: str, ignores: dict[str, set[str]]) -> set[str]:
    import fnmatch

    out: set[str] = set()
    for pat, codes in ignores.items():
        if fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch(
            Path(rel).name, pat
        ):
            out |= codes
    return out


def python_files() -> list[Path]:
    out = []
    for p in sorted(REPO.rglob("*.py")):
        if any(part in EXCLUDE_DIRS for part in p.parts):
            continue
        out.append(p)
    return out


# ---------------------------------------------------------------------------
# Fallback checks
# ---------------------------------------------------------------------------


class _ImportScan(ast.NodeVisitor):
    """Collect module-level import bindings and every name used."""

    def __init__(self) -> None:
        self.imports: dict[str, tuple[int, str]] = {}  # name -> (line, desc)
        self.used: set[str] = set()
        self._guard_depth = 0

    def visit_Try(self, node: ast.Try) -> None:
        # imports inside try/except are capability probes — never flag
        self._guard_depth += 1
        self.generic_visit(node)
        self._guard_depth -= 1

    def _bind(self, node, alias: ast.alias, desc: str) -> None:
        if self._guard_depth:
            return
        name = alias.asname or alias.name.split(".")[0]
        self.imports[name] = (node.lineno, desc)

    def visit_Import(self, node: ast.Import) -> None:
        if node.col_offset == 0:
            for alias in node.names:
                self._bind(node, alias, f"import {alias.name}")

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.col_offset == 0 and node.module != "__future__":
            for alias in node.names:
                if alias.name == "*":
                    continue
                self._bind(
                    node, alias,
                    f"from {node.module or '.'} import {alias.name}",
                )

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)


def _string_names(tree: ast.Module) -> set[str]:
    """Names referenced from string constants (__all__ entries, doctest
    fragments) — anything named in a string counts as used."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
    return out


def check_file(
    path: Path, limit: int, ignores: dict[str, set[str]],
) -> list[str]:
    rel = path.relative_to(REPO)
    skip = _ignored_codes(rel.as_posix(), ignores)
    findings: list[str] = []
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(rel))
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: E999 syntax error: {e.msg}"]

    lines = text.splitlines()
    for i, line in enumerate(lines, 1):
        if len(line) > limit and "E501" not in skip:
            findings.append(
                f"{rel}:{i}: E501 line too long "
                f"({len(line)} > {limit} characters)"
            )
        if line != line.rstrip():
            code = "W293" if not line.strip() else "W291"
            if code not in skip:
                findings.append(
                    f"{rel}:{i}: {code} trailing whitespace"
                )
    if text and not text.endswith("\n") and "W292" not in skip:
        findings.append(
            f"{rel}:{len(lines)}: W292 no newline at end of file"
        )

    if path.name not in F401_EXEMPT_FILES and "F401" not in skip:
        scan = _ImportScan()
        scan.visit(tree)
        if scan.imports:
            used = scan.used | _string_names(tree)
            for name, (lineno, desc) in sorted(
                scan.imports.items(), key=lambda kv: kv[1][0]
            ):
                if name not in used:
                    findings.append(
                        f"{rel}:{lineno}: F401 {desc!r} imported but "
                        f"unused"
                    )
    return findings


def run_fallback(emit_json: bool = False) -> int:
    limit = _line_length_limit()
    ignores = _per_file_ignores()
    findings: list[str] = []
    files = python_files()
    for path in files:
        findings.extend(check_file(path, limit, ignores))
    if emit_json:
        print(json.dumps(_json_doc(
            "stdlib-ast", findings, files=len(files),
        ), indent=2))
        return 1 if findings else 0
    for f in findings:
        print(f)
    status = "FAILED" if findings else "OK"
    print(
        f"ci/lint_repo (stdlib fallback): {status} — {len(files)} files, "
        f"{len(findings)} finding(s) [E999 E501 F401 W291 W292 W293 @ "
        f"line-length {limit}]"
    )
    return 1 if findings else 0


def _json_doc(
    engine: str, findings: list[str], files: int | None = None,
) -> dict:
    """The machine-readable report.  ``engine`` names which linter
    actually ran — CI logs were ambiguous about ruff vs the stdlib
    fallback until this field existed, and the two backends cover
    different rule breadths."""
    doc = {
        "format_version": 1,
        "engine": engine,
        "count": len(findings),
        "findings": findings,
        "duration_s": round(time.perf_counter() - _T0, 3),
    }
    if files is not None:
        doc["files"] = files
    return doc


def run_ruff(ruff: str, emit_json: bool = False) -> int:
    if not emit_json:
        proc = subprocess.run([ruff, "check", "."], cwd=REPO)
        status = "OK" if proc.returncode == 0 else "FAILED"
        print(f"ci/lint_repo (ruff): {status}")
        return proc.returncode
    proc = subprocess.run(
        [ruff, "check", ".", "--output-format", "concise"],
        cwd=REPO, capture_output=True, text=True,
    )
    findings = [
        line for line in proc.stdout.splitlines()
        if line.strip() and not line.startswith(("Found ", "All checks"))
    ]
    print(json.dumps(_json_doc("ruff", findings), indent=2))
    return proc.returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--list", action="store_true",
                    help="print the backend that would run and exit")
    ap.add_argument("--fallback", action="store_true",
                    help="force the stdlib checker even if ruff exists")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout (engine, "
                         "count, findings, duration_s); exit code "
                         "unchanged")
    args = ap.parse_args(argv)

    global _T0
    _T0 = time.perf_counter()

    ruff = shutil.which("ruff")
    if args.list:
        print("backend: " + (f"ruff ({ruff})" if ruff else
                             "stdlib fallback"))
        return 0
    if ruff and not args.fallback:
        return run_ruff(ruff, emit_json=args.json)
    return run_fallback(emit_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
