#!/usr/bin/env bash
# CI pipeline — the travis.sh / Jenkinsfile equivalent (reference:
# travis.sh:1-24 builds the sim, downloads prebuilt traces, simulates, and
# gates on the functional-test monitor; Jenkinsfile:26-52 adds the
# multi-config matrix).  tpusim's tiers:
#
#   1. build   — native components compiled from source (the `make` of
#                accel-sim.out) + the fastpath/native parity suite run
#                against the fresh .so; SKIPPED WITH A VISIBLE NOTICE
#                (never a silent pass) when no C++ compiler is present
#                — pricing then falls back to the vectorized/serial
#                Python paths, which the later tiers still verify
#   2. lint    — repo-wide static analysis (ruff when installed, the
#                stdlib fallback in ci/lint_repo.py otherwise); the
#                JSON report's `engine` field is printed so the log
#                names which linter actually ran
#   3. unit    — pytest fast tier (the improvement over the reference's
#                CI-only testing, SURVEY.md §4)
#   4. golden  — simulate committed fixture traces across a config matrix,
#                diff every stat against ci/golden/ (the prebuilt-trace
#                regression sims)
#   5. obs     — simulate a golden fixture with the observability layer
#                on; validate the emitted samples JSONL / Chrome trace /
#                prometheus text against ci/obs_schema.json
#   6. faults  — degraded-pod smoke: replay a tiny v5p slice with one
#                dead ICI link; check the fault-schedule contract and
#                faults_* stat keys against ci/faults_schema.json
#   7. tlint   — trace/config/schedule lint smoke: `tpusim lint` over
#                every checked-in golden artifact must report zero
#                error-level diagnostics (ci/check_golden --lint-smoke)
#   8. perf    — performance-layer determinism: the golden matrix under
#                --workers 4 + an on-disk result cache must match the
#                committed serial goldens byte-for-byte, and a warm-
#                cache pass must run zero engine pricing walks
#   9. fastpath — pricing-backend parity: the golden matrix priced
#                through the serial reference walk, the NumPy-vectorized
#                fastpath, and (when built) the native kernel must be
#                byte-identical and match the committed goldens; a
#                streaming leg (every module file-backed) must match
#                too; a durable leg (compiled columns persisted to a
#                throwaway store, in-memory tier cleared, traces
#                reloaded with deferred parsing) must match with zero
#                recompiles; and a cold-serve smoke boots a FRESH
#                daemon process against the warm store and requires
#                its first request priced with zero Python IR
#                construction (fastpath_ir_ops_built == 0)
#  10. serve   — serving-layer determinism: boot the daemon on a free
#                loopback port, replay the golden matrix over HTTP;
#                served stats docs must be byte-identical to the
#                committed CLI goldens, and a warm second pass must
#                report cache_hit on every response with zero engine
#                pricing walks — run through BOTH daemon shapes: the
#                single-process path and the serve v2 supervised
#                multi-worker pool (byte-identity across 1..N workers)
#  11. serve-chaos — serve v2 survivability: SIGKILL a supervised
#                worker while the golden matrix is in flight; the run
#                must finish with zero failed requests (the killed
#                request retried on a fresh worker, still golden) and
#                at least one recorded worker restart
#  12. front   — serve v3 front-tier contract: the golden matrix
#                byte-identical across acceptors=1 and acceptors=2
#                (with and without the shared mmap hot-response
#                cache), warm passes served from the mmap tier with
#                zero worker dispatches, an acceptor SIGKILLed
#                mid-matrix costing zero failed requests, and guard
#                deadline-504 / shared-quarantine semantics holding
#                across acceptors
#  13. reqtrace — request-tracing contract (tpusim.obs.reqtrace):
#                tracing off = golden matrix byte-identical with zero
#                new surface (no trace header, no reqtrace series,
#                debug routes 404); tracing on over a 2-acceptor
#                front = same bytes plus X-Tpusim-Trace on every
#                response, fleet /metrics histograms whose +Inf
#                bucket counts sum exactly to serve_requests_total,
#                the slowest recorded trace fetched by id (fleet
#                fan-out) with a valid Perfetto/Chrome export, and
#                parseable per-acceptor JSONL access logs
#  14. campaign — campaign-layer determinism: a fixed-seed 16-scenario
#                Monte-Carlo compound-fault campaign on the llama_tiny
#                fixture must reproduce the committed report
#                byte-for-byte (inflation percentiles, partition rate,
#                SLO capacity table), with the healthy golden matrix
#                untouched
#  15. dcn     — multi-slice fabric contract (tpusim.dcn): a fixed-seed
#                campaign over a 2-slice system with DCN fault kinds
#                (slice_down / dcn_link_down / link_degraded) must
#                reproduce the committed report byte-for-byte with the
#                slice-survival answer intact (loss scenarios, survival
#                histogram, partition attribution), the hierarchical
#                all-reduce decomposition must beat the flat scalar
#                model at a bandwidth-bound payload, and an
#                unconfigured fabric must degenerate byte-identically
#                to the flat model
#  16. advise  — sharding-advisor determinism: a fixed-spec strategy
#                sweep on the llama_tiny fixture must reproduce the
#                committed ranked report byte-for-byte (step-time/
#                ICI-bytes/HBM/watts columns, dp=4 x tp=2 synthesizing
#                the 14-collective MULTICHIP_r05 step), with a warm
#                pass running zero engine walks and the healthy golden
#                matrix untouched
#  17. guard   — resource-governance contract (tpusim.guard): the
#                golden matrix under a small --cache-quota stays
#                byte-identical while the cache dir never exceeds the
#                quota (LRU GC provably engaged), and a served request
#                past its deadline 504s through cooperative in-process
#                cancellation with the worker still alive (zero
#                restarts/kills, warm caches serving the next request)
#  18. fleet   — fleet digital-twin determinism (tpusim.fleet): a
#                fixed-seed traffic-driven fleet simulation on the
#                llama_tiny fixture must reproduce the committed
#                report byte-for-byte (goodput/p99 curve, per-policy
#                loss attribution with a live shedding window, a pod
#                loss with its elastic-recovery row, a non-null
#                capacity-frontier answer), with the healthy golden
#                matrix untouched
#  19. dataflow — tpusim.analysis v2 contract: committed fixtures +
#                golden-matrix traces lint clean of TL4xx/TL41x
#                errors, the liveness pass agrees byte-for-byte with
#                the engine's residency walk across the fixture +
#                silicon corpus, a seeded two-device
#                mismatched-collective trace is statically refused,
#                and the TL35x determinism/durability self-audit over
#                tpusim/'s own sources is green
#  20. cluster — multi-node cluster contract (serve --join +
#                campaign --nodes): the golden matrix byte-identical
#                served single-node vs through both nodes of a 2-node
#                localhost fleet (hot/compiled tiers engaged,
#                consistent-hash forwarding live), one node SIGKILLed
#                mid-matrix costing zero failed requests with the
#                heal recorded (death + epoch bump + gauges), and
#                sharded campaigns — shard child SIGKILLed mid-run,
#                and coordinator killed then --resume'd — merging
#                byte-identical to the uninterrupted single-node
#                report with zero re-priced scenarios
#  21. perflint — tpusim.analysis v3 perf-lint contract (TL5xx):
#                healthy fixtures emit a TL500 critical-path summary
#                and zero TL5xx errors across the arch matrix, the
#                critical-path <= engine-cycles <= serial-op-sum
#                inequality (and exposed <= priced per collective)
#                holds on the full fixture + silicon corpus, a
#                seeded exposed-collective trace trips TL501 through
#                both `tpusim lint --perf` and `tpusim perf-report`,
#                a strict-lint serve daemon admits TL5xx-only
#                verdicts (advisory, never refusing), and the
#                self-audit (now incl. TL353 fork-safety) is green
#  22. slow    — full pytest incl. subprocess CPU-mesh SPMD tests
#                (opt-in: CI_SLOW=1)
#
# Usage:  bash ci/run_ci.sh            # tiers 1-21
#         CI_SLOW=1 bash ci/run_ci.sh  # all tiers
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== [1/22] build native from source (+ native parity suite) ==="
if command -v "${CXX:-g++}" >/dev/null 2>&1; then
  make -C native clean all
  python -m pytest tests/test_native.py tests/test_fastpath.py -q -m "not slow"
else
  echo "**********************************************************************"
  echo "* NOTICE: no C++ compiler found (\$CXX / g++) — the build-native    *"
  echo "* tier is SKIPPED.  libtpusim_native.so was NOT rebuilt from source  *"
  echo "* and the native pricing kernel is unverified on this host; pricing  *"
  echo "* falls back to the vectorized/serial Python paths (still verified   *"
  echo "* by the fastpath-parity tier below).                                *"
  echo "**********************************************************************"
fi

echo "=== [2/22] repo static analysis (ruff / stdlib fallback) ==="
lint_rc=0
python ci/lint_repo.py --json > /tmp/tpusim_lint_repo.json || lint_rc=$?
python - <<'PYEOF'
import json
doc = json.load(open("/tmp/tpusim_lint_repo.json"))
print(f"lint engine: {doc['engine']} — {doc['count']} finding(s)")
for f in doc["findings"]:
    print(f)
PYEOF
[[ "$lint_rc" == "0" ]] || exit "$lint_rc"

echo "=== [3/22] unit tests (fast tier) ==="
python -m pytest tests/ -q -m "not slow"

echo "=== [4/22] golden-stat regression sims ==="
python ci/check_golden.py

echo "=== [5/22] obs export smoke (schema-checked) ==="
python ci/check_golden.py --obs-smoke

echo "=== [6/22] faults smoke (degraded-pod contract) ==="
python ci/check_golden.py --faults-smoke

echo "=== [7/22] trace/config/schedule lint smoke ==="
python ci/check_golden.py --lint-smoke

echo "=== [8/22] perf smoke (parallel+cached determinism) ==="
python ci/check_golden.py --perf-smoke

echo "=== [9/22] fastpath parity (pricing-backend + durable-tier + scenario-batch byte-identity) ==="
python ci/check_golden.py --fastpath-parity

echo "=== [10/22] serve smoke (HTTP daemon determinism, 1..N workers) ==="
python ci/check_golden.py --serve-smoke

echo "=== [11/22] serve chaos smoke (worker SIGKILL survivability) ==="
python ci/check_golden.py --serve-chaos-smoke

echo "=== [12/22] front smoke (serve v3 multi-acceptor contract) ==="
python ci/check_golden.py --front-smoke

echo "=== [13/22] reqtrace smoke (request-tracing + latency-histogram contract) ==="
python ci/check_golden.py --reqtrace-smoke

echo "=== [14/22] campaign smoke (Monte-Carlo determinism) ==="
python ci/check_golden.py --campaign-smoke

echo "=== [15/22] dcn smoke (multi-slice fabric contract) ==="
python ci/check_golden.py --dcn-smoke

echo "=== [16/22] advise smoke (sharding-advisor determinism) ==="
python ci/check_golden.py --advise-smoke

echo "=== [17/22] guard smoke (quota/GC + cooperative-cancel contract) ==="
python ci/check_golden.py --guard-smoke

echo "=== [18/22] fleet smoke (digital-twin determinism) ==="
python ci/check_golden.py --fleet-smoke

echo "=== [19/22] dataflow smoke (liveness/deadlock/self-audit contract) ==="
python ci/check_golden.py --dataflow-smoke

echo "=== [20/22] cluster smoke (multi-node membership + distributed campaign chaos) ==="
python ci/check_golden.py --cluster-smoke

echo "=== [21/22] perf-lint smoke (critical-path/TL5xx contract) ==="
python ci/check_golden.py --perf-lint-smoke

if [[ "${CI_SLOW:-0}" == "1" ]]; then
  echo "=== [22/22] slow tier (SPMD subprocess meshes) ==="
  python -m pytest tests/ -q -m slow
else
  echo "=== [22/22] slow tier skipped (set CI_SLOW=1) ==="
fi

echo "CI: all tiers green"
