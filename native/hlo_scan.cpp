// Fast HLO-text scanner — the native core of the stored-trace parser.
//
// The reference's trace parser is C++ (gpu-simulator/trace-parser/
// trace_parser.cc) and its per-line work (inst_trace_t::parse_from_string)
// is the hot path of trace loading; ours is the same but for HLO text.
// Llama-scale optimized HLO dumps run to tens of MB, and the pure-Python
// regex parser in tpusim/trace/hlo_text.py spends most of its time on line
// classification and balanced-delimiter splitting.  This scanner does that
// structural pass in C++ and emits a flat record stream; Python rebuilds IR
// objects from pre-split fields (tpusim/trace/native.py).
//
// Output format (returned as one malloc'd buffer, caller frees via
// hlo_scan_free): records separated by RS (0x1e), fields by US (0x1f).
//   M <US> module_name <US> raw_module_attrs
//   C <US> comp_name <US> is_entry("0"/"1")
//   I <US> name <US> is_root <US> shape_text <US> opcode <US>
//        operands(comma-joined) <US> raw_attr_text <US> literal
//   (literal = raw paren content, emitted for "constant" ops only)
// Control chars cannot appear in HLO text, so no escaping is needed.
//
// Build: make -C native   (produces libtpusim_native.so)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Out {
  std::string buf;
  void field(const char* s, size_t n) {
    buf.append(s, n);
    buf.push_back('\x1f');
  }
  void field(const std::string& s) { field(s.data(), s.size()); }
  void end_record() {
    if (!buf.empty() && buf.back() == '\x1f') buf.back() = '\x1e';
    else buf.push_back('\x1e');
  }
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  return p;
}

// Find the matching closer for the opener at p (p points at the opener).
// Respects double-quoted strings with backslash escapes.
const char* find_match(const char* p, const char* end) {
  char open = *p, close;
  switch (open) {
    case '(': close = ')'; break;
    case '{': close = '}'; break;
    case '[': close = ']'; break;
    default: return nullptr;
  }
  int depth = 0;
  bool in_str = false;
  for (; p < end; ++p) {
    char c = *p;
    if (in_str) {
      if (c == '\\') { ++p; continue; }
      if (c == '"') in_str = false;
    } else if (c == '"') {
      in_str = true;
    } else if (c == open) {
      ++depth;
    } else if (c == close) {
      if (--depth == 0) return p;
    }
  }
  return nullptr;
}

bool is_ident_char(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == '.' || c == '-';
}

// Parse one instruction line: [ROOT] %name = shape opcode(operands), attrs
// Returns false if the line is not an instruction.
bool scan_instruction(const char* p, const char* end, Out& out) {
  p = skip_ws(p, end);
  bool root = false;
  if (end - p > 5 && std::memcmp(p, "ROOT ", 5) == 0) {
    root = true;
    p = skip_ws(p + 5, end);
  }
  if (p < end && *p == '%') ++p;
  const char* name_start = p;
  while (p < end && is_ident_char(*p)) ++p;
  if (p == name_start) return false;
  const char* name_end = p;
  p = skip_ws(p, end);
  if (p >= end || *p != '=') return false;
  p = skip_ws(p + 1, end);

  // result shape: tuple "(...)" possibly followed by layout, or
  // "dtype[...]{...}" — scan until we hit " opcode("
  const char* shape_start = p;
  if (*p == '(') {
    const char* m = find_match(p, end);
    if (!m) return false;
    p = m + 1;
  } else {
    while (p < end && *p != ' ') ++p;
  }
  const char* shape_end = p;
  p = skip_ws(p, end);

  const char* opcode_start = p;
  while (p < end && *p != '(' && *p != ' ') ++p;
  const char* opcode_end = p;
  if (p >= end || *p != '(') return false;
  const char* close = find_match(p, end);
  if (!close) return false;

  // operands: collect %-prefixed identifiers at top level of the parens
  std::string operands;
  {
    const char* q = p + 1;
    int depth = 0;
    bool in_str = false;
    const char* last_pct = nullptr;
    auto flush = [&](const char* upto) {
      if (!last_pct) return;
      const char* s = last_pct + 1;
      const char* e = s;
      while (e < upto && is_ident_char(*e)) ++e;
      if (e > s) {
        if (!operands.empty()) operands.push_back(',');
        operands.append(s, e - s);
      }
      last_pct = nullptr;
    };
    for (; q < close; ++q) {
      char c = *q;
      if (in_str) {
        if (c == '\\') { ++q; continue; }
        if (c == '"') in_str = false;
      } else if (c == '"') {
        in_str = true;
      } else if (c == '(' || c == '{' || c == '[') {
        ++depth;
      } else if (c == ')' || c == '}' || c == ']') {
        --depth;
      } else if (depth == 0 && c == '%') {
        flush(q);           // a previous operand ends here
        last_pct = q;
      } else if (depth == 0 && c == ',') {
        flush(q);
      }
    }
    flush(close);
  }

  const char* attrs = close + 1;
  attrs = skip_ws(attrs, end);
  if (attrs < end && *attrs == ',') attrs = skip_ws(attrs + 1, end);

  out.field("I", 1);
  out.field(name_start, name_end - name_start);
  out.field(root ? "1" : "0", 1);
  out.field(shape_start, shape_end - shape_start);
  out.field(opcode_start, opcode_end - opcode_start);
  out.field(operands);
  out.field(attrs, end - attrs);
  // constants need their literal; parameters their index (for fusion
  // operand-to-param mapping) — both ride in the final field
  const size_t op_len = opcode_end - opcode_start;
  const bool keep_paren =
      (op_len == 8 && std::memcmp(opcode_start, "constant", 8) == 0) ||
      (op_len == 9 && std::memcmp(opcode_start, "parameter", 9) == 0);
  if (keep_paren)
    out.field(p + 1, close - p - 1);
  else
    out.field("", 0);
  out.end_record();
  return true;
}

}  // namespace

extern "C" {

// Scans the HLO text; returns a malloc'd record buffer (see header
// comment) and stores its length in *out_len.  Caller must free with
// hlo_scan_free.  Returns nullptr on allocation failure.
char* hlo_scan(const char* text, uint64_t len, uint64_t* out_len) {
  Out out;
  out.buf.reserve(len / 2);
  const char* p = text;
  const char* end = text + len;
  bool in_comp = false;

  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    // rtrim CR (CRLF input) and trailing whitespace — the Python parser
    // strips lines, so the native scanner must too
    while (line_end > p && (*(line_end - 1) == '\r' ||
                            *(line_end - 1) == ' ' ||
                            *(line_end - 1) == '\t'))
      --line_end;
    const char* s = skip_ws(p, line_end);
    size_t n = line_end - s;

    if (n == 0) { p = (nl ? nl : end) + 1; continue; }

    if (!in_comp) {
      if (n > 10 && std::memcmp(s, "HloModule ", 10) == 0) {
        const char* q = s + 10;
        const char* name_start = q;
        while (q < line_end && is_ident_char(*q)) ++q;
        out.field("M", 1);
        out.field(name_start, q - name_start);
        const char* rest = skip_ws(q, line_end);
        if (rest < line_end && *rest == ',') rest = skip_ws(rest + 1, line_end);
        out.field(rest, line_end - rest);
        out.end_record();
      } else if (line_end > s && *(line_end - 1) == '{' &&
                 memchr(s, '(', n) != nullptr &&
                 // a computation header has "(params) -> ret {"; the '%'
                 // prefix is optional (matches Python's _COMP_HEADER_RE)
                 [&] {
                   for (const char* q = s; q + 1 < line_end; ++q)
                     if (q[0] == '-' && q[1] == '>') return true;
                   return false;
                 }()) {
        bool entry = (n > 6 && std::memcmp(s, "ENTRY ", 6) == 0);
        const char* q = s + (entry ? 6 : 0);
        q = skip_ws(q, line_end);
        if (q < line_end && *q == '%') ++q;
        const char* name_start = q;
        while (q < line_end && is_ident_char(*q)) ++q;
        if (q > name_start) {
          out.field("C", 1);
          out.field(name_start, q - name_start);
          out.field(entry ? "1" : "0", 1);
          out.end_record();
          in_comp = true;
        }
      }
      // anything else outside a computation (stack-frame tables etc.):skip
    } else {
      if (n == 1 && *s == '}') {
        out.field("E", 1);
        out.end_record();
        in_comp = false;
      } else {
        scan_instruction(s, line_end, out);
      }
    }
    if (!nl) break;
    p = nl + 1;
  }
  // tolerate an unterminated final computation, like the Python parser
  if (in_comp) {
    out.field("E", 1);
    out.end_record();
  }

  char* result = static_cast<char*>(std::malloc(out.buf.size() + 1));
  if (!result) return nullptr;
  std::memcpy(result, out.buf.data(), out.buf.size());
  result[out.buf.size()] = '\0';
  *out_len = out.buf.size();
  return result;
}

void hlo_scan_free(char* p) { std::free(p); }

int hlo_scan_abi_version() { return 1; }

}  // extern "C"
