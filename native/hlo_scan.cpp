// Fast HLO-text scanner — the native core of the stored-trace parser.
//
// The reference's trace parser is C++ (gpu-simulator/trace-parser/
// trace_parser.cc) and its per-line work (inst_trace_t::parse_from_string)
// is the hot path of trace loading; ours is the same but for HLO text.
// Llama-scale optimized HLO dumps run to tens of MB, and the pure-Python
// regex parser in tpusim/trace/hlo_text.py spends most of its time on line
// classification and balanced-delimiter splitting.  This scanner does that
// structural pass in C++ and emits a flat record stream; Python rebuilds IR
// objects from pre-split fields (tpusim/trace/native.py).
//
// Output format (returned as one malloc'd buffer, caller frees via
// hlo_scan_free): records separated by RS (0x1e), fields by US (0x1f).
//   M <US> module_name <US> raw_module_attrs
//   C <US> comp_name <US> is_entry("0"/"1")
//   I <US> name <US> is_root <US> shape_text <US> opcode <US>
//        operands(comma-joined) <US> raw_attr_text <US> literal
//   (literal = raw paren content, emitted for "constant" ops only)
// Control chars cannot appear in HLO text, so no escaping is needed.
//
// hlo_scan2 (parse-to-columns) emits the same record frame with two
// fields upgraded so Python IR assembly runs no regex and no
// balanced-delimiter splitting at all:
//   * the shape field carries a pre-parsed token stream — ';'-joined
//     prefix tokens, "(N" opening an N-part tuple and
//     "dtype:dims:layout:tiling:space" per leaf (layout/tiling "n" when
//     absent; dims/layout canonical comma-joined ints) — or, when a
//     shape needs the reference parser (comments, exotic layouts), the
//     raw text prefixed with '!' so Python falls back per shape with
//     identical error semantics;
//   * the attr field carries the top-level attr tokens pre-split and
//     GS (0x1d)-joined — exactly split_top_level(raw_attr_text)'s
//     non-empty stripped tokens.
//
// Build: make -C native   (produces libtpusim_native.so)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

struct Out {
  std::string buf;
  void field(const char* s, size_t n) {
    buf.append(s, n);
    buf.push_back('\x1f');
  }
  void field(const std::string& s) { field(s.data(), s.size()); }
  void end_record() {
    if (!buf.empty() && buf.back() == '\x1f') buf.back() = '\x1e';
    else buf.push_back('\x1e');
  }
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  return p;
}

// Find the matching closer for the opener at p (p points at the opener).
// Respects double-quoted strings with backslash escapes.
const char* find_match(const char* p, const char* end) {
  char open = *p, close;
  switch (open) {
    case '(': close = ')'; break;
    case '{': close = '}'; break;
    case '[': close = ']'; break;
    default: return nullptr;
  }
  int depth = 0;
  bool in_str = false;
  for (; p < end; ++p) {
    char c = *p;
    if (in_str) {
      if (c == '\\') { ++p; continue; }
      if (c == '"') in_str = false;
    } else if (c == '"') {
      in_str = true;
    } else if (c == open) {
      ++depth;
    } else if (c == close) {
      if (--depth == 0) return p;
    }
  }
  return nullptr;
}

bool is_ident_char(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == '.' || c == '-';
}

// ---------------------------------------------------------------------------
// v2 shape encoding (parse-to-columns)
// ---------------------------------------------------------------------------
//
// Mirrors tpusim/trace/hlo_text.py parse_shape exactly on the fast
// path; anything the mirror cannot guarantee byte-for-byte falls back
// to the reference parser per shape (the caller emits '!' + raw text).

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

inline const char* trim_span(const char*& p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  while (end > p && (end[-1] == ' ' || end[-1] == '\t')) --end;
  return end;
}

// Parse a comma-separated int list ("8,128", entries padded with
// blanks; empty entries skipped) into canonical comma-joined
// decimals.  `allow_dyn` strips leading '<'/'=' chars per entry — the
// dynamic-dim form the reference strips ONLY in dims
// (`d.strip().lstrip("<=")` + int(d)); a layout minor list must NOT
// accept it, or the mirror would parse text the reference rejects.
// False when anything else appears.
bool enc_int_list(const char* p, const char* end, std::string& out,
                  int* count, bool allow_dyn) {
  *count = 0;
  while (p < end) {
    const char* seg_end =
        static_cast<const char*>(memchr(p, ',', end - p));
    if (!seg_end) seg_end = end;
    const char* s = p;
    const char* e = trim_span(s, seg_end);
    if (allow_dyn)
      while (s < e && (*s == '<' || *s == '=')) ++s;
    if (s < e) {
      if (e - s > 18) return false;  // int64 overflow guard
      long long v = 0;
      for (const char* q = s; q < e; ++q) {
        if (!is_digit(*q)) return false;
        v = v * 10 + (*q - '0');
      }
      if (*count) out.push_back(',');
      out += std::to_string(v);
      ++(*count);
    }
    p = seg_end + 1;
  }
  return true;
}

// Encode one array leaf "dtype[dims]{layout}" (whole span, anchored).
bool enc_leaf(const char* p, const char* end, std::string& out) {
  const char* q = p;
  if (q >= end || *q < 'a' || *q > 'z') return false;
  ++q;
  while (q < end &&
         ((*q >= 'a' && *q <= 'z') || is_digit(*q)))
    ++q;
  out.append(p, q - p);  // dtype
  out.push_back(':');
  if (q < end && *q == '[') {
    const char* close =
        static_cast<const char*>(memchr(q, ']', end - q));
    if (!close) return false;
    int n = 0;
    if (!enc_int_list(q + 1, close, out, &n, /*allow_dyn=*/true))
      return false;
    q = close + 1;
  }
  out.push_back(':');
  std::string tiling = "n";
  long long space = 0;
  if (q < end && *q == '{') {
    const char* close = find_match(q, end);
    if (!close) return false;
    const char* lay = q + 1;
    const char* colon =
        static_cast<const char*>(memchr(lay, ':', close - lay));
    const char* minor_end = colon ? colon : close;
    // minor list: layout None when (post-strip) empty, else canonical
    // ints; a non-empty minor yielding zero entries (e.g. "{,}") is an
    // empty-tuple layout the mirror refuses — reference parser decides
    const char* ms = lay;
    const char* me = trim_span(ms, minor_end);
    if (ms < me) {
      std::string minor;
      int n = 0;
      if (!enc_int_list(ms, me, minor, &n, /*allow_dyn=*/false))
        return false;
      if (n == 0) return false;
      out += minor;
    } else {
      out.push_back('n');
    }
    if (colon) {
      // extras: first T(...)+ run is the tiling, first S(d+) the space
      const char* x = colon + 1;
      for (const char* t = x; t + 1 < close; ++t) {
        if (*t != 'T' || t[1] != '(') continue;
        const char* g = t + 1;
        const char* run_end = g;
        while (run_end < close && *run_end == '(') {
          const char* h = run_end + 1;
          while (h < close && (is_digit(*h) || *h == ',')) ++h;
          if (h >= close || *h != ')') break;
          run_end = h + 1;
        }
        if (run_end > g) {
          tiling.assign(g, run_end - g);
          break;
        }
      }
      for (const char* t = x; t + 1 < close; ++t) {
        if (*t != 'S' || t[1] != '(') continue;
        const char* h = t + 2;
        long long v = 0;
        int digits = 0;
        while (h < close && is_digit(*h)) {
          if (++digits > 18) return false;  // mirror refuses, not wrong
          v = v * 10 + (*h - '0');
          ++h;
        }
        if (digits >= 1 && h < close && *h == ')') {
          space = v;
          break;
        }
      }
    }
    q = close + 1;
  } else {
    out.push_back('n');
  }
  if (q != end) return false;  // the reference regex is anchored
  out.push_back(':');
  out += tiling;
  out.push_back(':');
  out += std::to_string(space);
  return true;
}

// Encode a full (possibly tuple) shape span into the ';'-joined prefix
// token stream.  False -> caller emits the raw-text fallback.
bool enc_shape(const char* p, const char* end, std::string& out) {
  end = trim_span(p, end);
  if (p >= end) return false;
  // parse_shape strips /*...*/ comments first; the mirror defers
  for (const char* q = p; q + 1 < end; ++q)
    if (q[0] == '/' && q[1] == '*') return false;
  if (*p != '(') return enc_leaf(p, end, out);
  const char* close = find_match(p, end);
  if (!close) return false;
  // split the tuple interior at top level (quote-aware depth count),
  // mirroring split_top_level; trailing text past ')' is ignored like
  // the reference's tuple branch
  std::vector<std::pair<const char*, const char*>> parts;
  {
    const char* start = p + 1;
    int depth = 0;
    bool in_str = false;
    for (const char* q = p + 1; q < close; ++q) {
      char c = *q;
      if (in_str) {
        if (c == '\\') { ++q; continue; }
        if (c == '"') in_str = false;
      } else if (c == '"') {
        in_str = true;
      } else if (c == '(' || c == '{' || c == '[') {
        ++depth;
      } else if (c == ')' || c == '}' || c == ']') {
        --depth;
      } else if (c == ',' && depth == 0) {
        parts.emplace_back(start, q);
        start = q + 1;
      }
    }
    const char* s = start;
    const char* e = trim_span(s, close);
    if (s < e || !parts.empty()) {
      if (s < e) parts.emplace_back(start, close);
      else if (!parts.empty()) return false;  // trailing empty part
    }
  }
  out.push_back('(');
  out += std::to_string(parts.size());
  for (auto& pr : parts) {
    out.push_back(';');
    if (!enc_shape(pr.first, pr.second, out)) return false;
  }
  return true;
}

// split_top_level(raw_attr_text) in C++: GS-joined non-empty stripped
// top-level tokens (the only consumers skip empties).
void split_attr_tokens(const char* p, const char* end, std::string& out) {
  const char* start = p;
  int depth = 0;
  bool in_str = false;
  auto push = [&](const char* s, const char* e) {
    e = trim_span(s, e);
    if (s >= e) return;
    if (!out.empty()) out.push_back('\x1d');
    out.append(s, e - s);
  };
  for (const char* q = p; q < end; ++q) {
    char c = *q;
    if (in_str) {
      if (c == '\\') { ++q; continue; }
      if (c == '"') in_str = false;
    } else if (c == '"') {
      in_str = true;
    } else if (c == '(' || c == '{' || c == '[') {
      ++depth;
    } else if (c == ')' || c == '}' || c == ']') {
      --depth;
    } else if (c == ',' && depth == 0) {
      push(start, q);
      start = q + 1;
    }
  }
  push(start, end);
}

// Parse one instruction line: [ROOT] %name = shape opcode(operands), attrs
// Returns false if the line is not an instruction.  v2 upgrades the
// shape field to the pre-parsed token stream ('!'-prefixed raw text
// when the mirror defers to the reference parser) and the attr field
// to pre-split GS-joined top-level tokens.
bool scan_instruction(const char* p, const char* end, Out& out,
                      bool v2) {
  p = skip_ws(p, end);
  bool root = false;
  if (end - p > 5 && std::memcmp(p, "ROOT ", 5) == 0) {
    root = true;
    p = skip_ws(p + 5, end);
  }
  if (p < end && *p == '%') ++p;
  const char* name_start = p;
  while (p < end && is_ident_char(*p)) ++p;
  if (p == name_start) return false;
  const char* name_end = p;
  p = skip_ws(p, end);
  if (p >= end || *p != '=') return false;
  p = skip_ws(p + 1, end);

  // result shape: tuple "(...)" possibly followed by layout, or
  // "dtype[...]{...}" — scan until we hit " opcode("
  const char* shape_start = p;
  if (*p == '(') {
    const char* m = find_match(p, end);
    if (!m) return false;
    p = m + 1;
  } else {
    while (p < end && *p != ' ') ++p;
  }
  const char* shape_end = p;
  p = skip_ws(p, end);

  const char* opcode_start = p;
  while (p < end && *p != '(' && *p != ' ') ++p;
  const char* opcode_end = p;
  if (p >= end || *p != '(') return false;
  const char* close = find_match(p, end);
  if (!close) return false;

  // operands: collect %-prefixed identifiers at top level of the parens
  std::string operands;
  {
    const char* q = p + 1;
    int depth = 0;
    bool in_str = false;
    const char* last_pct = nullptr;
    auto flush = [&](const char* upto) {
      if (!last_pct) return;
      const char* s = last_pct + 1;
      const char* e = s;
      while (e < upto && is_ident_char(*e)) ++e;
      if (e > s) {
        if (!operands.empty()) operands.push_back(',');
        operands.append(s, e - s);
      }
      last_pct = nullptr;
    };
    for (; q < close; ++q) {
      char c = *q;
      if (in_str) {
        if (c == '\\') { ++q; continue; }
        if (c == '"') in_str = false;
      } else if (c == '"') {
        in_str = true;
      } else if (c == '(' || c == '{' || c == '[') {
        ++depth;
      } else if (c == ')' || c == '}' || c == ']') {
        --depth;
      } else if (depth == 0 && c == '%') {
        flush(q);           // a previous operand ends here
        last_pct = q;
      } else if (depth == 0 && c == ',') {
        flush(q);
      }
    }
    flush(close);
  }

  const char* attrs = close + 1;
  attrs = skip_ws(attrs, end);
  if (attrs < end && *attrs == ',') attrs = skip_ws(attrs + 1, end);

  out.field("I", 1);
  out.field(name_start, name_end - name_start);
  out.field(root ? "1" : "0", 1);
  if (v2) {
    std::string enc;
    if (enc_shape(shape_start, shape_end, enc)) {
      out.field(enc);
    } else {
      enc.clear();
      enc.push_back('!');
      enc.append(shape_start, shape_end - shape_start);
      out.field(enc);
    }
  } else {
    out.field(shape_start, shape_end - shape_start);
  }
  out.field(opcode_start, opcode_end - opcode_start);
  out.field(operands);
  if (v2) {
    std::string toks;
    split_attr_tokens(attrs, end, toks);
    out.field(toks);
  } else {
    out.field(attrs, end - attrs);
  }
  // constants need their literal; parameters their index (for fusion
  // operand-to-param mapping) — both ride in the final field
  const size_t op_len = opcode_end - opcode_start;
  const bool keep_paren =
      (op_len == 8 && std::memcmp(opcode_start, "constant", 8) == 0) ||
      (op_len == 9 && std::memcmp(opcode_start, "parameter", 9) == 0);
  if (keep_paren)
    out.field(p + 1, close - p - 1);
  else
    out.field("", 0);
  out.end_record();
  return true;
}

// Scans the HLO text; returns a malloc'd record buffer (see header
// comment) and stores its length in *out_len.  Caller must free with
// hlo_scan_free.  Returns nullptr on allocation failure.
char* scan_impl(const char* text, uint64_t len, uint64_t* out_len,
                bool v2) {
  Out out;
  out.buf.reserve(len / 2);
  const char* p = text;
  const char* end = text + len;
  bool in_comp = false;

  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    // rtrim CR (CRLF input) and trailing whitespace — the Python parser
    // strips lines, so the native scanner must too
    while (line_end > p && (*(line_end - 1) == '\r' ||
                            *(line_end - 1) == ' ' ||
                            *(line_end - 1) == '\t'))
      --line_end;
    const char* s = skip_ws(p, line_end);
    size_t n = line_end - s;

    if (n == 0) { p = (nl ? nl : end) + 1; continue; }

    if (!in_comp) {
      if (n > 10 && std::memcmp(s, "HloModule ", 10) == 0) {
        const char* q = s + 10;
        const char* name_start = q;
        while (q < line_end && is_ident_char(*q)) ++q;
        out.field("M", 1);
        out.field(name_start, q - name_start);
        const char* rest = skip_ws(q, line_end);
        if (rest < line_end && *rest == ',') rest = skip_ws(rest + 1, line_end);
        out.field(rest, line_end - rest);
        out.end_record();
      } else if (line_end > s && *(line_end - 1) == '{' &&
                 memchr(s, '(', n) != nullptr &&
                 // a computation header has "(params) -> ret {"; the '%'
                 // prefix is optional (matches Python's _COMP_HEADER_RE)
                 [&] {
                   for (const char* q = s; q + 1 < line_end; ++q)
                     if (q[0] == '-' && q[1] == '>') return true;
                   return false;
                 }()) {
        bool entry = (n > 6 && std::memcmp(s, "ENTRY ", 6) == 0);
        const char* q = s + (entry ? 6 : 0);
        q = skip_ws(q, line_end);
        if (q < line_end && *q == '%') ++q;
        const char* name_start = q;
        while (q < line_end && is_ident_char(*q)) ++q;
        if (q > name_start) {
          out.field("C", 1);
          out.field(name_start, q - name_start);
          out.field(entry ? "1" : "0", 1);
          out.end_record();
          in_comp = true;
        }
      }
      // anything else outside a computation (stack-frame tables etc.):skip
    } else {
      if (n == 1 && *s == '}') {
        out.field("E", 1);
        out.end_record();
        in_comp = false;
      } else {
        scan_instruction(s, line_end, out, v2);
      }
    }
    if (!nl) break;
    p = nl + 1;
  }
  // tolerate an unterminated final computation, like the Python parser
  if (in_comp) {
    out.field("E", 1);
    out.end_record();
  }

  char* result = static_cast<char*>(std::malloc(out.buf.size() + 1));
  if (!result) return nullptr;
  std::memcpy(result, out.buf.data(), out.buf.size());
  result[out.buf.size()] = '\0';
  *out_len = out.buf.size();
  return result;
}

}  // namespace

extern "C" {

char* hlo_scan(const char* text, uint64_t len, uint64_t* out_len) {
  return scan_impl(text, len, out_len, false);
}

// parse-to-columns variant: pre-parsed shapes + pre-split attr tokens
char* hlo_scan2(const char* text, uint64_t len, uint64_t* out_len) {
  return scan_impl(text, len, out_len, true);
}

void hlo_scan_free(char* p) { std::free(p); }

int hlo_scan_abi_version() { return 1; }

int hlo_scan2_abi_version() { return 1; }

}  // extern "C"
