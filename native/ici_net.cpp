// Detailed ICI network simulator: event-driven virtual cut-through packet
// switching on a k-ary n-torus (1..3 dims), dimension-order routing,
// per-link serialization and FIFO arbitration.
//
// This is tpusim's equivalent of the reference's detailed interconnect
// option (BookSim2's kncube torus, /root/reference/gpu-simulator/gpgpu-sim/
// src/intersim2/networks/kncube.{hpp,cpp}, selected by -network_mode): the
// analytic model in tpusim/ici/collectives.py answers "what does the
// schedule cost on paper", this one answers "what does it cost when every
// packet contends for real links".  Differences from BookSim, by design:
// packets cut through with infinite router buffering (no VC/credit stalls),
// because ICI collective traffic is long-flow dominated and the first-order
// contention effect is link serialization, not buffer occupancy.
//
// Model: a packet of B bytes crossing links l1..lk:
//   depart(l1)   = max(inject_time, free(l1))
//   arrive(l_i+1)= depart(l_i) + hop_cycles          (router+SerDes pipeline)
//   depart(l_i+1)= max(arrive(l_i+1), free(l_i+1))
//   free(l_i)    = depart(l_i) + B / flit_bytes      (serialization)
//   completion   = depart(l_k) + hop_cycles + B / flit_bytes
// Arbitration is FIFO in request time (ties broken by injection order).
//
// Exposed as a C ABI consumed via ctypes by tpusim/ici/detailed.py, which
// contains the contract-tested pure-Python fallback.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

struct Net {
  int ndims = 1;
  long dims[3] = {1, 1, 1};
  int wrap[3] = {0, 0, 0};
  double flit_bytes = 1.0;   // bytes the link moves per cycle
  long hop_cycles = 1;       // head latency per hop (router + wire)
  long num_nodes = 1;
  std::vector<double> link_free;  // indexed by directed link id

  long coord(long node, int axis) const {
    for (int a = 0; a < axis; ++a) node /= dims[a];
    return node % dims[axis];
  }

  long neighbor(long node, int axis, int dir) const {
    // dir: 0 = +1 along axis, 1 = -1 along axis
    long stride = 1;
    for (int a = 0; a < axis; ++a) stride *= dims[a];
    long c = coord(node, axis);
    long d = dims[axis];
    long nc = dir == 0 ? (c + 1) % d : (c - 1 + d) % d;
    return node + (nc - c) * stride;
  }

  long link_id(long node, int axis, int dir) const {
    return (node * ndims + axis) * 2 + dir;
  }

  // Dimension-order route: correct each axis in order, taking the shorter
  // way around on wrapped axes (positive direction on ties).  ``hint``
  // (axis*2+dir, or -1) forces the rotation direction for that one axis —
  // how counter-rotating ring schedules claim both directions of an axis
  // even when the short way ties or wins.
  void route(long src, long dst, long hint, std::vector<long>* links) const {
    links->clear();
    long cur = src;
    for (int axis = 0; axis < ndims; ++axis) {
      long d = dims[axis];
      long cs = coord(cur, axis), cd = coord(dst, axis);
      if (cs == cd) continue;
      long fwd = (cd - cs + d) % d;   // hops going +1
      long bwd = (cs - cd + d) % d;   // hops going -1
      int dir;
      long hops;
      if (hint >= 0 && hint / 2 == axis) {
        dir = static_cast<int>(hint % 2);
        hops = dir == 0 ? fwd : bwd;
        if (!wrap[axis]) {  // mesh edge: forced direction may be invalid
          if ((dir == 0 && cd < cs) || (dir == 1 && cd > cs)) {
            dir = cd > cs ? 0 : 1;
            hops = std::labs(cd - cs);
          }
        }
      } else if (!wrap[axis]) {
        dir = cd > cs ? 0 : 1;
        hops = std::labs(cd - cs);
      } else if (fwd <= bwd) {
        dir = 0;
        hops = fwd;
      } else {
        dir = 1;
        hops = bwd;
      }
      for (long h = 0; h < hops; ++h) {
        links->push_back(link_id(cur, axis, dir));
        cur = neighbor(cur, axis, dir);
      }
    }
  }
};

struct Packet {
  std::vector<long> links;
  size_t pos = 0;
  double ser = 0.0;  // serialization cycles for this packet
};

struct Event {
  double t;
  long seq;
  long pkt;
  bool operator>(const Event& o) const {
    if (t != o.t) return t > o.t;
    return seq > o.seq;
  }
};

}  // namespace

extern "C" {

int ici_net_abi_version() { return 2; }

void* ici_net_create(int ndims, const long* dims, const int* wrap,
                     double flit_bytes, long hop_cycles) {
  if (ndims < 1 || ndims > 3 || flit_bytes <= 0) return nullptr;
  Net* n = new Net();
  n->ndims = ndims;
  n->num_nodes = 1;
  for (int i = 0; i < ndims; ++i) {
    if (dims[i] < 1) {
      delete n;
      return nullptr;
    }
    n->dims[i] = dims[i];
    n->wrap[i] = wrap[i];
    n->num_nodes *= dims[i];
  }
  n->flit_bytes = flit_bytes;
  n->hop_cycles = hop_cycles < 0 ? 0 : hop_cycles;
  n->link_free.assign(static_cast<size_t>(n->num_nodes) * n->ndims * 2, 0.0);
  return n;
}

void ici_net_destroy(void* h) { delete static_cast<Net*>(h); }

// Simulate a sequence of phases (barrier between phases; time resets to 0
// for each and the per-phase makespans sum).  Transfers are given as
// parallel arrays; phase[] must be non-decreasing; hints[i] (axis*2+dir,
// -1 = auto) forces that transfer's rotation direction on one axis.  Each
// transfer is split into packets of at most packet_bytes.  Returns total
// cycles, or -1 on bad input.
double ici_net_sim_phases(void* h, long n, const long* phase, const long* src,
                          const long* dst, const double* nbytes,
                          const long* hints, double packet_bytes) {
  Net* net = static_cast<Net*>(h);
  if (!net || n < 0) return -1.0;
  if (packet_bytes <= 0) packet_bytes = 16384.0;

  double total = 0.0;
  long i = 0;
  while (i < n) {
    long cur_phase = phase[i];
    std::vector<Packet> pkts;
    std::priority_queue<Event, std::vector<Event>, std::greater<Event>> heap;
    long seq = 0;
    double phase_end = 0.0;

    for (; i < n && phase[i] == cur_phase; ++i) {
      if (src[i] < 0 || src[i] >= net->num_nodes || dst[i] < 0 ||
          dst[i] >= net->num_nodes || nbytes[i] < 0)
        return -1.0;
      if (src[i] == dst[i] || nbytes[i] == 0.0) continue;
      std::vector<long> links;
      net->route(src[i], dst[i], hints ? hints[i] : -1, &links);
      long npk = static_cast<long>(std::ceil(nbytes[i] / packet_bytes));
      if (npk < 1) npk = 1;
      double per = nbytes[i] / npk;
      for (long p = 0; p < npk; ++p) {
        Packet pk;
        pk.links = links;
        pk.ser = per / net->flit_bytes;
        pkts.push_back(std::move(pk));
        heap.push(Event{0.0, seq++, static_cast<long>(pkts.size()) - 1});
      }
    }

    std::fill(net->link_free.begin(), net->link_free.end(), 0.0);
    while (!heap.empty()) {
      Event ev = heap.top();
      heap.pop();
      Packet& pk = pkts[ev.pkt];
      long l = pk.links[pk.pos];
      double depart = std::max(ev.t, net->link_free[l]);
      net->link_free[l] = depart + pk.ser;
      double arrive = depart + net->hop_cycles;
      pk.pos += 1;
      if (pk.pos >= pk.links.size()) {
        phase_end = std::max(phase_end, arrive + pk.ser);
      } else {
        heap.push(Event{arrive, seq++, ev.pkt});
      }
    }
    total += phase_end;
  }
  return total;
}

}  // extern "C"
