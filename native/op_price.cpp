// Native pricing scan for the tpusim fastpath (tpusim/fastpath/).
//
// One fused serial pass over a compiled run of ordinary synchronous
// ops: advances the core clock and the six counter accumulators in the
// exact order the Python schedule walk performs its `+=` chain, so the
// result is byte-identical to the pure-Python path (IEEE-754 binary64,
// no reassociation -- build with -ffp-contract=off, see the Makefile).
//
// Everything stateful (async DMA channels, HBM contention, control
// flow, collectives) stays in Python: those steps are rare and cheap,
// while sync runs dominate op counts.  Loaded via ctypes from
// tpusim/fastpath/native.py with the same fallback contract as
// hlo_scan.cpp.

#include <cstdint>

extern "C" {

int op_price_abi_version() { return 1; }

// acc layout: [t, flops, mxu_flops, transcendentals, hbm_bytes,
//              vmem_bytes, vmem_spill_bytes]
//
// t_before (nullable) receives the core clock BEFORE each op: the
// Python side derives per-op aggregate values as (t + dur) - t, the
// exact float expression the serial walk's _emit performs (it is NOT
// equal to dur under IEEE rounding, and byte-identity means matching
// the walk, rounding artifacts included).
void op_price_scan(int64_t n,
                   const double* dur,
                   const double* flops,
                   const double* mxu,
                   const double* trans,
                   const double* hbm,
                   const double* vmem,
                   const double* spilled,  // may be null
                   double* acc,
                   double* t_before) {     // may be null
  double t = acc[0];
  double a_flops = acc[1];
  double a_mxu = acc[2];
  double a_trans = acc[3];
  double a_hbm = acc[4];
  double a_vmem = acc[5];
  double a_spill = acc[6];
  for (int64_t i = 0; i < n; ++i) {
    if (t_before) t_before[i] = t;
    t += dur[i];
    a_flops += flops[i];
    a_mxu += mxu[i];
    a_trans += trans[i];
    a_hbm += hbm[i];
    a_vmem += vmem[i];
    if (spilled) a_spill += spilled[i];
  }
  acc[0] = t;
  acc[1] = a_flops;
  acc[2] = a_mxu;
  acc[3] = a_trans;
  acc[4] = a_hbm;
  acc[5] = a_vmem;
  acc[6] = a_spill;
}

// Scenario-batched variant (tpusim/fastpath/batch.py): `lanes`
// degradation states price one run through lane-major scans.  Only the
// duration matrix is per-lane -- the counter columns are lane-INVARIANT
// (the degrade transform never touches byte counts and the spill split
// is a module-level fraction), so one pass over the shared columns
// serves every lane.  Each lane is the scalar kernel's exact serial
// chain, so lane s is byte-identical to an op_price_scan call seeded
// with that lane's accumulators.
//
// Versioned separately from op_price_scan so a stale prebuilt library
// degrades to the NumPy batch path instead of failing to load.
int op_price_batch_abi_version() { return 1; }

// dur: lanes*n lane-major; acc: lanes*7; t_before: lanes*n or null.
void op_price_scan_batch(int64_t lanes,
                         int64_t n,
                         const double* dur,
                         const double* flops,
                         const double* mxu,
                         const double* trans,
                         const double* hbm,
                         const double* vmem,
                         const double* spilled,  // may be null
                         double* acc,
                         double* t_before) {     // may be null
  for (int64_t s = 0; s < lanes; ++s) {
    op_price_scan(n, dur + s * n, flops, mxu, trans, hbm, vmem, spilled,
                  acc + s * 7,
                  t_before ? t_before + s * n : nullptr);
  }
}

}  // extern "C"
