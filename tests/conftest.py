"""Shared test fixtures.

The axon sitecustomize in this image registers the TPU backend at interpreter
startup, so JAX platform env vars cannot be changed in-process.  Tests that
need a multi-device CPU mesh therefore run their JAX piece in a subprocess
with a clean environment (``cpu_mesh_env``).  Pure-simulator tests (parser,
timing, ICI, driver) need no JAX at all — by design the timing core only
consumes the IR.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures"


@pytest.fixture(scope="session")
def repo_root() -> Path:
    return REPO_ROOT


@pytest.fixture(scope="session")
def fixtures_dir() -> Path:
    return FIXTURES


def cpu_mesh_env(n_devices: int = 8) -> dict[str, str]:
    """Environment for a subprocess that needs an ``n_devices`` CPU mesh."""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)  # drop axon site, keep tpusim
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices} "
        + env.get("TPUSIM_EXTRA_XLA_FLAGS", "")
    ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORM_NAME", None)
    return env


def run_in_cpu_mesh(code: str, n_devices: int = 8, timeout: int = 600) -> str:
    """Run a python snippet under a virtual CPU mesh; returns stdout."""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=cpu_mesh_env(n_devices),
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO_ROOT,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"subprocess failed (rc={proc.returncode}):\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr[-4000:]}"
        )
    return proc.stdout


@pytest.fixture(scope="session")
def cpu_mesh_runner():
    return run_in_cpu_mesh
