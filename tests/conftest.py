"""Shared test fixtures.

The axon sitecustomize in this image registers the TPU backend at interpreter
startup, so JAX platform env vars cannot be changed in-process.  Tests that
need a multi-device CPU mesh therefore run their JAX piece in a subprocess
with a clean environment (``cpu_mesh_env``).  Pure-simulator tests (parser,
timing, ICI, driver) need no JAX at all — by design the timing core only
consumes the IR.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures"


def pytest_sessionstart(session):
    """Build the native library before any test can cache a negative load.

    ``libtpusim_native.so`` is a build artifact (untracked); on a fresh
    checkout, tests that run before tests/test_native.py's build fixture
    would otherwise cache ``_LIB = None`` in tpusim.trace.native /
    tpusim.ici.detailed and the availability assertions fail spuriously.
    Best-effort: the pure-Python fallbacks keep everything else working."""
    try:
        subprocess.run(
            ["make", "-C", str(REPO_ROOT / "native")], capture_output=True
        )
    except OSError:
        pass


@pytest.fixture(scope="session")
def repo_root() -> Path:
    return REPO_ROOT


@pytest.fixture(scope="session")
def fixtures_dir() -> Path:
    return FIXTURES


from tpusim.envutil import cpu_mesh_env  # noqa: E402  (shared recipe)


def run_in_cpu_mesh(code: str, n_devices: int = 8, timeout: int = 600) -> str:
    """Run a python snippet under a virtual CPU mesh; returns stdout."""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=cpu_mesh_env(n_devices),
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO_ROOT,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"subprocess failed (rc={proc.returncode}):\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr[-4000:]}"
        )
    return proc.stdout


@pytest.fixture(scope="session")
def cpu_mesh_runner():
    return run_in_cpu_mesh


@pytest.fixture(autouse=True)
def _isolate_tuned_overlays(monkeypatch, tmp_path):
    """Pin TPUSIM_TUNED_DIR to an empty dir for every test: unit tests
    assert model numbers against the PRESETS; a committed
    ``configs/<arch>.tuned.flags`` (refreshed by any live bench run) must
    not shift them.  Tests of the overlay mechanism itself re-set the env
    var on top of this."""
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(tmp_path / "no_tuned"))


# -- live-backend availability ----------------------------------------------
#
# Under axon the TPU device is reached through a tunnel; when the tunnel is
# down, `import jax` blocks forever in-process.  Tests that exercise the
# live backend probe availability once per session (in a subprocess, with a
# timeout) and skip cleanly when it is unreachable.  The probe result is
# cached on disk for a few minutes so back-to-back pytest runs don't re-pay
# the timeout.

_PROBE_CACHE = Path("/tmp/tpusim_live_jax_probe")
_PROBE_TTL_S = 300
_live_jax_ok: bool | None = None


def live_jax_usable(timeout: int = 90) -> bool:
    global _live_jax_ok
    forced = os.environ.get("TPUSIM_LIVE_JAX")
    if forced is not None:
        return forced not in ("0", "false", "no")
    if _live_jax_ok is None:
        try:
            import time

            age = time.time() - _PROBE_CACHE.stat().st_mtime
            if age < _PROBE_TTL_S:
                _live_jax_ok = _PROBE_CACHE.read_text().strip() == "1"
                return _live_jax_ok
        except OSError:
            pass
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                timeout=timeout,
                cwd=REPO_ROOT,
            )
            _live_jax_ok = proc.returncode == 0
        except subprocess.TimeoutExpired:
            _live_jax_ok = False
        try:
            _PROBE_CACHE.write_text("1" if _live_jax_ok else "0")
        except OSError:
            pass
    return _live_jax_ok


@pytest.fixture(scope="session")
def live_jax():
    """Depend on this before any in-process ``import jax``."""
    if not live_jax_usable():
        pytest.skip("live JAX backend unreachable (axon TPU tunnel down)")


# -- jax.shard_map availability ----------------------------------------------
#
# The model zoo's sharded workloads (ring/ulysses attention, MoE ep,
# pipeline pp, sharded decode, the ici_allreduce microbench) build through
# the `jax.shard_map` entry point.  JAX has moved this surface across
# releases; on containers whose build does not expose it, every
# subprocess-mesh test that builds one of those workloads dies with
# AttributeError — an environment gap, not a model bug.  Probe once per
# session (in a subprocess, the same CPU-mesh environment the tests use)
# and skip with a clear reason, mirroring the xplane/ProfileData gates.

_SHARD_MAP_PROBE = Path("/tmp/tpusim_shard_map_probe")
_shard_map_ok: bool | None = None


def jax_shard_map_usable(timeout: int = 120) -> bool:
    global _shard_map_ok
    if _shard_map_ok is None:
        try:
            import time

            age = time.time() - _SHARD_MAP_PROBE.stat().st_mtime
            if age < _PROBE_TTL_S:
                _shard_map_ok = _SHARD_MAP_PROBE.read_text().strip() == "1"
                return _shard_map_ok
        except OSError:
            pass
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; raise SystemExit("
                 "0 if hasattr(jax, 'shard_map') else 3)"],
                env=cpu_mesh_env(2),
                capture_output=True,
                timeout=timeout,
                cwd=REPO_ROOT,
            )
            _shard_map_ok = proc.returncode == 0
        except (subprocess.TimeoutExpired, OSError):
            _shard_map_ok = False
        try:
            _SHARD_MAP_PROBE.write_text("1" if _shard_map_ok else "0")
        except OSError:
            pass
    return _shard_map_ok


def require_jax_shard_map() -> None:
    """Skip (never error) when this jax build lacks ``jax.shard_map``."""
    if not jax_shard_map_usable():
        pytest.skip(
            "jax.shard_map entry point absent in this jax build "
            "(jax-drift): the sharded model-zoo workloads cannot build"
        )
