"""tpusim.advise — spec validation, the strategy-transform layer,
determinism, cache sharing, and the serve tier's /v1/advise parity."""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tpusim.advise import (
    AdviseSpecError,
    build_cell_pod,
    build_profile,
    load_advise_spec,
    run_advise,
    scaled_module,
)
from tpusim.advise.runner import enumerate_cells
from tpusim.ir import CommandKind
from tpusim.trace.format import load_trace

FIXTURES = Path(__file__).parent / "fixtures" / "traces"
LLAMA = FIXTURES / "llama_tiny_tp2dp2"

BASE_SPEC = {
    "name": "t",
    "strategies": ["dp", "tp", "dp_tp", "sp", "pp"],
    "slices": [{"arch": "v5p", "chips": 8}],
    "tuned": False,
}


@pytest.fixture(scope="module")
def llama_pod():
    return load_trace(LLAMA)


@pytest.fixture(scope="module")
def llama_profile(llama_pod):
    return build_profile(llama_pod)


# -- spec validation --------------------------------------------------------

def test_spec_rejects_unknown_field():
    with pytest.raises(AdviseSpecError) as e:
        load_advise_spec({"warp_drive": True})
    assert e.value.code == "TL220"


def test_spec_rejects_unknown_strategy():
    with pytest.raises(AdviseSpecError) as e:
        load_advise_spec({"strategies": ["dp", "warp"]})
    assert e.value.code == "TL221"


def test_spec_rejects_slo_without_candidates():
    with pytest.raises(AdviseSpecError) as e:
        load_advise_spec({"slices": [], "slo": {"step_time_ms": 1.0}})
    assert e.value.code == "TL224"


def test_spec_defaults_slices_from_capture():
    spec = load_advise_spec({"strategies": ["dp"]})
    slices = spec.resolved_slices(4)
    assert [(s.arch, s.chips) for s in slices] == [("v5p", 4), ("v5p", 8)]


def test_advise_passes_flag_mesh_and_arch(tmp_path):
    from tpusim.analysis import analyze_advise_spec

    diags = analyze_advise_spec({
        "strategies": ["dp"],
        "slices": [{"arch": "v9z", "chips": 8}],
        "meshes": [{"dp": 3, "tp": 2}],
    }, default_chips=8)
    assert {"TL222", "TL223"} <= diags.codes()


def test_run_advise_refuses_bad_spec():
    from tpusim.analysis import ValidationError

    with pytest.raises(ValidationError):
        run_advise(
            dict(BASE_SPEC, meshes=[{"dp": 3, "tp": 2}]),
            trace_path=LLAMA,
        )


# -- capture profiling ------------------------------------------------------

def test_profile_classifies_llama_capture(llama_profile):
    p = llama_profile
    assert (p.chips0, p.dp0, p.tp0) == (4, 2, 2)
    # 14 collective sites total: 13 tp-role activation all-reduces +
    # the strided-group gradient all-reduce (dp role)
    assert len(p.sites) == 14
    assert len(p.tp_sites) == 13
    assert len(p.dp_sites) == 1
    assert not p.ep_sites
    # the gradient payload (params/tp0) recovers the full footprint
    assert p.param_bytes_total == p.dp_sites[0].payload_bytes * 2


# -- the strategy-transform layer -------------------------------------------

def test_scaled_module_halves_largest_dims(llama_pod, llama_profile):
    mod = llama_pod.modules[llama_profile.module_name]
    half = scaled_module(mod, 0.5, "half", llama_profile.capture_fp)
    # collectives are stripped to free ops
    assert not half.collectives()
    assert len(list(half.all_ops())) == len(list(mod.all_ops()))
    # a known activation shape: f32[4,256,128] -> largest dim halved
    op = half.entry.op("all-reduce.42")
    assert op.opcode == "bitcast"
    assert op.result.shape == (4, 128, 128)
    # distinct factors get distinct content hashes (cache identity)
    quarter = scaled_module(mod, 0.25, "q", llama_profile.capture_fp)
    assert half.meta["content_hash"] != quarter.meta["content_hash"]
    assert half.meta["platform"] == mod.meta.get("platform")


def _per_chip_collectives(pod, device=0):
    return [
        c for c in pod.devices[device].commands
        if c.kind == CommandKind.COLLECTIVE
    ]


def test_dp4tp2_synthesis_matches_multichip_r05(llama_profile):
    """The dp=4 x tp=2 cell must synthesize the 14-collective step
    MULTICHIP_r05 priced on the modeled v5p torus (13 tp activation
    all-reduces + 1 dp gradient all-reduce per chip)."""
    from tpusim.advise.transform import scaled_module as sm

    compute = sm(
        load_trace(LLAMA).modules[llama_profile.module_name],
        0.5, "c", llama_profile.capture_fp,
    )
    pod = build_cell_pod(
        llama_profile, compute, 8, {"dp": 4, "tp": 2},
    )
    colls = _per_chip_collectives(pod)
    assert len(colls) == 14
    kinds = [c.collective.kind for c in colls]
    assert kinds.count("all-reduce") == 14
    # group sizing: 13 tp collectives over groups of 2, 1 dp over 4
    sizes = sorted(c.collective.group_size for c in colls)
    assert sizes == [2] * 13 + [4]
    # dp groups are strided combs, tp groups contiguous blocks
    dp_cmd = colls[-1]
    assert dp_cmd.collective.replica_groups == ((0, 2, 4, 6), (1, 3, 5, 7))
    tp_cmd = colls[0]
    assert tp_cmd.collective.replica_groups == (
        (0, 1), (2, 3), (4, 5), (6, 7),
    )


def test_sp_ring_synthesis(llama_profile):
    from tpusim.advise.transform import scaled_module as sm

    compute = sm(
        load_trace(LLAMA).modules[llama_profile.module_name],
        0.5, "c", llama_profile.capture_fp,
    )
    pod = build_cell_pod(llama_profile, compute, 8, {"sp": 8})
    colls = _per_chip_collectives(pod)
    # 13 sites x (sp - 1) ring permutes + 1 full-pod gradient all-reduce
    assert len(colls) == 13 * 7 + 1
    assert sum(
        1 for c in colls if c.collective.kind == "collective-permute"
    ) == 91
    grad = [c for c in colls if c.collective.kind == "all-reduce"]
    assert len(grad) == 1 and grad[0].collective.group_size == 8
    # params replicate across sp: gradient payload is tp0-unsharded
    assert grad[0].nbytes == \
        llama_profile.dp_sites[0].payload_bytes * llama_profile.tp0


def test_dp_sp_composite_synthesis(llama_profile):
    """A pinned dp=2 x sp=4 mesh must build one sp subring PER dp
    replica (not one pod-wide ring), rendezvous each chip in its own
    subring, and sync gradients over the WHOLE pod (params replicate
    across both axes) at the tp0-unsharded payload."""
    from tpusim.advise.transform import scaled_module as sm

    compute = sm(
        load_trace(LLAMA).modules[llama_profile.module_name],
        0.5, "c", llama_profile.capture_fp,
    )
    pod = build_cell_pod(llama_profile, compute, 8, {"dp": 2, "sp": 4})
    colls = _per_chip_collectives(pod)
    perms = [
        c for c in colls if c.collective.kind == "collective-permute"
    ]
    assert len(perms) == 13 * 3  # (sp - 1) rotations per tp site
    # two 4-chip subrings, no cross-replica pair
    assert perms[0].collective.replica_groups == (
        (0, 1, 2, 3), (4, 5, 6, 7),
    )
    assert all(
        (a < 4) == (b < 4)
        for a, b in perms[0].collective.source_target_pairs
    )
    # the rotated block is the cell's per-chip activation:
    # capture payload x dp0 / (dp * sp)
    site = llama_profile.tp_sites[0]
    assert perms[0].nbytes == int(site.payload_bytes * 2 / 8)
    grads = [c for c in colls if c.collective.kind == "all-reduce"]
    assert len(grads) == 1
    assert grads[0].collective.replica_groups == (tuple(range(8)),)
    assert grads[0].nbytes == \
        llama_profile.dp_sites[0].payload_bytes * llama_profile.tp0


def test_unsupported_mesh_combos_are_skipped(llama_pod):
    from tpusim.advise.runner import _unsupported_combo

    doc = run_advise(
        {
            "name": "t", "strategies": ["dp"], "tuned": False,
            "slices": [{"arch": "v5p", "chips": 8}],
            "meshes": [{"tp": 2, "sp": 4}, {"sp": 2, "pp": 4}],
        },
        pod=llama_pod,
    ).doc
    reasons = [s["reason"] for s in doc["skipped"]]
    assert reasons == ["sp composes with a dp axis only"] * 2
    # the ep guard, directly (a dense capture skips ep cells earlier,
    # on the no-expert-sites reason)
    assert _unsupported_combo({"ep": 2, "pp": 4}) == \
        "ep composes with a dp axis only"
    assert _unsupported_combo({"dp": 2, "ep": 4}) is None
    assert _unsupported_combo({"dp": 2, "tp": 2, "pp": 2}) is None


def test_spec_rejects_absurd_slice():
    with pytest.raises(AdviseSpecError) as e:
        load_advise_spec({
            "slices": [{"arch": "v5p", "chips": 1 << 20}],
        })
    assert e.value.code == "TL220"


def test_pp_pipeline_streams(llama_profile):
    from tpusim.advise.transform import scaled_module as sm

    compute = sm(
        load_trace(LLAMA).modules[llama_profile.module_name],
        1 / 16, "c", llama_profile.capture_fp,
    )
    pod = build_cell_pod(
        llama_profile, compute, 8, {"pp": 8}, launches=8,
    )
    # every stage launches one microbatch 8 times
    for d in range(8):
        launches = [
            c for c in pod.devices[d].commands
            if c.kind == CommandKind.KERNEL_LAUNCH
        ]
        assert len(launches) == 8
    # edge stages permute once per microbatch, interior twice
    assert len(_per_chip_collectives(pod, 0)) == 8
    assert len(_per_chip_collectives(pod, 7)) == 8
    assert len(_per_chip_collectives(pod, 3)) == 16
    # the hand-off payload is the boundary activation per microbatch
    c0 = _per_chip_collectives(pod, 0)[0]
    assert c0.collective.kind == "collective-permute"
    assert c0.collective.source_target_pairs == ((0, 1),)


def test_pipeline_bubble_shows_in_step_time(llama_pod):
    """A pp cell's step must exceed a dp cell's on the same chip count
    (the fill/drain bubble the rendezvous reproduces), both pricing
    the same total work."""
    doc = run_advise(
        dict(BASE_SPEC, strategies=["dp", "pp"]),
        pod=llama_pod,
    ).doc
    by_strategy = {r["strategy"]: r for r in doc["cells"]}
    assert by_strategy["pp"]["step_ms"] > by_strategy["dp"]["step_ms"]


def test_ep_without_expert_capture_is_skipped(llama_pod):
    doc = run_advise(
        dict(BASE_SPEC, strategies=["dp", "ep"]),
        pod=llama_pod,
    ).doc
    assert len(doc["cells"]) == 1
    assert len(doc["skipped"]) == 1
    assert "expert" in doc["skipped"][0]["reason"]


# -- ranking / report contract ----------------------------------------------

def test_report_ranks_cells_with_contract_columns(llama_pod):
    spec = dict(
        BASE_SPEC,
        slices=[{"arch": "v5p", "chips": 8}, {"arch": "v5e", "chips": 8}],
        slo={"step_time_ms": 1.0},
    )
    res = run_advise(spec, pod=llama_pod)
    doc = res.doc
    assert len(doc["cells"]) >= 12
    ranks = [r["rank"] for r in doc["cells"]]
    assert ranks == sorted(ranks)
    feas = [r["feasible"] for r in doc["cells"]]
    # feasible cells rank above infeasible ones
    assert feas == sorted(feas, reverse=True)
    for r in doc["cells"]:
        for col in ("step_ms", "ici_bytes", "collectives_per_chip",
                    "hbm_resident_gib", "watts", "pod_watts",
                    "perf_per_watt", "slo_ok", "fits_hbm"):
            assert col in r, col
        assert r["step_ms"] > 0 and r["watts"] > 0
    rec = doc["recommendation"]
    assert rec is not None and rec["cell"] == doc["cells"][0]["cell"]
    assert res.stats.stats_dict()["advise_cells_priced"] == len(
        doc["cells"]
    )


def test_slo_flags_infeasible_cells(llama_pod):
    tight = run_advise(
        dict(BASE_SPEC, slo={"step_time_ms": 1e-6}), pod=llama_pod,
    ).doc
    assert all(r["slo_ok"] is False for r in tight["cells"])
    assert tight["recommendation"] is None


def test_residency_is_the_dataflow_liveness_peak(llama_pod):
    """The HBM-fit column is computed from the dataflow engine's
    liveness walk over the exact scaled module each cell prices — the
    same number the TL400 memory pass judges, so the ranked table and
    the linter can never disagree."""
    from tpusim.advise.transform import build_profile, scaled_module
    from tpusim.analysis.dataflow import analyze_module
    from tpusim.timing.config import load_config

    res = run_advise(BASE_SPEC, pod=llama_pod)
    profile = build_profile(llama_pod)
    base = llama_pod.modules[profile.module_name]
    assert res.doc["cells"]
    for r in res.doc["cells"]:
        factor = profile.chips0 / float(r["chips"] * r["launches"])
        mod = scaled_module(
            base, factor, f"pin_{factor!r}", profile.capture_fp,
        )
        want = analyze_module(mod).peak_live("hbm") / float(1 << 30)
        assert r["hbm_resident_gib"] == pytest.approx(want)
        cap = load_config(arch=r["arch"], tuned=False).arch.hbm_gib
        assert r["fits_hbm"] == (r["hbm_resident_gib"] <= cap)


def test_enumerate_cells_dedups_pinned(llama_profile):
    spec = load_advise_spec({
        "strategies": ["dp_tp"],
        "slices": [{"arch": "v5p", "chips": 8}],
        "meshes": [{"dp": 4, "tp": 2}],   # duplicates an enumerated cell
    })
    cells = enumerate_cells(spec, llama_profile.chips0)
    labels = [c.label for c in cells]
    assert len(labels) == len(set(labels)) == 2  # dp2xtp4 + dp4xtp2


# -- determinism & cache sharing --------------------------------------------

def test_fixed_spec_reports_are_byte_identical(llama_pod):
    a = run_advise(BASE_SPEC, pod=llama_pod).doc
    b = run_advise(BASE_SPEC, pod=llama_pod).doc
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_warm_rerun_prices_zero_engine_walks(llama_pod):
    from tpusim.perf.cache import ResultCache
    from tpusim.timing.engine import Engine

    cache = ResultCache()
    cold = run_advise(BASE_SPEC, pod=llama_pod, result_cache=cache)
    runs = {"n": 0}
    orig = Engine.run

    def counting(self, module):
        runs["n"] += 1
        return orig(self, module)

    Engine.run = counting
    try:
        warm = run_advise(BASE_SPEC, pod=llama_pod, result_cache=cache)
    finally:
        Engine.run = orig
    assert runs["n"] == 0
    assert json.dumps(cold.doc, sort_keys=True) == \
        json.dumps(warm.doc, sort_keys=True)


def test_cells_share_engine_walks_per_scale(llama_pod):
    """dp/tp/sp cells at the same chip count share ONE compute-module
    engine walk per arch — the collective-free clone's cache key has
    no topology component."""
    from tpusim.timing.engine import Engine

    runs = {"n": 0}
    orig = Engine.run

    def counting(self, module):
        runs["n"] += 1
        return orig(self, module)

    Engine.run = counting
    try:
        run_advise(
            dict(BASE_SPEC, strategies=["dp", "tp", "sp", "dp_tp"]),
            pod=llama_pod,
        )
    finally:
        Engine.run = orig
    assert runs["n"] == 1


# -- serve tier -------------------------------------------------------------

def test_served_advise_doc_matches_cli(llama_pod):
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    cli_doc = run_advise(BASE_SPEC, trace_path=LLAMA).doc
    with ServeDaemon(trace_root=FIXTURES) as d:
        c = ServeClient(d.url)
        job = c.advise(spec=BASE_SPEC, trace="llama_tiny_tp2dp2")
        st = c.wait_job(job, timeout_s=120.0)
        assert st.status == "done", st.error
        assert json.dumps(st.result, sort_keys=True) == \
            json.dumps(cli_doc, sort_keys=True)
        prom = c.metrics_text()
        assert "tpusim_serve_advise_cells_total" in prom


def test_served_advise_rejects_bad_spec():
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    with ServeDaemon(trace_root=FIXTURES) as d:
        c = ServeClient(d.url)
        job = c.advise(
            spec={"strategies": ["warp"]}, trace="llama_tiny_tp2dp2",
        )
        st = c.wait_job(job, timeout_s=30.0)
        assert st.status == "failed"
        assert "bad_advise_spec" in (st.error or "")


# -- CLI --------------------------------------------------------------------

def test_cli_advise_prints_table_and_writes_json(tmp_path, capsys):
    from tpusim.__main__ import main

    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(BASE_SPEC))
    out = tmp_path / "report.json"
    rc = main([
        "advise", str(spec_path), "--trace", str(LLAMA),
        "--json", str(out),
    ])
    assert rc == 0
    text = capsys.readouterr().out
    assert "recommendation:" in text and "step_ms" in text
    doc = json.loads(out.read_text())
    assert doc["cells"] and doc["recommendation"]


def test_cli_lint_advise_exit_codes(tmp_path):
    from tpusim.__main__ import main

    good = tmp_path / "good.json"
    good.write_text(json.dumps(BASE_SPEC))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"strategies": ["warp"]}))
    assert main(["lint", "--advise", str(good)]) == 0
    assert main(["lint", "--advise", str(bad)]) == 1
