"""Tests for the config system + arch presets."""

import pytest

from tpusim.timing import arch_preset
from tpusim.timing.arch import detect_arch
from tpusim.timing.config import (
    ArchConfig,
    SimConfig,
    load_config,
    overlay,
    parse_flag_file,
)


def test_presets_match_published_peaks():
    # derived bf16 peak = 2 * mxus * rows * cols * clock
    expect = {"v4": 275e12, "v5e": 219e12, "v5p": 459e12, "v6e": 918e12}
    for name, peak in expect.items():
        arch = arch_preset(name)
        assert arch.peak_bf16_flops == pytest.approx(peak, rel=0.02), name


def test_preset_unknown():
    with pytest.raises(KeyError):
        arch_preset("v99")


def test_detect_arch():
    assert detect_arch("TPU v5 lite").name == "v5e"
    assert detect_arch("TPU v5p").name == "v5p"
    assert detect_arch("TPU v4").name == "v4"
    assert detect_arch("weird accelerator").name == "v5e"  # fallback


def test_overlay_nested():
    cfg = SimConfig()
    out = overlay(cfg, {"arch": {"clock_ghz": 2.0, "ici": {"link_bandwidth": 1e9}}})
    assert out.arch.clock_ghz == 2.0
    assert out.arch.ici.link_bandwidth == 1e9
    # original untouched (frozen dataclasses)
    assert cfg.arch.clock_ghz != 2.0


def test_overlay_unknown_key():
    with pytest.raises(KeyError):
        overlay(SimConfig(), {"nonexistent_knob": 1})


def test_flag_file(tmp_path):
    p = tmp_path / "sim.config"
    p.write_text(
        "# comment\n"
        "-kernel_window 16\n"
        "-arch.mxu_count 4\n"
        "-arch.ici.link_bandwidth 4.5e10\n"
        "-overlap_collectives false\n"
    )
    updates = parse_flag_file(p)
    cfg = overlay(SimConfig(), updates)
    assert cfg.kernel_window == 16
    assert cfg.arch.mxu_count == 4
    assert cfg.arch.ici.link_bandwidth == 4.5e10
    assert cfg.overlap_collectives is False


def test_load_config_composition(tmp_path):
    p = tmp_path / "over.config"
    p.write_text("-arch.clock_ghz 1.0\n")
    cfg = load_config(arch="v5e", overlays=[p, {"kernel_window": 2}])
    assert cfg.arch.name == "v5e"
    assert cfg.arch.clock_ghz == 1.0
    assert cfg.kernel_window == 2


def test_derived_quantities():
    a = ArchConfig()
    assert a.hbm_bytes_per_cycle == pytest.approx(
        a.hbm_bandwidth * a.hbm_efficiency / a.clock_hz
    )
    assert a.vmem_bytes_per_cycle > a.hbm_bytes_per_cycle
    assert a.seconds_to_cycles(1.0) == a.clock_hz
    assert a.mxu_dtype_mult("bf16") == 1.0
    assert a.mxu_dtype_mult("s8") == 2.0


def test_tuned_overlay_applied_by_default(tmp_path, monkeypatch):
    """A committed configs/<arch>.tuned.flags must apply automatically —
    the tuner->tested-cfgs loop (VERDICT r3 #3: 'tune is never invoked in
    any artifact-producing path')."""
    from tpusim.timing.config import tuned_overlay_path

    (tmp_path / "v5e.tuned.flags").write_text(
        "# fit on silicon\n-arch.hbm_efficiency 0.91\n"
    )
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(tmp_path))
    assert tuned_overlay_path("v5e") == tmp_path / "v5e.tuned.flags"
    cfg = load_config(arch="v5e")
    assert cfg.arch.hbm_efficiency == 0.91
    # explicit overlays still win over the tuned values
    cfg2 = load_config(
        arch="v5e", overlays=[{"arch": {"hbm_efficiency": 0.5}}]
    )
    assert cfg2.arch.hbm_efficiency == 0.5
    # and the tuned layer can be disabled outright
    cfg3 = load_config(arch="v5e", tuned=False)
    assert cfg3.arch.hbm_efficiency != 0.91


def test_tuned_overlay_absent_is_silent(monkeypatch, tmp_path):
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(tmp_path))  # empty dir
    from tpusim.timing.config import tuned_overlay_path

    assert tuned_overlay_path("v5e") is None
    assert load_config(arch="v5e").arch.name == "v5e"
