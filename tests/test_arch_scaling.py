"""Cross-generation sanity: the presets must order real workloads the
way the hardware does.  The reference ships one tested config per card
and lets CI compare across them (QV100 / RTX2060 / RTX3070 matrix,
``Jenkinsfile:26-52``); the TPU analogue is that a strictly-better chip
(v5p: 2x the MXUs, 3.4x the HBM bandwidth, higher clock than v5e) must
never simulate slower on the same program."""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tpusim.timing.config import load_config
from tpusim.timing.engine import Engine
from tpusim.trace.format import load_trace, select_module

REPO = Path(__file__).resolve().parent.parent
SILICON = REPO / "reports" / "silicon"

pytestmark = pytest.mark.skipif(
    not (SILICON / "manifest.json").exists(),
    reason="no committed silicon fixtures",
)


def _times(arch: str) -> dict[str, float]:
    manifest = json.loads((SILICON / "manifest.json").read_text())
    eng = Engine(load_config(arch=arch, tuned=False))
    out = {}
    for e in manifest["workloads"]:
        td = load_trace(SILICON / e["trace"])
        mod = select_module(td, e.get("module"))
        out[e["name"]] = eng.run(mod).seconds
    return out


def test_v5p_never_slower_than_v5e():
    v5e = _times("v5e")
    v5p = _times("v5p")
    for name, t_e in v5e.items():
        assert v5p[name] <= t_e * 1.001, (
            f"{name}: v5p {v5p[name] * 1e6:.1f}us vs v5e "
            f"{t_e * 1e6:.1f}us — a strictly better chip must not lose"
        )


def test_bandwidth_bound_scales_with_hbm():
    """elementwise_stream is HBM-bound: the v5p/v5e time ratio should
    track the inverse bandwidth ratio (3.4x), not the clock ratio."""
    v5e = _times("v5e")["elementwise_stream"]
    v5p = _times("v5p")["elementwise_stream"]
    speedup = v5e / v5p
    bw_ratio = 2765e9 / 819e9
    assert speedup == pytest.approx(bw_ratio, rel=0.25)


def test_compute_bound_scales_with_peak_flops():
    """matmul_chain is MXU-bound: speedup should track peak bf16 ratio
    (v5p 459 TF/s vs v5e 219 TF/s at preset clocks)."""
    v5e = _times("v5e")["matmul_chain"]
    v5p = _times("v5p")["matmul_chain"]
    speedup = v5e / v5p
    flops_ratio = (2 * 8 * 128 * 128 * 1.75) / (2 * 4 * 128 * 128 * 1.67)
    assert speedup == pytest.approx(flops_ratio, rel=0.3)
