"""Tests for the auxiliary subsystems (SURVEY.md §5 parity): timeline
export, failure detection, checkpoint/resume, interactive debugger."""

import io
import json
from pathlib import Path

import pytest

from tpusim.ir import CommandKind, PodTrace, TraceCommand
from tpusim.sim.debugger import Debugger
from tpusim.sim.driver import SimDriver
from tpusim.sim.traceviz import timeline_to_chrome_trace, write_chrome_trace
from tpusim.timing.config import SimConfig, overlay
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


@pytest.fixture(scope="module")
def tiny_mlp():
    return parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())


def _pod(n_launches=1):
    pod = PodTrace()
    pod.modules["m"] = parse_hlo_module(
        (FIXTURES / "tiny_mlp.hlo").read_text()
    )
    for _ in range(n_launches):
        pod.device(0).commands.append(
            TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="m")
        )
    return pod


# -- timeline export --------------------------------------------------------

def test_chrome_trace_export(tiny_mlp, tmp_path):
    cfg = SimConfig()
    res = Engine(cfg, record_timeline=True).run(tiny_mlp)
    doc = timeline_to_chrome_trace(res, cfg.arch)
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(events) == len(res.timeline) > 0
    names = {e["args"]["op"] for e in events}
    assert "dot.1" in names and "ar-start" in names
    tids = {e["tid"] for e in events}
    assert len(tids) >= 2  # MXU + ICI rows at minimum

    out = tmp_path / "t.json"
    write_chrome_trace(res, cfg.arch, out)
    loaded = json.loads(out.read_text())
    assert loaded["traceEvents"]


# -- failure detection ------------------------------------------------------

def test_orphan_and_unjoined_async_detected():
    text = """
HloModule bad, is_scheduled=true

%r (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (x: f32[1024]) -> f32[1024] {
  %x = f32[1024]{0} parameter(0)
  %st = f32[1024]{0} all-reduce-start(%x), channel_id=1, replica_groups={{0,1}}, to_apply=%r
  ROOT %dn = f32[1024]{0} all-reduce-done(%ghost)
}
"""
    res = Engine(SimConfig()).run(parse_hlo_module(text))
    assert res.orphan_async_joins == 1   # -done joined a nonexistent start
    assert res.unjoined_async == 1       # -start never joined
    assert res.stats_dict()["orphan_async_joins"] == 1


def test_collective_rendezvous_mismatch_detected():
    from tpusim.ir import CollectiveInfo

    pod = PodTrace(meta={"num_devices": 2})
    info = CollectiveInfo("all-reduce", replica_groups=((0, 1),))
    pod.device(0).commands.append(TraceCommand(
        kind=CommandKind.COLLECTIVE, device_id=0, nbytes=1024,
        collective=info))
    pod.device(0).commands.append(TraceCommand(
        kind=CommandKind.COLLECTIVE, device_id=0, nbytes=1024,
        collective=info))
    pod.device(1).commands.append(TraceCommand(
        kind=CommandKind.COLLECTIVE, device_id=1, nbytes=1024,
        collective=info))
    report = SimDriver(SimConfig()).run(pod)
    assert report.stats.get("collective_rendezvous_mismatch") == 1


def test_no_orphan_join_when_overlap_disabled():
    """A well-formed start/done pair must not count as orphaned when
    overlap_collectives=False runs the start synchronously."""
    text = """
HloModule good, is_scheduled=true

%r (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (x: f32[1024]) -> f32[1024] {
  %x = f32[1024]{0} parameter(0)
  %st = f32[1024]{0} all-reduce-start(%x), channel_id=1, replica_groups={{0,1}}, to_apply=%r
  ROOT %dn = f32[1024]{0} all-reduce-done(%st)
}
"""
    mod = parse_hlo_module(text)
    for over in (True, False):
        res = Engine(overlay(SimConfig(), {"overlap_collectives": over})).run(mod)
        assert res.orphan_async_joins == 0, f"overlap={over}"
        assert res.unjoined_async == 0, f"overlap={over}"


def test_disjoint_replica_groups_not_flagged():
    """Group (0,1) doing 2 collectives while disjoint group (2,3) does 1
    is legal; a device with no collectives at all is also legal."""
    from tpusim.ir import CollectiveInfo

    pod = PodTrace(meta={"num_devices": 5})
    g01 = CollectiveInfo("all-reduce", replica_groups=((0, 1),))
    g23 = CollectiveInfo("all-reduce", replica_groups=((2, 3),))
    for d in (0, 1):
        for _ in range(2):
            pod.device(d).commands.append(TraceCommand(
                kind=CommandKind.COLLECTIVE, device_id=d, nbytes=64,
                collective=g01))
    for d in (2, 3):
        pod.device(d).commands.append(TraceCommand(
            kind=CommandKind.COLLECTIVE, device_id=d, nbytes=64,
            collective=g23))
    pod.device(4)  # issues nothing
    report = SimDriver(SimConfig()).run(pod)
    assert report.stats.get("collective_rendezvous_mismatch") is None


# -- checkpoint / resume ----------------------------------------------------

def test_checkpoint_resume_partition():
    full = SimDriver(SimConfig()).run(_pod(4))
    first = SimDriver(
        overlay(SimConfig(), {"checkpoint_kernel": 2})
    ).run(_pod(4))
    rest = SimDriver(
        overlay(SimConfig(), {"resume_kernel": 2})
    ).run(_pod(4))
    assert len(full.kernels) == 4
    assert len(first.kernels) == 2
    assert len(rest.kernels) == 2
    assert first.stats.get("checkpoint_stop_kernel") == 2
    # the two halves partition the work exactly
    assert first.cycles + rest.cycles == pytest.approx(full.cycles)
    assert (
        first.totals.flops + rest.totals.flops
        == pytest.approx(full.totals.flops)
    )


def test_checkpoint_resume_partition_with_memcpys():
    """Memcpys in the stream must be billed to exactly one half: the H2D
    before kernel 1 to the checkpoint run, the D2H after the last kernel
    to the resume run."""
    def pod():
        p = _pod(0)
        dev = p.device(0)
        dev.commands.append(TraceCommand(
            kind=CommandKind.MEMCPY_H2D, nbytes=1 << 20))
        for _ in range(4):
            dev.commands.append(TraceCommand(
                kind=CommandKind.KERNEL_LAUNCH, module="m"))
        dev.commands.append(TraceCommand(
            kind=CommandKind.MEMCPY_D2H, nbytes=1 << 20))
        return p

    full = SimDriver(SimConfig()).run(pod())
    first = SimDriver(
        overlay(SimConfig(), {"checkpoint_kernel": 2})
    ).run(pod())
    rest = SimDriver(
        overlay(SimConfig(), {"resume_kernel": 2})
    ).run(pod())
    assert len(first.kernels) == 2 and len(rest.kernels) == 2
    assert full.memcpy_cycles > 0
    # each memcpy simulated exactly once across the two halves
    assert first.memcpy_cycles + rest.memcpy_cycles == pytest.approx(
        full.memcpy_cycles
    )
    assert first.totals.flops + rest.totals.flops == pytest.approx(
        full.totals.flops
    )


# -- sub-kernel (op-index) checkpoint/resume --------------------------------
#
# tiny_mlp entry schedule: [x, w1, w2, dot.1, relu.1, ar-start, ar-done,
# dot.2] — op index 5 is a clean boundary (nothing in flight), index 6
# splits the async all-reduce across the halves.

def test_op_checkpoint_resume_partitions_exactly(tiny_mlp):
    full = Engine(SimConfig()).run(tiny_mlp)
    first = Engine(
        overlay(SimConfig(), {"checkpoint_op": 5})
    ).run(tiny_mlp)
    rest = Engine(
        overlay(SimConfig(), {"resume_op": 5})
    ).run(tiny_mlp)
    # nothing in flight at op 5: the halves partition the run exactly
    assert first.cycles + rest.cycles == pytest.approx(full.cycles)
    assert first.flops + rest.flops == pytest.approx(full.flops)
    assert first.op_count + rest.op_count == full.op_count
    assert first.collective_count == 0 and rest.collective_count == 1
    assert first.unjoined_async == 0 and rest.orphan_async_joins == 0


def test_op_checkpoint_across_async_boundary(tiny_mlp):
    """Splitting between ar-start and ar-done: the checkpoint drains the
    in-flight collective (barrier), the resume half joins it silently —
    no orphan/unjoined flags, and the barrier can only add time."""
    full = Engine(SimConfig()).run(tiny_mlp)
    first = Engine(
        overlay(SimConfig(), {"checkpoint_op": 6})
    ).run(tiny_mlp)
    rest = Engine(
        overlay(SimConfig(), {"resume_op": 6})
    ).run(tiny_mlp)
    assert first.unjoined_async == 0
    assert rest.orphan_async_joins == 0
    assert first.collective_count == 1 and rest.collective_count == 0
    assert first.cycles + rest.cycles >= full.cycles * 0.999
    assert first.flops + rest.flops == pytest.approx(full.flops)


def test_op_checkpoint_inside_driver_replay():
    """The op knobs compose with the kernel-level driver replay."""
    from tpusim.sim.driver import SimDriver as _SD

    pod = _pod(2)
    full = _SD(SimConfig()).run(pod)
    half = _SD(overlay(SimConfig(), {"checkpoint_op": 5})).run(pod)
    assert 0 < half.cycles < full.cycles


# -- debugger ---------------------------------------------------------------

def _run_debugger(tiny_mlp, commands: str) -> str:
    out = io.StringIO()
    dbg = Debugger(tiny_mlp, SimConfig())
    dbg.repl(io.StringIO(commands), out)
    return out.getvalue()


def test_debugger_step_and_continue(tiny_mlp):
    text = _run_debugger(tiny_mlp, "s 3\nstats\nc\nq\n")
    assert "tpusim debugger" in text
    assert "dot.1" in text
    assert "done:" in text and "cycles total" in text


def test_debugger_breakpoint(tiny_mlp):
    text = _run_debugger(tiny_mlp, "b ar-start\nc\np\nq\n")
    assert "breakpoint: next op is ar-start" in text
    # 'p' on the breakpoint op shows the collective detail
    assert "all-reduce-start" in text
    # the ops after the breakpoint were NOT yet executed
    assert "dot.2" not in text.split("breakpoint:")[1].split("next op")[0]


def test_debugger_list_and_help(tiny_mlp):
    text = _run_debugger(tiny_mlp, "l 3\nbogus\nq\n")
    assert "[   0]" in text
    assert "commands:" in text


# -- deadlock / runaway detection -------------------------------------------

def test_deadlock_detect_flags_runaway():
    pod = _pod(1)
    report = SimDriver(
        overlay(SimConfig(), {"deadlock_cycles": 1})  # absurdly low budget
    ).run(pod)
    assert report.stats.get("deadlock_suspected") == 1
    assert "m:" in report.stats.get("deadlock_suspects")
    # and a normal budget does not flag
    clean = SimDriver(SimConfig()).run(pod)
    assert clean.stats.get("deadlock_suspected") is None
    # opting out disables the check entirely
    off = SimDriver(
        overlay(SimConfig(), {"deadlock_cycles": 1, "deadlock_detect": False})
    ).run(pod)
    assert off.stats.get("deadlock_suspected") is None


BIG_DOT_HLO = """\
HloModule big, is_scheduled=true

ENTRY %main (a: bf16[2048,2048], b: bf16[2048,2048]) -> bf16[2048,2048] {
  %a = bf16[2048,2048]{1,0} parameter(0)
  %b = bf16[2048,2048]{1,0} parameter(1)
  ROOT %dot.0 = bf16[2048,2048]{1,0} dot(%a, %b), \
lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


def test_deadlock_suspects_weighted_by_launch_count():
    """A cheap module launched many times can dominate the pod clock; the
    suspect ranking must weight per-run cycles by launch count, not point
    at a single-run-expensive module."""
    pod = _pod(100)  # "m" (tiny_mlp) launched 100x
    pod.modules["big"] = parse_hlo_module(BIG_DOT_HLO)
    pod.device(0).commands.append(
        TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="big")
    )
    report = SimDriver(
        overlay(SimConfig(), {"deadlock_cycles": 1})
    ).run(pod)
    suspects = report.stats.get("deadlock_suspects")
    # sanity: "big" is the costlier single run, but "m" dominates in total
    per_run = {k.module: k.result.cycles for k in report.kernels}
    assert per_run["big"] > per_run["m"]
    assert suspects.startswith("m:x100:"), suspects
