"""End-to-end tests for the remaining BASELINE.json staged configs.

Config #2 — the ``examples/all-reduce`` equivalent: a hand-built 2-chip
command stream (comm init, H2D, all-reduce, D2H) replayed on a 2-chip ICI
link model.  The reference's fixture is ``examples/all-reduce/main.cu``
traced through the NCCL interposer; ours carries real byte counts.

Config #4 — ResNet-50 data-parallel on a modeled v5p-8: captured on an
8-device CPU mesh, gradient all-reduces simulated on the ICI torus.
"""

import json

import pytest

from tests.conftest import run_in_cpu_mesh
from tpusim.ir import CollectiveInfo, CommandKind, PodTrace, TraceCommand
from tpusim.sim.driver import SimDriver
from tpusim.timing.config import load_config


# -- config #2: two-chip all-reduce example ---------------------------------

def _allreduce_example_pod(nbytes: int) -> PodTrace:
    """The examples/all-reduce command stream, two devices."""
    pod = PodTrace(meta={"num_devices": 2})
    info = CollectiveInfo("all-reduce", replica_groups=((0, 1),))
    for dev in (0, 1):
        cmds = pod.device(dev).commands
        cmds.append(TraceCommand(kind=CommandKind.COMM_INIT, device_id=dev))
        cmds.append(TraceCommand(
            kind=CommandKind.MEMCPY_H2D, device_id=dev, nbytes=nbytes))
        cmds.append(TraceCommand(kind=CommandKind.GROUP_START, device_id=dev))
        cmds.append(TraceCommand(
            kind=CommandKind.COLLECTIVE, device_id=dev, nbytes=nbytes,
            collective=info))
        cmds.append(TraceCommand(kind=CommandKind.GROUP_END, device_id=dev))
        cmds.append(TraceCommand(
            kind=CommandKind.MEMCPY_D2H, device_id=dev, nbytes=nbytes))
        cmds.append(TraceCommand(kind=CommandKind.COMM_DESTROY, device_id=dev))
    return pod


def test_two_chip_allreduce_example():
    cfg = load_config(arch="v5p")
    nbytes = 64 * 1024 * 1024
    report = SimDriver(cfg).run(_allreduce_example_pod(nbytes))
    assert report.num_devices == 2
    assert report.totals.collective_count == 2  # one per device
    # the two devices' all-reduces rendezvous: identical finish times
    cycles = list(report.device_cycles.values())
    assert cycles[0] == pytest.approx(cycles[1], rel=1e-6)
    # time must scale with payload (unlike the fork's constant 100 cycles)
    small = SimDriver(cfg).run(_allreduce_example_pod(1024 * 1024))
    assert report.collective_cmd_cycles > 5 * small.collective_cmd_cycles


def test_allreduce_example_vs_analytic():
    """Driver timing must equal the collective model's analytic value."""
    from tpusim.ici.collectives import CollectiveModel
    from tpusim.ici.topology import torus_for

    cfg = load_config(arch="v5p")
    nbytes = 32 * 1024 * 1024
    report = SimDriver(cfg).run(_allreduce_example_pod(nbytes))
    expected_s = CollectiveModel(
        torus_for(2, "v5p"), cfg.arch.ici
    ).allreduce_seconds(float(nbytes), 2)
    per_dev = report.collective_cmd_cycles / 2 / cfg.arch.clock_hz
    assert per_dev == pytest.approx(expected_s, rel=1e-6)


# -- config #4: ResNet-50 data-parallel on v5p-8 ----------------------------

RESNET_DP8_SCRIPT = r"""
import json
from tpusim.models import get_workload
from tpusim.tracer.capture import capture
from tpusim.timing.engine import Engine
from tpusim.timing.config import load_config

wl = get_workload("resnet50_dp8")
fn, args = wl.build(batch=16, image=64)
cap = capture(fn, *args, name="resnet50_dp8", include_memcpy=False)
mod = cap.module
res = Engine(load_config(arch="v5p")).run(mod)
colls = mod.collectives()
print("RESULT " + json.dumps({
    "num_devices": mod.num_devices,
    "n_collectives": len(colls),
    "kinds": sorted({c.base for c in colls}),
    "step_seconds": res.seconds,
    "flops": res.flops,
    "ici_bytes": res.ici_bytes,
}))
"""


@pytest.mark.slow
def test_resnet50_dp8_on_v5p8():
    out = run_in_cpu_mesh(RESNET_DP8_SCRIPT, n_devices=8, timeout=580)
    line = [l for l in out.splitlines() if l.startswith("RESULT ")][0]
    r = json.loads(line[len("RESULT "):])
    assert r["num_devices"] == 8
    assert r["n_collectives"] >= 1
    assert "all-reduce" in r["kinds"], "DP training must all-reduce grads"
    # gradient sync must move roughly the model size (~25M params) per step
    assert r["ici_bytes"] > 25e6
    # conv fwd+bwd flops: ~3x fwd; fwd at 64px is ~ (64/224)^2 * 4.1 GFLOP
    # * batch 2 per chip => order 1e9-1e11; the point is it's not ~0
    assert r["flops"] > 1e9
    assert 1e-4 < r["step_seconds"] < 5.0
