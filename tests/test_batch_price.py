"""Scenario-batched pricing (tpusim.fastpath.batch, PR 19).

The batching contract is "faster, not different": one (ops x S)
lane-axis pass over S degradation launch classes must produce
EngineResults byte-identical to the per-state serial walk, populate the
shared result cache under the SAME per-state keys that walk mints, and
cancel cooperatively at batch grain.  Pinned here: corpus byte-identity
across every available backend, single-lane degeneration, BatchStats
engagement accounting, warm-cache interchangeability between modes
(batched leg warms, per-state leg gets pure hits — and vice versa),
cross-mode campaign resume (a cancelled batched leg resumed per-state
matches the uninterrupted report byte-for-byte), and cancel at batch
grain.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tpusim.fastpath import (
    native_batch_available,
    numpy_available,
    price_module_batch,
    resolve_engine_scales,
    warm_states,
)
from tpusim.fastpath.batch import BatchStats
from tpusim.guard.cancel import CancelToken, OperationCancelled
from tpusim.perf.cache import ResultCache, result_to_doc
from tpusim.timing.config import load_config
from tpusim.timing.engine import Engine
from tpusim.trace.format import load_trace

FIXTURES = Path(__file__).parent / "fixtures" / "traces"
TRACE = FIXTURES / "llama_tiny_tp2dp2"

# the campaign-style launch classes: healthy + a derate ladder
LANES = [(1.0, 1.0)] + [
    (round(0.4 + 0.05 * i, 10), round(0.9 - 0.03 * i, 10))
    for i in range(7)
]


def _jax_available() -> bool:
    try:
        from tpusim.fastpath.jax_backend import jax_price_available

        return jax_price_available()
    except Exception:  # noqa: BLE001 - probe only
        return False


BACKENDS = [
    pytest.param(
        "vectorized",
        marks=pytest.mark.skipif(
            not numpy_available(), reason="numpy not importable"),
    ),
    pytest.param(
        "native",
        marks=pytest.mark.skipif(
            not native_batch_available(),
            reason="native batch kernel not built"),
    ),
    pytest.param(
        "jax",
        marks=pytest.mark.skipif(
            not _jax_available(), reason="jax not importable"),
    ),
]


def _docs(results) -> list[str]:
    return [
        json.dumps(result_to_doc(r), sort_keys=False) for r in results
    ]


def _engines(cfg, lanes=LANES):
    return [
        Engine(cfg, clock_scale=cs, hbm_scale=hs) for cs, hs in lanes
    ]


# -- byte-identity ----------------------------------------------------------


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("arch", ["v5e", "v5p"])
def test_batched_matches_serial_walk_byte_identical(backend, arch):
    """Every lane of every fixture module prices byte-identically to
    the per-state serial reference walk, on every available backend."""
    cfg = load_config(arch=arch)
    for tdir in sorted(FIXTURES.iterdir()):
        if not tdir.is_dir():
            continue
        pod = load_trace(tdir)
        for _name, mod in sorted(pod.modules.items()):
            engines = _engines(cfg)
            serial = _docs([e._run_serial(mod) for e in engines])
            batched = _docs(
                price_module_batch(mod, engines, backend=backend)
            )
            assert batched == serial, f"{tdir.name}/{_name}"


def test_single_lane_degenerates_to_per_state_fastpath():
    """S=1 batching equals the per-state fastpath (and the serial
    walk) for the same launch class — no special-casing."""
    from tpusim.fastpath.price import price_module
    from tpusim.fastpath import resolve_backend

    cfg = load_config(arch="v5p")
    pod = load_trace(TRACE)
    mod = next(iter(pod.modules.values()))
    eng = Engine(cfg, clock_scale=0.77, hbm_scale=0.91)
    [batched] = price_module_batch(mod, [eng])
    ref = price_module(
        Engine(cfg, clock_scale=0.77, hbm_scale=0.91), mod,
        resolve_backend(None),
    )
    assert _docs([batched]) == _docs([ref])


def test_serial_backend_degenerates_to_per_lane_walk():
    cfg = load_config(arch="v5e")
    pod = load_trace(TRACE)
    mod = next(iter(pod.modules.values()))
    engines = _engines(cfg, LANES[:3])
    batched = _docs(price_module_batch(mod, engines, backend="serial"))
    serial = _docs([e._run_serial(mod) for e in _engines(cfg, LANES[:3])])
    assert batched == serial


def test_resolve_engine_scales_shared_helper():
    """The hoisted scale-resolution helper matches what the engines
    were constructed with (price.py and batch.py both consume it)."""
    cfg = load_config(arch="v5p")
    eng = Engine(cfg, clock_scale=0.5, hbm_scale=0.25)
    clock, hbm = resolve_engine_scales(eng)
    assert clock == 0.5 and hbm == 0.25


# -- warm_states: cache keys + accounting + cancel --------------------------


def _campaign_states(topo, n=4):
    """A healthy state (None) + hand-built degradation schedules."""
    from tpusim.faults import load_fault_schedule

    docs = [
        {"faults": [{"kind": "chip_straggler", "chip": 0,
                     "clock_scale": 0.5 + 0.1 * i}]}
        for i in range(n - 1)
    ]
    return [None] + [
        load_fault_schedule(d).bind(topo) for d in docs
    ]


def test_warm_states_fills_per_state_cache_keys():
    """warm_states mints the SAME keys the per-state walk asks for:
    after a warm pass, pricing each state through the cache is a pure
    hit, and the cached results are byte-identical to the walk's."""
    from tpusim.ici.topology import torus_for

    pod = load_trace(TRACE)
    cfg = load_config(arch="v5p")
    topo = torus_for(8, cfg.arch.name)
    states = _campaign_states(topo)
    cache = ResultCache()
    stats = warm_states(pod, cfg, topo, states, cache)
    assert stats.states > 0
    assert stats.groups >= 1
    assert stats.skipped == 0

    # the per-state walk now finds every (module, scales, topo) key
    misses_before = cache.misses
    for state in states:
        view = state.view_at(0.0) if state is not None else None
        topo_k = topo.with_faults(view) if view is not None else topo
        for dev_id in sorted(pod.devices):
            scales = (view.chip_scales(dev_id)
                      if view is not None else (1.0, 1.0))
            for cmd in pod.devices[dev_id].commands:
                mod = pod.modules.get(cmd.module)
                if mod is None:
                    continue
                key = cache.key_for(mod, cfg, scales, topo_k)
                if key is None:
                    continue
                hit = cache.get(key)
                assert hit is not None, "warm pass missed a state key"
                ref = Engine(
                    cfg, topology=topo_k, clock_scale=scales[0],
                    hbm_scale=scales[1],
                )._run_serial(mod)
                assert _docs([hit]) == _docs([ref])
    assert cache.misses == misses_before

    # re-warming is pure accounting: everything is already cached
    stats2 = warm_states(pod, cfg, topo, states, cache)
    assert stats2.states == 0
    assert stats2.lanes_cached > 0


def test_batch_stats_merge_and_keys():
    a, b = BatchStats(), BatchStats()
    a.states, a.groups = 3, 1
    b.states, b.groups, b.lanes_cached, b.skipped = 2, 1, 4, 5
    a.merge(b)
    assert a.states == 5 and a.groups == 2
    assert a.lanes_cached == 4 and a.skipped == 5
    d = a.stats_dict()
    assert d["fastpath_batched_states"] == 5.0
    assert d["fastpath_batch_groups"] == 2.0
    assert d["fastpath_batch_lanes_cached"] == 4.0
    assert d["fastpath_batch_skipped"] == 5.0
    assert all(isinstance(v, float) for v in d.values())


def test_warm_states_cancels_at_batch_grain():
    """A tripped token stops the warm pass with OperationCancelled
    between batch grains — never a partial lane write."""
    from tpusim.ici.topology import torus_for

    pod = load_trace(TRACE)
    cfg = load_config(arch="v5p")
    topo = torus_for(8, cfg.arch.name)
    states = _campaign_states(topo)
    cache = ResultCache()
    token = CancelToken()
    token.cancel("test trip")
    with pytest.raises(OperationCancelled):
        warm_states(pod, cfg, topo, states, cache, cancel=token)
    assert cache.hits == 0 and cache.misses == 0


# -- campaign integration: cross-mode cache + resume ------------------------


def _spec(**over) -> dict:
    doc = {
        "name": "batch-x", "seed": 11, "scenarios": 6,
        "arch": "v5p", "chips": 8, "tuned": False,
        "faults": {
            "count": {"dist": "uniform", "min": 0, "max": 2},
            "kinds": {"link_down": 1.0, "link_degraded": 1.0,
                      "chip_straggler": 0.5, "hbm_throttle": 0.5},
            "scale": {"min": 0.4, "max": 0.9},
        },
    }
    doc.update(over)
    return doc


def test_campaign_modes_share_cache_without_repricing():
    """A batched campaign leaves the shared result cache holding the
    exact per-state keys a per-state campaign asks for: the second run
    re-prices NOTHING and both reports are byte-identical."""
    from tpusim.campaign import run_campaign

    cache = ResultCache()
    batched = run_campaign(
        _spec(), trace_path=TRACE, result_cache=cache,
    )
    assert batched.batch_stats is not None
    assert batched.batch_stats.states > 0
    misses_before = cache.misses
    per_state = run_campaign(
        _spec(), trace_path=TRACE, result_cache=cache,
        scenario_batch=False,
    )
    assert per_state.batch_stats is None
    assert cache.misses == misses_before, (
        "per-state walk re-priced states the batch pass should have "
        "cached under identical keys"
    )
    assert (json.dumps(batched.doc, sort_keys=True)
            == json.dumps(per_state.doc, sort_keys=True))


def test_campaign_resume_across_modes(tmp_path):
    """Leg 1 prices batched and is cancelled mid-campaign; leg 2
    resumes with batching DISABLED and must complete to a report
    byte-identical to an uninterrupted per-state run (and to an
    uninterrupted batched run)."""
    from tpusim.campaign import run_campaign

    class Trip(CancelToken):
        """Trips after N grain checks (cooperative, like --max-wall-s
        but deterministic)."""

        def __init__(self, after: int):
            super().__init__()
            self.n = 0
            self.after = after

        def check(self) -> None:
            self.n += 1
            if self.n == self.after:
                self.cancel("test trip")
            super().check()

    out = tmp_path / "camp"
    with pytest.raises(OperationCancelled):
        run_campaign(
            _spec(), trace_path=TRACE, out_dir=out, cancel=Trip(12),
        )
    resumed = run_campaign(
        _spec(), trace_path=TRACE, out_dir=out, resume=True,
        scenario_batch=False,
    )
    reference = run_campaign(_spec(), trace_path=TRACE,
                             scenario_batch=False)
    assert (json.dumps(resumed.doc, sort_keys=True)
            == json.dumps(reference.doc, sort_keys=True))
    batched_ref = run_campaign(_spec(), trace_path=TRACE)
    assert (json.dumps(batched_ref.doc, sort_keys=True)
            == json.dumps(reference.doc, sort_keys=True))
