"""Tests for the two driver artifacts (round-2 hardening; VERDICT.md #1):

- ``bench.py`` must print exactly one JSON line on stdout in EVERY terminal
  state — live suite, fixture fallback, or unreachable-backend error.
- ``__graft_entry__.dryrun_multichip`` must succeed from the default
  (axon-poisoned) environment by re-execing into a clean CPU-mesh
  subprocess.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tests.conftest import require_jax_shard_map

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures"


def _run(cmd, env_extra=None, timeout=300):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO_ROOT,
    )


def test_bench_emits_json_when_backend_unreachable(tmp_path):
    """With zero live attempts and no fixture, bench must still print one
    parseable JSON line (the round-1 failure mode was rc=1 with none)."""
    proc = _run(
        [sys.executable, "bench.py"],
        env_extra={
            "TPUSIM_BENCH_ATTEMPTS": "0",
            # decouple from whatever reports/silicon/ the repo ships
            "TPUSIM_BENCH_FIXTURES": str(tmp_path / "empty"),
        },
    )
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"want exactly one stdout line, got: {lines!r}"
    out = json.loads(lines[0])
    assert out["metric"] == "sim_cycle_error_pct"
    assert "error" in out or out["value"] is not None
    assert proc.returncode == 1  # unreachable + no fixture is a failure


def test_bench_fixture_fallback_produces_numeric_value(tmp_path, capsys):
    """A committed silicon fixture must yield a numeric headline value via
    the pure-Python engine (no jax import)."""
    import bench

    fx = tmp_path / "silicon"
    shutil.copytree(FIXTURES / "traces" / "matmul_512", fx / "matmul_512")
    manifest = {
        "arch": "v5e",
        "device_kind": "TPU v5 lite",
        "captured": "test",
        "workloads": [
            {
                "name": "matmul_512",
                "trace": "matmul_512",
                "n_steps": 1,
                "real_seconds": 10e-6,
            }
        ],
    }
    (fx / "manifest.json").write_text(json.dumps(manifest))

    rc = bench.fixture_main(fixture_dir=fx)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "sim_cycle_error_pct"
    assert isinstance(out["value"], (int, float))
    assert out["source"] == "silicon_fixture"
    assert out["workloads"] == 1


def test_bench_last_json_line_parser():
    import bench

    stdout = "noise\n{broken\n" + json.dumps({"a": 1}) + "\ntrailing"
    assert bench._last_json_line(stdout) == json.dumps({"a": 1})
    assert bench._last_json_line("no json here") is None
    assert bench._last_json_line("") is None


@pytest.mark.slow
def test_dryrun_multichip_from_poisoned_env():
    """Running __graft_entry__ from the *inherited* environment (axon site
    active) must still complete: the parent re-execs into a clean CPU mesh."""
    require_jax_shard_map()
    proc = _run([sys.executable, "__graft_entry__.py", "4"], timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "llama tiny train step" in proc.stdout
    assert "ring attention" in proc.stdout


@pytest.mark.slow
def test_bench_child_runs_on_cpu_mesh(cpu_mesh_runner):
    """The child suite must run end-to-end on a CPU backend (numbers are
    meaningless vs the TPU model, but the mechanics — build, capture,
    simulate, time, emit JSON — must hold)."""
    code = (
        "import subprocess, sys, json\n"
        "import bench\n"
        "bench.SUITE = [('matmul_chain', {'m': 256, 'k': 256, 'depth': 2}, 2)]\n"
        "rc = bench.child_main()\n"
        "assert rc == 0\n"
        "print('CHILD_OK')\n"
    )
    out = cpu_mesh_runner(code, n_devices=1)
    assert "CHILD_OK" in out


@pytest.mark.slow
def test_bench_fixture_loop_closes(tmp_path, cpu_mesh_runner):
    """A live child run must save replayable silicon fixtures: child
    (forced fixture save) -> manifest + trace -> fixture_main produces a
    numeric headline value with no backend at all."""
    import os as _os
    import subprocess as _sp

    fx = tmp_path / "silicon"
    code = (
        "import json, bench\n"
        "bench.SUITE = [('matmul_chain', {'m': 256, 'k': 256, 'depth': 2}, 2)]\n"
        "rc = bench.child_main()\n"
        "assert rc == 0\n"
        f"m = json.loads(open({str(fx / 'manifest.json')!r}).read())\n"
        "assert m['workloads'][0]['name'] == 'matmul_chain'\n"
        "assert m['workloads'][0]['real_seconds'] > 0\n"
        "print('FIXTURES_SAVED')\n"
    )
    env = dict(_os.environ)
    env.update({
        "PYTHONPATH": str(REPO_ROOT),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TPUSIM_BENCH_FIXTURES": str(fx),
        "TPUSIM_BENCH_SAVE_FIXTURES": "force",
        "TPUSIM_BENCH_REPORT": "",  # skip the report/profile pass
    })
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = _sp.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=600, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "FIXTURES_SAVED" in proc.stdout

    # offline replay: no jax, just the engine vs the committed times
    import bench

    rc = bench.fixture_main(fixture_dir=fx)
    assert rc == 0


def test_committed_fixtures_meet_the_north_star(capsys, monkeypatch):
    """The round-4 calibration contract (VERDICT r3 #1): replaying the
    COMMITTED silicon fixtures through the engine must read <=15% mean
    |cycle error|.  If a model change or a fixture refresh pushes this
    back over the bar, this test turns red and forces recalibration —
    the reference re-validates its correlation every CI run
    (Jenkinsfile:83-97)."""
    import bench

    # replay EXACTLY as `python bench.py` does: with the committed tuner
    # overlay applied (the conftest isolation would otherwise make this
    # test disagree with the committed artifact after a live run lands
    # configs/<arch>.tuned.flags)
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(REPO_ROOT / "configs"))

    fixture_dir = REPO_ROOT / "reports" / "silicon"
    if not (fixture_dir / "manifest.json").exists():
        pytest.skip("committed silicon fixtures not present")
    rc = bench.fixture_main(fixture_dir=fixture_dir)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["workloads"] >= 8        # the full suite replayed
    assert out["value"] <= 15.0, (
        f"fixture-mode mean |error| {out['value']}% exceeds the 15% "
        f"north-star; detail: {out['detail']}"
    )


# -- refine_and_validate: the unattended live-bench tail, offline ----------

def _seed_overlay(tmp_path, monkeypatch):
    """Point the tuned-overlay machinery at a temp configs dir holding a
    microbench-style seed; returns (bench module, tuned_info, manifest
    workload entries).

    Uses the COMMITTED silicon fixtures as the replay truth — the same
    data the live tail would see."""
    import bench

    manifest = json.loads(
        (REPO_ROOT / "reports" / "silicon" / "manifest.json").read_text()
    )
    cfg_dir = tmp_path / "configs"
    cfg_dir.mkdir()
    seed = cfg_dir / "v5e.tuned.flags"
    seed.write_text(
        "# seed fit\n"
        "-arch.hbm_efficiency 0.803\n"
        "-arch.host_bandwidth 3.9e+07\n"
    )
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(cfg_dir))
    # overlay path handling in bench is REPO_ROOT-relative
    tuned_info = {"overlay": os.path.relpath(seed, REPO_ROOT), "fit": {}}
    return bench, tuned_info, manifest["workloads"]


@pytest.mark.skipif(
    not (REPO_ROOT / "reports" / "silicon" / "manifest.json").exists(),
    reason="no committed silicon fixtures",
)
def test_refine_and_validate_accepts_and_merges(tmp_path, monkeypatch):
    """Happy path: the refined overlay validates, keeps the seed's
    non-knob fits, records the refinement, and returns tuned replay rows
    for the headline."""
    bench, tuned_info, entries = _seed_overlay(tmp_path, monkeypatch)
    rows = bench.refine_and_validate(
        tuned_info, entries, "TPU v5 lite",
        fixture_dir=REPO_ROOT / "reports" / "silicon",
    )
    assert tuned_info.get("refined"), "refinement must run and be recorded"
    assert not tuned_info.get("rejected")
    overlay_text = (REPO_ROOT / tuned_info["overlay"]).read_text()
    # seed-only fit preserved alongside refined knobs
    assert "-arch.host_bandwidth 3.9e+07" in overlay_text
    assert "-arch.hbm_efficiency" in overlay_text
    # validated refinement switches the headline to tuned replay rows
    assert rows is not None and len(rows) > 0
    final = tuned_info["refined"]["replay_err_pct"]["final"]
    assert final <= tuned_info["refined"]["replay_err_pct"]["seed"]


@pytest.mark.skipif(
    not (REPO_ROOT / "reports" / "silicon" / "manifest.json").exists(),
    reason="no committed silicon fixtures",
)
def test_refine_and_validate_reverts_without_validation(
    tmp_path, monkeypatch,
):
    """When refinement succeeds but the self-validation replay returns
    no rows (both sides empty), the refined overlay must be reverted to
    the seed — an unvalidated fit must not become the committed config."""
    bench, tuned_info, entries = _seed_overlay(tmp_path, monkeypatch)
    seed_text = (REPO_ROOT / tuned_info["overlay"]).read_text()
    # the refiner replays internally; only the VALIDATION uses
    # bench.replay_fixture_errors — starve it so validation is skipped
    monkeypatch.setattr(bench, "replay_fixture_errors", lambda *a, **k: [])
    rows = bench.refine_and_validate(
        tuned_info, entries, "TPU v5 lite",
        fixture_dir=REPO_ROOT / "reports" / "silicon",
    )
    assert rows is None
    assert tuned_info.get("refined", {}).get("reverted")
    assert (REPO_ROOT / tuned_info["overlay"]).read_text() == seed_text


def test_refine_and_validate_refuses_empty_fixture_set(
    tmp_path, monkeypatch,
):
    """Entries whose traces don't exist: the refiner must refuse to
    label preset values as a fit — no overlay rewrite, no 'refined'
    record, no headline replacement."""
    bench, tuned_info, entries = _seed_overlay(tmp_path, monkeypatch)
    seed_text = (REPO_ROOT / tuned_info["overlay"]).read_text()
    bogus = [
        {"name": "nope", "trace": "does_not_exist", "n_steps": 1,
         "real_seconds": 1e-3}
    ]
    rows = bench.refine_and_validate(
        tuned_info, bogus, "TPU v5 lite",
        fixture_dir=REPO_ROOT / "reports" / "silicon",
    )
    assert rows is None
    assert "refined" not in tuned_info
    assert (REPO_ROOT / tuned_info["overlay"]).read_text() == seed_text


@pytest.mark.skipif(
    not (REPO_ROOT / "reports" / "silicon" / "manifest.json").exists(),
    reason="no committed silicon fixtures",
)
def test_refine_excludes_held_out_entries(tmp_path, monkeypatch):
    """Held-out full-model fixtures (VERDICT r4 #2) must never steer the
    refit: an entry flagged held_out with an absurd real_seconds would
    wreck the fit if trained on — the fit must come out identical to one
    without it."""
    bench, tuned_info, entries = _seed_overlay(tmp_path, monkeypatch)
    poisoned = list(entries) + [{
        "name": "matmul_chain",  # rows exist for it in the artifact
        "trace": "matmul_chain", "n_steps": 16,
        "real_seconds": 1.0,  # absurd: 1 s/step vs the real 390us
        "held_out": True,
    }]
    seed_text = (REPO_ROOT / tuned_info["overlay"]).read_text()
    try:
        bench.refine_and_validate(
            tuned_info, poisoned, "TPU v5 lite",
            fixture_dir=REPO_ROOT / "reports" / "silicon",
        )
    finally:
        # restore the seed: the run rewrites the overlay in place, and
        # the comparison needs both fits to start from the same point
        (REPO_ROOT / tuned_info["overlay"]).write_text(seed_text)
    assert tuned_info.get("refined"), "poisoned-run refine did not run"
    clean_info = {"overlay": tuned_info["overlay"], "fit": {}}
    bench.refine_and_validate(
        clean_info, entries, "TPU v5 lite",
        fixture_dir=REPO_ROOT / "reports" / "silicon",
    )
    assert (
        tuned_info["refined"]["changed"] == clean_info["refined"]["changed"]
    ), "held-out entry leaked into the training objective"
