"""Tests for the silicon-calibrated cost-model mechanisms added in
round 4's second calibration pass.  Each mechanism exists because a
committed device-timeline fixture contradicted the previous model
(``reports/correl_ops.json``); the test pins the mechanism, the tuned
overlay pins the numbers.

Reference slot: the per-unit latency tables the reference validates per
card (``trace.config``, ``trace_driven.cc:385-480``)."""

from __future__ import annotations

import pytest

from tpusim.timing.config import ArchConfig, SimConfig, overlay
from tpusim.timing.cost import CostModel
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module


def _module(body: str, params: str, ret: str) -> str:
    return (
        "HloModule m, is_scheduled=true\n\n"
        f"ENTRY %main ({params}) -> {ret} {{\n{body}\n}}\n"
    )


def _run_entry_op(text: str, op_name: str = "c", cfg: SimConfig | None = None):
    mod = parse_hlo_module(text)
    cfg = cfg or SimConfig()
    cm = CostModel(cfg.arch)
    entry = mod.entry
    return cm.op_cost(entry.op(op_name), entry, mod)


# -- free custom-call markers ----------------------------------------------

def test_marker_custom_calls_are_free():
    """ConcatBitcast/AllocateBuffer/AssumeGatherIndicesInBound measured
    ~0ns on v5e silicon; charging launch overhead + a memory roofline
    made matmul_chain carry 82us of phantom time per step."""
    text = _module(
        "  %p0 = bf16[2048,2048]{1,0:T(8,128)(2,1)} parameter(0)\n"
        '  ROOT %cc = bf16[2048,2048]{1,0:T(8,128)(2,1)} custom-call(%p0), '
        'custom_call_target="ConcatBitcast"',
        "p0: bf16[2048,2048]", "bf16[2048,2048]",
    )
    cost = _run_entry_op(text, "cc")
    assert cost.cycles == 0
    assert cost.hbm_bytes == 0

    # a custom-call with an unknown target still pays the roofline
    unknown = text.replace("ConcatBitcast", "MyRealKernel")
    assert _run_entry_op(unknown, "cc").cycles > 0


# -- copy pricing -----------------------------------------------------------

_COPY_PARAMS = "p0: bf16[1024,1024]"
_COPY_RET = "bf16[1024,1024]"


def _copy_text(src_layout: str, dst_layout: str) -> str:
    return _module(
        f"  %p0 = bf16[1024,1024]{src_layout} parameter(0)\n"
        f"  ROOT %c = bf16[1024,1024]{dst_layout} copy(%p0)",
        _COPY_PARAMS, _COPY_RET,
    )


def test_relayout_copy_slower_than_stream_copy():
    """A copy that changes minor-to-major order is a physical transpose:
    the conv2d fixture measured 0.42x the plain-copy stream rate.  (The
    64-wide minor dim makes this a sub-lane shuffle — the slow class.)"""
    plain = _run_entry_op(_sublane_copy_text(
        "{1,0:T(8,128)(2,1)}", "{1,0:T(8,128)(2,1)S(1)}"))
    relayout = _run_entry_op(_sublane_copy_text(
        "{1,0:T(8,128)(2,1)}", "{0,1:T(8,128)(2,1)S(1)}"))
    assert relayout.cycles > 1.5 * plain.cycles
    # traffic accounting is unchanged — only the achieved rate drops
    assert relayout.hbm_bytes == plain.hbm_bytes


def _sublane_copy_text(src_layout: str, dst_layout: str) -> str:
    return _module(
        f"  %p0 = bf16[1024,64]{src_layout} parameter(0)\n"
        f"  ROOT %c = bf16[1024,64]{dst_layout} copy(%p0)",
        "p0: bf16[1024,64]", "bf16[1024,64]",
    )


def test_lane_preserving_relayout_beats_sublane_shuffle():
    """A relayout whose minor dims stay dense 128-lane multiples on both
    sides reorders whole tiles: decode's 33.5MB KV-cache relayout copy
    achieved 452GB/s (0.66x pin) where conv2d's 64-lane transposing copy
    ran at 0.40x (``reports/correl_ops.json`` decode %copy.8)."""
    cfg = SimConfig()
    # [1024,1024] {1,0}->{0,1}: minor dims 1024/1024, both 128-multiples
    lane = _run_entry_op(_copy_text(
        "{1,0:T(8,128)(2,1)}", "{0,1:T(8,128)(2,1)S(1)}"), "c", cfg)
    shuffle = _run_entry_op(_sublane_copy_text(
        "{1,0:T(8,128)(2,1)}", "{0,1:T(8,128)(2,1)S(1)}"), "c", cfg)
    lane_cpb = lane.mem_cycles / lane.hbm_bytes
    shuffle_cpb = shuffle.mem_cycles / shuffle.hbm_bytes
    a = cfg.arch
    assert shuffle_cpb > lane_cpb * 1.2
    assert lane_cpb == pytest.approx(
        1.0 / (a.hbm_bytes_per_cycle * a.relayout_lane_efficiency)
    )


def test_vmem_to_vmem_copy_runs_at_port_rate():
    """Same-layout vmem->vmem copies measured 2.4TB/s against the 8.2TB/s
    banked operand-streaming rate (conv2d %copy.11)."""
    cfg = SimConfig()
    vv = _run_entry_op(_copy_text(
        "{1,0:T(8,128)(2,1)S(1)}", "{1,0:T(8,128)(2,1)S(1)}"), "c", cfg)
    ideal_vmem_cycles = (
        2.0 * 1024 * 1024 * 2 / cfg.arch.vmem_bytes_per_cycle
    )
    assert vv.mem_cycles > 1.5 * ideal_vmem_cycles


# -- reduce model -----------------------------------------------------------

def _reduce_text(dtype: str, dims: str, in_shape: str, out_shape: str,
                 layout: str) -> str:
    return _module(
        f"  %p0 = {dtype}{in_shape}{layout} parameter(0)\n"
        f"  %init = {dtype}[] constant(0)\n"
        f"  ROOT %r = {dtype}{out_shape} reduce(%p0, %init), "
        f"dimensions={{{dims}}}, to_apply=%add",
        f"p0: {dtype}{in_shape}", f"{dtype}{out_shape}",
    ).replace(
        "HloModule m, is_scheduled=true\n",
        "HloModule m, is_scheduled=true\n\n"
        "%add (a: f32[], b: f32[]) -> f32[] {\n"
        "  %a = f32[] parameter(0)\n"
        "  %b = f32[] parameter(1)\n"
        "  ROOT %s = f32[] add(%a, %b)\n"
        "}\n",
    )


def test_reduce_cost_scales_with_dtype_width():
    """The VPU accumulates packed words: f32 reduce is ~2x bf16 per
    element (9.2x vs 4.6x elementwise rate on v5e silicon)."""
    f32 = _run_entry_op(_reduce_text(
        "f32", "0", "[4096,1024]", "[1024]", "{1,0:T(8,128)}"), "r")
    bf16 = _run_entry_op(_reduce_text(
        "bf16", "0", "[4096,1024]", "[1024]", "{1,0:T(8,128)(2,1)}"), "r")
    assert f32.compute_cycles == pytest.approx(
        2.0 * bf16.compute_cycles, rel=0.01)


def test_minor_dim_reduce_pays_lane_crossing():
    """Reducing the minor (lane) dimension pays a per-output shuffle tail
    (decode_step fixture: GEMV-style [.,128]->[.] reduces)."""
    major = _run_entry_op(_reduce_text(
        "bf16", "0", "[128,65536]", "[65536]", "{1,0:T(8,128)(2,1)}"), "r")
    minor = _run_entry_op(_reduce_text(
        "bf16", "1", "[65536,128]", "[65536]", "{1,0:T(8,128)(2,1)}"), "r")
    # same element count; the minor-dim variant adds out_elems * tail
    arch = SimConfig().arch
    expected_tail = 65536 * arch.vpu_lane_cross_cycles
    assert minor.compute_cycles - major.compute_cycles == pytest.approx(
        expected_tail, rel=0.01)


# -- movement fusions -------------------------------------------------------

_MOVE_FUSION = """\
HloModule m, is_scheduled=true

%moved (param_0: bf16[2,1024,1024], param_1: s32[]) -> bf16[1,1024,1024] {
  %param_0 = bf16[2,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} parameter(0)
  %param_1 = s32[]{:T(128)} parameter(1)
  %c0 = s32[]{:T(128)} constant(0)
  ROOT %ds = bf16[1,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} dynamic-slice(%param_0, %param_1, %c0, %c0), dynamic_slice_sizes={1,1024,1024}
}

%mixed (param_0: bf16[2,1024,1024], param_1: s32[]) -> bf16[1,1024,1024] {
  %param_0 = bf16[2,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} parameter(0)
  %param_1 = s32[]{:T(128)} parameter(1)
  %c0 = s32[]{:T(128)} constant(0)
  %ds = bf16[1,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} dynamic-slice(%param_0, %param_1, %c0, %c0), dynamic_slice_sizes={1,1024,1024}
  ROOT %t = bf16[1,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} tanh(%ds)
}

ENTRY %main (p0: bf16[2,1024,1024], i: s32[]) -> bf16[1,1024,1024] {
  %p0 = bf16[2,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} parameter(0)
  %i = s32[]{:T(128)} parameter(1)
  %f0 = bf16[1,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} fusion(%p0, %i), kind=kLoop, calls=%moved
  ROOT %f1 = bf16[1,1024,1024]{2,1,0:T(8,128)(2,1)S(1)} fusion(%p0, %i), kind=kLoop, calls=%mixed
}
"""


def test_movement_fusion_streams_at_slice_rate():
    """A fusion containing only data movement (the KV-cache read pattern)
    streams at DMA slice rate, not banked operand bandwidth; one real
    compute op inside disables the derate."""
    mod = parse_hlo_module(_MOVE_FUSION)
    cfg = SimConfig()
    cm = CostModel(cfg.arch)
    entry = mod.entry
    move = cm.op_cost(entry.op("f0"), entry, mod)
    mixed = cm.op_cost(entry.op("f1"), entry, mod)
    assert move.mem_cycles == pytest.approx(
        mixed.mem_cycles / cfg.arch.vmem_slice_efficiency, rel=0.01)


# -- DMA issue latency ------------------------------------------------------

_SMALL_ASYNC_COPY = """\
HloModule m, is_scheduled=true

ENTRY %main (p0: bf16[4096]) -> bf16[4096] {
  %p0 = bf16[4096]{0:T(1024)(128)(2,1)} parameter(0)
  %cs = (bf16[4096]{0:T(1024)(128)(2,1)S(1)}, bf16[4096]{0:T(1024)(128)(2,1)}, u32[]{:S(2)}) copy-start(%p0)
  ROOT %cd = bf16[4096]{0:T(1024)(128)(2,1)S(1)} copy-done(%cs)
}
"""


def test_async_dma_start_pays_issue_latency():
    """An 8KB copy-start measured 1.57us on silicon — pure DMA issue
    latency, three orders of magnitude above its bandwidth cost.  The
    exposure appears when the program immediately joins."""
    mod = parse_hlo_module(_SMALL_ASYNC_COPY)
    cfg = SimConfig()
    r = Engine(cfg).run(mod)
    lat_cycles = cfg.arch.seconds_to_cycles(cfg.arch.dma_issue_latency)
    assert r.cycles >= lat_cycles
    no_lat = overlay(cfg, {"arch": {"dma_issue_latency": 0.0}})
    assert Engine(no_lat).run(mod).cycles < 0.25 * r.cycles


def test_dma_issue_latency_overlaps_across_transfers():
    """Latencies pipeline (many DMA engines): N back-to-back small copies
    joined at the end cost ~1 latency, not N."""
    n = 8
    starts = "\n".join(
        f"  %cs.{i} = (bf16[4096]{{0:T(1024)(128)(2,1)S(1)}}, "
        f"bf16[4096]{{0:T(1024)(128)(2,1)}}, u32[]{{:S(2)}}) "
        f"copy-start(%p0)" for i in range(n)
    )
    dones = "\n".join(
        f"  %cd.{i} = bf16[4096]{{0:T(1024)(128)(2,1)S(1)}} "
        f"copy-done(%cs.{i})" for i in range(n)
    )
    text = (
        "HloModule m, is_scheduled=true\n\n"
        "ENTRY %main (p0: bf16[4096]) -> bf16[4096] {\n"
        "  %p0 = bf16[4096]{0:T(1024)(128)(2,1)} parameter(0)\n"
        f"{starts}\n{dones}\n"
        "  ROOT %out = bf16[4096]{0:T(1024)(128)(2,1)S(1)} copy(%p0)\n"
        "}\n"
    )
    mod = parse_hlo_module(text)
    cfg = SimConfig()
    r = Engine(cfg).run(mod)
    lat = cfg.arch.seconds_to_cycles(cfg.arch.dma_issue_latency)
    assert r.cycles < 2.5 * lat  # not n * lat


# -- MXU split choice + sustained efficiency --------------------------------

def test_mxu_splits_rows_when_quantization_hurts():
    """5 weight passes on 4 MXUs with a huge m: splitting the streamed
    rows beats sending whole passes (which would round 5/4 up to 2)."""
    arch = ArchConfig(name="v5e", mxu_count=4)
    cm = CostModel(arch)
    m = 50176
    cycles = cm.mxu_cycles(1, m, 64, 576, "bf16")
    passes = 5  # ceil(576/128) * ceil(64/128) = 5 * 1
    old_quantized = 2 * m  # ceil(5/4) serial passes of m rows each
    assert cycles < 0.75 * old_quantized
    assert cycles >= passes * (m / 4) / arch.mxu_efficiency


def test_narrow_minor_dim_strands_vpu_lanes():
    """An elementwise op whose minor dim is 8 uses 8 of 128 lanes — the
    decode fixture's [8,1024,8] softmax stages run ~16x below bulk rate."""
    cm = CostModel(SimConfig().arch)
    from tpusim.ir import TensorSpec

    bulk = cm._vpu_util(TensorSpec("bf16", (8, 1024, 128), (2, 1, 0)))
    narrow = cm._vpu_util(TensorSpec("bf16", (8, 1024, 8), (0, 2, 1)))
    assert bulk == 1.0
    assert narrow == pytest.approx((8 / 128) * 1.0)
    # rank-1 vectors span lanes fully
    assert cm._vpu_util(TensorSpec("f32", (4096,), (0,))) == 1.0


def test_mxu_efficiency_derates_sustained_rate():
    a = ArchConfig()
    derated = ArchConfig(mxu_efficiency=0.87)
    big = (1, 4096, 4096, 4096, "bf16")
    assert CostModel(derated).mxu_cycles(*big) == pytest.approx(
        CostModel(a).mxu_cycles(*big) / 0.87)


# -- small-kernel floor ------------------------------------------------------

def test_small_kernel_floor_on_subtile_ops():
    """Sub-tile standalone kernels pay a fixed dispatch floor: v5e
    silicon ran [1,1] slices at 229-567ns, a scalar reduce-fusion at
    329ns, and a one-row DUS at 594ns where the roofline predicts ~5ns
    (``reports/correl_ops.json`` embedding/reduction rows; XLA's own
    cost model floors the same kernels at ~1830 estimated_cycles)."""
    cfg = SimConfig()
    floor = cfg.arch.small_kernel_floor_cycles
    assert floor > 0
    tiny_slice = _module(
        "  %p0 = bf16[131072,1024]{1,0:T(8,128)(2,1)} parameter(0)\n"
        "  ROOT %c = bf16[1,1]{1,0:T(2,128)(2,1)} slice(%p0), "
        "slice={[0:1], [0:1]}",
        "p0: bf16[131072,1024]", "bf16[1,1]",
    )
    assert _run_entry_op(tiny_slice, "c", cfg).cycles >= floor

    # a >32KB-region slice is roofline-priced, not floored
    big_slice = _module(
        "  %p0 = bf16[131072,1024]{1,0:T(8,128)(2,1)} parameter(0)\n"
        "  ROOT %c = bf16[1024,1024]{1,0:T(8,128)(2,1)} slice(%p0), "
        "slice={[0:1024], [0:1024]}",
        "p0: bf16[131072,1024]", "bf16[1024,1024]",
    )
    big = _run_entry_op(big_slice, "c", cfg)
    roofline = 2.0 * 1024 * 1024 * 2 / cfg.arch.hbm_bytes_per_cycle
    assert big.cycles >= roofline  # priced by bytes, no 5ns absurdity

    # an elementwise op with a large result is never floored
    add = _module(
        "  %p0 = bf16[1024,1024]{1,0:T(8,128)(2,1)S(1)} parameter(0)\n"
        "  ROOT %c = bf16[1024,1024]{1,0:T(8,128)(2,1)S(1)} "
        "add(%p0, %p0)",
        "p0: bf16[1024,1024]", "bf16[1024,1024]",
    )
    small_cfg = SimConfig()
    assert _run_entry_op(add, "c", small_cfg).cycles < floor


# -- DUS-fusion in-place aliasing -------------------------------------------

_DUS_FUSION_TEXT = """HloModule m, is_scheduled=true

%fused_dus (param_0: bf16[4096,1024], param_1: bf16[1,1024], param_2: s32[]) -> bf16[4096,1024] {
  %param_0 = bf16[4096,1024]{1,0:T(8,128)(2,1)S(1)} parameter(0)
  %param_1 = bf16[1,1024]{1,0:T(8,128)(2,1)S(1)} parameter(1)
  %param_2 = s32[]{:T(128)} parameter(2)
  %zero = s32[]{:T(128)} constant(0)
  ROOT %dus = bf16[4096,1024]{1,0:T(8,128)(2,1)S(1)} dynamic-update-slice(%param_0, %param_1, %param_2, %zero)
}

ENTRY %main (p0: bf16[4096,1024], p1: bf16[1,1024], p2: s32[]) -> bf16[4096,1024] {
  %p0 = bf16[4096,1024]{1,0:T(8,128)(2,1)S(1)} parameter(0)
  %p1 = bf16[1,1024]{1,0:T(8,128)(2,1)S(1)} parameter(1)
  %p2 = s32[]{:T(128)} parameter(2)
  ROOT %c = bf16[4096,1024]{1,0:T(8,128)(2,1)S(1)} fusion(%p0, %p1, %p2), kind=kLoop, calls=%fused_dus
}
"""


def test_dus_fusion_charges_update_region_not_carry():
    """XLA aliases a DUS fusion's destination operand onto its output:
    the kernel reads and writes the update region, not the 8MB carry.
    The lstm fixture's per-timestep stash (128KB update into an 8.4MB
    buffer) read +219% before this (``reports/correl_ops.json``
    lstm %bitcast_dynamic-update-slice_fusion.2)."""
    cost = _run_entry_op(_DUS_FUSION_TEXT, "c")
    region = 1024 * 2  # [1,1024] bf16 update
    full = 4096 * 1024 * 2
    total = cost.vmem_bytes + cost.hbm_bytes
    assert total <= 8 * region  # region-scaled, nowhere near the carry
    assert total < 0.01 * full
