"""tpusim.campaign — Monte-Carlo compound-fault campaigns.

The ISSUE-6 acceptance surface: byte-reproducible fixed-seed reports,
crash-safe resume (SIGKILL mid-campaign → --resume re-prices zero
completed scenarios), partitioned topologies landing as outcome rows,
the SLO capacity table joining watts, campaign-spec validation codes,
journal torn-write tolerance, JobTable disk persistence, and the
``POST /v1/campaign`` daemon-restart resume path.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import pytest

from tpusim.campaign import (
    CampaignSpecError,
    Journal,
    JournalError,
    load_campaign_spec,
    percentile,
    run_campaign,
    sample_schedule_doc,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "fixtures" / "traces"
TRACE = FIXTURES / "llama_tiny_tp2dp2"


def base_spec(**over) -> dict:
    doc = {
        "name": "t", "seed": 11, "scenarios": 4,
        "arch": "v5p", "chips": 8, "tuned": False,
        "faults": {
            "count": {"dist": "uniform", "min": 0, "max": 2},
            "kinds": {"link_down": 1.0, "link_degraded": 1.0,
                      "chip_straggler": 0.5, "hbm_throttle": 0.5},
            "scale": {"min": 0.4, "max": 0.9},
        },
    }
    doc.update(over)
    return doc


# -- spec validation --------------------------------------------------------

def test_spec_parses_and_defaults():
    spec = load_campaign_spec(base_spec())
    assert spec.seed == 11
    assert spec.scenarios == 4
    assert spec.faults.count.dist == "uniform"
    assert dict(spec.faults.kinds)["link_down"] == 1.0
    assert spec.slices(default_chips=4)[0].label == "v5p-8"
    # no chips pinned -> the pod's size
    spec2 = load_campaign_spec({"seed": 1})
    assert spec2.slices(default_chips=4)[0].label == "v5p-4"


@pytest.mark.parametrize("mutate, code", [
    ({"faults": {"kinds": ["gamma_burst"]}}, "TL210"),
    ({"scenarios": 0}, "TL210"),
    ({"faults": {"count": {"dist": "gaussian"}}}, "TL210"),
    ({"faults": {"count": {"dist": "uniform", "min": 0,
                           "max": 10 ** 9}}}, "TL210"),
    ({"faults": {"scale": {"min": 0.0, "max": 0.5}}}, "TL210"),
    ({"retries": 99}, "TL210"),
    ({"candidate_slices": []}, "TL211"),
    ({"candidate_slices": [{"arch": "v5p"}]}, "TL211"),
    ({"slo": {"step_time_ms": 1.0}}, "TL211"),   # slo w/o candidates
    ({"slo": {"step_time_ms": 1.0, "percentile": 0},
      "candidate_slices": [{"arch": "v5p", "chips": 4}]}, "TL212"),
    ({"slo": {"step_time_ms": 1.0, "percentile": 101},
      "candidate_slices": [{"arch": "v5p", "chips": 4}]}, "TL212"),
])
def test_spec_rejections_carry_stable_codes(mutate, code):
    with pytest.raises(CampaignSpecError) as e:
        load_campaign_spec(base_spec(**mutate))
    assert e.value.code == code


def test_group_link_absent_from_torus_is_tl213():
    from tpusim.analysis import analyze_campaign_spec

    doc = base_spec(correlated_groups=[
        {"name": "ghost", "prob": 0.5,
         "links": [[[0, 0, 0], [3, 0, 0]]]},   # not a 2x2x2 edge
    ])
    diags = analyze_campaign_spec(doc, default_chips=8)
    assert "TL213" in diags.codes()
    assert diags.has_errors
    # axis out of range too
    diags = analyze_campaign_spec(
        base_spec(correlated_groups=[
            {"name": "hyper", "prob": 0.5, "axis": 7},
        ]),
        default_chips=8,
    )
    assert "TL213" in diags.codes()


def test_runner_enforces_validation_before_pricing(tmp_path):
    from tpusim.analysis import ValidationError

    with pytest.raises(ValidationError, match="TL213"):
        run_campaign(
            base_spec(correlated_groups=[
                {"name": "ghost", "prob": 0.5,
                 "links": [[[0, 0, 0], [3, 0, 0]]]},
            ]),
            trace_path=TRACE, out_dir=tmp_path / "c",
        )
    # nothing journaled: the campaign failed before scenario 0
    assert not (tmp_path / "c" / "journal.jsonl").exists()


def test_resume_without_out_dir_is_refused():
    with pytest.raises(ValueError, match="journal"):
        run_campaign(base_spec(), trace_path=TRACE, resume=True)


def test_one_chip_slice_skips_impossible_link_faults():
    """A 1-chip slice has no ICI links; link-kind draws are omitted
    (the zero-fault scenario is a legitimate sample), never a crash."""
    res = run_campaign(
        base_spec(chips=1, scenarios=4,
                  faults={"count": {"dist": "fixed", "n": 2},
                          "kinds": ["link_down", "link_degraded"]}),
        trace_path=TRACE,
    )
    assert all(r["status"] == "ok" for r in res.doc["rows"])
    assert all(r["num_faults"] == 0 for r in res.doc["rows"])


# -- sampling ----------------------------------------------------------------

def test_sampling_is_seed_deterministic_and_order_free():
    from tpusim.ici.topology import torus_for

    spec = load_campaign_spec(base_spec(seed=99))
    topo = torus_for(8, "v5p")
    a = [sample_schedule_doc(spec, topo, "v5p-8", i) for i in range(6)]
    # per-scenario substreams: regenerating out of order changes nothing
    b = [
        sample_schedule_doc(spec, topo, "v5p-8", i)
        for i in (5, 3, 1, 0, 2, 4)
    ]
    assert a == [b[3], b[2], b[4], b[1], b[5], b[0]]
    # a different slice label draws a different stream
    c = sample_schedule_doc(spec, topo, "v5p-64", 0)
    assert c != a[0] or not a[0]["faults"]
    # every sampled record passes the schedule loader untouched
    from tpusim.faults import load_fault_schedule

    for doc in a:
        load_fault_schedule(doc)


def test_percentile_nearest_rank():
    vals = [1.0, 2.0, 3.0, 4.0]
    assert percentile(vals, 50.0) == 2.0
    assert percentile(vals, 75.0) == 3.0
    assert percentile(vals, 99.0) == 4.0
    assert percentile(vals, 100.0) == 4.0
    assert percentile([], 50.0) is None


# -- report determinism ------------------------------------------------------

def test_same_seed_campaign_twice_byte_identical():
    spec = base_spec(
        scenarios=5,
        slo={"step_time_ms": 5.0, "percentile": 80},
        candidate_slices=[{"arch": "v5p", "chips": 4}],
    )
    r1 = run_campaign(spec, trace_path=TRACE)
    r2 = run_campaign(spec, trace_path=TRACE)
    b1 = json.dumps(r1.doc, indent=1, sort_keys=True)
    b2 = json.dumps(r2.doc, indent=1, sort_keys=True)
    assert b1 == b2
    # the contract pieces the issue names, present in one document
    sl = r1.doc["slices"][0]
    assert {"p50", "p95", "p99", "max"} <= set(sl["inflation"])
    assert "partition_rate" in sl
    assert "capacity" in r1.doc
    for row in r1.doc["capacity"]["table"]:
        assert "healthy_watts" in row and "meets" in row


def test_different_seed_changes_the_report():
    r1 = run_campaign(base_spec(seed=1, scenarios=5), trace_path=TRACE)
    r2 = run_campaign(base_spec(seed=2, scenarios=5), trace_path=TRACE)
    assert r1.doc["rows"] != r2.doc["rows"]


# -- partitioned outcomes ----------------------------------------------------

def test_partitioned_topology_is_an_outcome_row_not_a_crash():
    # a dim-2 axis bundle at prob 1.0: every scenario severs the y-axis
    # entirely, disconnecting the replaying chips
    res = run_campaign(
        base_spec(
            scenarios=3,
            faults={"count": {"dist": "fixed", "n": 0}},
            correlated_groups=[
                {"name": "bundle-y", "prob": 1.0, "axis": 1},
            ],
            slo={"step_time_ms": 1.0, "percentile": 99},
            candidate_slices=[{"arch": "v5p", "chips": 8}],
        ),
        trace_path=TRACE,
    )
    rows = res.doc["rows"]
    assert rows and all(r["partitioned"] is True for r in rows)
    assert all(r["status"] == "partitioned" for r in rows)
    sl = res.doc["slices"][0]
    assert sl["partition_rate"] == 1.0
    # no step time exists at any percentile: the SLO cannot be met
    assert sl["slo"]["step_ms_at_percentile"] is None
    assert sl["slo"]["meets"] is False
    assert res.doc["capacity"]["smallest_meeting_slice"] is None
    assert res.stats.partitioned == res.stats.scenarios


def test_failed_scenarios_retry_then_land_as_outcomes(monkeypatch):
    import tpusim.campaign.runner as runner_mod

    calls = {"n": 0}
    orig = runner_mod._price

    def flaky(pod, cfg, topo, faults, cache, workers):
        if faults is not None:
            calls["n"] += 1
            raise OSError("transient infra failure")
        return orig(pod, cfg, topo, faults, cache, workers)

    monkeypatch.setattr(runner_mod, "_price", flaky)
    naps = []
    res = run_campaign(
        base_spec(scenarios=2, retries=2, backoff_s=0.01,
                  faults={"count": {"dist": "fixed", "n": 1},
                          "kinds": ["link_degraded"],
                          "scale": {"min": 0.5, "max": 0.5}}),
        trace_path=TRACE, sleep=naps.append,
    )
    rows = res.doc["rows"]
    assert all(r["status"] == "failed" for r in rows)
    assert all("transient infra failure" in r["error"] for r in rows)
    # 2 scenarios x (1 try + 2 retries), with a backoff nap per retry
    assert calls["n"] == 6
    assert len(naps) == 4
    assert res.stats.retries == 4 and res.stats.failed == 2


# -- journal -----------------------------------------------------------------

def test_journal_drops_torn_trailing_line(tmp_path):
    j = Journal(tmp_path)
    j.append({"kind": "header", "spec_hash": "x", "seed": 1,
              "model_version": "m"})
    j.append({"kind": "scenario", "slice": "s", "index": 0, "row": {}})
    j.close()
    # simulate a crash mid-append: torn partial line, no newline
    with open(j.path, "ab") as f:
        f.write(b'{"kind": "scenario", "slice": "s", "ind')
    recs = Journal(tmp_path).read_records()
    assert [r["kind"] for r in recs] == ["header", "scenario"]


def test_journal_mid_file_corruption_raises(tmp_path):
    j = Journal(tmp_path)
    j.append({"kind": "header"})
    j.close()
    with open(j.path, "ab") as f:
        f.write(b"garbage not json\n")
        f.write(b'{"kind": "scenario"}\n')
    with pytest.raises(JournalError, match="corrupt"):
        Journal(tmp_path).read_records()


def test_journal_refuses_foreign_resume(tmp_path):
    j = Journal(tmp_path)
    j.open_fresh({"spec_hash": "aaaa", "seed": 1, "model_version": "m"})
    j.close()
    with pytest.raises(JournalError, match="spec_hash"):
        Journal(tmp_path).open_resume(
            {"spec_hash": "bbbb", "seed": 1, "model_version": "m"}
        )
    with pytest.raises(JournalError, match="refusing"):
        Journal(tmp_path).open_resume(
            {"spec_hash": "aaaa", "seed": 2, "model_version": "m"}
        )


def test_fresh_journal_refuses_to_clobber(tmp_path):
    spec = base_spec(scenarios=2)
    run_campaign(spec, trace_path=TRACE, out_dir=tmp_path)
    with pytest.raises(JournalError, match="resume"):
        run_campaign(spec, trace_path=TRACE, out_dir=tmp_path)


# -- crash-safe resume -------------------------------------------------------

KILL_SCRIPT = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
from tpusim.campaign import run_campaign

done = 0
def progress(msg):
    global done
    done += 1
    if done == {kill_after}:
        os.kill(os.getpid(), signal.SIGKILL)   # no cleanup, no atexit

run_campaign(
    {spec!r}, trace_path={trace!r}, out_dir={out!r}, progress=progress,
)
"""


def test_resume_after_sigkill_reprices_zero_completed(tmp_path):
    """SIGKILL mid-campaign; --resume completes the run while re-pricing
    ONLY the scenarios the journal does not already hold."""
    spec = base_spec(scenarios=6, seed=21)
    out = tmp_path / "camp"
    kill_after = 3
    script = KILL_SCRIPT.format(
        repo=str(REPO), spec=spec, trace=str(TRACE), out=str(out),
        kill_after=kill_after,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr

    recs = Journal(out).read_records()
    pre = [r for r in recs if r.get("kind") == "scenario"]
    assert len(pre) == kill_after     # journal is a true prefix

    import tpusim.campaign.runner as runner_mod

    priced = {"n": 0, "faulted": 0}
    orig = runner_mod._price

    def counting(pod, cfg, topo, faults, cache, workers):
        priced["n"] += 1
        if faults is not None:
            priced["faulted"] += 1
        return orig(pod, cfg, topo, faults, cache, workers)

    runner_mod._price = counting
    try:
        res = run_campaign(
            spec, trace_path=TRACE, out_dir=out, resume=True,
        )
    finally:
        runner_mod._price = orig

    # zero completed scenarios re-priced: only the remaining 3 ran, and
    # the healthy baseline came back from the journal (0 healthy runs)
    assert priced["faulted"] <= spec["scenarios"] - kill_after
    assert priced["n"] == priced["faulted"]
    assert res.stats.resumed == kill_after
    assert res.stats.priced == spec["scenarios"] - kill_after

    recs = Journal(out).read_records()
    post = [r for r in recs if r.get("kind") == "scenario"]
    assert len(post) == spec["scenarios"]
    assert sorted(r["index"] for r in post) == list(range(6))

    # and the stitched report equals a clean single-process run
    clean = run_campaign(spec, trace_path=TRACE)
    assert json.dumps(res.doc, sort_keys=True) == \
        json.dumps(clean.doc, sort_keys=True)


# -- JobTable persistence ----------------------------------------------------

def test_jobtable_persists_and_recovers(tmp_path):
    from tpusim.serve.admission import JobTable

    t1 = JobTable(persist_dir=tmp_path)
    job_q = t1.submit("campaign", {"spec": {"seed": 1}})
    job_r = t1.submit("sweep", {"chips": 8})
    job_d = t1.submit("sweep", {"chips": 27})
    assert t1.next_job(timeout_s=0.01) is job_q   # queued -> running
    t1.finish(job_d, {"ok": 1}, None)
    files = sorted(p.name for p in tmp_path.glob("job-*.json"))
    assert files == [
        "job-000001.json", "job-000002.json", "job-000003.json",
    ]

    # "restart": a fresh table over the same dir
    t2 = JobTable(persist_dir=tmp_path)
    assert t2.recovered == 2          # running + queued re-enqueue
    got = t2.get(job_q.job_id)
    assert got is not None and got.status == "queued"
    assert got.kind == "campaign"
    assert got.request == {"spec": {"seed": 1}}
    done = t2.get(job_d.job_id)
    assert done.status == "done" and done.result == {"ok": 1}
    # recovered jobs drain in submission order under the SAME ids
    assert t2.next_job(timeout_s=0.01).job_id == job_q.job_id
    assert t2.next_job(timeout_s=0.01).job_id == job_r.job_id
    # ids continue past the recovered ones
    assert t2.submit("sweep", {}).job_id == "job-000004"


# -- the daemon path ---------------------------------------------------------

@pytest.fixture
def serve_daemon_factory():
    daemons = []

    def make(**kw):
        from tpusim.serve.daemon import ServeDaemon

        d = ServeDaemon(trace_root=FIXTURES, **kw)
        d.start()
        daemons.append(d)
        return d

    yield make
    for d in daemons:
        if not d._stopped.is_set():
            d.abort()


def test_daemon_restart_resumes_queued_campaign_job(
    tmp_path, serve_daemon_factory,
):
    """A queued ``POST /v1/campaign`` job survives a daemon crash: the
    restarted daemon re-enqueues it from the persisted spec, runs it to
    completion under the same job id, and journals under --state-dir."""
    from tpusim.serve.client import ServeClient

    spec = base_spec(scenarios=3, seed=8)
    state = tmp_path / "state"

    # job workers held at 0: the job is accepted + persisted, never run
    d1 = serve_daemon_factory(state_dir=state, job_workers=0)
    c1 = ServeClient(d1.url)
    job_id = c1.campaign(spec=spec, trace="llama_tiny_tp2dp2")
    assert c1.job(job_id).status == "queued"
    d1.abort()                        # crash: no drain, no cleanup

    d2 = serve_daemon_factory(state_dir=state, job_workers=1)
    assert d2.jobs.recovered == 1
    c2 = ServeClient(d2.url)
    st = c2.wait_job(job_id, timeout_s=120)
    assert st.status == "done", st.error
    doc = st.result
    assert doc["seed"] == 8
    assert doc["slices"][0]["scenarios"] == 3
    assert (state / "campaigns" / job_id / "journal.jsonl").is_file()
    assert d2.drain_and_stop()

    # the served report matches the CLI path byte for byte
    clean = run_campaign(spec, trace_path=TRACE)
    assert json.dumps(doc, sort_keys=True) == \
        json.dumps(clean.doc, sort_keys=True)


def test_bad_campaign_spec_fails_the_job_with_the_code(
    serve_daemon_factory,
):
    """Submission is async (202 always); a bad spec is refused when the
    job runs, landing as a failed job carrying the loader's message —
    never a daemon crash."""
    from tpusim.serve.client import ServeClient

    d = serve_daemon_factory()
    c = ServeClient(d.url)
    job_id = c.campaign(
        trace="llama_tiny_tp2dp2",
        spec={"seed": 1, "faults": {"kinds": ["tachyon_storm"]}},
    )
    st = c.wait_job(job_id, timeout_s=60)
    assert st.status == "failed"
    assert "tachyon_storm" in st.error
    assert d.drain_and_stop()
