"""The golden-stat CI gate must pass against the committed goldens and
catch an injected model change (ci/check_golden.py — the travis.sh /
Jenkinsfile parity tier)."""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "ci"))

import pytest  # noqa: E402

import check_golden  # noqa: E402


@pytest.fixture(scope="module")
def matrix_results():
    return check_golden.run_matrix()


def test_goldens_match_current_model(matrix_results):
    errors = check_golden.compare(matrix_results)
    assert not errors, "\n".join(errors)


def test_golden_catches_model_change(matrix_results):
    got = dict(matrix_results)
    name = next(iter(got))
    got[name] = dict(got[name])
    got[name]["sim_cycle"] = got[name].get("sim_cycle", 0) + 12345
    errors = check_golden.compare(got)
    assert any("sim_cycle" in e for e in errors)


def test_golden_files_are_committed():
    # one stats golden per matrix row, plus the campaign-smoke,
    # advise-smoke, fleet-smoke, and dcn-smoke reports (different
    # document shapes, pinned by their own --*-smoke modes)
    goldens = list((REPO / "ci" / "golden").glob("*.json"))
    matrix = [
        g for g in goldens
        if g not in (check_golden.CAMPAIGN_SMOKE_GOLDEN,
                     check_golden.ADVISE_SMOKE_GOLDEN,
                     check_golden.FLEET_SMOKE_GOLDEN,
                     check_golden.DCN_SMOKE_GOLDEN)
    ]
    assert len(matrix) == len(check_golden.MATRIX)
    assert check_golden.CAMPAIGN_SMOKE_GOLDEN in goldens
    assert check_golden.ADVISE_SMOKE_GOLDEN in goldens
    assert check_golden.FLEET_SMOKE_GOLDEN in goldens
    assert check_golden.DCN_SMOKE_GOLDEN in goldens
    for g in matrix:
        data = json.loads(g.read_text())
        assert "sim_cycle" in data
        for vol in check_golden.VOLATILE:
            assert vol not in data
