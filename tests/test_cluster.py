"""Multi-node cluster contract (tpusim.serve.cluster + campaign.shard).

Membership: the primary's registry is the single epoch writer — epochs
only climb, a rejoin carrying a stale epoch is refused (split-brain
safety), a node missing K heartbeats is marked dead with the death
rebroadcast to survivors through the beat-response view (pull gossip).

Affinity: the consistent-hash ring moves ONLY a dead node's keys when
membership changes, and skips members that are shedding.

Client failover: idempotent requests move to another known member on
connection-refused/reset; submissions that finished sending and ANY
timed-out request never do (the PR 11 never-replay rules).

Observability: the ``cluster_`` stats namespace and the ``node_id``
field on access-log lines / trace docs exist ONLY when clustered — the
single-node path stays byte-identical, pinned here.

Compute: ``campaign --nodes`` shards by journal signature; a shard
child SIGKILLed mid-run resumes its remaining scenarios on the
survivor with zero re-priced scenarios and a final report
byte-identical to the single-node run.
"""

from __future__ import annotations

import json
import signal
import socket
import threading
import time
from pathlib import Path

import pytest

from tpusim.serve.cluster import (
    AffinityRing,
    ClusterRegistry,
    HeartbeatLoop,
    StaleEpoch,
    alive_members,
    parse_addr,
    ring_for,
    seeded_jitter,
)

FIXTURES = Path(__file__).parent / "fixtures" / "traces"
TRACE = FIXTURES / "llama_tiny_tp2dp2"


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


def registry(**kw) -> tuple[ClusterRegistry, FakeClock]:
    clock = FakeClock()
    kw.setdefault("beat_interval_s", 1.0)
    kw.setdefault("missed_beats", 3)
    reg = ClusterRegistry(
        "prim:1", "http://prim:1", clock=clock, **kw,
    )
    return reg, clock


# -- membership / epoch -----------------------------------------------------

def test_epoch_monotonic_across_joins_beats_and_deaths():
    reg, clock = registry()
    seen = [reg.epoch]
    seen.append(reg.join("a:1", "http://a:1")["epoch"])
    seen.append(reg.join("b:2", "http://b:2")["epoch"])
    seen.append(reg.beat("a:1", epoch=seen[-1])["epoch"])
    clock.now += 10.0          # both members blow their beat deadline
    reg.reap()
    seen.append(reg.epoch)
    seen.append(reg.join("a:1", "http://a:1")["epoch"])  # heal rejoin
    assert seen == sorted(seen), seen
    assert len(set(seen)) >= 4  # joins and the death sweep each bumped


def test_stale_rejoin_refused_fresh_rejoin_accepted():
    reg, clock = registry()
    v1 = reg.join("a:1", "http://a:1")
    reg.join("b:2", "http://b:2")          # epoch moves past v1
    with pytest.raises(StaleEpoch):
        reg.join("a:1", "http://a:1", epoch=v1["epoch"] - 1)
    assert reg.stats_dict()["cluster_stale_rejoins_total"] == 1
    # epoch 0 is the declared-fresh path: always accepted (the heal)
    v = reg.join("a:1", "http://a:1", epoch=0)
    assert any(
        m["node_id"] == "a:1" and m["alive"] for m in v["members"]
    )


def test_heartbeat_timeout_death_is_rebroadcast_to_survivors():
    reg, clock = registry()
    va = reg.join("a:1", "http://a:1")
    vb = reg.join("b:2", "http://b:2")
    # a beats at the deadline minus epsilon; b goes silent
    clock.now += 2.9
    va = reg.beat("a:1", epoch=vb["epoch"])
    clock.now += 0.2                      # b is now past 3 * 1.0s
    died = reg.reap()
    assert died == ["b:2"]
    assert reg.stats_dict()["cluster_deaths_total"] == 1
    # the survivor's NEXT beat response carries the death (pull gossip)
    view = reg.beat("a:1", epoch=va["epoch"])
    dead = {
        m["node_id"] for m in view["members"] if not m["alive"]
    }
    assert dead == {"b:2"}
    assert {m["node_id"] for m in alive_members(view)} == {
        "prim:1", "a:1",
    }


def test_beat_from_dead_or_unknown_node_refused():
    reg, clock = registry()
    v = reg.join("a:1", "http://a:1")
    clock.now += 10.0
    reg.reap()
    with pytest.raises(StaleEpoch):
        reg.beat("a:1", epoch=v["epoch"])   # dead: must rejoin fresh
    with pytest.raises(StaleEpoch):
        reg.beat("ghost:9", epoch=0)        # never joined at all
    reg.join("a:1", "http://a:1", epoch=0)  # the rejoin heals it
    reg.beat("a:1", epoch=reg.epoch)


def test_heartbeat_loop_rejoins_fresh_after_reap():
    """Member-side half of the heal: a beat answered 409 (we were
    reaped while partitioned) drops the loop back to a fresh epoch-0
    join — never a quiet resurrection at the stale epoch."""
    reg, clock = registry()

    def post(path, doc):
        try:
            if path.endswith("/join"):
                return 200, reg.join(
                    doc["node_id"], doc["url"], epoch=doc["epoch"],
                )
            return 200, reg.beat(
                doc["node_id"], epoch=doc["epoch"],
                shedding=doc["shedding"],
            )
        except StaleEpoch:
            return 409, None

    hb = HeartbeatLoop("a:1", "http://a:1", "prim:1", post=post)
    assert hb.step() and hb.joined          # join
    assert hb.step()                        # beat carries the view
    assert hb.view()["epoch"] == reg.epoch
    clock.now += 10.0
    reg.reap()                              # reaped while partitioned
    assert not hb.step() and not hb.joined  # beat → 409 → fresh state
    assert hb.step() and hb.joined          # epoch-0 rejoin heals
    assert any(
        m["node_id"] == "a:1" and m["alive"]
        for m in reg.view()["members"]
    )


def test_reap_never_kills_the_primary_itself():
    reg, clock = registry()
    clock.now += 1000.0
    assert reg.reap() == []
    assert [m["node_id"] for m in alive_members(reg.view())] == [
        "prim:1",
    ]


# -- affinity ring ----------------------------------------------------------

def test_affinity_remaps_only_the_dead_nodes_keys():
    nodes = ["n0:1", "n1:2", "n2:3"]
    ring = AffinityRing(nodes)
    keys = [f"trace-{i}" for i in range(300)]
    before = {k: ring.owner(k) for k in keys}
    assert set(before.values()) == set(nodes)  # all nodes own some
    survivor_ring = AffinityRing(["n0:1", "n2:3"])
    moved = 0
    for k in keys:
        after = survivor_ring.owner(k)
        if before[k] == "n1:2":
            moved += 1
            assert after in ("n0:1", "n2:3")
        else:
            # the consistent-hash contract: survivors keep their keys
            assert after == before[k], k
    assert moved > 0


def test_ring_for_skips_shedding_members_with_floor():
    view = {
        "members": [
            {"node_id": "a:1", "alive": True, "shedding": False},
            {"node_id": "b:2", "alive": True, "shedding": True},
            {"node_id": "c:3", "alive": False, "shedding": False},
        ],
    }
    ring = ring_for(view)
    assert {ring.owner(f"k{i}") for i in range(100)} == {"a:1"}
    # everyone shedding: fall back to all alive rather than an empty
    # ring (shedding nodes answering slowly beats nobody answering)
    for m in view["members"]:
        m["shedding"] = True
    ring = ring_for(view)
    assert {ring.owner(f"k{i}") for i in range(100)} == {"a:1", "b:2"}


def test_seeded_jitter_deterministic_and_bounded():
    a = seeded_jitter("node:1", 3, 2.0)
    assert a == seeded_jitter("node:1", 3, 2.0)
    assert a != seeded_jitter("node:2", 3, 2.0)
    assert 0.0 <= a <= 0.5 * 2.0


def test_parse_addr():
    assert parse_addr("127.0.0.1:8080") == ("127.0.0.1", 8080)
    with pytest.raises(ValueError):
        parse_addr("no-port")


# -- zero new keys when unclustered (satellite pin) -------------------------

def test_unclustered_daemon_mints_zero_cluster_keys():
    from tpusim.serve.daemon import ServeDaemon

    d = ServeDaemon(trace_root=FIXTURES).start()
    try:
        from tpusim.serve.client import ServeClient

        c = ServeClient(d.url)
        c.simulate(trace="matmul_512", arch="v5e")
        assert not any(
            k.startswith("cluster_") or k.startswith("serve_nodes")
            for k in d.metrics_values()
        )
        text = c.metrics_text()
        assert "cluster_" not in text
        assert "serve_nodes" not in text
        assert "cluster" not in c.healthz()
    finally:
        d.abort()


# -- two live daemons: join, gossip, forward --------------------------------

@pytest.mark.slow
def test_two_daemons_join_forward_and_heal():
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    a = ServeDaemon(trace_root=FIXTURES).start()
    b = None
    try:
        b = ServeDaemon(
            trace_root=FIXTURES, cluster_join=f"{a.host}:{a.port}",
        ).start()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if a.cluster is not None and len(
                alive_members(a.cluster.view())
            ) == 2:
                break
            time.sleep(0.05)
        else:
            pytest.fail("node B never joined")
        assert b.cluster_view_doc()["epoch"] == a.cluster.epoch
        ra = ServeClient(a.url).simulate(trace="matmul_512", arch="v5e")
        rb = ServeClient(b.url).simulate(trace="matmul_512", arch="v5e")
        assert ra.sim_cycles == rb.sim_cycles
        ha = ServeClient(a.url).healthz()
        assert ha["cluster"]["nodes_alive"] == 2
        assert ha["cluster"]["primary"] is True
        b.abort()
        b = None
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            stats = a.cluster.stats_dict()
            if stats["cluster_deaths_total"] >= 1:
                break
            time.sleep(0.1)
        else:
            pytest.fail("node B's death never recorded")
        # the fleet keeps serving through the heal
        r2 = ServeClient(a.url).simulate(trace="matmul_512", arch="v5e")
        assert r2.sim_cycles == ra.sim_cycles
    finally:
        if b is not None:
            b.abort()
        a.abort()


# -- client failover (stub servers) -----------------------------------------

class StubServer:
    """Raw-socket stub: records request counts; per-mode behavior lets
    each failover rule be pinned without a real daemon."""

    def __init__(self, mode: str = "ok"):
        self.mode = mode
        self.hits = 0
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def _run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.hits += 1
            try:
                conn.settimeout(5.0)
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = conn.recv(65536)
                    if not chunk:
                        break
                    data += chunk
                if self.mode == "reset_after_recv":
                    # pause so the client is parked in getresponse()
                    # (bytes FINISHED sending — a send-stage reset is
                    # legitimately safe to replay, not what we pin here)
                    self._stop.wait(0.3)
                    # RST, not FIN: FIN reads as idle keep-alive close
                    conn.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        b"\x01\x00\x00\x00\x00\x00\x00\x00",
                    )
                    conn.close()
                    continue
                if self.mode == "stall":
                    # accept, read, never answer: the client times out
                    self._stop.wait(10.0)
                    conn.close()
                    continue
                body = b'{"ok": true}'
                conn.sendall(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() +
                    b"\r\nConnection: close\r\n\r\n" + body
                )
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_client_fails_over_idempotent_post_on_refused():
    from tpusim.serve.client import ServeClient

    live = StubServer()
    try:
        c = ServeClient(
            f"http://127.0.0.1:{_dead_port()}",
            retries=0, members=[live.url],
        )
        doc = c._request(
            "POST", "/v1/simulate", {"trace": "t"}, idempotent=True,
        )
        assert doc == {"ok": True}
        assert live.hits == 1
    finally:
        live.close()


def test_client_fails_over_get_on_refused():
    from tpusim.serve.client import ServeClient

    live = StubServer()
    try:
        c = ServeClient(
            f"http://127.0.0.1:{_dead_port()}",
            retries=0, members=[live.url],
        )
        assert c._request("GET", "/healthz") == {"ok": True}
        assert live.hits == 1
    finally:
        live.close()


def test_client_never_fails_over_sent_submission():
    from tpusim.serve.client import ServeClient, ServeError

    first = StubServer(mode="reset_after_recv")
    fallback = StubServer()
    try:
        c = ServeClient(first.url, retries=2, members=[fallback.url])
        with pytest.raises(ServeError) as ei:
            # a job submission: idempotent NOT set, bytes finish
            # sending before the RST — replaying it elsewhere could
            # enqueue a duplicate job
            c._request("POST", "/v1/sweep", {"job": 1})
        assert ei.value.code == "connection_failed"
        assert fallback.hits == 0
    finally:
        first.close()
        fallback.close()


def test_client_never_fails_over_after_timeout():
    from tpusim.serve.client import ServeClient, ServeError

    first = StubServer(mode="stall")
    fallback = StubServer()
    try:
        c = ServeClient(
            first.url, timeout_s=0.3, retries=2,
            members=[fallback.url],
        )
        with pytest.raises(ServeError) as ei:
            # even idempotent bodies: the stalled node may still be
            # executing, and stacking a replay compounds the load
            c._request(
                "POST", "/v1/simulate", {"trace": "t"},
                idempotent=True,
            )
        assert ei.value.code == "timeout"
        assert fallback.hits == 0
    finally:
        first.close()
        fallback.close()


# -- node_id on observability surfaces (satellite pin) ----------------------

def test_access_log_node_id_only_when_clustered(tmp_path):
    from tpusim.obs.reqtrace import AccessLog

    plain = AccessLog(tmp_path / "plain.jsonl")
    plain.write(route="simulate", status=200, latency_ms=1.0)
    plain.close()
    clustered = AccessLog(tmp_path / "clustered.jsonl")
    clustered.write(
        route="simulate", status=200, latency_ms=1.0,
        node_id="127.0.0.1:9",
    )
    clustered.close()
    doc = json.loads((tmp_path / "plain.jsonl").read_text())
    assert "node_id" not in doc
    doc = json.loads((tmp_path / "clustered.jsonl").read_text())
    assert doc["node_id"] == "127.0.0.1:9"


def test_trace_doc_node_id_only_when_clustered():
    from tpusim.obs.reqtrace import RequestTracer

    plain = RequestTracer()
    tr = plain.begin("simulate")
    doc = plain.finish(tr, 200)
    assert "node_id" not in doc
    clustered = RequestTracer(node_id="127.0.0.1:9")
    tr = clustered.begin("simulate")
    doc = clustered.finish(tr, 200)
    assert doc["node_id"] == "127.0.0.1:9"


# -- distributed campaign ---------------------------------------------------

def shard_spec(**over) -> dict:
    doc = {
        "name": "t-shard", "seed": 11, "scenarios": 4,
        "arch": "v5p", "chips": 8, "tuned": False,
        "faults": {
            "count": {"dist": "uniform", "min": 0, "max": 2},
            "kinds": {"link_down": 1.0, "link_degraded": 1.0,
                      "chip_straggler": 0.5, "hbm_throttle": 0.5},
            "scale": {"min": 0.4, "max": 0.9},
        },
    }
    doc.update(over)
    return doc


def all_shard_sigs(out_dir) -> tuple[set, int]:
    from tpusim.campaign.journal import Journal

    seen: set = set()
    dup = 0
    shards = Path(out_dir) / "shards"
    for d in sorted(shards.iterdir()) if shards.is_dir() else []:
        if not (d / "journal.jsonl").is_file():
            continue
        for rec in Journal(d).iter_records():
            if rec.get("kind") != "scenario":
                continue
            sig = (rec["slice"], rec["index"])
            if sig in seen:
                dup += 1
            seen.add(sig)
    return seen, dup


def test_shard_assignment_stable_when_a_node_dies():
    from tpusim.campaign import shard_assignment

    work = [("v5p-8", i) for i in range(64)]
    before = shard_assignment(work, [0, 1, 2], "deadbeef")
    assert all(before[n] for n in (0, 1, 2))  # every node owns some
    after = shard_assignment(work, [0, 2], "deadbeef")
    # survivors keep EXACTLY their keys; only node 1's work moved
    assert before[0] <= after[0]
    assert before[2] <= after[2]
    assert (after[0] | after[2]) == set(work)
    assert (after[0] - before[0]) | (after[2] - before[2]) == before[1]


def test_sharded_campaign_requires_out_dir():
    from tpusim.campaign import run_sharded_campaign

    with pytest.raises(ValueError, match="--out"):
        run_sharded_campaign(shard_spec(), trace_path=TRACE, nodes=2)


@pytest.mark.slow
def test_shard_kill_resumes_elsewhere_report_byte_identical(tmp_path):
    """The tentpole chaos contract at unit grain: one shard child
    SIGKILLed as soon as it spawns; the survivor prices the dead
    shard's scenarios in the next wave, nothing prices twice, and the
    merged report is byte-identical to the single-node run."""
    from tpusim.campaign import run_campaign, run_sharded_campaign

    spec = shard_spec()
    single = run_campaign(
        spec, trace_path=TRACE, out_dir=tmp_path / "single",
    )
    single_bytes = (tmp_path / "single" / "report.json").read_text()

    killed = {"n": 0}

    def kill_first(procs):
        if killed["n"] == 0 and procs:
            victim = procs[sorted(procs)[0]]
            import os

            os.kill(victim.pid, signal.SIGKILL)
            killed["n"] += 1

    msgs: list[str] = []
    res = run_sharded_campaign(
        spec, trace_path=TRACE, out_dir=tmp_path / "sharded",
        nodes=2, progress=msgs.append, on_spawn=kill_first,
    )
    assert killed["n"] == 1
    assert any("died" in m for m in msgs), msgs
    merged = (tmp_path / "sharded" / "report.json").read_text()
    assert merged == single_bytes
    sigs, dup = all_shard_sigs(tmp_path / "sharded")
    assert dup == 0
    assert len(sigs) == res.stats.scenarios == single.stats.scenarios
