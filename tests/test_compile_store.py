"""tpusim.fastpath.store — the durable compiled-module tier.

The tier's contract has three legs, each pinned here:

* **byte identity** — a module priced from disk-loaded columns must
  reproduce the serial walk and the freshly-compiled fastpath float for
  float, per-op aggregates included;
* **cross-process durability semantics** — torn/corrupt records
  quarantine with exactly one warning and heal on recompile, stale
  model/parser versions orphan records into plain misses, and N
  processes racing one cold key converge on identical results with no
  torn reads;
* **zero-IR cold path** — with a warm store, a defer-parsed trace
  prices without building a single IR op (no computation parses, no
  span index).
"""

from __future__ import annotations

import json
import multiprocessing
import subprocess
import sys
import warnings
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
SILICON = REPO / "reports" / "silicon"
CI_TRACES = REPO / "tests" / "fixtures" / "traces"

pytestmark = pytest.mark.skipif(
    not pytest.importorskip("numpy"), reason="numpy unavailable"
)


@pytest.fixture(autouse=True)
def _clean_tiers():
    """Every test starts and ends with no process-wide compiled state."""
    from tpusim.fastpath.store import set_compile_store
    from tpusim.perf.cache import clear_compiled_cache

    set_compile_store(None)
    clear_compiled_cache()
    yield
    set_compile_store(None)
    clear_compiled_cache()


def _load_module(trace_dir: Path, defer: bool | None = None):
    from tpusim.trace.format import load_trace

    pod = load_trace(trace_dir, defer_parse=defer)
    return pod.modules[sorted(pod.modules)[0]]


def _engine(arch="v5e", backend=None):
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine

    return Engine(load_config(arch=arch), pricing_backend=backend)


def _doc(result) -> str:
    from tpusim.perf.cache import result_to_doc

    return json.dumps(result_to_doc(result), sort_keys=False)


def _trace_dirs() -> list[Path]:
    manifest = json.loads((SILICON / "manifest.json").read_text())
    return [SILICON / e["trace"] for e in manifest["workloads"]]


# ---------------------------------------------------------------------------
# Round trip + byte identity
# ---------------------------------------------------------------------------


def test_roundtrip_byte_identity_full_corpus(tmp_path):
    """serial == fresh-compile == disk-loaded, for every fixture module
    (multi-chip CI traces included: collectives, tuples, while loops)."""
    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.perf.cache import clear_compiled_cache

    dirs = _trace_dirs() + [
        d for d in sorted(CI_TRACES.iterdir()) if d.is_dir()
    ]
    serial = {}
    for d in dirs:
        serial[d.name] = _doc(
            _engine(backend="serial").run(_load_module(d))
        )

    store = CompileStore(tmp_path)
    set_compile_store(store)
    fresh = {}
    for d in dirs:
        fresh[d.name] = _doc(_engine().run(_load_module(d)))
    assert store.stores == len(dirs)

    clear_compiled_cache()
    store2 = CompileStore(tmp_path)
    set_compile_store(store2)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        loaded = {}
        for d in dirs:
            loaded[d.name] = _doc(_engine().run(_load_module(d)))
    assert store2.hits == len(dirs)
    assert store2.misses == 0
    for name in serial:
        assert fresh[name] == serial[name], name
        assert loaded[name] == serial[name], name


def test_record_carries_module_scalars(tmp_path):
    """entry_name (and the residency slots) ride the record, so a
    loaded instance answers without touching the module."""
    from tpusim.fastpath.store import (
        CompileStore, read_record_header, set_compile_store,
    )
    from tpusim.perf.cache import clear_compiled_cache

    d = _trace_dirs()[0]
    store = CompileStore(tmp_path)
    set_compile_store(store)
    mod = _load_module(d)
    _engine().run(mod)
    records = list(Path(tmp_path).glob("*.cmod"))
    assert len(records) == 1
    header = read_record_header(records[0])
    assert header["module"]["entry_name"] == mod.entry_name

    clear_compiled_cache()
    set_compile_store(CompileStore(tmp_path))
    mod2 = _load_module(d)
    eng = _engine()
    from tpusim.perf.cache import compiled_for

    cm = compiled_for(mod2, eng)
    assert cm.entry_name == mod.entry_name
    assert cm.comps  # populated from disk, no compile needed


# ---------------------------------------------------------------------------
# Corruption / staleness
# ---------------------------------------------------------------------------


def test_corrupt_record_quarantines_once_and_heals(tmp_path):
    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.perf.cache import clear_compiled_cache

    d = _trace_dirs()[0]
    set_compile_store(CompileStore(tmp_path))
    want = _doc(_engine().run(_load_module(d)))

    record = next(Path(tmp_path).glob("*.cmod"))
    raw = record.read_bytes()
    record.write_bytes(raw[: len(raw) // 2])  # torn write

    clear_compiled_cache()
    store = CompileStore(tmp_path)
    set_compile_store(store)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        got = _doc(_engine().run(_load_module(d)))
    assert got == want
    relevant = [
        w for w in caught if "compiled-module" in str(w.message)
    ]
    assert len(relevant) == 1  # exactly one warning, ever
    assert store.quarantined == 1
    assert (Path(tmp_path) / "quarantine").is_dir()
    # the recompile's publish healed the store: a fresh lookup is a
    # clean hit with zero warnings
    clear_compiled_cache()
    store3 = CompileStore(tmp_path)
    set_compile_store(store3)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert _doc(_engine().run(_load_module(d))) == want
    assert store3.hits >= 1


def test_stale_model_version_is_a_plain_miss(tmp_path):
    """A model/parser bump orphans old records: no warning, no load —
    and verify_store counts them as stale."""
    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.guard.store import verify_store
    from tpusim.perf.cache import clear_compiled_cache

    d = _trace_dirs()[0]
    store = CompileStore(tmp_path)
    store._model_version = "ancient+parser"  # records stamp this
    set_compile_store(store)
    _engine().run(_load_module(d))
    assert store.stores == 1

    res = verify_store(tmp_path)
    assert res.compiled_checked == 1
    assert res.stale_model == 1  # well-formed, merely unreachable
    assert res.quarantined_corrupt == 0

    clear_compiled_cache()
    live = CompileStore(tmp_path)  # live composite stamp
    set_compile_store(live)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _engine().run(_load_module(d))
    assert live.hits == 0
    assert live.quarantined == 0  # stale, not corrupt
    assert live.stores == 1  # the recompile re-published under the
    # same key, healing the record to the live stamp
    res = verify_store(tmp_path)
    assert res.stale_model == 0
    assert res.ok == 1


# ---------------------------------------------------------------------------
# Cross-process race
# ---------------------------------------------------------------------------


def _race_child(trace_dir: str, store_dir: str, q) -> None:
    try:
        import warnings as _w

        from tpusim.fastpath.store import CompileStore, set_compile_store
        from tpusim.trace.format import load_trace

        set_compile_store(CompileStore(store_dir))
        pod = load_trace(trace_dir)
        mod = pod.modules[sorted(pod.modules)[0]]
        with _w.catch_warnings():
            _w.simplefilter("error")
            doc = _doc(_engine().run(mod))
        q.put(("ok", doc))
    except BaseException as e:  # noqa: BLE001 - report, don't hang
        q.put(("err", f"{type(e).__name__}: {e}"))


def test_processes_racing_one_cold_key_converge(tmp_path):
    """N processes pricing the same cold module into one store dir all
    succeed with byte-identical results, no torn reads, and exactly one
    servable record at the end."""
    d = _trace_dirs()[0]
    ctx = multiprocessing.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_race_child, args=(str(d), str(tmp_path), q))
        for _ in range(3)
    ]
    for p in procs:
        p.start()
    results = [q.get(timeout=120) for _ in procs]
    for p in procs:
        p.join(timeout=60)
    statuses = {s for s, _ in results}
    assert statuses == {"ok"}, results
    docs = {doc for _, doc in results}
    assert len(docs) == 1
    records = list(Path(tmp_path).glob("*.cmod"))
    assert len(records) == 1
    assert not (Path(tmp_path) / "quarantine").exists()
    # and the record the racers converged on loads cleanly
    from tpusim.fastpath.store import CompileStore, set_compile_store

    store = CompileStore(tmp_path)
    set_compile_store(store)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert _doc(_engine().run(_load_module(d))) in docs
    assert store.hits == 1


# ---------------------------------------------------------------------------
# Zero-IR cold path
# ---------------------------------------------------------------------------


def test_warm_store_prices_with_zero_ir_construction(tmp_path):
    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.ir import ir_build_counter
    from tpusim.perf.cache import clear_compiled_cache

    d = _trace_dirs()[0]
    set_compile_store(CompileStore(tmp_path))
    want = _doc(_engine().run(_load_module(d)))

    clear_compiled_cache()
    set_compile_store(CompileStore(tmp_path))
    mod = _load_module(d)  # defer_parse auto-engages (store active)
    before = ir_build_counter["ops"]
    got = _doc(_engine().run(mod))
    assert got == want
    assert ir_build_counter["ops"] == before  # zero ops built
    assert mod.parsed_count == 0  # no computation ever parsed
    assert mod._spans_cache is None  # not even the span index


def test_lazy_span_index_builds_on_demand():
    """The deferred span index is transparent: entry access, pricing,
    and residency scans on a lazy module still work (and match the
    eager parse) when no store serves them."""
    from tpusim.trace.format import load_trace

    d = _trace_dirs()[0]
    eager = load_trace(d, defer_parse=False)
    lazy = load_trace(d, defer_parse=True)
    name = sorted(eager.modules)[0]
    em, lm = eager.modules[name], lazy.modules[name]
    assert lm._spans_cache is None
    assert lm.entry_name == em.entry_name  # forces the index
    assert lm._spans_cache is not None
    assert sorted(lm.computations.keys()) == sorted(
        em.computations.keys()
    )
    assert _doc(_engine(backend="serial").run(lm)) == _doc(
        _engine(backend="serial").run(em)
    )


# ---------------------------------------------------------------------------
# Guard integration: tiers in one store
# ---------------------------------------------------------------------------


def test_gc_and_scan_cover_both_tiers(tmp_path):
    from tpusim.fastpath.store import CompileStore, set_compile_store
    from tpusim.guard.store import gc_store, scan_store
    from tpusim.perf.cache import ResultCache

    d = _trace_dirs()[0]
    set_compile_store(CompileStore(tmp_path))
    mod = _load_module(d)
    cache = ResultCache(disk_dir=tmp_path)
    from tpusim.perf.cache import CachedEngine
    from tpusim.timing.config import load_config

    CachedEngine(load_config(arch="v5e"), result_cache=cache).run(mod)
    stats = scan_store(tmp_path)
    assert stats.result_entries == 1
    assert stats.compiled_entries == 1
    assert stats.entries == 2
    assert stats.bytes == stats.result_bytes + stats.compiled_bytes
    res = gc_store(tmp_path, max_entries=0)
    assert res.deleted == 2  # tier-blind whole-record eviction
    assert scan_store(tmp_path).entries == 0


def test_cache_cli_covers_compiled_tier(tmp_path):
    """``tpusim cache stats|verify|clear`` see (and govern) ``.cmod``
    records beside the result records."""
    from tpusim.fastpath.store import CompileStore, set_compile_store

    d = _trace_dirs()[0]
    set_compile_store(CompileStore(tmp_path))
    _engine().run(_load_module(d))
    set_compile_store(None)

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "tpusim", "cache", *args,
             "--dir", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO,
        )

    out = cli("stats")
    assert out.returncode == 0
    assert "compiled: 1" in out.stdout

    record = next(Path(tmp_path).glob("*.cmod"))
    record.write_bytes(b"TPUCMODX garbage")
    out = cli("verify")
    assert out.returncode == 0
    assert "quarantined (corrupt): 1" in out.stdout
    assert not record.exists()

    out = cli("clear")
    assert out.returncode == 0
    assert not list(Path(tmp_path).glob("*.cmod"))
    assert not (tmp_path / "quarantine").exists()


def test_compile_cache_cli_flag_end_to_end(tmp_path):
    """``tpusim simulate --compile-cache``: run 2 loads what run 1
    compiled (fastpath_store_hits on the report), byte-identical
    stats."""
    store_dir = tmp_path / "store"
    trace = _trace_dirs()[0]

    def run(json_out):
        return subprocess.run(
            [sys.executable, "-m", "tpusim", "simulate", str(trace),
             "--arch", "v5e", "--compile-cache", str(store_dir),
             "--json", str(json_out)],
            capture_output=True, text=True, cwd=REPO,
        )

    r1 = run(tmp_path / "a.json")
    assert r1.returncode == 0, r1.stderr
    r2 = run(tmp_path / "b.json")
    assert r2.returncode == 0, r2.stderr
    a = json.loads((tmp_path / "a.json").read_text())
    b = json.loads((tmp_path / "b.json").read_text())
    assert a["fastpath_store_writes"] >= 1
    assert b["fastpath_store_hits"] >= 1
    assert b["fastpath_ir_ops_built"] == 0  # defer-parse + warm store
    strip = ("simulation_rate_kops", "silicon_slowdown", "sim_elapsed_s")
    sa = {k: v for k, v in a.items()
          if not k.startswith("fastpath_") and k not in strip}
    sb = {k: v for k, v in b.items()
          if not k.startswith("fastpath_") and k not in strip}
    assert sa == sb


# ---------------------------------------------------------------------------
# Namespace registration
# ---------------------------------------------------------------------------


def test_fastpath_namespace_licenses_serve():
    from tpusim.analysis.statskeys import STATS_NAMESPACES

    assert "tpusim/serve/" in STATS_NAMESPACES["fastpath_"]


def test_enospc_disables_store_writes_with_one_warning(
    tmp_path, monkeypatch,
):
    """ENOSPC/EIO graceful degradation on the compiled tier: a failed
    staging write warns ONCE, disables further saves for the instance,
    and pricing still serves the computed result."""
    import errno

    import tpusim.fastpath.store as S
    from tpusim.fastpath.store import CompileStore, set_compile_store

    def boom(tmp, payload, durable):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(S, "_stage_bytes", boom)
    store = CompileStore(tmp_path)
    set_compile_store(store)
    dirs = _trace_dirs()[:2]
    serial = [
        _doc(_engine(backend="serial").run(_load_module(d)))
        for d in dirs
    ]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        priced = [_doc(_engine().run(_load_module(d))) for d in dirs]
    disabled = [
        w for w in caught
        if "disabling further store writes" in str(w.message)
    ]
    assert len(disabled) == 1            # two failed saves, one warning
    assert store._write_disabled
    assert store.stores == 0
    assert priced == serial              # results served regardless
    assert not list(tmp_path.glob("*.cmod"))
