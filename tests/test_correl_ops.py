"""Tests for per-op silicon correlation (VERDICT r1 #2 — the
plot-correlation.py / correl_mappings.py rebuild at HLO-instruction
grain)."""

from __future__ import annotations

import json
import math

import pytest

from tpusim.harness.correl_ops import (
    OpCorrelation,
    OpRow,
    OpSilicon,
    correlate_ops,
    write_correl_ops,
)
from tpusim.timing.engine import EngineResult


def _result(ops: dict[str, tuple[float, float, str]]) -> EngineResult:
    """ops: name -> (total_cycles, count, opcode)"""
    r = EngineResult()
    for name, (cycles, count, opcode) in ops.items():
        r.per_op_cycles[name] = cycles
        r.per_op_count[name] = count
        r.per_op_opcode[name] = opcode
    return r


def test_correlate_ops_matches_and_normalizes():
    # 1 GHz clock: 1 cycle == 1 ns
    res = _result({
        "dot.1": (1000.0, 1.0, "dot"),
        "fusion.2": (600.0, 2.0, "fusion"),   # 300ns per occurrence
        "ghost.3": (50.0, 1.0, "add"),        # not on silicon
        "while.1": (99999.0, 1.0, "while"),   # control flow: excluded
    })
    silicon = {
        "dot.1": OpSilicon("dot.1", count=3.0, total_ns=2400.0),   # 800ns avg
        "fusion.2": OpSilicon("fusion.2", count=6.0, total_ns=1200.0),  # 200
        "extra.9": OpSilicon("extra.9", count=3.0, total_ns=300.0),
    }
    corr = correlate_ops(
        res, silicon, clock_hz=1e9, workload="t", real_iters=3,
    )
    rows = {r.name: r for r in corr.rows}
    assert set(rows) == {"dot.1", "fusion.2"}
    assert rows["dot.1"].sim_ns == pytest.approx(1000.0)
    assert rows["dot.1"].real_ns == pytest.approx(800.0)
    assert rows["dot.1"].error_pct == pytest.approx(25.0)
    assert rows["fusion.2"].sim_ns == pytest.approx(300.0)
    assert rows["fusion.2"].real_ns == pytest.approx(200.0)
    # per-iteration occurrence count on the silicon side
    assert rows["fusion.2"].real_count == pytest.approx(2.0)
    assert "ghost.3" in corr.sim_only
    assert "extra.9" in corr.silicon_only
    assert "while.1" not in rows
    # matched fraction: (2400 + 1200) / (2400 + 1200 + 300)
    assert corr.matched_time_fraction == pytest.approx(3600 / 3900)
    assert math.isfinite(corr.weighted_abs_error_pct)
    # time-weighted: (25% * 2400 + 50% * 1200) / 3600
    assert corr.weighted_abs_error_pct == pytest.approx(
        (25 * 2400 + 50 * 1200) / 3600
    )


def test_worst_ranks_by_time_delta():
    corr = OpCorrelation("t", rows=[
        OpRow("small_bad", "add", sim_ns=10.0, real_ns=1.0,
              sim_count=1, real_count=1),      # 900% error, 9ns delta
        OpRow("big_slightly_off", "dot", sim_ns=11000.0, real_ns=10000.0,
              sim_count=1, real_count=1),      # 10% error, 1000ns delta
    ])
    worst = corr.worst(2)
    assert worst[0].name == "big_slightly_off"


def test_by_opcode_aggregates():
    corr = OpCorrelation("t", rows=[
        OpRow("dot.1", "dot", 150.0, 100.0, 1, 1),
        OpRow("dot.2", "dot", 100.0, 100.0, 1, 1),
        OpRow("f.1", "fusion", 50.0, 100.0, 1, 1),
    ])
    agg = corr.by_opcode()
    assert agg["dot"]["error_pct"] == pytest.approx(25.0)
    assert agg["fusion"]["error_pct"] == pytest.approx(-50.0)


def test_write_correl_ops(tmp_path):
    corr = OpCorrelation("w1", rows=[
        OpRow("dot.1", "dot", 150.0, 100.0, 1, 1),
    ])
    corr.matched_time_fraction = 1.0
    p = write_correl_ops([corr], tmp_path / "correl_ops.json")
    doc = json.loads(p.read_text())
    assert doc["mean_weighted_abs_error_pct"] == pytest.approx(50.0)
    assert doc["workloads"][0]["workload"] == "w1"
    assert doc["workloads"][0]["rows"][0]["name"] == "dot.1"


def test_engine_records_per_op_aggregates():
    """Loop bodies must appear in per_op_cycles scaled by trip count."""
    from pathlib import Path

    from tpusim.timing.config import SimConfig
    from tpusim.timing.engine import Engine
    from tpusim.trace.hlo_text import parse_hlo_module

    fixtures = Path(__file__).parent / "fixtures"
    mod = parse_hlo_module((fixtures / "tiny_mlp.hlo").read_text())
    res = Engine(SimConfig()).run(mod)
    assert res.per_op_cycles.get("dot.1", 0) > 0
    assert res.per_op_count.get("dot.1") == 1.0
    assert res.per_op_opcode.get("dot.1") == "dot"


# end-to-end on the CPU backend (numbers meaningless vs the TPU model;
# the mechanics — profile, xplane parse, name matching — are the test)
CORREL_SCRIPT = r"""
import json
from tpusim.harness.correl_ops import correlate_workload_ops, write_correl_ops
from tpusim.models import get_workload

fn, args = get_workload("matmul_chain").build(m=256, k=256, depth=2)
corr = correlate_workload_ops(fn, args, name="mini", arch="v5e", iters=2)
assert len(corr.rows) >= 2, corr.rows
assert corr.matched_time_fraction > 0.5, corr.matched_time_fraction
p = write_correl_ops([corr], OUT)
doc = json.loads(open(p).read())
assert doc["workloads"][0]["n_matched"] >= 2
print("CORREL_OPS_OK")
"""


@pytest.mark.slow
def test_correlate_workload_ops_end_to_end(tmp_path, cpu_mesh_runner):
    _require_xplane_support()
    out = cpu_mesh_runner(
        CORREL_SCRIPT.replace(
            "OUT", repr(str(tmp_path / "correl_ops.json"))
        ),
        n_devices=1,
    )
    assert "CORREL_OPS_OK" in out


# -- real-TPU xplane fixture (VERDICT r3 #2) --------------------------------
# Captured live on a TPU v5 lite through the axon tunnel:
# elementwise_stream (32Mi f32) wrapped in loopify(16), 3 executions under
# jax.profiler.trace.  Real device planes name XLA Ops events with the FULL
# instruction text ('%copy.8 = f32[...]{0:T(1024)} copy(...)'), carry no
# hlo_op stat, and put whole-program durations on the 'XLA Modules' line.

import pathlib

XPLANE_FIXTURE = (
    pathlib.Path(__file__).parent / "fixtures" / "xplane"
    / "v5e_elementwise_loop16.xplane.pb"
)


def _require_xplane_support() -> None:
    """Skip (never error) when the optional xplane parser is absent.

    The extraction path needs ``jax.profiler.ProfileData``, which only
    some jax builds ship (it rides the bundled tensorflow-profiler
    protos).  An environment without it cannot exercise these tests at
    all — that is a missing optional dep, not a regression — but when
    the import DOES resolve, any failure inside the tests is real and
    must surface."""
    pytest.importorskip("jax")
    try:
        from jax.profiler import ProfileData  # noqa: F401
    except ImportError as e:
        pytest.skip(
            f"optional xplane support missing: jax.profiler.ProfileData "
            f"not importable in this jax build ({e})"
        )


def test_event_op_name_real_tpu_shapes():
    from tpusim.harness.correl_ops import _event_op_name

    assert _event_op_name(
        "%copy.8 = f32[33554432]{0:T(1024)} copy(f32[33554432]{0:T(1024)} "
        "%first.1)"
    ) == "copy.8"
    assert _event_op_name("%fusion.2") == "fusion.2"
    assert _event_op_name("dot.1") == "dot.1"   # CPU planes: bare names


def test_extract_op_profile_real_tpu_xplane():
    _require_xplane_support()
    from tpusim.harness.correl_ops import extract_op_profile

    ops = extract_op_profile(XPLANE_FIXTURE)
    # keys must be bare instruction names, not full instruction text
    assert "multiply_add_fusion.2" in ops, sorted(ops)[:10]
    assert "while" in ops
    fusion = ops["multiply_add_fusion.2"]
    # 3 profiled executions x 16 loop iterations
    assert fusion.count == 48.0
    # ~408us per occurrence on the v5e (HBM-bound 256MB stream)
    assert 2e5 < fusion.avg_ns < 8e5
    # no host-python junk
    assert not any(k.startswith("$") for k in ops)


def test_extract_module_profile_real_tpu_xplane():
    _require_xplane_support()
    from tpusim.harness.correl_ops import extract_module_profile

    mods = extract_module_profile(XPLANE_FIXTURE)
    assert len(mods) == 1
    (mod,) = mods.values()
    assert mod.count == 3.0               # three program executions
    # whole program ~6.9ms: 16 x ~408us fusion + one-time carry copy
    assert 5e6 < mod.avg_ns < 9e6


def test_correlate_ops_matches_real_tpu_event_names():
    """End-to-end name matching: engine per-op names vs real-TPU event
    text must line up (the round-3 matcher matched ZERO ops)."""
    _require_xplane_support()
    from tpusim.harness.correl_ops import extract_op_profile

    silicon = extract_op_profile(XPLANE_FIXTURE)
    res = _result({
        "multiply_add_fusion.2": (1000.0, 16.0, "fusion"),
        "copy.8": (500.0, 1.0, "copy"),
        # the engine always records the loop container; silicon reports it
        # too, spanning its whole body — it must not poison the denominator
        "while": (16000.0, 1.0, "while"),
    })
    corr = correlate_ops(
        res, silicon, clock_hz=1e9, workload="elem", real_iters=3,
    )
    names = {r.name for r in corr.rows}
    assert "multiply_add_fusion.2" in names
    assert "copy.8" in names
    assert corr.matched_time_fraction > 0.9


def test_correlate_counters_from_real_xplane():
    """Counter-level cross-check (VERDICT r3 #8): achieved HBM GB/s of the
    heaviest streaming op derived from static bytes + measured device
    time, vs the model's streaming rate."""
    _require_xplane_support()
    from tpusim.harness.correl_ops import (
        correlate_counters, extract_op_profile,
    )
    from tpusim.timing.config import load_config

    silicon = extract_op_profile(XPLANE_FIXTURE)
    res = _result({
        "multiply_add_fusion.2": (6_500_000.0, 16.0, "fusion"),
        "copy.8": (760_000.0, 1.0, "copy"),
    })
    # the fusion streams 32Mi f32 in + out per occurrence = 256MB
    res.per_op_hbm_bytes["multiply_add_fusion.2"] = 16 * 2 * 32 * 2**20 * 4.0
    res.per_op_flops["multiply_add_fusion.2"] = 16 * 32 * 2**20 * 1.0
    # MXU counter keys on mxu_flops; tag the fusion as carrying a matmul
    res.per_op_mxu_flops["multiply_add_fusion.2"] = 16 * 32 * 2**20 * 1.0

    arch = load_config(arch="v5e", tuned=False).arch
    counters = correlate_counters(
        res, silicon, clock_hz=arch.clock_hz, arch=arch,
    )
    hbm = counters["hbm"]
    assert hbm["op"] == "multiply_add_fusion.2"
    # 256MB / ~408us measured = ~650 GB/s on the v5e — within the chip's
    # physical envelope and of the same order as the modeled stream rate
    assert 400.0 < hbm["real_gbps"] < 900.0
    assert hbm["model_stream_gbps"] == pytest.approx(
        arch.hbm_bandwidth * arch.hbm_efficiency / 1e9, rel=1e-3
    )
    assert 0.5 < hbm["real_vs_model"] < 1.6
    assert counters["mxu"]["op"] == "multiply_add_fusion.2"


def test_engine_fills_per_op_counters():
    from pathlib import Path

    from tpusim.timing.config import SimConfig
    from tpusim.timing.engine import Engine
    from tpusim.trace.hlo_text import parse_hlo_module

    fixtures = Path(__file__).parent / "fixtures"
    mod = parse_hlo_module((fixtures / "tiny_mlp.hlo").read_text())
    res = Engine(SimConfig()).run(mod)
    assert res.per_op_flops.get("dot.1", 0) > 0
    assert res.per_op_mxu_flops.get("dot.1", 0) > 0
    assert res.per_op_hbm_bytes.get("dot.1", 0) > 0


def test_correlate_counters_skips_non_mxu_and_zero_traffic():
    """A VPU-only fusion (flops but no mxu_flops) must not masquerade as
    the MXU check, and zero-traffic entries must not report 0 GB/s as if
    it were a measurement."""
    _require_xplane_support()
    from tpusim.harness.correl_ops import (
        correlate_counters, extract_op_profile,
    )
    from tpusim.timing.config import load_config

    silicon = extract_op_profile(XPLANE_FIXTURE)
    res = _result({
        "multiply_add_fusion.2": (6_500_000.0, 16.0, "fusion"),
    })
    res.per_op_flops["multiply_add_fusion.2"] = 1e9   # VPU flops only
    res.per_op_hbm_bytes["multiply_add_fusion.2"] = 0.0

    arch = load_config(arch="v5e", tuned=False).arch
    counters = correlate_counters(
        res, silicon, clock_hz=arch.clock_hz, arch=arch,
    )
    assert "mxu" not in counters    # no matmul op -> no MXU claim
    assert "hbm" not in counters    # zero bytes -> no bandwidth claim


def test_known_outliers_annotate_but_never_excuse_regressions(tmp_path):
    """The curated outlier list (known.correlation.outliers.list slot):
    matches are annotated, the honest mean is unchanged, and an entry's
    bound stops covering a deviation that regresses past it."""
    from tpusim.harness.correl_ops import (
        match_known_outlier, write_correl_ops,
    )

    outliers = [{
        "workload": "w_known", "reason": "understood: wall-sourced gap",
        "max_abs_error_pct": 30,
    }]
    assert match_known_outlier(outliers, "w_known", abs_error_pct=25.0)
    assert match_known_outlier(outliers, "w_known", abs_error_pct=300.0) \
        is None                        # regressed past its bound
    assert match_known_outlier(outliers, "other", abs_error_pct=25.0) is None

    known = OpCorrelation("w_known", rows=[
        OpRow("a", "fusion", 125.0, 100.0, 1, 1),   # +25%
    ])
    fresh = OpCorrelation("w_new", rows=[
        OpRow("b", "dot", 110.0, 100.0, 1, 1),      # +10%
    ])
    p = write_correl_ops(
        [known, fresh], tmp_path / "c.json", known_outliers=outliers,
    )
    doc = json.loads(p.read_text())
    assert doc["mean_weighted_abs_error_pct"] == pytest.approx(17.5)
    assert doc["mean_excl_known_outliers_pct"] == pytest.approx(10.0)
    by_wl = {w["workload"]: w for w in doc["workloads"]}
    assert "known_outlier" in by_wl["w_known"]
    assert "known_outlier" not in by_wl["w_new"]


def test_load_known_outliers_reads_committed_config():
    from tpusim.harness.correl_ops import load_known_outliers

    outliers = load_known_outliers()
    assert isinstance(outliers, list)
    # the list is currently EMPTY by design: the one entry it carried
    # (wall-sourced elementwise truth) resolved when the live run
    # refreshed every fixture with device-sourced times.  The loader and
    # matcher machinery stay exercised by the synthetic tests above.


def test_known_outlier_edge_cases(tmp_path):
    """Malformed configs degrade to no-outliers; non-finite regressions
    are never excused; a missing workload key never wildcards."""
    import math as _math

    from tpusim.harness.correl_ops import (
        load_known_outliers, match_known_outlier,
    )

    # wrong-shaped but valid JSON -> []
    p = tmp_path / "bad1.json"
    p.write_text('[{"workload": "x"}]')
    assert load_known_outliers(p) == []
    p.write_text('{"outliers": {"workload": "x"}}')
    assert load_known_outliers(p) == []
    p.write_text('{"outliers": ["just-a-string", {"workload": "x"}]}')
    assert load_known_outliers(p) == [{"workload": "x"}]

    bounded = [{"workload": "w", "reason": "r", "max_abs_error_pct": 30}]
    # inf/NaN/unmeasured regressions are the worst case, not covered
    assert match_known_outlier(bounded, "w", abs_error_pct=_math.inf) is None
    assert match_known_outlier(bounded, "w", abs_error_pct=None) is None
    # a typo'd/missing workload key must not match everything
    assert match_known_outlier(
        [{"worklaod": "w", "reason": "r"}], "anything", abs_error_pct=1.0,
    ) is None
    # explicit wildcard still works
    assert match_known_outlier(
        [{"workload": "*", "reason": "r"}], "anything", abs_error_pct=1.0,
    ) == "r"


# the op_profile_out plumbing runs unattended at round end (live bench);
# exercise it off-TPU with measure_device_time stubbed
PROFILE_REUSE_SCRIPT = r"""
import os
os.environ["TPUSIM_FORCE_DEVICE_TIMING"] = "1"

import tpusim.harness.correl_ops as co

def fake_mdt(fn, *args, iters=3, warmup=2, log_dir=None, with_ops=False):
    d = {"median_s": 1e-3, "n_exec": 3.0, "module": "jit_loop"}
    if with_ops:
        d["ops"] = {"dot.1": co.OpSilicon("dot.1", count=3.0,
                                          total_ns=3000.0)}
    return d

co.measure_device_time = fake_mdt

from tpusim.harness.correlate import correlate_workload
from tpusim.models import get_workload

fn, args = get_workload("matmul").build(m=64, n=64, k=64)
prof = {}
pt = correlate_workload(fn, args, name="m", n_steps=2, arch="v5e",
                        iters=3, op_profile_out=prof)
assert pt.real_source == "device", pt.real_source
assert pt.real_seconds == 1e-3 / 2
assert "ops" in prof and "dot.1" in prof["ops"]
assert prof["engine_result"].cycles > 0
assert prof["clock_hz"] > 0 and prof["arch"].name == "v5e"
assert prof["iters"] == 3

# the assembled artifact path the bench child runs
corr = co.correlate_ops(
    prof["engine_result"], prof["ops"], clock_hz=prof["clock_hz"],
    workload="m", real_iters=prof["iters"],
)
corr.counters = co.correlate_counters(
    prof["engine_result"], prof["ops"], clock_hz=prof["clock_hz"],
    arch=prof["arch"],
)
assert isinstance(corr.counters, dict)
print("PROFILE_REUSE_OK")
"""


@pytest.mark.slow
def test_op_profile_reuse_plumbing(cpu_mesh_runner):
    out = cpu_mesh_runner(PROFILE_REUSE_SCRIPT, n_devices=1)
    assert "PROFILE_REUSE_OK" in out
