"""Tests for the cost model + timing engine on the hand-written fixture."""

from pathlib import Path

import pytest

from tpusim.ir import Unit
from tpusim.timing.config import SimConfig
from tpusim.timing.cost import CostModel, dot_dims, while_trip_count
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module, parse_instruction

FIXTURES = Path(__file__).parent / "fixtures"


@pytest.fixture(scope="module")
def tiny_mlp():
    return parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())


@pytest.fixture(scope="module")
def v5p_cfg():
    return SimConfig()  # default arch is v5p


# -- dot dims ---------------------------------------------------------------

def test_dot_dims(tiny_mlp):
    entry = tiny_mlp.entry
    b, m, n, k, dt = dot_dims(entry.op("dot.1"), entry)
    assert (b, m, n, k) == (1, 128, 256, 512)
    assert dt == "bf16"


def test_mxu_cycles_big_matmul_near_peak(v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    m = n = k = 4096
    cycles = cm.mxu_cycles(1, m, n, k, "bf16")
    ideal = 2.0 * m * n * k / v5p_cfg.arch.mxu_flops_per_cycle
    # fill/drain overhead keeps us within ~5% of ideal for big shapes
    assert ideal <= cycles <= ideal * 1.1


def test_mxu_cycles_small_matmul_inefficient(v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    cycles = cm.mxu_cycles(1, 8, 8, 8, "bf16")
    ideal = 2.0 * 8 * 8 * 8 / v5p_cfg.arch.mxu_flops_per_cycle
    assert cycles > 50 * ideal  # tiny matmuls waste the systolic array


def test_int8_faster_than_bf16(v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    assert cm.mxu_cycles(1, 1024, 1024, 1024, "s8") < cm.mxu_cycles(
        1, 1024, 1024, 1024, "bf16"
    )


def test_while_trip_count():
    op = parse_instruction(
        "%w = f32[8]{0} while(%init), condition=%cond, body=%body, "
        'backend_config={"known_trip_count":{"n":"12"}}'
    )
    assert while_trip_count(op) == 12
    op2 = parse_instruction(
        "%w2 = f32[8]{0} while(%init), condition=%cond, body=%body"
    )
    assert while_trip_count(op2, default=3) == 3


# -- op costs ---------------------------------------------------------------

def test_dot_cost_compute_bound(tiny_mlp, v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    entry = tiny_mlp.entry
    c = cm.op_cost(entry.op("dot.1"), entry, tiny_mlp)
    assert c.unit == Unit.MXU
    assert c.flops == 2 * 128 * 256 * 512
    assert c.cycles > 0
    assert c.hbm_bytes == (128 * 512 + 512 * 256 + 128 * 256) * 2


def test_fusion_cost_aggregates_inner(tiny_mlp, v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    entry = tiny_mlp.entry
    c = cm.op_cost(entry.op("relu.1"), entry, tiny_mlp)
    assert c.unit == Unit.VPU
    # fused max + broadcast over 128x256 elements
    assert c.flops >= 128 * 256
    # memory-bound: reads + writes 128x256 bf16
    assert c.hbm_bytes == 2 * 128 * 256 * 2


def test_free_ops_cost_nothing(tiny_mlp, v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    entry = tiny_mlp.entry
    c = cm.op_cost(entry.op("x"), entry, tiny_mlp)
    assert c.cycles == 0


def test_collective_payload(tiny_mlp, v5p_cfg):
    cm = CostModel(v5p_cfg.arch)
    entry = tiny_mlp.entry
    c = cm.op_cost(entry.op("ar-start"), entry, tiny_mlp)
    assert c.unit == Unit.ICI
    assert c.ici_bytes == 128 * 256 * 2


# -- engine -----------------------------------------------------------------

def test_engine_runs_fixture(tiny_mlp, v5p_cfg):
    res = Engine(v5p_cfg).run(tiny_mlp)
    assert res.cycles > 0
    assert res.seconds == pytest.approx(
        res.cycles / v5p_cfg.arch.clock_hz
    )
    assert res.collective_count == 1
    assert res.ici_bytes == 128 * 256 * 2
    # both dots' flops accounted
    assert res.mxu_flops == 2 * 128 * 256 * 512 + 2 * 128 * 64 * 256


def test_engine_overlap_beats_serial(tiny_mlp):
    ov = Engine(SimConfig(overlap_collectives=True)).run(tiny_mlp)
    ser = Engine(
        SimConfig(overlap_collectives=False)
    ).run(tiny_mlp)
    # fixture has compute after the all-reduce-done, so overlap gain is
    # bounded, but serial must never be faster
    assert ser.cycles >= ov.cycles
    assert ser.exposed_collective_cycles >= ov.exposed_collective_cycles


def test_engine_timeline(tiny_mlp, v5p_cfg):
    eng = Engine(v5p_cfg, record_timeline=True)
    res = eng.run(tiny_mlp)
    names = [e.name for e in res.timeline]
    assert "dot.1" in names and "ar-start" in names
    for e in res.timeline:
        assert e.end_cycle >= e.start_cycle >= 0


def test_engine_while_loop(v5p_cfg):
    text = """
HloModule loop_test, is_scheduled=true

%body (p: f32[1024,1024]) -> f32[1024,1024] {
  %p = f32[1024,1024]{1,0} parameter(0)
  ROOT %dotb = f32[1024,1024]{1,0} dot(%p, %p), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

%cond (p2: f32[1024,1024]) -> pred[] {
  %p2 = f32[1024,1024]{1,0} parameter(0)
  ROOT %lt = pred[] constant(true)
}

ENTRY %main (x: f32[1024,1024]) -> f32[1024,1024] {
  %x = f32[1024,1024]{1,0} parameter(0)
  ROOT %w = f32[1024,1024]{1,0} while(%x), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"10"}}
}
"""
    mod = parse_hlo_module(text)
    res = Engine(v5p_cfg).run(mod)
    single = """
HloModule one, is_scheduled=true

ENTRY %main (x: f32[1024,1024]) -> f32[1024,1024] {
  %x = f32[1024,1024]{1,0} parameter(0)
  ROOT %d = f32[1024,1024]{1,0} dot(%x, %x), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    one = Engine(v5p_cfg).run(parse_hlo_module(single))
    assert res.cycles == pytest.approx(10 * one.cycles, rel=0.15)
    assert res.flops == pytest.approx(10 * one.flops, rel=1e-6)


def test_stats_dict(tiny_mlp, v5p_cfg):
    res = Engine(v5p_cfg).run(tiny_mlp)
    d = res.stats_dict()
    assert d["sim_cycles"] == res.cycles
    assert d["collective_count"] == 1
    assert "busy_cycles_mxu" in d


# -- conv dims: true convs vs XLA's matmul-as-dilated-conv lowering ---------

def _conv_module(window: str, dim_labels: str | None,
                 lhs: str = "bf16[4,1024,8,128]",
                 rhs: str = "bf16[4,1024,8,128]",
                 out: str = "bf16[4,8,1024,1024]") -> str:
    dl = f", dim_labels={dim_labels}" if dim_labels else ""
    return f"""
HloModule convs, is_scheduled=true

ENTRY %main (a: {lhs}, b: {rhs}) -> {out} {{
  %a = {lhs} parameter(0)
  %b = {rhs} parameter(1)
  ROOT %conv = {out} convolution(%a, %b), window={{{window}}}{dl}
}}
"""


def test_conv_dims_degenerate_batch_matmul():
    """XLA:TPU lowers batched matmuls to convolution-base-dilated with
    stride==size and lhs_dilate chosen so each output position hits exactly
    one real tap per spatial dim (observed in the round-3 attention silicon
    fixture, reports/silicon/attention_1chip).  K must be head_dim, not
    head_dim x prod(window size) — the +3169% bug."""
    from tpusim.timing.cost import conv_dims

    mod = parse_hlo_module(_conv_module(
        "size=4x8 stride=4x8 pad=3_3x7_7 lhs_dilate=3x7 rhs_reversal=1x1",
        "0b1f_0o1i->01fb",
    ))
    op = mod.entry.op("conv")
    b, m, n, k, dt = conv_dims(op, mod.entry)
    assert k == 128                      # head_dim only: one real tap/dim
    assert n == 1024
    assert m == 4 * 8 * 1024
    flops = 2.0 * b * m * n * k
    assert flops == pytest.approx(2 * 32 * 1024 * 1024 * 128)  # true matmul


def test_conv_dims_true_conv_same_padding():
    """A plain 3x3 SAME conv charges ~9 taps in the interior, trimmed at
    the edges (exact counting, not the full-kernel upper bound)."""
    from tpusim.timing.cost import conv_dims

    mod = parse_hlo_module(_conv_module(
        "size=3x3 pad=1_1x1_1",
        "b01f_01io->b01f",
        lhs="bf16[16,56,56,64]", rhs="bf16[3,3,64,64]",
        out="bf16[16,56,56,64]",
    ))
    op = mod.entry.op("conv")
    b, m, n, k, dt = conv_dims(op, mod.entry)
    # avg taps/dim = (2 + 3*54 + 2)/56; K = round(taps^2 * 64)
    taps = (2 + 3 * 54 + 2) / 56
    assert k == round(taps * taps * 64)
    assert 0.9 * 9 * 64 < k < 9 * 64    # trimmed, but near the full kernel
    assert n == 64
    assert m == 16 * 56 * 56


def test_conv_dims_missing_dim_labels_charges_full_kernel():
    """Unparseable dim_labels must fall back to the full kernel extent
    (the conservative pre-round-4 charge), not collapse the spatial
    factor to 1."""
    from tpusim.timing.cost import conv_dims

    mod = parse_hlo_module(_conv_module(
        "size=3x3 pad=1_1x1_1", None,
        lhs="bf16[16,56,56,64]", rhs="bf16[3,3,64,64]",
        out="bf16[16,56,56,64]",
    ))
    op = mod.entry.op("conv")
    _, _, _, k, _ = conv_dims(op, mod.entry)
    assert k == 9 * 64                   # in_feat fallback x prod(size)


def test_avg_real_taps_trims_high_edge():
    """pad=0_N windows run off the high edge; those taps must be trimmed
    just like low-edge ones (fast path must not trigger)."""
    from tpusim.timing.cost import _avg_real_taps

    # in=10, k=2, stride=1, no pad_low: last output reads past the end
    assert _avg_real_taps(10, 10, 2, 1, 0, 1, 1) == pytest.approx(1.9)
    # fully interior: fast path, every tap real
    assert _avg_real_taps(10, 9, 2, 1, 0, 1, 1) == 2.0


def test_parse_window_negative_pad():
    """XLA emits negative pads (conv gradients); they must parse, not be
    silently dropped to 0."""
    from tpusim.timing.cost import _parse_window

    w = _parse_window("size=3 pad=-1_-1", 1)
    assert w["pad"] == [(-1, -1)]


def test_gather_charges_per_row_descriptor_overhead(v5p_cfg):
    """A scattered gather pays a per-row DMA descriptor cost the streaming
    roofline can't see (embedding fixture read -50% without it)."""
    from tpusim.timing.cost import CostModel

    text = """
HloModule g, is_scheduled=true

ENTRY %main (t: bf16[131072,1024], ids: s32[8192]) -> bf16[8192,1024] {
  %t = bf16[131072,1024]{1,0} parameter(0)
  %ids = s32[8192]{0} parameter(1)
  ROOT %g = bf16[8192,1024]{1,0} gather(%t, %ids), offset_dims={1}, collapsed_slice_dims={0}, start_index_map={0}, index_vector_dim=1, slice_sizes={1,1024}
}
"""
    mod = parse_hlo_module(text)
    comp = mod.entry
    cm = CostModel(v5p_cfg.arch)
    c = cm._compute_cost(comp.op("g"), comp, mod)
    assert c.compute_cycles == pytest.approx(
        8192 * v5p_cfg.arch.gather_row_overhead_cycles
    )
    # region scoping still caps the memory side at the moved rows
    full = cm.op_cost(comp.op("g"), comp, mod)
    assert full.hbm_bytes <= 2 * 2 * 8192 * 1024 + 8192 * 4


def _scatter_text(idx_shape: str, attrs: str) -> str:
    return f"""
HloModule s, is_scheduled=true

%add_f32 (a: f32[], b: f32[]) -> f32[] {{
  %a = f32[]{{:T(128)}} parameter(0)
  %b = f32[]{{:T(128)}} parameter(1)
  ROOT %r = f32[]{{:T(128)}} add(%a, %b)
}}

ENTRY %main (t: f32[16384,256], ids: {idx_shape}, upd: f32[1024,256]) -> f32[16384,256] {{
  %t = f32[16384,256]{{1,0}} parameter(0)
  %ids = {idx_shape} parameter(1)
  %upd = f32[1024,256]{{1,0}} parameter(2)
  ROOT %s = f32[16384,256]{{1,0}} scatter(%t, %ids, %upd), {attrs}, to_apply=%add_f32
}}
"""


def test_scatter_rows_use_index_vector_dim(v5p_cfg):
    """The scatter descriptor count divides out the dimension
    ``index_vector_dim`` names, not blindly the trailing one (a
    leading-coordinate layout would undercount rows 1024/3 -> 341)."""
    cm = CostModel(v5p_cfg.arch)

    # coordinate vectors on the LEADING dim: s32[2,1024] with
    # index_vector_dim=0 is 1024 rows of 2-coordinates each
    mod = parse_hlo_module(_scatter_text(
        "s32[2,1024]{1,0}",
        "update_window_dims={1}, inserted_window_dims={0}, "
        "scatter_dims_to_operand_dims={0}, index_vector_dim=0",
    ))
    comp = mod.entry
    c = cm._compute_cost(comp.op("s"), comp, mod)
    assert c.compute_cycles == pytest.approx(
        1024 * v5p_cfg.arch.gather_row_overhead_cycles
    )

    # index_vector_dim == rank: every element is a scalar row index —
    # nothing is divided out (s32[1024] -> 1024 rows)
    mod = parse_hlo_module(_scatter_text(
        "s32[1024]{0}",
        "update_window_dims={1}, inserted_window_dims={0}, "
        "scatter_dims_to_operand_dims={0}, index_vector_dim=1",
    ))
    comp = mod.entry
    c = cm._compute_cost(comp.op("s"), comp, mod)
    assert c.compute_cycles == pytest.approx(
        1024 * v5p_cfg.arch.gather_row_overhead_cycles
    )

    # attr absent: the trailing-dim fallback still applies (rank >= 2)
    mod = parse_hlo_module(_scatter_text(
        "s32[1024,1]{1,0}",
        "update_window_dims={1}, inserted_window_dims={0}, "
        "scatter_dims_to_operand_dims={0}",
    ))
    comp = mod.entry
    c = cm._compute_cost(comp.op("s"), comp, mod)
    assert c.compute_cycles == pytest.approx(
        1024 * v5p_cfg.arch.gather_row_overhead_cycles
    )


# -- DUS param-read cap: the whole chase chain must be private ---------------

_DUS_SIBLING_READER_TEXT = """HloModule m, is_scheduled=true

%fused (param_0: bf16[4096,1024], param_1: bf16[1,1024], param_2: s32[]) -> (bf16[4096,1024], bf16[]) {
  %param_0 = bf16[4096,1024]{1,0:T(8,128)(2,1)} parameter(0)
  %param_1 = bf16[1,1024]{1,0:T(8,128)(2,1)} parameter(1)
  %param_2 = s32[]{:T(128)} parameter(2)
  %zero = s32[]{:T(128)} constant(0)
  %view = bf16[4096,1024]{1,0:T(8,128)(2,1)} bitcast(%param_0)
  %red = bf16[]{:T(128)} bitcast(%view)
  %dus = bf16[4096,1024]{1,0:T(8,128)(2,1)} dynamic-update-slice(%view, %param_1, %param_2, %zero)
  ROOT %t = (bf16[4096,1024]{1,0:T(8,128)(2,1)}, bf16[]{:T(128)}) tuple(%dus, %red)
}

ENTRY %main (p0: bf16[4096,1024], p1: bf16[1,1024], p2: s32[]) -> (bf16[4096,1024], bf16[]) {
  %p0 = bf16[4096,1024]{1,0:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[1,1024]{1,0:T(8,128)(2,1)} parameter(1)
  %p2 = s32[]{:T(128)} parameter(2)
  ROOT %c = (bf16[4096,1024]{1,0:T(8,128)(2,1)}, bf16[]{:T(128)}) fusion(%p0, %p1, %p2), kind=kLoop, calls=%fused
}
"""


def test_dus_param_cap_blocked_by_chain_sibling_reader(v5p_cfg):
    """An intermediate view on the DUS destination chase chain that also
    feeds a sibling op means the kernel reads the FULL carried buffer;
    the param-read cap must not apply (before the fix only the
    parameter's own consumers were checked, so a bitcast feeding both
    the DUS and a reduce still capped the read at the update region)."""
    cm = CostModel(v5p_cfg.arch)
    mod = parse_hlo_module(_DUS_SIBLING_READER_TEXT)
    comp = mod.entry
    cost = cm.op_cost(comp.op("c"), comp, mod)
    full = 4096 * 1024 * 2  # the carried bf16 buffer
    # the full carry is read through %red's view: traffic must be at
    # least one full-buffer read, not the ~2KB update region
    assert cost.hbm_bytes + cost.vmem_bytes >= full


def test_small_kernel_floor_band_is_floored(v5p_cfg):
    """The dispatch floor binds through the whole <=32KB-region band
    (cost.py _SMALL_KERNEL_REGION_BYTES; the 2x at the use site mirrors
    _region_bytes' read+write doubling): a 24KB-region slice prices at
    the floor, a 1MB-region slice at its (larger) roofline."""
    from tpusim.timing.config import SimConfig

    cfg = SimConfig()
    a = cfg.arch
    floor = a.small_kernel_floor_cycles
    assert floor > 0
    cm = CostModel(a)

    def slice_cost(rows: int) -> float:
        text = f"""
HloModule m, is_scheduled=true

ENTRY %main (p0: f32[131072,128]) -> f32[{rows},128] {{
  %p0 = f32[131072,128]{{1,0:T(8,128)}} parameter(0)
  ROOT %c = f32[{rows},128]{{1,0:T(8,128)}} slice(%p0), slice={{[0:{rows}], [0:128]}}
}}
"""
        mod = parse_hlo_module(text)
        comp = mod.entry
        return cm.op_cost(comp.op("c"), comp, mod).cycles

    in_band = slice_cost(48)      # 48*128*4 = 24KB region
    assert in_band >= floor
    big = slice_cost(2048)        # 1MB region: roofline-priced
    roofline = 2.0 * 2048 * 128 * 4 / a.hbm_bytes_per_cycle
    assert big >= roofline
    assert big > in_band  # the floor never lowers a roofline price
