"""tpusim.analysis.critpath — critical path, slack, and exposed
communication over the dataflow def-use chains.

Pins the contracts the TL5xx perf-lint family stands on:

1. **the three-way inequality** — per module per arch across the full
   fixture + silicon corpus, ``critical_path <= engine total <=
   serial op-cost sum`` (the analyzer's lower/upper bounds bracket the
   engine's serial walk, priced with the SAME composed config);
2. **exposure accounting** — every collective's exposed cycles never
   exceed its priced cycles, per record and per computation;
3. **DAG semantics** — slack arithmetic on a diamond, async
   start/done halves spanning issue windows, while/call composition;
4. **the advise column** — ``exposed_comm_frac`` equals a direct
   ``analyze_module_perf`` of the exact scaled module each cell
   prices plus the synthesized standalone collectives (the ranked
   table and the analyzer can never disagree);
5. **streaming discipline** — perf lint on a streaming-scale trace
   holds the bounded-RSS contract (bounds vs the engine are NOT
   asserted in streaming mode: without the module in hand the
   builder cannot recover backend_config trip counts, a documented
   limitation in :mod:`tpusim.analysis.critpath`).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tpusim.analysis.critpath import analyze_module_perf, module_perf_doc
from tpusim.timing.config import load_config
from tpusim.timing.engine import Engine
from tpusim.trace.format import load_trace
from tpusim.trace.hlo_text import parse_hlo_module

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "traces"
SILICON = REPO / "reports" / "silicon"
LLAMA = FIXTURES / "llama_tiny_tp2dp2"


def _corpus_dirs() -> list[Path]:
    dirs = [FIXTURES / "llama_tiny_tp2dp2", FIXTURES / "matmul_512"]
    if SILICON.is_dir():
        dirs += sorted(
            d for d in SILICON.iterdir() if (d / "modules").is_dir()
        )
    return dirs


def _cfg(arch: str = "v5e"):
    return load_config(arch=arch, tuned=False)


# ---------------------------------------------------------------------------
# The corpus inequality: critical path <= engine <= serial sum
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("arch", ["v5e", "v5p"])
@pytest.mark.parametrize(
    "trace_dir", _corpus_dirs(), ids=lambda d: d.name,
)
def test_corpus_inequality(trace_dir, arch):
    """The analyzer's bounds bracket the engine on every committed
    module: the weighted-DAG critical path can never exceed what the
    engine's serial walk measured, and that walk can never exceed the
    serial sum of per-op contributions."""
    cfg = _cfg(arch)
    pod = load_trace(trace_dir)
    assert pod.modules, trace_dir
    for name, mod in sorted(pod.modules.items()):
        mp = analyze_module_perf(mod, cfg)
        eng = Engine(cfg).run(mod).cycles
        tol = 1e-6 * max(eng, 1.0)
        assert mp.critical_path_cycles <= eng + tol, (
            f"{trace_dir.name}/{name}@{arch}: critical path "
            f"{mp.critical_path_cycles} > engine {eng}"
        )
        assert eng <= mp.serial_cycles + tol, (
            f"{trace_dir.name}/{name}@{arch}: engine {eng} > "
            f"serial bound {mp.serial_cycles}"
        )
        # exposure accounting: exposed <= priced, per record and
        # rolled up per computation
        for cp in mp.comps.values():
            assert cp.exposed_collective_cycles <= (
                cp.collective_cycles + tol
            )
            for e in cp.exposures:
                assert -tol <= e.exposed_cycles <= e.priced_cycles + tol
                assert e.overlapped_cycles >= -tol


# ---------------------------------------------------------------------------
# DAG semantics
# ---------------------------------------------------------------------------

_DIAMOND = """HloModule diamond, is_scheduled=true

ENTRY %main (p0: f32[512,512]) -> f32[512,512] {
  %p0 = f32[512,512]{1,0} parameter(0)
  %d1 = f32[512,512]{1,0} dot(%p0, %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d2 = f32[512,512]{1,0} dot(%d1, %d1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %b = f32[512,512]{1,0} negate(%p0)
  ROOT %join = f32[512,512]{1,0} add(%d2, %b)
}
"""


def test_diamond_slack():
    """Two chains joining: the long (dot) arm is the critical path
    with zero slack, the short (negate) arm's slack is exactly how
    much later it could finish without moving the join."""
    mp = analyze_module_perf(parse_hlo_module(_DIAMOND), _cfg())
    cp = next(iter(mp.comps.values()))
    ops = {o.name: o for o in cp.ops}
    assert {"d1", "d2", "b", "join"} <= set(ops)
    for n in ("d1", "d2", "join"):
        assert ops[n].on_critical_path, n
        assert ops[n].slack == pytest.approx(0.0, abs=1e-6), n
    assert not ops["b"].on_critical_path
    assert ops["b"].slack == pytest.approx(
        ops["d2"].finish - ops["b"].finish
    )
    assert cp.critical_path_cycles == pytest.approx(
        max(o.finish for o in cp.ops)
    )
    # every slack is non-negative and the chain is anchored op-by-op
    assert all(o.slack >= -1e-6 for o in cp.ops)
    assert [n for n, _, _ in cp.critical_ops][-1] == "join"


_ASYNC_TMPL = """HloModule ac, is_scheduled=true, num_partitions=4

%r (a: f32[], b: f32[]) -> f32[] {{
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}}

ENTRY %main (p0: f32[2097152], p1: f32[1024,1024]) -> f32[2097152] {{
  %p0 = f32[2097152]{{0}} parameter(0)
  %p1 = f32[1024,1024]{{1,0}} parameter(1)
  %st = f32[2097152]{{0}} all-reduce-start(%p0), channel_id=1, replica_groups={{{{0,1,2,3}}}}, to_apply=%r
{overlap}  %dn = f32[2097152]{{0}} all-reduce-done(%st)
  ROOT %out = f32[2097152]{{0}} add(%dn, %dn)
}}
"""

_DOT_LINE = (
    "  %dot = f32[1024,1024]{1,0} dot(%p1, %p1), "
    "lhs_contracting_dims={1}, rhs_contracting_dims={0}\n"
)


def test_async_halves_span_issue_window():
    """The start/done halves are zero-width edges spanning the
    transfer: compute issued inside the window covers the collective,
    so the exposed share drops by exactly the covered cycles and the
    critical path shortens vs. the empty-window variant."""
    cfg = _cfg()
    bare = analyze_module_perf(
        parse_hlo_module(_ASYNC_TMPL.format(overlap="")), cfg,
    )
    lapped = analyze_module_perf(
        parse_hlo_module(_ASYNC_TMPL.format(overlap=_DOT_LINE)), cfg,
    )
    e0 = next(iter(bare.comps.values())).exposures[0]
    e1 = next(iter(lapped.comps.values())).exposures[0]
    assert e0.priced_cycles == pytest.approx(e1.priced_cycles)
    assert e1.exposed_cycles < e0.exposed_cycles
    assert e1.overlapped_cycles > e0.overlapped_cycles
    # the hidden dot does not extend the path: the window absorbs it
    assert lapped.critical_path_cycles <= (
        bare.critical_path_cycles
        + next(iter(lapped.comps.values())).ops[0].cycles * 1e-6
        + 1e-6
    )
    assert lapped.exposed_collective_cycles < \
        bare.exposed_collective_cycles


_WHILE_TMPL = """HloModule wh, is_scheduled=true

%body (p: f32[512,512]) -> f32[512,512] {{
  %p = f32[512,512]{{1,0}} parameter(0)
  ROOT %d = f32[512,512]{{1,0}} dot(%p, %p), lhs_contracting_dims={{1}}, rhs_contracting_dims={{0}}
}}

%cond (q: f32[512,512]) -> pred[] {{
  %q = f32[512,512]{{1,0}} parameter(0)
  ROOT %t = pred[] constant(true)
}}

ENTRY %main (p0: f32[512,512]) -> f32[512,512] {{
  %p0 = f32[512,512]{{1,0}} parameter(0)
  ROOT %w = f32[512,512]{{1,0}} while(%p0), condition=%cond, body=%body, backend_config={{"known_trip_count":{{"n":"{trips}"}}}}
}}
"""


def test_while_call_composition():
    """Loop composition matches the engine's scaling: the critical
    path grows with the declared trip count, and the inequality
    brackets the engine at both counts."""
    cfg = _cfg()
    totals = {}
    for trips in (1, 8):
        mod = parse_hlo_module(_WHILE_TMPL.format(trips=trips))
        mp = analyze_module_perf(mod, cfg)
        eng = Engine(cfg).run(mod).cycles
        tol = 1e-6 * eng
        assert mp.critical_path_cycles <= eng + tol
        assert eng <= mp.serial_cycles + tol
        totals[trips] = mp.critical_path_cycles
    assert totals[8] > 4 * totals[1]


def test_module_doc_shape():
    """``module_perf_doc`` carries the documented schema the
    ``lint --format json --perf`` / ``perf-report --format json``
    consumers parse."""
    mp = analyze_module_perf(parse_hlo_module(_DIAMOND), _cfg())
    doc = module_perf_doc(mp)
    for k in ("module", "entry", "critical_path_cycles",
              "serial_cycles", "collective_cycles",
              "exposed_collective_cycles", "computations"):
        assert k in doc, k
    assert doc["computations"]
    comp = next(iter(doc["computations"].values()))
    for k in ("critical_path_cycles", "serial_cycles",
              "op_count", "dominant_bound", "bound_cycles",
              "critical_path", "ops", "exposures"):
        assert k in comp, k
    assert comp["critical_path"], "critical chain must be non-empty"
    for step in comp["critical_path"]:
        assert {"op", "opcode", "cycles"} <= set(step)
    for row in comp["ops"]:
        assert row["slack"] >= -1e-6
    json.dumps(doc)  # must be serializable as-is


# ---------------------------------------------------------------------------
# The advise column: exposed_comm_frac == the analyzer on the exact
# scaled module each cell prices
# ---------------------------------------------------------------------------


def test_advise_exposed_comm_frac_matches_analyzer():
    from tpusim.advise import (
        build_profile, run_advise, scaled_module,
    )
    from tpusim.advise.transform import build_cell_pod
    from tpusim.ici.detailed import make_collective_model
    from tpusim.ici.topology import torus_for
    from tpusim.ir import CommandKind

    pod = load_trace(LLAMA)
    profile = build_profile(pod)
    base = pod.modules[profile.module_name]
    res = run_advise({
        "name": "pin",
        "strategies": ["dp", "tp", "dp_tp"],
        "slices": [{"arch": "v5p", "chips": 8}],
        "tuned": False,
    }, pod=pod)
    cells = res.doc["cells"]
    assert cells
    for r in cells:
        assert "exposed_comm_frac" in r
        cfg = load_config(
            arch=r["arch"], overlays=[{"power_enabled": True}],
            tuned=False,
        )
        factor = profile.chips0 / float(r["chips"] * r["launches"])
        compute = scaled_module(
            base, factor, f"pin_{factor!r}", profile.capture_fp,
        )
        topo = torus_for(r["chips"], cfg.arch.name)
        module_exposed = analyze_module_perf(
            compute, cfg, topology=topo,
        ).exposed_collective_cycles
        cell_pod = build_cell_pod(
            profile, compute, r["chips"], dict(r["mesh"]),
            launches=r["launches"],
        )
        coll = make_collective_model(topo, cfg.arch.ici)
        launches = 0
        cmd_cycles = 0.0
        for c in cell_pod.devices[0].commands:
            if c.kind == CommandKind.KERNEL_LAUNCH:
                launches += 1
            elif c.kind == CommandKind.COLLECTIVE and \
                    c.collective is not None:
                cmd_cycles += cfg.arch.seconds_to_cycles(
                    coll.seconds(c.collective, float(c.nbytes))
                )
        step_cycles = r["step_ms"] / 1e3 * cfg.arch.clock_hz
        want = (
            module_exposed * max(launches, 1) + cmd_cycles
        ) / step_cycles
        assert r["exposed_comm_frac"] == pytest.approx(want), r["cell"]
        assert math.isfinite(r["exposed_comm_frac"])
        assert r["exposed_comm_frac"] >= 0.0


# ---------------------------------------------------------------------------
# CLI: perf-report end to end
# ---------------------------------------------------------------------------


def test_perf_report_cli_text_and_json():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tpusim", "perf-report",
         str(FIXTURES / "matmul_512"), "--top", "3"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "critical path" in proc.stdout
    jproc = subprocess.run(
        [sys.executable, "-m", "tpusim", "perf-report",
         str(FIXTURES / "matmul_512"), "--format", "json"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert jproc.returncode == 0, jproc.stderr[-2000:]
    doc = json.loads(jproc.stdout)
    assert doc["perf"], "json report must carry the perf documents"
    perf0 = doc["perf"][0]
    assert perf0["computations"]
    assert any(
        d["code"] == "TL500" for d in doc["diagnostics"]
    ), "the opt-in summary diagnostic must ride along"


# ---------------------------------------------------------------------------
# Streaming: bounded RSS with --perf on a streaming-scale trace
# ---------------------------------------------------------------------------

_PERF_RSS_SNIPPET = r'''
import json, resource, sys
from tpusim.analysis import analyze_trace_dir

if sys.argv[1] == "--baseline":
    print(json.dumps({
        "peak_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
    }))
    raise SystemExit(0)
diags = analyze_trace_dir(sys.argv[1], arch="v5e", tuned=False,
                          perf=True)
print(json.dumps({
    "peak_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
    "errors": sum(1 for d in diags.items
                  if d.severity.value == "error"),
    "tl500": sum(1 for d in diags.items if d.code == "TL500"),
}))
'''


@pytest.mark.slow
def test_streaming_perf_lint_bounded_rss(tmp_path):
    """``lint --perf`` on a streaming-scale trace walks the deferred
    per-computation feed without materializing the module: the added
    RSS stays well below the trace size and the TL500 summary still
    lands.  Deliberately NOT asserted: bounds vs. the engine — the
    streaming builder prices loop trips from the config default (it
    never holds the module needed for backend_config recovery), a
    limitation pinned in the critpath docstring."""
    from test_dataflow import _write_big_trace

    tdir = tmp_path / "giant"
    hlo = _write_big_trace(tdir, n_comps=100, n_ops=1000)
    size = hlo.stat().st_size
    assert size >= 64 * 1024 * 1024, f"generator produced {size} bytes"

    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("TPUSIM_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    base = subprocess.run(
        [sys.executable, "-c", _PERF_RSS_SNIPPET, "--baseline"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert base.returncode == 0, base.stderr[-2000:]
    baseline = json.loads(
        base.stdout.strip().splitlines()[-1]
    )["peak_kb"] * 1024

    proc = subprocess.run(
        [sys.executable, "-c", _PERF_RSS_SNIPPET, str(tdir)],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["errors"] == 0
    assert out["tl500"] >= 1
    peak = out["peak_kb"] * 1024
    assert peak - baseline < 0.35 * size, (
        f"streaming perf lint added {(peak - baseline) / 1e6:.0f} MB "
        f"over the {baseline / 1e6:.0f} MB import floor — not well "
        f"below the {size / 1e6:.0f} MB trace"
    )
