"""tpusim.analysis.dataflow — the whole-trace dataflow engine.

Pins the three contracts the semantic passes stand on:

1. **engine agreement** — static per-space liveness (vmem residency
   sum + peak-live bytes) equals the engine's own capacity-model walk
   byte-for-byte across the committed fixture + silicon corpus;
2. **def-use / schedule chains** — undefined and misordered operands
   surface exactly once each, and liveness intervals cover def→last-use
   with alias extension;
3. **collective matching** — the TL41x matcher aligns staggered
   disjoint groups without false positives and reports a genuine
   stall deterministically;

plus the streaming-lint discipline: analysis through the deferred
per-computation walk is byte-identical to the eager walk, and lint on
a streaming-scale module holds the bounded-RSS contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tpusim.analysis.dataflow import analyze_module
from tpusim.trace.hlo_text import parse_hlo_module

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "traces"
SILICON = REPO / "reports" / "silicon"


def _corpus_dirs() -> list[Path]:
    dirs = [FIXTURES / "llama_tiny_tp2dp2", FIXTURES / "matmul_512"]
    if SILICON.is_dir():
        dirs += sorted(
            d for d in SILICON.iterdir() if (d / "modules").is_dir()
        )
    return dirs


# ---------------------------------------------------------------------------
# Engine agreement (acceptance criterion: static peaks == measured
# residency on the fixture corpus)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "trace_dir", _corpus_dirs(), ids=lambda d: d.name,
)
def test_liveness_agrees_with_engine(trace_dir):
    from tpusim.timing.engine import (
        _vmem_peak_live_bytes, _vmem_resident_bytes,
    )
    from tpusim.trace.format import load_trace

    pod = load_trace(trace_dir)
    assert pod.modules
    for name, module in pod.modules.items():
        df = analyze_module(module)
        assert df.alloc_total("vmem") == _vmem_resident_bytes(module), (
            f"{trace_dir.name}/{name}: residency sum diverged"
        )
        assert df.peak_live("vmem") == _vmem_peak_live_bytes(module), (
            f"{trace_dir.name}/{name}: peak-live diverged"
        )
        # HBM peaks are positive for any module with real buffers and
        # bounded by the conservative allocation sum
        assert 0 < df.peak_live("hbm") <= df.alloc_total("hbm")


def test_analyze_module_memoizes_on_the_module():
    mod = parse_hlo_module(
        "HloModule m\n\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  ROOT %r = f32[8]{0} negate(%p0)\n"
        "}\n",
        name_hint="m",
    )
    assert analyze_module(mod) is analyze_module(mod)


# ---------------------------------------------------------------------------
# Def-use chains + intervals
# ---------------------------------------------------------------------------


def test_def_use_chains_and_schedule_defects():
    from tpusim.analysis.dataflow import ModuleDataflowBuilder

    mod = parse_hlo_module(
        "HloModule m\n\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  %a = f32[8]{0} add(%p0, %b)\n"       # %b used before def
        "  %b = f32[8]{0} negate(%p0)\n"
        "  ROOT %r = f32[8]{0} add(%a, %ghost)\n"  # %ghost undefined
        "}\n",
        name_hint="m",
    )
    comp = mod.entry
    cdf = ModuleDataflowBuilder().feed(comp, is_entry=True)
    assert not cdf.schedule_ok
    assert cdf.undefined == [(3, "ghost")]
    assert cdf.misordered == [(1, "b", 2)]
    assert cdf.defs["a"] == 1
    assert cdf.uses["p0"] == [1, 2]
    assert cdf.uses["a"] == [3]


def test_liveness_intervals_cover_def_to_last_use():
    from tpusim.analysis.dataflow import ModuleDataflowBuilder

    mod = parse_hlo_module(
        "HloModule m\n\n"
        "ENTRY %main (p0: f32[1024]) -> f32[1024] {\n"
        "  %p0 = f32[1024]{0} parameter(0)\n"
        "  %a = f32[1024]{0} negate(%p0)\n"
        "  %b = f32[1024]{0} negate(%a)\n"
        "  ROOT %r = f32[1024]{0} add(%b, %b)\n"
        "}\n",
        name_hint="m",
    )
    cdf = ModuleDataflowBuilder().feed(mod.entry, is_entry=True)
    spans = {iv.name: (iv.start, iv.end) for iv in cdf.intervals
             if iv.space == "hbm"}
    assert spans["p0"] == (0, 1)     # param dies at %a
    assert spans["a"] == (1, 2)      # dies at %b
    assert spans["b"] == (2, 3)      # dies at the root
    # 4 KiB buffers: peak is two concurrently-live (operand + result)
    assert cdf.summary.local_peak["hbm"] == 2 * 4096
    assert cdf.summary.alloc["hbm"] == 4 * 4096


def test_alias_extension_keeps_source_alive():
    """A get-tuple-element alias extends its operand's lifetime: the
    underlying buffer lives until the alias's own last use."""
    from tpusim.analysis.dataflow import ModuleDataflowBuilder

    mod = parse_hlo_module(
        "HloModule m\n\n"
        "ENTRY %main (p0: f32[1024]) -> f32[1024] {\n"
        "  %p0 = f32[1024]{0} parameter(0)\n"
        "  %t = (f32[1024]{0}) tuple(%p0)\n"
        "  %g = f32[1024]{0} get-tuple-element(%t), index=0\n"
        "  %x = f32[1024]{0} negate(%p0)\n"
        "  ROOT %r = f32[1024]{0} add(%g, %x)\n"
        "}\n",
        name_hint="m",
    )
    cdf = ModuleDataflowBuilder().feed(mod.entry, is_entry=True)
    spans = {iv.name: (iv.start, iv.end) for iv in cdf.intervals}
    # p0 must live to the root (index 4) through the %t -> %g chain,
    # not die at its last direct use
    assert spans["p0"][1] == 4


# ---------------------------------------------------------------------------
# Collective matching: no false positives on legal schedules
# ---------------------------------------------------------------------------


def _pt_with_commands(tmp_path, commands):
    from tpusim.analysis.trace_passes import load_parsed_trace

    root = tmp_path / "trace"
    (root / "modules").mkdir(parents=True)
    (root / "modules" / "m.hlo").write_text(
        "HloModule m, num_partitions=4\n\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  ROOT %r = f32[8]{0} negate(%p0)\n"
        "}\n"
    )
    (root / "meta.json").write_text(
        json.dumps({"num_devices": 4, "device_kind": "cpu"})
    )
    (root / "commandlist.jsonl").write_text(
        "\n".join(json.dumps(c) for c in commands) + "\n"
    )
    return load_parsed_trace(root)


def _coll(device, kind, groups, nbytes=1024):
    return {
        "kind": "collective", "device": device, "bytes": nbytes,
        "collective": {"kind": kind, "replica_groups": groups},
    }


def test_matching_healthy_multi_device_stream_is_clean(tmp_path):
    from tpusim.analysis.collective_passes import run_collective_matching
    from tpusim.analysis.diagnostics import Diagnostics

    pt = _pt_with_commands(tmp_path, [
        {"kind": "kernel_launch", "module": "m", "device": 0},
        {"kind": "kernel_launch", "module": "m", "device": 1},
        _coll(0, "all-reduce", [[0, 1]]),
        _coll(1, "all-reduce", [[0, 1]]),
        _coll(0, "all-gather", [[0, 1]]),
        _coll(1, "all-gather", [[0, 1]]),
    ])
    diags = Diagnostics()
    run_collective_matching(pt, diags)
    assert diags.items == [], "\n".join(diags.text_lines())


def test_matching_staggered_disjoint_groups_is_clean(tmp_path):
    """Device 0 waits on {0,1} while devices 1,2 legally complete
    {1,2} first — disjoint groups rendezvous in any order; only a
    whole-pod stall is a deadlock."""
    from tpusim.analysis.collective_passes import run_collective_matching
    from tpusim.analysis.diagnostics import Diagnostics

    pt = _pt_with_commands(tmp_path, [
        {"kind": "kernel_launch", "module": "m", "device": 0},
        _coll(0, "all-reduce", [[0, 1], [2, 3]]),
        _coll(1, "all-to-all", [[1, 2]]),
        _coll(2, "all-to-all", [[1, 2]]),
        _coll(1, "all-reduce", [[0, 1], [2, 3]]),
        _coll(2, "all-reduce", [[0, 1], [2, 3]]),
        _coll(3, "all-reduce", [[0, 1], [2, 3]]),
    ])
    diags = Diagnostics()
    run_collective_matching(pt, diags)
    assert diags.items == [], "\n".join(diags.text_lines())


def test_matching_single_device_capture_is_exempt(tmp_path):
    """The normal trace-one-replay-many capture: one device's stream
    issues collectives whose groups cover the whole declared pod —
    there are no peer streams to align, so the matcher stays silent."""
    from tpusim.analysis.collective_passes import run_collective_matching
    from tpusim.analysis.diagnostics import Diagnostics

    pt = _pt_with_commands(tmp_path, [
        {"kind": "kernel_launch", "module": "m", "device": 0},
        _coll(0, "all-reduce", [[0, 1], [2, 3]]),
    ])
    diags = Diagnostics()
    run_collective_matching(pt, diags)
    assert diags.items == []


def test_matching_reports_one_stall_not_a_cascade(tmp_path):
    from tpusim.analysis.collective_passes import run_collective_matching
    from tpusim.analysis.diagnostics import Diagnostics

    pt = _pt_with_commands(tmp_path, [
        {"kind": "kernel_launch", "module": "m", "device": 0},
        _coll(0, "all-reduce", [[0, 1]]),
        _coll(1, "all-gather", [[0, 1]]),
        # everything after the broken rendezvous is speculative
        _coll(0, "reduce-scatter", [[0, 1]]),
        _coll(1, "collective-permute", [[0, 1]]),
    ])
    diags = Diagnostics()
    run_collective_matching(pt, diags)
    assert [d.code for d in diags.items] == ["TL410"]


# ---------------------------------------------------------------------------
# Self-audit mechanics
# ---------------------------------------------------------------------------


def test_selfaudit_pragma_suppresses_with_reason(tmp_path):
    from tpusim.analysis import analyze_self_audit

    root = tmp_path / "repo"
    (root / "tpusim" / "campaign").mkdir(parents=True)
    (root / "tpusim" / "campaign" / "x.py").write_text(
        "import random\n"
        "def draw():\n"
        "    # lint-allow: TL350 entropy for a non-deterministic id\n"
        "    return random.random()\n"
    )
    assert analyze_self_audit(root=root).items == []


def test_selfaudit_pragma_reason_may_start_uppercase(tmp_path):
    """The pragma captures CODES only — an uppercase-leading reason
    ('CI artifact', 'RNG for ids') must not be swallowed into the
    code token and silently break the suppression it documents."""
    from tpusim.analysis import analyze_self_audit

    root = tmp_path / "repo"
    (root / "tpusim" / "campaign").mkdir(parents=True)
    (root / "tpusim" / "campaign" / "x.py").write_text(
        "import random\n"
        "def draw():\n"
        "    # lint-allow: TL350 RNG seeds a non-replayed id\n"
        "    return random.random()\n"
    )
    assert analyze_self_audit(root=root).items == []


def test_selfaudit_fsync_helper_satisfies_the_staging_rule(tmp_path):
    from tpusim.analysis import analyze_self_audit

    root = tmp_path / "repo"
    (root / "tpusim" / "store").mkdir(parents=True)
    (root / "tpusim" / "store" / "x.py").write_text(
        "import os\n"
        "def _stage(tmp, data):\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(data)\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "def publish(tmp, path, data):\n"
        "    _stage(tmp, data)\n"
        "    os.replace(tmp, path)\n"
        "def torn_publish(tmp, path):\n"
        "    os.replace(tmp, path)\n"
    )
    diags = analyze_self_audit(root=root)
    assert [d.code for d in diags.items] == ["TL352"]
    (d,) = diags.items
    assert d.line == 11  # only the helper-less publish fires


def test_selfaudit_seeded_constructors_are_legal(tmp_path):
    from tpusim.analysis import analyze_self_audit

    root = tmp_path / "repo"
    (root / "tpusim" / "fleet").mkdir(parents=True)
    (root / "tpusim" / "fleet" / "x.py").write_text(
        "import random\n"
        "def stream(seed):\n"
        "    rng = random.Random(f'{seed}:arrivals')\n"
        "    return rng.random()\n"
    )
    assert analyze_self_audit(root=root).items == []


def test_repo_selfaudit_is_green():
    """The acceptance gate: the TL35x audit over tpusim/ itself."""
    from tpusim.analysis import analyze_self_audit

    diags = analyze_self_audit()
    assert diags.items == [], "\n".join(diags.text_lines())


# ---------------------------------------------------------------------------
# Streaming lint: deferred == eager, and the RSS bound
# ---------------------------------------------------------------------------


def _write_big_trace(tdir: Path, n_comps: int, n_ops: int,
                     pad: int = 580) -> Path:
    (tdir / "modules").mkdir(parents=True)
    (tdir / "meta.json").write_text(json.dumps({
        "format_version": 1, "num_devices": 1, "device_kind": "cpu",
    }))
    filler = "x" * pad
    hlo = tdir / "modules" / "giant.hlo"
    with open(hlo, "w") as f:
        f.write("HloModule giant_lint, is_scheduled=true\n\n")
        for c in range(n_comps):
            f.write(f"%body_{c} (p0: f32[512,512]) -> f32[512,512] {{\n")
            f.write("  %p0 = f32[512,512]{1,0:T(8,128)} parameter(0)\n")
            prev = "%p0"
            for i in range(n_ops):
                f.write(
                    f"  %add_{i} = f32[512,512]{{1,0:T(8,128)}} "
                    f"add({prev}, %p0), metadata={{op_name="
                    f"\"layer{c}/add{i}/{filler}\" "
                    f"source_file=\"g.py\" source_line={i}}}\n"
                )
                prev = f"%add_{i}"
            f.write(f"  ROOT %root = f32[512,512]{{1,0:T(8,128)}} "
                    f"copy({prev})\n}}\n\n")
        f.write("ENTRY %main (p0: f32[512,512]) -> f32[512,512] {\n")
        f.write("  %p0 = f32[512,512]{1,0:T(8,128)} parameter(0)\n")
        prev = "%p0"
        for c in range(n_comps):
            f.write(f"  %call_{c} = f32[512,512]{{1,0:T(8,128)}} "
                    f"call({prev}), to_apply=%body_{c}\n")
            prev = f"%call_{c}"
        f.write(f"  ROOT %out = f32[512,512]{{1,0:T(8,128)}} "
                f"copy({prev})\n}}\n")
    (tdir / "commandlist.jsonl").write_text(json.dumps(
        {"kind": "kernel_launch", "module": "giant", "device": 0}
    ) + "\n")
    return hlo


def test_deferred_module_meta_parses_at_load(tmp_path, monkeypatch):
    from tpusim.analysis.trace_passes import load_parsed_trace

    _write_big_trace(tmp_path / "t", n_comps=2, n_ops=5, pad=8)
    monkeypatch.setenv("TPUSIM_STREAM_THRESHOLD", "64")
    pt = load_parsed_trace(tmp_path / "t")
    pm = pt.modules["giant"]
    assert pm.deferred_path is not None
    assert pm.module.name == "giant_lint"
    # nothing parsed yet: the header scan stops at the HloModule line
    assert not pm.comp_lines and not pm.op_lines


_LINT_RSS_SNIPPET = r'''
import json, resource, sys
from tpusim.analysis import analyze_trace_dir

if sys.argv[1] == "--baseline":
    print(json.dumps({
        "peak_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
    }))
    raise SystemExit(0)
diags = analyze_trace_dir(sys.argv[1], arch="v5e", tuned=False)
print(json.dumps({
    "peak_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
    "errors": sum(1 for d in diags.items
                  if d.severity.value == "error"),
}))
'''


@pytest.mark.slow
def test_streaming_lint_bounded_rss(tmp_path):
    """Acceptance (satellite): ``tpusim lint`` on a streaming-scale
    trace holds the streaming RSS bound — the same harness discipline
    as the pricing-path test in test_fastpath.py: subprocess-isolated
    ru_maxrss, a same-session import-floor baseline, and an absolute
    cap that full-text materialization (or retained per-op IR/line
    maps, which cost several times the text) trips decisively."""
    tdir = tmp_path / "giant"
    hlo = _write_big_trace(tdir, n_comps=100, n_ops=1000)
    size = hlo.stat().st_size
    assert size >= 64 * 1024 * 1024, f"generator produced {size} bytes"

    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("TPUSIM_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    base = subprocess.run(
        [sys.executable, "-c", _LINT_RSS_SNIPPET, "--baseline"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert base.returncode == 0, base.stderr[-2000:]
    baseline = json.loads(
        base.stdout.strip().splitlines()[-1]
    )["peak_kb"] * 1024

    proc = subprocess.run(
        [sys.executable, "-c", _LINT_RSS_SNIPPET, str(tdir)],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["errors"] == 0
    peak = out["peak_kb"] * 1024
    assert peak - baseline < 0.35 * size, (
        f"streaming lint added {(peak - baseline) / 1e6:.0f} MB over "
        f"the {baseline / 1e6:.0f} MB import floor — not well below "
        f"the {size / 1e6:.0f} MB trace"
    )
    assert peak < baseline + 0.5 * size, (
        f"absolute peak RSS {peak / 1e6:.0f} MB over the "
        f"{baseline / 1e6:.0f} MB floor is too close to the "
        f"{size / 1e6:.0f} MB trace size (full-text "
        f"materialization?)"
    )
