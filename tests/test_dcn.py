"""tpusim.dcn — the multi-slice DCN fabric layer.

Covers the ISSUE 20 acceptance criteria: single-slice / unconfigured
fabrics price byte-identically to the flat scalar model, hierarchical
AR/AG/RS on a 2-slice fixture match hand-computed costs, DCN fault
kinds flow end-to-end through the campaign and fleet executors
(slice-survival accounting, partition attribution, fabric-priced
recovery migration), and the advise ranked table grows dp-over-DCN
cells whose ordering flips with the fabric bandwidth.
"""

from __future__ import annotations

import math
from pathlib import Path

import pytest

from tpusim.dcn import (
    DcnBlock,
    DcnFabric,
    DcnSpecError,
    SliceTopology,
    fabric_overlay,
    slice_topology_for,
)
from tpusim.ici.collectives import CollectiveModel
from tpusim.ici.topology import torus_for
from tpusim.timing.config import load_config

FIXTURES = Path(__file__).parent / "fixtures" / "traces"
TRACE = FIXTURES / "llama_tiny_tp2dp2"

MiB = float(1 << 20)


def _ici(overlay: dict):
    return load_config(
        arch="v5p", overlays=[{"arch": {"ici": overlay}}], tuned=False,
    ).arch.ici


# -- slice topology ----------------------------------------------------------


def test_fabric_gated_on_nic_count():
    # chips_per_slice alone (the pre-fabric flat config) composes NO
    # fabric — the flat scalar model stays in charge
    assert slice_topology_for(8, _ici({"chips_per_slice": 4})) is None
    assert slice_topology_for(8, _ici({})) is None
    st = slice_topology_for(
        8, _ici({"chips_per_slice": 4, "dcn_nics_per_slice": 4}),
    )
    assert st is not None
    assert (st.num_slices, st.chips_per_slice) == (2, 4)


def test_hop_fields_fall_back_to_flat_scalars():
    cfg = _ici({"chips_per_slice": 4, "dcn_nics_per_slice": 1})
    st = slice_topology_for(8, cfg)
    assert st.nic_bandwidth == cfg.dcn_bandwidth
    assert st.hop_latency == cfg.dcn_latency


def test_slice_geometry():
    st = SliceTopology(
        num_slices=2, chips_per_slice=4, nics_per_slice=4,
        nic_bandwidth=25e9, hop_latency=1e-5, oversubscription=2.0,
    )
    assert [st.slice_of(c) for c in range(8)] == [0] * 4 + [1] * 4
    assert st.slice_bandwidth() == 4 * 25e9 / 2.0
    assert st.slices_for_group(4) == 1
    assert st.slices_for_group(5) == 2
    assert st.slices_for_group(8) == 2


def test_fabric_overlay_rounds_slices_up():
    block = DcnBlock.parse({"num_slices": 3, "nics_per_slice": 2})
    ov = fabric_overlay(block, 8)
    # ceil(8/3) = 3 chips per slice so the partial slice still counts
    assert ov["arch"]["ici"]["chips_per_slice"] == 3
    assert ov["arch"]["ici"]["dcn_nics_per_slice"] == 2


def test_dcn_block_rejections():
    with pytest.raises(DcnSpecError):
        DcnBlock.parse({"num_slices": 1})
    with pytest.raises(DcnSpecError):
        DcnBlock.parse({"num_slices": 2, "oversubscription": 0})
    with pytest.raises(DcnSpecError):
        DcnBlock.parse({"num_slices": 2, "warp_drive": True})


# -- degeneration: unconfigured fabric is byte-identical ---------------------


def test_unconfigured_fabric_prices_byte_identically():
    """Setting every dcn_hop_* knob WITHOUT a NIC count composes no
    fabric: all collective kinds price bit-for-bit as the flat model."""
    topo = torus_for(8, "v5p")
    flat = CollectiveModel(topo, _ici({"chips_per_slice": 4}))
    hopped = CollectiveModel(topo, _ici({
        "chips_per_slice": 4,
        "dcn_hop_bandwidth": 25e9, "dcn_hop_latency": 1e-5,
        "dcn_oversubscription": 2.0,
    }))
    for n in (2, 4, 8):
        for b in (4096.0, 64 * MiB):
            assert flat.allreduce_seconds(b, n) == \
                hopped.allreduce_seconds(b, n)
            assert flat.allgather_seconds(b, n) == \
                hopped.allgather_seconds(b, n)
            assert flat.reducescatter_seconds(b, n) == \
                hopped.reducescatter_seconds(b, n)
            assert flat.alltoall_seconds(b, n) == \
                hopped.alltoall_seconds(b, n)
    pairs = tuple((i, (i + 1) % 8) for i in range(8))
    assert flat.permute_seconds(4096.0, pairs) == \
        hopped.permute_seconds(4096.0, pairs)


def test_single_slice_group_never_pays_dcn():
    """A group that fits one slice prices identically with and without
    the fabric — the hierarchical path only engages past the slice."""
    topo = torus_for(8, "v5p")
    flat = CollectiveModel(topo, _ici({}))
    fab = CollectiveModel(topo, _ici({
        "chips_per_slice": 4, "dcn_nics_per_slice": 4,
    }))
    assert flat.allreduce_seconds(64 * MiB, 4) == \
        fab.allreduce_seconds(64 * MiB, 4)


# -- hierarchical decomposition vs hand-computed costs -----------------------


FABRIC_ICI = {
    "chips_per_slice": 4,
    "dcn_nics_per_slice": 4,
    "dcn_hop_bandwidth": 25e9,
    "dcn_hop_latency": 1e-5,
}
W_SLICE = 4 * 25e9  # per-slice injection bandwidth, 2 slices of 4


def _models():
    topo = torus_for(8, "v5p")
    return (
        CollectiveModel(topo, _ici({"chips_per_slice": 4})),
        CollectiveModel(topo, _ici(FABRIC_ICI)),
    )


def test_hierarchical_allreduce_matches_hand_cost():
    flat, fab = _models()
    b = 64 * MiB
    cfg = fab.cfg
    # in-slice reduce-scatter -> cross-slice ring AR over 2 slices
    # (2(S-1)/S * B / W + lat*ceil(log2 2)) -> in-slice all-gather
    cross = 2.0 * (2 - 1) / 2 * b / W_SLICE + 1e-5
    hier = (
        fab.reducescatter_seconds(b, 4)
        + cfg.launch_latency + cross
        + fab.allgather_seconds(b, 4)
    )
    got = fab.allreduce_seconds(b, 8)
    assert got == pytest.approx(
        min(flat.allreduce_seconds(b, 8), hier), rel=1e-12,
    )
    # at 64 MiB over 4 healthy NICs the hierarchical path wins
    assert got < flat.allreduce_seconds(b, 8)


def test_hierarchical_allgather_and_rs_match_hand_cost():
    flat, fab = _models()
    b = 64 * MiB
    cross = (2 - 1) / 2 * b / W_SLICE + 1e-5
    hier = (
        fab.cfg.launch_latency + cross + fab.allgather_seconds(b, 4)
    )
    got = fab.allgather_seconds(b, 8)
    assert got == pytest.approx(
        min(flat.allgather_seconds(b, 8), hier), rel=1e-12,
    )
    # reduce-scatter is the mirrored walk — same cost by construction
    assert fab.reducescatter_seconds(b, 8) == got


def test_hierarchical_alltoall_matches_hand_cost():
    flat, fab = _models()
    b = 64 * MiB
    # each 4-chip slice pushes 4*B*(S-1)/S bytes through its NIC bank
    cross = (4 * b * (2 - 1) / 2) / W_SLICE + 1e-5
    hier = (
        fab.alltoall_seconds(b, 4) + fab.cfg.launch_latency + cross
    )
    assert fab.alltoall_seconds(b, 8) == pytest.approx(
        min(flat.alltoall_seconds(b, 8), hier), rel=1e-12,
    )


def test_tiny_payload_keeps_flat_model():
    """Per-phase launch latencies make the hierarchy a bad deal for
    small payloads — min(flat, hier) must keep the flat price."""
    flat, fab = _models()
    assert fab.allreduce_seconds(1024.0, 8) == \
        flat.allreduce_seconds(1024.0, 8)


# -- fault-aware fabric ------------------------------------------------------


class _View:
    """Minimal FaultView stand-in (duck-typed by DcnFabric)."""

    def __init__(self, nics_down=None, scales=None, slices_down=()):
        self.dcn_nics_down = nics_down or {}
        self.dcn_scales = scales or {}
        self.slices_down = frozenset(slices_down)


def _st(nics=4, oversub=1.0):
    return SliceTopology(
        num_slices=2, chips_per_slice=4, nics_per_slice=nics,
        nic_bandwidth=25e9, hop_latency=1e-5, oversubscription=oversub,
    )


def test_fabric_degradation_semantics():
    st = _st()
    assert DcnFabric(st).slice_bandwidth(0) == 4 * 25e9
    assert DcnFabric(st, _View(nics_down={0: 1})) \
        .slice_bandwidth(0) == 3 * 25e9
    assert DcnFabric(st, _View(scales={1: 0.5})) \
        .slice_bandwidth(1) == 2 * 25e9
    assert DcnFabric(st, _View(slices_down=[1])).slice_bandwidth(1) == 0.0
    assert DcnFabric(st, _View(nics_down={0: 4})).slice_bandwidth(0) == 0.0


def test_dead_slice_makes_cross_terms_inf_and_flat_caps():
    fab = DcnFabric(_st(), _View(slices_down=[1]))
    assert math.isinf(fab.cross_allreduce_seconds(64 * MiB, 2))
    assert math.isinf(fab.transfer_seconds(1024.0, 1))
    # ... and the collective model falls back to the flat cap
    from tpusim.faults import load_fault_schedule

    topo = torus_for(8, "v5p")
    view = load_fault_schedule(
        {"faults": [{"kind": "slice_down", "slice": 1}]}
    ).bind(topo).view_at(0.0)
    flat = CollectiveModel(topo.with_faults(view), _ici(
        {"chips_per_slice": 4},
    ))
    degraded = CollectiveModel(topo.with_faults(view), _ici(FABRIC_ICI))
    assert degraded.allreduce_seconds(64 * MiB, 8) == \
        flat.allreduce_seconds(64 * MiB, 8)


def test_nic_loss_slows_the_hierarchical_path():
    topo = torus_for(8, "v5p")
    from tpusim.faults import load_fault_schedule

    view = load_fault_schedule(
        {"faults": [{"kind": "dcn_link_down", "slice": 0},
                    {"kind": "dcn_link_down", "slice": 0}]}
    ).bind(topo).view_at(0.0)
    healthy = CollectiveModel(topo, _ici(FABRIC_ICI))
    hurt = CollectiveModel(topo.with_faults(view), _ici(FABRIC_ICI))
    assert hurt.allreduce_seconds(64 * MiB, 8) > \
        healthy.allreduce_seconds(64 * MiB, 8)


# -- driver stats ------------------------------------------------------------


def test_driver_stamps_dcn_stats_only_when_spanning():
    from tpusim.sim.driver import simulate_trace

    healthy = simulate_trace(TRACE, arch="v5p", tuned=False)
    assert not [
        k for k in healthy.stats.values if k.startswith("dcn_")
    ]
    fab = simulate_trace(
        TRACE, arch="v5p", tuned=False,
        overlays=[{"arch": {"ici": {
            "chips_per_slice": 2, "dcn_nics_per_slice": 2,
        }}}],
    )
    assert fab.stats.get("dcn_slices") == 2
    assert fab.stats.get("dcn_chips_per_slice") == 2
    assert fab.stats.get("dcn_slice_bandwidth") == pytest.approx(
        2 * fab.stats.get("dcn_nics_per_slice") * 25e9 / 2
    )


# -- campaign: DCN faults end-to-end -----------------------------------------


def _campaign_spec(**over) -> dict:
    doc = {
        "name": "dcn-e2e", "seed": 7, "scenarios": 6,
        "arch": "v5p", "chips": 4, "tuned": False,
        "dcn": {"num_slices": 2, "nics_per_slice": 2,
                "nic_bandwidth": 25e9, "hop_latency": 1e-5},
        "faults": {
            "count": {"dist": "uniform", "min": 1, "max": 2},
            "kinds": {"slice_down": 2.0, "dcn_link_down": 1.0,
                      "link_degraded": 0.5},
            "scale": {"min": 0.4, "max": 0.9},
        },
    }
    doc.update(over)
    return doc


def test_campaign_answers_slice_survival():
    from tpusim.campaign import run_campaign

    res = run_campaign(_campaign_spec(), trace_path=TRACE)
    sl = res.doc["slices"][0]
    dcn = sl["dcn"]
    assert dcn["slices"] == 2
    assert dcn["slice_loss_scenarios"] >= 1
    assert 1 <= dcn["min_slices_ok"] <= 2
    assert sum(dcn["slices_ok_hist"].values()) == sl["scenarios"]
    # every row carries per-scenario survival, and slice-loss rows are
    # partition outcomes with the DCN attribution string
    lost_rows = [
        r for r in res.doc["rows"] if r["dcn"]["slices_lost"] > 0
    ]
    assert lost_rows
    for r in lost_rows:
        assert r["status"] == "partitioned"
        assert "unreachable over the DCN fabric" in r["error"]
    for r in res.doc["rows"]:
        assert r["dcn"]["slices_ok"] + r["dcn"]["slices_lost"] == 2


def test_campaign_without_dcn_has_no_dcn_keys():
    from tpusim.campaign import run_campaign

    spec = _campaign_spec()
    del spec["dcn"]
    spec["faults"]["kinds"] = {"link_degraded": 1.0}
    res = run_campaign(spec, trace_path=TRACE)
    assert all("dcn" not in r for r in res.doc["rows"])
    assert all("dcn" not in s for s in res.doc["slices"])


def test_campaign_dcn_kind_without_fabric_refused():
    from tpusim.analysis import ValidationError
    from tpusim.campaign import run_campaign

    spec = _campaign_spec()
    del spec["dcn"]
    with pytest.raises((ValidationError, ValueError)) as ei:
        run_campaign(spec, trace_path=TRACE)
    assert getattr(ei.value, "code", None) == "TL231" \
        or "TL231" in str(ei.value)


def test_campaign_same_seed_byte_identical_with_dcn():
    import json

    from tpusim.campaign import run_campaign

    a = run_campaign(_campaign_spec(), trace_path=TRACE)
    b = run_campaign(_campaign_spec(), trace_path=TRACE)
    assert json.dumps(a.doc, sort_keys=True) == \
        json.dumps(b.doc, sort_keys=True)


# -- fleet: DCN faults, partition attribution, fabric migration --------------


def _fleet_spec(**over) -> dict:
    doc = {
        "name": "t-fleet-dcn", "seed": 3, "pods": 2,
        "arch": "v5p", "chips": 8, "tuned": False,
        "horizon_s": 30.0,
        "dcn": {"num_slices": 2, "nics_per_slice": 2,
                "nic_bandwidth": 25e9, "hop_latency": 1e-5},
        "traffic": {
            "load_points": [6.0],
            "mix": [{"name": "chat", "weight": 3.0, "steps": 50},
                    {"name": "batch", "weight": 1.0, "steps": 200}],
        },
        "faults": {
            "count": {"dist": "uniform", "min": 1, "max": 2},
            "kinds": {"slice_down": 2.0, "dcn_link_down": 1.0},
            "scale": {"min": 0.4, "max": 0.9},
            "window": {"min_s": 5.0, "max_s": 15.0},
            "pod_loss": {"prob": 0.0},
        },
        "policies": {"max_inflight": 1, "queue_depth": 4,
                     "deadline_s": 0.5, "restart_backoff_s": 3.0},
    }
    doc.update(over)
    return doc


def test_fleet_slice_loss_windows_attribute_to_partition():
    from tpusim.fleet import run_fleet

    res = run_fleet(_fleet_spec(), trace_path=TRACE)
    point = res.doc["curve"][0]
    assert point["losses"]["partition"] > 0
    # the sampler stamped slice-targeted fault records
    sigs = "".join(
        iv["signature"] for pod in res.doc["degradation"]
        for iv in pod["intervals"]
    )
    assert "slice_down" in sigs or "dcn_link_down" in sigs


def test_fleet_migration_priced_over_the_modeled_fabric():
    from tpusim.advise.transform import build_profile
    from tpusim.fleet import run_fleet
    from tpusim.trace.format import load_trace

    spec = _fleet_spec()
    spec["faults"]["pod_loss"] = {"prob": 0.9}
    res = run_fleet(spec, trace_path=TRACE)
    assert res.doc["recovery"], "seeded pod losses produced no rows"
    pb = build_profile(load_trace(TRACE)).param_bytes_total
    expect = pb / (2 * 25e9) + 1e-5   # healthy NIC bank + one DCN hop
    for r in res.doc["recovery"]:
        assert r["migration_s"] == pytest.approx(expect, rel=1e-12)


def test_fleet_dcn_kind_without_fabric_refused():
    from tpusim.fleet.spec import FleetSpecError, load_fleet_spec

    spec = _fleet_spec()
    del spec["dcn"]
    with pytest.raises(FleetSpecError) as ei:
        load_fleet_spec(spec)
    assert ei.value.code == "TL231"


# -- advise: dp-over-DCN cells and the bandwidth crossover -------------------


def _advise_spec(nic_bandwidth: float, nics: int) -> dict:
    return {
        "name": "dcn-advise", "strategies": ["dp", "dp_tp"],
        "slices": [{"arch": "v5p", "chips": 8}],
        "tuned": False,
        "dcn": {"num_slices": 4, "nics_per_slice": nics,
                "nic_bandwidth": nic_bandwidth, "hop_latency": 1e-5},
    }


def test_advise_ranks_dp_over_dcn_and_crossover_flips():
    from tpusim.advise import run_advise

    fast = run_advise(_advise_spec(25e9, 4), trace_path=TRACE)
    slow = run_advise(_advise_spec(2e8, 1), trace_path=TRACE)

    # the ranked table grew dp-over-DCN x tp-over-ICI cells: dp4xtp2
    # spans 4 slices of 2 chips on dp while tp stays inside a slice
    by_cell = {r["cell"]: r for r in fast.doc["cells"]}
    mixed = by_cell["v5p-8/dp4xtp2"]
    assert mixed["dcn"] == {
        "slices": 4, "dp_over_dcn": True, "spanning_axes": ["dp"],
    }
    pure = by_cell["v5p-8/dp8"]
    assert pure["dcn"]["dp_over_dcn"] is True

    # crossover: a fast fabric ranks the all-DCN dp8 mesh first; a slow
    # fabric flips it below the dp-over-DCN x tp-over-ICI hybrid
    fast_order = [r["cell"] for r in fast.doc["cells"]]
    slow_order = [r["cell"] for r in slow.doc["cells"]]
    assert fast_order.index("v5p-8/dp8") < \
        fast_order.index("v5p-8/dp4xtp2")
    assert slow_order.index("v5p-8/dp4xtp2") < \
        slow_order.index("v5p-8/dp8")


def test_advise_without_dcn_rows_unchanged():
    from tpusim.advise import run_advise

    spec = _advise_spec(25e9, 4)
    del spec["dcn"]
    res = run_advise(spec, trace_path=TRACE)
    assert res.doc["cells"]
    assert all("dcn" not in r for r in res.doc["cells"])


def test_advise_bad_dcn_block_is_tl230():
    from tpusim.advise.spec import AdviseSpecError, load_advise_spec

    spec = _advise_spec(25e9, 4)
    spec["dcn"] = {"num_slices": 1}
    with pytest.raises(AdviseSpecError) as ei:
        load_advise_spec(spec)
    assert ei.value.code == "TL230"
