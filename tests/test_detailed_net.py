"""Tests for the detailed ICI network model (`tpusim/ici/detailed.py` +
`native/ici_net.cpp`) — the BookSim-kncube-equivalent behind
``IciConfig.network_mode`` (reference: ``icnt_wrapper.h:36-64`` selecting
intersim2 vs the built-in xbar)."""

import random

import pytest

from tpusim.ici.detailed import (
    DetailedCollectiveModel,
    TorusNetwork,
    make_collective_model,
    native_net_available,
    NET_CYCLE_S,
)
from tpusim.ici.collectives import CollectiveModel
from tpusim.ici.topology import Topology
from tpusim.ir import CollectiveInfo
from tpusim.timing.config import IciConfig


def ring4():
    return Topology(dims=(4,), wrap=(True,))


def torus44():
    return Topology(dims=(4, 4), wrap=(True, True))


# -- routing / base latency -------------------------------------------------

def test_single_transfer_uncontended_latency():
    """One packet over h hops: h*hop + serialization, cut-through."""
    net = TorusNetwork(ring4(), flit_bytes=8.0, hop_cycles=10,
                       use_native=False)
    # 0 -> 2: two hops (either way on a 4-ring); 800 bytes = 100 cycles ser
    cycles = net.run_phases([[(0, 2, 800.0)]], packet_bytes=1e9)
    assert cycles == pytest.approx(2 * 10 + 100.0)


def test_wraparound_shorter_path():
    """3 -> 0 on a wrapped 4-ring is one hop, not three."""
    net = TorusNetwork(ring4(), flit_bytes=8.0, hop_cycles=10,
                       use_native=False)
    cycles = net.run_phases([[(3, 0, 80.0)]], packet_bytes=1e9)
    assert cycles == pytest.approx(10 + 10.0)
    # without wrap links it must take 3 hops
    mesh = Topology(dims=(4,), wrap=(False,))
    net2 = TorusNetwork(mesh, flit_bytes=8.0, hop_cycles=10,
                        use_native=False)
    assert net2.run_phases([[(3, 0, 80.0)]], packet_bytes=1e9) == \
        pytest.approx(3 * 10 + 10.0)


def test_contention_serializes_shared_link():
    """Two transfers over the same directed link take 2x the bandwidth
    time; transfers on disjoint links don't."""
    net = TorusNetwork(ring4(), flit_bytes=8.0, hop_cycles=0,
                       use_native=False)
    one = net.run_phases([[(0, 1, 800.0)]], packet_bytes=1e9)
    shared = net.run_phases(
        [[(0, 1, 800.0), (0, 1, 800.0)]], packet_bytes=1e9
    )
    disjoint = net.run_phases(
        [[(0, 1, 800.0), (2, 3, 800.0)]], packet_bytes=1e9
    )
    assert one == pytest.approx(100.0)
    assert shared == pytest.approx(200.0)
    assert disjoint == pytest.approx(100.0)


def test_cut_through_pipelines_across_hops():
    """Serialization is paid once on an idle path, not per hop."""
    topo = Topology(dims=(8,), wrap=(True,))
    net = TorusNetwork(topo, flit_bytes=1.0, hop_cycles=5, use_native=False)
    # 0 -> 3: 3 hops, 1000-byte packet: 3*5 + 1000, NOT 3*(5+1000)
    cycles = net.run_phases([[(0, 3, 1000.0)]], packet_bytes=1e9)
    assert cycles == pytest.approx(3 * 5 + 1000.0)


def test_phases_are_barriers():
    net = TorusNetwork(ring4(), flit_bytes=8.0, hop_cycles=0,
                       use_native=False)
    two_phases = net.run_phases(
        [[(0, 1, 800.0)], [(0, 1, 800.0)]], packet_bytes=1e9
    )
    assert two_phases == pytest.approx(200.0)


def test_packet_chunking_interleaves_fairly():
    """With small packets two flows through one link finish together at
    2x single-flow time (fair round-robin-ish), not one after the other."""
    net = TorusNetwork(ring4(), flit_bytes=8.0, hop_cycles=1,
                       use_native=False)
    t = net.run_phases(
        [[(0, 1, 8000.0), (0, 1, 8000.0)]], packet_bytes=800.0
    )
    # 2 flows x 1000 cycles of serialization each; chunked they share the
    # link and total ~2000 (+hop)
    assert 1990 <= t <= 2050


# -- native parity ----------------------------------------------------------

@pytest.mark.skipif(not native_net_available(), reason="native lib not built")
def test_native_matches_python_backend():
    rng = random.Random(7)
    for topo in (ring4(), torus44(),
                 Topology(dims=(2, 2, 4), wrap=(False, True, True))):
        n = topo.num_chips
        phases = []
        for _ in range(3):
            phase = []
            for _ in range(20):
                s, d = rng.randrange(n), rng.randrange(n)
                phase.append((s, d, float(rng.randrange(1, 5)) * 512.0))
            phases.append(phase)
        py = TorusNetwork(topo, 16.0, 3, use_native=False)
        nat = TorusNetwork(topo, 16.0, 3, use_native=True)
        t_py = py.run_phases(phases, packet_bytes=1024.0)
        t_nat = nat.run_phases(phases, packet_bytes=1024.0)
        assert t_nat == pytest.approx(t_py, rel=1e-9), topo


# -- collective schedules on the detailed net -------------------------------

def _cfg(**kw) -> IciConfig:
    base = dict(
        link_bandwidth=100e9, efficiency=1.0, hop_latency=1e-9,
        launch_latency=0.0, network_mode="detailed",
    )
    base.update(kw)
    return IciConfig(**base)


def test_detailed_allreduce_tracks_analytic_for_large_payload():
    """Bandwidth-dominated ring all-reduce: the simulated schedule must
    land near the closed form 2(N-1)/N * B / (W*D)."""
    topo = ring4()
    cfg = _cfg()
    det = DetailedCollectiveModel(topo, cfg)
    ana = CollectiveModel(topo, cfg)
    info = CollectiveInfo("all-reduce", replica_groups=((0, 1, 2, 3),))
    payload = 64 * 1024 * 1024.0
    t_det = det.seconds(info, payload)
    t_ana = ana.seconds(info, payload)
    assert t_det == pytest.approx(t_ana, rel=0.25), (t_det, t_ana)


def test_detailed_permute_matches_hop_count():
    topo = torus44()
    cfg = _cfg(hop_latency=100e-9)
    det = DetailedCollectiveModel(topo, cfg)
    # one ring shift: every chip sends to +1 neighbor (1 hop each)
    pairs = tuple((i, (i + 1) % 16) for i in range(16))
    info = CollectiveInfo("collective-permute", source_target_pairs=pairs)
    payload = 1024.0 * 1024
    t = det.seconds(info, payload)
    # uncontended: ser (payload/flit) + 1 hop
    expected = payload / (100e9 * NET_CYCLE_S) * NET_CYCLE_S + 100e-9
    assert t == pytest.approx(expected, rel=0.05)


def test_detailed_disjoint_groups_run_concurrently():
    topo = ring4()
    cfg = _cfg()
    det = DetailedCollectiveModel(topo, cfg)
    one = det.seconds(
        CollectiveInfo("all-reduce", replica_groups=((0, 1),)), 1e6
    )
    both = det.seconds(
        CollectiveInfo("all-reduce", replica_groups=((0, 1), (2, 3))), 1e6
    )
    assert both == pytest.approx(one, rel=0.05)


def test_detailed_multislice_aliased_group_falls_back_to_analytic():
    """Replica ids >= num_chips alias onto the same chip under the mod
    mapping (multi-slice groups); the packet sim would drop the collapsed
    src==dst transfers, so those groups must be priced analytically."""
    topo = ring4()
    cfg = _cfg(chips_per_slice=4)
    det = DetailedCollectiveModel(topo, cfg)
    ana = CollectiveModel(topo, cfg)
    # 8 replicas over a 4-chip slice topology: ids 4..7 alias 0..3
    info = CollectiveInfo(
        "all-reduce", replica_groups=(tuple(range(8)),)
    )
    payload = 16 * 1024 * 1024.0
    assert det._aliases_chips(info)
    assert det.seconds(info, payload) == ana.seconds(info, payload)
    # non-aliased groups keep the packet-sim path (differs from analytic)
    clean = CollectiveInfo("all-reduce", replica_groups=((0, 1, 2, 3),))
    assert not det._aliases_chips(clean)


def test_detailed_alltoall_bounded_by_link_load():
    """All-to-all must respect the aggregate link-load lower bound
    (total byte-hops / total directed link capacity) yet beat a
    single-link neighbor shift of the same per-chip payload — it spreads
    traffic over all 4 output links of the 2D torus."""
    topo = torus44()
    cfg = _cfg()
    det = DetailedCollectiveModel(topo, cfg)
    a2a = det.seconds(
        CollectiveInfo("all-to-all", replica_groups=(tuple(range(16)),)),
        1e6,
    )
    # lower bound: per chip 15 flows x (1e6/16) bytes, total hop-weighted
    # traffic sum(hops)=32 per source on the wrapped 4x4 torus; 64 directed
    # links at 100 B/cycle
    lb = (16 * (1e6 / 16) * 32) / (64 * 100.0) * NET_CYCLE_S
    shift = det.seconds(
        CollectiveInfo(
            "collective-permute",
            source_target_pairs=tuple((i, (i + 1) % 16) for i in range(16)),
        ),
        1e6,
    )
    assert a2a >= 0.95 * lb
    assert a2a < shift  # same injected volume, 4x the usable links


def test_snake_order_adjacent_on_3d_torus():
    """Every consecutive pair in the boustrophedon ring must be 1 torus
    hop apart (a sum-parity snake breaks at block boundaries)."""
    from tpusim.ici.detailed import _snake_order

    for dims in ((4, 4, 4), (2, 2, 4), (4, 4), (8,)):
        topo = Topology(dims=dims, wrap=tuple(True for _ in dims))
        ring = _snake_order(topo, range(topo.num_chips))
        n = len(ring)
        bad = [
            (ring[i], ring[(i + 1) % n])
            for i in range(n - 1)  # closing edge may legitimately be longer
            if topo.hop_distance(ring[i], ring[i + 1]) != 1
        ]
        assert not bad, (dims, bad)


@pytest.mark.parametrize(
    "dims", [(4, 4), (4, 4, 4), (2, 2), (2, 2, 2), (8, 8)]
)
def test_multiaxis_allreduce_matches_analytic(dims):
    """The axis-factored counter-rotating schedule must realize the
    analytic model's D = 2*axes bandwidth on full-torus groups — including
    length-2 axes, whose wrap links form genuine double links."""
    topo = Topology(dims=dims, wrap=tuple(True for _ in dims))
    n = topo.num_chips
    cfg = _cfg()
    det = DetailedCollectiveModel(topo, cfg)
    ana = CollectiveModel(topo, cfg)
    info = CollectiveInfo("all-reduce", replica_groups=(tuple(range(n)),))
    B = 64 * 1024 * 1024.0
    assert det.seconds(info, B) == pytest.approx(
        ana.seconds(info, B), rel=0.1
    )


def test_two_member_subgroup_no_double_billing():
    """A 2-member group on a longer ring sends each step directly (one
    link each way), not twice over the same link: total time = B bytes
    per directed link at W."""
    topo = ring4()
    cfg = _cfg()
    det = DetailedCollectiveModel(topo, cfg)
    B = 1e6
    t = det.seconds(
        CollectiveInfo("all-reduce", replica_groups=((0, 1),)), B
    )
    w_bytes_per_sec = 100e9
    assert t == pytest.approx(B / w_bytes_per_sec, rel=0.05)


# -- selection / integration ------------------------------------------------

def test_make_collective_model_dispatch():
    topo = ring4()
    assert isinstance(
        make_collective_model(topo, IciConfig()), CollectiveModel
    )
    assert isinstance(
        make_collective_model(topo, _cfg()), DetailedCollectiveModel
    )
    with pytest.raises(ValueError):
        make_collective_model(topo, IciConfig(network_mode="bogus"))


def test_engine_runs_with_detailed_network(fixtures_dir):
    from tpusim.timing.config import SimConfig, overlay
    from tpusim.timing.engine import Engine
    from tpusim.trace.hlo_text import parse_hlo_module

    mod = parse_hlo_module((fixtures_dir / "tiny_mlp.hlo").read_text())
    ana = Engine(SimConfig()).run(mod)
    det = Engine(
        overlay(SimConfig(), {"arch": {"ici": {"network_mode": "detailed"}}})
    ).run(mod)
    assert det.cycles > 0
    assert det.collective_count == ana.collective_count
    # both models price the same payloads; totals must be same order
    assert 0.2 < det.cycles / ana.cycles < 5.0
