"""Unit tests for the replay driver (tpusim/sim/driver.py)."""

from pathlib import Path

import pytest

from tpusim.ir import (
    CollectiveInfo,
    CommandKind,
    PodTrace,
    TraceCommand,
)
from tpusim.sim.driver import SimDriver
from tpusim.timing.config import SimConfig
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


def _pod_with_collectives(n_devices: int, nbytes: int) -> PodTrace:
    pod = PodTrace(meta={"num_devices": n_devices})
    info = CollectiveInfo(
        "all-reduce", replica_groups=(tuple(range(n_devices)),)
    )
    for d in range(n_devices):
        pod.device(d).commands.append(TraceCommand(
            kind=CommandKind.COLLECTIVE, device_id=d, nbytes=nbytes,
            collective=info,
        ))
    return pod


def test_collective_rendezvous_aligns_not_serializes():
    cfg = SimConfig()
    r2 = SimDriver(cfg).run(_pod_with_collectives(2, 64 * 1024 * 1024))
    r8 = SimDriver(cfg).run(_pod_with_collectives(8, 64 * 1024 * 1024))
    # all devices run the SAME collective concurrently: per-device finish
    # times must be equal, and the pod time must not scale with device count
    assert len(set(round(c, 3) for c in r2.device_cycles.values())) == 1
    assert len(set(round(c, 3) for c in r8.device_cycles.values())) == 1
    # ring allreduce time grows ~ (n-1)/n, far from linear serialization
    assert r8.cycles < 2.5 * r2.cycles


def test_disjoint_group_rendezvous_not_coupled():
    """Disjoint replica groups must not synchronize with each other, even
    when they issue different collective counts (rendezvous is keyed by
    (group, per-group index), not a global per-device index)."""
    nb = 64 * 1024 * 1024
    g01 = CollectiveInfo("all-reduce", replica_groups=((0, 1),))
    g23 = CollectiveInfo("all-reduce", replica_groups=((2, 3),))
    mod = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())

    def group01_cmds(pod):
        for d in (0, 1):
            pod.device(d).commands.append(TraceCommand(
                kind=CommandKind.COLLECTIVE, device_id=d, nbytes=nb,
                collective=g01,
            ))

    pod = PodTrace(meta={"num_devices": 4})
    pod.modules["m"] = mod
    group01_cmds(pod)
    for d in (2, 3):
        # group (2,3) is delayed behind a kernel and issues TWO collectives
        pod.device(d).commands.append(TraceCommand(
            kind=CommandKind.KERNEL_LAUNCH, device_id=d, module="m",
        ))
        for _ in range(2):
            pod.device(d).commands.append(TraceCommand(
                kind=CommandKind.COLLECTIVE, device_id=d, nbytes=nb,
                collective=g23,
            ))
    r = SimDriver(SimConfig()).run(pod)

    # baseline: group (0,1) alone on the same topology
    solo = PodTrace(meta={"num_devices": 4})
    group01_cmds(solo)
    r_solo = SimDriver(SimConfig()).run(solo)

    # (0,1) must finish exactly as if (2,3) didn't exist
    assert r.device_cycles[0] == pytest.approx(r_solo.device_cycles[0])
    assert r.device_cycles[0] < r.device_cycles[2]
    # disjoint groups with different counts are NOT a rendezvous mismatch
    assert r.stats.get("collective_rendezvous_mismatch") is None


def test_report_totals_have_wall_clock_stats():
    mod_text = (FIXTURES / "tiny_mlp.hlo").read_text()
    pod = PodTrace()
    pod.modules["m"] = parse_hlo_module(mod_text)
    pod.device(0).commands.append(
        TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="m")
    )
    report = SimDriver(SimConfig()).run(pod)
    d = report.stats.values
    assert d["tot_sim_cycles"] > 0
    assert d["tot_achieved_tflops"] > 0
    assert d["tot_mxu_utilization"] > 0


def test_unknown_module_raises():
    pod = PodTrace()
    pod.device(0).commands.append(
        TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="ghost")
    )
    with pytest.raises(KeyError, match="ghost"):
        SimDriver(SimConfig()).run(pod)


def test_steady_state_memcpy_shape(live_jax):
    """launches=N must yield one H2D (before first) and one D2H (after
    last), kernels in between."""
    import jax.numpy as jnp

    from tpusim.tracer.capture import capture_to_dir
    from tpusim.trace.format import parse_commandlist

    def f(x):
        return (x * 2.0).sum()

    td = capture_to_dir(
        "/tmp/tpusim_test_steady", f, jnp.ones((256, 256)), launches=3
    )
    cmds = parse_commandlist(td.commandlist_path)
    kinds = [c.kind for c in cmds]
    assert kinds.count(CommandKind.MEMCPY_H2D) == 1
    assert kinds.count(CommandKind.KERNEL_LAUNCH) == 3
    assert kinds.count(CommandKind.MEMCPY_D2H) == 1
    assert kinds[0] == CommandKind.MEMCPY_H2D
    assert kinds[-1] == CommandKind.MEMCPY_D2H


def test_multi_stream_overlap():
    """Kernels on one stream serialize on the core; memcpys on another
    stream overlap with them."""
    mod_text = (FIXTURES / "tiny_mlp.hlo").read_text()

    def build(streams: bool) -> PodTrace:
        pod = PodTrace()
        pod.modules["m"] = parse_hlo_module(mod_text)
        dev = pod.device(0)
        for i in range(4):
            dev.commands.append(TraceCommand(
                kind=CommandKind.KERNEL_LAUNCH, module="m", stream_id=0,
            ))
            dev.commands.append(TraceCommand(
                kind=CommandKind.MEMCPY_H2D, nbytes=64 * 1024 * 1024,
                stream_id=1 if streams else 0,
            ))
        return pod

    cfg = SimConfig()
    overlapped = SimDriver(cfg).run(build(streams=True))
    serial = SimDriver(cfg).run(build(streams=False))
    assert overlapped.cycles < serial.cycles


def test_kernel_window_bounds_lookahead():
    """With kernel_window=1, a second-stream memcpy issued after N kernels
    cannot overlap them all — it waits for all but the newest in-flight
    kernel (main.cc:74-115 busy-stream gating).  A wide window lets it
    overlap from cycle 0."""
    from tests.test_aux_subsystems import _pod
    from tpusim.ir import CommandKind, TraceCommand
    from tpusim.timing.config import overlay

    def pod():
        p = _pod(3)  # three kernels on stream 0
        p.device(0).commands.append(TraceCommand(
            kind=CommandKind.MEMCPY_H2D, nbytes=64 * 1024 * 1024,
            stream_id=1,
        ))
        return p

    wide = SimDriver(SimConfig()).run(pod())
    narrow = SimDriver(
        overlay(SimConfig(), {"kernel_window": 1})
    ).run(pod())
    # kernel timing itself is unchanged (they serialize on the core)
    assert [k.end_cycle for k in narrow.kernels] == \
        [k.end_cycle for k in wide.kernels]
    # but the trailing memcpy is pushed behind the second kernel's end
    assert narrow.cycles > wide.cycles
