"""tpusim.fastpath — pricing-backend parity + streaming-RSS contract.

The fastpath's whole license to exist is byte-identity: the serial
reference walk, the NumPy-vectorized path, and the native kernel must
produce the same :class:`EngineResult` float for float — not merely
stats-close.  The corpus test below prices EVERY committed fixture
trace (single-chip silicon suite + the multi-chip CI fixtures) across
archs, degraded launch classes, and a faulted topology, comparing the
FULL serialized result document (per-op aggregates included) across
backends.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
SILICON = REPO / "reports" / "silicon"
CI_TRACES = REPO / "tests" / "fixtures" / "traces"

ARCHS = ("v5e", "v5p", "v6e")
SCALE_CLASSES = ((1.0, 1.0), (0.7, 0.9), (1.0, 0.5))


def _numpy_ok() -> bool:
    from tpusim.fastpath import numpy_available

    return numpy_available()


def _native_ok() -> bool:
    from tpusim.fastpath import native_price_available

    return native_price_available()


def _backends() -> list[str]:
    out = ["serial"]
    if _numpy_ok():
        out.append("vectorized")
    if _native_ok():
        out.append("native")
    return out


def _corpus() -> list[tuple[str, object]]:
    """(label, module) for every committed fixture trace module."""
    from tpusim.trace.format import load_trace

    out = []
    manifest = json.loads((SILICON / "manifest.json").read_text())
    for e in manifest["workloads"]:
        pod = load_trace(SILICON / e["trace"])
        for mname, mod in sorted(pod.modules.items()):
            out.append((f"{e['trace']}/{mname}", mod))
    for tdir in sorted(CI_TRACES.iterdir()):
        if tdir.is_dir():
            pod = load_trace(tdir)
            for mname, mod in sorted(pod.modules.items()):
                out.append((f"{tdir.name}/{mname}", mod))
    return out


def _doc(result) -> str:
    from tpusim.perf.cache import result_to_doc

    return json.dumps(result_to_doc(result), sort_keys=False)


def _engine(arch, backend, cs=1.0, hs=1.0, topology=None, config=None):
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine

    return Engine(
        config or load_config(arch=arch), topology=topology,
        clock_scale=cs, hbm_scale=hs, pricing_backend=backend,
    )


# ---------------------------------------------------------------------------
# Backend resolution
# ---------------------------------------------------------------------------


def test_resolve_backend_contract(monkeypatch):
    from tpusim.fastpath import resolve_backend

    assert resolve_backend("serial") == "serial"
    if _numpy_ok():
        assert resolve_backend("vectorized") == "vectorized"
        assert resolve_backend(None) in ("vectorized", "native")
    monkeypatch.setenv("TPUSIM_PRICING_BACKEND", "serial")
    assert resolve_backend(None) == "serial"
    monkeypatch.delenv("TPUSIM_PRICING_BACKEND")
    with pytest.raises(ValueError):
        resolve_backend("warp-speed")


def test_explicit_native_raises_when_unavailable(monkeypatch):
    """Pinning an unavailable backend must fail loudly, never silently
    price through something else."""
    import tpusim.fastpath.native as fn
    from tpusim.fastpath import resolve_backend

    monkeypatch.setattr(fn, "_LIB", None)
    monkeypatch.setattr(fn, "_LIB_TRIED", True)
    with pytest.raises(ValueError, match="native"):
        resolve_backend("native")
    # auto quietly falls back
    assert resolve_backend(None) in ("vectorized", "serial")


# ---------------------------------------------------------------------------
# The parity corpus
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_corpus_parity_all_archs():
    """Every fixture module × every arch: serial / vectorized / native
    full result documents must be byte-identical."""
    backends = _backends()
    assert len(backends) >= 2
    corpus = _corpus()
    assert len(corpus) >= 10
    checked = 0
    for arch in ARCHS:
        engines = {b: _engine(arch, b) for b in backends}
        for label, mod in corpus:
            want = _doc(engines["serial"].run(mod))
            for b in backends[1:]:
                got = _doc(engines[b].run(mod))
                assert got == want, (
                    f"{label} @ {arch}: backend {b} diverged from the "
                    f"serial walk"
                )
            checked += 1
    assert checked == len(ARCHS) * len(corpus)


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_corpus_parity_degraded_classes():
    """Straggler/HBM-throttle launch classes (the faults-layer chip
    multipliers) through every backend."""
    backends = _backends()
    corpus = _corpus()
    for cs, hs in SCALE_CLASSES[1:]:
        engines = {b: _engine("v5e", b, cs=cs, hs=hs) for b in backends}
        for label, mod in corpus:
            want = _doc(engines["serial"].run(mod))
            for b in backends[1:]:
                assert _doc(engines[b].run(mod)) == want, (
                    f"{label} @ scales ({cs},{hs}): {b} diverged"
                )


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_corpus_parity_faulted_topology():
    """A degraded-link fault view changes collective pricing; the
    compiled columns must flow through the same ICI model."""
    from tpusim.faults import load_fault_schedule
    from tpusim.ici.topology import torus_for
    from tpusim.trace.format import load_trace

    sched = load_fault_schedule({"faults": [
        {"kind": "link_degraded", "src": 0, "dst": 1,
         "bandwidth_scale": 0.5},
    ]})
    pod = load_trace(CI_TRACES / "llama_tiny_tp2dp2")
    backends = _backends()
    for arch in ("v5e", "v5p"):
        base = torus_for(4, arch)
        view = sched.bind(base).view_at(0.0)
        topo = base.with_faults(view)
        engines = {b: _engine(arch, b, topology=topo) for b in backends}
        for mname, mod in sorted(pod.modules.items()):
            want = _doc(engines["serial"].run(mod))
            for b in backends[1:]:
                assert _doc(engines[b].run(mod)) == want, (
                    f"{mname} @ faulted {arch}: {b} diverged"
                )


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_parity_under_vmem_spill():
    """A starved vmem budget exercises the spill transform (bytes
    migrate vmem->HBM, spill counter accumulates) on every backend."""
    from tpusim.timing.config import load_config
    from tpusim.trace.format import load_trace

    cfg = load_config(arch="v5e", overlays=[
        {"arch": {"vmem_bytes": 64 * 1024}},
    ])
    corpus = _corpus()
    backends = _backends()
    engines = {
        b: _engine("v5e", b, config=cfg) for b in backends
    }
    spilled_somewhere = False
    for label, mod in corpus:
        want_res = engines["serial"].run(mod)
        want = _doc(want_res)
        if want_res.vmem_spill_bytes > 0:
            spilled_somewhere = True
        for b in backends[1:]:
            assert _doc(engines[b].run(mod)) == want, (
                f"{label} under spill: {b} diverged"
            )
    assert spilled_somewhere, (
        "corpus never exercised the spill path — the parity claim "
        "above is vacuous; shrink the vmem overlay"
    )


# ---------------------------------------------------------------------------
# Engagement / disengagement
# ---------------------------------------------------------------------------


def test_fastpath_disengages_under_obs(monkeypatch):
    """Instrumented runs carry run-scoped observables (samplers, cost
    wall spans) — the serial walk must stay in charge."""
    import tpusim.fastpath.price as fp
    from tpusim.obs import Instrumentation
    from tpusim.trace.format import load_trace, select_module

    called = []
    real = fp.price_module
    monkeypatch.setattr(
        fp, "price_module",
        lambda *a, **k: called.append(1) or real(*a, **k),
    )
    mod = select_module(load_trace(SILICON / "matmul_chain"), None)
    obs = Instrumentation(window_cycles=0.0)
    eng = _engine("v5e", None)
    eng.obs = obs
    res = eng.run(mod)
    assert not called, "fastpath engaged under obs instrumentation"
    assert res.samples is not None
    # the same engine without obs engages (auto backend)
    if _numpy_ok():
        eng2 = _engine("v5e", None)
        eng2.run(mod)
        assert called


def test_fastpath_disengages_under_timeline():
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace, select_module

    mod = select_module(load_trace(SILICON / "matmul_chain"), None)
    eng = Engine(load_config(arch="v5e"), record_timeline=True)
    res = eng.run(mod)
    assert res.timeline, "timeline runs must price through the serial walk"


def test_cached_engine_composes_with_fastpath():
    """CachedEngine over the fastpath: hit returns the identical doc,
    and the cached bytes equal a serial-walk pricing of the same key."""
    from tpusim.perf.cache import CachedEngine, ResultCache
    from tpusim.timing.config import load_config
    from tpusim.trace.format import load_trace, select_module

    mod = select_module(load_trace(SILICON / "reduction"), None)
    cfg = load_config(arch="v5e")
    cache = ResultCache()
    eng = CachedEngine(cfg, result_cache=cache)
    first = _doc(eng.run(mod))
    again = _doc(eng.run(mod))
    assert cache.hits == 1 and first == again
    serial = _doc(_engine("v5e", "serial").run(mod))
    assert first == serial


# ---------------------------------------------------------------------------
# Compiled-module cache tier
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_compile_shared_across_launch_classes():
    """Every degraded launch class of one module must share ONE compile
    (columns are healthy; transforms are per-class)."""
    import tpusim.perf.cache as pc
    from tpusim.trace.format import load_trace, select_module

    mod = select_module(load_trace(SILICON / "mlp_train_step"), None)
    base_misses = pc._compiled_misses
    base_hits = pc._compiled_hits
    for cs, hs in SCALE_CLASSES:
        _engine("v5e", "vectorized", cs=cs, hs=hs).run(mod)
    assert pc._compiled_misses - base_misses <= 1
    assert pc._compiled_hits - base_hits >= 2


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_compile_shared_across_reparse_of_same_text():
    """A fresh parse of the same text (same content hash) reuses the
    compiled columns — the content-addressed tier, not object identity."""
    import tpusim.perf.cache as pc
    from tpusim.trace.format import load_trace, select_module

    eng = _engine("v5e", "vectorized")
    m1 = select_module(load_trace(SILICON / "conv2d"), None)
    eng.run(m1)
    base_hits = pc._compiled_hits
    m2 = select_module(load_trace(SILICON / "conv2d"), None)
    assert m1 is not m2
    doc1 = _doc(eng.run(m1))
    doc2 = _doc(eng.run(m2))
    assert doc1 == doc2
    assert pc._compiled_hits > base_hits


@pytest.mark.skipif(not _numpy_ok(), reason="numpy unavailable")
def test_custom_cost_model_bypasses_shared_compile_tier():
    """A caller-supplied cost model is outside every fingerprint: its
    compiled columns must not cross-serve the default population."""
    from tpusim.perf.cache import compiled_for
    from tpusim.timing.config import load_config
    from tpusim.timing.cost import CostModel
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace, select_module

    mod = select_module(load_trace(SILICON / "reduction"), None)
    cfg = load_config(arch="v5e")
    default_eng = Engine(cfg)
    custom_eng = Engine(
        cfg, cost_model=CostModel(cfg.arch, custom_call_flops={"k": 1e12}),
    )
    cm_default = compiled_for(mod, default_eng)
    cm_custom = compiled_for(mod, custom_eng)
    assert cm_default is not cm_custom
    # and the custom engine's own repeat run reuses ITS compile
    assert compiled_for(mod, custom_eng) is cm_custom


def test_scalar_memo_skips_rewalk_on_reparse(monkeypatch):
    """The content-hash memo (satellite): a second parse of the same
    text never re-runs the residency scan."""
    import tpusim.timing.engine as te
    from tpusim.trace.format import load_trace, select_module

    calls = []
    real = te._vmem_resident_bytes
    monkeypatch.setattr(
        te, "_vmem_resident_bytes",
        lambda m: calls.append(1) or real(m),
    )
    m1 = select_module(load_trace(SILICON / "transcendental"), None)
    m2 = select_module(load_trace(SILICON / "transcendental"), None)
    eng = _engine("v5e", "serial")
    eng.run(m1)
    n_after_first = len(calls)
    eng.run(m2)
    assert len(calls) == n_after_first, (
        "re-parse of identical text re-ran the residency walk despite "
        "the content-hash memo"
    )


# ---------------------------------------------------------------------------
# Streaming (bounded-RSS) pricing
# ---------------------------------------------------------------------------


def test_streaming_module_stats_parity():
    """File-backed streaming modules price stats-identically to the
    serial walk over the same representation (and the index finds the
    same computations the full parser does)."""
    from tpusim.trace.format import load_trace, select_module
    from tpusim.trace.lazy import StreamingModuleTrace

    for name in ("matmul_chain", "decode_step", "lstm_layer"):
        full = select_module(load_trace(SILICON / name), None)
        hlo = next((SILICON / name / "modules").glob("*.hlo"))
        meta = json.loads((SILICON / name / "meta.json").read_text())

        def stamped():
            m = StreamingModuleTrace(hlo, name_hint=hlo.stem)
            for k in ("platform", "device_kind"):
                if k in meta:
                    m.meta.setdefault(k, meta[k])
            return m

        assert set(stamped()._spans) == set(full.computations)
        assert stamped().meta["content_hash"] == \
            full.meta["content_hash"]
        want = _engine("v5e", "serial").run(stamped()).stats_dict()
        for b in _backends()[1:]:
            got = _engine("v5e", b).run(stamped()).stats_dict()
            assert json.dumps(got) == json.dumps(want), (
                f"streaming {name} via {b} diverged"
            )


def test_streaming_releases_parsed_ir():
    """Fastpath pricing of a streaming module must not retain every
    parsed computation (compile-then-release)."""
    if not _numpy_ok():
        pytest.skip("numpy unavailable")
    from tpusim.trace.lazy import StreamingModuleTrace

    hlo = next((SILICON / "decode_step" / "modules").glob("*.hlo"))
    mod = StreamingModuleTrace(hlo, name_hint=hlo.stem, parsed_cap=4)
    res = _engine("v5e", "vectorized").run(mod)
    assert res.cycles > 0
    assert mod.parsed_count <= 4
    # lean pricing: the per-op name table is the O(trace) memory term
    assert not res.per_op_cycles


_GEN_SNIPPET = r'''
import json, resource, sys
from tpusim.sim.driver import simulate_trace
from tpusim.trace.lazy import StreamingModuleTrace

if sys.argv[1] == "--baseline":
    # same imports, zero trace work: the interpreter+numpy floor
    print(json.dumps({
        "peak_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
    }))
    raise SystemExit(0)
report = simulate_trace(sys.argv[1], arch="v5e", tuned=False)
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({
    "peak_kb": peak_kb,
    "sim_cycle": report.stats.get("sim_cycle"),
    "ops": report.totals.op_count,
}))
'''


@pytest.mark.slow
def test_streaming_bounded_rss_on_200mb_trace(tmp_path):
    """Acceptance: a synthetic >=200 MB trace prices to completion in
    streaming mode with peak RSS bounded well below the trace size.

    Runs in a subprocess so ru_maxrss measures THIS pricing run, not
    the test session's high-water mark."""
    n_comps, n_ops = 300, 1000
    pad = "x" * 580
    tdir = tmp_path / "giant"
    (tdir / "modules").mkdir(parents=True)
    (tdir / "meta.json").write_text(json.dumps({
        "format_version": 1, "platform": "tpu",
        "device_kind": "TPU v5 lite",
    }))
    hlo = tdir / "modules" / "giant.hlo"
    with open(hlo, "w") as f:
        f.write("HloModule giant_stream, is_scheduled=true\n\n")
        for c in range(n_comps):
            f.write(f"%body_{c} (p0: f32[512,512]) -> f32[512,512] {{\n")
            f.write("  %p0 = f32[512,512]{1,0:T(8,128)} parameter(0)\n")
            prev = "%p0"
            for i in range(n_ops):
                f.write(
                    f"  %add_{i} = f32[512,512]{{1,0:T(8,128)}} "
                    f"add({prev}, %p0), metadata={{op_name="
                    f"\"layer{c}/add{i}/{pad}\" source_file=\"g.py\" "
                    f"source_line={i}}}\n"
                )
                prev = f"%add_{i}"
            f.write(f"  ROOT %root = f32[512,512]{{1,0:T(8,128)}} "
                    f"copy({prev})\n}}\n\n")
        f.write("ENTRY %main (p0: f32[512,512]) -> f32[512,512] {\n")
        f.write("  %p0 = f32[512,512]{1,0:T(8,128)} parameter(0)\n")
        prev = "%p0"
        for c in range(n_comps):
            f.write(f"  %call_{c} = f32[512,512]{{1,0:T(8,128)}} "
                    f"call({prev}), to_apply=%body_{c}\n")
            prev = f"%call_{c}"
        f.write(f"  ROOT %out = f32[512,512]{{1,0:T(8,128)}} "
                f"copy({prev})\n}}\n")
    size = hlo.stat().st_size
    assert size >= 200 * 1024 * 1024, f"generator produced {size} bytes"

    # Suite-context isolation: the pricing run must see NO tpusim env
    # state leaked by earlier tests (a stray TPUSIM_STREAM_THRESHOLD /
    # TPUSIM_PRICING_BACKEND would silently flip the streaming mode
    # this test exists to measure) — standalone and full-suite runs
    # must execute the identical configuration.
    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("TPUSIM_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    base_proc = subprocess.run(
        [sys.executable, "-c", _GEN_SNIPPET, "--baseline"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert base_proc.returncode == 0, base_proc.stderr[-2000:]
    baseline = json.loads(
        base_proc.stdout.strip().splitlines()[-1]
    )["peak_kb"] * 1024

    proc = subprocess.run(
        [sys.executable, "-c", _GEN_SNIPPET, str(tdir)],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["sim_cycle"] > 0
    assert out["ops"] >= n_comps * n_ops
    peak = out["peak_kb"] * 1024
    # The trace-dependent memory (peak minus the interpreter+numpy
    # import floor, which is ~80 MB regardless of trace size) must be
    # well below the trace: compiled columns + span index + a handful
    # of parsed computations, never the text.  The absolute cap would
    # trip on any regression that materializes the full text (that
    # alone would add ~size bytes).
    assert peak - baseline < 0.35 * size, (
        f"streaming pricing added {(peak - baseline) / 1e6:.0f} MB over "
        f"the {baseline / 1e6:.0f} MB import floor — not well below "
        f"the {size / 1e6:.0f} MB trace"
    )
    # The absolute cap is SUITE-AWARE: it rides on the baseline
    # measured in the same session, so a full-suite run whose
    # interpreter+numpy floor is inflated (allocator arenas, hugepage
    # policy, import growth) does not fail a bound tuned for a fresh
    # shell.  Full-text materialization still trips it decisively —
    # that alone adds ~size bytes, twice this margin.
    assert peak < baseline + 0.5 * size, (
        f"absolute peak RSS {peak / 1e6:.0f} MB over the "
        f"{baseline / 1e6:.0f} MB floor is too close to the "
        f"{size / 1e6:.0f} MB trace size (full-text materialization?)"
    )
