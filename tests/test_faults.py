"""Tests for fault injection & degraded-pod simulation (tpusim/faults/).

Covers the ISSUE-2 acceptance surface: schedule schema validation,
link-down route-around path lengths, the torus→mesh collective fallback,
straggler/HBM multipliers plumbed to engine cycles, the
partitioned-topology error message, the driver's faults_* stats
discipline, the obs faults_active series, and the single-link sweep
(library + CLI)."""

import json
from pathlib import Path

import pytest

from tpusim.faults import (
    FaultScheduleError,
    TopologyPartitionedError,
    link_down_schedule,
    load_fault_schedule,
    single_link_sweep,
)
from tpusim.ici.collectives import CollectiveModel
from tpusim.ici.detailed import DetailedCollectiveModel, TorusNetwork
from tpusim.ici.topology import Topology, torus_for
from tpusim.ir import CollectiveInfo
from tpusim.timing.config import IciConfig, SimConfig
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"
MB = 1024 * 1024

ICI = IciConfig(
    link_bandwidth=100e9, efficiency=1.0, hop_latency=1e-6,
    launch_latency=0.0,
)


def _dead_link_view(topo, a, b):
    return link_down_schedule(topo, a, b).bind(topo).view_at(0.0)


# -- schedule schema validation ---------------------------------------------

def test_schedule_rejects_unknown_kind():
    with pytest.raises(FaultScheduleError, match="unknown kind"):
        load_fault_schedule({"faults": [{"kind": "meteor_strike"}]})


def test_schedule_requires_endpoints_and_scales():
    with pytest.raises(FaultScheduleError, match="requires 'dst'"):
        load_fault_schedule({"faults": [{"kind": "link_down", "src": 0}]})
    with pytest.raises(FaultScheduleError, match="requires 'chip'"):
        load_fault_schedule(
            {"faults": [{"kind": "chip_straggler", "clock_scale": 0.5}]}
        )
    with pytest.raises(FaultScheduleError, match="bandwidth_scale"):
        load_fault_schedule(
            {"faults": [{"kind": "link_degraded", "src": 0, "dst": 1}]}
        )


def test_schedule_rejects_bad_scale_and_window():
    for bad in (0.0, -0.5, 1.5, "half"):
        with pytest.raises(FaultScheduleError, match=r"\(0, 1\]"):
            load_fault_schedule({"faults": [{
                "kind": "chip_straggler", "chip": 0, "clock_scale": bad,
            }]})
    with pytest.raises(FaultScheduleError, match="empty window"):
        load_fault_schedule({"faults": [{
            "kind": "link_down", "src": 0, "dst": 1,
            "start_cycle": 100, "end_cycle": 100,
        }]})


def test_schedule_rejects_unknown_fields_and_bad_doc():
    with pytest.raises(FaultScheduleError, match="unknown field"):
        load_fault_schedule({"faults": [{
            "kind": "link_down", "src": 0, "dst": 1, "oops": True,
        }]})
    with pytest.raises(FaultScheduleError, match="'faults' list"):
        load_fault_schedule({"nope": []})
    with pytest.raises(FaultScheduleError, match="invalid"):
        load_fault_schedule("{not json")


def test_bind_validates_coords_and_adjacency():
    topo = torus_for(64, "v5p")  # 4x4x4
    # out-of-range coordinate
    s = load_fault_schedule({"faults": [{
        "kind": "link_down", "src": [9, 0, 0], "dst": [0, 0, 0],
    }]})
    with pytest.raises(FaultScheduleError, match="out of range"):
        s.bind(topo)
    # wrong dimensionality
    s = load_fault_schedule({"faults": [{
        "kind": "link_down", "src": [0, 0], "dst": [1, 0],
    }]})
    with pytest.raises(FaultScheduleError, match="2 dims"):
        s.bind(topo)
    # endpoints that are not torus neighbors carry no link
    s = load_fault_schedule({"faults": [{
        "kind": "link_down", "src": [0, 0, 0], "dst": [2, 0, 0],
    }]})
    with pytest.raises(FaultScheduleError, match="not torus neighbors"):
        s.bind(topo)
    # chip id past the pod
    s = load_fault_schedule({"faults": [{
        "kind": "hbm_throttle", "chip": 64, "hbm_scale": 0.5,
    }]})
    with pytest.raises(FaultScheduleError, match="out of range"):
        s.bind(topo)


def test_schedule_roundtrip_and_windows():
    doc = {"faults": [
        {"kind": "link_down", "src": [0, 0, 0], "dst": [0, 1, 0]},
        {"kind": "chip_straggler", "chip": 3, "clock_scale": 0.5,
         "start_cycle": 1000.0, "end_cycle": 2000.0},
    ]}
    sched = load_fault_schedule(doc)
    assert sched.windowed
    assert load_fault_schedule(sched.to_doc()).to_doc() == sched.to_doc()
    topo = torus_for(64, "v5p")
    state = sched.bind(topo)
    assert state.view_at(0.0).num_active == 1      # straggler not yet
    assert state.view_at(1500.0).num_active == 2
    assert state.view_at(2500.0).num_active == 1
    # views are cached per active set
    assert state.view_at(0.0) is state.view_at(2500.0)


def test_overlapping_fault_composition_is_order_independent():
    """Same-resource faults stack multiplicatively in a deterministic
    order: a generated schedule must produce a bit-identical view however
    its records are ordered (float products are not associative, so the
    naive file-order product can differ in the last ulp)."""
    topo = torus_for(64, "v5p")
    # three scales chosen so the float64 product depends on order
    scales = [0.6375365295912734, 0.8810846638965013, 0.5785151418630428]
    a_, b_, c_ = scales
    assert (a_ * b_) * c_ != (a_ * c_) * b_
    throttles = [
        {"kind": "hbm_throttle", "chip": 5, "hbm_scale": s}
        for s in scales
    ]
    degrades = [
        {"kind": "link_degraded", "src": [0, 0, 0], "dst": [0, 1, 0],
         "bandwidth_scale": s}
        for s in scales
    ]
    views = []
    for order in ([0, 1, 2], [2, 0, 1], [1, 2, 0]):
        doc = {"faults": [throttles[i] for i in order]
               + [degrades[i] for i in order]}
        views.append(load_fault_schedule(doc).bind(topo).view_at(0.0))
    ref = views[0]
    for v in views[1:]:
        assert v.chip_hbm == ref.chip_hbm
        assert v.scales == ref.scales
        assert v.signature == ref.signature
    # and the composition really is the multiplicative stack
    a, b = topo.chip_at((0, 0, 0)), topo.chip_at((0, 1, 0))
    prod = 1.0
    for s in sorted(scales):
        prod *= s
    assert ref.chip_scales(5)[1] == prod
    assert ref.link_scale(a, b) == prod


# -- link-down routing (detailed network) -----------------------------------

def test_route_around_dead_link_is_longer_and_live():
    topo = torus_for(64, "v5p")
    a = topo.chip_at((2, 3, 0))
    b = topo.chip_at((3, 3, 0))
    ft = topo.with_faults(_dead_link_view(topo, a, b))
    healthy = TorusNetwork(topo, flit_bytes=90.0, hop_cycles=1,
                           use_native=False)
    faulted = TorusNetwork(ft, flit_bytes=90.0, hop_cycles=1)
    assert len(healthy._route(a, b)) == 1
    detour = faulted._route(a, b)
    # shortest live detour on a wrapped length-4 axis: the long way round
    assert len(detour) == 3
    for lid in detour:
        src, dst = faulted._link_endpoints(lid)
        assert ft.link_alive(src, dst)
    # unrelated routes are untouched
    c, d = topo.chip_at((0, 0, 1)), topo.chip_at((0, 0, 2))
    assert faulted._route(c, d) == healthy._route(c, d)


def test_partitioned_topology_raises_clear_error():
    line = Topology(dims=(4,), wrap=(False,))
    mid = _dead_link_view(line, 1, 2)
    net = TorusNetwork(line.with_faults(mid), flit_bytes=90.0, hop_cycles=1)
    with pytest.raises(
        TopologyPartitionedError,
        match=r"no live ICI route from chip 1 \[1\] to chip 2",
    ):
        net._route(1, 2)


def test_degraded_link_slows_packet_sim():
    topo = Topology(dims=(4,), wrap=(True,))
    sched = load_fault_schedule({"faults": [{
        "kind": "link_degraded", "src": 0, "dst": 1,
        "bandwidth_scale": 0.25,
    }]})
    view = sched.bind(topo).view_at(0.0)
    healthy = TorusNetwork(topo, flit_bytes=90.0, hop_cycles=1,
                           use_native=False)
    faulted = TorusNetwork(topo.with_faults(view), flit_bytes=90.0,
                           hop_cycles=1)
    phases = [[(0, 1, 9000.0)]]
    th = healthy.run_phases(phases)
    tf = faulted.run_phases(phases)
    assert tf > th
    # serialization term quadruples; hop latency is unchanged
    assert tf == pytest.approx(th + 3 * (9000.0 / 90.0), rel=1e-6)


def test_native_backend_refused_on_faulted_topology():
    topo = Topology(dims=(4,), wrap=(True,))
    ft = topo.with_faults(_dead_link_view(topo, 0, 1))
    with pytest.raises(RuntimeError, match="fault injection"):
        TorusNetwork(ft, flit_bytes=90.0, hop_cycles=1, use_native=True)


# -- torus -> mesh collective fallback (analytic) ---------------------------

def test_dead_wrap_link_falls_back_to_mesh_bandwidth():
    topo = Topology(dims=(8,), wrap=(True,))
    model_h = CollectiveModel(topo, ICI)
    ft = topo.with_faults(_dead_link_view(topo, 0, 7))  # the wrap link
    model_f = CollectiveModel(ft, ICI)
    payload = 256 * MB
    th = model_h.allreduce_seconds(payload, 8)
    tf = model_f.allreduce_seconds(payload, 8)
    assert tf > th
    # 2 directions -> 1: the bandwidth term exactly doubles
    lat_ring = 2 * 7 * ICI.hop_latency
    lat_tree = 2 * 3 * ICI.hop_latency
    bw_h = min(th - lat_ring, th - lat_tree)
    bw_f = min(tf - lat_ring, tf - lat_tree)
    assert bw_f == pytest.approx(2 * bw_h, rel=1e-6)
    assert not ft.axis_ring_intact(0)
    assert topo.axis_ring_intact(0)


def test_degraded_axis_scales_analytic_bandwidth():
    topo = Topology(dims=(8,), wrap=(True,))
    sched = load_fault_schedule({"faults": [{
        "kind": "link_degraded", "src": 2, "dst": 3,
        "bandwidth_scale": 0.5,
    }]})
    ft = topo.with_faults(sched.bind(topo).view_at(0.0))
    payload = 256 * MB
    th = CollectiveModel(topo, ICI).allreduce_seconds(payload, 8)
    tf = CollectiveModel(ft, ICI).allreduce_seconds(payload, 8)
    assert tf > th
    # the ring drains at the slowest link: ring axis still intact
    assert ft.axis_ring_intact(0)


def test_detailed_model_inflates_on_dead_link():
    topo = torus_for(64, "v5p")
    a, b = topo.chip_at((2, 3, 0)), topo.chip_at((3, 3, 0))
    ft = topo.with_faults(_dead_link_view(topo, a, b))
    info = CollectiveInfo("all-reduce", replica_groups=(tuple(range(64)),))
    th = DetailedCollectiveModel(topo, ICI).seconds(info, 64 * MB)
    tf = DetailedCollectiveModel(ft, ICI).seconds(info, 64 * MB)
    assert tf > th


# -- straggler / HBM multipliers plumbed to the engine ----------------------

#: two chained dots, no collectives — every cycle is on the chip clock
#: or the HBM channel, so the multipliers are directly observable
_DOTS_HLO = """\
HloModule straggler_test, is_scheduled=true

ENTRY %main (x: bf16[256,256], w: bf16[256,256]) -> bf16[256,256] {
  %x = bf16[256,256]{1,0:T(8,128)(2,1)} parameter(0)
  %w = bf16[256,256]{1,0:T(8,128)(2,1)} parameter(1)
  %dot.1 = bf16[256,256]{1,0:T(8,128)(2,1)} dot(%x, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %dot.2 = bf16[256,256]{1,0:T(8,128)(2,1)} dot(%dot.1, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


@pytest.fixture(scope="module")
def dots_module():
    return parse_hlo_module(_DOTS_HLO)


def test_straggler_clock_scale_inflates_engine_cycles(dots_module):
    cfg = SimConfig()
    base = Engine(cfg).run(dots_module)
    clock_only = Engine(cfg, clock_scale=0.5).run(dots_module)
    hbm_only = Engine(cfg, hbm_scale=0.5).run(dots_module)
    both = Engine(cfg, clock_scale=0.5, hbm_scale=0.5).run(dots_module)
    assert clock_only.cycles > base.cycles
    assert hbm_only.cycles > base.cycles
    # with chip AND HBM at half rate, every term doubles exactly
    assert both.cycles == pytest.approx(2.0 * base.cycles, rel=1e-9)
    # a single-sided derate cannot exceed the fully-derated bound
    assert clock_only.cycles <= both.cycles
    assert hbm_only.cycles <= both.cycles


def test_hbm_throttle_inflates_memory_bound_cycles(dots_module):
    cfg = SimConfig()
    base = Engine(cfg).run(dots_module)
    slow = Engine(cfg, hbm_scale=0.25).run(dots_module)
    assert slow.cycles > base.cycles


def test_engine_rejects_out_of_range_scales():
    with pytest.raises(ValueError, match="clock_scale"):
        Engine(SimConfig(), clock_scale=0.0)
    with pytest.raises(ValueError, match="clock_scale"):
        Engine(SimConfig(), hbm_scale=1.5)


# -- driver integration ------------------------------------------------------

TRACE = FIXTURES / "traces" / "llama_tiny_tp2dp2"


def _replay(**kw):
    from tpusim.sim.driver import simulate_trace

    return simulate_trace(TRACE, arch="v5p", tuned=False, **kw)


def test_driver_stamps_fault_stats_only_when_enabled():
    healthy = _replay()
    assert not any(
        k.startswith("faults_") for k in healthy.stats.values
    )
    topo = torus_for(healthy.num_devices, "v5p")
    a, b = topo.undirected_links()[0]
    faulted = _replay(faults=link_down_schedule(topo, a, b), topology=topo)
    s = faulted.stats
    assert s.get("faults_active") == 1
    assert s.get("faults_links_down") == 2       # directed count
    assert s.get("faults_min_link_scale") == 0.0
    assert faulted.cycles > healthy.cycles


def test_driver_straggler_slows_only_that_chips_kernels():
    from tpusim.ir import CommandKind, PodTrace, TraceCommand
    from tpusim.sim.driver import SimDriver

    # two devices launching the same (collective-free) module: only the
    # straggler's kernel re-times under its multiplier class
    def pod():
        p = PodTrace(meta={"num_devices": 2})
        p.modules["m"] = parse_hlo_module(_DOTS_HLO)
        for d in (0, 1):
            p.device(d).commands.append(TraceCommand(
                kind=CommandKind.KERNEL_LAUNCH, device_id=d, module="m",
            ))
        return p

    cfg = SimConfig()
    healthy = SimDriver(cfg).run(pod())
    st = SimDriver(cfg, faults={"faults": [{
        "kind": "chip_straggler", "chip": 0, "clock_scale": 0.5,
    }]}).run(pod())
    assert st.stats.get("faults_chips_degraded") == 1
    assert st.cycles > healthy.cycles
    k_h = {k.device_id: k.end_cycle - k.start_cycle
           for k in healthy.kernels}
    k_s = {k.device_id: k.end_cycle - k.start_cycle for k in st.kernels}
    assert k_s[0] > k_h[0]
    assert k_s[1] == pytest.approx(k_h[1])


def test_driver_accepts_schedule_path(tmp_path):
    topo = torus_for(4, "v5p")
    a, b = topo.undirected_links()[0]
    p = tmp_path / "sched.json"
    p.write_text(json.dumps(link_down_schedule(topo, a, b).to_doc()))
    rep = _replay(faults=str(p))
    assert rep.stats.get("faults_links_down") == 2


def test_obs_surfaces_faults_active_series():
    from tpusim.obs import Instrumentation, window_rows

    obs = Instrumentation()
    topo = torus_for(4, "v5p")
    a, b = topo.undirected_links()[0]
    rep = _replay(
        faults=link_down_schedule(topo, a, b), topology=topo, obs=obs,
    )
    rows = window_rows(rep.samples, rep.arch_config, 1)
    assert rows and all("faults_active" in r for r in rows)
    # one unwindowed fault: active in (essentially) every window
    assert max(r["faults_active"] for r in rows) == pytest.approx(1.0)
    # healthy obs runs carry the key at 0.0
    obs2 = Instrumentation()
    rep2 = _replay(obs=obs2)
    rows2 = window_rows(rep2.samples, rep2.arch_config, 1)
    assert all(r["faults_active"] == 0.0 for r in rows2)


def test_windowed_link_fault_applies_only_within_window():
    """A link fault with a cycle window hits the standalone collectives
    it overlaps and spares the ones before it."""
    from tpusim.ir import CommandKind, CollectiveInfo, PodTrace, TraceCommand
    from tpusim.sim.driver import SimDriver

    n, nb = 8, 64 * MB
    topo = Topology(dims=(8,), wrap=(True,))
    info = CollectiveInfo("all-reduce", replica_groups=(tuple(range(n)),))

    def pod():
        p = PodTrace(meta={"num_devices": n})
        for d in range(n):
            for _ in range(2):
                p.device(d).commands.append(TraceCommand(
                    kind=CommandKind.COLLECTIVE, device_id=d, nbytes=nb,
                    collective=info,
                ))
        return p

    cfg = SimConfig()
    healthy = SimDriver(cfg, topology=topo).run(pod())
    first_end = healthy.cycles / 2.0  # two identical back-to-back colls

    def dead_wrap(window):
        rec = {"kind": "link_down", "src": 0, "dst": 7}
        rec.update(window)
        return {"faults": [rec]}

    full = SimDriver(cfg, topology=topo, faults=dead_wrap({})).run(pod())
    # window opens just before the second collective issues (at
    # first_end), so the first prices healthy and the second degraded
    windowed = SimDriver(
        cfg, topology=topo,
        faults=dead_wrap({"start_cycle": first_end * 0.99}),
    ).run(pod())
    # only the second collective runs degraded: strictly between the
    # healthy and fully-faulted pods
    assert healthy.cycles < windowed.cycles < full.cycles
    assert windowed.cycles == pytest.approx(
        (healthy.cycles + full.cycles) / 2.0, rel=1e-6
    )


def test_windowed_straggler_hits_only_overlapped_kernels():
    """Chip-fault windows resolve at kernel-issue grain: a straggler
    window opening after the first launch slows only the second."""
    from tpusim.ir import CommandKind, PodTrace, TraceCommand
    from tpusim.sim.driver import SimDriver

    def pod():
        p = PodTrace(meta={"num_devices": 1})
        p.modules["m"] = parse_hlo_module(_DOTS_HLO)
        for _ in range(2):
            p.device(0).commands.append(TraceCommand(
                kind=CommandKind.KERNEL_LAUNCH, device_id=0, module="m",
            ))
        return p

    def straggle(window):
        rec = {"kind": "chip_straggler", "chip": 0, "clock_scale": 0.5}
        rec.update(window)
        return {"faults": [rec]}

    cfg = SimConfig()
    healthy = SimDriver(cfg).run(pod())
    first_end = healthy.cycles / 2.0
    full = SimDriver(cfg, faults=straggle({})).run(pod())
    windowed = SimDriver(
        cfg, faults=straggle({"start_cycle": first_end * 0.99}),
    ).run(pod())
    late = SimDriver(
        cfg, faults=straggle({"start_cycle": healthy.cycles * 10}),
    ).run(pod())
    assert healthy.cycles < windowed.cycles < full.cycles
    # a window that never opens during the run changes nothing (but the
    # schedule-shape stats still describe it)
    assert late.cycles == pytest.approx(healthy.cycles)
    assert late.stats.get("faults_chips_degraded") == 1


# -- single-link-failure sweep ----------------------------------------------

def test_single_link_sweep_inflates_every_scenario():
    topo = torus_for(64, "v5p")
    res = single_link_sweep(topo, ICI, payload_bytes=64 * MB)
    assert len(res.rows) == len(topo.undirected_links()) == 192
    assert all(r.inflation > 1.0 for r in res.rows)
    assert res.worst is not None and res.worst.inflation > 1.0
    doc = res.to_doc()
    assert doc["scenarios"] == 192 and doc["worst_inflation"] > 1.0


def test_faults_cli_sweep(capsys, tmp_path):
    from tpusim.__main__ import main

    out = tmp_path / "sweep.json"
    rc = main([
        "faults", "--arch", "v5p", "--chips", "64",
        "--payload-mb", "16", "--top", "3", "--json", str(out),
    ])
    assert rc == 0
    text = capsys.readouterr().out
    assert "4x4x4 torus (64 chips, 192 scenarios)" in text
    assert "worst-case inflation" in text
    assert "192/192 scenarios inflate" in text
    doc = json.loads(out.read_text())
    assert doc["sweep_kind"] == "collective"
    assert len(doc["rows"]) == 192
