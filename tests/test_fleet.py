"""tpusim.fleet — the traffic-driven fleet digital twin.

Covers the subsystem's contracts: spec validation codes (TL24x),
deterministic seeded arrivals and fault streams, the event walk's
loss-attribution taxonomy (shed / deadline / partition / restart, each
pinned by a hand-built scenario), same-seed byte-identical report
documents, crash-safe resume (SIGKILL mid-run → ``--resume`` re-prices
ZERO journaled pricing intervals and matches the uninterrupted report
byte-for-byte), elastic-recovery rows, and the ``POST /v1/fleet`` serve
path producing the CLI-identical document.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import pytest

from tpusim.fleet import (
    FleetSpecError,
    load_fleet_spec,
    run_fleet,
    simulate_cell,
)
from tpusim.fleet.runner import PodState, build_intervals
from tpusim.fleet.spec import Policies
from tpusim.fleet.traffic import sample_arrivals, sample_pod_stream

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "fixtures" / "traces"
TRACE = FIXTURES / "llama_tiny_tp2dp2"


def base_spec(**over) -> dict:
    doc = {
        "name": "t-fleet", "seed": 3, "pods": 2,
        "arch": "v5p", "chips": 8, "tuned": False,
        "horizon_s": 30.0,
        "traffic": {
            "load_points": [6.0],
            "mix": [{"name": "chat", "weight": 3.0, "steps": 50},
                    {"name": "batch", "weight": 1.0, "steps": 200}],
        },
        "faults": {
            "count": {"dist": "uniform", "min": 0, "max": 2},
            "kinds": {"link_down": 1.0, "hbm_throttle": 1.0},
            "scale": {"min": 0.4, "max": 0.9},
            "window": {"min_s": 5.0, "max_s": 15.0},
            "pod_loss": {"prob": 0.9},
        },
        "policies": {"max_inflight": 1, "queue_depth": 4,
                     "deadline_s": 0.5, "restart_backoff_s": 3.0},
    }
    doc.update(over)
    return doc


# -- spec validation ---------------------------------------------------------


def test_spec_parses_and_defaults():
    spec = load_fleet_spec(base_spec())
    assert spec.pods == 2
    assert spec.horizon_s == 30.0
    assert spec.policies.queue_depth == 4
    assert spec.traffic.mix[1].steps == 200
    assert spec.faults.pod_loss_prob == 0.9
    # bare defaults compose too
    spec2 = load_fleet_spec({"seed": 1})
    assert spec2.pods == 1
    assert spec2.traffic.shape == "poisson"


@pytest.mark.parametrize("mutate, code", [
    ({"pods": 0}, "TL240"),
    ({"policies": {"deadline_s": 0.0}}, "TL240"),
    ({"policies": {"warp_core": 1}}, "TL240"),
    ({"faults": {"kinds": ["gamma_burst"]}}, "TL240"),
    ({"faults": {"pod_loss": {"prob": 2.0}}}, "TL240"),
    ({"recovery": {"dcn_gbps": 0}}, "TL240"),
    ({"traffic": {"shape": "tidal"}}, "TL241"),
    ({"traffic": {"load_points": []}}, "TL241"),
    ({"traffic": {"load_points": [1e9]}, "horizon_s": 3600.0}, "TL241"),
    ({"traffic": {"mix": [{"name": "a", "weight": 0}]}}, "TL241"),
    ({"traffic": {"burst": {"factor": 20.0, "fraction": 0.5}}},
     "TL241"),
    ({"slo": {"latency_ms": 100.0, "percentile": 250}}, "TL242"),
    ({"frontier": {"target_rps": [10.0], "max_pods": 4}}, "TL242"),
])
def test_spec_rejections_carry_codes(mutate, code):
    doc = base_spec(**mutate)
    if code == "TL242" and "slo" not in mutate:
        doc.pop("slo", None)
    with pytest.raises(FleetSpecError) as ei:
        load_fleet_spec(doc)
    assert ei.value.code == code


def test_analyzer_maps_group_against_torus():
    from tpusim.analysis import analyze_fleet_spec

    diags = analyze_fleet_spec(
        base_spec(correlated_groups=[
            {"name": "ghost", "prob": 0.5, "axis": 7},
        ]),
        default_chips=8,
    )
    assert "TL243" in diags.codes()


# -- seeded inputs -----------------------------------------------------------


def test_arrivals_deterministic_and_rate_keyed():
    spec = load_fleet_spec(base_spec())
    a1 = sample_arrivals(spec.traffic, spec.seed, 6.0, 30.0)
    a2 = sample_arrivals(spec.traffic, spec.seed, 6.0, 30.0)
    assert a1 == a2
    assert a1 != sample_arrivals(spec.traffic, spec.seed, 7.0, 30.0)
    # open-loop Poisson at 6 req/s over 30s: a seeded draw near 180
    assert 120 <= len(a1) <= 260
    assert all(0.0 <= t < 30.0 for t, _ in a1)
    assert all(cls in (0, 1) for _, cls in a1)


def test_bursty_arrivals_preserve_mean_rate():
    spec = load_fleet_spec(base_spec(traffic={
        "shape": "bursty", "load_points": [20.0],
        "burst": {"factor": 4.0, "fraction": 0.1, "period_s": 10.0},
    }))
    arr = sample_arrivals(spec.traffic, spec.seed, 20.0, 30.0)
    assert 400 <= len(arr) <= 800        # mean 600, loose seeded bounds


def test_pod_streams_are_per_pod_substreams():
    from tpusim.ici.topology import torus_for

    spec = load_fleet_spec(base_spec())
    topo = torus_for(8, "v5p")
    s0 = sample_pod_stream(spec, topo, 0)
    s1 = sample_pod_stream(spec, topo, 1)
    assert s0 == sample_pod_stream(spec, topo, 0)   # deterministic
    assert s0 != s1                                  # independent
    tl = build_intervals(s0, spec.horizon_s)
    assert tl[0][0] == 0.0 and tl[-1][1] == spec.horizon_s
    # contiguous cover
    for (a, b, _s, _d), (c, _e, _s2, _d2) in zip(tl, tl[1:]):
        assert b == c


# -- the event walk's attribution taxonomy ----------------------------------


def _row(step_s=0.1, energy=2.0, partitioned=False):
    return {"partitioned": partitioned, "step_s": step_s,
            "energy_j": energy, "inflation": 1.0}


def _pod(intervals=None, deaths=(), horizon=100.0):
    if intervals is None:
        intervals = [(0.0, horizon, _row())]
    return PodState(intervals=intervals, deaths=list(deaths))


_POL = Policies(max_inflight=1, queue_depth=8, deadline_s=100.0,
                restart_backoff_s=3.0)


def test_partition_window_requests_land_in_partition_bucket():
    """Requests dispatched into a partition window are partition
    losses — not shed, not deadline — and service resumes after."""
    pod = _pod(intervals=[
        (0.0, 50.0, _row()),
        (50.0, 80.0, _row(partitioned=True)),
        (80.0, 100.0, _row()),
    ])
    cell = simulate_cell(
        [(10.0, 0), (55.0, 0), (60.0, 0), (90.0, 0)],
        [pod], _POL, 100.0, healthy_step_s=0.1, mix_steps=[1],
    )
    assert cell["losses"] == {"deadline": 0, "partition": 2,
                              "restart": 0, "shed": 0}
    assert cell["served"] == 2


def test_queue_full_sheds():
    pol = Policies(max_inflight=1, queue_depth=1, deadline_s=100.0,
                   restart_backoff_s=3.0)
    pod = _pod(intervals=[(0.0, 100.0, _row(step_s=10.0))])
    cell = simulate_cell(
        [(0.0, 0), (1.0, 0), (2.0, 0), (3.0, 0)],
        [pod], pol, 100.0, healthy_step_s=10.0, mix_steps=[1],
    )
    # t=0 starts, t=1 queues (depth 1 full), t=2 and t=3 shed
    assert cell["losses"]["shed"] == 2
    assert cell["served"] == 2


def test_deadline_cooperative_cancel_frees_server_at_budget():
    pol = Policies(max_inflight=1, queue_depth=8, deadline_s=5.0,
                   restart_backoff_s=3.0)
    pod = _pod(intervals=[(0.0, 100.0, _row(step_s=10.0))])
    cell = simulate_cell(
        [(0.0, 0), (6.0, 0)],
        [pod], pol, 100.0, healthy_step_s=10.0, mix_steps=[1],
    )
    # both requests outlive the budget: cancelled at t+5, the server
    # freed at the deadline instant (t=6 starts at 6, not at 10)
    assert cell["losses"]["deadline"] == 2
    assert cell["served"] == 0


def test_pod_crash_kills_inflight_and_redistributes():
    pod0 = _pod(intervals=[(0.0, 100.0, _row(step_s=10.0))],
                deaths=[(5.0, 8.0)])
    pod1 = _pod()
    # rr dispatch: t=0 -> pod0 (in flight at the crash -> restart),
    # t=6 -> pod1 (pod0 down, next alive pod takes it), t=9 -> pod0
    cell = simulate_cell(
        [(0.0, 0), (6.0, 0), (9.0, 0)],
        [pod0, pod1], _POL, 100.0, healthy_step_s=0.1, mix_steps=[1],
    )
    assert cell["losses"]["restart"] == 1
    assert cell["served"] == 2


def test_crash_beats_queued_deadline_attribution():
    """A request queued past its deadline while the pod CRASHES first
    is a restart loss, not a deadline loss — the crash killed the wait
    line before the 504 would have fired."""
    pol = Policies(max_inflight=1, queue_depth=8, deadline_s=1.0,
                   restart_backoff_s=20.0)
    pod = _pod(intervals=[(0.0, 100.0, _row(step_s=0.5))],
               deaths=[(0.8, 20.8)])
    # A serves (done before the crash); B and C are in flight/queued
    # across it; D's virtual start (1.5) is past its deadline, but the
    # crash at 0.8 got the line first
    cell = simulate_cell(
        [(0.0, 0), (0.01, 0), (0.05, 0), (0.1, 0)],
        [pod], pol, 100.0, healthy_step_s=0.5, mix_steps=[1],
    )
    assert cell["served"] == 1
    assert cell["losses"] == {"deadline": 0, "partition": 0,
                              "restart": 3, "shed": 0}


def test_all_pods_down_is_a_restart_loss():
    pod = _pod(deaths=[(5.0, 8.0)])
    cell = simulate_cell(
        [(6.0, 0)], [pod], _POL, 100.0,
        healthy_step_s=0.1, mix_steps=[1],
    )
    assert cell["losses"]["restart"] == 1
    assert cell["served"] == 0


def test_energy_and_mfu_accounting():
    pod = _pod(intervals=[(0.0, 100.0, _row(step_s=2.0, energy=3.0))])
    cell = simulate_cell(
        [(0.0, 0), (10.0, 1)], [pod], _POL, 100.0,
        healthy_step_s=2.0, mix_steps=[1, 2],
    )
    assert cell["served"] == 2
    assert cell["energy_j"] == pytest.approx(3.0 * 1 + 3.0 * 2)
    # 3 healthy-equivalent steps x 2s over 100 server-seconds
    assert cell["mfu"] == pytest.approx(6.0 / 100.0)


# -- end-to-end determinism --------------------------------------------------


def test_same_seed_reproduces_report_byte_for_byte():
    a = run_fleet(base_spec(), trace_path=TRACE)
    b = run_fleet(base_spec(), trace_path=TRACE)
    assert json.dumps(a.doc, sort_keys=True) == \
        json.dumps(b.doc, sort_keys=True)
    assert a.stats.stats_dict() == b.stats.stats_dict()
    # a different seed is a different fleet
    c = run_fleet(base_spec(seed=4), trace_path=TRACE)
    assert json.dumps(c.doc, sort_keys=True) != \
        json.dumps(a.doc, sort_keys=True)


def test_axis_group_partitions_and_attributes():
    """A correlated axis outage (prob 1) splits the 2x2x2 torus: the
    state prices as partitioned and the window's requests land in the
    partition bucket of the curve."""
    spec = base_spec(
        # axis 0 splits the fixture's 4 REPLAYING chips across the cut
        # (axis 2 would only separate the replay set from idle chips)
        correlated_groups=[{"name": "axis-x", "prob": 1.0, "axis": 0}],
        faults={
            "count": {"dist": "fixed", "n": 0},
            "window": {"min_s": 10.0, "max_s": 20.0},
            "pod_loss": {"prob": 0.0},
        },
        traffic={"load_points": [8.0],
                 "mix": [{"name": "chat", "weight": 1.0, "steps": 20}]},
    )
    res = run_fleet(spec, trace_path=TRACE)
    assert res.stats.states_partitioned >= 1
    row = res.doc["curve"][0]
    assert row["losses"]["partition"] > 0
    assert row["requests"] == row["served"] + sum(
        row["losses"].values()
    )


def test_recovery_rows_price_rerank_and_migration():
    res = run_fleet(base_spec(), trace_path=TRACE)
    assert res.stats.pod_losses >= 1
    assert res.doc["recovery"], "seeded pod losses produced no rows"
    for r in res.doc["recovery"]:
        assert r["time_to_recover_s"] >= r["restart_s"]
        assert r["migration_s"] > 0
        if r["survivors"] >= 1:
            labels = {c["candidate"] for c in r["rerank"]}
            assert "keep" in labels
            assert r["chosen"] in labels
            for c in r["rerank"]:
                assert c["step_ms"] > 0
                assert c["fleet_rps"] > 0
            # the choice maximizes effective fleet throughput —
            # requests-worth of the original load served per second
            best = max(c["fleet_rps"] for c in r["rerank"])
            chosen_row = next(
                c for c in r["rerank"] if c["candidate"] == r["chosen"]
            )
            assert chosen_row["fleet_rps"] == best


def test_frontier_answers_pods_needed():
    spec = base_spec(
        slo={"latency_ms": 400.0, "percentile": 95},
        frontier={"target_rps": [10.0], "max_pods": 4},
    )
    res = run_fleet(spec, trace_path=TRACE)
    table = res.doc["frontier"]["table"]
    assert len(table) == 1
    need = table[0]["pods_needed"]
    assert need is not None and 1 <= need <= 4
    # the ladder stops at the first meeting size
    assert table[0]["cells"][-1]["slo"]["meets"]
    assert all(not c["slo"]["meets"] for c in table[0]["cells"][:-1])


def test_fleet_keys_only_when_fleet_ran():
    """The campaign_* discipline: a healthy simulate run stamps no
    fleet_* keys (the namespace is registered and owned)."""
    from tpusim.analysis.statskeys import STATS_NAMESPACES
    from tpusim.sim.driver import simulate_trace

    assert "fleet_" in STATS_NAMESPACES
    assert "tpusim/fleet/" in STATS_NAMESPACES["fleet_"]
    report = simulate_trace(str(TRACE), arch="v5p", tuned=False)
    stats = json.loads(report.stats.to_json())
    assert not [k for k in stats if k.startswith("fleet_")]


# -- crash-safe resume -------------------------------------------------------

KILL_SCRIPT = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
from tpusim.fleet import run_fleet

done = 0
def progress(msg):
    global done
    done += 1
    if done == {kill_after}:
        os.kill(os.getpid(), signal.SIGKILL)   # no cleanup, no atexit

run_fleet(
    {spec!r}, trace_path={trace!r}, out_dir={out!r}, progress=progress,
)
"""


def test_resume_after_sigkill_reprices_zero_journaled(tmp_path):
    """SIGKILL mid-run; --resume completes while re-pricing ONLY the
    states the journal does not already hold, and the stitched report
    is byte-identical to an uninterrupted run."""
    from tpusim.campaign.journal import Journal

    spec = base_spec(seed=3)      # seed 3 prices 3 distinct states
    out = tmp_path / "fleet"
    kill_after = 2
    script = KILL_SCRIPT.format(
        repo=str(REPO), spec=spec, trace=str(TRACE), out=str(out),
        kill_after=kill_after,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr

    pre = [
        r for r in Journal(out).read_records()
        if r.get("kind") in ("state", "recovery")
    ]
    # the journal is a true prefix: exactly the rows whose progress
    # message fired before the kill (states price before recoveries)
    assert len(pre) == kill_after
    assert all(r["kind"] == "state" for r in pre)

    import tpusim.fleet.runner as runner_mod

    priced = {"n": 0}
    orig = runner_mod._price

    def counting(*args, **kw):
        priced["n"] += 1
        return orig(*args, **kw)

    runner_mod._price = counting
    try:
        res = run_fleet(spec, trace_path=TRACE, out_dir=out, resume=True)
    finally:
        runner_mod._price = orig

    clean = run_fleet(spec, trace_path=TRACE)
    total_states = clean.stats.states_priced
    assert total_states > kill_after   # the kill landed mid-pricing
    assert res.stats.states_resumed == kill_after
    assert res.stats.states_priced == total_states - kill_after
    assert priced["n"] == total_states - kill_after
    assert json.dumps(res.doc, sort_keys=True) == \
        json.dumps(clean.doc, sort_keys=True)


def test_full_journal_resume_prices_nothing(tmp_path):
    """Resume over a COMPLETE journal re-prices zero intervals: no
    state replays, no recovery replays, no engine walks at all."""
    import tpusim.sim.driver as driver_mod

    spec = base_spec()
    out = tmp_path / "fleet"
    first = run_fleet(spec, trace_path=TRACE, out_dir=out)

    runs = {"n": 0}
    orig_run = driver_mod.SimDriver.run

    def counting_run(self, pod):
        runs["n"] += 1
        return orig_run(self, pod)

    driver_mod.SimDriver.run = counting_run
    try:
        res = run_fleet(spec, trace_path=TRACE, out_dir=out, resume=True)
    finally:
        driver_mod.SimDriver.run = orig_run

    assert runs["n"] == 0
    assert res.stats.states_priced == 0
    assert json.dumps(res.doc, sort_keys=True) == \
        json.dumps(first.doc, sort_keys=True)
    assert (out / "report.json").is_file()


def test_fresh_journal_refuses_to_clobber(tmp_path):
    from tpusim.fleet import JournalError

    spec = base_spec()
    run_fleet(spec, trace_path=TRACE, out_dir=tmp_path)
    with pytest.raises(JournalError, match="resume"):
        run_fleet(spec, trace_path=TRACE, out_dir=tmp_path)


def test_resume_refuses_a_different_fleet(tmp_path):
    from tpusim.fleet import JournalError

    run_fleet(base_spec(), trace_path=TRACE, out_dir=tmp_path)
    with pytest.raises(JournalError, match="refusing"):
        run_fleet(base_spec(seed=99), trace_path=TRACE,
                  out_dir=tmp_path, resume=True)


# -- serve path --------------------------------------------------------------


def test_served_fleet_doc_is_byte_identical_to_direct(tmp_path):
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    spec = base_spec()
    direct = run_fleet(spec, trace_path=TRACE)
    with ServeDaemon(trace_root=FIXTURES, port=0) as d:
        c = ServeClient(d.url)
        job_id = c.fleet(spec=spec, trace=TRACE.name)
        status = c.wait_job(job_id, timeout_s=300)
        assert status.status == "done", status.error
        assert json.dumps(status.result, sort_keys=True) == \
            json.dumps(direct.doc, sort_keys=True)
        # the executor totals ride /metrics under the serve_fleet_* name
        metrics = c.metrics_text()
        assert "serve_fleet_requests_total" in metrics


def test_bad_fleet_spec_fails_job_with_code(tmp_path):
    from tpusim.serve.client import ServeClient
    from tpusim.serve.daemon import ServeDaemon

    with ServeDaemon(trace_root=FIXTURES, port=0) as d:
        c = ServeClient(d.url)
        job_id = c.fleet(spec={"pods": 0}, trace=TRACE.name)
        status = c.wait_job(job_id, timeout_s=60)
        assert status.status == "failed"
        assert "bad_fleet_spec" in (status.error or "")
