"""serve v3 — multi-acceptor front tier + shared mmap hot-response cache.

Covers the new contracts on top of tests/test_serve.py's daemon suite:

* :class:`~tpusim.serve.hotcache.HotResponseCache` unit behavior —
  publish/read round trip across instances (processes), generation
  invalidation, quota rotation, concurrent publishers;
* the hot path end-to-end: a warm repeat served from the mmap is
  byte-identical (modulo wall-clock keys) to a REAL warm priced
  response, ``cache_hit`` true, counted on /metrics;
* the front fleet: SO_REUSEPORT acceptors behind one port, fleet
  /healthz + /metrics merges, job-family routes proxied to the primary
  acceptor, acceptor SIGKILL healing under live traffic, and the
  fd-passing fallback (``TPUSIM_NO_REUSEPORT=1``);
* the client's serve v3 retry discipline: idempotent POSTs (simulate)
  reconnect-and-retry through a connection reset from a recycled
  acceptor; job submissions are still never replayed.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from pathlib import Path

import pytest

from tpusim.serve.client import ServeClient, ServeError
from tpusim.serve.daemon import ServeDaemon
from tpusim.serve.front import FrontSupervisor
from tpusim.serve.hotcache import HotResponseCache

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "traces"

VOLATILE = {"simulation_rate_kops", "wall_seconds", "silicon_slowdown"}


def canonical(stats: dict) -> str:
    return json.dumps(
        {k: v for k, v in stats.items() if k not in VOLATILE},
        indent=1, sort_keys=True,
    )


def _metric(text: str, key: str) -> float:
    for line in text.splitlines():
        if line.startswith(f"tpusim_{key} "):
            return float(line.split()[1])
    return 0.0


# ---------------------------------------------------------------------------
# HotResponseCache unit behavior
# ---------------------------------------------------------------------------


def test_hotcache_round_trip_across_instances(tmp_path):
    """A publish by one instance is readable by a second instance on
    the same dir (the cross-process shape, minus the fork)."""
    a = HotResponseCache(tmp_path, generation="g1")
    assert a.get("k") is None
    assert a.publish("k", b'{"x": 1}\n')
    got = a.get("k")
    assert bytes(got) == b'{"x": 1}\n'
    b = HotResponseCache(tmp_path, generation="g1")
    assert bytes(b.get("k")) == b'{"x": 1}\n'
    # first writer wins; a duplicate publish is a no-op
    assert not b.publish("k", b"other")
    assert bytes(a.get("k")) == b'{"x": 1}\n'
    assert "k" in a and "missing" not in a


def test_hotcache_generation_invalidation(tmp_path):
    """A generation bump (model_version / format / tuned state moved)
    orphans the old entries AND reaps the old files."""
    old = HotResponseCache(tmp_path, generation="old1")
    old.publish("k", b"stale")
    new = HotResponseCache(tmp_path, generation="new2")
    assert new.get("k") is None
    names = {p.name for p in tmp_path.iterdir()}
    assert not any("old1" in n for n in names), names


def test_hotcache_quota_rotation(tmp_path):
    """Past the quota the writer rotates to a fresh epoch segment; old
    keys become misses (repopulated in one request each), the new key
    serves, and the store never exceeds the quota."""
    c = HotResponseCache(tmp_path, generation="g", quota_bytes=1 << 16)
    body = b"x" * 4096
    for i in range(20):  # 80 KB through a 64 KB quota
        assert c.publish(f"k{i}", body)
    assert c.rotations >= 1
    seg = tmp_path / c._read_index_doc()["segment"]
    assert seg.stat().st_size <= 1 << 16
    assert bytes(c.get("k19")) == body   # newest entry survives
    assert c.get("k0") is None           # rotated away


def test_hotcache_oversized_body_never_publishes(tmp_path):
    c = HotResponseCache(tmp_path, generation="g", quota_bytes=1 << 16)
    assert not c.publish("big", b"x" * (1 << 15))  # > quota/8
    assert c.get("big") is None


def test_hotcache_concurrent_publishers(tmp_path):
    """Two instances racing distinct keys (the two-acceptor shape):
    every key lands, every body reads back exactly."""
    a = HotResponseCache(tmp_path, generation="g")
    b = HotResponseCache(tmp_path, generation="g")
    errors = []

    def pump(cache, prefix):
        try:
            for i in range(25):
                cache.publish(f"{prefix}{i}", f"{prefix}{i}".encode())
        except Exception as e:  # noqa: BLE001 - the assertion below
            errors.append(e)

    ts = [
        threading.Thread(target=pump, args=(a, "a")),
        threading.Thread(target=pump, args=(b, "b")),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    reader = HotResponseCache(tmp_path, generation="g")
    for prefix in ("a", "b"):
        for i in range(25):
            assert bytes(reader.get(f"{prefix}{i}")) == \
                f"{prefix}{i}".encode()


# ---------------------------------------------------------------------------
# Hot path end-to-end (standalone daemon)
# ---------------------------------------------------------------------------


def test_hot_served_bytes_match_real_warm_response(tmp_path):
    """The hot tier stores SYNTHESIZED warm-form bytes on first
    pricing; they must equal what a real (result-cache-hit) warm
    response produces — cache accounting included, wall-clock keys
    excepted."""
    hot = ServeDaemon(
        trace_root=FIXTURES, max_inflight=4, hot_cache=tmp_path / "hot",
    ).start()
    plain = ServeDaemon(trace_root=FIXTURES, max_inflight=4).start()
    try:
        ch = ServeClient(hot.url)
        cp = ServeClient(plain.url)
        first = ch.simulate(trace="matmul_512", arch="v5e")
        assert not first.cache_hit
        served = ch.simulate(trace="matmul_512", arch="v5e")
        assert served.cache_hit  # from the mmap, warm form
        assert _metric(ch.metrics_text(), "serve_hot_hits_total") == 1
        cp.simulate(trace="matmul_512", arch="v5e")
        real_warm = cp.simulate(trace="matmul_512", arch="v5e")
        assert real_warm.cache_hit
        assert canonical(served.stats) == canonical(real_warm.stats)
        # the accounting keys too: synthesis must fold misses exactly
        assert served.stats.get("cache_hits") == \
            real_warm.stats.get("cache_hits")
        assert served.stats.get("cache_misses") == \
            real_warm.stats.get("cache_misses")
    finally:
        hot.drain_and_stop()
        plain.drain_and_stop()


def test_hot_entries_keyed_by_trace_content(tmp_path):
    """A hot dir surviving a restart must not serve bytes priced from
    DIFFERENT on-disk trace content: the key carries a stat fingerprint
    of the trace dir."""
    d = ServeDaemon(
        trace_root=FIXTURES, max_inflight=4, hot_cache=tmp_path / "hot",
    )
    body = {"trace": "matmul_512", "arch": "v5e", "tuned": True}
    k1 = d.hot_key_for("simulate", body)
    assert k1 is not None
    # unknown traces are not hot-servable (the 404 path answers)
    assert d.hot_key_for("simulate", {"trace": "nope"}) is None
    # a different fingerprint yields a different key
    d2 = ServeDaemon(
        trace_root=FIXTURES, max_inflight=4, hot_cache=tmp_path / "hot",
    )
    d2._trace_fp_cache["matmul_512"] = "different"
    assert d2.hot_key_for("simulate", body) != k1
    # volatile keys never fragment the hot tier
    assert d.hot_key_for(
        "simulate", {**body, "deadline_ms": 123}
    ) == k1


# ---------------------------------------------------------------------------
# Front fleet (SO_REUSEPORT acceptors)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def front(tmp_path_factory):
    # Acceptors pin their environment AT FORK TIME.  This module-scoped
    # fixture is created before the function-scoped autouse
    # ``_isolate_tuned_overlays`` patch, so without pinning here the
    # fleet would fork with the repo's tuned overlays visible while
    # in-process daemons compose lazily under the per-test isolation —
    # byte-identity would then fail on a config skew, not a front bug
    # (the exact serve v2 pool-fixture lesson).
    import os

    old = os.environ.get("TPUSIM_TUNED_DIR")
    os.environ["TPUSIM_TUNED_DIR"] = str(
        tmp_path_factory.mktemp("no_tuned_front")
    )
    td = tmp_path_factory.mktemp("front")
    f = FrontSupervisor(
        settings={
            "trace_root": str(FIXTURES),
            "max_inflight": 4,
            "hot_cache": str(td / "hot"),
            "quarantine_dir": str(td / "quarantine"),
        },
        num_acceptors=2,
        restart_backoff_s=0.1,
    ).start()
    try:
        yield f
    finally:
        f.stop()
        if old is None:
            os.environ.pop("TPUSIM_TUNED_DIR", None)
        else:
            os.environ["TPUSIM_TUNED_DIR"] = old


@pytest.fixture(scope="module")
def front_client(front):
    return ServeClient(front.url, retries=3)


def test_front_round_trip_and_fleet_health(front, front_client):
    health = front_client.healthz()
    assert health["status"] == "ok"
    assert health["acceptors_alive"] == 2
    assert health["acceptors_configured"] == 2
    indices = {a["acceptor_index"] for a in health["acceptors"]}
    assert indices == {0, 1}
    primary = [a for a in health["acceptors"] if a.get("primary")]
    assert len(primary) == 1 and primary[0]["acceptor_index"] == 0
    r = front_client.simulate(trace="matmul_512", arch="v5e")
    assert r.sim_cycles > 0


def test_front_served_bytes_match_plain_daemon(front_client):
    """Byte-identity across topologies: the fleet's response equals the
    standalone daemon's for the same request."""
    plain = ServeDaemon(trace_root=FIXTURES, max_inflight=4).start()
    try:
        want = ServeClient(plain.url).simulate(
            trace="llama_tiny_tp2dp2", arch="v5p",
        )
        got = front_client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
        assert canonical(got.stats) == canonical(want.stats)
    finally:
        plain.drain_and_stop()


def test_front_warm_repeat_is_hot_hit(front, front_client):
    before = _metric(
        front_client.metrics_text(), "serve_hot_hits_total",
    )
    front_client.simulate(trace="matmul_512", arch="v5e")  # publishes
    r = front_client.simulate(trace="matmul_512", arch="v5e")
    assert r.cache_hit
    after = _metric(front_client.metrics_text(), "serve_hot_hits_total")
    assert after > before


def test_front_metrics_merge_is_fleet_wide(front, front_client):
    """/metrics merges every acceptor; ?scope=local stays one
    acceptor's view."""
    fleet = front_client.metrics_text()
    assert _metric(fleet, "serve_acceptors_alive") == 2
    assert _metric(fleet, "serve_acceptors_configured") == 2
    # fleet-summed request counter >= any local view's
    resp, payload = front_client._raw("GET", "/metrics?scope=local")
    local = payload.decode()
    assert "serve_acceptors_alive" not in local
    assert _metric(fleet, "serve_requests_total") >= _metric(
        local, "serve_requests_total",
    )


def test_front_jobs_proxied_to_primary(front, front_client):
    """Async jobs work through ANY acceptor: submissions and polls on
    secondaries proxy to the primary's JobTable (single-owner ids)."""
    ids = set()
    for _ in range(4):  # fresh connections spread over acceptors
        c = ServeClient(front.url)
        jid = c.sweep(arch="v5p", chips=8, payload_mb=1)
        st = c.wait_job(jid, timeout_s=60)
        assert st.status == "done"
        ids.add(jid)
    assert len(ids) == 4  # one id space: the primary owns the table


def test_front_acceptor_kill_heals_under_traffic(front, front_client):
    """SIGKILL one acceptor mid-traffic: zero client-visible failures
    (idempotent simulate retries reconnect onto the survivor), the
    front supervisor respawns the slot, and the fleet view recovers."""
    front_client.simulate(trace="matmul_512", arch="v5e")  # hot
    boots_before = front.slots[1].boots
    for i in range(30):
        if i == 5:
            front.kill_acceptor(1)
        r = ServeClient(front.url, retries=3).simulate(
            trace="matmul_512", arch="v5e",
        )
        assert r.cache_hit
    deadline = time.monotonic() + 20
    while (
        front.slots[1].boots <= boots_before
        or not front.slots[1].alive
    ) and time.monotonic() < deadline:
        time.sleep(0.1)
    assert front.slots[1].boots > boots_before
    assert front.slots[1].alive
    health = front_client.healthz()
    assert health["acceptors_alive"] == 2


def test_front_fd_fallback_serves(tmp_path, monkeypatch):
    """TPUSIM_NO_REUSEPORT=1 forces the parent-accept + send_fds path:
    same API, same bytes, one extra syscall per connection."""
    monkeypatch.setenv("TPUSIM_NO_REUSEPORT", "1")
    f = FrontSupervisor(
        settings={
            "trace_root": str(FIXTURES), "max_inflight": 4,
            "hot_cache": str(tmp_path / "hot"),
        },
        num_acceptors=2,
    ).start()
    try:
        assert not f.reuse_port
        c = ServeClient(f.url)
        assert c.healthz()["acceptors_alive"] == 2
        r1 = c.simulate(trace="matmul_512", arch="v5e")
        r2 = c.simulate(trace="matmul_512", arch="v5e")
        assert not r1.cache_hit and r2.cache_hit
    finally:
        assert f.stop()


# ---------------------------------------------------------------------------
# Shared quarantine (fleet-wide poison refusal)
# ---------------------------------------------------------------------------


def test_shared_quarantine_across_supervisors(tmp_path):
    """A poison verdict published by one acceptor's supervisor refuses
    the same body in ANOTHER acceptor's supervisor immediately — no
    worker deaths spent re-learning it."""
    from tpusim.serve.supervisor import Supervisor
    from tpusim.serve.worker import RequestError

    qdir = tmp_path / "quarantine"
    sup_a = Supervisor({}, num_workers=1, quarantine_dir=qdir)
    sup_b = Supervisor({}, num_workers=1, quarantine_dir=qdir)
    body = {"trace": "t", "hlo_text": None}
    key = Supervisor.affinity_key("simulate", body)
    sup_a._quarantine_add(key, "simulate", body, "killed 2 workers")
    with pytest.raises(RequestError) as ei:
        sup_b.execute("simulate", body)
    assert ei.value.status == 422
    assert ei.value.extra["poison"]["content_hash"] == key
    assert sup_b.poisoned == 1


# ---------------------------------------------------------------------------
# Client retry discipline (serve v3)
# ---------------------------------------------------------------------------


@pytest.fixture()
def resetting_server():
    """A server that RESETS the first connection after reading its
    request (the recycled-acceptor stand-in), then answers normally."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    seen = []
    doc = {
        "format_version": 1, "model_version": "t", "trace": "matmul_512",
        "arch": "v5e", "num_devices": 1, "sim_cycles": 7.0,
        "cache_hit": True, "stats": {}, "job_id": "job-000001",
        "status": "queued",
    }
    body = json.dumps(doc).encode()
    response = (
        b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
        b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body
    )

    def acceptor():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            seen.append(conn)
            try:
                conn.settimeout(5.0)
                buf = b""
                while b"\r\n\r\n" not in buf:
                    buf += conn.recv(65536)
                head = buf.partition(b"\r\n\r\n")[0]
                clen = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length"):
                        clen = int(line.split(b":", 1)[1])
                got = buf.partition(b"\r\n\r\n")[2]
                while len(got) < clen:
                    got += conn.recv(65536)
                if len(seen) == 1:
                    # read fully, then RST: the request was sent AND
                    # received, the response never came — exactly a
                    # SIGKILLed acceptor mid-response
                    conn.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        b"\x01\x00\x00\x00\x00\x00\x00\x00",
                    )
                    conn.close()
                    continue
                conn.sendall(response)
                conn.close()
            except OSError:
                pass

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    try:
        yield srv.getsockname(), seen
    finally:
        srv.close()


def test_client_retries_idempotent_post_on_reset(resetting_server):
    """A fully-sent /v1/simulate POST whose connection is reset by a
    recycled acceptor IS retried (pricing is pure), and succeeds on the
    reconnect."""
    (host, port), seen = resetting_server
    c = ServeClient(
        f"http://{host}:{port}", timeout_s=5.0, retries=2,
        backoff_base_s=0.01,
    )
    r = c.simulate(trace="matmul_512", arch="v5e")
    assert r.sim_cycles == 7.0
    assert len(seen) == 2  # the reset attempt + the successful retry


def test_client_never_replays_job_submission_on_reset(resetting_server):
    """The same reset on a JOB submission is NOT retried: the server
    may have enqueued the job before dying."""
    (host, port), seen = resetting_server
    c = ServeClient(
        f"http://{host}:{port}", timeout_s=5.0, retries=3,
        backoff_base_s=0.01,
    )
    with pytest.raises(ServeError) as ei:
        c.sweep(arch="v5p", chips=8)
    assert ei.value.code == "connection_failed"
    assert len(seen) == 1  # one attempt, no replay


def test_hotcache_enospc_disables_publishes_with_one_warning(
    tmp_path, monkeypatch,
):
    """ENOSPC/EIO graceful degradation on the hot tier: a failed
    segment append warns ONCE, disables further publishes for the
    instance, and requests keep flowing through the ordinary path."""
    import errno
    import warnings as _warnings

    import tpusim.serve.hotcache as H

    c = HotResponseCache(tmp_path, generation="g")
    assert c.publish("k0", b"before")   # the healthy path works

    def boom(seg_path, body):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(H, "_append_segment", boom)
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        assert c.publish("k1", b"body1") is False
        assert c.publish("k2", b"body2") is False   # no re-warn
    disabled = [
        w for w in caught
        if "disabling further hot publishes" in str(w.message)
    ]
    assert len(disabled) == 1
    assert c._publish_disabled
    # reads keep serving what the index already names
    assert bytes(c.get("k0")) == b"before"
    assert c.get("k1") is None
    # a fresh instance (healthy medium again) publishes normally
    monkeypatch.undo()
    fresh = HotResponseCache(tmp_path, generation="g")
    assert fresh.publish("k1", b"body1")
    assert bytes(fresh.get("k1")) == b"body1"
