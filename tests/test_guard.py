"""tpusim.guard — bounded stores, memory governance, cooperative cancel.

The layer's three contracts, pinned:

* **bounded durable stores** — the quota GC deletes whole LRU records
  only, survives any number of concurrent writers (the multi-process
  chaos test races a daemon-shaped writer against forked peers under a
  tight quota and requires ZERO torn reads), and the integrity sweep
  quarantines damage once instead of warning forever;
* **memory watchdog** — the degradation ladder runs in its documented
  order (shrink LRUs → drop compiled tier → force lean streaming) and
  the terminal shed state clears when pressure does;
* **cooperative cancellation** — a tripped token unwinds the serial
  walk, the fastpath, the driver, and the campaign executor at their
  documented grains; an armed-but-untripped token leaves every byte of
  the result unchanged; a cancelled campaign resumes to a report
  byte-identical to an uninterrupted run.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import time
import warnings
from pathlib import Path

import pytest

from tpusim.guard.cancel import CancelToken, OperationCancelled
from tpusim.guard.store import (
    QUARANTINE_DIR,
    clear_store,
    format_size,
    gc_store,
    parse_size,
    scan_store,
    store_bytes,
    verify_store,
)
from tpusim.guard.watchdog import MemoryWatchdog, default_ladder, rss_bytes
from tpusim.perf.cache import (
    CACHE_FORMAT_VERSION,
    ResultCache,
    clear_compiled_cache,
    set_compiled_cache_max,
)
from tpusim.timing.config import load_config
from tpusim.timing.engine import Engine, EngineResult
from tpusim.trace.format import load_trace

FIXTURES = Path(__file__).parent / "fixtures" / "traces"

_VOLATILE = ("simulation_rate_kops", "silicon_slowdown", "wall_seconds")


def _stats(report) -> dict:
    # fastpath_* compile counters are process-global cumulative (they
    # ride only explicitly-backended runs) — outside the per-run
    # byte-identity claim, like the volatile wall-clock stats
    return {
        k: v for k, v in json.loads(report.stats.to_json()).items()
        if k not in _VOLATILE and not k.startswith("fastpath_")
    }


# -- sizes ------------------------------------------------------------------

def test_parse_size_units_and_refusals():
    assert parse_size(None) is None
    assert parse_size(4096) == 4096
    assert parse_size("65536") == 65536
    assert parse_size("64K") == 64 * 1024
    assert parse_size("512M") == 512 << 20
    assert parse_size("2G") == 2 << 30
    assert parse_size("1.5g") == int(1.5 * (1 << 30))
    assert parse_size("2GB") == 2 << 30
    for bad in ("zero", "-4K", "0", ""):
        with pytest.raises(ValueError):
            parse_size(bad)


def test_format_size_round_trip_readable():
    assert format_size(512) == "512B"
    assert format_size(64 * 1024) == "64.0KiB"
    assert format_size(3 * (1 << 30)) == "3.0GiB"


# -- the store: GC / verify / clear -----------------------------------------

def _write_record(d: Path, name: str, nbytes: int, mtime: float) -> Path:
    d.mkdir(parents=True, exist_ok=True)
    p = d / f"{name}.json"
    doc = {
        "format_version": CACHE_FORMAT_VERSION,
        "model_version": "m", "key": name,
        "result": {"pad": "x" * max(nbytes - 120, 0)},
    }
    p.write_text(json.dumps(doc))
    os.utime(p, (mtime, mtime))
    return p


def test_gc_store_deletes_lru_whole_records_to_quota(tmp_path):
    now = time.time()
    # oldest-mtime first is LRU order; each record ~1KB
    for i in range(8):
        _write_record(tmp_path, f"r{i}", 1024, now - 100 + i)
    total = store_bytes(tmp_path)
    res = gc_store(tmp_path, quota_bytes=total // 2)
    assert store_bytes(tmp_path) <= total // 2
    # the oldest records went, the newest survived intact
    assert not (tmp_path / "r0.json").exists()
    assert (tmp_path / "r7.json").exists()
    assert res.deleted >= 4 and res.freed_bytes > 0
    assert res.remaining_entries == len(list(tmp_path.glob("*.json")))


def test_gc_store_entry_quota_and_tmp_reaping(tmp_path):
    now = time.time()
    for i in range(6):
        _write_record(tmp_path, f"r{i}", 256, now - 50 + i)
    stale_tmp = tmp_path / "w.123.tmp"
    stale_tmp.write_text("half a rec")
    os.utime(stale_tmp, (now - 7200, now - 7200))
    fresh_tmp = tmp_path / "w.456.tmp"
    fresh_tmp.write_text("live publish in flight")
    res = gc_store(tmp_path, max_entries=2)
    assert len(list(tmp_path.glob("*.json"))) == 2
    assert res.tmp_reaped == 1
    assert not stale_tmp.exists()
    assert fresh_tmp.exists()  # never reap a possibly-live writer


def test_verify_store_quarantines_damage_once(tmp_path):
    now = time.time()
    _write_record(tmp_path, "good", 512, now)
    (tmp_path / "trunc.json").write_text('{"format_version":')
    stale = {
        "format_version": CACHE_FORMAT_VERSION + 999,
        "model_version": "m", "key": "s", "result": {},
    }
    (tmp_path / "stale.json").write_text(json.dumps(stale))
    old_model = {
        "format_version": CACHE_FORMAT_VERSION,
        "model_version": "ancient", "key": "o", "result": {},
    }
    (tmp_path / "oldmodel.json").write_text(json.dumps(old_model))
    res = verify_store(tmp_path, model_version="m")
    assert res.quarantined_corrupt == 1
    assert res.quarantined_stale_format == 1
    # old-model records are well-formed: counted, left for GC to age out
    assert res.stale_model == 1
    assert res.ok == 2
    qdir = tmp_path / QUARANTINE_DIR
    assert len(list(qdir.iterdir())) == 2
    # the quota ignores quarantine (it governs the servable tier)
    stats = scan_store(tmp_path)
    assert stats.entries == 2 and stats.quarantined == 2
    removed = clear_store(tmp_path)
    assert removed == 4  # 2 live + 2 quarantined
    assert not qdir.exists()


def test_verify_store_defaults_to_live_model_stamp(tmp_path):
    """Calling verify_store without a model_version must resolve the
    live composite stamp (timing model + parser) — the daemon's startup
    sweep counts stale records without re-deriving it, so the
    guard_startup_stale_model gauge actually means something."""
    cache = ResultCache(disk_dir=tmp_path)
    cache.put("fresh", EngineResult(cycles=1.0))
    old = {
        "format_version": CACHE_FORMAT_VERSION,
        "model_version": "ancient+parser", "key": "o", "result": {},
    }
    (tmp_path / "oldmodel.json").write_text(json.dumps(old))
    res = verify_store(tmp_path)
    assert res.ok == 2 and res.quarantined_corrupt == 0
    assert res.stale_model == 1
    # empty string = skip the staleness count entirely
    assert verify_store(tmp_path, model_version="").stale_model == 0


def test_result_cache_quota_gc_keeps_store_bounded(tmp_path):
    """The data-plane path: puts past the quota trigger the LRU GC and
    the store ends every put at or under the quota; disk hits refresh
    recency so a USED record outlives an older unused one."""
    cache_dir = tmp_path / "cache"
    cache = ResultCache(disk_dir=cache_dir, quota_bytes=6 * 1024)
    for i in range(24):
        r = EngineResult(cycles=float(i), op_count=i)
        cache.put(f"key-{i}", r)
        assert store_bytes(cache_dir) <= 6 * 1024
    assert cache.gc_runs >= 1 and cache.gc_deleted > 0
    g = cache.guard_stats_dict()
    assert g["store_quota_bytes"] == 6 * 1024
    assert g["store_gc_deleted_total"] == cache.gc_deleted


def test_disk_hit_refreshes_lru_recency(tmp_path):
    cache_dir = tmp_path / "cache"
    c = ResultCache(disk_dir=cache_dir)
    c.put("old-but-used", EngineResult(cycles=1.0))
    c.put("newer-unused", EngineResult(cycles=2.0))
    used_path = c._path_for("old-but-used")
    unused_path = c._path_for("newer-unused")
    # age both far into the past, the used one older
    now = time.time()
    os.utime(used_path, (now - 2000, now - 2000))
    os.utime(unused_path, (now - 1000, now - 1000))
    # a fresh cache's disk hit must touch the record's mtime
    reader = ResultCache(disk_dir=cache_dir)
    assert reader.get("old-but-used") is not None
    assert used_path.stat().st_mtime > now - 10
    # GC to one record: the untouched one dies, the used one survives
    gc_store(cache_dir, max_entries=1)
    assert [p.name for p in cache_dir.glob("*.json")] == [used_path.name]


def test_shrink_and_compiled_tier_bounds():
    cache = ResultCache(max_entries=64)
    for i in range(64):
        cache.put(f"k{i}", EngineResult(cycles=float(i)))
    dropped = cache.shrink()
    assert dropped == 32 and cache.max_entries == 32
    assert len(cache._mem) == 32
    assert cache.lru_shrinks == 1
    # the floor holds
    for _ in range(10):
        cache.shrink()
    assert cache.max_entries == 16
    # compiled tier: clearing and re-bounding never raises, returns
    # counts (contents depend on what this process priced before)
    n = clear_compiled_cache()
    assert n >= 0
    set_compiled_cache_max(8)
    set_compiled_cache_max(256)  # restore the default for later tests


# -- multi-process GC chaos (the tentpole's concurrency claim) --------------

def _chaos_worker(idx: int, cache_dir: str, quota: int, q) -> None:
    try:
        cache = ResultCache(disk_dir=cache_dir, quota_bytes=quota)
        torn = 0
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for i in range(60):
                cache.put(
                    f"w{idx}-{i}",
                    EngineResult(cycles=float(i), op_count=i),
                )
                # read keys any writer may have published or GC'd:
                # every outcome must be a clean hit or a clean miss
                for peer in range(3):
                    cache.get(f"w{peer}-{max(i - 2, 0)}")
                if i % 16 == 0:
                    gc_store(cache_dir, quota_bytes=quota)
        torn = sum(
            1 for w in caught if "corrupt result-cache" in str(w.message)
        )
        q.put((idx, torn, cache.quarantined, cache.gc_runs))
    except Exception as e:  # pragma: no cover - failure reporting
        q.put((idx, f"{type(e).__name__}: {e}", -1, -1))


def test_multiprocess_gc_chaos_zero_torn_reads(tmp_path):
    """Three processes hammer one store under a tight quota — puts,
    gets of each other's keys, and explicit GCs all racing.  The
    concurrency contract requires zero torn reads (no corrupt-record
    warnings, no quarantines) and a final store at or under quota."""
    cache_dir = tmp_path / "shared"
    quota = 8 * 1024
    ctx = multiprocessing.get_context(
        "fork" if "fork" in multiprocessing.get_all_start_methods()
        else "spawn"
    )
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_chaos_worker, args=(i, str(cache_dir), quota, q),
        )
        for i in range(3)
    ]
    for p in procs:
        p.start()
    results = [q.get(timeout=120) for _ in procs]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    for idx, torn, quarantined, _gc_runs in results:
        assert torn == 0, f"worker {idx} saw a torn read: {torn}"
        assert quarantined == 0, f"worker {idx} quarantined {quarantined}"
    assert sum(r[3] for r in results) >= 1, "the quota never engaged"
    gc_store(cache_dir, quota_bytes=quota)
    assert store_bytes(cache_dir) <= quota
    assert not (cache_dir / QUARANTINE_DIR).exists()


# -- cancel token -----------------------------------------------------------

def test_cancel_token_semantics():
    tok = CancelToken()
    assert not tok.cancelled and tok.remaining() is None
    tok.check()  # live token: no raise
    tok.cancel("first")
    tok.cancel("second")  # idempotent; first reason wins
    assert tok.cancelled
    with pytest.raises(OperationCancelled, match="first"):
        tok.check()

    deadline = CancelToken.after(0.02)
    assert not deadline.cancelled
    assert 0.0 < deadline.remaining() <= 0.02
    time.sleep(0.03)
    assert deadline.cancelled and deadline.remaining() == 0.0
    with pytest.raises(OperationCancelled, match="deadline"):
        deadline.check()


# -- cancellation through the pricing stack ---------------------------------

@pytest.mark.parametrize("backend", ["serial", "auto"])
def test_engine_cancellation_both_backends(backend):
    pod = load_trace(FIXTURES / "llama_tiny_tp2dp2")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5p", tuned=False)
    tripped = CancelToken()
    tripped.cancel("stop")
    eng = Engine(cfg, pricing_backend=backend, cancel=tripped)
    with pytest.raises(OperationCancelled):
        eng.run(mod)


@pytest.mark.parametrize("backend", ["serial", "auto"])
def test_armed_token_is_byte_identical(backend):
    from tpusim.sim.driver import simulate_trace

    plain = simulate_trace(
        FIXTURES / "llama_tiny_tp2dp2", arch="v5p", tuned=False,
        pricing_backend=backend,
    )
    armed = simulate_trace(
        FIXTURES / "llama_tiny_tp2dp2", arch="v5p", tuned=False,
        pricing_backend=backend, cancel=CancelToken.after(600.0),
    )
    assert _stats(armed) == _stats(plain)


def test_driver_cancels_at_command_grain():
    from tpusim.sim.driver import SimDriver

    pod = load_trace(FIXTURES / "llama_tiny_tp2dp2")
    cfg = load_config(arch="v5p", tuned=False)
    tok = CancelToken()
    tok.cancel("client went away")
    with pytest.raises(OperationCancelled, match="client went away"):
        SimDriver(cfg, cancel=tok).run(pod)


def test_guard_stats_ride_reports_only_under_quota(tmp_path):
    from tpusim.sim.driver import simulate_trace

    plain = simulate_trace(
        FIXTURES / "matmul_512", arch="v5e", tuned=False,
        result_cache=ResultCache(disk_dir=tmp_path / "a"),
    )
    assert not any(k.startswith("guard_") for k in _stats(plain))
    governed = simulate_trace(
        FIXTURES / "matmul_512", arch="v5e", tuned=False,
        result_cache=ResultCache(
            disk_dir=tmp_path / "b", quota_bytes=1 << 20,
        ),
    )
    g = _stats(governed)
    assert g["guard_store_quota_bytes"] == 1 << 20
    assert "guard_store_gc_runs_total" in g
    # the governance keys are the ONLY difference
    assert {k: v for k, v in g.items()
            if not k.startswith(("guard_", "cache_"))} == \
           {k: v for k, v in _stats(plain).items()
            if not k.startswith("cache_")}


# -- memory watchdog --------------------------------------------------------

def test_watchdog_ladder_order_and_recovery():
    cache = ResultCache(max_entries=64)
    for i in range(64):
        cache.put(f"k{i}", EngineResult(cycles=float(i)))
    rss = {"v": 100}
    shed_flips = []
    dog = default_ladder(
        MemoryWatchdog(
            soft_bytes=200, hard_bytes=400, rss_fn=lambda: rss["v"],
            on_shed=lambda: shed_flips.append("shed"),
            on_recover=lambda: shed_flips.append("recover"),
        ),
        result_cache=cache,
    )
    prev_stream = os.environ.get("TPUSIM_STREAM_THRESHOLD")
    try:
        dog.poll_once()
        assert dog.steps_taken == [] and not dog.shedding
        rss["v"] = 250  # soft: one step per sample, in ladder order
        dog.poll_once()
        assert dog.steps_taken == ["shrink_lru"]
        assert cache.max_entries == 32
        dog.poll_once()
        assert dog.steps_taken == ["shrink_lru", "drop_compiled"]
        rss["v"] = 500  # hard: every remaining step, then shed
        dog.poll_once()
        assert dog.steps_taken[-1] == "force_lean"
        assert os.environ.get("TPUSIM_STREAM_THRESHOLD") == "0"
        assert dog.shedding and shed_flips == ["shed"]
        rss["v"] = 100  # back under the soft line: recover + re-arm
        dog.poll_once()
        assert not dog.shedding
        assert shed_flips == ["shed", "recover"]
        # recovery UNDID force_lean: one transient spike must not pin
        # lean streaming for the process lifetime
        assert os.environ.get("TPUSIM_STREAM_THRESHOLD") == prev_stream
        # ... and restored the L1 entry budget: repeated transient
        # excursions must not ratchet a long-lived daemon down to the
        # shrink floor (the budget is the step's lasting effect;
        # contents refill on demand)
        assert cache.max_entries == 64
        rss["v"] = 250
        dog.poll_once()  # the ladder re-armed from the top
        assert dog.steps_taken[-1] == "shrink_lru"
        assert cache.max_entries == 32  # halved from the RESTORED budget
        stats = dog.stats_dict()
        assert stats["rss_peak_bytes"] == 500
        assert stats["shed_entries_total"] == 1
        assert stats["recoveries_total"] == 1
    finally:
        if prev_stream is None:
            os.environ.pop("TPUSIM_STREAM_THRESHOLD", None)
        else:
            os.environ["TPUSIM_STREAM_THRESHOLD"] = prev_stream
        set_compiled_cache_max(256)


def test_rss_bytes_reads_this_process():
    rss = rss_bytes()
    assert rss > 10 * 1024 * 1024  # a live CPython is tens of MB


def test_watchdog_samples_current_rss_never_the_peak_fallback():
    """The watchdog's default sampler must be the current-RSS reader:
    rss_bytes' ru_maxrss fallback is MONOTONE, and a governor sampling
    a peak would turn one transient spike into permanent load-shedding
    with no possible recovery."""
    from tpusim.guard.watchdog import _rss_current

    dog = MemoryWatchdog(soft_bytes=None, hard_bytes=None)
    assert dog._rss_fn is _rss_current
    # the /proc-only reader: live value for us, 0 ("no signal", never
    # "no memory") for a pid that cannot exist
    assert _rss_current() > 10 * 1024 * 1024
    assert _rss_current(1 << 30) == 0


# -- campaign: cancel + resume parity (the satellite's contract) ------------

def _campaign_spec() -> dict:
    return {
        "name": "guard-parity", "seed": 7, "scenarios": 8,
        "arch": "v5p", "chips": 8, "tuned": False,
        "faults": {
            "count": {"dist": "uniform", "min": 0, "max": 2},
            "kinds": {"link_down": 1.0, "chip_straggler": 0.5},
            "scale": {"min": 0.4, "max": 0.9},
        },
    }


def test_campaign_cancel_then_resume_byte_identical(tmp_path):
    """Cancel mid-campaign at scenario grain, resume, and the final
    report must be byte-identical to an uninterrupted run — with the
    resumed run re-pricing ONLY the scenarios the cancel pre-empted."""
    from tpusim.campaign import run_campaign
    from tpusim.campaign.journal import Journal

    full = run_campaign(
        _campaign_spec(), trace_path=FIXTURES / "llama_tiny_tp2dp2",
        out_dir=tmp_path / "full",
    )

    tok = CancelToken()
    done = {"n": 0}

    def progress(msg: str) -> None:
        done["n"] += 1
        if done["n"] == 3:
            tok.cancel("operator cancel")

    with pytest.raises(OperationCancelled, match="operator cancel"):
        run_campaign(
            _campaign_spec(), trace_path=FIXTURES / "llama_tiny_tp2dp2",
            out_dir=tmp_path / "cut", cancel=tok, progress=progress,
        )
    recs = Journal(tmp_path / "cut").read_records()
    assert [r["kind"] for r in recs] == \
        ["header", "healthy"] + ["scenario"] * 3

    resumed = run_campaign(
        _campaign_spec(), trace_path=FIXTURES / "llama_tiny_tp2dp2",
        out_dir=tmp_path / "cut", resume=True,
    )
    assert resumed.stats.resumed == 3
    assert resumed.stats.priced + resumed.stats.partitioned + \
        resumed.stats.failed <= 5
    want = json.dumps(full.doc, sort_keys=True)
    got = json.dumps(resumed.doc, sort_keys=True)
    assert got == want, "resumed report diverged from uninterrupted run"
    # the report files on disk match byte for byte too
    assert (tmp_path / "cut" / "report.json").read_bytes() == \
        (tmp_path / "full" / "report.json").read_bytes()


def test_journal_iteration_is_lazy(tmp_path):
    """iter_records streams: records before a mid-file corruption are
    yielded before the damage is even read — the O(1)-memory resume
    path for 10^5-scenario campaigns."""
    from tpusim.campaign.journal import Journal, JournalError

    j = Journal(tmp_path)
    j.append({"kind": "header", "spec_hash": "h", "seed": 1,
              "model_version": "m"})
    j.append({"kind": "scenario", "slice": "s", "index": 0, "row": {}})
    j.close()
    with open(j.path, "ab") as f:
        f.write(b"garbage not json\n")
    it = Journal(tmp_path).iter_records()
    assert next(it)["kind"] == "header"
    assert next(it)["kind"] == "scenario"  # yielded BEFORE the damage
    with pytest.raises(JournalError, match="corrupt"):
        next(it)


# -- serve: in-process cooperative 504 + job cancellation -------------------

def test_serve_worker_simulate_cancels_in_process():
    from tpusim.serve.registry import TraceRegistry
    from tpusim.serve.worker import ServeWorker

    worker = ServeWorker(
        TraceRegistry(FIXTURES), result_cache=ResultCache(),
    )
    tok = CancelToken()
    tok.cancel("deadline")
    with pytest.raises(OperationCancelled):
        worker.simulate(
            {"trace": "matmul_512", "arch": "v5e", "tuned": False},
            cancel=tok,
        )


def test_sweep_jobs_are_cancellable_at_link_grain():
    """Sweep was the one job kind ``DELETE /v1/jobs/<id>`` could not
    actually stop: the token tripped, the table answered 'cancelling',
    and the sweep priced to terminal 'done' anyway.  Both sweep
    flavors and the serve worker must honor the token now."""
    from tpusim.faults.sweep import single_link_sweep, trace_step_sweep
    from tpusim.ici.topology import torus_for
    from tpusim.serve.registry import TraceRegistry
    from tpusim.serve.worker import ServeWorker

    tok = CancelToken()
    tok.cancel("client DELETE")
    cfg = load_config(arch="v5p", tuned=False)
    topo = torus_for(8, cfg.arch.name)
    with pytest.raises(OperationCancelled):
        single_link_sweep(topo, cfg.arch.ici, cancel=tok)
    with pytest.raises(OperationCancelled):
        trace_step_sweep(
            str(FIXTURES / "llama_tiny_tp2dp2"), topo, config=cfg,
            cancel=tok,
        )
    worker = ServeWorker(
        TraceRegistry(FIXTURES), result_cache=ResultCache(),
    )
    with pytest.raises(OperationCancelled):
        worker.sweep({"arch": "v5p", "chips": 8}, cancel=tok)
    # an armed-but-untripped token changes nothing: the sweep completes
    live = single_link_sweep(
        torus_for(8, cfg.arch.name), cfg.arch.ici,
        cancel=CancelToken(),
    )
    bare = single_link_sweep(torus_for(8, cfg.arch.name), cfg.arch.ici)
    assert live.to_doc() == bare.to_doc()


def test_inprocess_daemon_coop_cancel_504():
    """Single-process daemon: a cold pricing run that outlives its
    deadline must 504 through the in-process CancelToken (detail names
    the cooperative cancel), and the daemon keeps serving."""
    import http.client

    from tpusim.serve.daemon import ServeDaemon

    with ServeDaemon(trace_root=FIXTURES) as d:
        conn = http.client.HTTPConnection(d.host, d.port, timeout=30)
        try:
            # cold llama pricing takes hundreds of ms; 50ms of budget
            # comfortably clears admission and trips mid-walk
            conn.request(
                "POST", "/v1/simulate",
                json.dumps({
                    "trace": "llama_tiny_tp2dp2", "arch": "v5p",
                    "tuned": False, "deadline_ms": 50,
                }),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            doc = json.loads(resp.read())
            assert resp.status == 504
            assert "cooperative" in doc["detail"]
            # the daemon survives and prices the same request fine
            conn.request(
                "POST", "/v1/simulate",
                json.dumps({
                    "trace": "matmul_512", "arch": "v5e", "tuned": False,
                }),
                {"Content-Type": "application/json"},
            )
            resp2 = conn.getresponse()
            assert resp2.status == 200
            resp2.read()
        finally:
            conn.close()


def test_jobtable_cancel_queued_and_running(tmp_path):
    from tpusim.serve.admission import JobTable

    table = JobTable(queue_depth=4, persist_dir=tmp_path / "jobs")
    queued = table.submit("sweep", {"arch": "v5p"})
    running = table.submit("campaign", {"spec": {}})
    assert table.cancel("job-999999") is None
    # cancel while queued: terminal immediately
    assert table.cancel(queued.job_id) == "cancelled"
    assert table.get(queued.job_id).status == "cancelled"
    # a worker picks up the other job (the queued-cancelled one is gone
    # from the line)
    job = table.next_job(timeout_s=0.1)
    assert job is running and job.status == "running"
    # cancel while running: the token trips, the loop lands it terminal
    assert table.cancel(running.job_id) == "cancelling"
    assert running.cancel_token.cancelled
    table.finish(job, None, "cancelled: client asked",
                 status="cancelled")
    assert table.get(running.job_id).status == "cancelled"
    # cancelled is terminal for drain purposes
    assert table.wait_idle(timeout_s=1.0)
    # and persisted terminally: a recovering table retains, not re-runs
    table2 = JobTable(queue_depth=4, persist_dir=tmp_path / "jobs")
    assert table2.get(queued.job_id).status == "cancelled"
    assert table2.get(running.job_id).status == "cancelled"
    assert table2.recovered == 0


def test_statskeys_guard_namespace_registered():
    from tpusim.analysis.statskeys import (
        AUDIT_GLOBS, STATS_NAMESPACES,
    )

    assert "guard_" in STATS_NAMESPACES
    assert "tpusim/guard/" in STATS_NAMESPACES["guard_"]
    assert "tpusim/guard/*.py" in AUDIT_GLOBS
