"""Tests for the orchestration harness: procman, scraper, runner, tuner."""

import sys
from pathlib import Path

import pytest

from tpusim.harness.procman import ProcMan
from tpusim.harness.runner import RunSpec, run_experiments
from tpusim.harness.scrape import scrape_log, scrape_run_dirs, write_csv
from tpusim.sim.stats import EXIT_SENTINEL

FIXTURES = Path(__file__).parent / "fixtures"


# -- procman ----------------------------------------------------------------

def test_procman_runs_jobs(tmp_path):
    pm = ProcMan(parallel=2)
    for i in range(4):
        pm.submit(
            [sys.executable, "-c", f"print('job {i}')"],
            log_path=tmp_path / f"j{i}.log",
        )
    assert pm.run(poll_s=0.05)
    assert pm.status_summary() == {"done": 4}
    assert "job 2" in (tmp_path / "j2.log").read_text()


def test_procman_retries_job_killed_by_signal(tmp_path):
    """A job that dies from a transient signal is no longer terminal:
    with retries budgeted it is reaped and resubmitted (exponential
    backoff), and the second attempt succeeds."""
    marker = tmp_path / "first_attempt_done"
    code = (
        "import os, signal\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "print('recovered')\n"
    )
    pm = ProcMan(parallel=1)
    job = pm.submit(
        [sys.executable, "-c", code],
        log_path=tmp_path / "flaky.log",
        retries=1, backoff_s=0.01,
    )
    assert pm.run(poll_s=0.02)
    assert job.status == "done"
    assert job.attempts == 2
    assert job.retried == 1
    s = pm.status_summary()
    assert s["done"] == 1 and s["retries"] == 1
    log = (tmp_path / "flaky.log").read_text()
    assert "retry attempt 2/2" in log and "recovered" in log
    pm.dump_state(tmp_path / "jobs.json")
    state = __import__("json").loads((tmp_path / "jobs.json").read_text())
    assert state[0]["attempts"] == 2


def test_procman_retry_budget_exhausts_to_failed(tmp_path):
    pm = ProcMan(parallel=1)
    job = pm.submit(
        [sys.executable, "-c", "raise SystemExit(7)"],
        log_path=tmp_path / "always_bad.log",
        retries=2, backoff_s=0.01,
    )
    assert not pm.run(poll_s=0.02)
    assert job.status == "failed"
    assert job.attempts == 3          # 1 original + 2 resubmissions
    assert job.returncode == 7


def test_procman_backoff_grows_and_caps():
    from tpusim.harness.procman import MAX_BACKOFF_S, Job

    j = Job(job_id=3, cmd=["x"], retries=10, backoff_s=0.5)
    delays = []
    for attempt in (1, 2, 3, 4):
        j.attempts = attempt
        delays.append(j.next_backoff_s())
    # exponential (jitter <= 25%) and bounded
    assert delays[1] > delays[0] and delays[2] > delays[1]
    for base, got in zip((0.5, 1.0, 2.0, 4.0), delays):
        assert base <= got <= min(base * 1.25, MAX_BACKOFF_S)
    j.attempts = 30
    assert j.next_backoff_s() == MAX_BACKOFF_S


def test_procman_drain_finishes_running_cancels_pending(tmp_path):
    """request_drain mid-run: the running child is reaped normally (its
    work completes), never-started jobs go 'cancelled', and run()
    returns instead of hanging on the frozen pending set."""
    import threading
    import time

    pm = ProcMan(parallel=1)
    marker = tmp_path / "slow.done"
    j0 = pm.submit([
        sys.executable, "-c",
        f"import time, pathlib; time.sleep(0.4); "
        f"pathlib.Path({str(marker)!r}).write_text('done')",
    ])
    j1 = pm.submit([sys.executable, "-c", "print('never runs')"])
    results: list[bool] = []
    th = threading.Thread(
        target=lambda: results.append(pm.run(poll_s=0.02))
    )
    th.start()
    deadline = time.time() + 10.0
    while j0.status != "running":
        assert time.time() < deadline, "job 0 never started"
        time.sleep(0.01)
    pm.request_drain()
    th.join(timeout=30.0)
    assert not th.is_alive()
    assert j0.status == "done" and j0.returncode == 0
    assert marker.exists()  # the in-flight child genuinely finished
    assert j1.status == "cancelled"
    assert results == [False]  # not all jobs succeeded (one cancelled)
    summary = pm.status_summary()
    assert summary == {"done": 1, "cancelled": 1}


def test_procman_sigterm_drains_gracefully(tmp_path):
    """run(drain_signals=True) under a real SIGTERM: the slow child is
    never orphaned, the queue stops, and the parent exits cleanly (rc
    0) — unlike the default disposition, which kills the parent
    mid-reap and leaves the child running."""
    import signal
    import subprocess
    import textwrap
    import time

    marker = tmp_path / "slow.done"
    code = textwrap.dedent(f"""
        import json, sys
        from tpusim.harness.procman import ProcMan

        pm = ProcMan(parallel=1)
        pm.submit([sys.executable, "-c",
                   "import time, pathlib; time.sleep(0.4); "
                   "pathlib.Path({str(marker)!r}).write_text('done')"])
        pm.submit([sys.executable, "-c", "print('never runs')"])
        printed = [False]

        def tick(p):
            if not printed[0] and any(
                j.status == "running" for j in p.jobs
            ):
                printed[0] = True
                print("RUNNING", flush=True)

        ok = pm.run(poll_s=0.02, on_tick=tick, drain_signals=True)
        print("SUMMARY " + json.dumps(pm.status_summary()), flush=True)
    """)
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, text=True,
        cwd=Path(__file__).resolve().parent.parent,
        start_new_session=True,
    )
    try:
        assert proc.stdout.readline().strip() == "RUNNING"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30.0)
        out = proc.stdout.read()
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 0, out  # graceful: run() returned, process exited
    assert marker.exists()  # the running child finished its work
    import json as _json

    summary = _json.loads(out.split("SUMMARY ", 1)[1])
    assert summary == {"done": 1, "cancelled": 1}
    # no orphan child in the group
    import os as _os

    deadline = time.time() + 5.0
    while time.time() < deadline:
        try:
            _os.killpg(proc.pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("orphan procman child outlived the parent")


def test_procman_reports_failure(tmp_path):
    pm = ProcMan(parallel=2)
    pm.submit([sys.executable, "-c", "raise SystemExit(3)"],
              log_path=tmp_path / "bad.log")
    pm.submit([sys.executable, "-c", "print('ok')"],
              log_path=tmp_path / "good.log")
    assert not pm.run(poll_s=0.05)
    s = pm.status_summary()
    assert s == {"done": 1, "failed": 1}
    pm.dump_state(tmp_path / "jobs.json")
    assert (tmp_path / "jobs.json").exists()


# -- scraper ----------------------------------------------------------------

def test_scrape_requires_sentinel(tmp_path):
    log = tmp_path / "run.log"
    log.write_text("tpusim_sim_cycle = 123\n")  # no sentinel
    assert scrape_log(log) is None
    log.write_text(f"tpusim_sim_cycle = 123\ntpusim_x = 1.5\n{EXIT_SENTINEL}\n")
    stats = scrape_log(log)
    assert stats == {"sim_cycle": 123, "x": 1.5}


def test_scrape_run_dirs_and_csv(tmp_path):
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "run.log").write_text(
        f"tpusim_sim_cycle = 10\n{EXIT_SENTINEL}\n"
    )
    (tmp_path / "b" / "run.log").write_text("crashed\n")
    rows = scrape_run_dirs(tmp_path, "**/run.log")
    assert rows["a/run.log"]["sim_cycle"] == 10
    assert rows["__failed__"]["runs"] == ["b/run.log"]
    write_csv(rows, tmp_path / "out.csv")
    text = (tmp_path / "out.csv").read_text()
    assert "sim_cycle" in text and "a/run.log" in text


# -- runner (end-to-end over a real trace dir) ------------------------------

@pytest.mark.slow
def test_run_experiments_end_to_end(tmp_path, live_jax):
    import jax.numpy as jnp

    from tpusim.tracer.capture import capture_to_dir

    def f(x, w):
        return (x @ w).sum()

    trace = tmp_path / "trace"
    capture_to_dir(
        trace, f, jnp.ones((256, 256), jnp.bfloat16),
        jnp.ones((256, 256), jnp.bfloat16), name="mini", launches=2,
    )
    specs = [
        RunSpec(trace=trace, arch="v5e", name="mini"),
        RunSpec(trace=trace, arch="v5p", name="mini",
                overlays=["-kernel_window 4"], power=True),
    ]
    rows = run_experiments(specs, tmp_path / "runs", parallel=2)
    assert "__failed__" not in rows
    assert len(rows) == 2
    for stats in rows.values():
        assert stats["sim_cycle"] > 0
    power_rows = [
        s for s in rows.values() if "power_avg_watts" in s
    ]
    assert len(power_rows) == 1
    assert power_rows[0]["power_avg_watts"] > 0


# -- suites / run matrix ----------------------------------------------------

def test_builtin_suites_from_registry():
    from tpusim.harness.suites import list_suites, load_suite

    suites = list_suites()
    assert suites.get("ubench", 0) >= 10
    assert "all" in suites
    entries = load_suite("ubench")
    names = {e.workload for e in entries}
    assert "matmul_chain" in names and "embedding_lookup" in names
    with pytest.raises(KeyError, match="unknown suite"):
        load_suite("nope")


def test_yaml_suites_and_configs(tmp_path):
    from tpusim.harness.suites import load_named_configs, load_suite

    y = tmp_path / "apps.yml"
    y.write_text(
        "suites:\n"
        "  quick:\n"
        "    - workload: matmul_chain\n"
        "      params: {m: 256}\n"
        "      launches: 2\n"
        "    - reduction\n"
        "configs:\n"
        "  narrow: {kernel_window: 1}\n"
        "  dcn: {arch: {ici: {chips_per_slice: 4}}}\n"
    )
    entries = load_suite("quick", y)
    assert entries[0].workload == "matmul_chain"
    assert entries[0].params == {"m": 256}
    assert entries[0].launches == 2
    assert entries[1].workload == "reduction"
    assert entries[0].run_name == "matmul_chain__m256"
    cfgs = load_named_configs(y)
    assert cfgs["narrow"] == {"kernel_window": 1}
    assert cfgs["dcn"]["arch"]["ici"]["chips_per_slice"] == 4
    # yaml suites shadow nothing built-in; builtin still resolvable
    assert load_suite("ubench", y)


def test_overlay_to_flag_lines():
    from tpusim.harness.runner import overlay_to_flag_lines
    from tpusim.timing.config import SimConfig, overlay, parse_flag_file

    d = {"kernel_window": 1, "arch": {"ici": {"chips_per_slice": 4}}}
    lines = overlay_to_flag_lines(d)
    assert "-kernel_window 1" in lines
    assert "-arch.ici.chips_per_slice 4" in lines
    # round-trip through the flag-file parser into a real config
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".cfg", delete=False) as f:
        f.write("\n".join(lines))
        path = f.name
    cfg = overlay(SimConfig(), parse_flag_file(path))
    assert cfg.kernel_window == 1
    assert cfg.arch.ici.chips_per_slice == 4


def test_run_suite_missing_trace_errors(tmp_path):
    from tpusim.harness.runner import run_suite

    with pytest.raises(FileNotFoundError, match="--capture"):
        run_suite(
            "ubench", ["v5e"], tmp_path, capture_missing=False,
        )


RUN_SUITE_SCRIPT = r"""
import json
from pathlib import Path
from tpusim.harness.runner import run_suite

yaml_path = Path(OUT) / "apps.yml"
yaml_path.write_text(
    "suites:\n"
    "  quick:\n"
    "    - workload: matmul_chain\n"
    "      params: {m: 256, k: 256, depth: 2}\n"
    "configs:\n"
    "  narrow: {kernel_window: 1}\n"
)
rows = run_suite(
    "quick", ["v5e", "v5p+narrow"], Path(OUT) / "runs",
    yaml_path=yaml_path, capture_missing=True, parallel=2,
    monitor_interval_s=None,
)
assert "__failed__" not in rows, rows
assert len(rows) == 2, list(rows)
for stats in rows.values():
    assert stats["sim_cycle"] > 0
assert (Path(OUT) / "runs" / "stats.csv").exists()
assert json.loads((Path(OUT) / "runs" / "failures.json").read_text()) == []
print("RUN_SUITE_OK")
"""


@pytest.mark.slow
def test_run_suite_end_to_end(tmp_path, cpu_mesh_runner):
    out = cpu_mesh_runner(
        RUN_SUITE_SCRIPT.replace("OUT", repr(str(tmp_path))), n_devices=1,
    )
    assert "RUN_SUITE_OK" in out


# -- tuner ------------------------------------------------------------------

@pytest.mark.slow
def test_tuner_on_live_chip(live_jax):
    """The tuner must land near the calibrated preset on this chip."""
    import jax

    if jax.devices()[0].platform not in ("tpu",):
        pytest.skip("tuner fit needs the real chip")

    from tpusim.harness.tuner import tune

    result = tune()
    assert result.base_arch == "v5e"
    # measured peak should imply a clock near the calibrated 1.67 GHz
    assert 1.3 < result.clock_ghz < 2.1, result
    assert 0.4 < result.hbm_efficiency <= 1.0, result
    assert 1.0 <= result.vpu_reduce_slowdown < 64, result
    lines = result.overlay_lines()
    assert any("clock_ghz" in l for l in lines)

def test_diff_stats_compares_two_runs():
    """The merge-stats compare role (util/plotting/merge-stats.py): two
    configs over the same runs, numeric tolerance, one-sided runs."""
    from tpusim.harness.scrape import diff_stats

    old = {
        "a/run.log": {"cycles": 100.0, "flops": 5.0, "note": "x"},
        "gone/run.log": {"cycles": 1.0},
        "__failed__": {"runs": ["dead"]},
    }
    new = {
        "a/run.log": {"cycles": 103.0, "flops": 5.0, "note": "y"},
        "fresh/run.log": {"cycles": 2.0},
    }
    d = diff_stats(old, new, rel_tol=0.05)
    # 3% cycle delta is inside the 5% tolerance; note differs exactly
    assert d["a/run.log"] == {"note": ("x", "y")}
    assert "gone/run.log" in d["__only_old__"]
    assert "fresh/run.log" in d["__only_new__"]
    strict = diff_stats(old, new)
    assert strict["a/run.log"]["cycles"] == (100.0, 103.0)


def test_stats_diff_cli(tmp_path):
    import subprocess
    import sys

    for side, cyc in (("old", 100), ("new", 110)):
        d = tmp_path / side / "runA"
        d.mkdir(parents=True)
        (d / "run.log").write_text(
            f"tpusim_tot_sim_cycles = {cyc}\n"
            "TPUSIM: *** exit detected ***\n"
        )
    p = subprocess.run(
        [sys.executable, "-m", "tpusim", "stats-diff",
         str(tmp_path / "old"), str(tmp_path / "new"), "--check"],
        capture_output=True, text=True,
        cwd=Path(__file__).resolve().parent.parent,
    )
    assert p.returncode == 1
    assert "tot_sim_cycles 100" in p.stdout
    p2 = subprocess.run(
        [sys.executable, "-m", "tpusim", "stats-diff",
         str(tmp_path / "old"), str(tmp_path / "new"),
         "--rel-tol", "0.2"],
        capture_output=True, text=True,
        cwd=Path(__file__).resolve().parent.parent,
    )
    assert p2.returncode == 0
