"""Tests for the orchestration harness: procman, scraper, runner, tuner."""

import sys
from pathlib import Path

import pytest

from tpusim.harness.procman import ProcMan
from tpusim.harness.runner import RunSpec, run_experiments
from tpusim.harness.scrape import scrape_log, scrape_run_dirs, write_csv
from tpusim.sim.stats import EXIT_SENTINEL

FIXTURES = Path(__file__).parent / "fixtures"


# -- procman ----------------------------------------------------------------

def test_procman_runs_jobs(tmp_path):
    pm = ProcMan(parallel=2)
    for i in range(4):
        pm.submit(
            [sys.executable, "-c", f"print('job {i}')"],
            log_path=tmp_path / f"j{i}.log",
        )
    assert pm.run(poll_s=0.05)
    assert pm.status_summary() == {"done": 4}
    assert "job 2" in (tmp_path / "j2.log").read_text()


def test_procman_reports_failure(tmp_path):
    pm = ProcMan(parallel=2)
    pm.submit([sys.executable, "-c", "raise SystemExit(3)"],
              log_path=tmp_path / "bad.log")
    pm.submit([sys.executable, "-c", "print('ok')"],
              log_path=tmp_path / "good.log")
    assert not pm.run(poll_s=0.05)
    s = pm.status_summary()
    assert s == {"done": 1, "failed": 1}
    pm.dump_state(tmp_path / "jobs.json")
    assert (tmp_path / "jobs.json").exists()


# -- scraper ----------------------------------------------------------------

def test_scrape_requires_sentinel(tmp_path):
    log = tmp_path / "run.log"
    log.write_text("tpusim_sim_cycle = 123\n")  # no sentinel
    assert scrape_log(log) is None
    log.write_text(f"tpusim_sim_cycle = 123\ntpusim_x = 1.5\n{EXIT_SENTINEL}\n")
    stats = scrape_log(log)
    assert stats == {"sim_cycle": 123, "x": 1.5}


def test_scrape_run_dirs_and_csv(tmp_path):
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "run.log").write_text(
        f"tpusim_sim_cycle = 10\n{EXIT_SENTINEL}\n"
    )
    (tmp_path / "b" / "run.log").write_text("crashed\n")
    rows = scrape_run_dirs(tmp_path, "**/run.log")
    assert rows["a/run.log"]["sim_cycle"] == 10
    assert rows["__failed__"]["runs"] == ["b/run.log"]
    write_csv(rows, tmp_path / "out.csv")
    text = (tmp_path / "out.csv").read_text()
    assert "sim_cycle" in text and "a/run.log" in text


# -- runner (end-to-end over a real trace dir) ------------------------------

@pytest.mark.slow
def test_run_experiments_end_to_end(tmp_path, live_jax):
    import jax.numpy as jnp

    from tpusim.tracer.capture import capture_to_dir

    def f(x, w):
        return (x @ w).sum()

    trace = tmp_path / "trace"
    capture_to_dir(
        trace, f, jnp.ones((256, 256), jnp.bfloat16),
        jnp.ones((256, 256), jnp.bfloat16), name="mini", launches=2,
    )
    specs = [
        RunSpec(trace=trace, arch="v5e", name="mini"),
        RunSpec(trace=trace, arch="v5p", name="mini",
                overlays=["-kernel_window 4"], power=True),
    ]
    rows = run_experiments(specs, tmp_path / "runs", parallel=2)
    assert "__failed__" not in rows
    assert len(rows) == 2
    for stats in rows.values():
        assert stats["sim_cycle"] > 0
    power_rows = [
        s for s in rows.values() if "power_avg_watts" in s
    ]
    assert len(power_rows) == 1
    assert power_rows[0]["power_avg_watts"] > 0


# -- tuner ------------------------------------------------------------------

@pytest.mark.slow
def test_tuner_on_live_chip(live_jax):
    """The tuner must land near the calibrated preset on this chip."""
    import jax

    if jax.devices()[0].platform not in ("tpu",):
        pytest.skip("tuner fit needs the real chip")

    from tpusim.harness.tuner import tune

    result = tune()
    assert result.base_arch == "v5e"
    # measured peak should imply a clock near the calibrated 1.67 GHz
    assert 1.3 < result.clock_ghz < 2.1, result
    assert 0.4 < result.hbm_efficiency <= 1.0, result
    assert 1.0 <= result.vpu_reduce_slowdown < 64, result
    lines = result.overlay_lines()
    assert any("clock_ghz" in l for l in lines)