"""Unit tests for the HLO text parser (tpusim/trace/hlo_text.py).

The hand-written fixture plays the role the reference's tiny traces play for
its parser (SURVEY.md §7 build order step 1).
"""

from pathlib import Path

import pytest

from tpusim.ir import TensorSpec, TupleSpec
from tpusim.trace.hlo_text import (
    parse_hlo_module,
    parse_instruction,
    parse_shape,
    split_top_level,
)

FIXTURES = Path(__file__).parent / "fixtures"


# -- tokenizer ---------------------------------------------------------------

def test_split_top_level():
    assert split_top_level("a, b, c") == ["a", "b", "c"]
    assert split_top_level("f(a, b), {x, y}, z") == ["f(a, b)", "{x, y}", "z"]
    assert split_top_level('a="x,y", b') == ['a="x,y"', "b"]
    assert split_top_level("") == []


# -- shapes ------------------------------------------------------------------

def test_parse_shape_basic():
    s = parse_shape("bf16[256,512]")
    assert isinstance(s, TensorSpec)
    assert s.dtype == "bf16" and s.shape == (256, 512)


def test_parse_shape_layout_tiling_space():
    s = parse_shape("bf16[512,1024]{1,0:T(8,128)(2,1)S(1)}")
    assert s.layout == (1, 0)
    assert s.tiling == "(8,128)(2,1)"
    assert s.memory_space == 1


def test_parse_shape_scalar_and_token():
    s = parse_shape("f32[]{:T(256)}")
    assert s.shape == () and s.tiling == "(256)"
    assert parse_shape("token[]").nbytes == 0


def test_parse_shape_tuple():
    s = parse_shape("(bf16[128,256]{1,0}, u32[]{:T(256)})")
    assert isinstance(s, TupleSpec)
    assert len(s.parts) == 2
    assert s.nbytes == 128 * 256 * 2 + 4


# -- instructions ------------------------------------------------------------

def test_parse_instruction_dot():
    op = parse_instruction(
        "%dot.1 = bf16[128,256]{1,0} dot(%x, %w1), "
        "lhs_contracting_dims={1}, rhs_contracting_dims={0}, "
        'metadata={op_name="jit(f)/dot_general" source_file="t.py" source_line=5}'
    )
    assert op.opcode == "dot"
    assert op.operands == ("x", "w1")
    assert op.attrs["lhs_contracting_dims"] == "{1}"
    assert op.metadata["op_name"] == "jit(f)/dot_general"
    assert not op.is_root


def test_parse_instruction_root_and_typed_operands():
    op = parse_instruction(
        "ROOT %add.2 = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)"
    )
    assert op.is_root
    assert op.operands == ("a", "b")


def test_parse_instruction_constant_literal():
    op = parse_instruction("%c = f32[]{:T(256)} constant(3.14)")
    assert op.opcode == "constant"
    assert op.operands == ()


def test_parse_instruction_collective():
    op = parse_instruction(
        "%ar = f32[1024]{0} all-reduce(%x), channel_id=5, "
        "replica_groups={{0,1,2,3}}, use_global_device_ids=true, "
        "to_apply=%region_add"
    )
    assert op.is_collective
    assert op.collective.kind == "all-reduce"
    assert op.collective.replica_groups == ((0, 1, 2, 3),)
    assert op.collective.channel_id == 5
    assert op.collective.use_global_device_ids
    assert "region_add" in op.called


def test_parse_instruction_iota_replica_groups():
    op = parse_instruction(
        "%ag = f32[64]{0} all-gather(%x), channel_id=2, "
        "replica_groups=[2,4]<=[8], dimensions={0}"
    )
    groups = op.collective.replica_groups
    assert len(groups) == 2 and all(len(g) == 4 for g in groups)
    assert op.collective.group_size == 4


def test_parse_instruction_transposed_iota_replica_groups():
    """``[2,2]<=[2,2]T(1,0)`` is XLA's encoding of a MAJOR-mesh-axis
    collective (e.g. the dp gradient all-reduce of a dp x tp mesh):
    the transpose yields STRIDED groups, not contiguous ones.  Group
    membership feeds the replay driver's rendezvous keys and the
    advise layer's mesh-role classification."""
    op = parse_instruction(
        "%ar = f32[64]{0} all-reduce(%x), channel_id=2, "
        "replica_groups=[2,2]<=[2,2]T(1,0), to_apply=%add"
    )
    assert op.collective.replica_groups == ((0, 2), (1, 3))
    # a larger mesh: dp=4 groups on a dp4 x tp2 device order
    op = parse_instruction(
        "%ar2 = f32[64]{0} all-reduce(%x), channel_id=3, "
        "replica_groups=[2,4]<=[4,2]T(1,0), to_apply=%add"
    )
    assert op.collective.replica_groups == ((0, 2, 4, 6), (1, 3, 5, 7))


def test_parse_instruction_collective_permute():
    op = parse_instruction(
        "%cp = f32[16]{0} collective-permute(%x), channel_id=3, "
        "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}"
    )
    assert op.collective.source_target_pairs == ((0, 1), (1, 2), (2, 3), (3, 0))
    assert op.collective.group_size == 4


def test_parse_instruction_async_pair():
    start = parse_instruction(
        "%ar-start = bf16[128]{0} all-reduce-start(%r), channel_id=1, "
        "replica_groups={{0,1}}, to_apply=%region_add"
    )
    done = parse_instruction("%ar-done = bf16[128]{0} all-reduce-done(%ar-start)")
    assert start.is_async_start and start.is_collective
    assert done.is_async_done
    assert done.operands == ("ar-start",)


# -- full module -------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_mlp():
    return parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())


def test_module_header(tiny_mlp):
    assert tiny_mlp.name == "jit_tiny_mlp"
    assert tiny_mlp.num_partitions == 4
    assert tiny_mlp.num_replicas == 1
    assert tiny_mlp.meta["is_scheduled"] is True


def test_module_computations(tiny_mlp):
    assert set(tiny_mlp.computations) == {"region_add", "fused_relu", "main.10"}
    assert tiny_mlp.entry.name == "main.10"
    assert len(tiny_mlp.entry.ops) == 8


def test_module_fusion_links(tiny_mlp):
    relu = tiny_mlp.entry.op("relu.1")
    assert relu.fusion_kind == "kLoop"
    assert relu.called == ("fused_relu",)
    fused = tiny_mlp.computation("fused_relu")
    assert fused.root.opcode == "maximum"


def test_module_collective(tiny_mlp):
    ars = tiny_mlp.entry.op("ar-start")
    assert ars.is_collective and ars.is_async_start
    assert ars.collective.replica_groups == ((0, 1), (2, 3))
    assert len(tiny_mlp.collectives()) == 1  # -done is not a collective op


def test_module_root(tiny_mlp):
    assert tiny_mlp.entry.root.name == "dot.2"
    assert tiny_mlp.entry.root.result.shape == (128, 64)


# -- lenient (salvage) parse mode --------------------------------------------

def _corrupt_mlp_text() -> str:
    """The tiny_mlp dump with one instruction line corrupted the way a
    truncated capture write corrupts it (mangled shape text)."""
    lines = (FIXTURES / "tiny_mlp.hlo").read_text().splitlines()
    out = []
    for line in lines:
        if line.lstrip().startswith("%relu.1 ="):
            out.append(
                "  %relu.1 = bf16[128,&&&GARBAGE] fusion(%dot.1), "
                "kind=kLoop, calls=%fused_relu"
            )
        else:
            out.append(line)
    return "\n".join(out)


def test_strict_parse_raises_on_corrupt_line():
    with pytest.raises(ValueError, match="malformed HLO line"):
        parse_hlo_module(_corrupt_mlp_text())


def test_lenient_parse_skips_corrupt_line_with_counted_warning():
    with pytest.warns(UserWarning, match="skipped 1 malformed"):
        mod = parse_hlo_module(_corrupt_mlp_text(), strict=False)
    assert mod.meta["parse_skipped_lines"] == 1
    # everything else survived: one op lost from the entry, rest intact
    assert len(mod.entry.ops) == 7
    assert mod.entry.root.name == "dot.2"
    assert set(mod.computations) == {"region_add", "fused_relu", "main.10"}


def test_lenient_parse_clean_text_adds_no_meta():
    mod = parse_hlo_module(
        (FIXTURES / "tiny_mlp.hlo").read_text(), strict=False
    )
    assert "parse_skipped_lines" not in mod.meta
    assert len(mod.entry.ops) == 8


def test_lenient_load_trace_and_cli_flag(tmp_path):
    """--lenient-parse end to end: a trace dir with one corrupt module
    line loads (and replays) in salvage mode, raises in strict mode."""
    from tpusim.trace.format import load_trace

    trace = tmp_path / "trace"
    (trace / "modules").mkdir(parents=True)
    (trace / "modules" / "m.hlo").write_text(_corrupt_mlp_text())
    (trace / "meta.json").write_text('{"num_devices": 1}')
    # strict mode raises (native scanner or python reference path)
    with pytest.raises(ValueError, match="GARBAGE"):
        load_trace(trace)
    with pytest.warns(UserWarning, match="skipped 1 malformed"):
        pod = load_trace(trace, lenient=True)
    assert pod.modules["m"].meta["parse_skipped_lines"] == 1

    from tpusim.sim.driver import simulate_trace

    with pytest.warns(UserWarning):
        report = simulate_trace(trace, arch="v5e", lenient=True)
    assert report.cycles > 0
