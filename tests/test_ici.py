"""Tests for the ICI topology + collective schedules (vs analytic formulas)."""

import pytest

from tpusim.ici.collectives import CollectiveModel
from tpusim.ici.topology import Topology, torus_for
from tpusim.ir import CollectiveInfo
from tpusim.timing.config import IciConfig

MB = 1024 * 1024


# -- topology ---------------------------------------------------------------

def test_torus_factorization():
    t = torus_for(64, "v5p")
    assert t.num_chips == 64
    assert t.dims == (4, 4, 4)
    assert all(t.wrap)

    t2 = torus_for(16, "v5e")
    assert t2.num_chips == 16
    assert t2.dims == (4, 4)

    t1 = torus_for(1)
    assert t1.num_chips == 1


def test_coords_roundtrip():
    t = torus_for(64, "v5p")
    for chip in (0, 1, 17, 63):
        assert t.chip_at(t.coords(chip)) == chip


def test_hop_distance_wraparound():
    t = Topology(dims=(8,), wrap=(True,))
    assert t.hop_distance(0, 1) == 1
    assert t.hop_distance(0, 7) == 1  # wrap link
    assert t.hop_distance(0, 4) == 4
    m = Topology(dims=(8,), wrap=(False,))
    assert m.hop_distance(0, 7) == 7  # no wrap


# -- collectives ------------------------------------------------------------

@pytest.fixture
def model8():
    topo = Topology(dims=(8,), wrap=(True,))
    cfg = IciConfig(
        link_bandwidth=100e9, efficiency=1.0, hop_latency=1e-6,
        launch_latency=0.0,
    )
    return CollectiveModel(topo, cfg)


def test_allreduce_matches_ring_formula(model8):
    n, payload = 8, 256 * MB
    t = model8.allreduce_seconds(payload, n)
    # 1 axis -> 2 directions; ring term 2(N-1)/N * B / (W*2)
    ring = 2 * (n - 1) / n * payload / (100e9 * 2) + 2 * (n - 1) * 1e-6
    tree = 2 * payload / (100e9 * 2) + 2 * 3 * 1e-6
    assert t == pytest.approx(min(ring, tree), rel=1e-9)


def test_allreduce_large_payload_scales_linearly(model8):
    t1 = model8.allreduce_seconds(64 * MB, 8)
    t2 = model8.allreduce_seconds(128 * MB, 8)
    assert t2 / t1 == pytest.approx(2.0, rel=0.05)


def test_allgather_cheaper_than_allreduce(model8):
    b = 128 * MB
    assert model8.allgather_seconds(b, 8) < model8.allreduce_seconds(b, 8)


def test_multi_axis_speedup():
    cfg = IciConfig(link_bandwidth=100e9, efficiency=1.0,
                    hop_latency=0.0, launch_latency=0.0)
    one_axis = CollectiveModel(Topology((64,), (True,)), cfg)
    three_axis = CollectiveModel(Topology((4, 4, 4), (True,) * 3), cfg)
    b = 1024 * MB
    # 3 torus axes = 3x the usable link directions
    assert three_axis.allreduce_seconds(b, 64) < one_axis.allreduce_seconds(b, 64)


def test_permute_neighbor_shift(model8):
    pairs = tuple((i, (i + 1) % 8) for i in range(8))
    t = model8.permute_seconds(64 * MB, pairs)
    # each chip sends one payload over one hop
    assert t == pytest.approx(64 * MB / 100e9 + 1e-6, rel=1e-6)


def test_small_message_latency_dominated(model8):
    t = model8.allreduce_seconds(64, 8)  # 64 bytes
    # tree: 2*log2(8) hops of 1us dominates
    assert t == pytest.approx(6e-6, rel=0.2)


def test_dcn_spanning_group():
    cfg = IciConfig(
        link_bandwidth=100e9, efficiency=1.0, hop_latency=1e-6,
        launch_latency=0.0, chips_per_slice=8, dcn_bandwidth=10e9,
    )
    m = CollectiveModel(Topology((16,), (True,)), cfg)
    intra = CollectiveModel(
        Topology((16,), (True,)),
        IciConfig(link_bandwidth=100e9, efficiency=1.0, hop_latency=1e-6,
                  launch_latency=0.0),
    )
    b = 256 * MB
    assert m.allreduce_seconds(b, 16) > intra.allreduce_seconds(b, 16)


def test_dispatch_kinds(model8):
    b = 8 * MB
    for kind in ("all-reduce", "all-gather", "reduce-scatter", "all-to-all"):
        info = CollectiveInfo(kind, replica_groups=(tuple(range(8)),))
        assert model8.seconds(info, b) > 0
    cp = CollectiveInfo(
        "collective-permute", source_target_pairs=((0, 1), (1, 0))
    )
    assert model8.seconds(cp, b) > 0
