"""Tests for interval-sampled stats (`tpusim/sim/interval.py`) — the
``gpu_stat_sample_freq`` / visualizer-log parity slot (SURVEY.md §5)."""

from pathlib import Path

import pytest

from tpusim.sim.interval import (
    read_interval_log,
    render_text_lanes,
    sample_intervals,
    write_interval_log,
)
from tpusim.timing.config import SimConfig
from tpusim.timing.engine import Engine, EngineResult, TimelineEvent
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


def _result(events):
    res = EngineResult()
    res.timeline = [TimelineEvent(*e) for e in events]
    return res


def test_event_split_across_windows():
    res = _result([("a", "dot", "mxu", 50.0, 250.0)])
    samples = sample_intervals(res, 100.0)
    assert len(samples) == 3
    assert samples[0].unit_busy["mxu"] == pytest.approx(50.0)
    assert samples[1].unit_busy["mxu"] == pytest.approx(100.0)
    assert samples[2].unit_busy["mxu"] == pytest.approx(50.0)
    # op counted once, in its starting window
    assert [s.op_count for s in samples] == [1, 0, 0]
    assert samples[1].utilization("mxu") == pytest.approx(1.0)


def test_busy_conservation():
    """Total bucketed busy time must equal the sum of event durations."""
    res = _result([
        ("a", "dot", "mxu", 0.0, 333.0),
        ("b", "add", "vpu", 100.0, 450.0),
        ("c", "ar", "ici", 50.0, 60.0),
    ])
    samples = sample_intervals(res, 128.0)
    tot = {}
    for s in samples:
        for u, b in s.unit_busy.items():
            tot[u] = tot.get(u, 0.0) + b
    assert tot["mxu"] == pytest.approx(333.0)
    assert tot["vpu"] == pytest.approx(350.0)
    assert tot["ici"] == pytest.approx(10.0)


def test_log_roundtrip(tmp_path):
    res = _result([("a", "dot", "mxu", 0.0, 150.0)])
    samples = sample_intervals(res, 100.0)
    path = tmp_path / "ivl.jsonl.gz"
    write_interval_log(samples, path, meta={"module": "m"})
    header, loaded = read_interval_log(path)
    assert header["module"] == "m"
    assert len(loaded) == len(samples)
    assert loaded[0].unit_busy == samples[0].unit_busy
    with pytest.raises(ValueError):
        import gzip

        bad = tmp_path / "bad.gz"
        with gzip.open(bad, "wt") as f:
            f.write('{"nope": 1}\n')
        read_interval_log(bad)


def test_render_text_lanes_resamples():
    res = _result(
        [("a", "dot", "mxu", float(i * 10), float(i * 10 + 5))
         for i in range(200)]
    )
    samples = sample_intervals(res, 10.0)
    text = render_text_lanes(samples, width=40)
    lane = [l for l in text.splitlines() if l.strip().startswith("mxu")][0]
    assert lane.count("|") == 2
    assert len(lane.split("|")[1]) == 40


def test_engine_timeline_to_intervals(fixtures_dir):
    mod = parse_hlo_module((fixtures_dir / "tiny_mlp.hlo").read_text())
    cfg = SimConfig()
    res = Engine(cfg, record_timeline=True).run(mod)
    samples = sample_intervals(res, cfg.stat_sample_cycles)
    assert samples
    busy = sum(s.unit_busy.get("mxu", 0.0) for s in samples)
    assert busy == pytest.approx(
        res.unit_busy_cycles["mxu"], rel=1e-6
    )
