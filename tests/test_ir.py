"""Unit tests for the trace IR (tpusim/ir.py)."""

from tpusim.ir import (
    CollectiveInfo,
    CommandKind,
    TensorSpec,
    TraceCommand,
    TupleSpec,
    base_opcode,
    dtype_bytes,
)


def test_dtype_bytes():
    assert dtype_bytes("f32") == 4
    assert dtype_bytes("bf16") == 2
    assert dtype_bytes("s8") == 1
    assert dtype_bytes("u4") == 0.5
    assert dtype_bytes("pred") == 1


def test_tensor_spec_bytes():
    t = TensorSpec("bf16", (128, 512))
    assert t.elems == 128 * 512
    assert t.nbytes == 128 * 512 * 2
    scalar = TensorSpec("f32", ())
    assert scalar.elems == 1
    assert scalar.nbytes == 4
    sub_byte = TensorSpec("u4", (3,))
    assert sub_byte.nbytes == 2  # ceil(1.5)


def test_tuple_spec():
    t = TupleSpec((TensorSpec("f32", (8,)), TensorSpec("u32", ())))
    assert t.nbytes == 32 + 4
    assert [str(x) for x in t.leaves()] == ["f32[8]", "u32[]"]


def test_base_opcode():
    assert base_opcode("all-reduce-start") == "all-reduce"
    assert base_opcode("all-reduce-done") == "all-reduce"
    assert base_opcode("copy-start") == "copy"
    assert base_opcode("dot") == "dot"


def test_collective_group_size():
    c = CollectiveInfo("all-reduce", replica_groups=((0, 1), (2, 3)))
    assert c.group_size == 2
    p = CollectiveInfo(
        "collective-permute", source_target_pairs=((0, 1), (1, 2), (2, 0))
    )
    assert p.group_size == 3


def test_trace_command_roundtrip():
    from tpusim.trace.format import command_from_json, command_to_json

    cmd = TraceCommand(
        kind=CommandKind.COLLECTIVE,
        stream_id=2,
        device_id=1,
        nbytes=4096,
        collective=CollectiveInfo("all-reduce", replica_groups=((0, 1),)),
    )
    back = command_from_json(command_to_json(cmd))
    assert back.kind == cmd.kind
    assert back.nbytes == 4096
    assert back.collective.replica_groups == ((0, 1),)
