"""Trace scale hygiene (VERDICT r1 #9): gzip'd module storage + lazy
per-computation parsing, so Llama-70B-class optimized HLO (100s of MB of
text) replays under a bounded memory footprint.  Reference spirit:
``trace_parser.cc:86-125`` on-the-fly decompression + per-kernel
streaming."""

from __future__ import annotations

import tracemalloc
from pathlib import Path

import pytest

from tpusim.ir import CommandKind, TraceCommand
from tpusim.timing.config import SimConfig
from tpusim.timing.engine import Engine
from tpusim.trace.format import load_trace, save_trace
from tpusim.trace.hlo_text import parse_hlo_module
from tpusim.trace.lazy import parse_hlo_module_lazy

FIXTURES = Path(__file__).parent / "fixtures"


def _synthetic_module(n_unreachable: int, pad_lines: int = 6) -> str:
    """ENTRY + one reachable fusion + ``n_unreachable`` dead computations
    (partition variants / dead branches in real dumps)."""
    parts = ["HloModule synthetic, is_scheduled=true", ""]
    parts.append(
        "%live_fusion (p0: f32[256,256]) -> f32[256,256] {\n"
        "  %p0 = f32[256,256]{1,0} parameter(0)\n"
        "  %czero = f32[] constant(0)\n"
        "  %bz = f32[256,256]{1,0} broadcast(%czero), dimensions={}\n"
        "  ROOT %mx = f32[256,256]{1,0} maximum(%p0, %bz)\n"
        "}\n"
    )
    for i in range(n_unreachable):
        lines = [f"%dead.{i} (a: f32[128,128]) -> f32[128,128] {{",
                 "  %a = f32[128,128]{1,0} parameter(0)"]
        prev = "%a"
        for j in range(pad_lines):
            lines.append(
                f"  %m.{i}.{j} = f32[128,128]{{1,0}} multiply({prev}, {prev})"
            )
            prev = f"%m.{i}.{j}"
        lines.append(f"  ROOT %r.{i} = f32[128,128]{{1,0}} add({prev}, {prev})")
        lines.append("}\n")
        parts.append("\n".join(lines))
    parts.append(
        "ENTRY %main (x: f32[256,256], w: f32[256,256]) -> f32[256,256] {\n"
        "  %x = f32[256,256]{1,0} parameter(0)\n"
        "  %w = f32[256,256]{1,0} parameter(1)\n"
        "  %dot.0 = f32[256,256]{1,0} dot(%x, %w), "
        "lhs_contracting_dims={1}, rhs_contracting_dims={0}\n"
        "  ROOT %f = f32[256,256]{1,0} fusion(%dot.0), kind=kLoop, "
        "calls=%live_fusion\n"
        "}\n"
    )
    return "\n".join(parts)


def test_lazy_matches_eager_on_fixture():
    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    eager = Engine(SimConfig()).run(parse_hlo_module(text))
    lazy_mod = parse_hlo_module_lazy(text)
    lazy = Engine(SimConfig()).run(lazy_mod)
    assert lazy.cycles == pytest.approx(eager.cycles)
    assert lazy.flops == pytest.approx(eager.flops)
    assert lazy.op_count == eager.op_count
    assert lazy.ici_bytes == pytest.approx(eager.ici_bytes)


def test_lazy_parses_only_reachable_computations():
    text = _synthetic_module(n_unreachable=200)
    mod = parse_hlo_module_lazy(text)
    assert len(mod.computations) == 202  # entry + live + 200 dead
    assert mod.parsed_count == 0
    res = Engine(SimConfig()).run(mod)
    assert res.cycles > 0
    # the walk touches ENTRY + live_fusion only
    assert mod.parsed_count <= 2, mod.parsed_count
    # membership checks must not force parsing
    assert "dead.0" in mod.computations
    assert mod.parsed_count <= 2


def test_lazy_vmem_scan_matches_eager_walk():
    from tpusim.timing.engine import _vmem_resident_bytes

    text = "\n".join([
        "HloModule vm, is_scheduled=true",
        "",
        "ENTRY %main (p0: f32[1024]) -> f32[1024] {",
        "  %p0 = f32[1024]{0:T(1024)S(1)} parameter(0)",
        "  %a = f32[1024]{0:T(1024)S(1)} add(%p0, %p0)",
        "  %b = f32[1024]{0:T(1024)} add(%a, %a)",   # HBM, not counted
        "  ROOT %c = f32[1024]{0:T(1024)S(1)} copy(%b)",
        "}",
    ])
    eager = _vmem_resident_bytes(parse_hlo_module(text))
    lazy = parse_hlo_module_lazy(text)
    assert lazy.vmem_resident_bytes() == pytest.approx(eager)
    assert eager == 3 * 1024 * 4


def test_gzip_roundtrip_and_simulate(tmp_path):
    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    td = save_trace(
        tmp_path / "trace", modules={"m": text},
        commands=[TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="m")],
        meta={"num_devices": 4},
        compress=True,
    )
    assert (tmp_path / "trace" / "modules" / "m.hlo.gz").exists()
    assert not (tmp_path / "trace" / "modules" / "m.hlo").exists()
    assert td.module_names() == ["m"]
    pod = load_trace(tmp_path / "trace")
    assert "m" in pod.modules
    from tpusim.sim.driver import SimDriver

    report = SimDriver(SimConfig()).run(pod)
    assert report.cycles > 0


def test_auto_compress_threshold(tmp_path):
    import tpusim.trace.format as fmt

    small = "HloModule s\n\nENTRY %e (x: f32[4]) -> f32[4] {\n" \
            "  %x = f32[4]{0} parameter(0)\n" \
            "  ROOT %y = f32[4]{0} add(%x, %x)\n}\n"
    big = small + "// pad\n" * (fmt.COMPRESS_THRESHOLD_BYTES // 6)
    save_trace(tmp_path / "t", modules={"small": small, "big": big},
               commands=[], compress="auto")
    assert (tmp_path / "t" / "modules" / "small.hlo").exists()
    assert (tmp_path / "t" / "modules" / "big.hlo.gz").exists()
    pod = load_trace(tmp_path / "t")
    assert set(pod.modules) == {"small", "big"}


@pytest.mark.slow
def test_large_module_memory_bound():
    """Replaying a big module lazily must stay within a stated memory
    bound (< 4x the text size), while the eager parse blows well past it
    — the 70B-scale property at test-tractable size."""
    text = _synthetic_module(n_unreachable=8000, pad_lines=16)
    size = len(text)
    assert size > 8 * 1024 * 1024  # engages the lazy path by threshold

    tracemalloc.start()
    mod = parse_hlo_module_lazy(text)
    res = Engine(SimConfig()).run(mod)
    _, lazy_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert res.cycles > 0

    tracemalloc.start()
    eager_mod = parse_hlo_module(text)
    _, eager_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    assert lazy_peak < 4 * size, (lazy_peak, size)
    assert eager_peak > lazy_peak * 2, (eager_peak, lazy_peak)


def test_lazy_vmem_scan_matches_eager_on_real_silicon_trace():
    """Contract on a REAL captured module (reduction fixture): the lazy
    raw-text scan and the eager IR walk must agree on vmem residency,
    including the alias rules (copy-start tuples, while results, in-place
    body DUS) that round 4 added after a 5x overcount."""
    from pathlib import Path

    from tpusim.timing.engine import _vmem_resident_bytes
    from tpusim.trace.format import load_trace

    fdir = (
        Path(__file__).parent.parent / "reports" / "silicon" / "reduction"
    )
    if not fdir.exists():
        pytest.skip("silicon fixtures not present")
    td = load_trace(fdir)
    mod = next(iter(td.modules.values()))
    eager = _vmem_resident_bytes(mod)
    text = (fdir / "modules" / "reduction.hlo").read_text()
    lazy = parse_hlo_module_lazy(text)
    assert lazy.vmem_resident_bytes() == pytest.approx(eager, rel=0.02)
    # one 67MB carry + its double buffer — NOT five aliases of it
    assert eager < 3 * 67.2e6
