"""tpusim.analysis — the static analyzer's seeded-defect corpus.

One deliberately broken trace / config / schedule per diagnostic code,
asserting each code fires exactly where expected (file:line anchors
included), plus: registry coverage (every code in CODES is triggered by
at least one seeded defect), JSON-output round-trip, ``--list-codes``
sync, CLI exit codes, and the ``simulate --validate`` refusal path.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tpusim.analysis import (
    CODES,
    Diagnostics,
    Severity,
    analyze_schedule,
    analyze_stats_keys,
    analyze_trace_dir,
    list_code_lines,
)
from tpusim.ici.topology import torus_for

# ---------------------------------------------------------------------------
# Corpus builders
# ---------------------------------------------------------------------------

GOOD_HLO = """HloModule good, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %r = f32[8]{0} negate(%p0)
}
"""


def make_trace(
    tmp_path: Path,
    hlo: str = GOOD_HLO,
    name: str = "good",
    commands: list | None = None,
    raw_commands: list[str] | None = None,
    meta: dict | str | None = None,
) -> Path:
    root = tmp_path / "trace"
    (root / "modules").mkdir(parents=True)
    (root / "modules" / f"{name}.hlo").write_text(hlo)
    if meta is None:
        meta = {"num_devices": 4, "device_kind": "cpu"}
    (root / "meta.json").write_text(
        meta if isinstance(meta, str) else json.dumps(meta)
    )
    lines = [json.dumps(c) for c in (
        commands if commands is not None
        else [{"kind": "kernel_launch", "module": name, "device": 0}]
    )]
    lines += raw_commands or []
    (root / "commandlist.jsonl").write_text("\n".join(lines) + "\n")
    return root


def _trace_defect(hlo: str, name: str = "bad", **kw):
    def build(tmp_path: Path) -> Diagnostics:
        return analyze_trace_dir(
            make_trace(tmp_path, hlo=hlo, name=name, **kw),
            arch="v5e", tuned=False,
        )
    return build


def _perf_defect(hlo: str, name: str = "bad", **kw):
    """Trace defect analyzed with the opt-in TL50x perf passes on."""
    def build(tmp_path: Path) -> Diagnostics:
        return analyze_trace_dir(
            make_trace(tmp_path, hlo=hlo, name=name, **kw),
            arch="v5e", tuned=False, perf=True,
        )
    return build


def _cmd_defect(commands=None, raw=None, meta=None):
    def build(tmp_path: Path) -> Diagnostics:
        return analyze_trace_dir(
            make_trace(
                tmp_path, commands=commands, raw_commands=raw, meta=meta,
            ),
            arch="v5e", tuned=False,
        )
    return build


def _config_defect(overlay: dict, meta: dict | None = None):
    def build(tmp_path: Path) -> Diagnostics:
        return analyze_trace_dir(
            make_trace(tmp_path, meta=meta),
            arch="v5e", overlays=[overlay], tuned=False,
        )
    return build


def _schedule_defect(doc: dict):
    def build(tmp_path: Path) -> Diagnostics:
        return analyze_schedule(doc, torus_for(64, "v5p"))
    return build


def _campaign_defect(doc: dict):
    def build(tmp_path: Path) -> Diagnostics:
        from tpusim.analysis import analyze_campaign_spec

        return analyze_campaign_spec(doc, default_chips=64)
    return build


def _advise_defect(doc: dict):
    def build(tmp_path: Path) -> Diagnostics:
        from tpusim.analysis import analyze_advise_spec

        return analyze_advise_spec(doc, default_chips=8)
    return build


def _fleet_defect(doc: dict):
    def build(tmp_path: Path) -> Diagnostics:
        from tpusim.analysis import analyze_fleet_spec

        return analyze_fleet_spec(doc, default_chips=8)
    return build


def _selfaudit_defect(files: dict[str, str]):
    """Seed a miniature repo and run the TL35x self-audit against it."""
    def build(tmp_path: Path) -> Diagnostics:
        from tpusim.analysis import analyze_self_audit

        root = tmp_path / "repo"
        for rel, text in files.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(text)
        return analyze_self_audit(root=root)
    return build


def _memory_defect(hlo_builder):
    """Trace defect whose HLO depends on the arch capacity (the TL40x
    thresholds are config values, not constants)."""
    def build(tmp_path: Path) -> Diagnostics:
        from tpusim.timing.config import load_config

        cap = load_config(arch="v5e", tuned=False).arch.hbm_gib
        return analyze_trace_dir(
            make_trace(tmp_path, hlo=hlo_builder(cap), name="bad"),
            arch="v5e", tuned=False,
        )
    return build


def _hbm_hlo(frac):
    """Entry param + negate, sized so peak-live HBM = frac * capacity
    (the liveness walk holds param + result simultaneously: 8N bytes
    for f32[N])."""
    def make(cap_gib: float) -> str:
        n = int(frac * cap_gib * (1 << 30) / 8.0)
        return (
            "HloModule big, num_partitions=4\n"
            "\n"
            f"ENTRY %main (p0: f32[{n}]) -> f32[{n}] {{\n"
            f"  %p0 = f32[{n}]{{0}} parameter(0)\n"
            f"  ROOT %r = f32[{n}]{{0}} negate(%p0)\n"
            "}\n"
        )
    return make


def _statskey_defect(files: dict[str, str], schema: dict | None = None):
    """Seed a miniature repo with the audited layout and run the
    stats-key contract pass against it."""
    def build(tmp_path: Path) -> Diagnostics:
        root = tmp_path / "repo"
        defaults = {
            "tpusim/sim/stats.py": "", "tpusim/sim/driver.py": "",
            "tpusim/obs/hub.py": "", "tpusim/faults/schedule.py": "",
            "tpusim/ici/topology.py": "", "tpusim/timing/engine.py": "",
            "tpusim/__main__.py": "",
        }
        defaults.update(files)
        for rel, text in defaults.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(text)
        schema_path = root / "ci" / "faults_schema.json"
        schema_path.parent.mkdir(parents=True, exist_ok=True)
        schema_path.write_text(json.dumps(
            schema if schema is not None
            else {"stats_required_when_active": []}
        ))
        return analyze_stats_keys(root=root, schema_path=schema_path)
    return build


#: (name, codes the defect must fire, builder) — the registry-coverage
#: test asserts the union of `codes` equals the full CODES table.
SEEDED_DEFECTS = [
    ("undefined-operand", {"TL001"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %r = f32[8]{0} add(%p0, %ghost)
}
""")),
    ("use-before-def", {"TL002"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %a = f32[8]{0} add(%p0, %b)
  %b = f32[8]{0} negate(%p0)
  ROOT %r = f32[8]{0} add(%a, %b)
}
""")),
    ("arity", {"TL003"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %r = f32[8]{0} negate(%p0, %p0)
}
""")),
    ("shape-mismatch", {"TL004"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[4] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %r = f32[4]{0} multiply(%p0, %p0)
}
""")),
    ("while-shape", {"TL005"}, _trace_defect(
        """HloModule bad, num_partitions=4

%cond (t: f32[8]) -> pred[] {
  %t = f32[8]{0} parameter(0)
  ROOT %lt = pred[] constant(true)
}

%body (t2: f32[8]) -> f32[4] {
  %t2 = f32[8]{0} parameter(0)
  ROOT %s = f32[4]{0} slice(%t2), slice={[0:4]}
}

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %w = f32[8]{0} while(%p0), condition=%cond, body=%body
}
""")),
    ("unknown-module", {"TL006"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "kernel_launch", "module": "nope", "device": 0},
    ])),
    ("device-range", {"TL007"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 9},
    ])),
    ("collective-bytes", {"TL008"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[16] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %ar = f32[16]{0} all-reduce(%p0), replica_groups={{0,1},{2,3}}
}
""")),
    ("replica-group-range", {"TL009"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %ar = f32[8]{0} all-reduce(%p0), replica_groups={{0,1},{2,7}}
}
""")),
    ("commandlist-syntax", {"TL010"}, _cmd_defect(
        raw=["{not json", '{"kind": "warp_launch"}'],
    )),
    ("meta-syntax", {"TL010"}, _cmd_defect(meta="{broken")),
    ("no-entry", {"TL011"}, _trace_defect(
        """HloModule bad, num_partitions=4

%helper (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %r = f32[8]{0} negate(%p0)
}
""")),
    ("parse-skipped", {"TL012"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %x = f32[8]{0} add(%p0, %p0 qq
  ROOT %r = f32[8]{0} negate(%p0)
}
""")),
    ("missing-called", {"TL013"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %f = f32[8]{0} fusion(%p0), kind=kLoop, calls=%gone
}
""")),
    ("group-tiling", {"TL014"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %ar = f32[8]{0} all-reduce(%p0), replica_groups={{0,1}}
}
""")),
    ("zero-byte-collective", {"TL015"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "collective", "device": 0, "bytes": 0,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1], [2, 3]]}},
    ])),
    ("nonpositive-clock", {"TL101"}, _config_defect(
        {"arch": {"clock_ghz": 0.0}},
    )),
    ("roofline", {"TL102"}, _config_defect(
        {"arch": {"mxu_rows": 12}},
    )),
    ("arch-mismatch", {"TL103"}, _config_defect(
        {}, meta={"num_devices": 4, "device_kind": "TPU v4"},
    )),
    ("fraction-range", {"TL104"}, _config_defect(
        {"arch": {"hbm_efficiency": 1.5}},
    )),
    ("bad-enum", {"TL105"}, _config_defect(
        {"arch": {"ici": {"network_mode": "quantum"}}},
    )),
    ("negative-latency", {"TL106"}, _config_defect(
        {"arch": {"hbm_latency": -1e-6}},
    )),
    ("config-compose", {"TL107"}, _config_defect(
        "/nonexistent/overlay.flags",
    )),
    ("slice-tiling", {"TL108"}, _config_defect(
        {"arch": {"ici": {"chips_per_slice": 3}}},
    )),
    ("schedule-window", {"TL201"}, _schedule_defect(
        {"faults": [{"kind": "chip_straggler", "chip": 0,
                     "clock_scale": 0.5,
                     "start_cycle": 5, "end_cycle": 5}]},
    )),
    ("schedule-binding", {"TL202"}, _schedule_defect(
        {"faults": [{"kind": "link_down",
                     "src": [0, 0, 0], "dst": [2, 0, 0]}]},
    )),
    ("overlapping-faults", {"TL203"}, _schedule_defect(
        {"faults": [
            {"kind": "link_degraded", "src": 0, "dst": 1,
             "bandwidth_scale": 0.5},
            {"kind": "link_degraded", "src": 1, "dst": 0,
             "bandwidth_scale": 0.25},
        ]},
    )),
    ("no-effect-scale", {"TL204"}, _schedule_defect(
        {"faults": [{"kind": "hbm_throttle", "chip": 3,
                     "hbm_scale": 1.0}]},
    )),
    ("campaign-unknown-kind", {"TL210"}, _campaign_defect(
        {"seed": 1, "scenarios": 4,
         "faults": {"kinds": ["cosmic_ray"]}},
    )),
    ("campaign-empty-candidates", {"TL211"}, _campaign_defect(
        {"seed": 1, "scenarios": 4, "candidate_slices": []},
    )),
    ("campaign-percentile", {"TL212"}, _campaign_defect(
        {"seed": 1, "scenarios": 4,
         "slo": {"step_time_ms": 2.0, "percentile": 250},
         "candidate_slices": [{"arch": "v5p", "chips": 16}]},
    )),
    ("campaign-absent-group-link", {"TL213"}, _campaign_defect(
        {"seed": 1, "scenarios": 4, "arch": "v5p", "chips": 64,
         "correlated_groups": [
             {"name": "ghost-bundle", "prob": 0.5,
              "links": [[[0, 0, 0], [2, 0, 0]]]},
         ]},
    )),
    ("dcn-bad-block", {"TL230"}, _campaign_defect(
        {"seed": 1, "scenarios": 4, "dcn": {"num_slices": 1}},
    )),
    ("dcn-kind-without-fabric", {"TL231"}, _campaign_defect(
        {"seed": 1, "scenarios": 4,
         "faults": {"kinds": ["slice_down"]}},
    )),
    ("dcn-geometry", {"TL232"}, _campaign_defect(
        {"seed": 1, "scenarios": 4, "chips": 4,
         "dcn": {"num_slices": 8}},
    )),
    ("advise-unknown-field", {"TL220"}, _advise_defect(
        {"strategies": ["dp"], "warp_drive": True},
    )),
    ("advise-unknown-strategy", {"TL221"}, _advise_defect(
        {"strategies": ["dp", "warp"]},
    )),
    ("advise-mesh-not-factoring", {"TL222"}, _advise_defect(
        {"strategies": ["dp"],
         "slices": [{"arch": "v5p", "chips": 8}],
         "meshes": [{"dp": 3, "tp": 2}]},
    )),
    ("advise-unknown-arch", {"TL223"}, _advise_defect(
        {"strategies": ["dp"],
         "slices": [{"arch": "v9z", "chips": 8}]},
    )),
    ("advise-slo-without-candidates", {"TL224"}, _advise_defect(
        {"strategies": ["dp"], "slices": [],
         "slo": {"step_time_ms": 1.0}},
    )),
    ("fleet-bad-policy", {"TL240"}, _fleet_defect(
        {"seed": 1, "pods": 2,
         "policies": {"deadline_s": 0.0}},
    )),
    ("fleet-bad-load-point", {"TL241"}, _fleet_defect(
        {"seed": 1, "pods": 2, "horizon_s": 3600.0,
         "traffic": {"load_points": [1e9]}},
    )),
    ("fleet-frontier-without-slo", {"TL242"}, _fleet_defect(
        {"seed": 1, "pods": 2,
         "frontier": {"target_rps": [10.0], "max_pods": 4}},
    )),
    ("fleet-absent-group-axis", {"TL243"}, _fleet_defect(
        {"seed": 1, "pods": 2, "arch": "v5p", "chips": 8,
         "correlated_groups": [
             {"name": "ghost-axis", "prob": 0.5, "axis": 7},
         ]},
    )),
    ("hbm-will-not-fit", {"TL400"}, _memory_defect(_hbm_hlo(1.5))),
    ("hbm-near-capacity", {"TL402"}, _memory_defect(_hbm_hlo(0.97))),
    ("vmem-spill", {"TL401"}, _trace_defect(
        """HloModule bad, num_partitions=4

ENTRY %main (p0: f32[8192,8192]) -> f32[8192,8192] {
  %p0 = f32[8192,8192]{1,0:T(8,128)S(1)} parameter(0)
  ROOT %r = f32[8192,8192]{1,0:T(8,128)S(1)} negate(%p0)
}
""")),
    ("collective-kind-mismatch", {"TL410"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "kernel_launch", "module": "good", "device": 1},
        {"kind": "collective", "device": 0, "bytes": 1024,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1]]}},
        {"kind": "collective", "device": 1, "bytes": 1024,
         "collective": {"kind": "all-gather",
                        "replica_groups": [[0, 1]]}},
    ])),
    ("collective-group-mismatch", {"TL411"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "kernel_launch", "module": "good", "device": 1},
        {"kind": "collective", "device": 0, "bytes": 1024,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1]]}},
        {"kind": "collective", "device": 1, "bytes": 1024,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[1, 0]]}},
    ])),
    ("collective-never-issued", {"TL412"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "kernel_launch", "module": "good", "device": 1},
        {"kind": "collective", "device": 0, "bytes": 1024,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1]]}},
    ])),
    ("collective-bytes-mismatch", {"TL413"}, _cmd_defect(commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "kernel_launch", "module": "good", "device": 1},
        {"kind": "collective", "device": 0, "bytes": 1024,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1]]}},
        {"kind": "collective", "device": 1, "bytes": 2048,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1]]}},
    ])),
    ("unseeded-rng", {"TL350"}, _selfaudit_defect({
        "tpusim/campaign/evil.py":
            "import random\n"
            "def draw():\n"
            "    return random.random()\n",
    })),
    ("wall-clock", {"TL351"}, _selfaudit_defect({
        "tpusim/fleet/evil.py":
            "import time\n"
            "def stamp():\n"
            "    return time.time()\n",
    })),
    ("unsynced-replace", {"TL352"}, _selfaudit_defect({
        "tpusim/newstore/store.py":
            "import os\n"
            "def publish(tmp, path):\n"
            "    with open(tmp, 'w') as f:\n"
            "        f.write('x')\n"
            "    os.replace(tmp, path)\n",
    })),
    ("lock-across-fork", {"TL353"}, _selfaudit_defect({
        "tpusim/serve/evil.py":
            "import multiprocessing\n"
            "import threading\n"
            "_lock = threading.Lock()\n"
            "def spawn():\n"
            "    ctx = multiprocessing.get_context('fork')\n"
            "    with _lock:\n"
            "        ctx.Process(target=None).start()\n",
    })),
    # TL50x: the opt-in perf passes (critical path / exposed
    # communication over tpusim.analysis.critpath), each seeded with a
    # module engineered on v5e to trip exactly one finding family.
    ("perf-summary", {"TL500"}, _perf_defect(GOOD_HLO)),
    ("exposed-collective", {"TL500", "TL501"}, _perf_defect(
        # the async all-reduce is ~100% exposed while an independent
        # 1024^3 dot sits AFTER the join — movable into its window
        """HloModule tl501, is_scheduled=true, num_partitions=4

%r (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (p0: f32[2097152], p1: f32[1024,1024]) -> f32[2097152] {
  %p0 = f32[2097152]{0} parameter(0)
  %p1 = f32[1024,1024]{1,0} parameter(1)
  %st = f32[2097152]{0} all-reduce-start(%p0), channel_id=1, replica_groups={{0,1,2,3}}, to_apply=%r
  %dn = f32[2097152]{0} all-reduce-done(%st)
  %dot = f32[1024,1024]{1,0} dot(%p1, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %out = f32[2097152]{0} add(%dn, %dn)
}
""")),
    ("serialization-bubble", {"TL500", "TL502"}, _perf_defect(
        # 'n' is a big kernel pinned behind a cheap convert tapped off
        # the dot chain at d4: its other operand (p0) was ready at t=0,
        # so it idles ~4 dot-widths; the chain through d12 keeps n off
        # the critical path (on the path TL502 would be a TL500 story)
        """HloModule tl502, is_scheduled=true

ENTRY %main (p0: f32[512,512]) -> f32[512,512] {
  %p0 = f32[512,512]{1,0} parameter(0)
  %d1 = f32[512,512]{1,0} dot(%p0, %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d2 = f32[512,512]{1,0} dot(%d1, %d1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d3 = f32[512,512]{1,0} dot(%d2, %d2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d4 = f32[512,512]{1,0} dot(%d3, %d3), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %cv = f32[1]{0} convert(%d4)
  %n = f32[512,512]{1,0} custom-call(%p0, %cv), custom_call_target="tpu_custom_call", backend_config={"custom_call_config": {"cost_estimate": {"flops": 5200000000, "transcendentals": 0, "bytes_accessed": 8192}}}
  %d5 = f32[512,512]{1,0} dot(%d4, %d4), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d6 = f32[512,512]{1,0} dot(%d5, %d5), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d7 = f32[512,512]{1,0} dot(%d6, %d6), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d8 = f32[512,512]{1,0} dot(%d7, %d7), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d9 = f32[512,512]{1,0} dot(%d8, %d8), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d10 = f32[512,512]{1,0} dot(%d9, %d9), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d11 = f32[512,512]{1,0} dot(%d10, %d10), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d12 = f32[512,512]{1,0} dot(%d11, %d11), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %out = f32[512,512]{1,0} add(%d12, %n)
}
""")),
    ("hbm-dominated-path", {"TL500", "TL503"}, _perf_defect(
        # cost_estimate claims 10 GiB of traffic against 8.6 GFLOP on
        # an 8 MB shape: intensity 1024 flops/byte, far past the v5e
        # ridge, yet the op prices HBM-bound and IS the critical path
        """HloModule tl503, is_scheduled=true

ENTRY %main (a: f32[1024,1024]) -> f32[1024,1024] {
  %a = f32[1024,1024]{1,0} parameter(0)
  ROOT %cc = f32[1024,1024]{1,0} custom-call(%a), custom_call_target="tpu_custom_call", backend_config={"custom_call_config": {"cost_estimate": {"flops": 8589934592, "transcendentals": 0, "bytes_accessed": 10737418240}}}
}
""")),
    ("non-finite-cost", {"TL500", "TL504"}, _perf_defect(
        # 1e999 overflows to inf in the cost_estimate parser — the
        # analyzer must flag the poisoned op, not propagate NaN math
        """HloModule tl504, is_scheduled=true

ENTRY %main (a: f32[1024,1024]) -> f32[1024,1024] {
  %a = f32[1024,1024]{1,0} parameter(0)
  ROOT %cc = f32[1024,1024]{1,0} custom-call(%a), custom_call_target="tpu_custom_call", backend_config={"custom_call_config": {"cost_estimate": {"flops": 1e999, "transcendentals": 0, "bytes_accessed": 4096}}}
}
""")),
    ("statskey-ownership", {"TL301"}, _statskey_defect({
        "tpusim/timing/engine.py":
            'def stats_dict(self):\n'
            '    return {"obs_rogue_key": 1.0}\n',
    })),
    ("statskey-prefix", {"TL302"}, _statskey_defect({
        "tpusim/sim/driver.py":
            'report.stats.update(d, prefix="zzz_")\n',
    })),
    ("statskey-schema", {"TL303"}, _statskey_defect(
        {}, schema={"stats_required_when_active": ["faults_phantom"]},
    )),
]

_IDS = [name for name, _, _ in SEEDED_DEFECTS]


@pytest.mark.parametrize(
    "name, codes, build", SEEDED_DEFECTS, ids=_IDS,
)
def test_seeded_defect_fires(name, codes, build, tmp_path):
    diags = build(tmp_path)
    fired = diags.codes()
    assert codes <= fired, (
        f"{name}: expected {sorted(codes)} ⊆ fired {sorted(fired)}:\n"
        + "\n".join(diags.text_lines())
    )
    # severity of every firing matches the registry default
    for d in diags.items:
        assert d.severity is CODES[d.code].severity


def test_registry_fully_covered():
    """Every registered diagnostic code is triggered by at least one
    seeded defect — a new code without a corpus entry fails here."""
    covered = set()
    for _, codes, _ in SEEDED_DEFECTS:
        covered |= codes
    assert covered == set(CODES), (
        f"uncovered codes: {sorted(set(CODES) - covered)}; "
        f"unknown codes in corpus: {sorted(covered - set(CODES))}"
    )


# ---------------------------------------------------------------------------
# Anchors: findings point at the exact artifact line
# ---------------------------------------------------------------------------


def test_anchor_module_line(tmp_path):
    hlo = (
        "HloModule bad, num_partitions=4\n"
        "\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  ROOT %r = f32[8]{0} add(%p0, %ghost)\n"
        "}\n"
    )
    diags = analyze_trace_dir(
        make_trace(tmp_path, hlo=hlo, name="bad"),
        arch="v5e", tuned=False,
    )
    (d,) = diags.by_code("TL001")
    assert d.file == "modules/bad.hlo"
    assert d.line == 5  # the ROOT %r line
    assert d.anchor == "modules/bad.hlo:5"


def test_anchor_commandlist_line(tmp_path):
    diags = analyze_trace_dir(
        make_trace(tmp_path, commands=[
            {"kind": "kernel_launch", "module": "good", "device": 0},
            {"kind": "kernel_launch", "module": "nope", "device": 0},
        ]),
        arch="v5e", tuned=False,
    )
    (d,) = diags.by_code("TL006")
    assert d.anchor == "commandlist.jsonl:2"


def test_line_walk_parity_with_reference_parser():
    """The analyzer's line-anchored module walk must stay behaviorally
    identical to hlo_text.parse_hlo_module — if the two parsers drift,
    lint and replay stop agreeing on what a trace contains.  Pinned on
    the real multi-computation golden fixture."""
    from tpusim.analysis.trace_passes import _parse_module_lines
    from tpusim.trace.hlo_text import parse_hlo_module

    path = (
        Path(__file__).parent / "fixtures" / "traces"
        / "llama_tiny_tp2dp2" / "modules" / "llama_tiny_tp2dp2.hlo"
    )
    text = path.read_text()
    ref = parse_hlo_module(text, name_hint="llama_tiny_tp2dp2")
    pm = _parse_module_lines("llama_tiny_tp2dp2", "m.hlo", text)
    got = pm.module
    assert got.name == ref.name
    assert got.entry_name == ref.entry_name
    assert got.meta.get("num_partitions") == ref.meta.get(
        "num_partitions"
    )
    assert sorted(got.computations) == sorted(ref.computations)
    for name, comp in ref.computations.items():
        got_ops = [(o.name, o.opcode) for o in got.computations[name].ops]
        ref_ops = [(o.name, o.opcode) for o in comp.ops]
        assert got_ops == ref_ops, f"drift in computation {name}"
        # every op has a line anchor, and anchors are strictly ordered
        lines = [
            pm.op_lines[(name, o.name)]
            for o in got.computations[name].ops
        ]
        assert lines == sorted(lines)
    assert not pm.skipped


def test_roofline_pass_survives_non_numeric_overlay(tmp_path):
    """A stringly-typed overlay value must yield diagnostics, not a
    TypeError traceback (the analyzer exists to report broken configs,
    not crash on them)."""
    diags = analyze_trace_dir(
        make_trace(tmp_path), arch="v5e",
        overlays=[{"arch": {"vpu_lanes": "128", "mxu_rows": "8"}}],
        tuned=False,
    )
    assert {"TL101"} <= diags.codes()


def test_clean_trace_is_clean(tmp_path):
    diags = analyze_trace_dir(
        make_trace(tmp_path), arch="v5e", tuned=False,
    )
    assert diags.items == [], "\n".join(diags.text_lines())


def test_golden_fixtures_lint_clean():
    """The acceptance gate in miniature: every checked-in fixture trace
    lints with zero error-level diagnostics on every matrix arch."""
    fixtures = Path(__file__).parent / "fixtures" / "traces"
    for fixture in ("matmul_512", "llama_tiny_tp2dp2"):
        for arch in ("v5e", "v5p", "v6e"):
            diags = analyze_trace_dir(
                fixtures / fixture, arch=arch, tuned=False,
            )
            assert not diags.has_errors, (
                f"{fixture}@{arch}:\n" + "\n".join(diags.text_lines())
            )


def test_repo_statskey_audit_clean():
    diags = analyze_stats_keys()
    assert not diags.items, "\n".join(diags.text_lines())


# ---------------------------------------------------------------------------
# JSON round-trip + registry listing
# ---------------------------------------------------------------------------


def test_json_roundtrip(tmp_path):
    diags = analyze_trace_dir(
        make_trace(tmp_path, commands=[
            {"kind": "kernel_launch", "module": "nope", "device": 9},
        ]),
        arch="v5e", tuned=False,
    )
    assert diags.items
    doc = json.loads(diags.to_json())
    assert doc["format_version"] == 1
    assert doc["counts"]["error"] == diags.count(Severity.ERROR)
    back = Diagnostics.from_doc(doc)
    assert {(d.code, d.severity, d.message, d.file, d.line)
            for d in back.items} \
        == {(d.code, d.severity, d.message, d.file, d.line)
            for d in diags.items}


def test_list_codes_matches_registry():
    """The grouped dump: one [family — module] header per family, one
    line per code, every code under its owning pass module."""
    from tpusim.analysis import family_of

    lines = list_code_lines()
    code_lines = [ln for ln in lines if ln.startswith("TL")]
    headers = [ln for ln in lines if ln.startswith("[")]
    assert len(code_lines) == len(CODES)
    assert headers, "grouped dump must carry family headers"
    current = None
    for line in lines:
        if line.startswith("["):
            current = line
            continue
        code, severity = line.split()[:2]
        assert CODES[code].severity.value == severity
        assert CODES[code].summary in line
        family, module = family_of(code)
        assert current == f"[{family} — {module}]", (
            f"{code} listed under {current}, owner is {module}"
        )
        assert Path(module).exists() or (
            Path(__file__).parent.parent / module
        ).exists(), f"{code}: owning module {module} does not exist"


# ---------------------------------------------------------------------------
# CLI + --validate integration
# ---------------------------------------------------------------------------


def test_cli_lint_exit_codes(tmp_path, capsys):
    from tpusim.__main__ import main

    good = make_trace(tmp_path)
    assert main(["lint", str(good), "--arch", "v5e"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out

    bad = tmp_path / "bad"
    (bad / "modules").mkdir(parents=True)
    (bad / "modules" / "m.hlo").write_text(GOOD_HLO)
    (bad / "commandlist.jsonl").write_text(
        json.dumps({"kind": "kernel_launch", "module": "zzz"}) + "\n"
    )
    assert main(["lint", str(bad), "--arch", "v5e"]) == 1
    out = capsys.readouterr().out
    assert "TL006" in out


def test_cli_lint_strict_gates_warnings(tmp_path, capsys):
    from tpusim.__main__ import main

    # zero-byte standalone collective: warning-only trace
    trace = make_trace(tmp_path, commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "collective", "device": 0, "bytes": 0,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1], [2, 3]]}},
    ])
    assert main(["lint", str(trace), "--arch", "v5e"]) == 0
    capsys.readouterr()
    assert main(
        ["lint", str(trace), "--arch", "v5e", "--strict"]
    ) == 1
    assert "TL015" in capsys.readouterr().out


def test_cli_lint_json_format(tmp_path, capsys):
    from tpusim.__main__ import main

    assert main([
        "lint", str(make_trace(tmp_path)), "--arch", "v5e",
        "--format", "json",
    ]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["diagnostics"] == []


def test_cli_list_codes(capsys):
    from tpusim.__main__ import main

    assert main(["lint", "--list-codes"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert out == list_code_lines()


def test_validate_refuses_broken_trace(tmp_path):
    from tpusim.analysis import ValidationError
    from tpusim.sim.driver import simulate_trace

    trace = make_trace(tmp_path, commands=[
        {"kind": "kernel_launch", "module": "nope", "device": 0},
        {"kind": "kernel_launch", "module": "good", "device": 0},
    ])
    with pytest.raises(ValidationError) as ei:
        simulate_trace(trace, arch="v5e", tuned=False, validate="on")
    assert "TL006" in str(ei.value)
    # the same trace still prices without --validate (opt-in contract):
    # the driver only needs the launches it can resolve... it cannot —
    # an unknown module raises at replay time; validate just says so
    # up front with an anchor instead of mid-run
    with pytest.raises(KeyError):
        simulate_trace(trace, arch="v5e", tuned=False)


def test_validate_strict_gates_warnings(tmp_path):
    from tpusim.analysis import ValidationError
    from tpusim.sim.driver import simulate_trace

    trace = make_trace(tmp_path, commands=[
        {"kind": "kernel_launch", "module": "good", "device": 0},
        {"kind": "collective", "device": 0, "bytes": 0,
         "collective": {"kind": "all-reduce",
                        "replica_groups": [[0, 1], [2, 3]]}},
    ])
    report = simulate_trace(
        trace, arch="v5e", tuned=False, validate="on",
    )
    assert report.cycles > 0
    with pytest.raises(ValidationError):
        simulate_trace(
            trace, arch="v5e", tuned=False, validate="strict",
        )


def test_validate_clean_trace_passes(tmp_path):
    from tpusim.sim.driver import simulate_trace

    report = simulate_trace(
        make_trace(tmp_path), arch="v5e", tuned=False, validate="on",
    )
    assert report.cycles > 0


def test_validate_analyzes_explicit_config(tmp_path):
    """A config passed explicitly to simulate_trace is the one that
    replays, so it is the one --validate must analyze."""
    import dataclasses

    from tpusim.analysis import ValidationError
    from tpusim.sim.driver import simulate_trace
    from tpusim.timing.config import SimConfig

    broken = dataclasses.replace(
        SimConfig(),
        arch=dataclasses.replace(SimConfig().arch, clock_ghz=0.0),
    )
    trace = make_trace(tmp_path)
    with pytest.raises(ValidationError) as ei:
        simulate_trace(trace, config=broken, validate="on")
    assert "TL101" in str(ei.value)


def test_undeclared_pod_allows_any_device_lane(tmp_path):
    """Without an explicit meta num_devices, the driver infers the pod
    from the command lanes — lint must not invent a range to enforce
    (a 1-wide module legitimately replays on every lane)."""
    trace = make_trace(
        tmp_path,
        hlo=GOOD_HLO.replace(", num_partitions=4", ""),
        meta={"device_kind": "cpu"},
        commands=[
            {"kind": "kernel_launch", "module": "good", "device": 0},
            {"kind": "kernel_launch", "module": "good", "device": 1},
        ],
    )
    diags = analyze_trace_dir(trace, arch="v5e", tuned=False)
    assert not diags.by_code("TL007"), "\n".join(diags.text_lines())


def test_validate_binds_schedule_to_explicit_topology(tmp_path):
    """simulate_trace(topology=...) binds faults against that topology;
    --validate must judge the schedule against the same one."""
    from tpusim.sim.driver import simulate_trace

    topo = torus_for(8, "v5p")  # wider than the trace's 4 lanes
    a, b = topo.undirected_links()[-1]
    sched = {"faults": [{"kind": "link_down", "src": a, "dst": b}]}
    trace = make_trace(tmp_path)
    report = simulate_trace(
        trace, arch="v5p", tuned=False, topology=topo, faults=sched,
        validate="on",
    )
    assert report.stats.get("faults_links_down") == 2


def test_validate_escalates_parse_damage_under_strict_loader(tmp_path):
    """A malformed HLO line is fatal to the default strict load_trace,
    so non-lenient --validate must refuse it up front (TL012 at error
    severity); the lenient replay keeps it a warning."""
    from tpusim.analysis import ValidationError
    from tpusim.sim.driver import simulate_trace

    hlo = GOOD_HLO.replace(
        "  ROOT %r = f32[8]{0} negate(%p0)\n",
        "  %x = f32[8]{0} add(%p0, %p0 qq\n"
        "  ROOT %r = f32[8]{0} negate(%p0)\n",
    )
    trace = make_trace(tmp_path, hlo=hlo, name="good")
    with pytest.raises(ValidationError) as ei:
        simulate_trace(trace, arch="v5e", tuned=False, validate="on")
    assert "TL012" in str(ei.value)
    report = simulate_trace(
        trace, arch="v5e", tuned=False, validate="on", lenient=True,
    )
    assert report.cycles > 0


def test_cli_lint_stats_keys_exit_code(capsys):
    """`tpusim lint --stats-keys` exits 0 on a clean repo and shares
    the error gate with trace diagnostics (the documented contract:
    exit 1 on any error-level finding, --strict extends to warnings)."""
    from tpusim.__main__ import main

    assert main(["lint", "--stats-keys"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out
    # the gate is the shared severity gate: a repo with an ownership
    # violation (TL301, error severity) must exit 1 — proven at the
    # analyzer level (the CLI has no root override by design)
    from tpusim.analysis.diagnostics import Severity

    diags = Diagnostics()
    diags.emit("TL301", "seeded")
    gate = diags.has_errors
    assert gate and CODES["TL301"].severity is Severity.ERROR


def test_cli_lint_self_audit(capsys):
    """`tpusim lint --self-audit` runs the TL35x audit over the repo
    and is green (the dataflow-smoke CI gate in miniature)."""
    from tpusim.__main__ import main

    assert main(["lint", "--self-audit"]) == 0
    assert "0 error(s)" in capsys.readouterr().out


def test_cli_lint_faults_requires_trace(capsys):
    from tpusim.__main__ import main

    assert main(["lint", "--stats-keys", "--faults", "x.json"]) == 2
    assert "need a trace dir" in capsys.readouterr().err


def test_overlap_directed_vs_undirected_same_cable():
    """A directed fault written src>dst still stacks with an undirected
    fault on the same cable (normalized-cable bucketing)."""
    topo = torus_for(8, "v5p")
    diags = analyze_schedule({"faults": [
        {"kind": "link_degraded", "src": 1, "dst": 0,
         "bandwidth_scale": 0.5, "directed": True},
        {"kind": "link_down", "src": 0, "dst": 1},
    ]}, topo)
    assert diags.by_code("TL203"), "\n".join(diags.text_lines())
    # ... but opposite DIRECTED halves of one cable are two physical
    # links: no stacking, no diagnostic
    diags = analyze_schedule({"faults": [
        {"kind": "link_degraded", "src": 1, "dst": 0,
         "bandwidth_scale": 0.5, "directed": True},
        {"kind": "link_degraded", "src": 0, "dst": 1,
         "bandwidth_scale": 0.5, "directed": True},
    ]}, topo)
    assert not diags.by_code("TL203"), "\n".join(diags.text_lines())


# ---------------------------------------------------------------------------
# Lenient-parse dedup satellite (hlo_text)
# ---------------------------------------------------------------------------


def test_lenient_parse_dedups_repeated_malformed_lines():
    from tpusim.trace.hlo_text import parse_hlo_module

    corrupt = "  %x = f32[8]{0} add(%p0, %p0 qq\n"
    text = (
        "HloModule torn\n"
        "\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        + corrupt * 50
        + "  %y = f32[8]{0} oops(%p0 zz\n"
        "  ROOT %r = f32[8]{0} negate(%p0)\n"
        "}\n"
    )
    with pytest.warns(UserWarning, match="2 distinct"):
        mod = parse_hlo_module(text, name_hint="torn", strict=False)
    assert mod.meta["parse_skipped_lines"] == 51
    assert mod.meta["parse_skipped_distinct"] == 2
    assert len(mod.meta["parse_skipped_samples"]) == 2


def test_lint_surfaces_parse_damage_as_tl012(tmp_path):
    hlo = (
        "HloModule torn, num_partitions=4\n"
        "\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  %x = f32[8]{0} add(%p0, %p0 qq\n"
        "  ROOT %r = f32[8]{0} negate(%p0)\n"
        "}\n"
    )
    diags = analyze_trace_dir(
        make_trace(tmp_path, hlo=hlo, name="torn"),
        arch="v5e", tuned=False,
    )
    (d,) = diags.by_code("TL012")
    assert d.severity is Severity.WARNING
    assert d.anchor == "modules/torn.hlo:5"
