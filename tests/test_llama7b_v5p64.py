"""BASELINE config #5: Llama-2-7B pjit train step on a modeled v5p-64.

The capture runs ahead-of-silicon (AOT): ShapeDtypeStruct args with real
dp8 x tp8 GSPMD shardings on 64 virtual CPU devices — no parameters are
ever materialized — then the trace is replayed on the v5p-64 ICI torus
model.  This is the framework's flagship end-to-end path.
"""

import json

import pytest

from tests.conftest import run_in_cpu_mesh

CAPTURE_SCRIPT = r"""
import json
import os
from pathlib import Path

# the flagship path exercises the shipped model: preset + the committed
# cross-generation derived overlay (docs/V5P.md), not the bare preset
# the conftest isolation would leave us with (run_in_cpu_mesh children
# run with cwd = repo root)
os.environ["TPUSIM_TUNED_DIR"] = str(Path.cwd() / "configs")

from tpusim.models.llama import build_llama_aot
from tpusim.tracer.capture import capture
from tpusim.timing.engine import Engine
from tpusim.timing.config import load_config
from tpusim.ici.topology import torus_for

fn, args = build_llama_aot(preset="7b", batch=8, seq=2048, dp=8, tp=8,
                           train=True)
cap = capture(fn, *args, name="llama7b_v5p64", include_memcpy=False)
mod = cap.module
cfg = load_config(arch="v5p")
res = Engine(cfg).run(mod)
topo = torus_for(64, "v5p")
print("RESULT " + json.dumps({
    "num_partitions": mod.num_partitions,
    "collectives": len(mod.collectives()),
    "step_seconds": res.seconds,
    "per_chip_flops": res.flops,
    "mxu_utilization": res.mxu_utilization,
    "ici_bytes": res.ici_bytes,
    "exposed_coll_s": res.exposed_collective_cycles / cfg.arch.clock_hz,
    "topo_dims": list(topo.dims),
}))
"""


@pytest.mark.slow
def test_llama7b_aot_capture_and_v5p64_sim():
    out = run_in_cpu_mesh(CAPTURE_SCRIPT, n_devices=64, timeout=580)
    line = [l for l in out.splitlines() if l.startswith("RESULT ")][0]
    r = json.loads(line[len("RESULT "):])

    assert r["num_partitions"] == 64
    assert r["collectives"] >= 1, "tp/dp sharded train step must communicate"
    assert r["topo_dims"] == [4, 4, 4]

    # per-chip useful flops for batch 8 x seq 2048 over 64 chips:
    # ~6 * 6.7e9 params * 16384 tokens / 64 chips ~= 1.0e13
    assert 0.5e13 < r["per_chip_flops"] < 3e13

    # defended window (docs/V5P.md): MFU in [9%, 90%] for 2048
    # tokens/chip with tp8 -> step in [25ms, 250ms].  The lower edge is
    # the impossible-best bound; the upper edge quantifies the known
    # CPU-capture bias (f32 fusion buffers, unoverlapped dp all-reduce).
    # Round-5 regressions this pins: the per-table-element scatter
    # charge (271ms -> ~1ms) and f32-rate pricing of upcast bf16 dots
    # (3.5% MFU); either coming back pushes step past 250ms.
    assert 0.025 < r["step_seconds"] < 0.250, r["step_seconds"]

    peak = 2.0 * 8 * 128 * 128 * 1.75e9 * 64  # v5p-64 bf16 peak
    mfu = r["per_chip_flops"] * 64 / peak / r["step_seconds"]
    assert 0.09 < mfu < 0.90, mfu

    # collectives must neither be free nor dominate this compute-heavy step
    assert 0 < r["exposed_coll_s"] < r["step_seconds"] * 0.8
    assert r["ici_bytes"] > 1e9  # gradients + activations actually moved
