"""The committed leave-one-out artifact: out-of-sample validation of the
refinement procedure.

Round 4's 1.19% headline was in-sample — 15 knobs fit to the same ten
totals the bench reports (VERDICT r4 Missing #2).  ``python -m tpusim
loo`` refits on N-1 fixtures per fold (preset-seeded, anchored) and
scores the held-out replay; the committed ``reports/loo.json`` pins the
procedure's generalization at the north-star bound.

Reference analogue: the tuner fits on microbenchmarks and validates on
different applications (``util/tuner/tuner.py:23-67`` +
``define-all-apps.yml:12-40``).
"""

from __future__ import annotations

import json
import math
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
ARTIFACT = REPO / "reports" / "loo.json"


@pytest.fixture(scope="module")
def loo() -> dict:
    assert ARTIFACT.is_file(), "committed LOO artifact missing"
    return json.loads(ARTIFACT.read_text())


def test_loo_mean_within_north_star(loo):
    mean = loo.get("mean_loo_abs_err_pct")
    assert mean is not None and math.isfinite(mean)
    # the north-star bound (BASELINE.md <=15%), held-out this time
    assert mean <= 15.0, f"out-of-sample mean regressed: {mean}%"


def test_loo_covers_all_fixture_workloads(loo):
    man = json.loads(
        (REPO / "reports" / "silicon" / "manifest.json").read_text()
    )
    fold_names = {f["workload"] for f in loo.get("folds", [])}
    manifest_names = {w["name"] for w in man.get("workloads", [])}
    assert manifest_names <= fold_names, (
        f"workloads never held out: {manifest_names - fold_names}"
    )
    for f in loo["folds"]:
        assert f.get("held_out_err_pct") is not None, (
            f"{f['workload']}: fold did not score"
        )


def test_loo_procedure_is_anchored_and_preset_seeded(loo):
    """The committed number must describe the regularized procedure the
    production refit uses (bench.py passes anchor_weight=1.0), seeded
    from the preset so the committed all-ten overlay can't leak into a
    fold."""
    assert loo.get("seed") == "preset"
    assert loo.get("anchor_weight", 0) > 0


def test_mini_loo_runs(tmp_path):
    """Two-fold LOO over two fixture workloads exercises the code path
    end-to-end in the fast tier (the full ten-fold run is an offline
    CLI: ``python -m tpusim loo``)."""
    from tpusim.harness.refine import leave_one_out, load_per_op_rows

    man = json.loads(
        (REPO / "reports" / "silicon" / "manifest.json").read_text()
    )
    entries = [
        e for e in man["workloads"]
        if e["name"] in ("reduction", "transcendental")
    ]
    doc = leave_one_out(
        "v5e", entries, REPO / "reports" / "silicon",
        per_op_rows=load_per_op_rows(REPO / "reports" / "correl_ops.json"),
        max_sweeps=1, anchor_weight=1.0,
    )
    assert len(doc["folds"]) == 2
    assert doc["mean_loo_abs_err_pct"] is not None
