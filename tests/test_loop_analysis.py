"""Tests for while-loop trip-count inference (tpusim/trace/loop_analysis.py)."""

from tpusim.trace.hlo_text import parse_hlo_module
from tpusim.trace.loop_analysis import infer_trip_count


def _loop_module(start: int, bound: int, step: int, direction: str = "LT"):
    return parse_hlo_module(f"""
HloModule loop

%body (p: (s32[], f32[8])) -> (s32[], f32[8]) {{
  %p = (s32[], f32[8]) parameter(0)
  %iv = s32[] get-tuple-element(%p), index=0
  %cstep = s32[] constant({step})
  %next = s32[] add(%iv, %cstep)
  %x = f32[8]{{0}} get-tuple-element(%p), index=1
  %y = f32[8]{{0}} add(%x, %x)
  ROOT %t = (s32[], f32[8]) tuple(%next, %y)
}}

%cond (p2: (s32[], f32[8])) -> pred[] {{
  %p2 = (s32[], f32[8]) parameter(0)
  %iv2 = s32[] get-tuple-element(%p2), index=0
  %bound = s32[] constant({bound})
  ROOT %cmp = pred[] compare(%iv2, %bound), direction={direction}
}}

ENTRY %main (a: f32[8]) -> (s32[], f32[8]) {{
  %a = f32[8]{{0}} parameter(0)
  %c0 = s32[] constant({start})
  %init = (s32[], f32[8]) tuple(%c0, %a)
  ROOT %w = (s32[], f32[8]) while(%init), condition=%cond, body=%body
}}
""")


def _trips(mod):
    entry = mod.entry
    return infer_trip_count(mod, entry, entry.op("w"), default=-1)


def test_basic_lt():
    assert _trips(_loop_module(0, 32, 1)) == 32


def test_nonzero_start_and_step():
    assert _trips(_loop_module(4, 32, 2)) == 14
    assert _trips(_loop_module(0, 10, 3)) == 4  # ceil(10/3)


def test_le_direction():
    assert _trips(_loop_module(0, 9, 1, "LE")) == 10


def test_countdown_gt():
    mod = parse_hlo_module("""
HloModule loop

%body (p: (s32[], f32[8])) -> (s32[], f32[8]) {
  %p = (s32[], f32[8]) parameter(0)
  %iv = s32[] get-tuple-element(%p), index=0
  %cstep = s32[] constant(1)
  %next = s32[] subtract(%iv, %cstep)
  %x = f32[8]{0} get-tuple-element(%p), index=1
  ROOT %t = (s32[], f32[8]) tuple(%next, %x)
}

%cond (p2: (s32[], f32[8])) -> pred[] {
  %p2 = (s32[], f32[8]) parameter(0)
  %iv2 = s32[] get-tuple-element(%p2), index=0
  %zero = s32[] constant(0)
  ROOT %cmp = pred[] compare(%iv2, %zero), direction=GT
}

ENTRY %main (a: f32[8]) -> (s32[], f32[8]) {
  %a = f32[8]{0} parameter(0)
  %c = s32[] constant(7)
  %init = (s32[], f32[8]) tuple(%c, %a)
  ROOT %w = (s32[], f32[8]) while(%init), condition=%cond, body=%body
}
""")
    assert _trips(mod) == 7


def test_unrecognized_falls_back_to_default():
    mod = parse_hlo_module("""
HloModule loop

%body (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  ROOT %y = f32[8]{0} add(%p, %p)
}

%cond (p2: f32[8]) -> pred[] {
  %p2 = f32[8]{0} parameter(0)
  ROOT %c = pred[] constant(true)
}

ENTRY %main (a: f32[8]) -> f32[8] {
  %a = f32[8]{0} parameter(0)
  ROOT %w = f32[8]{0} while(%a), condition=%cond, body=%body
}
""")
    entry = mod.entry
    assert infer_trip_count(mod, entry, entry.op("w"), default=5) == 5


def test_real_scan_capture_roundtrip(live_jax):
    """A jax.lax.scan captured on the live backend must get its length
    recovered (backend_config is absent on some backends)."""
    import jax

    from tpusim.tracer.capture import capture
    from tpusim.timing.config import SimConfig
    from tpusim.timing.engine import Engine

    K = 17

    def f(x):
        def body(h, _):
            return h @ h, ()
        out, _ = jax.lax.scan(body, x, None, length=K)
        return out

    import jax.numpy as jnp

    cap = capture(f, jnp.eye(256, dtype=jnp.float32), name="scan17")
    res = Engine(SimConfig()).run(cap.module)
    # 17 iterations of a 256^3 matmul
    assert res.mxu_flops >= K * 2 * 256 ** 3 * 0.99
