"""Tests for while-loop trip-count inference (tpusim/trace/loop_analysis.py)."""

import pytest

from tpusim.trace.hlo_text import parse_hlo_module
from tpusim.trace.loop_analysis import infer_trip_count


def _loop_module(start: int, bound: int, step: int, direction: str = "LT"):
    return parse_hlo_module(f"""
HloModule loop

%body (p: (s32[], f32[8])) -> (s32[], f32[8]) {{
  %p = (s32[], f32[8]) parameter(0)
  %iv = s32[] get-tuple-element(%p), index=0
  %cstep = s32[] constant({step})
  %next = s32[] add(%iv, %cstep)
  %x = f32[8]{{0}} get-tuple-element(%p), index=1
  %y = f32[8]{{0}} add(%x, %x)
  ROOT %t = (s32[], f32[8]) tuple(%next, %y)
}}

%cond (p2: (s32[], f32[8])) -> pred[] {{
  %p2 = (s32[], f32[8]) parameter(0)
  %iv2 = s32[] get-tuple-element(%p2), index=0
  %bound = s32[] constant({bound})
  ROOT %cmp = pred[] compare(%iv2, %bound), direction={direction}
}}

ENTRY %main (a: f32[8]) -> (s32[], f32[8]) {{
  %a = f32[8]{{0}} parameter(0)
  %c0 = s32[] constant({start})
  %init = (s32[], f32[8]) tuple(%c0, %a)
  ROOT %w = (s32[], f32[8]) while(%init), condition=%cond, body=%body
}}
""")


def _trips(mod):
    entry = mod.entry
    return infer_trip_count(mod, entry, entry.op("w"), default=-1)


def test_basic_lt():
    assert _trips(_loop_module(0, 32, 1)) == 32


def test_nonzero_start_and_step():
    assert _trips(_loop_module(4, 32, 2)) == 14
    assert _trips(_loop_module(0, 10, 3)) == 4  # ceil(10/3)


def test_le_direction():
    assert _trips(_loop_module(0, 9, 1, "LE")) == 10


def test_countdown_gt():
    mod = parse_hlo_module("""
HloModule loop

%body (p: (s32[], f32[8])) -> (s32[], f32[8]) {
  %p = (s32[], f32[8]) parameter(0)
  %iv = s32[] get-tuple-element(%p), index=0
  %cstep = s32[] constant(1)
  %next = s32[] subtract(%iv, %cstep)
  %x = f32[8]{0} get-tuple-element(%p), index=1
  ROOT %t = (s32[], f32[8]) tuple(%next, %x)
}

%cond (p2: (s32[], f32[8])) -> pred[] {
  %p2 = (s32[], f32[8]) parameter(0)
  %iv2 = s32[] get-tuple-element(%p2), index=0
  %zero = s32[] constant(0)
  ROOT %cmp = pred[] compare(%iv2, %zero), direction=GT
}

ENTRY %main (a: f32[8]) -> (s32[], f32[8]) {
  %a = f32[8]{0} parameter(0)
  %c = s32[] constant(7)
  %init = (s32[], f32[8]) tuple(%c, %a)
  ROOT %w = (s32[], f32[8]) while(%init), condition=%cond, body=%body
}
""")
    assert _trips(mod) == 7


def test_unrecognized_falls_back_to_default():
    mod = parse_hlo_module("""
HloModule loop

%body (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  ROOT %y = f32[8]{0} add(%p, %p)
}

%cond (p2: f32[8]) -> pred[] {
  %p2 = f32[8]{0} parameter(0)
  ROOT %c = pred[] constant(true)
}

ENTRY %main (a: f32[8]) -> f32[8] {
  %a = f32[8]{0} parameter(0)
  ROOT %w = f32[8]{0} while(%a), condition=%cond, body=%body
}
""")
    entry = mod.entry
    assert infer_trip_count(mod, entry, entry.op("w"), default=5) == 5


def test_real_scan_capture_roundtrip(live_jax):
    """A jax.lax.scan captured on the live backend must get its length
    recovered (backend_config is absent on some backends)."""
    import jax

    from tpusim.tracer.capture import capture
    from tpusim.timing.config import SimConfig
    from tpusim.timing.engine import Engine

    K = 17

    def f(x):
        def body(h, _):
            return h @ h, ()
        out, _ = jax.lax.scan(body, x, None, length=K)
        return out

    import jax.numpy as jnp

    cap = capture(f, jnp.eye(256, dtype=jnp.float32), name="scan17")
    res = Engine(SimConfig()).run(cap.module)
    # 17 iterations of a 256^3 matmul
    assert res.mxu_flops >= K * 2 * 256 ** 3 * 0.99


# -- error-mode visibility (VERDICT r1 weak #5) -----------------------------
#
# When inference fails the engine must (a) flag it, (b) scale with the
# configured fallback — so a silently-dominating heuristic is visible in
# the stats rather than hiding inside the headline number.

DATA_DEPENDENT_WHILE = """\
HloModule dd, is_scheduled=true

%body (p: (f32[1024], f32[])) -> (f32[1024], f32[]) {
  %p = (f32[1024]{0}, f32[]) parameter(0)
  %x = f32[1024]{0} get-tuple-element(%p), index=0
  %m = f32[1024]{0} multiply(%x, %x)
  %e = f32[] reduce-err-placeholder(%m)
  ROOT %t = (f32[1024]{0}, f32[]) tuple(%m, %e)
}

%cond (p2: (f32[1024], f32[])) -> pred[] {
  %p2 = (f32[1024]{0}, f32[]) parameter(0)
  %err = f32[] get-tuple-element(%p2), index=1
  %tol = f32[] constant(0.0001)
  ROOT %c = pred[] compare(%err, %tol), direction=GT
}

ENTRY %main (a: f32[1024], e0: f32[]) -> (f32[1024], f32[]) {
  %a = f32[1024]{0} parameter(0)
  %e0 = f32[] parameter(1)
  %init = (f32[1024]{0}, f32[]) tuple(%a, %e0)
  ROOT %w = (f32[1024]{0}, f32[]) while(%init), condition=%cond, body=%body
}
"""


def test_data_dependent_loop_flagged_and_scales_with_fallback():
    from tpusim.timing.config import SimConfig, overlay
    from tpusim.timing.engine import Engine

    mod = parse_hlo_module(DATA_DEPENDENT_WHILE)
    r1 = Engine(SimConfig()).run(mod)
    assert r1.unknown_trip_loops == 1          # the flag
    r8 = Engine(
        overlay(SimConfig(), {"default_loop_trip_count": 8})
    ).run(mod)
    assert r8.unknown_trip_loops == 1
    # body cost must scale ~linearly with the configured fallback
    assert r8.flops == pytest.approx(8 * r1.flops)
    assert r8.cycles > 4 * r1.cycles
    # and the stat surfaces at driver level
    assert r1.stats_dict()["unknown_trip_loops"] == 1


LOPSIDED_CONDITIONAL = """\
HloModule lop, is_scheduled=true

%cheap (ca: f32[64,64]) -> f32[64,64] {
  %ca = f32[64,64]{1,0} parameter(0)
  ROOT %r0 = f32[64,64]{1,0} add(%ca, %ca)
}

%costly (cb: f32[64,64]) -> f32[64,64] {
  %cb = f32[64,64]{1,0} parameter(0)
  %d1 = f32[64,64]{1,0} dot(%cb, %cb), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d2 = f32[64,64]{1,0} dot(%d1, %d1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d3 = f32[64,64]{1,0} dot(%d2, %d2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %r1 = f32[64,64]{1,0} add(%d3, %d3)
}

ENTRY %main (pr: pred[], x: f32[64,64]) -> f32[64,64] {
  %pr = pred[] parameter(0)
  %x = f32[64,64]{1,0} parameter(1)
  ROOT %c = f32[64,64]{1,0} conditional(%pr, %x, %x), true_computation=%costly, false_computation=%cheap
}
"""


def test_conditional_worst_case_flagged():
    from tpusim.timing.config import SimConfig
    from tpusim.timing.engine import Engine

    mod = parse_hlo_module(LOPSIDED_CONDITIONAL)
    res = Engine(SimConfig()).run(mod)
    # lopsided arms: the worst-case pricing is flagged
    assert res.worst_case_branches == 1
    assert res.stats_dict()["worst_case_branches"] == 1
    # and the time is the costly arm's (3 dots landed in the totals)
    assert res.mxu_flops == pytest.approx(3 * 2 * 64 ** 3)


@pytest.mark.slow
def test_dynamic_loop_workload_flags_unknown_trips(cpu_mesh_runner):
    """The zoo's data-dependent while loop, captured from real XLA output,
    must trip the unknown-bound fallback path visibly."""
    code = (
        "from tpusim.models import get_workload\n"
        "from tpusim.tracer.capture import capture\n"
        "from tpusim.timing.config import SimConfig\n"
        "from tpusim.timing.engine import Engine\n"
        "fn, args = get_workload('dynamic_loop').build(elems=4096)\n"
        "cap = capture(fn, *args, name='dyn')\n"
        "res = Engine(SimConfig()).run(cap.module)\n"
        "assert res.unknown_trip_loops >= 1, res.unknown_trip_loops\n"
        "import numpy as np, jax\n"
        "x = jax.jit(fn)(*args)\n"
        "assert np.allclose(np.asarray(x) ** 2, np.asarray(args[0]), atol=1e-2)\n"
        "print('DYN_OK')\n"
    )
    out = cpu_mesh_runner(code, n_devices=1)
    assert "DYN_OK" in out
