"""Memory-system fidelity tests (VERDICT r1 #4): vmem capacity
enforcement and HBM bandwidth contention between async DMA and compute.

Reference slots: shmem/L1 capacity machinery (``gpu-cache.h``) and the
FR-FCFS DRAM scheduler (``dram_sched.h:41``) — rebuilt here as a vmem
residency budget with spill pricing and a fair-share HBM split.
"""

from __future__ import annotations

import pytest

from tpusim.timing.config import SimConfig, overlay
from tpusim.timing.engine import Engine, _vmem_resident_bytes
from tpusim.trace.hlo_text import parse_hlo_module


def _vmem_module(n_bufs: int, elems: int) -> str:
    """A module whose adds run on ``S(1)`` (vmem-pinned) f32 buffers.

    Every add reads ``p0``, and the root tuples ALL of them together, so
    the buffers are concurrently live — the module's liveness peak equals
    its allocation sum (the capacity model spills on peak *live* bytes,
    not total allocations; a chain of short-lived temporaries would
    correctly never spill)."""
    lines = [
        "HloModule vmem_test, is_scheduled=true",
        "",
        f"ENTRY %main (p0: f32[{elems}]) -> f32[{elems}] {{",
        f"  %p0 = f32[{elems}]{{0:T(1024)S(1)}} parameter(0)",
    ]
    for i in range(n_bufs):
        lines.append(
            f"  %add.{i} = f32[{elems}]{{0:T(1024)S(1)}} add(%p0, %p0)"
        )
    parts = ", ".join(f"%add.{i}" for i in range(n_bufs))
    shapes = ", ".join(
        f"f32[{elems}]{{0:T(1024)S(1)}}" for _ in range(n_bufs)
    )
    lines.append(f"  ROOT %out = ({shapes}) tuple({parts})")
    lines.append("}")
    return "\n".join(lines)


def test_vmem_residency_counted():
    mod = parse_hlo_module(_vmem_module(n_bufs=4, elems=1024))
    # p0 + 4 adds = 5 buffers x 4KB (the tuple root aliases)
    assert _vmem_resident_bytes(mod) == 5 * 1024 * 4


def _vmem_chain_module(n_bufs: int, elems: int) -> str:
    """Like ``_vmem_module`` but each add consumes the previous one, so
    only two buffers are ever live at once despite the same total."""
    lines = [
        "HloModule vmem_chain, is_scheduled=true",
        "",
        f"ENTRY %main (p0: f32[{elems}]) -> f32[{elems}] {{",
        f"  %p0 = f32[{elems}]{{0:T(1024)S(1)}} parameter(0)",
    ]
    prev = "%p0"
    for i in range(n_bufs):
        lines.append(
            f"  %add.{i} = f32[{elems}]{{0:T(1024)S(1)}} "
            f"add({prev}, {prev})"
        )
        prev = f"%add.{i}"
    lines.append(f"  ROOT %out = f32[{elems}]{{0:T(1024)S(1)}} copy({prev})")
    lines.append("}")
    return "\n".join(lines)


def test_dead_temporaries_do_not_spill():
    """XLA reuses vmem slots across disjoint lifetimes: a chain whose
    allocations SUM over budget but whose concurrent peak fits must not
    be priced as spilling (round-4 silicon: decode_step's 210MB-sum /
    120MB-peak step ran fully vmem-resident on a 128MB chip)."""
    elems = 8 * 1024 * 1024  # 32MB per f32 buffer
    over_sum = parse_hlo_module(_vmem_chain_module(n_bufs=6, elems=elems))
    assert _vmem_resident_bytes(over_sum) > SimConfig().arch.vmem_bytes
    r = Engine(SimConfig()).run(over_sum)
    assert r.vmem_spill_bytes == 0
    assert r.vmem_resident_bytes <= SimConfig().arch.vmem_bytes


def test_over_vmem_trace_costs_more():
    """Pinning more S(1) bytes than the 128MB budget must spill: the same
    program shape gets measurably slower once it over-subscribes vmem."""
    elems = 8 * 1024 * 1024  # 32MB per f32 buffer
    # 3 buffers = 96MB: fits.  8 buffers = ~288MB: over-subscribed ~2.3x.
    fits = parse_hlo_module(_vmem_module(n_bufs=1, elems=elems))
    over = parse_hlo_module(_vmem_module(n_bufs=6, elems=elems))

    cfg = SimConfig()
    r_fits = Engine(cfg).run(fits)
    r_over = Engine(cfg).run(over)
    assert r_fits.vmem_spill_bytes == 0
    assert r_over.vmem_spill_bytes > 0
    assert r_over.vmem_resident_bytes > cfg.arch.vmem_bytes

    # per-op cost must rise sharply: spilled traffic streams at HBM rate
    # (10x slower than vmem here), so >2x per-add is a conservative bar
    per_op_fits = r_fits.cycles / len(fits.entry.ops)
    per_op_over = r_over.cycles / len(over.entry.ops)
    assert per_op_over > 2.0 * per_op_fits

    # and the knob turns it off
    off = overlay(cfg, {"model_vmem_capacity": False})
    r_off = Engine(off).run(over)
    assert r_off.vmem_spill_bytes == 0
    assert r_off.cycles < r_over.cycles


HBM_OVERLAP_HLO = """\
HloModule overlap, is_scheduled=true

ENTRY %main (p0: f32[16777216], big: f32[33554432]) -> f32[16777216] {
  %p0 = f32[16777216]{0} parameter(0)
  %big = f32[33554432]{0} parameter(1)
  %cs = (f32[33554432]{0}, f32[33554432]{0:S(1)}, u32[]{:T(256)}) copy-start(%big)
  %mul.0 = f32[16777216]{0} multiply(%p0, %p0)
  %cd = f32[33554432]{0:S(1)} copy-done(%cs)
  ROOT %add.0 = f32[16777216]{0} add(%mul.0, %mul.0)
}
"""


def test_async_copy_contends_with_bandwidth_bound_compute():
    """A 128MB async copy overlapping a 64MB-stream multiply must slow the
    multiply (and stretch the copy) under the fair-share HBM model."""
    mod = parse_hlo_module(HBM_OVERLAP_HLO)
    on = Engine(SimConfig()).run(mod)
    off = Engine(
        overlay(SimConfig(), {"model_hbm_contention": False})
    ).run(mod)
    assert on.hbm_contention_cycles > 0
    assert off.hbm_contention_cycles == 0
    assert on.cycles > off.cycles
    # the contention delta must be material relative to the multiply's own
    # stream time (shared bytes ~= the multiply's traffic)
    assert on.cycles - off.cycles > 0.2 * off.cycles


def test_contention_skipped_when_no_dma_inflight():
    """Back-to-back sync ops (no async DMA) must be unaffected by the
    contention model."""
    text = """\
HloModule plain, is_scheduled=true

ENTRY %main (p0: f32[1048576]) -> f32[1048576] {
  %p0 = f32[1048576]{0} parameter(0)
  %mul.0 = f32[1048576]{0} multiply(%p0, %p0)
  ROOT %add.0 = f32[1048576]{0} add(%mul.0, %mul.0)
}
"""
    mod = parse_hlo_module(text)
    on = Engine(SimConfig()).run(mod)
    off = Engine(
        overlay(SimConfig(), {"model_hbm_contention": False})
    ).run(mod)
    assert on.hbm_contention_cycles == 0
    assert on.cycles == pytest.approx(off.cycles)
