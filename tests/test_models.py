"""Tests for the workload model zoo — including numerical self-checks, the
framework's version of the reference's self-checking rodinia apps
(SURVEY.md §4: each app verifies its own output against a golden result)."""

import pytest

from tests.conftest import require_jax_shard_map, run_in_cpu_mesh
from tpusim.models import get_workload, list_workloads


def test_registry():
    names = {w.name for w in list_workloads()}
    assert {"matmul", "conv2d", "resnet50", "llama_tiny",
            "llama7b_tp8dp8", "ring_attention_sp8", "moe_ep4",
            "pipeline_pp4", "embedding_lookup", "lstm_layer",
            "small_matmul_chain", "ici_allreduce"} <= names
    with pytest.raises(KeyError):
        get_workload("nope")


def test_workload_param_override(live_jax):
    wl = get_workload("matmul")
    fn, args = wl.build(m=64, n=32, k=16)
    a, b = args
    assert a.shape == (64, 16) and b.shape == (16, 32)


def test_llama_tiny_forward_finite(live_jax):
    import jax.numpy as jnp

    wl = get_workload("llama_tiny")
    fn, (params, tokens) = wl.build(batch=2)
    out = fn(params, tokens)
    assert out.shape == (2, tokens.shape[1], 512)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_resnet50_param_count(live_jax):
    import jax

    from tpusim.models.resnet import init_resnet50

    params = init_resnet50(jax.random.PRNGKey(0))
    n = sum(p.size for p in jax.tree_util.tree_leaves(params))
    # torchvision resnet50: 25.56M params; ours lacks BN running stats
    assert 24e6 < n < 27e6


RING_CORRECTNESS_SCRIPT = r"""
import jax, jax.numpy as jnp, numpy as np
from functools import partial
from jax.sharding import Mesh, PartitionSpec as P
from tpusim.models.attention import ring_attention, ulysses_attention

B, S, H, D = 1, 8 * 32, 8, 16
key = jax.random.PRNGKey(0)
kq, kk, kv = jax.random.split(key, 3)
q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
v = jax.random.normal(kv, (B, S, H, D), jnp.float32)

# dense reference
s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D ** 0.5)
ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)

mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
for inner, name in ((ring_attention, "ring"), (ulysses_attention, "ulysses")):
    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
             out_specs=P(None, "sp"))
    def sharded(q, k, v):
        return inner(q, k, v, "sp")
    out = jax.jit(sharded)(q, k, v)
    err = float(jnp.abs(out - ref).max())
    print(name, "max_err", err)
    assert err < 2e-3, (name, err)
print("RING_OK")
"""


@pytest.mark.slow
def test_ring_and_ulysses_match_dense_attention():
    require_jax_shard_map()
    out = run_in_cpu_mesh(RING_CORRECTNESS_SCRIPT, n_devices=8)
    assert "RING_OK" in out


MLP_SELFCHECK_SCRIPT = r"""
import jax, jax.numpy as jnp
from tpusim.models import get_workload

wl = get_workload("mlp_train_step")
step, (params, x, y) = wl.build(batch=64, width=256, depth=2, dtype="float32")
jstep = jax.jit(step)
loss0, params = jstep(params, x, y)
for _ in range(50):
    loss, params = jstep(params, x, y)
print("losses", float(loss0), float(loss))
assert float(loss) < 0.95 * float(loss0), "training must reduce the loss"
print("MLP_OK")
"""


@pytest.mark.slow
def test_mlp_train_step_learns():
    out = run_in_cpu_mesh(MLP_SELFCHECK_SCRIPT, n_devices=1)
    assert "MLP_OK" in out


MOE_SELFCHECK_SCRIPT = r"""
import jax, jax.numpy as jnp
from tpusim.models import get_workload
from tpusim.tracer.capture import capture

# forward path + collective signature
fn, args = get_workload("moe_ep4").build()
out = jax.jit(fn)(*args)
assert bool(jnp.isfinite(out).all())
cap = capture(fn, *args, name="moe")
kinds = {op.base for op in cap.module.all_ops()}
assert "all-to-all" in kinds, kinds

# training self-check: reconstruction loss must descend
step, (params, x, y) = get_workload("moe_ep8_train").build()
jstep = jax.jit(step)
l0, p = jstep(params, x, y)
for _ in range(60):
    l, p = jstep(p, x, y)
assert float(l) < 0.9 * float(l0), (float(l0), float(l))
print("MOE_OK")
"""


@pytest.mark.slow
def test_moe_expert_parallel(cpu_mesh_runner):
    require_jax_shard_map()
    out = cpu_mesh_runner(MOE_SELFCHECK_SCRIPT, n_devices=8)
    assert "MOE_OK" in out


PIPELINE_SCRIPT = r"""
import jax, jax.numpy as jnp
from tpusim.models import get_workload
from tpusim.models.pipeline import reference_forward
from tpusim.tracer.capture import capture

fn, (params, xmb) = get_workload("pipeline_pp4").build()
out = jax.jit(fn)(params, xmb)
ref = reference_forward(params, xmb)
err = float(jnp.abs(out - ref).max())
assert err < 1e-5, err

cap = capture(fn, params, xmb, name="pp")
kinds = {op.base for op in cap.module.all_ops()}
assert "collective-permute" in kinds, kinds
# the schedule is a scan: a while loop must carry the ppermute chain
assert any(op.base == "while" for op in cap.module.all_ops())
print("PP_OK")
"""


@pytest.mark.slow
def test_pipeline_matches_sequential(cpu_mesh_runner):
    require_jax_shard_map()
    out = cpu_mesh_runner(PIPELINE_SCRIPT, n_devices=4)
    assert "PP_OK" in out


DECODE_SELFCHECK_SCRIPT = r"""
import jax, jax.numpy as jnp
import numpy as np
from tpusim.models import get_workload

B, S, H, D, L, P = 2, 16, 2, 8, 2, 5
wl = get_workload("decode_step")
step, (h0, ck, cv, pos, wq, wk, wv, wo) = wl.build(
    batch=B, seq_cache=S, heads=H, head_dim=D, layers=L,
    dtype="float32", pos=P,
)
h1, ck1, cv1, pos1 = jax.jit(step)(h0, ck, cv, pos, wq, wk, wv, wo)
assert int(pos1) == P + 1
assert np.isfinite(np.asarray(h1)).all()

# dense reference: attention over cache[:P+1] per layer, same weights
h = np.asarray(h0, np.float32)
ckn = np.asarray(ck, np.float32).copy()
cvn = np.asarray(cv, np.float32).copy()
for l in range(L):
    q = (h @ np.asarray(wq[l])).reshape(B, H, D)
    k = (h @ np.asarray(wk[l])).reshape(B, H, D)
    v = (h @ np.asarray(wv[l])).reshape(B, H, D)
    ckn[l, :, P] = k
    cvn[l, :, P] = v
    kc = ckn[l][:, : P + 1]          # [B, P+1, H, D]
    vc = cvn[l][:, : P + 1]
    s = np.einsum("bhd,bshd->bhs", q, kc) * (D ** -0.5)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    attn = np.einsum("bhs,bshd->bhd", p, vc)
    h = h + attn.reshape(B, H * D) @ np.asarray(wo[l])

assert np.allclose(np.asarray(h1), h, atol=2e-4), (
    np.abs(np.asarray(h1) - h).max()
)
# the cache rows at P must hold the new k/v; untouched rows unchanged —
# a stray write past P would poison FUTURE steps without changing h here
assert np.allclose(np.asarray(ck1)[:, :, P], ckn[:, :, P], atol=2e-5)
assert np.allclose(np.asarray(ck1)[:, :, P + 1:], ckn[:, :, P + 1:])
assert np.allclose(np.asarray(cv1)[:, :, P], cvn[:, :, P], atol=2e-5)
assert np.allclose(np.asarray(cv1)[:, :, P + 1:], cvn[:, :, P + 1:])

# the cache-full boundary must refuse, not clamp
try:
    wl.build(batch=2, seq_cache=8, heads=2, head_dim=8, layers=1,
             dtype="float32", pos=8)
    raise AssertionError("pos == seq_cache must raise")
except ValueError:
    pass
print("DECODE_OK")
"""


@pytest.mark.slow
def test_decode_step_matches_dense_reference():
    out = run_in_cpu_mesh(DECODE_SELFCHECK_SCRIPT, n_devices=1)
    assert "DECODE_OK" in out


def test_decode_step_capture_and_simulate(cpu_mesh_runner):
    """The decode regime must flow through capture -> engine with the
    in-place DUS appends visible (vmem/dus pricing exercised)."""
    out = cpu_mesh_runner(
        r"""
from tpusim.models import get_workload
from tpusim.tracer.capture import capture
from tpusim.timing.config import load_config
from tpusim.timing.engine import Engine

step, args = get_workload("decode_step").build(
    batch=2, seq_cache=64, heads=2, head_dim=16, layers=2,
    dtype="float32", pos=10,
)
cap = capture(step, *args, name="decode")
res = Engine(load_config(arch="v5e")).run(cap.module)
assert res.cycles > 0
assert res.mxu_flops > 0
print("DECODE_SIM_OK")
""",
        n_devices=1,
    )
    assert "DECODE_SIM_OK" in out


DECODE_TP_SCRIPT = r"""
import jax, jax.numpy as jnp
import numpy as np
from tpusim.models import get_workload
from tpusim.models.decode import _build

kw = dict(batch=2, seq_cache=32, heads=8, head_dim=8, layers=2,
          dtype="float32", pos=7)
tp_step, tp_args = get_workload("decode_step_tp8").build(tp=8, **kw)
h_tp, ck_tp, cv_tp, pos_tp = jax.jit(tp_step)(*tp_args)

ref_step, ref_args = _build(**kw)
h_ref, ck_ref, cv_ref, pos_ref = jax.jit(ref_step)(*ref_args)

# head-sharded attention + psum'd output projection must reproduce the
# single-chip step exactly (same seeds build identical weights/caches)
assert np.allclose(np.asarray(h_tp), np.asarray(h_ref), atol=1e-4), (
    np.abs(np.asarray(h_tp) - np.asarray(h_ref)).max()
)
assert np.allclose(np.asarray(ck_tp), np.asarray(ck_ref), atol=1e-5)
assert int(pos_tp) == int(pos_ref) == 8
print("DECODE_TP_OK")
"""


@pytest.mark.slow
def test_decode_tp8_matches_single_chip():
    require_jax_shard_map()
    out = run_in_cpu_mesh(DECODE_TP_SCRIPT, n_devices=8)
    assert "DECODE_TP_OK" in out
