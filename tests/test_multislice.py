"""Multi-slice / DCN end-to-end tests (VERDICT r1 #6).

A group whose replica ids span ``chips_per_slice`` crosses slice
boundaries; the inter-slice portion rides DCN (25GB/s class) instead of
ICI (90GB/s/link class), so the DCN term must dominate the cost.
Reference spirit: the fork's multi-GPU tracing path
(``tracer_tool.cu:442-445``) — which recorded no byte counts at all; here
the driver prices the recorded groups end-to-end.  Also anchors the
analytic all-to-all model to the detailed packet simulation (the round-1
gap: the axis-factored heuristic had no cross-check).
"""

from __future__ import annotations

import pytest

from tpusim.ici.collectives import CollectiveModel
from tpusim.ici.detailed import DetailedCollectiveModel
from tpusim.ici.topology import Topology
from tpusim.ir import CollectiveInfo, CommandKind, PodTrace, TraceCommand
from tpusim.sim.driver import SimDriver
from tpusim.timing.config import IciConfig, SimConfig, overlay
from tpusim.trace.format import load_trace, save_trace


def _multislice_pod(n_devices: int = 8, nbytes: int = 64 * 1024 * 1024):
    """A pod trace with one all-reduce whose group spans all devices."""
    pod = PodTrace(meta={"num_devices": n_devices})
    info = CollectiveInfo(
        "all-reduce", replica_groups=(tuple(range(n_devices)),)
    )
    for d in range(n_devices):
        pod.device(d).commands.append(TraceCommand(
            kind=CommandKind.COLLECTIVE, device_id=d, nbytes=nbytes,
            collective=info,
        ))
    return pod


def test_dcn_term_engages_at_driver_level(tmp_path):
    """The same trace must cost far more when its group spans two slices
    (chips_per_slice=4 over an 8-chip group) than on a single slice."""
    pod = _multislice_pod(8)
    # round-trip through the on-disk format: this is the fixture path
    td_path = tmp_path / "trace"
    save_trace(
        td_path,
        modules={},
        commands=[c for d in pod.devices.values() for c in d.commands],
        meta=pod.meta,
    )
    pod = load_trace(td_path)

    single = SimDriver(SimConfig()).run(pod)
    multi = SimDriver(overlay(
        SimConfig(), {"arch": {"ici": {"chips_per_slice": 4}}}
    )).run(pod)

    # DCN: 2*(S-1)/S * B / 25GB/s with S=2 slices = B/25e9 ~ 2.7ms vs
    # the ICI ring's ~0.1ms class: at least 3x slower end to end
    assert multi.cycles > 3.0 * single.cycles


def test_dcn_term_matches_closed_form():
    cfg = IciConfig(chips_per_slice=4, dcn_bandwidth=25e9, dcn_latency=10e-6)
    topo = Topology(dims=(8,), wrap=(True,))
    model = CollectiveModel(topo, cfg)
    payload = 100e6
    t = model.allreduce_seconds(payload, 8)
    # 2 slices: 2*(1/2)*B/dcn_bw + dcn_latency*log2(2) + launch
    expect = payload / 25e9 + 10e-6 + cfg.launch_latency
    assert t == pytest.approx(expect, rel=0.01)


def test_multislice_group_in_detailed_mode_uses_analytic_dcn(tmp_path):
    """network_mode=detailed must not collapse a multi-slice group: ids
    >= num_chips alias, so the detailed model defers to the analytic
    slice/DCN split (round-2 aliasing guard) and the DCN cost survives."""
    nbytes = 64 * 1024 * 1024
    pod = PodTrace(meta={"num_devices": 4})
    # 8 replicas on a 4-chip slice topology: ids 4..7 are the second slice
    info = CollectiveInfo("all-reduce", replica_groups=(tuple(range(8)),))
    for d in range(4):
        pod.device(d).commands.append(TraceCommand(
            kind=CommandKind.COLLECTIVE, device_id=d, nbytes=nbytes,
            collective=info,
        ))
    base = {"arch": {"ici": {"chips_per_slice": 4}}}
    ana = SimDriver(overlay(SimConfig(), base)).run(pod)
    det = SimDriver(overlay(
        SimConfig(), base, {"arch": {"ici": {"network_mode": "detailed"}}}
    )).run(pod)
    assert det.cycles == pytest.approx(ana.cycles, rel=0.01)


# -- analytic vs detailed cross-checks --------------------------------------

def _cfg(**kw) -> IciConfig:
    base = dict(
        link_bandwidth=100e9, efficiency=1.0, hop_latency=1e-9,
        launch_latency=0.0, network_mode="detailed",
    )
    base.update(kw)
    return IciConfig(**base)


@pytest.mark.parametrize("dims", [(4,), (8,), (4, 4)])
def test_alltoall_analytic_vs_detailed(dims):
    """The analytic all-to-all (balanced shortest-path bound per axis)
    must agree with the packet simulation within a stated tolerance.
    The detailed model runs above the bound (DOR breaks tie-distance
    routes one way, unbalancing links) but below 1.6x of it."""
    n = 1
    for d in dims:
        n *= d
    topo = Topology(dims=dims, wrap=tuple(True for _ in dims))
    cfg = _cfg()
    info = CollectiveInfo("all-to-all", replica_groups=(tuple(range(n)),))
    payload = 64e6
    t_ana = CollectiveModel(topo, cfg).seconds(info, payload)
    t_det = DetailedCollectiveModel(topo, cfg).seconds(info, payload)
    ratio = t_det / t_ana
    assert 0.75 <= ratio <= 1.6, (dims, t_ana, t_det, ratio)


def test_alltoall_analytic_respects_link_load_bound():
    """The analytic time must never beat the aggregate link-load lower
    bound (total byte-hops / total directed capacity)."""
    n = 8
    topo = Topology(dims=(n,), wrap=(True,))
    cfg = _cfg(hop_latency=0.0)
    payload = 64e6
    t = CollectiveModel(topo, cfg).seconds(
        CollectiveInfo("all-to-all", replica_groups=(tuple(range(n)),)),
        payload,
    )
    w = cfg.link_bandwidth
    # mean shortest-path distance on an even ring = n/4
    byte_hops = n * payload * (n / 4.0)
    bound = byte_hops / (2 * n * w)
    assert t >= bound * 0.999
