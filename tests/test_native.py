"""Contract tests: the native C++ HLO scanner must produce the same IR as
the pure-Python parser (tpusim/trace/hlo_text.py is the reference
implementation)."""

import subprocess
from pathlib import Path

import pytest

from tpusim.trace.hlo_text import parse_hlo_module
from tpusim.trace.native import (
    native_available,
    parse_hlo_module_fast,
    parse_hlo_module_native,
)

FIXTURES = Path(__file__).parent / "fixtures"
REPO = Path(__file__).parent.parent


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(
        ["make", "-C", str(REPO / "native")], capture_output=True, check=True
    )


def _assert_same_module(a, b):
    assert a.name == b.name
    assert a.meta.get("num_partitions") == b.meta.get("num_partitions")
    assert set(a.computations) == set(b.computations)
    assert a.entry_name == b.entry_name
    for cname, comp_a in a.computations.items():
        comp_b = b.computations[cname]
        assert len(comp_a.ops) == len(comp_b.ops), cname
        for oa, ob in zip(comp_a.ops, comp_b.ops):
            assert oa.name == ob.name
            assert oa.opcode == ob.opcode
            assert oa.operands == ob.operands
            assert oa.is_root == ob.is_root
            assert str(oa.result) == str(ob.result)
            assert oa.result.nbytes == ob.result.nbytes
            assert oa.called == ob.called
            assert oa.fusion_kind == ob.fusion_kind
            if oa.collective or ob.collective:
                assert oa.collective == ob.collective
            assert oa.attrs.get("literal") == ob.attrs.get("literal")


def test_native_builds_and_loads():
    assert native_available()


def test_parity_on_fixture():
    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    _assert_same_module(
        parse_hlo_module(text, "tiny"), parse_hlo_module_native(text, "tiny")
    )


def test_parity_on_live_capture(live_jax):
    import jax.numpy as jnp

    from tpusim.tracer.capture import capture

    def f(a, b):
        return (jnp.maximum(a @ b, 0.0) ** 2).mean()

    cap = capture(
        f, jnp.ones((128, 256), jnp.bfloat16), jnp.ones((256, 64), jnp.bfloat16),
        name="parity",
    )
    _assert_same_module(
        parse_hlo_module(cap.hlo_text, "parity"),
        parse_hlo_module_native(cap.hlo_text, "parity"),
    )


def test_parity_engine_results():
    """Both parsers must produce identical simulated cycle counts."""
    from tpusim.timing.config import SimConfig
    from tpusim.timing.engine import Engine

    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    cfg = SimConfig()
    r_py = Engine(cfg).run(parse_hlo_module(text))
    r_nat = Engine(cfg).run(parse_hlo_module_native(text))
    assert r_py.cycles == pytest.approx(r_nat.cycles)
    assert r_py.flops == r_nat.flops


def test_fast_path_prefers_native():
    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    mod = parse_hlo_module_fast(text)
    assert mod.entry_name is not None


def test_native_speedup_on_large_module():
    """The native scanner should beat pure Python on a big module."""
    import time

    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    # synthesize a large module: many cloned computations
    body = text.split("ENTRY")[0]
    clones = []
    for i in range(300):
        clones.append(
            body.replace("region_add", f"region_add_{i}")
                .replace("fused_relu", f"fused_relu_{i}")
                .replace("HloModule jit_tiny_mlp, is_scheduled=true, num_partitions=4, replica_count=1", "")
        )
    big = text.split("ENTRY")[0] + "\n".join(clones) + "ENTRY" + text.split("ENTRY")[1]

    m_py = parse_hlo_module(big)  # warm both paths before timing
    m_nat = parse_hlo_module_native(big)
    assert len(m_py.computations) == len(m_nat.computations)

    def best_of(fn, reps=3):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(big)
            times.append(time.perf_counter() - t0)
        return min(times)

    t_py = best_of(parse_hlo_module)
    t_nat = best_of(parse_hlo_module_native)
    # native is usually ~5-10x faster; allow slack for noisy CI machines
    assert t_nat < t_py * 1.2, (t_nat, t_py)

# ---------------------------------------------------------------------------
# v2: parse-to-columns
# ---------------------------------------------------------------------------


def _assert_leaves_exact(a, b):
    """Full TensorSpec field equality (layout/tiling/memory_space
    included — the columns the v2 scan pre-parses in C++)."""
    from tpusim.ir import leaves_of

    for cname, comp_a in a.computations.items():
        for oa, ob in zip(comp_a.ops, b.computations[cname].ops):
            la, lb = leaves_of(oa.result), leaves_of(ob.result)
            assert len(la) == len(lb), oa.name
            for x, y in zip(la, lb):
                assert (
                    x.dtype, x.shape, x.layout, x.tiling, x.memory_space
                ) == (
                    y.dtype, y.shape, y.layout, y.tiling, y.memory_space
                ), oa.name
            assert oa.attrs == ob.attrs, oa.name
            assert oa.metadata == ob.metadata, oa.name


def test_v2_scan_available():
    import tpusim.trace.native as tn

    tn._load()
    assert tn._HAS_V2  # the rebuilt library exports hlo_scan2


def test_v2_parity_full_fields_on_corpus():
    """v2 (pre-parsed shapes + pre-split attrs) must equal the Python
    reference on every committed trace module, field for field."""
    import json

    corpus = [(FIXTURES / "tiny_mlp.hlo").read_text()]
    silicon = REPO / "reports" / "silicon"
    manifest = json.loads((silicon / "manifest.json").read_text())
    for e in manifest["workloads"]:
        for p in (silicon / e["trace"] / "modules").glob("*.hlo"):
            corpus.append(p.read_text())
    for tdir in sorted((FIXTURES / "traces").iterdir()):
        for p in (tdir / "modules").glob("*.hlo"):
            corpus.append(p.read_text())
    for text in corpus:
        m_py = parse_hlo_module(text, "x")
        m_v2 = parse_hlo_module_native(text, "x")
        _assert_same_module(m_py, m_v2)
        _assert_leaves_exact(m_py, m_v2)


def test_v2_shape_edge_cases_match_reference():
    """Fast-path and fallback ('!'-prefixed raw) shapes both land on
    the reference parser's exact TensorSpec — including the shapes the
    C++ mirror deliberately refuses (comments, odd layouts)."""
    tmpl = (
        "HloModule m\n\nENTRY %e (p: f32[2]) -> f32[2] {\n"
        "  %p = SHAPE parameter(0)\n"
        "  ROOT %r = f32[2]{0} add(%p, %p)\n}\n"
    )
    shapes = [
        "f32[2]", "f32[]", "pred[]", "u32[08]", "s32[<=128]",
        "bf16[256,512]{1,0:T(8,128)(2,1)}",
        "f32[8,128]{1,0:T(8,128)S(1)}",
        "f32[2,3]{1,0:T(2,1)(8,128)S(3)}",
        "(f32[2]{0}, u32[])",
        "((f32[2], s8[3,4]{1,0}), token[])",
        "(f32[2]{0:T(2)S(1)}, (u32[], pred[1]))",
        "f32[2]/*cmt*/", "c128[4]{0:T(4)}", "f8e4m3[16]{0}",
    ]
    for s in shapes:
        text = tmpl.replace("SHAPE", s)
        m_py = parse_hlo_module(text, "x")
        m_v2 = parse_hlo_module_native(text, "x")
        _assert_same_module(m_py, m_v2)
        _assert_leaves_exact(m_py, m_v2)


def test_v1_fallback_when_v2_absent(monkeypatch):
    """An older library without hlo_scan2 still parses through the v1
    record stream, byte-identically."""
    import tpusim.trace.native as tn

    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    want = parse_hlo_module_native(text, "x")
    monkeypatch.setattr(tn, "_HAS_V2", False)
    got = parse_hlo_module_native(text, "x")
    _assert_same_module(want, got)
    _assert_leaves_exact(want, got)


def test_native_robust_to_line_ending_variants():
    """CRLF, trailing whitespace, and %-less headers must parse the same
    as the Python reference (a trace dir copied through Windows must not
    silently produce an empty module)."""
    text = (FIXTURES / "tiny_mlp.hlo").read_text()
    variants = {
        "crlf": text.replace("\n", "\r\n"),
        "trailing_space": text.replace("{\n", "{ \n"),
        "no_percent_headers": text.replace("\n%region_add", "\nregion_add"),
    }
    for label, variant in variants.items():
        m_py = parse_hlo_module(variant, "v")
        m_nat = parse_hlo_module_native(variant, "v")
        assert set(m_py.computations) == set(m_nat.computations), label
        assert len(m_py.computations) == 3, label
        _assert_same_module(m_py, m_nat)
