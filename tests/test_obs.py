"""Tests for the tpusim.obs observability layer: cycle-window sampler
math, span nesting/monotonicity, export schema round-trips, and the
driver-level contract that the DISABLED path changes nothing.

Reference slot: the AerialVision interval logs + per-kernel stat lines
the reference scrapes (``src/gpgpu-sim/visualizer.cc``,
``util/job_launching/get_stats.py``), plus the simulation-rate
self-reporting of ``gpgpusim_entrypoint.cc:262-268``.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from tpusim.obs import (
    COUNTER_TRACKS,
    CycleWindowSampler,
    Instrumentation,
    counter_track_events,
    prometheus_text,
    read_samples_jsonl,
    validate_sample_rows,
    window_rows,
    write_samples_jsonl,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "traces"
SCHEMA = json.loads((REPO / "ci" / "obs_schema.json").read_text())


# -- sampler window math -----------------------------------------------------

def test_sampler_splits_event_across_windows_proportionally():
    s = CycleWindowSampler(window_cycles=100.0)
    s.add("mxu", 50.0, 250.0, flops=1000.0, hbm_bytes=400.0)
    bins = s.bins()
    assert len(bins) == 3
    assert bins[0].busy["mxu"] == pytest.approx(50.0)
    assert bins[1].busy["mxu"] == pytest.approx(100.0)
    assert bins[2].busy["mxu"] == pytest.approx(50.0)
    # traffic splits with the same fractions, totals preserved
    assert bins[0].flops == pytest.approx(250.0)
    assert bins[1].flops == pytest.approx(500.0)
    assert s.total("flops") == pytest.approx(1000.0)
    assert s.total("hbm_bytes") == pytest.approx(400.0)


def test_sampler_partial_last_window():
    """An event ending mid-window leaves the tail window partially busy —
    its utilization reflects only the covered fraction."""
    s = CycleWindowSampler(window_cycles=100.0)
    s.add("vpu", 0.0, 130.0)
    bins = s.bins()
    assert len(bins) == 2
    assert bins[0].busy["vpu"] == pytest.approx(100.0)
    assert bins[1].busy["vpu"] == pytest.approx(30.0)
    # an event ending exactly on a boundary adds no phantom window
    s2 = CycleWindowSampler(window_cycles=100.0)
    s2.add("vpu", 0.0, 200.0)
    assert len(s2.bins()) == 2


def test_sampler_zero_cycle_ops_count_in_their_window():
    s = CycleWindowSampler(window_cycles=100.0)
    s.add("none", 150.0, 150.0, hbm_bytes=64.0)
    bins = s.bins()
    assert bins[1].op_count == pytest.approx(1.0)
    assert bins[1].busy.get("none", 0.0) == 0.0  # no phantom busy cycles
    assert bins[1].hbm_bytes == pytest.approx(64.0)


def test_pinned_window_is_honored_to_the_memory_cap():
    """--obs-window-cycles pins the window: pinned samplers get the high
    memory-safety cap, not auto mode's 4096, so a long run keeps the
    requested resolution (coarsenings would record any cap breach)."""
    s = CycleWindowSampler(window_cycles=10.0)
    assert s.pinned and s.max_windows == CycleWindowSampler.PINNED_MAX_WINDOWS
    s.add("mxu", 0.0, 100_000.0)  # 10k windows: >4096, under the cap
    assert s.window_cycles == 10.0 and s.coarsenings == 0
    assert s.num_windows == 10_000


def test_sampler_auto_coarsens_but_preserves_totals():
    s = CycleWindowSampler(max_windows=8)  # auto window, tiny cap
    w0 = s.window_cycles
    for i in range(100):
        s.add("mxu", i * w0, (i + 1) * w0, flops=10.0)
    assert s.coarsenings > 0
    assert s.num_windows <= 8
    assert s.total("flops") == pytest.approx(1000.0)
    assert s.total_busy("mxu") == pytest.approx(100.0 * w0)


def test_sampler_add_series_tiles_loop_bodies():
    body = CycleWindowSampler(window_cycles=10.0)
    body.add("mxu", 0.0, 10.0, flops=100.0)
    pod = CycleWindowSampler(window_cycles=10.0)
    pod.add_series(body, offset=20.0, repeats=3, period=10.0)
    assert pod.total("flops") == pytest.approx(300.0)
    assert pod.total_busy("mxu") == pytest.approx(30.0)
    assert pod.bins()[1].is_empty()          # nothing before the offset
    assert pod.bins()[2].busy["mxu"] == pytest.approx(10.0)


def test_sampler_add_series_clamps_to_true_body_length():
    """A loop body shorter than the sub-sampler window must not smear
    each trip past where it happened: a 50-cycle body x 10 trips spans
    [0, 500), never out to the 1024-cycle window quantum (which placed
    activity after the end of the program)."""
    body = CycleWindowSampler()        # auto window: 1024 cycles
    body.add("mxu", 0.0, 50.0, flops=100.0)
    pod = CycleWindowSampler(window_cycles=100.0)
    pod.add_series(body, offset=0.0, repeats=10, period=50.0, length=50.0)
    bins = pod.bins()
    assert len(bins) == 5                       # exactly the loop's span
    assert pod.total("flops") == pytest.approx(1000.0)
    assert pod.total_busy("mxu") == pytest.approx(500.0)
    # uniform across the loop: each 100-cycle window holds 2 trips
    assert bins[0].busy["mxu"] == pytest.approx(100.0)
    assert bins[4].busy["mxu"] == pytest.approx(100.0)


def test_sampler_add_series_smears_past_tile_budget(monkeypatch):
    monkeypatch.setattr(CycleWindowSampler, "_TILE_BUDGET", 10)
    body = CycleWindowSampler(window_cycles=10.0)
    body.add("vpu", 0.0, 10.0, flops=7.0)
    pod = CycleWindowSampler(window_cycles=1000.0)
    pod.add_series(body, offset=0.0, repeats=1000, period=10.0)
    # totals survive the smear exactly
    assert pod.total("flops") == pytest.approx(7000.0)
    assert pod.total_busy("vpu") == pytest.approx(10000.0)


# -- spans -------------------------------------------------------------------

def test_span_nesting_and_timing_monotonicity():
    obs = Instrumentation(sample=False)
    with obs.span("outer"):
        with obs.span("inner"):
            time.sleep(0.002)
        with obs.span("inner"):
            time.sleep(0.002)
        obs.add_time("manual", 0.001, count=3)
    outer = obs.spans["outer"]
    inner = obs.spans["outer/inner"]
    manual = obs.spans["outer/manual"]
    assert inner.count == 2 and inner.seconds >= 0.004
    assert manual.count == 3 and manual.seconds == pytest.approx(0.001)
    # a parent's wall covers its REAL children (add_time attributes
    # claimed seconds that need not be backed by the parent's wall, so
    # it joins child_seconds but not this bound — asserting it did made
    # the test flake whenever span overhead dipped below the claim)
    assert outer.seconds >= inner.seconds
    assert outer.child_seconds == pytest.approx(
        inner.seconds + manual.seconds
    )
    assert outer.self_seconds <= outer.seconds
    assert outer.peak_rss_kb > 0
    # tree order: parent immediately precedes its children
    paths = [s.path for s in obs.span_table()]
    assert paths[0] == "outer"
    assert set(paths[1:]) == {"outer/inner", "outer/manual"}


def test_profile_lines_phase_coverage():
    obs = Instrumentation(sample=False)
    with obs.span("a"):
        time.sleep(0.002)
    with obs.span("b"):
        time.sleep(0.002)
    lines = obs.profile_lines(total_seconds=0.004)
    assert any("(phases cover)" in l for l in lines)
    # depth-0 spans sum to >= the measured work
    top = sum(s.seconds for s in obs.span_table() if s.depth == 0)
    assert top >= 0.004


def test_null_hub_is_inert():
    from tpusim.obs import NULL_OBS

    with NULL_OBS.span("x"):
        NULL_OBS.counter_add("c")
        NULL_OBS.add_time("y", 1.0)
    assert not NULL_OBS.enabled
    assert not hasattr(NULL_OBS, "spans")


# -- export schema round-trip ------------------------------------------------

def _mini_rows():
    from tpusim.timing.config import load_config

    arch = load_config(arch="v5e", tuned=False).arch
    s = CycleWindowSampler(window_cycles=1000.0)
    s.add("mxu", 0.0, 800.0, flops=1e6, mxu_flops=1e6, hbm_bytes=1e5)
    s.add("ici", 500.0, 2100.0, ici_bytes=3e5)
    s.add("dma", 1000.0, 1500.0, hbm_bytes=2e5)
    return arch, s, window_rows(s, arch, n_devices=1)


def test_counter_rows_round_trip_schema(tmp_path):
    arch, s, rows = _mini_rows()
    header_meta = {
        "arch": arch.name, "window_cycles": s.window_cycles,
        "num_devices": 1, "replayed_devices": 1,
        "clock_hz": arch.clock_hz, "config_name": arch.name,
    }
    p = tmp_path / "samples.jsonl"
    write_samples_jsonl(rows, p, header_meta)
    header, rows2 = read_samples_jsonl(p)
    validate_sample_rows(header, rows2, SCHEMA)  # must not raise
    assert rows2 == json.loads(json.dumps(rows))  # float-stable
    # utilization derives from busy cycles; ici occupancy spans windows
    assert rows2[0]["mxu_util"] == pytest.approx(0.8)
    assert rows2[0]["ici_occupancy"] == pytest.approx(0.5)
    assert rows2[1]["ici_occupancy"] == pytest.approx(1.0)
    assert rows2[0]["watts"] > 0

    # a row violating the schema is rejected
    bad = [dict(rows2[0])]
    del bad[0]["watts"]
    with pytest.raises(ValueError, match="watts"):
        validate_sample_rows(header, bad, SCHEMA)


def test_counter_track_events_cover_required_tracks():
    arch, _, rows = _mini_rows()
    events = counter_track_events(rows, arch.clock_hz)
    names = {e["name"] for e in events}
    assert set(SCHEMA["counter_tracks_required"]) <= names
    assert all(e["ph"] == "C" for e in events)
    ts = [e["ts"] for e in events if e["name"] == "mxu_util"]
    assert ts == sorted(ts)


def test_prometheus_text_format():
    text = prometheus_text({"sim_cycle": 123.0, "weird key!": 1,
                            "skip": "strings"})
    lines = [l for l in text.splitlines() if l and not l.startswith("#")]
    assert "tpusim_sim_cycle 123" in lines
    assert any(l.startswith("tpusim_weird_key_ ") for l in lines)
    assert not any("skip" in l for l in lines)


def test_prometheus_text_hardened_for_service_use():
    """The serving daemon exports request-derived values, so the
    exposition document must survive hostile keys/labels: names
    sanitized (leading digits guarded), label values escaped, HELP/TYPE
    emitted, non-finite floats in the spellings scrapers accept."""
    text = prometheus_text(
        {"1starts_with_digit": 2.0, "ok": 1.5, "inf_v": float("inf"),
         "ninf_v": float("-inf"), "nan_v": float("nan"),
         "bool_skipped": True},
        labels={"trace": 'evil"name\\with\nnewline', "bad key!": "v"},
        help_text={"ok": "a help line\nwith newline"},
    )
    lines = text.splitlines()
    # names: prefix keeps most keys safe; a digit straight after an
    # empty prefix would still be guarded
    assert any(l.startswith("tpusim_1starts_with_digit{") for l in lines)
    bare = prometheus_text({"9lives": 1}, prefix="")
    assert bare.splitlines()[-1].startswith("_9lives ")
    # HELP/TYPE lines present, help newline escaped
    assert "# HELP tpusim_ok a help line\\nwith newline" in lines
    assert "# TYPE tpusim_ok gauge" in lines
    # label values escaped per the exposition format; label names
    # sanitized
    ok_line = next(l for l in lines if l.startswith("tpusim_ok{"))
    assert '\\"' in ok_line and "\\n" in ok_line and "\\\\" in ok_line
    assert "bad_key_=" in ok_line
    assert "\n" not in ok_line
    # non-finite spellings
    assert any(l.endswith(" +Inf") for l in lines)
    assert any(l.endswith(" -Inf") for l in lines)
    assert any(l.endswith(" NaN") for l in lines)
    # bools stay excluded
    assert "bool_skipped" not in text


def test_prometheus_collided_names_keep_one_sample():
    # two keys that sanitize onto the same metric name: exactly one
    # TYPE line and ONE sample survives — duplicate series with the
    # same labelset make the whole exposition document unscrapable
    text = prometheus_text({"a b": 1.0, "a!b": 2.0})
    lines = text.splitlines()
    assert lines.count("# TYPE tpusim_a_b gauge") == 1
    samples = [l for l in lines if l.startswith("tpusim_a_b ")]
    assert samples == ["tpusim_a_b 1"]  # first key in sorted order wins


# -- driver-level contract ---------------------------------------------------

@pytest.fixture(scope="module")
def fixture_trace():
    return FIXTURES / "llama_tiny_tp2dp2"


def test_disabled_path_adds_no_stats_keys(fixture_trace):
    from tpusim.sim.driver import simulate_trace

    report = simulate_trace(fixture_trace, arch="v5p", tuned=False)
    assert report.samples is None
    assert not [k for k in report.stats.values if k.startswith("obs_")]
    for k in report.kernels:
        assert k.result.samples is None


def test_enabled_path_samples_and_stats(fixture_trace, tmp_path):
    from tpusim.obs import validate_obs_dir, write_obs_dir
    from tpusim.sim.driver import simulate_trace

    obs = Instrumentation()
    report = simulate_trace(fixture_trace, arch="v5p", tuned=False, obs=obs)
    s = report.samples
    assert s is not None and s.num_windows >= 2
    # the sampler's busy cycles agree with the engine's unit totals
    # (windows only re-bucket, they don't invent work); the pod series
    # covers the whole replay
    assert s.end_cycle >= report.cycles
    tot = report.totals
    for unit in ("mxu", "vpu"):
        assert s.total_busy(unit) == pytest.approx(
            tot.unit_busy_cycles.get(unit, 0.0), rel=1e-6)
    assert s.total("mxu_flops") == pytest.approx(tot.mxu_flops, rel=1e-6)
    # pod hbm traffic = module traffic + host memcpy commands (which the
    # engine totals don't carry), so >= with a sane bound
    assert tot.hbm_bytes <= s.total("hbm_bytes") <= tot.hbm_bytes * 1.05
    # spans + counters rode into the stats report
    keys = report.stats.values
    assert "obs_span_simulate.engine_s" in keys
    assert "obs_samples.windows" in keys
    # full export set validates against the checked-in schema
    write_obs_dir(tmp_path, report, obs=obs)
    summary = validate_obs_dir(tmp_path, SCHEMA)
    assert summary["windows"] == s.num_windows
    assert set(SCHEMA["counter_tracks_required"]) <= set(
        summary["counter_tracks"]
    )


def test_obs_stats_keys_do_not_leak_into_golden_set(fixture_trace):
    """The golden stat gate compares exact key sets; obs keys are only
    present when obs is on, so a default run's key set must be identical
    with and without the obs import having happened."""
    from tpusim.sim.driver import simulate_trace

    r1 = simulate_trace(fixture_trace, arch="v5p", tuned=False)
    Instrumentation()  # constructing a hub must not install any global
    r2 = simulate_trace(fixture_trace, arch="v5p", tuned=False)
    assert set(r1.stats.values) == set(r2.stats.values)


def test_timeline_counter_merge(fixture_trace):
    """`timeline --counters` path: module-level engine run with sampling,
    counter events merged into the Chrome trace via extra_events."""
    from tpusim.sim.traceviz import timeline_to_chrome_trace
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace

    pod = load_trace(fixture_trace)
    mod = pod.modules[sorted(pod.modules)[0]]
    cfg = load_config(arch="v5p", tuned=False)
    obs = Instrumentation()
    res = Engine(cfg, record_timeline=True, obs=obs).run(mod)
    assert res.samples is not None and res.samples.num_windows >= 2
    rows = window_rows(res.samples, cfg.arch)
    trace = timeline_to_chrome_trace(
        res, cfg.arch, extra_events=counter_track_events(
            rows, cfg.arch.clock_hz
        ),
    )
    phs = {e["ph"] for e in trace["traceEvents"]}
    assert "C" in phs and "X" in phs
    counters = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
    assert set(COUNTER_TRACKS) <= counters


def test_profile_cli_phases_sum(fixture_trace, capsys):
    """``python -m tpusim profile`` prints the per-phase table with
    depth-0 phases covering >= 90% of the measured total, and the top
    costliest ops."""
    from tpusim.__main__ import main

    rc = main(["profile", str(fixture_trace), "--arch", "v5p", "--top", "5"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "phase" in out and "peak_rss_mb" in out
    assert "costliest ops" in out
    cover = [l for l in out.splitlines() if "(phases cover)" in l]
    assert cover, out
    pct = float(cover[0].split("%")[0].split()[-1])
    assert pct >= 90.0, f"phases cover only {pct}% of total:\n{out}"
