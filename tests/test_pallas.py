"""Pallas kernel workload + Mosaic custom-call costing.

The hand-written-kernel slot: the reference ships hand-tuned CUDA in its
benchmark suites; here the TPU-idiomatic equivalent is a Pallas kernel
(Mosaic custom-call on TPU, interpret mode elsewhere), and the cost model
prices the custom-call from the kernel's own ``cost_estimate``."""

from __future__ import annotations

import pytest

from tpusim.timing.config import SimConfig
from tpusim.timing.cost import _parse_cost_estimate
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module
from tpusim.ir import Unit


def test_parse_cost_estimate():
    bc = ('{"custom_call_config": {"cost_estimate": {"flops": 1024, '
          '"transcendentals": 16, "bytes_accessed": 4096}}}')
    assert _parse_cost_estimate(bc) == (1024.0, 16.0, 4096.0)
    assert _parse_cost_estimate("{}") is None
    assert _parse_cost_estimate("") is None


MOSAIC_HLO = """\
HloModule mosaic, is_scheduled=true

ENTRY %main (a: f32[1024,1024], b: f32[1024,1024]) -> f32[1024,1024] {
  %a = f32[1024,1024]{1,0} parameter(0)
  %b = f32[1024,1024]{1,0} parameter(1)
  ROOT %cc = f32[1024,1024]{1,0} custom-call(%a, %b), \
custom_call_target="tpu_custom_call", \
backend_config={"custom_call_config": {"cost_estimate": \
{"flops": 2147483648, "transcendentals": 0, "bytes_accessed": 4194304}}}
}
"""


def test_mosaic_custom_call_priced_from_cost_estimate():
    mod = parse_hlo_module(MOSAIC_HLO)
    cfg = SimConfig()
    res = Engine(cfg).run(mod)
    # flops flow into the MXU accounting
    assert res.mxu_flops == pytest.approx(2 ** 31)
    assert res.flops == pytest.approx(2 ** 31)
    # bytes_accessed supersedes the operand/result approximation (which
    # would be 3 x 4MB = 12.58MB; the kernel reports only 4MB, so a
    # matching result proves the estimate actually took precedence)
    assert res.hbm_bytes == pytest.approx(4194304)
    # compute time ~ flops / MXU rate (compute-bound for this shape)
    a = cfg.arch
    expect = 2 ** 31 / a.mxu_flops_per_cycle
    per_op = res.per_op_cycles["cc"]
    assert per_op == pytest.approx(expect + a.op_overhead_cycles, rel=0.05)
    assert res.unit_busy_cycles.get(Unit.MXU.value, 0) > 0


def test_mosaic_custom_call_without_estimate_falls_back():
    text = MOSAIC_HLO.replace(
        ', backend_config={"custom_call_config": {"cost_estimate": '
        '{"flops": 2147483648, "transcendentals": 0, '
        '"bytes_accessed": 4194304}}}',
        "",
    ).replace("\\\n", "")
    mod = parse_hlo_module(text)
    res = Engine(SimConfig()).run(mod)
    # no estimate: memory-roofline fallback (operands + result)
    assert res.mxu_flops == 0
    assert res.hbm_bytes == pytest.approx(3 * 1024 * 1024 * 4)
    assert res.cycles > 0


PALLAS_SCRIPT = r"""
import numpy as np, jax, jax.numpy as jnp
from tpusim.models import get_workload
from tpusim.tracer.capture import capture
from tpusim.timing.config import SimConfig
from tpusim.timing.engine import Engine

fn, (q, k, v) = get_workload("flash_attention_pallas").build(
    batch=1, seq=256, heads=2, head_dim=64)
out = jax.jit(fn)(q, k, v)

def dense(q, k, v):
    s = jnp.einsum("bqd,bkd->bqk", q, k) / (q.shape[-1] ** 0.5)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)

ref = dense(q, k, v)
assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

# interpret-mode capture still yields a simulatable module
cap = capture(fn, q, k, v, name="flash")
res = Engine(SimConfig()).run(cap.module)
assert res.cycles > 0
print("PALLAS_WL_OK")
"""


@pytest.mark.slow
def test_flash_attention_pallas_self_checks(cpu_mesh_runner):
    out = cpu_mesh_runner(PALLAS_SCRIPT, n_devices=1)
    assert "PALLAS_WL_OK" in out
