"""Robustness fuzz for the HLO text parser: mutated real-world HLO must
never crash the parser with anything other than a clean parse error —
the trace is untrusted input (the reference's parser asserts on corrupt
traces, ``trace_parser.cc:413-443``; ours must stay exception-typed).

Deterministic (seeded) so CI failures reproduce."""

from __future__ import annotations

import random
from pathlib import Path

import pytest

from tpusim.trace.hlo_text import parse_hlo_module

REPO = Path(__file__).resolve().parent.parent
FIXTURE = REPO / "tests" / "fixtures" / "tiny_mlp.hlo"

#: exception types a hostile trace is allowed to raise — anything else
#: (AttributeError, UnboundLocalError, RecursionError...) is a parser bug
_ALLOWED = (ValueError, KeyError, IndexError)


def _mutations(text: str, rng: random.Random, n: int):
    lines = text.splitlines()
    for _ in range(n):
        kind = rng.randrange(5)
        ls = list(lines)
        i = rng.randrange(len(ls))
        if kind == 0:      # drop a line
            del ls[i]
        elif kind == 1:    # duplicate a line
            ls.insert(i, ls[i])
        elif kind == 2:    # truncate a line
            ls[i] = ls[i][: rng.randrange(max(len(ls[i]), 1))]
        elif kind == 3:    # splice random bytes into a line
            j = rng.randrange(max(len(ls[i]), 1))
            junk = "".join(
                chr(rng.randrange(33, 127)) for _ in range(rng.randrange(8))
            )
            ls[i] = ls[i][:j] + junk + ls[i][j:]
        else:              # swap two lines
            j = rng.randrange(len(ls))
            ls[i], ls[j] = ls[j], ls[i]
        yield "\n".join(ls)


def test_parser_survives_mutated_hlo():
    base = FIXTURE.read_text()
    rng = random.Random(0xA11CE)
    parsed = rejected = 0
    for mutant in _mutations(base, rng, 400):
        try:
            parse_hlo_module(mutant, "fuzz")
            parsed += 1
        except _ALLOWED:
            rejected += 1
    # most single-line mutations still parse (the grammar is line-local);
    # the assertion of interest is simply that we got here without an
    # unexpected exception type
    assert parsed + rejected == 400
    assert parsed > 0


def test_parser_rejects_garbage_cleanly():
    for garbage in ("", "\x00\x01\x02", "HloModule", "ENTRY {", "a" * 4096):
        try:
            parse_hlo_module(garbage, "g")
        except _ALLOWED:
            pass  # clean rejection is fine


@pytest.mark.parametrize("depth", [50, 200])
def test_deeply_nested_tuple_shapes_do_not_blow_the_stack(depth):
    shape = "f32[2]"
    for _ in range(depth):
        shape = f"({shape})"
    text = (
        "HloModule deep, is_scheduled=true\n\n"
        f"ENTRY %main (p0: {shape}) -> f32[2] {{\n"
        f"  %p0 = {shape} parameter(0)\n"
        "  ROOT %r = f32[2] bitcast(%p0)\n"
        "}\n"
    )
    try:
        parse_hlo_module(text, "deep")
    except _ALLOWED:
        pass  # clean rejection allowed; RecursionError is not
