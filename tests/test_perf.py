"""tpusim.perf — result cache + worker pool contracts.

The layer's whole promise is "free speed": parallel and cached replays
must be bit-identical to the serial path (stats dict equality), the
cache must invalidate on exactly the things that change a price (config
overlays, model version, degraded-chip multipliers), a damaged disk
record must degrade to a recompute with a warning, and the sweep's
shared cache must price the healthy-kernel class exactly once.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from tpusim.perf.cache import (
    CachedEngine,
    ResultCache,
    config_fingerprint,
    module_fingerprint,
    result_from_doc,
    result_to_doc,
)
from tpusim.perf.pool import map_ordered, resolve_workers
from tpusim.timing.config import load_config, overlay
from tpusim.timing.engine import Engine
from tpusim.trace.format import load_trace

FIXTURES = Path(__file__).parent / "fixtures" / "traces"

#: host-dependent stats + the perf layer's own accounting (present only
#: when the feature is on — the documented determinism contract)
_VOLATILE = ("simulation_rate_kops", "silicon_slowdown")
_PERF_PREFIXES = ("cache_", "pool_")


def _stats(report) -> dict:
    return {
        k: v for k, v in json.loads(report.stats.to_json()).items()
        if k not in _VOLATILE and not k.startswith(_PERF_PREFIXES)
    }


def _count_engine_runs(monkeypatch):
    """Patch Engine.run to count actual pricing walks (cache hits return
    before reaching it)."""
    calls = {"n": 0}
    orig = Engine.run

    def counting(self, module):
        calls["n"] += 1
        return orig(self, module)

    monkeypatch.setattr(Engine, "run", counting)
    return calls


# ---------------------------------------------------------------------------
# pool
# ---------------------------------------------------------------------------


def _double(x):
    return x * 2


def _pid_of(x):
    return os.getpid()


def test_workers_one_short_circuits_pool():
    """workers<=1 must run in-process: no fork, no pickling (the fn here
    is a closure — unpicklable — and still works)."""
    seen = []
    out = map_ordered(lambda x: seen.append(x) or x + 1, [1, 2, 3],
                      workers=1)
    assert out == [2, 3, 4]
    assert seen == [1, 2, 3]
    pids = map_ordered(_pid_of, [0, 1], workers=1)
    assert set(pids) == {os.getpid()}


def test_pool_parallel_preserves_order_and_forks():
    out = map_ordered(_double, list(range(20)), workers=4)
    assert out == [x * 2 for x in range(20)]
    pids = map_ordered(_pid_of, list(range(8)), workers=4)
    assert os.getpid() not in pids  # work really left the parent


def test_nested_serial_map_preserves_outer_context():
    """A nested serial map (a sweep worker whose own fan-out degrades to
    serial) must not clobber the outer call's pool context."""
    from tpusim.perf.pool import pool_context

    def outer(x):
        ctx = pool_context()
        map_ordered(lambda y: y, [1, 2], workers=1, context="inner")
        assert pool_context() == ctx
        return ctx

    assert map_ordered(outer, [1, 2, 3], workers=1,
                       context="outer") == ["outer"] * 3


def test_task_exception_propagates_not_swallowed():
    """A task failure (OSError from a missing trace, say) must reach the
    caller as-is, not be misread as pool failure and re-run serially.

    Runs in a pristine subprocess: this suite has jax's thread pools
    loaded, and forking under them is exactly what real pool callers
    (the jax-free replay paths) never do — the flake is the harness's,
    not the pool's."""
    import subprocess
    import sys
    import textwrap

    from tpusim.envutil import REPO_ROOT, cpu_mesh_env

    code = textwrap.dedent("""
        import tpusim.perf.pool as P

        def boom(x):
            raise OSError(f"task {x} failed")

        def no_serial(fn, items, context):
            raise AssertionError("fell back to a serial re-run")

        P._serial = no_serial
        try:
            P.map_ordered(boom, [0, 1, 2, 3], workers=2)
        except OSError as e:
            assert "task" in str(e), e
        else:
            raise AssertionError("task OSError did not propagate")
        print("OK")
    """)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env=cpu_mesh_env(1), cwd=REPO_ROOT,
    )
    assert proc.returncode == 0 and "OK" in proc.stdout, (
        proc.stdout + proc.stderr
    )


def test_resolve_workers_env(monkeypatch):
    monkeypatch.delenv("TPUSIM_WORKERS", raising=False)
    assert resolve_workers(None) == 1
    assert resolve_workers(3) == 3
    monkeypatch.setenv("TPUSIM_WORKERS", "5")
    assert resolve_workers(None) == 5
    assert resolve_workers(2) == 2   # explicit beats env
    monkeypatch.setenv("TPUSIM_WORKERS", "garbage")
    assert resolve_workers(None) == 1


# ---------------------------------------------------------------------------
# cache keys + hit/invalidation
# ---------------------------------------------------------------------------


def test_cache_hit_and_config_invalidation():
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache = ResultCache()

    r1 = CachedEngine(cfg, result_cache=cache).run(mod)
    assert (cache.hits, cache.misses) == (0, 1)
    r2 = CachedEngine(cfg, result_cache=cache).run(mod)
    assert r2 is r1
    assert (cache.hits, cache.misses) == (1, 1)

    # a config overlay changes the composed-config fingerprint -> miss
    cfg2 = overlay(cfg, {"arch": {"hbm_efficiency": 0.5}})
    assert config_fingerprint(cfg2) != config_fingerprint(cfg)
    r3 = CachedEngine(cfg2, result_cache=cache).run(mod)
    assert cache.misses == 2
    assert r3.cycles != r1.cycles

    # degraded-chip multipliers are their own cache class
    r4 = CachedEngine(
        cfg, clock_scale=0.5, result_cache=cache
    ).run(mod)
    assert cache.misses == 3
    assert r4.cycles > r1.cycles


def test_custom_cost_model_bypasses_cache():
    """A caller-supplied cost model is outside the cache key, so such an
    engine must never share results with the default-model population."""
    from tpusim.timing.cost import CostModel

    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache = ResultCache()
    CachedEngine(cfg, result_cache=cache).run(mod)
    assert cache.misses == 1
    custom = CachedEngine(
        cfg, cost_model=CostModel(cfg.arch), result_cache=cache,
    )
    custom.run(mod)
    # neither a hit against the default population nor a poisoning put
    assert cache.hits == 0 and cache.misses == 1
    assert len(cache._mem) == 1


def test_cache_invalidates_on_model_version_bump(monkeypatch):
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache = ResultCache()
    key_now = cache.key_for(mod, cfg)
    monkeypatch.setattr(
        "tpusim.perf.cache.model_version", lambda: "bumped-model"
    )
    bumped = ResultCache()
    key_bumped = bumped.key_for(mod, cfg)
    assert key_now != key_bumped
    # parser/IR edits (outside MODEL_FILES) must invalidate too: the
    # text hash can't see a FREE_OPCODES or trip-count parsing fix
    monkeypatch.setattr(
        "tpusim.perf.cache.parser_version", lambda: "parser-a"
    )
    pa = ResultCache().key_for(mod, cfg)
    monkeypatch.setattr(
        "tpusim.perf.cache.parser_version", lambda: "parser-b"
    )
    pb = ResultCache().key_for(mod, cfg)
    assert pa != pb


def test_capture_platform_joins_cache_key():
    """Identical HLO text captured on cpu vs tpu prices differently (the
    cost model's capture-backend dtype normalization) — the key must
    separate them or a shared cache cross-serves wrong results."""
    pod_a = load_trace(FIXTURES / "matmul_512")
    pod_b = load_trace(FIXTURES / "matmul_512")
    mod_a = next(iter(pod_a.modules.values()))
    mod_b = next(iter(pod_b.modules.values()))
    mod_b.meta["platform"] = "tpu"
    cfg = load_config(arch="v5e", tuned=False)
    cache = ResultCache()
    assert mod_a.meta.get("platform") != mod_b.meta.get("platform")
    assert cache.key_for(mod_a, cfg) != cache.key_for(mod_b, cfg)


def test_module_fingerprint_stamped_by_load_trace():
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    assert mod.meta.get("content_hash")
    assert module_fingerprint(mod) == mod.meta["content_hash"]


def test_result_doc_round_trip_is_exact():
    pod = load_trace(FIXTURES / "llama_tiny_tp2dp2")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5p", tuned=False)
    res = Engine(cfg).run(mod)
    back = result_from_doc(json.loads(json.dumps(result_to_doc(res))))
    assert result_to_doc(back) == result_to_doc(res)
    assert back.cycles == res.cycles
    assert back.op_count == res.op_count
    assert isinstance(back.op_count, int)
    assert dict(back.per_op_cycles) == dict(res.per_op_cycles)


# ---------------------------------------------------------------------------
# disk tier
# ---------------------------------------------------------------------------


def test_disk_cache_warm_run_skips_engine(tmp_path, monkeypatch):
    from tpusim.sim.driver import simulate_trace

    cache_dir = tmp_path / "cache"
    cold = simulate_trace(
        FIXTURES / "llama_tiny_tp2dp2", arch="v5p", tuned=False,
        result_cache=cache_dir,
    )
    assert list(cache_dir.glob("*.json")), "disk tier wrote nothing"
    calls = _count_engine_runs(monkeypatch)
    warm = simulate_trace(
        FIXTURES / "llama_tiny_tp2dp2", arch="v5p", tuned=False,
        result_cache=cache_dir,
    )
    assert calls["n"] == 0, "warm-cache run still priced modules"
    assert _stats(warm) == _stats(cold)
    assert warm.stats.get("cache_hits") == 1
    assert warm.stats.get("cache_disk_hits") == 1


def test_corrupt_disk_entry_recomputes_with_warning(tmp_path):
    """tpusim.guard regression: a corrupt record warns EXACTLY ONCE —
    first detection quarantines the file off the lookup path, so the
    recompute's put heals it permanently instead of every later lookup
    warning again (pre-guard, a racing pre-scan + engine get produced
    two warnings per run, and a failed healing put warned forever)."""
    import warnings as _warnings

    from tpusim.guard.store import QUARANTINE_DIR

    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache_dir = tmp_path / "cache"

    c1 = ResultCache(disk_dir=cache_dir)
    r1 = CachedEngine(cfg, result_cache=c1).run(mod)
    entries = list(cache_dir.glob("*.json"))
    assert len(entries) == 1
    # truncate the record mid-document
    entries[0].write_text(entries[0].read_text()[: 40])

    c2 = ResultCache(disk_dir=cache_dir)
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        r2 = CachedEngine(cfg, result_cache=c2).run(mod)
        # a second lookup through a FRESH cache (no in-memory hit, no
        # local state) must find a healed record, not the corpse
        c2b = ResultCache(disk_dir=cache_dir)
        r2b = CachedEngine(cfg, result_cache=c2b).run(mod)
    corrupt_warnings = [
        w for w in caught if "corrupt result-cache" in str(w.message)
    ]
    assert len(corrupt_warnings) == 1, (
        f"expected exactly one corrupt-record warning, got "
        f"{[str(w.message) for w in corrupt_warnings]}"
    )
    assert c2.disk_errors == 1
    assert c2.misses == 1 and c2.hits == 0
    assert c2.quarantined == 1
    assert r2.cycles == r1.cycles  # recomputed, not garbage
    # the corpse moved into quarantine for post-mortems
    qdir = cache_dir / QUARANTINE_DIR
    assert qdir.is_dir() and len(list(qdir.iterdir())) == 1
    # the recompute healed the record: the fresh cache disk-hit it
    assert c2b.disk_hits == 1
    assert r2b.cycles == r1.cycles
    c3 = ResultCache(disk_dir=cache_dir)
    r3 = CachedEngine(cfg, result_cache=c3).run(mod)
    assert c3.disk_hits == 1
    assert r3.cycles == r1.cycles


def test_stale_format_version_is_silent_miss(tmp_path):
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache_dir = tmp_path / "cache"
    c1 = ResultCache(disk_dir=cache_dir)
    CachedEngine(cfg, result_cache=c1).run(mod)
    entry = next(cache_dir.glob("*.json"))
    doc = json.loads(entry.read_text())
    doc["format_version"] = 999
    entry.write_text(json.dumps(doc))
    c2 = ResultCache(disk_dir=cache_dir)
    CachedEngine(cfg, result_cache=c2).run(mod)  # no warning expected
    assert c2.disk_errors == 0
    assert c2.misses == 1


def test_lru_eviction_counts():
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cache = ResultCache(max_entries=1)
    cfg_a = load_config(arch="v5e", tuned=False)
    cfg_b = overlay(cfg_a, {"arch": {"hbm_efficiency": 0.5}})
    CachedEngine(cfg_a, result_cache=cache).run(mod)
    CachedEngine(cfg_b, result_cache=cache).run(mod)
    assert cache.evictions == 1
    # cfg_a was evicted: re-running it misses again
    CachedEngine(cfg_a, result_cache=cache).run(mod)
    assert cache.misses == 3


# ---------------------------------------------------------------------------
# serial vs parallel bit-identity (driver + sweeps)
# ---------------------------------------------------------------------------


def _two_module_trace(tmp_path: Path) -> Path:
    """A pod trace with two distinct modules so the driver's
    segment-parallel pricing path actually engages (>1 launch class)."""
    import shutil

    src = FIXTURES / "matmul_512"
    dst = tmp_path / "two_mod"
    (dst / "modules").mkdir(parents=True)
    hlo = (src / "modules" / "matmul_512.hlo").read_text()
    (dst / "modules" / "mm_a.hlo").write_text(hlo)
    (dst / "modules" / "mm_b.hlo").write_text(
        hlo.replace("f32[512,512]", "f32[256,512]", 1)
    )
    shutil.copy(src / "meta.json", dst / "meta.json")
    cmds = [
        {"kind": "kernel_launch", "module": "mm_a", "device": 0},
        {"kind": "kernel_launch", "module": "mm_b", "device": 0},
        {"kind": "kernel_launch", "module": "mm_a", "device": 0},
    ]
    (dst / "commandlist.jsonl").write_text(
        "\n".join(json.dumps(c) for c in cmds) + "\n"
    )
    return dst


def test_driver_serial_vs_parallel_bit_identity(tmp_path):
    from tpusim.sim.driver import simulate_trace

    trace = _two_module_trace(tmp_path)
    serial = simulate_trace(trace, arch="v5e", tuned=False)
    par = simulate_trace(trace, arch="v5e", tuned=False, workers=4)
    assert _stats(par) == _stats(serial)
    # the pool really engaged and said so
    assert par.stats.get("pool_workers") == 4
    assert par.stats.get("pool_parallel_segments") == 2
    assert serial.stats.get("pool_workers") is None  # off by default


def test_sweep_serial_parallel_cached_byte_identity():
    from tpusim.faults.sweep import single_link_sweep, trace_step_sweep
    from tpusim.ici.topology import torus_for
    from tpusim.timing.config import load_config as _lc

    topo = torus_for(8, "v5p")
    serial = trace_step_sweep(
        FIXTURES / "llama_tiny_tp2dp2", topo, arch="v5p",
        max_scenarios=6, tuned=False,
    )
    par = trace_step_sweep(
        FIXTURES / "llama_tiny_tp2dp2", topo, arch="v5p",
        max_scenarios=6, tuned=False, workers=4,
    )
    assert json.dumps(serial.to_doc()) == json.dumps(par.to_doc())

    cfg = _lc(arch="v5p", tuned=False)
    a_serial = single_link_sweep(topo, cfg.arch.ici)
    a_par = single_link_sweep(topo, cfg.arch.ici, workers=4)
    assert json.dumps(a_serial.to_doc()) == json.dumps(a_par.to_doc())


def test_sweep_prices_healthy_class_exactly_once(monkeypatch):
    """The double-pricing fix: a collective-free trace swept over N
    dead-link scenarios runs the engine ONCE (baseline), not N+1 times
    — link faults cannot change a collective-free module's price."""
    from tpusim.faults.sweep import trace_step_sweep
    from tpusim.ici.topology import torus_for

    calls = _count_engine_runs(monkeypatch)
    result = trace_step_sweep(
        FIXTURES / "matmul_512", torus_for(8, "v5p"), arch="v5p",
        max_scenarios=8, tuned=False,
    )
    assert len(result.rows) == 8
    assert calls["n"] == 1, (
        f"healthy-kernel class priced {calls['n']}x across the sweep "
        f"(expected once)"
    )
    # and the physics agrees: no collective, no inflation
    assert all(r.inflation == 1.0 for r in result.rows)


def test_healthy_run_adds_no_perf_keys():
    """No cache, no workers -> the report is key-identical to PR 3."""
    from tpusim.sim.driver import simulate_trace

    report = simulate_trace(
        FIXTURES / "llama_tiny_tp2dp2", arch="v5p", tuned=False,
    )
    leaked = [
        k for k in report.stats.values
        if k.startswith(("cache_", "pool_"))
    ]
    assert leaked == []


def test_perf_namespaces_registered():
    from tpusim.analysis.statskeys import (
        DOCUMENTED_UPDATE_PREFIXES, STATS_NAMESPACES,
    )

    assert "cache_" in STATS_NAMESPACES
    assert "pool_" in STATS_NAMESPACES
    assert "cache_" in DOCUMENTED_UPDATE_PREFIXES
    assert "pool_" in DOCUMENTED_UPDATE_PREFIXES


def test_pool_sigterm_drains_in_flight_work(tmp_path):
    """SIGTERM mid-map drains the in-flight tasks and reaps the pool's
    children before the signal takes effect: every task's side effect
    lands, the process still dies of SIGTERM, and no orphan worker
    lingers in the process group.

    Runs in a pristine subprocess (this suite has jax's thread pools
    loaded; forking under them is the harness's flake, not the pool's)
    started as a session leader so the orphan check can interrogate the
    whole group afterwards."""
    import signal
    import subprocess
    import sys
    import textwrap
    import time

    from tpusim.envutil import REPO_ROOT, cpu_mesh_env

    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()
    code = textwrap.dedent(f"""
        import pathlib, time
        from tpusim.perf.pool import map_ordered

        OUT = pathlib.Path({str(marker_dir)!r})

        def slow(i):
            time.sleep(0.4)
            (OUT / f"task{{i}}.done").write_text(str(i))
            return i

        print("MAPPING", flush=True)
        map_ordered(slow, [0, 1, 2, 3], workers=2)
        # unreachable when a SIGTERM arrived mid-map: the deferred
        # signal is re-delivered before results return to the caller
        (OUT / "after_map").write_text("reached")
    """)
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, text=True,
        env=cpu_mesh_env(1), cwd=REPO_ROOT,
        start_new_session=True,
    )
    try:
        assert proc.stdout.readline().strip() == "MAPPING"
        # let round 1 get in flight, then kill mid-map
        deadline = time.time() + 10.0
        while not list(marker_dir.glob("task*.done")):
            assert time.time() < deadline, "no task ever completed"
            time.sleep(0.02)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30.0)
    finally:
        if proc.poll() is None:
            proc.kill()
    # died OF the SIGTERM (default disposition, re-delivered post-drain)
    assert rc == -signal.SIGTERM, rc
    # ... but only after the whole map drained
    done = sorted(p.name for p in marker_dir.glob("task*.done"))
    assert done == ["task0.done", "task1.done", "task2.done", "task3.done"]
    assert not (marker_dir / "after_map").exists()
    # and no orphan pool worker survives in the process group
    deadline = time.time() + 5.0
    while time.time() < deadline:
        try:
            os.killpg(proc.pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        os.killpg(proc.pid, signal.SIGKILL)
        raise AssertionError("orphan pool workers outlived the parent")


def test_durable_disk_cache_fsyncs_before_replace(tmp_path, monkeypatch):
    """durable=True (the serve v2 worker fleet's L2 mode) must fsync the
    record AND its directory entry before the atomic publish — a worker
    killed mid-publish (or a host dying under the pool) can then never
    leave a short-read record for every later reader to warn about.
    The default mode must not pay the fsyncs."""
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)

    fsyncs: list[int] = []
    real_fsync = os.fsync
    monkeypatch.setattr(
        os, "fsync", lambda fd: (fsyncs.append(fd), real_fsync(fd))[1],
    )

    plain = ResultCache(disk_dir=tmp_path / "plain")
    CachedEngine(cfg, result_cache=plain).run(mod)
    assert fsyncs == [], "non-durable mode paid fsyncs"
    assert plain.durable is False

    durable = ResultCache(disk_dir=tmp_path / "durable", durable=True)
    r1 = CachedEngine(cfg, result_cache=durable).run(mod)
    # one for the record file, one for the directory entry
    assert len(fsyncs) == 2
    # and the durable record round-trips exactly
    c2 = ResultCache(disk_dir=tmp_path / "durable", durable=True)
    r2 = CachedEngine(cfg, result_cache=c2).run(mod)
    assert c2.disk_hits == 1
    assert r2.cycles == r1.cycles


def test_enospc_disables_disk_writes_with_one_warning(tmp_path, monkeypatch):
    """ENOSPC/EIO graceful degradation: a staging write failing with a
    medium-level errno warns ONCE, disables further disk writes for the
    instance, and every request still serves from the computed result —
    never a crash, never a warning per request."""
    import errno
    import warnings as _warnings

    import tpusim.perf.cache as C

    def boom(tmp, text, durable):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(C, "_stage_write", boom)
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache = ResultCache(disk_dir=tmp_path / "store")

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        r1 = CachedEngine(cfg, result_cache=cache).run(mod)  # put fails
        r2 = CachedEngine(cfg, result_cache=cache).run(mod)  # mem hit
        # a second DISTINCT put must not re-warn (writes are disabled)
        cfg2 = overlay(cfg, {"arch": {"hbm_efficiency": 0.5}})
        r3 = CachedEngine(cfg2, result_cache=cache).run(mod)
    disabled = [
        w for w in caught if "disabling further" in str(w.message)
    ]
    assert len(disabled) == 1
    assert r2 is r1                      # the result still serves
    assert r3.cycles != r1.cycles        # and fresh work still prices
    assert cache._disk_write_disabled
    assert cache.disk_errors == 1
    assert not list((tmp_path / "store").glob("*.json"))
    # the drain-time flush is a no-op, not a warning storm
    with _warnings.catch_warnings(record=True) as caught2:
        _warnings.simplefilter("always")
        assert cache.flush() == 0
    assert not caught2


def test_transient_oserror_keeps_disk_writes_enabled(tmp_path, monkeypatch):
    """A non-medium OSError (EACCES and friends) keeps the pre-existing
    warn-and-continue behavior — only full/failing media disable."""
    import errno
    import warnings as _warnings

    import tpusim.perf.cache as C

    calls = {"n": 0}
    real = C._stage_write

    def flaky(tmp, text, durable):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(errno.EACCES, "Permission denied")
        return real(tmp, text, durable)

    monkeypatch.setattr(C, "_stage_write", flaky)
    pod = load_trace(FIXTURES / "matmul_512")
    mod = next(iter(pod.modules.values()))
    cfg = load_config(arch="v5e", tuned=False)
    cache = ResultCache(disk_dir=tmp_path / "store")
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        CachedEngine(cfg, result_cache=cache).run(mod)
    assert [w for w in caught if "continuing uncached" in str(w.message)]
    assert not cache._disk_write_disabled
    # the next put succeeds and publishes
    cfg2 = overlay(cfg, {"arch": {"hbm_efficiency": 0.5}})
    CachedEngine(cfg2, result_cache=cache).run(mod)
    assert list((tmp_path / "store").glob("*.json"))
