"""Tests for the correlation plot/report layer (`tpusim/harness/plots.py`
— the plot-correlation.py / correl-html parity slot)."""

import math

import pytest

from tpusim.harness.correlate import CorrelationPoint
from tpusim.harness.plots import correlation_stats, write_correlation_report


def _points():
    return [
        CorrelationPoint("mxu", 1.00e-3, 1.05e-3, 1e6, 1e12, 1e9),
        CorrelationPoint("hbm", 2.10e-4, 2.00e-4, 2e5, 1e9, 4e9),
        CorrelationPoint("mix", 5.30e-5, 5.00e-5, 5e4, 1e10, 1e8),
    ]


def test_correlation_stats():
    stats = correlation_stats(_points())
    assert stats["n"] == 3
    errs = [abs(100 * (p.sim_seconds - p.real_seconds) / p.real_seconds)
            for p in _points()]
    assert stats["mean_abs_error_pct"] == pytest.approx(sum(errs) / 3)
    assert stats["max_abs_error_pct"] == pytest.approx(max(errs))
    assert 0.99 < stats["log_correlation"] <= 1.0


def test_correlation_stats_empty_and_degenerate():
    assert correlation_stats([]) == {"n": 0}
    bad = [CorrelationPoint("z", 1e-3, 0.0, 1.0, 1.0, 1.0)]
    assert correlation_stats(bad) == {"n": 0}
    one = correlation_stats([_points()[0]])
    assert one["n"] == 1
    assert math.isfinite(one["mean_abs_error_pct"])


def test_write_report(tmp_path):
    path = write_correlation_report(_points(), tmp_path)
    assert path.name == "correl.html"
    text = path.read_text()
    assert "data:image/png;base64," in text
    assert "mxu" in text and "hbm" in text
    assert (tmp_path / "correl.png").stat().st_size > 1000
    # worst-error row first IN THE TABLE (hbm: |5.0%| > mxu: |4.76%|);
    # search after the <table> tag so base64 image bytes can't match
    table = text[text.index("<table"):]
    assert table.index("hbm") < table.index("mxu")
