"""Tests for the TPUWattch power model."""

from pathlib import Path

import pytest

from tpusim.power.model import POWER_PRESETS, PowerModel
from tpusim.timing.config import SimConfig, overlay
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


@pytest.fixture(scope="module")
def mlp_result():
    mod = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())
    return Engine(SimConfig()).run(mod)


def test_power_report_components(mlp_result):
    rep = PowerModel("v5p").report(mlp_result)
    assert rep.component_joules["mxu"] > 0
    assert rep.component_joules["hbm"] > 0
    assert rep.component_joules["ici"] > 0  # the fixture has an all-reduce
    assert rep.avg_watts > rep.static_watts + rep.idle_watts
    assert rep.total_joules > rep.dynamic_joules


def test_full_utilization_lands_near_tdp():
    """A chip at 100% MXU + full HBM streaming for 1s must land in the
    published TDP class (sanity anchor for the coefficients)."""
    from tpusim.timing.arch import arch_preset
    from tpusim.timing.engine import EngineResult

    for gen, lo, hi in (("v5e", 100, 300), ("v5p", 250, 700)):
        arch = arch_preset(gen)
        res = EngineResult(
            cycles=arch.clock_hz, seconds=1.0,
            flops=arch.peak_bf16_flops, mxu_flops=arch.peak_bf16_flops,
            hbm_bytes=arch.hbm_bandwidth,
        )
        watts = PowerModel(gen).report(res).avg_watts
        assert lo < watts < hi, (gen, watts)


def test_power_report_text(mlp_result):
    text = PowerModel("v5e").report(mlp_result).report_text()
    assert "TPUWattch power report" in text
    assert "avg power" in text


def test_driver_power_stats():
    from tpusim.ir import CommandKind, PodTrace, TraceCommand
    from tpusim.sim.driver import SimDriver

    pod = PodTrace()
    pod.modules["m"] = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())
    pod.device(0).commands.append(
        TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="m")
    )
    cfg = overlay(SimConfig(), {"power_enabled": True})
    report = SimDriver(cfg).run(pod)
    assert report.stats.get("power_avg_watts") > 0
    assert report.power is not None


def test_presets_exist():
    assert set(POWER_PRESETS) == {"v4", "v5e", "v5p", "v6e"}
