"""Tests for the TPUWattch power model."""

from pathlib import Path

import pytest

from tpusim.power.model import POWER_PRESETS, PowerModel
from tpusim.timing.config import SimConfig, overlay
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


@pytest.fixture(scope="module")
def mlp_result():
    mod = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())
    return Engine(SimConfig()).run(mod)


def test_power_report_components(mlp_result):
    rep = PowerModel("v5p").report(mlp_result)
    assert rep.component_joules["mxu"] > 0
    assert rep.component_joules["hbm"] > 0
    assert rep.component_joules["ici"] > 0  # the fixture has an all-reduce
    assert rep.avg_watts > rep.static_watts + rep.idle_watts
    assert rep.total_joules > rep.dynamic_joules


def test_full_utilization_lands_near_tdp():
    """A chip at 100% MXU + full HBM streaming for 1s must land in the
    published TDP class (sanity anchor for the coefficients)."""
    from tpusim.timing.arch import arch_preset
    from tpusim.timing.engine import EngineResult

    for gen, lo, hi in (("v5e", 100, 300), ("v5p", 250, 700)):
        arch = arch_preset(gen)
        res = EngineResult(
            cycles=arch.clock_hz, seconds=1.0,
            flops=arch.peak_bf16_flops, mxu_flops=arch.peak_bf16_flops,
            hbm_bytes=arch.hbm_bandwidth,
        )
        watts = PowerModel(gen).report(res).avg_watts
        assert lo < watts < hi, (gen, watts)


def test_power_report_text(mlp_result):
    text = PowerModel("v5e").report(mlp_result).report_text()
    assert "TPUWattch power report" in text
    assert "avg power" in text


def test_driver_power_stats():
    from tpusim.ir import CommandKind, PodTrace, TraceCommand
    from tpusim.sim.driver import SimDriver

    pod = PodTrace()
    pod.modules["m"] = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())
    pod.device(0).commands.append(
        TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="m")
    )
    cfg = overlay(SimConfig(), {"power_enabled": True})
    report = SimDriver(cfg).run(pod)
    assert report.stats.get("power_avg_watts") > 0
    assert report.power is not None


def test_presets_exist():
    assert set(POWER_PRESETS) == {"v4", "v5e", "v5p", "v6e"}


# -- DVFS + power-over-time (AccelWattch DVFS / mcpat_cycle sampling slots) --

def test_dvfs_scaling_quadratic():
    from tpusim.power.model import POWER_PRESETS, PowerModel

    base = POWER_PRESETS["v5p"]
    down = base.scaled(0.8)
    assert down.mxu_pj_per_flop == pytest.approx(
        base.mxu_pj_per_flop * 0.64
    )
    assert down.static_watts == pytest.approx(base.static_watts * 0.64)
    # HBM/SerDes rails are not on the core voltage plane
    assert down.hbm_pj_per_byte == base.hbm_pj_per_byte
    assert down.ici_pj_per_byte == base.ici_pj_per_byte
    # PowerModel applies the scale
    m = PowerModel("v5p", dvfs_scale=0.8)
    assert m.coeffs.mxu_pj_per_flop == pytest.approx(
        base.mxu_pj_per_flop * 0.64
    )


def test_dvfs_overlays_compose():
    from tpusim.power.model import dvfs_overlays
    from tpusim.timing.config import SimConfig, overlay

    cfg = SimConfig()
    scaled = overlay(cfg, *dvfs_overlays(cfg.arch.clock_ghz, 0.9))
    assert scaled.arch.clock_ghz == pytest.approx(cfg.arch.clock_ghz * 0.9)
    assert scaled.dvfs_scale == pytest.approx(0.9)


def test_power_timeline_tracks_utilization():
    from tpusim.power.model import POWER_PRESETS, power_timeline
    from tpusim.sim.interval import IntervalSample
    from tpusim.timing.config import ArchConfig

    arch = ArchConfig()
    c = POWER_PRESETS["v5p"]
    samples = [
        IntervalSample(0, 100, {"mxu": 100.0}),       # MXU pegged
        IntervalSample(100, 200, {"mxu": 50.0}),      # half busy
        IntervalSample(200, 300, {}),                 # idle
    ]
    tl = power_timeline(samples, arch, c)
    assert len(tl) == 3
    static = c.static_watts + c.idle_clock_watts
    peak_mxu = c.mxu_pj_per_flop * arch.peak_bf16_flops * 1e-12
    assert tl[0]["watts"] == pytest.approx(static + peak_mxu)
    assert tl[1]["watts"] == pytest.approx(static + 0.5 * peak_mxu)
    assert tl[2]["watts"] == pytest.approx(static)
    # full-power MXU on v5p should land in the hundreds of watts
    assert 100 < tl[0]["watts"] < 1500
