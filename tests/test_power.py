"""Tests for the TPUWattch power model."""

from pathlib import Path

import pytest

from tpusim.power.model import POWER_PRESETS, PowerModel
from tpusim.timing.config import SimConfig, overlay
from tpusim.timing.engine import Engine
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


@pytest.fixture(scope="module")
def mlp_result():
    mod = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())
    return Engine(SimConfig()).run(mod)


def test_power_report_components(mlp_result):
    rep = PowerModel("v5p").report(mlp_result)
    assert rep.component_joules["mxu"] > 0
    assert rep.component_joules["hbm"] > 0
    assert rep.component_joules["ici"] > 0  # the fixture has an all-reduce
    assert rep.avg_watts > rep.static_watts + rep.idle_watts
    assert rep.total_joules > rep.dynamic_joules


def test_full_utilization_lands_near_tdp():
    """A chip at 100% MXU + full HBM streaming for 1s must land in the
    published TDP class (sanity anchor for the coefficients)."""
    from tpusim.timing.arch import arch_preset
    from tpusim.timing.engine import EngineResult

    # upper bounds allow theoretical-100%-utilization draw above the TDP
    # class (the fitted anchors put 0.65 MXU utilization AT the TDP, so
    # an unachievable 100% legitimately projects past it)
    for gen, lo, hi in (("v5e", 100, 350), ("v5p", 250, 800)):
        arch = arch_preset(gen)
        res = EngineResult(
            cycles=arch.clock_hz, seconds=1.0,
            flops=arch.peak_bf16_flops, mxu_flops=arch.peak_bf16_flops,
            hbm_bytes=arch.hbm_bandwidth,
        )
        watts = PowerModel(gen).report(res).avg_watts
        assert lo < watts < hi, (gen, watts)


def test_power_report_text(mlp_result):
    text = PowerModel("v5e").report(mlp_result).report_text()
    assert "TPUWattch power report" in text
    assert "avg power" in text


def test_driver_power_stats():
    from tpusim.ir import CommandKind, PodTrace, TraceCommand
    from tpusim.sim.driver import SimDriver

    pod = PodTrace()
    pod.modules["m"] = parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())
    pod.device(0).commands.append(
        TraceCommand(kind=CommandKind.KERNEL_LAUNCH, module="m")
    )
    cfg = overlay(SimConfig(), {"power_enabled": True})
    report = SimDriver(cfg).run(pod)
    assert report.stats.get("power_avg_watts") > 0
    assert report.power is not None


def test_presets_exist():
    assert set(POWER_PRESETS) == {"v4", "v5e", "v5p", "v6e"}


# -- DVFS + power-over-time (AccelWattch DVFS / mcpat_cycle sampling slots) --

def test_dvfs_scaling_quadratic():
    from tpusim.power.model import POWER_PRESETS, PowerModel

    base = POWER_PRESETS["v5p"]
    down = base.scaled(0.8)
    assert down.mxu_pj_per_flop == pytest.approx(
        base.mxu_pj_per_flop * 0.64
    )
    assert down.static_watts == pytest.approx(base.static_watts * 0.64)
    # HBM/SerDes rails are not on the core voltage plane
    assert down.hbm_pj_per_byte == base.hbm_pj_per_byte
    assert down.ici_pj_per_byte == base.ici_pj_per_byte
    # PowerModel applies the scale (to the fitted coefficients, which
    # take precedence over the preset when committed)
    unscaled = PowerModel("v5p").coeffs
    m = PowerModel("v5p", dvfs_scale=0.8)
    assert m.coeffs.mxu_pj_per_flop == pytest.approx(
        unscaled.mxu_pj_per_flop * 0.64
    )


def test_dvfs_overlays_compose():
    from tpusim.power.model import dvfs_overlays
    from tpusim.timing.config import SimConfig, overlay

    cfg = SimConfig()
    scaled = overlay(cfg, *dvfs_overlays(cfg.arch.clock_ghz, 0.9))
    assert scaled.arch.clock_ghz == pytest.approx(cfg.arch.clock_ghz * 0.9)
    assert scaled.dvfs_scale == pytest.approx(0.9)


def test_power_timeline_tracks_utilization():
    from tpusim.power.model import POWER_PRESETS, power_timeline
    from tpusim.sim.interval import IntervalSample
    from tpusim.timing.config import ArchConfig

    arch = ArchConfig()
    c = POWER_PRESETS["v5p"]
    samples = [
        IntervalSample(0, 100, {"mxu": 100.0}),       # MXU pegged
        IntervalSample(100, 200, {"mxu": 50.0}),      # half busy
        IntervalSample(200, 300, {}),                 # idle
    ]
    tl = power_timeline(samples, arch, c)
    assert len(tl) == 3
    static = c.static_watts + c.idle_clock_watts
    peak_mxu = c.mxu_pj_per_flop * arch.peak_bf16_flops * 1e-12
    assert tl[0]["watts"] == pytest.approx(static + peak_mxu)
    assert tl[1]["watts"] == pytest.approx(static + 0.5 * peak_mxu)
    assert tl[2]["watts"] == pytest.approx(static)
    # full-power MXU on v5p should land in the hundreds of watts
    assert 100 < tl[0]["watts"] < 1500


# -- power validation: telemetry hook + coefficient fit (VERDICT r1 #5) -----

def test_fit_recovers_known_coefficients():
    """A well-determined synthetic sample set must recover the generating
    coefficients (the quadprog_solver.m property)."""
    from tpusim.power.telemetry import (
        PowerSample, RATE_KEYS, fit_power_coefficients,
    )
    from tpusim.power.model import POWER_PRESETS

    truth = POWER_PRESETS["v5e"]
    coefs = dict(zip(RATE_KEYS, (
        truth.mxu_pj_per_flop, truth.vpu_pj_per_flop, truth.sfu_pj_per_op,
        truth.hbm_pj_per_byte, truth.vmem_pj_per_byte, truth.ici_pj_per_byte,
    )))
    static = truth.static_watts + truth.idle_clock_watts
    scale = {  # plausible absolute event rates
        "mxu_flops": 4e14, "vpu_flops": 7e12, "transcendentals": 9e11,
        "hbm_bytes": 2.7e12, "vmem_bytes": 2.7e13, "ici_bytes": 5e11,
    }
    samples = [PowerSample("idle", static, {})]
    # one sample per rate key at full scale, plus two mixes
    for k in RATE_KEYS:
        rates = {k: scale[k]}
        w = static + coefs[k] * scale[k] * 1e-12
        samples.append(PowerSample(f"only_{k}", w, rates))
    mix = {k: 0.5 * scale[k] for k in RATE_KEYS}
    samples.append(PowerSample(
        "mix", static + sum(coefs[k] * mix[k] * 1e-12 for k in RATE_KEYS),
        mix,
    ))
    fit = fit_power_coefficients(samples, "v5e", prior_weight=1e-4)
    assert fit.mxu_pj_per_flop == pytest.approx(
        truth.mxu_pj_per_flop, rel=0.05
    )
    assert fit.hbm_pj_per_byte == pytest.approx(
        truth.hbm_pj_per_byte, rel=0.05
    )
    assert fit.static_watts + fit.idle_clock_watts == pytest.approx(
        static, rel=0.02
    )


@pytest.mark.parametrize("arch", ["v5e", "v5p"])
def test_fitted_coefficients_match_anchors_within_band(arch):
    """The COMMITTED fitted coefficients must reproduce every anchor
    operating point within the stated +/-16% band."""
    from tpusim.power.telemetry import (
        RATE_KEYS, _COEF_FIELDS, anchor_samples, load_fitted,
    )

    c = load_fitted(arch)
    assert c is not None, f"tpusim/power/fitted/{arch}.json not committed"
    for s in anchor_samples(arch):
        watts = sum(
            getattr(c, f) * s.rates.get(k, 0.0) * 1e-12
            for f, k in zip(_COEF_FIELDS, RATE_KEYS)
        ) + c.static_watts + c.idle_clock_watts
        err = abs(watts - s.watts) / s.watts
        assert err < 0.16, (arch, s.name, watts, s.watts)


def test_power_model_prefers_fitted_coefficients():
    from tpusim.power.model import PowerModel
    from tpusim.power.telemetry import load_fitted

    fitted = load_fitted("v5e")
    assert PowerModel("v5e").coeffs == fitted


def test_tune_power_writes_fitted_json(tmp_path):
    from tpusim.harness.tuner import tune_power
    import json as _json

    p = tune_power("v5e", out_dir=tmp_path)
    doc = _json.loads(p.read_text())
    assert doc["name"] == "v5e"
    assert doc["meta"]["source"] in ("anchors", "telemetry")
    assert doc["coefficients"]["static_watts"] > 0


def test_telemetry_hook_returns_none_or_positive():
    from tpusim.power.telemetry import read_power_watts

    w = read_power_watts()
    assert w is None or w > 0


def test_probe_power_sources_reports_every_source():
    """The probe is the committed evidence for anchor-based coefficients
    (VERDICT r3 #6): every source must appear with an ok flag and, when
    it failed, a reason."""
    from tpusim.power.telemetry import probe_power_sources

    probe = probe_power_sources()
    sources = {t["source"] for t in probe["tried"]}
    assert {"tpu_info", "hwmon"} <= sources
    for t in probe["tried"]:
        assert isinstance(t["ok"], bool)
        if not t["ok"]:
            assert t["detail"]
    if probe["watts"] is not None:
        assert probe["watts"] > 0


def test_tune_power_meta_records_probe(tmp_path):
    from tpusim.harness.tuner import tune_power
    import json as _json

    p = tune_power("v5e", out_dir=tmp_path)
    doc = _json.loads(p.read_text())
    probe = doc["meta"]["telemetry_probe"]
    assert isinstance(probe, list) and probe
    if doc["meta"]["source"] == "anchors":
        assert "note" in doc["meta"]      # why no measurement exists
    else:
        assert doc["meta"]["measured_idle_watts"] > 0


def test_hw_mode_uses_measured_duration():
    """AccelWattch HW-mode analogue: activity counts are static program
    properties, so power with measured device time is independent of the
    timing model — half the duration at the same activity doubles the
    dynamic power, and static/idle watts are duration-invariant."""
    from tpusim.power.model import PowerModel
    from tpusim.timing.engine import EngineResult

    res = EngineResult(
        seconds=1e-3, mxu_flops=1e12, flops=1.1e12,
        hbm_bytes=1e9, vmem_bytes=1e9,
    )
    pm = PowerModel("v5e")
    sim = pm.report(res)
    hw = pm.report(res, measured_seconds=0.5e-3)
    assert hw.seconds == 0.5e-3
    assert hw.dynamic_joules == sim.dynamic_joules
    sim_dyn_w = sim.dynamic_joules / sim.seconds
    hw_dyn_w = hw.dynamic_joules / hw.seconds
    assert hw_dyn_w == 2 * sim_dyn_w
