"""Energy-proportionality sanity for TPUWattch (VERDICT r4 #7).

No watts are measurable in this environment (probe evidence committed in
``reports/silicon/manifest.json .power_probe`` and the fitted-coeff
meta), so the coefficients cannot be validated absolutely — but
published figures still imply testable RATIOS and bands:

* HBM2e/3 access energy is ~3.9 pJ/bit ≈ 31 pJ/byte (public memory-
  vendor figures) — the fitted coefficient must land within 2x;
* a v5e board is a ~200W TDP class part — a compute-bound matmul chain
  replayed in HW-mode (real device durations) must draw average power
  within 2x of that band, and strictly more than a bandwidth-bound
  elementwise stream (compute-bound kernels run hotter);
* energy composition must track the workload: MXU joules dominate the
  matmul chain, HBM joules dominate the elementwise stream.

Reference slot: ``util/accelwattch/hw_power_validation_volta.csv``
methodology (measured-watts fit) — degraded honestly to ratio checks
until a telemetry-capable TPU-VM is available.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def reports():
    from tpusim.power.model import PowerModel
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace, select_module

    fd = REPO / "reports" / "silicon"
    entries = {
        e["name"]: e
        for e in json.loads((fd / "manifest.json").read_text())["workloads"]
    }
    eng = Engine(load_config(arch="v5e"))
    pm = PowerModel("v5e")
    out = {}
    for name in ("matmul_chain", "elementwise_stream"):
        e = entries[name]
        mod = select_module(load_trace(fd / e["trace"]), e.get("module"))
        res = eng.run(mod)
        # HW-mode: activity counts are exact; duration is the DEVICE
        # truth, so the ratio test cannot be polluted by timing error
        steps = float(e.get("n_steps", 1))
        out[name] = pm.report(
            res, measured_seconds=float(e["real_seconds"]) * steps,
        )
    return out


def test_hbm_energy_coefficient_within_published_band():
    from tpusim.power.model import PowerModel

    pj_per_byte = PowerModel("v5e").coeffs.hbm_pj_per_byte
    # HBM2e/3 ~3.9 pJ/bit = 31.2 pJ/byte; pin within 2x either way
    assert 31.2 / 2 <= pj_per_byte <= 31.2 * 2, pj_per_byte


def test_board_power_band(reports):
    watts = reports["matmul_chain"].avg_watts
    # v5e ~200W TDP class; within 2x either way
    assert 100.0 <= watts <= 400.0, watts


def test_compute_bound_runs_hotter_than_bandwidth_bound(reports):
    assert (
        reports["matmul_chain"].avg_watts
        > reports["elementwise_stream"].avg_watts
    ), (
        reports["matmul_chain"].avg_watts,
        reports["elementwise_stream"].avg_watts,
    )


def test_energy_composition_tracks_workload(reports):
    mm = reports["matmul_chain"].component_joules
    ew = reports["elementwise_stream"].component_joules
    assert mm.get("mxu", 0) > mm.get("hbm", 0), mm
    assert ew.get("hbm", 0) > ew.get("mxu", 0), ew
