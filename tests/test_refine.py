"""Tests for the replay-based parameter refiner (``harness/refine.py``)
— the loop-closing piece the reference's tuner lacks (its microbench
fit ships unvalidated until the CI correlation run; ours descends on
the replay objective directly, so the emitted overlay can only improve
on its seed)."""

from __future__ import annotations

import json
import math
from pathlib import Path

import pytest

from tpusim.harness.refine import (
    KNOBS,
    RefineResult,
    refine,
    refine_arch_on_fixtures,
)

REPO = Path(__file__).resolve().parent.parent
SILICON = REPO / "reports" / "silicon"


def test_refine_never_regresses_the_seed():
    """Strict-improvement acceptance: the final objective is <= start."""
    target = {"clock_ghz": 1.5, "hbm_efficiency": 0.8}

    def evaluate(vec):
        return sum(
            abs(vec[k] - t) * 100 for k, t in target.items()
        )

    base = {"clock_ghz": 1.7, "hbm_efficiency": 0.72}
    r = refine(
        base, evaluate,
        knobs={k: KNOBS[k] for k in base},
        max_sweeps=8,
    )
    assert r.final_err_pct <= r.start_err_pct
    # a smooth separable objective should be nearly solved
    assert r.final_err_pct < 0.25 * r.start_err_pct
    assert set(r.changed) <= set(base)


def test_refine_respects_bounds():
    """Values outside the physical bounds never ship, even if the
    objective prefers them."""

    def evaluate(vec):
        return vec["hbm_efficiency"] * 100  # wants 0 — below the bound

    r = refine(
        {"hbm_efficiency": 0.8}, evaluate,
        knobs={"hbm_efficiency": KNOBS["hbm_efficiency"]},
        max_sweeps=6,
    )
    assert r.values["hbm_efficiency"] >= KNOBS["hbm_efficiency"][0]


def test_int_knobs_stay_integral():
    def evaluate(vec):
        return abs(vec["mxu_fill_cycles"] - 100.5)

    r = refine(
        {"mxu_fill_cycles": 128}, evaluate,
        knobs={"mxu_fill_cycles": KNOBS["mxu_fill_cycles"]},
        max_sweeps=4,
    )
    assert r.values["mxu_fill_cycles"] == round(r.values["mxu_fill_cycles"])


def test_overlay_lines_roundtrip_through_flag_parser():
    from tpusim.timing.config import SimConfig, overlay, parse_flag_file

    r = RefineResult(
        start_err_pct=10.0, final_err_pct=2.0,
        values={"hbm_efficiency": 0.83, "mxu_fill_cycles": 121.0},
    )
    lines = r.overlay_lines("TPU v5 lite")
    tmp = Path("/tmp/tpusim_test_overlay.flags")
    tmp.write_text("\n".join(lines) + "\n")
    cfg = overlay(SimConfig(), parse_flag_file(tmp))
    assert cfg.arch.hbm_efficiency == pytest.approx(0.83)
    assert cfg.arch.mxu_fill_cycles == 121


@pytest.mark.skipif(
    not (SILICON / "manifest.json").exists(),
    reason="no committed silicon fixtures",
)
def test_refine_cli_writes_overlay(tmp_path):
    from tpusim.__main__ import main

    out = tmp_path / "refined.flags"
    rc = main([
        "refine", "--fixtures", str(SILICON), "--sweeps", "1",
        "--out", str(out),
    ])
    assert rc == 0
    text = out.read_text()
    assert text.startswith("# tpusim replay-refined fit")
    assert "-arch." in text


@pytest.mark.skipif(
    not (SILICON / "manifest.json").exists(),
    reason="no committed silicon fixtures",
)
def test_refine_on_committed_fixtures_improves_or_holds():
    """End-to-end on the real committed fixtures: a short descent from
    the raw preset must improve the replay objective (the committed
    overlay was produced exactly this way)."""
    manifest = json.loads((SILICON / "manifest.json").read_text())
    r = refine_arch_on_fixtures(
        manifest.get("arch", "v5e"), manifest["workloads"], SILICON,
        max_sweeps=1,
    )
    assert math.isfinite(r.start_err_pct)
    assert r.final_err_pct <= r.start_err_pct
    # raw preset starts near 10%; one sweep should already move it
    assert r.final_err_pct < r.start_err_pct or r.start_err_pct < 3.0
