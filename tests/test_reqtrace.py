"""Request-scoped tracing (tpusim.obs.reqtrace, L24): span-tree unit
math, tail-sampling policy, histogram exposition, and the live-daemon
contract both ways:

* tracing OFF (the default) is zero-overhead — no recorder allocated,
  no new stats keys, no response header, debug routes 404, and the
  volatile-stripped response bytes match a tracing-on daemon exactly;
* tracing ON grows only ``/metrics`` (real ``_bucket``/``_sum``/
  ``_count`` histogram series whose +Inf counts sum to
  ``serve_requests_total``), the ``/v1/debug/traces`` routes, and the
  ``X-Tpusim-Trace`` response header.

Also pins the prometheus TYPE contract: ``*_total`` keys are counters,
everything else a gauge, one TYPE line per name.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from tpusim.obs.export import prometheus_text, request_chrome_trace
from tpusim.obs.reqtrace import (
    BUCKET_BOUNDS_MS,
    TRACE_HEADER,
    AccessLog,
    FlightRecorder,
    LatencyHistogram,
    RequestTracer,
    histogram_exposition,
    mint_trace_id,
    valid_trace_id,
)
from tpusim.serve.client import ServeClient
from tpusim.serve.daemon import ServeDaemon

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "traces"

#: the serve test suite's canonicalization: host-dependent values and
#: the perf layer's own accounting never take part in byte equality
VOLATILE = {"simulation_rate_kops", "wall_seconds", "silicon_slowdown"}
PERF_PREFIXES = ("cache_", "pool_")

SIM_BODY = {
    "trace": "matmul_512", "arch": "v5p", "tuned": True, "validate": True,
}


def canonical(payload: bytes) -> str:
    doc = json.loads(payload)
    doc["stats"] = {
        k: v for k, v in doc["stats"].items()
        if k not in VOLATILE and not k.startswith(PERF_PREFIXES)
    }
    return json.dumps(doc, sort_keys=True)


# ---------------------------------------------------------------------------
# trace ids
# ---------------------------------------------------------------------------


def test_mint_trace_id_random_and_wellformed():
    a, b = mint_trace_id(), mint_trace_id()
    assert a != b
    assert valid_trace_id(a) and valid_trace_id(b)
    assert len(a) == 16


def test_mint_trace_id_honors_wellformed_inbound():
    assert mint_trace_id("deadbeef01234567") == "deadbeef01234567"
    # normalized, not rejected
    assert mint_trace_id("  DEADBEEF01234567 ") == "deadbeef01234567"


@pytest.mark.parametrize("bad", [
    "", "xyz", "short", "deadbeef0123456g", "a" * 33,
    "../../etc/passwd", "deadbeef 0123",
])
def test_mint_trace_id_rejects_malformed_inbound(bad):
    tok = mint_trace_id(bad)
    assert tok != bad.strip().lower()
    assert valid_trace_id(tok)
    assert not valid_trace_id(bad)


# ---------------------------------------------------------------------------
# span tree
# ---------------------------------------------------------------------------


def test_span_nesting_builds_slash_paths():
    rt = RequestTracer(acceptor_index=3)
    tr = rt.begin("simulate", None)
    with tr.span("dispatch"):
        with tr.span("price"):
            pass
        with tr.span("serialize"):
            pass
    doc = rt.finish(tr, 200)
    paths = [s["path"] for s in doc["spans"]]
    assert paths == ["dispatch", "dispatch/price", "dispatch/serialize"]
    assert doc["acceptor"] == 3
    # children start no earlier than the parent and fit inside it
    parent = next(s for s in doc["spans"] if s["path"] == "dispatch")
    for s in doc["spans"]:
        if s["path"].startswith("dispatch/"):
            assert s["start_ms"] >= parent["start_ms"]
            assert s["start_ms"] + s["dur_ms"] <= (
                parent["start_ms"] + parent["dur_ms"] + 1e-3
            )


def test_worker_spans_merge_under_dispatch_and_tolerate_garbage():
    rt = RequestTracer()
    tr = rt.begin("simulate", None)
    t0 = time.monotonic()
    tr.add_worker_spans(
        [("price", t0, 0.001), ("serialize", t0, 0.0005),
         "garbage", ("short",), None],
    )
    doc = rt.finish(tr, 200)
    assert [s["path"] for s in doc["spans"]] == [
        "dispatch/price", "dispatch/serialize",
    ]


def test_fd_dispatch_pulls_start_back_to_accept_instant():
    rt = RequestTracer()
    t_accept = time.monotonic()
    time.sleep(0.002)
    tr = rt.begin("simulate", None, start_s=time.monotonic())
    tr.note_fd_dispatch(t_accept, time.monotonic())
    doc = rt.finish(tr, 200)
    fdd = next(s for s in doc["spans"] if s["path"] == "fd_dispatch")
    assert fdd["start_ms"] == 0.0
    assert fdd["dur_ms"] >= 1.0  # the slept handoff leg is visible


def test_finish_is_idempotent():
    rt = RequestTracer()
    tr = rt.begin("metrics", None)
    doc1 = rt.finish(tr, 200)
    time.sleep(0.001)
    doc2 = rt.finish(tr, 500)  # late second call changes nothing
    assert doc2 is doc1
    assert doc1["status"] == 200
    # and the completion was observed exactly once
    assert rt.metrics_values()["reqtrace_route_ms__metrics__cnt"] == 1.0


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------


def test_histogram_bucketing_boundaries_and_overflow():
    h = LatencyHistogram()
    h.observe(0.25)      # on the first bound -> bucket 0 (le is <=)
    h.observe(0.26)      # just past -> bucket 1
    h.observe(1e9)       # overflow slot
    h.observe(-5.0)      # clamped to 0 -> bucket 0
    assert h.counts[0] == 2
    assert h.counts[1] == 1
    assert h.counts[-1] == 1
    assert h.count == 4
    assert h.sum_ms == pytest.approx(0.25 + 0.26 + 1e9)


def test_histogram_exposition_renders_cumulative_series():
    rt = RequestTracer()
    for _ in range(3):
        tr = rt.begin("simulate", None)
        rt.finish(tr, 200)
    values = rt.metrics_values()
    rest, lines = histogram_exposition(values)
    # histogram keys split out; counters flow through untouched
    assert not any(k.startswith("reqtrace_route_ms") for k in rest)
    assert "reqtrace_recorded_total" in rest
    assert "# TYPE tpusim_reqtrace_route_ms histogram" in lines
    bucket = [ln for ln in lines if ln.startswith(
        'tpusim_reqtrace_route_ms_bucket{route="simulate"')]
    assert len(bucket) == len(BUCKET_BOUNDS_MS) + 1  # + the +Inf line
    counts = [float(ln.split()[1]) for ln in bucket]
    assert counts == sorted(counts)  # cumulative is monotone
    assert counts[-1] == 3.0         # +Inf == observation count
    # every sample line splits into exactly two whitespace parts (the
    # scrape validators' invariant): labels contain no spaces
    for ln in lines:
        if ln.startswith("#"):
            continue
        parts = ln.split()
        assert len(parts) == 2, ln
        float(parts[1])


def test_histogram_exposition_accepts_fleet_merged_floats():
    # the fleet merge sums peer values into floats; exposition must
    # render them without complaint
    values = {
        "reqtrace_route_ms__simulate__b0": 3.0,
        "reqtrace_route_ms__simulate__b2": 1.0,
        "reqtrace_route_ms__simulate__sum": 12.5,
        "reqtrace_route_ms__simulate__cnt": 4.0,
        "serve_requests_total": 4.0,
    }
    rest, lines = histogram_exposition(values)
    assert rest == {"serve_requests_total": 4.0}
    inf = next(ln for ln in lines if 'le="+Inf"' in ln)
    assert float(inf.split()[1]) == 4.0
    le1 = next(ln for ln in lines if 'le="4"' in ln)
    assert float(le1.split()[1]) == 4.0  # 3 + 1 cumulative


# ---------------------------------------------------------------------------
# flight recorder tail-sampling
# ---------------------------------------------------------------------------


def _doc(tid, route="simulate", status=200, total_ms=1.0):
    return {"trace_id": tid, "route": route, "status": status,
            "total_ms": total_ms, "acceptor": None, "spans": []}


def test_recorder_keeps_n_slowest_per_route():
    rec = FlightRecorder(keep_slowest=3)
    for i in range(10):
        rec.record(_doc(f"{i:016x}", total_ms=float(i)))
    kept = rec.snapshot()
    assert [d["total_ms"] for d in kept] == [9.0, 8.0, 7.0]
    # a faster trace never evicts a slower one
    assert rec.record(_doc("f" * 16, total_ms=0.5)) is False
    assert rec.sampled_out_total == 7 + 1
    assert rec.live == 3


def test_recorder_keeps_every_error_in_its_own_ring():
    rec = FlightRecorder(keep_slowest=1, keep_errors=4)
    rec.record(_doc("a" * 16, total_ms=99.0))
    for i in range(6):
        rec.record(_doc(f"e{i:015x}", status=504, total_ms=0.01))
    kept = rec.snapshot()
    # the slow success survives a flood of fast errors, and the error
    # ring holds the most recent keep_errors of them
    assert kept[0]["total_ms"] == 99.0
    assert sum(1 for d in kept if d["status"] == 504) == 4
    assert rec.get("e5" + "0" * 13 + "5") is None  # malformed id
    assert rec.get("a" * 16)["total_ms"] == 99.0


def test_recorder_bounds_route_cardinality():
    rec = FlightRecorder(keep_slowest=2, max_routes=2)
    assert rec.record(_doc("1" * 16, route="a"))
    assert rec.record(_doc("2" * 16, route="b"))
    assert rec.record(_doc("3" * 16, route="c")) is False
    assert rec.sampled_out_total == 1


# ---------------------------------------------------------------------------
# prometheus TYPE contract
# ---------------------------------------------------------------------------


def test_prometheus_total_keys_are_counters():
    text = prometheus_text({
        "serve_requests_total": 5, "serve_uptime_s": 1.5, "ok": 1,
    })
    assert "# TYPE tpusim_serve_requests_total counter" in text
    assert "# TYPE tpusim_serve_uptime_s gauge" in text
    assert "# TYPE tpusim_ok gauge" in text


def test_prometheus_one_type_line_per_name():
    rt = RequestTracer()
    tr = rt.begin("simulate", None)
    rt.finish(tr, 200)
    rest, lines = histogram_exposition(rt.metrics_values())
    text = prometheus_text(rest) + "\n".join(lines) + "\n"
    seen: dict[str, str] = {}
    for ln in text.splitlines():
        if not ln.startswith("# TYPE "):
            continue
        _, _, name, mtype = ln.split()
        assert name not in seen, f"duplicate TYPE for {name}"
        seen[name] = mtype
    assert seen["tpusim_reqtrace_recorded_total"] == "counter"
    assert seen["tpusim_reqtrace_traces_live"] == "gauge"
    assert seen["tpusim_reqtrace_route_ms"] == "histogram"


# ---------------------------------------------------------------------------
# chrome export
# ---------------------------------------------------------------------------


def test_request_chrome_trace_shape():
    rt = RequestTracer(acceptor_index=1)
    tr = rt.begin("simulate", "deadbeefcafef00d")
    with tr.span("dispatch"):
        with tr.span("price"):
            pass
    doc = rt.finish(tr, 200)
    ct = request_chrome_trace(doc)
    assert ct["displayTimeUnit"] == "ms"
    events = ct["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    # the request envelope plus one slice per span
    assert len(xs) == 1 + len(doc["spans"])
    assert all(e["dur"] > 0 for e in xs)
    names = {e["name"] for e in events if e.get("ph") == "M"}
    assert "process_name" in names and "thread_name" in names
    json.dumps(ct)  # serializable as-is for Perfetto


# ---------------------------------------------------------------------------
# access log
# ---------------------------------------------------------------------------


def test_access_log_jsonl_fields_and_rotation(tmp_path):
    path = tmp_path / "access.jsonl"
    log = AccessLog(path, max_bytes=256)
    for i in range(16):
        log.write(route="simulate", status=200, latency_ms=1.25,
                  trace_id="ab" * 8, tier="warm", acceptor=0)
    log.close()
    log.close()  # idempotent
    assert path.exists()
    rotated = path.with_name(path.name + ".1")
    assert rotated.exists()  # 16 lines of ~100B crossed 256B repeatedly
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    recs += [json.loads(ln) for ln in rotated.read_text().splitlines()]
    assert recs
    r = recs[0]
    assert set(r) == {
        "ts_s", "trace_id", "route", "status", "latency_ms", "tier",
        "acceptor",
    }
    assert r["status"] == 200 and r["tier"] == "warm"
    assert log.lines_total == 16


# ---------------------------------------------------------------------------
# statskeys namespace
# ---------------------------------------------------------------------------


def test_statskeys_reqtrace_namespace_registered():
    from tpusim.analysis.statskeys import AUDIT_GLOBS, STATS_NAMESPACES

    assert "reqtrace_" in STATS_NAMESPACES
    owners = STATS_NAMESPACES["reqtrace_"]
    assert "tpusim/obs/" in owners
    assert "tpusim/serve/" in owners
    # the minting module is inside an audited glob, so the namespace is
    # actually enforced, not merely declared
    assert "tpusim/obs/*.py" in AUDIT_GLOBS


# ---------------------------------------------------------------------------
# live daemon: zero-overhead-off contract + tracing-on surfaces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def off_daemon():
    d = ServeDaemon(trace_root=str(FIXTURES), max_inflight=4).start()
    yield d
    d.drain_and_stop()


#: lazy response matrix from the tracing-off daemon, issued from INSIDE
#: a test (module-fixture setup would run before the conftest autouse
#: TPUSIM_TUNED_DIR pin, composing a differently-tuned config)
_OFF_PASS: dict = {}


@pytest.fixture
def off_pass(off_daemon):
    if not _OFF_PASS:
        c = ServeClient(off_daemon.url)
        bodies = []
        for _ in range(2):  # cold then warm
            resp, payload = c._raw("POST", "/v1/simulate", SIM_BODY)
            bodies.append((resp, payload))
        _OFF_PASS["sim"] = bodies
        _OFF_PASS["metrics"] = c.metrics_text()
        _OFF_PASS["debug_status"] = \
            c._raw("GET", "/v1/debug/traces")[0].status
        _OFF_PASS["stats_keys"] = set(off_daemon.metrics_values())
        _OFF_PASS["reqtrace_attr"] = off_daemon.reqtrace
        _OFF_PASS["access_log_attr"] = off_daemon.access_log
    return _OFF_PASS


@pytest.fixture(scope="module")
def on_daemon(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("reqtrace_on")
    d = ServeDaemon(
        trace_root=str(FIXTURES), max_inflight=4,
        trace_requests=True, access_log=str(tmp / "access.jsonl"),
    ).start()
    d._access_log_path = tmp / "access.jsonl"
    yield d
    d.drain_and_stop()


def test_tracing_off_is_zero_overhead(off_pass):
    assert off_pass["reqtrace_attr"] is None
    assert off_pass["access_log_attr"] is None
    assert not any(k.startswith("reqtrace_") for k in off_pass["stats_keys"])
    assert "reqtrace" not in off_pass["metrics"]
    assert off_pass["debug_status"] == 404
    for resp, _ in off_pass["sim"]:
        assert resp.getheader(TRACE_HEADER) is None


def test_tracing_on_is_byte_identical_and_traced(off_pass, on_daemon):
    c = ServeClient(on_daemon.url)
    for resp_off, payload_off in off_pass["sim"]:
        resp_on, payload_on = c._raw("POST", "/v1/simulate", SIM_BODY)
        tid = resp_on.getheader(TRACE_HEADER)
        assert tid and valid_trace_id(tid)
        # the body never changes — only the header grows
        assert canonical(payload_on) == canonical(payload_off)
        assert set(json.loads(payload_on)["stats"]) == \
            set(json.loads(payload_off)["stats"])
    assert c.last_trace_id == tid

    # the trace is retrievable and its top-level spans fit the total
    doc = c.trace_detail(tid)
    assert doc["trace_id"] == tid
    paths = [s["path"] for s in doc["spans"]]
    assert "dispatch" in paths and "dispatch/price" in paths
    top = sum(s["dur_ms"] for s in doc["spans"] if "/" not in s["path"])
    assert top <= doc["total_ms"] + 0.05
    assert (doc.get("meta") or {}).get("tier") in ("warm", "priced")

    # recent_traces lists it; chrome export loads
    assert any(s["trace_id"] == tid for s in c.recent_traces())
    assert "traceEvents" in c.trace_detail(tid, chrome=True)


def test_tracing_on_inbound_header_is_honored(on_daemon):
    import http.client

    conn = http.client.HTTPConnection(
        on_daemon.host, on_daemon.port, timeout=30,
    )
    try:
        conn.request(
            "POST", "/v1/simulate", body=json.dumps(SIM_BODY).encode(),
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: "deadbeef01234567"},
        )
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader(TRACE_HEADER) == "deadbeef01234567"
    finally:
        conn.close()


def test_tracing_on_metrics_histograms_sum_to_request_counter(on_daemon):
    c = ServeClient(on_daemon.url)
    c.healthz()
    text = c.metrics_text()
    assert "# TYPE tpusim_reqtrace_route_ms histogram" in text
    assert "# TYPE tpusim_serve_requests_total counter" in text
    inf = [ln for ln in text.splitlines()
           if ln.startswith("tpusim_reqtrace_route_ms_bucket")
           and 'le="+Inf"' in ln]
    bucket_total = sum(float(ln.split()[1]) for ln in inf)
    counter = next(
        float(ln.split()[1]) for ln in text.splitlines()
        if ln.startswith("tpusim_serve_requests_total ")
    )
    # the /metrics request observes itself before rendering, so the
    # equality is exact, not off-by-one
    assert bucket_total == counter
    for ln in text.splitlines():
        if ln.startswith("#") or not ln.strip():
            continue
        parts = ln.split()
        assert len(parts) == 2, ln
        float(parts[1])


def test_tracing_on_writes_access_log(on_daemon):
    # run after the traffic-generating tests: flush happens on close,
    # so read through the daemon's still-open handle state via a sync
    on_daemon.access_log._fh.flush()
    lines = on_daemon._access_log_path.read_text().splitlines()
    assert lines
    recs = [json.loads(ln) for ln in lines]
    assert any(r["route"] == "simulate" and r["trace_id"] for r in recs)
    assert all(
        {"ts_s", "route", "status", "latency_ms"} <= set(r) for r in recs
    )
