"""tpusim.serve end-to-end: the daemon over an ephemeral loopback port.

Covers the serving contract the subsystem promises:

* simulate / lint / sweep round-trips through the real HTTP stack;
* byte-equality of a served stats doc vs the same request through the
  ``simulate`` CLI (the determinism contract);
* warm repeat requests served from the shared result cache
  (``cache_hit`` true, stats byte-identical to the cold pass);
* error-level TLxxx diagnostics reject a request as 400 with the list;
* admission: 429 + Retry-After with the queue full, 504 past the
  deadline, 413 for oversized bodies (all deterministic — the daemon's
  ``work_hook`` injection point holds a request in-flight on an Event,
  so no test races a timer against real pricing);
* ``/metrics`` parses as Prometheus text;
* SIGTERM drain of a real ``python -m tpusim serve`` process: the
  in-flight request completes, the process exits 0.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from tpusim.serve.client import ServeClient, ServeError
from tpusim.serve.daemon import SERVE_FORMAT_VERSION, ServeDaemon

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "traces"

#: keys excluded from byte-equality comparisons: host-dependent values
#: plus the perf layer's own accounting (present exactly when a cache /
#: pool is mounted, which differs between served and plain-CLI runs)
VOLATILE = {"simulation_rate_kops", "wall_seconds", "silicon_slowdown"}
PERF_PREFIXES = ("cache_", "pool_")


def canonical(stats: dict) -> str:
    doc = {
        k: v for k, v in stats.items()
        if k not in VOLATILE and not k.startswith(PERF_PREFIXES)
    }
    return json.dumps(doc, indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# shared daemon (round-trip tests)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def daemon():
    d = ServeDaemon(trace_root=FIXTURES, max_inflight=4).start()
    yield d
    d.drain_and_stop()


@pytest.fixture(scope="module")
def client(daemon):
    return ServeClient(daemon.url)


def test_healthz_and_traces(client):
    health = client.healthz()
    assert health["status"] == "ok"
    assert health["format_version"] == SERVE_FORMAT_VERSION
    assert set(client.traces()) >= {"llama_tiny_tp2dp2", "matmul_512"}


def test_simulate_round_trip(client):
    r = client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    assert r.arch == "v5p"
    assert r.num_devices == 4
    assert r.sim_cycles > 0
    assert r.stats["kernel_launches"] > 0
    assert r.format_version == SERVE_FORMAT_VERSION
    assert r.model_version  # stamped so clients can reason about staleness


def test_warm_repeat_is_cache_hit_and_byte_identical(client):
    cold = client.simulate(trace="matmul_512", arch="v5e")
    warm = client.simulate(trace="matmul_512", arch="v5e")
    assert warm.cache_hit
    assert canonical(warm.stats) == canonical(cold.stats)


def test_served_stats_byte_equal_cli(client, tmp_path):
    """The same request through the one-shot CLI must produce the same
    stats doc byte for byte (minus host-dependent keys and the cache
    layer's own accounting)."""
    served = client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    out = tmp_path / "cli_stats.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpusim", "simulate",
         str(FIXTURES / "llama_tiny_tp2dp2"), "--arch", "v5p",
         "--json", str(out)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    cli_stats = json.loads(out.read_text())
    assert canonical(served.stats) == canonical(cli_stats)


def test_inline_hlo_simulate(client):
    text = (FIXTURES / "matmul_512" / "modules" / "matmul_512.hlo").read_text()
    r1 = client.simulate(hlo_text=text, arch="v5e")
    assert r1.sim_cycles > 0
    assert r1.trace.startswith("inline:")
    # the inline pod is cached under its content hash: the repeat
    # request parses nothing and prices nothing
    r2 = client.simulate(hlo_text=text, arch="v5e")
    assert r2.cache_hit
    assert canonical(r2.stats) == canonical(r1.stats)


def test_simulate_with_faults_stamps_fault_stats(client):
    r = client.simulate(
        trace="llama_tiny_tp2dp2", arch="v5p",
        faults={"faults": [{"kind": "chip_straggler", "chip": 0,
                            "clock_scale": 0.5}]},
    )
    assert any(k.startswith("faults_") for k in r.stats)
    healthy = client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    assert r.sim_cycles > healthy.sim_cycles


def test_partitioned_topology_is_422_not_500(client):
    """A fault schedule that disconnects the pod is the request's
    fault: the replay refusal (TopologyPartitionedError) must surface
    as 422, never the 500 boundary."""
    faults = {"faults": [
        {"kind": "link_down", "src": 0, "dst": 1},
        {"kind": "link_down", "src": 0, "dst": 2},
    ]}
    with pytest.raises(ServeError) as ei:
        client.simulate(
            trace="llama_tiny_tp2dp2", arch="v5p", faults=faults,
            overlays=[{"arch": {"ici": {"network_mode": "detailed"}}}],
        )
    assert ei.value.status == 422
    assert ei.value.code == "replay_failed"
    assert "partitioned" in ei.value.detail


def test_lint_round_trip(client):
    rep = client.lint(trace="llama_tiny_tp2dp2", arch="v5p")
    assert rep.errors == 0
    assert "error(s)" in rep.summary
    assert isinstance(rep.diagnostics.get("items", []), list)


def test_sweep_job_round_trip(client):
    job_id = client.sweep(arch="v5p", chips=8, payload_mb=1.0)
    assert job_id.startswith("job-")
    status = client.wait_job(job_id, timeout_s=60)
    assert status.status == "done"
    assert status.result["scenarios"] > 0
    assert status.result["worst_inflation"] >= 1.0


def test_sweep_trace_mode_honors_overlays(client):
    """A trace sweep must price under the request's composed config —
    overlays silently dropped would return wrong inflation numbers."""
    base = client.wait_job(
        client.sweep(trace="llama_tiny_tp2dp2", arch="v5p",
                     max_scenarios=2),
        timeout_s=120,
    )
    slow_ici = client.wait_job(
        client.sweep(trace="llama_tiny_tp2dp2", arch="v5p",
                     max_scenarios=2,
                     overlays=[{"arch": {"ici": {
                         "link_bandwidth": 9.0e9}}}]),
        timeout_s=120,
    )
    assert base.status == "done" and slow_ici.status == "done"
    # 10x slower links must inflate the healthy step-time baseline
    assert slow_ici.result["healthy"] > base.result["healthy"]


def test_unknown_job_404(client):
    with pytest.raises(ServeError) as ei:
        client.job("job-999999")
    assert ei.value.status == 404


def test_unknown_trace_404_and_no_path_walk(client):
    for name in ("nope", "../nope", "a/b"):
        with pytest.raises(ServeError) as ei:
            client.simulate(trace=name, arch="v5p")
        assert ei.value.status == 404, name


def test_bad_request_400(client):
    with pytest.raises(ServeError) as ei:
        client.simulate(trace="matmul_512", hlo_text="x", arch="v5e")
    assert ei.value.status == 400
    with pytest.raises(ServeError) as ei:
        client.simulate(hlo_text="definitely not hlo {", arch="v5e")
    assert ei.value.status == 400


def test_metrics_prometheus_parses(client):
    client.healthz()
    text = client.metrics_text()
    gauges = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.split()
        gauges[name] = float(value)
    assert gauges["tpusim_serve_requests_total"] > 0
    assert "tpusim_serve_admission_inflight" in gauges
    assert "tpusim_serve_cache_hits" in gauges
    assert "# TYPE tpusim_serve_requests_total counter" in text
    assert "# HELP tpusim_serve_requests_total" in text


# ---------------------------------------------------------------------------
# validation refusal (400 with the TLxxx list)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def broken_root(tmp_path_factory):
    """A trace root holding one trace whose commandlist references a
    module that does not exist — an error-level TL006."""
    root = tmp_path_factory.mktemp("serve_broken_root")
    td = root / "broken"
    (td / "modules").mkdir(parents=True)
    src = FIXTURES / "matmul_512" / "modules" / "matmul_512.hlo"
    (td / "modules" / "matmul_512.hlo").write_text(src.read_text())
    (td / "meta.json").write_text(
        json.dumps({"num_devices": 1, "format_version": 1})
    )
    (td / "commandlist.jsonl").write_text(
        json.dumps({"kind": "kernel_launch", "module": "no_such_module",
                    "device": 0}) + "\n"
    )
    return root


def test_error_diagnostics_reject_as_400(broken_root):
    with ServeDaemon(trace_root=broken_root) as d:
        c = ServeClient(d.url)
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="broken", arch="v5e")
        err = ei.value
        assert err.status == 400
        assert err.code == "validation_failed"
        assert "TL006" in err.doc.get("codes", [])
        assert any(
            item["code"] == "TL006" for item in err.diagnostics
        )
        # the lint endpoint REPORTS the same finding instead of failing
        rep = c.lint(trace="broken", arch="v5e")
        assert rep.errors >= 1
        assert "TL006" in rep.codes
        # validate=False skips the pre-flight; the replay itself then
        # refuses (422) rather than pricing garbage silently
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="broken", arch="v5e", validate=False)
        assert ei.value.status == 422


# ---------------------------------------------------------------------------
# strict lint (serve --strict-lint): 422 + diagnostics doc, verdict
# cached by content hash
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def warned_root(tmp_path_factory):
    """A trace whose lint verdict is warning-only (TL015 zero-byte
    standalone collective): the default pre-flight admits it, the
    strict-lint gate refuses it."""
    root = tmp_path_factory.mktemp("serve_warned_root")
    td = root / "warned"
    (td / "modules").mkdir(parents=True)
    src = FIXTURES / "matmul_512" / "modules" / "matmul_512.hlo"
    (td / "modules" / "matmul_512.hlo").write_text(src.read_text())
    (td / "meta.json").write_text(
        json.dumps({"num_devices": 4, "format_version": 1})
    )
    (td / "commandlist.jsonl").write_text("\n".join([
        json.dumps({"kind": "kernel_launch",
                    "module": "matmul_512", "device": 0}),
        json.dumps({"kind": "collective", "device": 0, "bytes": 0,
                    "collective": {"kind": "all-reduce",
                                   "replica_groups": [[0, 1], [2, 3]]}}),
    ]) + "\n")
    return root


def test_strict_lint_refuses_warnings_as_422(warned_root):
    with ServeDaemon(trace_root=warned_root, strict_lint=True) as d:
        c = ServeClient(d.url)
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="warned", arch="v5e")
        err = ei.value
        assert err.status == 422
        assert err.code == "strict_lint_refused"
        assert any(
            item["code"] == "TL015" for item in err.diagnostics
        )
        # the repeat is refused from the CACHED verdict: one verdict
        # entry, two refusals, zero re-lints
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="warned", arch="v5e")
        assert ei.value.status == 422
        stats = d.worker.stats_dict()
        assert stats["strict_lint_refused_total"] == 2
        assert stats["lint_verdicts_cached"] == 1
        # nothing priced
        assert d.worker.priced == 0


def test_strict_lint_verdicts_do_not_cross_serve_commandlists(
    tmp_path_factory,
):
    """Two traces sharing byte-identical modules but DIFFERENT
    commandlists must each get their own verdict: the trace passes
    judge both artifacts, so the cache key carries a commandlist
    fingerprint beside the module content hashes."""
    root = tmp_path_factory.mktemp("serve_twin_root")
    src = FIXTURES / "matmul_512" / "modules" / "matmul_512.hlo"
    for name, extra in (("clean", []), ("dirty", [
        json.dumps({"kind": "collective", "device": 0, "bytes": 0,
                    "collective": {"kind": "all-reduce",
                                   "replica_groups": [[0, 1], [2, 3]]}}),
    ])):
        td = root / name
        (td / "modules").mkdir(parents=True)
        (td / "modules" / "matmul_512.hlo").write_text(src.read_text())
        (td / "meta.json").write_text(
            json.dumps({"num_devices": 4, "format_version": 1})
        )
        (td / "commandlist.jsonl").write_text("\n".join(
            [json.dumps({"kind": "kernel_launch",
                         "module": "matmul_512", "device": 0})] + extra
        ) + "\n")
    # a third twin differing only in META (pod declaration 1 -> the
    # device-0 launch is still fine, but a dirty meta declaring fewer
    # devices than a command uses would TL007): meta must join the key
    td = root / "metatwin"
    (td / "modules").mkdir(parents=True)
    (td / "modules" / "matmul_512.hlo").write_text(src.read_text())
    (td / "meta.json").write_text(
        json.dumps({"num_devices": 1, "format_version": 1})
    )
    (td / "commandlist.jsonl").write_text(
        json.dumps({"kind": "kernel_launch",
                    "module": "matmul_512", "device": 0}) + "\n"
    )
    with ServeDaemon(trace_root=root, strict_lint=True) as d:
        c = ServeClient(d.url)
        # the clean twin admits first — its verdict must NOT admit the
        # dirty twin afterwards (nor vice versa on a fresh daemon)
        r = c.simulate(trace="clean", arch="v5e")
        assert r.stats["sim_cycle"] > 0
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="dirty", arch="v5e")
        assert ei.value.status == 422
        r = c.simulate(trace="metatwin", arch="v5e")
        assert r.stats["sim_cycle"] > 0
        # three distinct verdicts: modules alone must not key the cache
        assert d.worker.stats_dict()["lint_verdicts_cached"] == 3


def test_strict_lint_admits_clean_traces(warned_root):
    with ServeDaemon(trace_root=FIXTURES, strict_lint=True) as d:
        c = ServeClient(d.url)
        r = c.simulate(trace="matmul_512", arch="v5e")
        assert r.stats["sim_cycle"] > 0
        r2 = c.simulate(trace="matmul_512", arch="v5e")
        assert r2.cache_hit
        stats = d.worker.stats_dict()
        assert stats["strict_lint_refused_total"] == 0
        assert stats["lint_verdicts_cached"] == 1


def test_strict_lint_passes_tl5xx_findings_as_warnings(warned_root):
    """TL5xx perf-lint findings are advisory by contract: a verdict
    whose only warnings are TL5xx must ADMIT the trace (the findings
    still ride along in the cached doc), while a TL5xx finding next to
    a genuine warning changes nothing about the refusal."""
    def _with_perf_findings(registry):
        orig = registry.trace_diagnostics

        def fake(entry):
            diags = orig(entry)
            diags.emit("TL500", "critical path summary (synthetic)")
            diags.emit("TL501", "collective 90% exposed (synthetic)")
            return diags
        registry.trace_diagnostics = fake

    with ServeDaemon(trace_root=FIXTURES, strict_lint=True) as d:
        _with_perf_findings(d.worker.registry)
        c = ServeClient(d.url)
        r = c.simulate(trace="matmul_512", arch="v5e")
        assert r.stats["sim_cycle"] > 0
        stats = d.worker.stats_dict()
        assert stats["strict_lint_refused_total"] == 0
        assert stats["lint_verdicts_cached"] == 1

    with ServeDaemon(trace_root=warned_root, strict_lint=True) as d:
        _with_perf_findings(d.worker.registry)
        c = ServeClient(d.url)
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="warned", arch="v5e")
        assert ei.value.status == 422
        # the perf findings ride along in the refusal doc unchanged
        assert any(
            item["code"] == "TL501" for item in ei.value.diagnostics
        )


def test_strict_lint_off_keeps_warning_traces_servable(warned_root):
    """The default daemon admits warning-only traces — strict lint is
    an opt-in tightening, not a behavior change."""
    with ServeDaemon(trace_root=warned_root) as d:
        c = ServeClient(d.url)
        r = c.simulate(trace="warned", arch="v5e")
        assert r.stats["sim_cycle"] > 0
        assert "strict_lint_refused_total" not in d.worker.stats_dict()


def test_strict_lint_inline_hlo_verdict_cached_by_content(warned_root):
    bad_inline = (
        "HloModule bad, num_partitions=4\n\n"
        "ENTRY %main (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        "  ROOT %r = f32[8]{0} add(%p0, %ghost)\n"
        "}\n"
    )
    with ServeDaemon(trace_root=None, strict_lint=True) as d:
        c = ServeClient(d.url)
        for _ in range(2):
            with pytest.raises(ServeError) as ei:
                c.simulate(hlo_text=bad_inline, arch="v5e")
            assert ei.value.status == 422
            assert any(
                item["code"] == "TL001"
                for item in ei.value.diagnostics
            )
        stats = d.worker.stats_dict()
        assert stats["strict_lint_refused_total"] == 2
        assert stats["lint_verdicts_cached"] == 1


# ---------------------------------------------------------------------------
# admission: 429 / 504 / 413
# ---------------------------------------------------------------------------


def _blocked_daemon(**kw):
    """A daemon whose simulate requests block on an Event — admission
    behavior becomes deterministic (no timer races)."""
    release = threading.Event()
    entered = threading.Event()

    def hook(endpoint, body):
        if body.get("block"):
            entered.set()
            assert release.wait(30.0), "test never released the hook"

    d = ServeDaemon(trace_root=FIXTURES, work_hook=hook, **kw)
    return d, release, entered


def test_429_when_queue_full():
    d, release, entered = _blocked_daemon(max_inflight=1, queue_depth=0)
    with d:
        c = ServeClient(d.url)
        # hold one request in-flight (the body carries the block flag
        # only through the hook; it prices normally once released)
        blocker = threading.Thread(target=lambda: ServeClient(d.url)._request(
            "POST", "/v1/simulate",
            {"trace": "matmul_512", "arch": "v5e", "block": True},
        ), daemon=True)
        blocker.start()
        assert entered.wait(10.0)
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="matmul_512", arch="v5e")
        assert ei.value.status == 429
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s >= 1.0
        release.set()
        blocker.join(timeout=30.0)
        # with the slot free again the same request succeeds
        assert c.simulate(trace="matmul_512", arch="v5e").sim_cycles > 0


def test_504_when_deadline_expires_in_queue():
    d, release, entered = _blocked_daemon(max_inflight=1, queue_depth=4)
    with d:
        c = ServeClient(d.url)
        blocker = threading.Thread(target=lambda: ServeClient(d.url)._request(
            "POST", "/v1/simulate",
            {"trace": "matmul_512", "arch": "v5e", "block": True},
        ), daemon=True)
        blocker.start()
        assert entered.wait(10.0)
        t0 = time.monotonic()
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="matmul_512", arch="v5e", deadline_ms=300)
        waited = time.monotonic() - t0
        assert ei.value.status == 504
        assert waited >= 0.25  # it genuinely queued until the deadline
        release.set()
        blocker.join(timeout=30.0)


def test_413_for_oversized_body():
    with ServeDaemon(trace_root=FIXTURES, max_request_bytes=1024) as d:
        c = ServeClient(d.url)
        with pytest.raises(ServeError) as ei:
            c.simulate(hlo_text="x" * 4096, arch="v5e")
        assert ei.value.status == 413


def test_queued_waiter_is_not_starved_by_fresh_arrivals():
    """FIFO admission: while a request is queued, a freed slot goes to
    it, not to whichever newcomer happens to arrive next — a steady
    arrival stream must not ride a queued request to its 504."""
    from tpusim.serve.admission import AdmissionController

    adm = AdmissionController(max_inflight=1, queue_depth=4)
    order: list[str] = []
    first = adm.admit()
    waiter_ready = threading.Event()

    def waiter():
        waiter_ready.set()
        with adm.admit(deadline=time.monotonic() + 10.0):
            order.append("waiter")

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    waiter_ready.wait(5.0)
    deadline = time.monotonic() + 5.0
    while adm.stats_dict()["queued"] < 1:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    # newcomers while the waiter queues: they must line up BEHIND it
    results: list[str] = []

    def newcomer(tag):
        with adm.admit(deadline=time.monotonic() + 10.0):
            results.append(tag)
            order.append(tag)

    n1 = threading.Thread(target=newcomer, args=("n1",), daemon=True)
    n1.start()
    time.sleep(0.05)
    first.__exit__(None, None, None)  # free the slot
    t.join(timeout=10.0)
    n1.join(timeout=10.0)
    assert order[0] == "waiter", order  # the queued request went first


def test_job_queue_overload_429():
    d = ServeDaemon(trace_root=FIXTURES, job_queue_depth=1, job_workers=1)
    # NOT started: no job worker drains the queue, so the second submit
    # must bounce off the bounded table
    d.jobs.submit("sweep", {"arch": "v5p", "chips": 8})
    from tpusim.serve.admission import Overloaded

    with pytest.raises(Overloaded):
        d.jobs.submit("sweep", {"arch": "v5p", "chips": 8})


# ---------------------------------------------------------------------------
# SIGTERM drain (real process)
# ---------------------------------------------------------------------------


def test_sigterm_drains_real_daemon(tmp_path):
    """``python -m tpusim serve`` under SIGTERM: the in-flight request
    completes with 200, the process exits 0, and the listener is gone
    afterwards."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpusim", "serve", "--port", "0",
         "--trace-root", str(FIXTURES), "--drain-grace-s", "60"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO,
    )
    try:
        line = proc.stdout.readline()
        assert "listening on http://" in line, line
        url = line.split("listening on ", 1)[1].split()[0]
        c = ServeClient(url, timeout_s=120.0)

        result: dict = {}

        def slow_request():
            # cold llama: trace load + pricing keeps this in flight
            # long enough for the SIGTERM to land mid-request
            result["r"] = c.simulate(trace="llama_tiny_tp2dp2", arch="v5p")

        t = threading.Thread(target=slow_request, daemon=True)
        t.start()
        time.sleep(0.15)  # let the request reach the daemon
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=120.0)
        assert not t.is_alive(), "in-flight request never completed"
        assert result["r"].sim_cycles > 0  # drained, not dropped
        assert proc.wait(timeout=60.0) == 0  # the exit-0 contract
        out = proc.stdout.read()
        assert "drained" in out
        # the listener is really gone
        with pytest.raises(Exception):
            ServeClient(url, timeout_s=2.0).healthz()
    finally:
        if proc.poll() is None:
            proc.kill()


def test_in_process_drain_rejects_new_work():
    """From the first drain instant, new requests get 503 and /healthz
    reports draining — load balancers stop routing before the listener
    disappears."""
    d = ServeDaemon(trace_root=FIXTURES).start()
    try:
        c = ServeClient(d.url)
        assert c.simulate(trace="matmul_512", arch="v5e").sim_cycles > 0
        assert c.healthz()["status"] == "ok"
        d.admission.start_drain()
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="matmul_512", arch="v5e")
        assert ei.value.status == 503
        with pytest.raises(ServeError) as ei:
            c.healthz()
        assert ei.value.status == 503
        assert ei.value.doc.get("status") == "draining"
    finally:
        d.drain_and_stop()


# ---------------------------------------------------------------------------
# serve v2: supervised worker pool — chaos paths
# ---------------------------------------------------------------------------
#
# These tests exercise the supervision policies with the daemon's
# chaos hooks armed (``chaos_hooks=True``: ``_chaos_exit`` makes the
# worker ``os._exit`` the instant the request lands, ``_chaos_sleep_s``
# stalls it before pricing — a stand-in for a hung native call).  The
# invariant under test throughout: one bad request costs exactly one
# worker, never the service, and every surviving response stays
# byte-identical to the single-process path.


def _raw_post(daemon, path, body, timeout=60.0):
    """POST without the typed client: chaos bodies carry hook keys the
    client API (rightly) has no parameter for, and some assertions need
    the raw status + headers."""
    conn = http.client.HTTPConnection(daemon.host, daemon.port, timeout=timeout)
    try:
        conn.request(
            "POST", path, body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        payload = resp.read()
        return resp.status, dict(resp.getheaders()), json.loads(payload)
    finally:
        conn.close()


@pytest.fixture(scope="module")
def pool_daemon(tmp_path_factory):
    # Workers pin their environment AT FORK TIME.  This module-scoped
    # fixture is created before the function-scoped autouse
    # ``_isolate_tuned_overlays`` patch, so without pinning here the
    # fleet would fork with the repo's tuned overlays visible while the
    # in-process daemons compose lazily under the per-test isolation —
    # and "byte-identical" would fail on a config skew, not a pool bug.
    old = os.environ.get("TPUSIM_TUNED_DIR")
    os.environ["TPUSIM_TUNED_DIR"] = str(
        tmp_path_factory.mktemp("no_tuned_pool")
    )
    d = ServeDaemon(
        trace_root=FIXTURES, max_inflight=4, serve_workers=2,
        chaos_hooks=True,
    ).start()
    try:
        yield d
    finally:
        d.drain_and_stop()
        if old is None:
            os.environ.pop("TPUSIM_TUNED_DIR", None)
        else:
            os.environ["TPUSIM_TUNED_DIR"] = old


@pytest.fixture(scope="module")
def pool_client(pool_daemon):
    return ServeClient(pool_daemon.url)


def test_multi_worker_byte_identical_to_single_process(pool_client, client):
    """The byte-identity contract across 1..N workers: the supervised
    pool's stats docs equal the single-process daemon's for the same
    requests (the CI serve smoke extends this to the full golden
    matrix)."""
    for trace, arch in (("llama_tiny_tp2dp2", "v5p"), ("matmul_512", "v5e")):
        multi = pool_client.simulate(trace=trace, arch=arch)
        single = client.simulate(trace=trace, arch=arch)
        assert canonical(multi.stats) == canonical(single.stats)


def test_healthz_and_metrics_expose_worker_fleet(pool_client):
    health = pool_client.healthz()
    assert health["workers_configured"] == 2
    assert health["workers_alive"] >= 1
    docs = health["workers"]
    assert len(docs) == 2
    assert {d["index"] for d in docs} == {0, 1}
    for key in ("alive", "pid", "restarts", "kills", "crashes"):
        assert key in docs[0]
    prom = pool_client.metrics_text()
    for gauge in (
        "serve_workers_alive", "serve_worker_restarts_total",
        "serve_worker_kills_total", "serve_quarantine_size",
        "serve_shed_503_total",
    ):
        assert f"tpusim_{gauge} " in prom


def test_sigkilled_worker_mid_request_is_retried_byte_identical(
    pool_daemon, pool_client, client,
):
    """The headline chaos path: SIGKILL the worker while it holds a
    request.  The daemon survives, the request is retried on a fresh
    worker and answers 200 with stats byte-identical to the
    single-process baseline, and the supervisor records the restart."""
    sup = pool_daemon.supervisor
    baseline = client.simulate(trace="matmul_512", arch="v5e")
    restarts0 = sum(s.restarts for s in sup.slots)
    retried0 = sup.retried
    out = {}

    def go():
        out["resp"] = _raw_post(pool_daemon, "/v1/simulate", {
            "trace": "matmul_512", "arch": "v5e",
            "_chaos_sleep_s": 1.0,  # a window to land the SIGKILL in
        })

    t = threading.Thread(target=go, daemon=True)
    t.start()
    victim = None
    deadline = time.monotonic() + 5.0
    while victim is None and time.monotonic() < deadline:
        for s in sup.slots:
            if s.busy and s.pid is not None:
                victim = s.pid
                break
        time.sleep(0.01)
    assert victim is not None, "request never reached a worker"
    os.kill(victim, signal.SIGKILL)
    t.join(timeout=60.0)
    assert not t.is_alive(), "request never completed after the kill"
    status, _headers, doc = out["resp"]
    assert status == 200, doc
    assert canonical(doc["stats"]) == canonical(baseline.stats)
    assert sup.retried == retried0 + 1
    # the dead slot is respawned (poll: restart rides the monitor loop)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if (sum(s.restarts for s in sup.slots) >= restarts0 + 1
                and sup.alive_count() == 2):
            break
        time.sleep(0.02)
    assert sum(s.restarts for s in sup.slots) >= restarts0 + 1
    assert sup.alive_count() == 2


def test_poison_request_quarantined_after_retry_budget(
    pool_daemon, pool_client,
):
    """A request that kills EVERY worker it lands on burns its retry
    budget, then 422s with a diagnostic; the identical request is
    refused immediately (no further worker deaths) and the pool keeps
    serving clean traffic."""
    sup = pool_daemon.supervisor
    body = {"trace": "llama_tiny_tp2dp2", "arch": "v5p", "_chaos_exit": True}
    status, _headers, doc = _raw_post(pool_daemon, "/v1/simulate", body)
    assert status == 422, doc
    assert doc["error"] == "poison_request"
    assert doc["poison"]["worker_deaths"] == 2  # original + one retry
    assert doc["poison"]["content_hash"]
    # identical request again: quarantine answers, nobody dies
    crashes0 = sum(s.crashes for s in sup.slots)
    status2, _h2, doc2 = _raw_post(pool_daemon, "/v1/simulate", body)
    assert status2 == 422 and doc2["error"] == "poison_request"
    assert sum(s.crashes for s in sup.slots) == crashes0
    # a different deadline is the same poison (volatile keys stripped
    # from the quarantine identity)
    status3, _h3, doc3 = _raw_post(
        pool_daemon, "/v1/simulate", {**body, "deadline_ms": 9999},
    )
    assert status3 == 422 and doc3["error"] == "poison_request"
    # the poison burned both workers; once the backed-off restarts land
    # (what Retry-After tells a real client to wait for) the pool
    # serves clean traffic again
    deadline = time.monotonic() + 10.0
    while sup.alive_count() < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sup.alive_count() == 2
    r = pool_client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    assert r.sim_cycles > 0


def test_deadline_kill_of_stuck_worker_504_and_restart(
    pool_daemon, pool_client,
):
    """A worker stuck past the request deadline is killed (SIGTERM →
    SIGKILL escalation), the request 504s, and the slot is restarted —
    a hung native call can no longer pin the daemon."""
    sup = pool_daemon.supervisor
    kills0 = sum(s.kills for s in sup.slots)
    status, _headers, doc = _raw_post(pool_daemon, "/v1/simulate", {
        "trace": "matmul_512", "arch": "v5e",
        "_chaos_sleep_s": 30.0, "deadline_ms": 400,
    })
    assert status == 504, doc
    assert doc["error"] == "deadline_exceeded"
    assert "killed" in doc["detail"]
    assert sum(s.kills for s in sup.slots) == kills0 + 1
    # the killed slot comes back and the pool keeps serving
    deadline = time.monotonic() + 10.0
    while sup.alive_count() < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sup.alive_count() == 2
    assert pool_client.simulate(trace="matmul_512", arch="v5e").sim_cycles > 0


def test_degraded_pool_sheds_load_503_with_retry_after():
    """Once live workers fall below the floor the daemon sheds load
    (503 + Retry-After) instead of queueing into a dead pool, and
    /healthz reports the degraded state (200 — the daemon itself is
    answering; balancers read the field)."""
    d = ServeDaemon(
        trace_root=FIXTURES, serve_workers=1, min_workers=1,
        restart_backoff_s=5.0, chaos_hooks=True,
    ).start()
    try:
        c = ServeClient(d.url)
        assert c.simulate(trace="matmul_512", arch="v5e").sim_cycles > 0
        d.supervisor.kill_worker(0)
        deadline = time.monotonic() + 5.0
        while d.supervisor.alive_count() > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert d.supervisor.alive_count() == 0
        status, headers, doc = _raw_post(
            d, "/v1/simulate", {"trace": "matmul_512", "arch": "v5e"},
        )
        assert status == 503, doc
        assert doc["error"] == "degraded"
        assert int(headers.get("Retry-After", "0")) >= 1
        assert d.supervisor.shed >= 1
        health = c.healthz()
        assert health["status"] == "degraded"
        assert health["workers_alive"] == 0
    finally:
        d.drain_and_stop()


def test_affinity_key_ignores_deadline_but_not_content():
    from tpusim.serve.supervisor import Supervisor

    a = Supervisor.affinity_key("simulate", {"trace": "x", "deadline_ms": 100})
    b = Supervisor.affinity_key("simulate", {"trace": "x", "deadline_ms": 900})
    other = Supervisor.affinity_key("simulate", {"trace": "y"})
    assert a == b
    assert a != other


# ---------------------------------------------------------------------------
# serve v2: client timeouts + safe retries
# ---------------------------------------------------------------------------


@pytest.fixture()
def black_hole():
    """A server that accepts connections and never answers — the
    stalled-daemon stand-in."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    accepted = []

    def acceptor():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            accepted.append(conn)

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    try:
        yield srv.getsockname(), accepted
    finally:
        srv.close()
        for conn in accepted:
            try:
                conn.close()
            except OSError:
                pass


def test_client_get_times_out_and_retries(black_hole):
    """A stalled daemon can no longer block the client forever: the
    call times out, the (idempotent) GET retries once with backoff,
    and the failure surfaces as a typed 'timeout' error."""
    (host, port), accepted = black_hole
    c = ServeClient(
        f"http://{host}:{port}", timeout_s=0.3, retries=1,
        backoff_base_s=0.01,
    )
    t0 = time.monotonic()
    with pytest.raises(ServeError) as ei:
        c.healthz()
    assert ei.value.status == 0
    assert ei.value.code == "timeout"
    assert time.monotonic() - t0 < 5.0
    # both the original attempt and the retry reached the server
    assert len(accepted) == 2


def test_client_never_replays_a_sent_post(black_hole):
    """A POST whose bytes finished sending is NOT retried on timeout —
    the server may have executed it (a replayed /v1/sweep would
    enqueue a duplicate job)."""
    (host, port), accepted = black_hole
    c = ServeClient(
        f"http://{host}:{port}", timeout_s=0.3, retries=3,
        backoff_base_s=0.01,
    )
    with pytest.raises(ServeError) as ei:
        c.simulate(trace="matmul_512", arch="v5e")
    assert ei.value.code == "timeout"
    assert len(accepted) == 1  # one attempt, no replay


def test_client_per_call_timeout_override(black_hole):
    """timeout_s= on a single call beats the constructor default, even
    on a warm keep-alive connection."""
    (host, port), _accepted = black_hole
    c = ServeClient(f"http://{host}:{port}", timeout_s=60.0, retries=0)
    t0 = time.monotonic()
    with pytest.raises(ServeError):
        c.healthz(timeout_s=0.25)
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# Retry-After honoring (opt-in) + the preserved never-replay rules
# ---------------------------------------------------------------------------


@pytest.fixture()
def backpressure_server():
    """A stub daemon that 503s with Retry-After N times, then 202s —
    the restarting-primary / overloaded-queue stand-in.  Yields
    ``((host, port), hits, set_refusals)``."""
    import http.server

    hits: list[str] = []
    state = {"refusals": 1, "retry_after": "0.3"}

    class _H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", "0") or 0)
            self.rfile.read(length)
            hits.append(self.path)
            if len(hits) <= state["refusals"]:
                body = json.dumps({"error": "overloaded",
                                   "detail": "queue full"}).encode()
                self.send_response(503)
                self.send_header("Retry-After", state["retry_after"])
            else:
                body = json.dumps({"job_id": "job-000001",
                                   "status": "queued"}).encode()
                self.send_response(202)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield srv.server_address, hits, state
    finally:
        srv.shutdown()
        srv.server_close()


def test_client_honors_retry_after_on_503(backpressure_server):
    """With honor_retry_after=True the client sleeps at least the
    server's hint and re-issues — safe even for a job submission,
    because a clean 503 means the server refused (nothing to replay)."""
    (host, port), hits, _state = backpressure_server
    c = ServeClient(
        f"http://{host}:{port}", retries=2, honor_retry_after=True,
        backoff_base_s=0.01,
    )
    t0 = time.monotonic()
    job_id = c.sweep(arch="v5p", chips=8)
    elapsed = time.monotonic() - t0
    assert job_id == "job-000001"
    assert len(hits) == 2              # one refusal + one success
    assert elapsed >= 0.3              # the hint was honored
    assert elapsed < 10.0              # and capped/jittered sanely


def test_client_retry_after_is_opt_in(backpressure_server):
    """The default client surfaces the 503 immediately (PR 5/9/11
    behavior unchanged) — honoring is opt-in."""
    (host, port), hits, _state = backpressure_server
    c = ServeClient(f"http://{host}:{port}", retries=2)
    with pytest.raises(ServeError) as ei:
        c.sweep(arch="v5p", chips=8)
    assert ei.value.status == 503
    assert ei.value.retry_after_s == pytest.approx(0.3)
    assert len(hits) == 1


def test_client_retry_after_budget_exhausts(backpressure_server):
    """Refusals past the retries budget surface the last 503 — the
    client never spins forever on a saturated server."""
    (host, port), hits, state = backpressure_server
    state["refusals"] = 5
    state["retry_after"] = "0.01"
    c = ServeClient(
        f"http://{host}:{port}", retries=2, honor_retry_after=True,
        backoff_base_s=0.001,
    )
    with pytest.raises(ServeError) as ei:
        c.sweep(arch="v5p", chips=8)
    assert ei.value.status == 503
    assert len(hits) == 3              # initial + retries budget of 2


def test_honoring_client_still_never_replays_sent_post(black_hole):
    """honor_retry_after must not weaken the transport-level rule: a
    POST whose bytes finished sending and then TIMED OUT is never
    replayed — the server may still be executing it."""
    (host, port), accepted = black_hole
    c = ServeClient(
        f"http://{host}:{port}", timeout_s=0.3, retries=3,
        backoff_base_s=0.01, honor_retry_after=True,
    )
    with pytest.raises(ServeError) as ei:
        c.sweep(arch="v5p", chips=8)
    assert ei.value.code == "timeout"
    assert len(accepted) == 1          # one attempt, no replay


# ---------------------------------------------------------------------------
# JobTable boot robustness: torn persist files quarantine, never abort
# ---------------------------------------------------------------------------


def test_jobtable_recovery_quarantines_torn_persist_file(tmp_path):
    """A truncated per-job JSON file (daemon killed mid-persist before
    the atomic replace, or disk damage) quarantines with ONE warning;
    the healthy jobs recover intact."""
    import warnings as _warnings

    from tpusim.serve.admission import JobTable

    jobs_dir = tmp_path / "jobs"
    jobs_dir.mkdir()
    healthy = {
        "job-000001": {"job_id": "job-000001", "kind": "sweep",
                       "request": {"arch": "v5p"}, "status": "queued"},
        "job-000003": {"job_id": "job-000003", "kind": "campaign",
                       "request": {"spec": {}}, "status": "done",
                       "result": {"ok": True}},
    }
    for jid, doc in healthy.items():
        (jobs_dir / f"{jid}.json").write_text(json.dumps(doc))
    # the torn file: a prefix of valid JSON (no closing brace)
    (jobs_dir / "job-000002.json").write_text(
        '{"job_id": "job-000002", "kind": "sweep", "requ'
    )

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        table = JobTable(persist_dir=jobs_dir)
    warned = [w for w in caught if "job-000002" in str(w.message)]
    assert len(warned) == 1
    assert "quarantined" in str(warned[0].message)

    # the healthy jobs are all present, the torn one is gone
    assert table.get("job-000001").status == "queued"
    assert table.get("job-000003").status == "done"
    assert table.get("job-000002") is None
    assert table.recovered == 1        # the queued job re-enqueued
    assert (jobs_dir / "quarantine" / "job-000002.json").is_file()
    assert not (jobs_dir / "job-000002.json").exists()
    # id allocation continues past every healthy id
    job = table.submit("sweep", {"arch": "v5p"})
    assert job.job_id == "job-000004"

    # a SECOND boot over the same dir re-warns nothing (the damage
    # was moved aside, not left to re-trip every startup)
    with _warnings.catch_warnings(record=True) as caught2:
        _warnings.simplefilter("always")
        table2 = JobTable(persist_dir=jobs_dir)
    assert not [w for w in caught2 if "unreadable" in str(w.message)]
    assert table2.get("job-000001") is not None


def test_jobtable_recovery_quarantines_wrong_shape(tmp_path):
    """A file that parses as JSON but is not a job object (a list, a
    doc missing its request) also quarantines instead of silently
    lingering forever."""
    import warnings as _warnings

    from tpusim.serve.admission import JobTable

    jobs_dir = tmp_path / "jobs"
    jobs_dir.mkdir()
    (jobs_dir / "job-000001.json").write_text(json.dumps(["not", "a", "job"]))
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        table = JobTable(persist_dir=jobs_dir)
    assert len([w for w in caught if "job-000001" in str(w.message)]) == 1
    assert table.get("job-000001") is None
    assert (jobs_dir / "quarantine" / "job-000001.json").is_file()
