"""tpusim.serve end-to-end: the daemon over an ephemeral loopback port.

Covers the serving contract the subsystem promises:

* simulate / lint / sweep round-trips through the real HTTP stack;
* byte-equality of a served stats doc vs the same request through the
  ``simulate`` CLI (the determinism contract);
* warm repeat requests served from the shared result cache
  (``cache_hit`` true, stats byte-identical to the cold pass);
* error-level TLxxx diagnostics reject a request as 400 with the list;
* admission: 429 + Retry-After with the queue full, 504 past the
  deadline, 413 for oversized bodies (all deterministic — the daemon's
  ``work_hook`` injection point holds a request in-flight on an Event,
  so no test races a timer against real pricing);
* ``/metrics`` parses as Prometheus text;
* SIGTERM drain of a real ``python -m tpusim serve`` process: the
  in-flight request completes, the process exits 0.
"""

from __future__ import annotations

import json
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from tpusim.serve.client import ServeClient, ServeError
from tpusim.serve.daemon import SERVE_FORMAT_VERSION, ServeDaemon

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "traces"

#: keys excluded from byte-equality comparisons: host-dependent values
#: plus the perf layer's own accounting (present exactly when a cache /
#: pool is mounted, which differs between served and plain-CLI runs)
VOLATILE = {"simulation_rate_kops", "wall_seconds", "silicon_slowdown"}
PERF_PREFIXES = ("cache_", "pool_")


def canonical(stats: dict) -> str:
    doc = {
        k: v for k, v in stats.items()
        if k not in VOLATILE and not k.startswith(PERF_PREFIXES)
    }
    return json.dumps(doc, indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# shared daemon (round-trip tests)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def daemon():
    d = ServeDaemon(trace_root=FIXTURES, max_inflight=4).start()
    yield d
    d.drain_and_stop()


@pytest.fixture(scope="module")
def client(daemon):
    return ServeClient(daemon.url)


def test_healthz_and_traces(client):
    health = client.healthz()
    assert health["status"] == "ok"
    assert health["format_version"] == SERVE_FORMAT_VERSION
    assert set(client.traces()) >= {"llama_tiny_tp2dp2", "matmul_512"}


def test_simulate_round_trip(client):
    r = client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    assert r.arch == "v5p"
    assert r.num_devices == 4
    assert r.sim_cycles > 0
    assert r.stats["kernel_launches"] > 0
    assert r.format_version == SERVE_FORMAT_VERSION
    assert r.model_version  # stamped so clients can reason about staleness


def test_warm_repeat_is_cache_hit_and_byte_identical(client):
    cold = client.simulate(trace="matmul_512", arch="v5e")
    warm = client.simulate(trace="matmul_512", arch="v5e")
    assert warm.cache_hit
    assert canonical(warm.stats) == canonical(cold.stats)


def test_served_stats_byte_equal_cli(client, tmp_path):
    """The same request through the one-shot CLI must produce the same
    stats doc byte for byte (minus host-dependent keys and the cache
    layer's own accounting)."""
    served = client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    out = tmp_path / "cli_stats.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpusim", "simulate",
         str(FIXTURES / "llama_tiny_tp2dp2"), "--arch", "v5p",
         "--json", str(out)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    cli_stats = json.loads(out.read_text())
    assert canonical(served.stats) == canonical(cli_stats)


def test_inline_hlo_simulate(client):
    text = (FIXTURES / "matmul_512" / "modules" / "matmul_512.hlo").read_text()
    r1 = client.simulate(hlo_text=text, arch="v5e")
    assert r1.sim_cycles > 0
    assert r1.trace.startswith("inline:")
    # the inline pod is cached under its content hash: the repeat
    # request parses nothing and prices nothing
    r2 = client.simulate(hlo_text=text, arch="v5e")
    assert r2.cache_hit
    assert canonical(r2.stats) == canonical(r1.stats)


def test_simulate_with_faults_stamps_fault_stats(client):
    r = client.simulate(
        trace="llama_tiny_tp2dp2", arch="v5p",
        faults={"faults": [{"kind": "chip_straggler", "chip": 0,
                            "clock_scale": 0.5}]},
    )
    assert any(k.startswith("faults_") for k in r.stats)
    healthy = client.simulate(trace="llama_tiny_tp2dp2", arch="v5p")
    assert r.sim_cycles > healthy.sim_cycles


def test_partitioned_topology_is_422_not_500(client):
    """A fault schedule that disconnects the pod is the request's
    fault: the replay refusal (TopologyPartitionedError) must surface
    as 422, never the 500 boundary."""
    faults = {"faults": [
        {"kind": "link_down", "src": 0, "dst": 1},
        {"kind": "link_down", "src": 0, "dst": 2},
    ]}
    with pytest.raises(ServeError) as ei:
        client.simulate(
            trace="llama_tiny_tp2dp2", arch="v5p", faults=faults,
            overlays=[{"arch": {"ici": {"network_mode": "detailed"}}}],
        )
    assert ei.value.status == 422
    assert ei.value.code == "replay_failed"
    assert "partitioned" in ei.value.detail


def test_lint_round_trip(client):
    rep = client.lint(trace="llama_tiny_tp2dp2", arch="v5p")
    assert rep.errors == 0
    assert "error(s)" in rep.summary
    assert isinstance(rep.diagnostics.get("items", []), list)


def test_sweep_job_round_trip(client):
    job_id = client.sweep(arch="v5p", chips=8, payload_mb=1.0)
    assert job_id.startswith("job-")
    status = client.wait_job(job_id, timeout_s=60)
    assert status.status == "done"
    assert status.result["scenarios"] > 0
    assert status.result["worst_inflation"] >= 1.0


def test_sweep_trace_mode_honors_overlays(client):
    """A trace sweep must price under the request's composed config —
    overlays silently dropped would return wrong inflation numbers."""
    base = client.wait_job(
        client.sweep(trace="llama_tiny_tp2dp2", arch="v5p",
                     max_scenarios=2),
        timeout_s=120,
    )
    slow_ici = client.wait_job(
        client.sweep(trace="llama_tiny_tp2dp2", arch="v5p",
                     max_scenarios=2,
                     overlays=[{"arch": {"ici": {
                         "link_bandwidth": 9.0e9}}}]),
        timeout_s=120,
    )
    assert base.status == "done" and slow_ici.status == "done"
    # 10x slower links must inflate the healthy step-time baseline
    assert slow_ici.result["healthy"] > base.result["healthy"]


def test_unknown_job_404(client):
    with pytest.raises(ServeError) as ei:
        client.job("job-999999")
    assert ei.value.status == 404


def test_unknown_trace_404_and_no_path_walk(client):
    for name in ("nope", "../nope", "a/b"):
        with pytest.raises(ServeError) as ei:
            client.simulate(trace=name, arch="v5p")
        assert ei.value.status == 404, name


def test_bad_request_400(client):
    with pytest.raises(ServeError) as ei:
        client.simulate(trace="matmul_512", hlo_text="x", arch="v5e")
    assert ei.value.status == 400
    with pytest.raises(ServeError) as ei:
        client.simulate(hlo_text="definitely not hlo {", arch="v5e")
    assert ei.value.status == 400


def test_metrics_prometheus_parses(client):
    client.healthz()
    text = client.metrics_text()
    gauges = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.split()
        gauges[name] = float(value)
    assert gauges["tpusim_serve_requests_total"] > 0
    assert "tpusim_serve_admission_inflight" in gauges
    assert "tpusim_serve_cache_hits" in gauges
    assert "# TYPE tpusim_serve_requests_total gauge" in text
    assert "# HELP tpusim_serve_requests_total" in text


# ---------------------------------------------------------------------------
# validation refusal (400 with the TLxxx list)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def broken_root(tmp_path_factory):
    """A trace root holding one trace whose commandlist references a
    module that does not exist — an error-level TL006."""
    root = tmp_path_factory.mktemp("serve_broken_root")
    td = root / "broken"
    (td / "modules").mkdir(parents=True)
    src = FIXTURES / "matmul_512" / "modules" / "matmul_512.hlo"
    (td / "modules" / "matmul_512.hlo").write_text(src.read_text())
    (td / "meta.json").write_text(
        json.dumps({"num_devices": 1, "format_version": 1})
    )
    (td / "commandlist.jsonl").write_text(
        json.dumps({"kind": "kernel_launch", "module": "no_such_module",
                    "device": 0}) + "\n"
    )
    return root


def test_error_diagnostics_reject_as_400(broken_root):
    with ServeDaemon(trace_root=broken_root) as d:
        c = ServeClient(d.url)
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="broken", arch="v5e")
        err = ei.value
        assert err.status == 400
        assert err.code == "validation_failed"
        assert "TL006" in err.doc.get("codes", [])
        assert any(
            item["code"] == "TL006" for item in err.diagnostics
        )
        # the lint endpoint REPORTS the same finding instead of failing
        rep = c.lint(trace="broken", arch="v5e")
        assert rep.errors >= 1
        assert "TL006" in rep.codes
        # validate=False skips the pre-flight; the replay itself then
        # refuses (422) rather than pricing garbage silently
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="broken", arch="v5e", validate=False)
        assert ei.value.status == 422


# ---------------------------------------------------------------------------
# admission: 429 / 504 / 413
# ---------------------------------------------------------------------------


def _blocked_daemon(**kw):
    """A daemon whose simulate requests block on an Event — admission
    behavior becomes deterministic (no timer races)."""
    release = threading.Event()
    entered = threading.Event()

    def hook(endpoint, body):
        if body.get("block"):
            entered.set()
            assert release.wait(30.0), "test never released the hook"

    d = ServeDaemon(trace_root=FIXTURES, work_hook=hook, **kw)
    return d, release, entered


def test_429_when_queue_full():
    d, release, entered = _blocked_daemon(max_inflight=1, queue_depth=0)
    with d:
        c = ServeClient(d.url)
        # hold one request in-flight (the body carries the block flag
        # only through the hook; it prices normally once released)
        blocker = threading.Thread(target=lambda: ServeClient(d.url)._request(
            "POST", "/v1/simulate",
            {"trace": "matmul_512", "arch": "v5e", "block": True},
        ), daemon=True)
        blocker.start()
        assert entered.wait(10.0)
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="matmul_512", arch="v5e")
        assert ei.value.status == 429
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s >= 1.0
        release.set()
        blocker.join(timeout=30.0)
        # with the slot free again the same request succeeds
        assert c.simulate(trace="matmul_512", arch="v5e").sim_cycles > 0


def test_504_when_deadline_expires_in_queue():
    d, release, entered = _blocked_daemon(max_inflight=1, queue_depth=4)
    with d:
        c = ServeClient(d.url)
        blocker = threading.Thread(target=lambda: ServeClient(d.url)._request(
            "POST", "/v1/simulate",
            {"trace": "matmul_512", "arch": "v5e", "block": True},
        ), daemon=True)
        blocker.start()
        assert entered.wait(10.0)
        t0 = time.monotonic()
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="matmul_512", arch="v5e", deadline_ms=300)
        waited = time.monotonic() - t0
        assert ei.value.status == 504
        assert waited >= 0.25  # it genuinely queued until the deadline
        release.set()
        blocker.join(timeout=30.0)


def test_413_for_oversized_body():
    with ServeDaemon(trace_root=FIXTURES, max_request_bytes=1024) as d:
        c = ServeClient(d.url)
        with pytest.raises(ServeError) as ei:
            c.simulate(hlo_text="x" * 4096, arch="v5e")
        assert ei.value.status == 413


def test_queued_waiter_is_not_starved_by_fresh_arrivals():
    """FIFO admission: while a request is queued, a freed slot goes to
    it, not to whichever newcomer happens to arrive next — a steady
    arrival stream must not ride a queued request to its 504."""
    from tpusim.serve.admission import AdmissionController

    adm = AdmissionController(max_inflight=1, queue_depth=4)
    order: list[str] = []
    first = adm.admit()
    waiter_ready = threading.Event()

    def waiter():
        waiter_ready.set()
        with adm.admit(deadline=time.monotonic() + 10.0):
            order.append("waiter")

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    waiter_ready.wait(5.0)
    deadline = time.monotonic() + 5.0
    while adm.stats_dict()["queued"] < 1:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    # newcomers while the waiter queues: they must line up BEHIND it
    results: list[str] = []

    def newcomer(tag):
        with adm.admit(deadline=time.monotonic() + 10.0):
            results.append(tag)
            order.append(tag)

    n1 = threading.Thread(target=newcomer, args=("n1",), daemon=True)
    n1.start()
    time.sleep(0.05)
    first.__exit__(None, None, None)  # free the slot
    t.join(timeout=10.0)
    n1.join(timeout=10.0)
    assert order[0] == "waiter", order  # the queued request went first


def test_job_queue_overload_429():
    d = ServeDaemon(trace_root=FIXTURES, job_queue_depth=1, job_workers=1)
    # NOT started: no job worker drains the queue, so the second submit
    # must bounce off the bounded table
    d.jobs.submit("sweep", {"arch": "v5p", "chips": 8})
    from tpusim.serve.admission import Overloaded

    with pytest.raises(Overloaded):
        d.jobs.submit("sweep", {"arch": "v5p", "chips": 8})


# ---------------------------------------------------------------------------
# SIGTERM drain (real process)
# ---------------------------------------------------------------------------


def test_sigterm_drains_real_daemon(tmp_path):
    """``python -m tpusim serve`` under SIGTERM: the in-flight request
    completes with 200, the process exits 0, and the listener is gone
    afterwards."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpusim", "serve", "--port", "0",
         "--trace-root", str(FIXTURES), "--drain-grace-s", "60"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO,
    )
    try:
        line = proc.stdout.readline()
        assert "listening on http://" in line, line
        url = line.split("listening on ", 1)[1].split()[0]
        c = ServeClient(url, timeout_s=120.0)

        result: dict = {}

        def slow_request():
            # cold llama: trace load + pricing keeps this in flight
            # long enough for the SIGTERM to land mid-request
            result["r"] = c.simulate(trace="llama_tiny_tp2dp2", arch="v5p")

        t = threading.Thread(target=slow_request, daemon=True)
        t.start()
        time.sleep(0.15)  # let the request reach the daemon
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=120.0)
        assert not t.is_alive(), "in-flight request never completed"
        assert result["r"].sim_cycles > 0  # drained, not dropped
        assert proc.wait(timeout=60.0) == 0  # the exit-0 contract
        out = proc.stdout.read()
        assert "drained" in out
        # the listener is really gone
        with pytest.raises(Exception):
            ServeClient(url, timeout_s=2.0).healthz()
    finally:
        if proc.poll() is None:
            proc.kill()


def test_in_process_drain_rejects_new_work():
    """From the first drain instant, new requests get 503 and /healthz
    reports draining — load balancers stop routing before the listener
    disappears."""
    d = ServeDaemon(trace_root=FIXTURES).start()
    try:
        c = ServeClient(d.url)
        assert c.simulate(trace="matmul_512", arch="v5e").sim_cycles > 0
        assert c.healthz()["status"] == "ok"
        d.admission.start_drain()
        with pytest.raises(ServeError) as ei:
            c.simulate(trace="matmul_512", arch="v5e")
        assert ei.value.status == 503
        with pytest.raises(ServeError) as ei:
            c.healthz()
        assert ei.value.status == 503
        assert ei.value.doc.get("status") == "draining"
    finally:
        d.drain_and_stop()
