"""Simulator-throughput regression guard (VERDICT r4 #8).

The engine got 2.5x faster in round 4 (commit f42c7a0) and prints
``sim_rate_kops``/``silicon_slowdown`` (the ``gpgpu_simulation_rate``
analogue, ``gpgpusim_entrypoint.cc:262-268``); nothing pinned it, so a
future fidelity fix could silently cost 10x sim speed.  This replays the
committed fixture set (real workload mix, zero jax) under a wall-clock
budget and a floor on ops simulated per host-second.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

#: very generous floors — ~25x below the currently-measured 570 kops/s,
#: so only a genuine order-of-magnitude regression (an O(n^2) walk, a
#: cache dropped) trips them, not a loaded CI runner.  Override with
#: TPUSIM_MIN_KOPS for slower machines.
MIN_KOPS_PER_SEC = float(os.environ.get("TPUSIM_MIN_KOPS", "20"))
MAX_WALL_SECONDS = 60.0


def test_fixture_replay_throughput():
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace, select_module

    fd = REPO / "reports" / "silicon"
    entries = json.loads((fd / "manifest.json").read_text())["workloads"]
    mods = [
        select_module(load_trace(fd / e["trace"]), e.get("module"))
        for e in entries
    ]
    eng = Engine(load_config(arch="v5e"))
    t0 = time.perf_counter()
    ops = 0
    for mod in mods:
        res = eng.run(mod)
        ops += res.op_count
    wall = time.perf_counter() - t0
    assert wall < MAX_WALL_SECONDS, f"replay took {wall:.1f}s"
    kops = ops / wall / 1e3
    assert kops > MIN_KOPS_PER_SEC, (
        f"simulation rate {kops:.1f} kops/s below the {MIN_KOPS_PER_SEC} "
        f"floor ({ops} ops in {wall:.2f}s) — an engine change cost an "
        f"order of magnitude of throughput"
    )
