"""Tests for the auxiliary analysis tools (`tpusim/tools/`) — the
bbv_tool / occupancy_calc_tool / silicon_checkpoint_tool parity slots
(`util/tracer_nvbit/others/`)."""

from pathlib import Path

import pytest

from tests.conftest import run_in_cpu_mesh
from tpusim.timing.config import load_config
from tpusim.tools.bbv import compute_bbv, write_simpoint_bb
from tpusim.tools.occupancy import occupancy_report
from tpusim.trace.hlo_text import parse_hlo_module

FIXTURES = Path(__file__).parent / "fixtures"


@pytest.fixture(scope="module")
def tiny_mlp():
    return parse_hlo_module((FIXTURES / "tiny_mlp.hlo").read_text())


# -- bbv --------------------------------------------------------------------

def test_bbv_vectors_cover_all_ops(tiny_mlp):
    res = compute_bbv(tiny_mlp, interval_ops=4)
    total = sum(sum(v.values()) for v in res.vectors)
    assert total > 0
    # every interval except possibly the last is exactly full
    for v in res.vectors[:-1]:
        assert sum(v.values()) == 4
    assert sum(res.vectors[-1].values()) <= 4
    # dot must appear as a dimension
    assert "dot" in res.dims


def test_bbv_while_bodies_repeat():
    """A while with trip count K must contribute K copies of its body —
    the phase behavior SimPoint clusters on."""
    text = """
HloModule loopy, is_scheduled=true

%body (p: (f32[64,64], s32[])) -> (f32[64,64], s32[]) {
  %p = (f32[64,64]{1,0}, s32[]) parameter(0)
  %x = f32[64,64]{1,0} get-tuple-element(%p), index=0
  %i = s32[] get-tuple-element(%p), index=1
  %d = f32[64,64]{1,0} dot(%x, %x), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %one = s32[] constant(1)
  %ni = s32[] add(%i, %one)
  ROOT %t = (f32[64,64]{1,0}, s32[]) tuple(%d, %ni)
}

%cond (p: (f32[64,64], s32[])) -> pred[] {
  %p = (f32[64,64]{1,0}, s32[]) parameter(0)
  %i = s32[] get-tuple-element(%p), index=1
  %lim = s32[] constant(7)
  ROOT %lt = pred[] compare(%i, %lim), direction=LT
}

ENTRY %main (a: f32[64,64]) -> (f32[64,64], s32[]) {
  %a = f32[64,64]{1,0} parameter(0)
  %z = s32[] constant(0)
  %t0 = (f32[64,64]{1,0}, s32[]) tuple(%a, %z)
  ROOT %w = (f32[64,64]{1,0}, s32[]) while(%t0), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"7"}}
}
"""
    mod = parse_hlo_module(text)
    res = compute_bbv(mod, interval_ops=100000)
    dots = sum(v.get(res.dims["dot"], 0) for v in res.vectors)
    assert dots == 7


def test_simpoint_format(tmp_path, tiny_mlp):
    res = compute_bbv(tiny_mlp, interval_ops=4)
    out = tmp_path / "trace.bb"
    write_simpoint_bb(res, out)
    lines = out.read_text().splitlines()
    assert len(lines) == res.num_intervals
    for line in lines:
        assert line.startswith("T:")
        for part in line[1:].split():
            _, dim, count = part.split(":")
            assert int(dim) >= 1 and int(count) >= 1


# -- occupancy --------------------------------------------------------------

def test_occupancy_full_tiles(tiny_mlp):
    cfg = load_config(arch="v5p")
    report = occupancy_report(tiny_mlp, cfg.arch)
    assert report.ops, "fixture has dots"
    for o in report.ops:
        assert 0 < o.tile_fill <= 1.0
        assert 0 < o.row_fill <= 1.0
        assert 0 < o.pipeline_eff < 1.0
        assert 0 < o.mxu_occupancy <= 1.0


def test_occupancy_flags_skinny_matmul():
    """A K=32 matmul fills 25% of a 128-row array; a 1-row M is
    pipeline-starved.  The calculator must rank them below a full tile."""
    text = """
HloModule skinny, is_scheduled=true

ENTRY %main (a: f32[1,32], b: f32[32,256], c: bf16[512,128], d: bf16[128,128]) -> f32[1,256] {
  %a = f32[1,32]{1,0} parameter(0)
  %b = f32[32,256]{1,0} parameter(1)
  %c = bf16[512,128]{1,0} parameter(2)
  %d = bf16[128,128]{1,0} parameter(3)
  %big = bf16[512,128]{1,0} dot(%c, %d), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %sk = f32[1,256]{1,0} dot(%a, %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    mod = parse_hlo_module(text)
    cfg = load_config(arch="v5p")
    report = occupancy_report(mod, cfg.arch)
    by_name = {o.name: o for o in report.ops}
    assert by_name["sk"].tile_fill == pytest.approx(32 / 128 * 256 / 256)
    assert by_name["sk"].row_fill == pytest.approx(1 / 8)
    assert by_name["big"].mxu_occupancy > by_name["sk"].mxu_occupancy
    assert report.worst[0].name == "sk"


# -- buffer snapshots -------------------------------------------------------

SNAPSHOT_SCRIPT = r"""
import numpy as np
import jax.numpy as jnp
from tpusim.tracer.capture import snapshot_buffers

def f(x):
    return x * 2.0, x.sum()

paths = snapshot_buffers(
    f, jnp.arange(8.0), out_dir=OUT, launches=2
)
assert len(paths) == 4, paths
a = np.load(paths[0])
assert np.allclose(a, np.arange(8.0) * 2.0)
s = np.load(paths[1])
assert float(s) == 28.0
print("SNAP_OK")
"""


@pytest.mark.slow
def test_snapshot_buffers(tmp_path):
    out = run_in_cpu_mesh(
        SNAPSHOT_SCRIPT.replace("OUT", repr(str(tmp_path / "ckpt"))),
        n_devices=1,
    )
    assert "SNAP_OK" in out


# a step function whose output[1] structurally matches its input[0]: the
# carried state must be threaded forward so per-launch snapshots EVOLVE
# (the reference silicon_checkpoint_tool snapshots evolving device state;
# identical "per-launch" checkpoints would blind a divergence hunt)
SNAPSHOT_CARRY_SCRIPT = r"""
import numpy as np
import jax.numpy as jnp
from tpusim.tracer.capture import snapshot_buffers

def step(w, x):
    loss = ((w - x) ** 2).sum()
    return loss, w - 0.1 * (w - x)   # (loss, updated_w)

w0 = jnp.ones((4, 4)) * 5.0
x = jnp.zeros((4, 4))
paths = snapshot_buffers(step, w0, x, out_dir=OUT, launches=3)
# 2 buffers per launch x 3 launches
assert len(paths) == 6, paths
losses = [float(np.load(p)) for p in paths if "buf0" in p.name]
# the loss must strictly decrease across launches: state was carried
assert losses[0] > losses[1] > losses[2], losses
print("CARRY_OK")
"""


@pytest.mark.slow
def test_snapshot_buffers_carries_state(tmp_path):
    out = run_in_cpu_mesh(
        SNAPSHOT_CARRY_SCRIPT.replace("OUT", repr(str(tmp_path / "ckpt"))),
        n_devices=1,
    )
    assert "CARRY_OK" in out
