"""End-to-end tests: live JAX capture → stored trace → parse → simulate.

The single-device path runs in-process on whatever backend is present (the
real TPU under axon, CPU elsewhere).  Multi-device SPMD paths run in a
subprocess CPU mesh (see conftest) — the "fake cluster" this framework uses
the way the reference uses procman + prerecorded traces (SURVEY.md §4).
"""

import sys

import pytest

from tests.conftest import require_jax_shard_map, run_in_cpu_mesh
from tpusim.sim.driver import SimDriver, simulate_trace
from tpusim.sim.stats import EXIT_SENTINEL
from tpusim.timing.config import SimConfig
from tpusim.trace.format import load_trace


@pytest.fixture(scope="module")
def matmul_capture(live_jax):
    import jax
    import jax.numpy as jnp

    from tpusim.tracer.capture import capture

    def f(a, b):
        return jnp.maximum(a @ b, 0.0).sum()

    a = jnp.ones((256, 512), jnp.bfloat16)
    b = jnp.ones((512, 1024), jnp.bfloat16)
    return capture(f, a, b, name="relu_matmul")


def test_capture_basic(matmul_capture):
    cap = matmul_capture
    assert "ENTRY" in cap.hlo_text
    mod = cap.module
    assert mod.entry_name is not None
    # the dot is in the entry or inside a fusion; total flops must include it
    assert cap.in_bytes == (256 * 512 + 512 * 1024) * 2
    assert cap.meta["num_devices"] >= 1


def test_capture_simulate_roundtrip(tmp_path, matmul_capture):
    from tpusim.trace.format import save_trace

    cap = matmul_capture
    save_trace(
        tmp_path / "t", modules={cap.name: cap.hlo_text},
        commands=cap.commands(), meta=cap.meta,
    )
    pod = load_trace(tmp_path / "t")
    assert cap.name in pod.modules
    report = SimDriver(SimConfig()).run(pod)
    assert report.cycles > 0
    # 2*M*N*K flops must be visible to the model (dot may be fused)
    assert report.totals.mxu_flops >= 2 * 256 * 512 * 1024
    assert report.stats.get("sim_cycle") == report.cycles


def test_xla_cost_analysis_agrees(matmul_capture):
    """XLA's own flop count is the ground truth the cost model must track
    (the correlation-harness idea at unit-test scale)."""
    cap = matmul_capture
    xla_flops = cap.meta["xla_cost_analysis"].get("flops", 0)
    if not xla_flops:
        pytest.skip("backend does not report flops")
    from tpusim.timing.engine import Engine

    res = Engine(SimConfig()).run(cap.module)
    assert res.flops > 0
    # within 2x of XLA's count (XLA counts some ops differently)
    assert 0.5 <= res.flops / xla_flops <= 2.0


SHARDED_CAPTURE_SCRIPT = r"""
import jax, sys
from tpusim.models import get_workload
from tpusim.tracer.capture import capture_to_dir

wl = get_workload("llama_tiny_tp2dp2")
fn, args = wl.build()
td = capture_to_dir(sys.argv[1], fn, *args, name=wl.name)
print("OK", td.path)
"""


@pytest.mark.slow
def test_sharded_capture_has_collectives(tmp_path):
    out = tmp_path / "llama_tiny_trace"
    run_in_cpu_mesh(
        SHARDED_CAPTURE_SCRIPT.replace("sys.argv[1]", repr(str(out))),
        n_devices=4,
    )
    pod = load_trace(out)
    mod = pod.modules["llama_tiny_tp2dp2"]
    assert mod.num_devices == 4
    colls = mod.collectives()
    assert colls, "sharded train step must contain collectives"
    kinds = {op.base for op in colls}
    assert kinds & {"all-reduce", "all-gather", "reduce-scatter"}
    # groups must carry real device ids
    assert any(op.collective.group_size > 1 for op in colls)

    report = SimDriver(SimConfig()).run(pod)
    assert report.totals.collective_count >= 1
    assert report.totals.ici_bytes > 0


RING_CAPTURE_SCRIPT = r"""
import sys
from tpusim.models import get_workload
from tpusim.tracer.capture import capture_to_dir

wl = get_workload("ring_attention_sp8")
fn, args = wl.build(seq=8*256, heads=4, head_dim=64)
capture_to_dir(sys.argv[1], fn, *args, name=wl.name)
print("OK")
"""


@pytest.mark.slow
def test_ring_attention_trace_has_ppermute(tmp_path):
    require_jax_shard_map()
    out = tmp_path / "ring_trace"
    run_in_cpu_mesh(
        RING_CAPTURE_SCRIPT.replace("sys.argv[1]", repr(str(out))),
        n_devices=8,
    )
    pod = load_trace(out)
    mod = pod.modules["ring_attention_sp8"]
    ops = list(mod.all_ops())
    assert any(op.base == "collective-permute" for op in ops), (
        "ring attention must lower to collective-permute chains"
    )
    report = SimDriver(SimConfig()).run(pod)
    assert report.totals.collective_count >= 1


CLI_SCRIPT = r"""
import sys
from tpusim.__main__ import main

rc = main(["capture", "matmul", sys.argv[1]])
assert rc == 0
rc = main(["simulate", sys.argv[1], "--arch", "v5p"])
assert rc == 0
"""


@pytest.mark.slow
def test_cli_capture_simulate(tmp_path, capfd):
    out = tmp_path / "cli_trace"
    stdout = run_in_cpu_mesh(
        CLI_SCRIPT.replace("sys.argv[1]", repr(str(out))), n_devices=1
    )
    assert EXIT_SENTINEL in stdout
    assert "tpusim_sim_cycle" in stdout


def test_simulate_trace_defaults_to_captured_arch(tmp_path, matmul_capture):
    from tpusim.trace.format import save_trace

    cap = matmul_capture
    save_trace(
        tmp_path / "t2", modules={cap.name: cap.hlo_text},
        commands=cap.commands(), meta=cap.meta,
    )
    report = simulate_trace(tmp_path / "t2")
    assert report.cycles > 0


def test_measure_wall_time_smoke(live_jax):
    import jax.numpy as jnp

    from tpusim.tracer.capture import measure_wall_time

    def f(x):
        return (x * 2).sum()

    t = measure_wall_time(f, jnp.ones((1024, 1024)), iters=3, warmup=1)
    assert t["min_s"] > 0
    assert t["median_s"] >= t["min_s"]
