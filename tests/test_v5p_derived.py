"""The cross-generation derived overlay (docs/V5P.md).

With no v5p silicon reachable, `tpusim.timing.derive` carries the
v5e-calibrated transferable knobs (dimensionless fractions +
cycle counts of the shared TensorCore design) over the v5p preset's
published absolutes.  Reference slot: per-card tested-cfgs
(`gpu-simulator/configs/tested-cfgs/`).
"""

from __future__ import annotations

from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def test_derived_file_is_current(monkeypatch):
    """configs/v5p.derived.flags must match what the committed v5e
    overlay derives — a refit that forgets to re-derive fails here."""
    from tpusim.timing.derive import derive_overlay

    committed = REPO / "configs" / "v5p.derived.flags"
    assert committed.is_file(), (
        "missing configs/v5p.derived.flags; run "
        "`python -m tpusim derive-arch`"
    )
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(REPO / "configs"))
    lines = derive_overlay("v5e", "v5p")
    want = [ln for ln in lines if ln.startswith("-")]
    have = [
        ln for ln in committed.read_text().splitlines()
        if ln.startswith("-")
    ]
    assert want == have


def test_partition_covers_every_refinable_knob():
    """Every knob the refiner can move is classified: transferable or
    explicitly not — an unclassified knob would silently stay at the
    v5p preset with no recorded justification."""
    from tpusim.harness.refine import KNOBS
    from tpusim.timing.derive import (
        NON_TRANSFERABLE_KNOBS, TRANSFERABLE_KNOBS,
    )

    classified = set(TRANSFERABLE_KNOBS) | set(NON_TRANSFERABLE_KNOBS)
    assert set(KNOBS) <= classified, set(KNOBS) - classified


def test_derived_overlay_applies_to_v5p(monkeypatch):
    """load_config('v5p') falls back to the derived overlay when no real
    v5p.tuned.flags exists, leaving published absolutes untouched."""
    from tpusim.timing.config import load_config

    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(REPO / "configs"))
    cfg = load_config(arch="v5p")
    base = load_config(arch="v5p", tuned=False)
    # published absolutes: never derived
    assert cfg.arch.clock_ghz == base.arch.clock_ghz == 1.75
    assert cfg.arch.mxu_count == 8
    assert cfg.arch.hbm_bandwidth == 2765e9
    # transferable calibration landed (preset default differs)
    v5e = load_config(arch="v5e")
    assert cfg.arch.hbm_efficiency == pytest.approx(
        v5e.arch.hbm_efficiency
    )
    assert cfg.arch.op_overhead_cycles == v5e.arch.op_overhead_cycles


def test_real_tuned_overlay_beats_derived(monkeypatch, tmp_path):
    """A real <arch>.tuned.flags must shadow the derived fallback."""
    from tpusim.timing.config import load_config, tuned_overlay_path

    cfgdir = tmp_path / "configs"
    cfgdir.mkdir()
    (cfgdir / "v5p.derived.flags").write_text("-arch.hbm_efficiency 0.5\n")
    monkeypatch.setenv("TPUSIM_TUNED_DIR", str(cfgdir))
    assert tuned_overlay_path("v5p").name == "v5p.derived.flags"
    assert load_config(arch="v5p").arch.hbm_efficiency == 0.5
    (cfgdir / "v5p.tuned.flags").write_text("-arch.hbm_efficiency 0.9\n")
    assert tuned_overlay_path("v5p").name == "v5p.tuned.flags"
    assert load_config(arch="v5p").arch.hbm_efficiency == 0.9
