"""tpusim — a TPU-native, trace-driven, cycle-level simulator framework.

A ground-up rebuild of the capabilities of Accel-Sim (distributed fork,
reference: /root/reference) for TPU hardware:

* an **XLA-HLO op tracer** that captures JAX workloads (in place of the NVBit
  SASS tracer, ``util/tracer_nvbit/``),
* a **timing core** that models the TPU TensorCore — MXU systolic array, VPU
  lanes, scalar unit, vmem and HBM — (in place of the GPGPU-Sim 4.0
  SM/cache/DRAM model under ``gpu-simulator/gpgpu-sim/src/``),
* an **ICI torus interconnect model** with ring / bidirectional / tree
  collective schedules (in place of the fork's constant-latency NCCL replay,
  ``gpu-simulator/main.cc:116-134``),
* an **AccelWattch-style power model** re-fit to TPU units
  (``src/accelwattch/``), and
* **orchestration / correlation harnesses** (``util/job_launching/``,
  ``util/plotting/``).

The central architectural idea carried over from the reference
(``gpu-simulator/README.md:5-9``): the timing core consumes an
ISA-independent IR (here: an HLO-op trace) fed by swappable frontends —
live JAX capture or stored trace files.
"""

from tpusim.version import __version__

__all__ = ["__version__"]
