"""Command-line interface — the ``accel-sim.out`` equivalent.

    python -m tpusim simulate <trace-dir> [--arch v5p] [--config file ...]
    python -m tpusim capture  <workload> <out-dir> [--launches N]
    python -m tpusim info     <trace-dir>

``simulate`` mirrors ``accel-sim.out -trace kernelslist.g -config
gpgpusim.config`` (``gpu-simulator/main.cc:55-206``); ``capture`` mirrors
``run_hw_trace.py``; workload names come from the registry in
:mod:`tpusim.models`.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _cmd_simulate(args: argparse.Namespace) -> int:
    from tpusim.sim.driver import simulate_trace

    overlays = list(args.config or [])
    if args.power:
        overlays.append({"power_enabled": True})
    if args.resume_kernel:
        overlays.append({"resume_kernel": args.resume_kernel})
    if args.checkpoint_kernel:
        overlays.append({"checkpoint_kernel": args.checkpoint_kernel})
    if args.resume_op:
        overlays.append({"resume_op": args.resume_op})
    if args.checkpoint_op:
        overlays.append({"checkpoint_op": args.checkpoint_op})
    if args.network_mode:
        overlays.append({"arch": {"ici": {"network_mode": args.network_mode}}})
    obs = None
    if args.obs_window_cycles and not args.obs_out:
        print("tpusim: error: --obs-window-cycles requires --obs-out "
              "(nothing would be sampled or written)", file=sys.stderr)
        return 2
    if args.obs_out:
        from tpusim.obs import Instrumentation

        obs = Instrumentation(window_cycles=args.obs_window_cycles)
    faults = None
    if args.faults:
        from tpusim.faults import load_fault_schedule

        faults = load_fault_schedule(args.faults)
    # tpusim.guard: --cache-quota bounds the disk result cache (implies
    # --result-cache), --max-wall-s arms a cooperative deadline token,
    # --max-rss mounts the memory watchdog whose terminal shed step
    # cancels the run cleanly instead of meeting the OOM-killer
    result_cache = args.result_cache
    compile_cache = getattr(args, "compile_cache", None)
    cancel = None
    watchdog = None
    try:
        if getattr(args, "cache_quota", None):
            from tpusim.guard.store import parse_size
            from tpusim.perf.cache import as_result_cache

            result_cache = as_result_cache(
                True if result_cache is None else result_cache
            )
            result_cache.quota_bytes = parse_size(args.cache_quota)
        if compile_cache and getattr(args, "cache_quota", None):
            # one quota governs the whole store dir — the compiled tier
            # enforces it on its own publishes like every other writer
            from tpusim.fastpath.store import as_compile_store
            from tpusim.guard.store import parse_size

            compile_cache = as_compile_store(
                compile_cache, quota_bytes=parse_size(args.cache_quota)
            )
        if getattr(args, "max_wall_s", None):
            from tpusim.guard.cancel import CancelToken

            cancel = CancelToken.after(args.max_wall_s)
        if getattr(args, "max_rss", None):
            from tpusim.guard.cancel import CancelToken
            from tpusim.guard.store import parse_size
            from tpusim.guard.watchdog import MemoryWatchdog, default_ladder
            from tpusim.perf.cache import as_result_cache

            if cancel is None:
                cancel = CancelToken()
            # the ladder's shrink step needs the LIVE ResultCache, not
            # the raw flag value (True / a dir path) — coerce here and
            # hand the same instance to simulate_trace below
            result_cache = as_result_cache(result_cache)
            watchdog = default_ladder(
                MemoryWatchdog(
                    soft_bytes=None,
                    hard_bytes=parse_size(args.max_rss),
                    on_shed=lambda: cancel.cancel(
                        "RSS passed --max-rss with every droppable "
                        "store already shed"
                    ),
                ),
                result_cache=result_cache,
            ).start()
    except ValueError as e:
        print(f"tpusim: error: {e}", file=sys.stderr)
        return 2
    from tpusim.guard.cancel import OperationCancelled

    try:
        report = simulate_trace(
            args.trace, arch=args.arch, overlays=overlays, obs=obs,
            faults=faults, lenient=args.lenient_parse,
            validate=args.validate,
            result_cache=result_cache, workers=args.workers,
            pricing_backend=args.pricing_backend, cancel=cancel,
            compile_cache=compile_cache,
        )
    except OperationCancelled as e:
        # the clean refusal: nothing half-written, caches warm on disk
        print(f"tpusim simulate: cancelled: {e}", file=sys.stderr)
        return 3
    finally:
        if watchdog is not None:
            watchdog.stop()
    if args.power and report.power is not None:
        print(report.power.report_text())
    if obs is not None:
        from tpusim.obs import write_obs_dir

        with obs.span("export"):
            paths = write_obs_dir(args.obs_out, report, obs=obs)
        n_win = report.samples.num_windows if report.samples else 0
        w_cyc = report.samples.window_cycles if report.samples else 0
        print(f"obs: {n_win} windows x {w_cyc:.0f} cycles -> "
              + ", ".join(str(p) for p in paths.values()))
        s = report.samples
        if s is not None and s.pinned and s.coarsenings:
            print(f"obs: warning: requested window "
                  f"{args.obs_window_cycles:.0f} cycles exceeded the "
                  f"{s.max_windows}-window memory cap; coarsened "
                  f"{2 ** s.coarsenings}x to {s.window_cycles:.0f} cycles",
                  file=sys.stderr)
        # refresh the obs stats snapshot now that the simulate/export
        # spans have closed (the driver snapshotted mid-span)
        report.stats.update(obs.stats_dict(), prefix="obs_")
    report.print_report()
    if args.json:
        with open(args.json, "w") as f:
            f.write(report.stats.to_json() + "\n")
    return 0


def _parse_sets(items: list[str] | None) -> dict:
    """``--set k=v`` overrides (ints/floats/json parsed, else string)."""
    out = {}
    for item in items or []:
        k, _, v = item.partition("=")
        try:
            out[k] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            out[k] = v
    return out


def _cmd_capture(args: argparse.Namespace) -> int:
    from tpusim.models import get_workload
    from tpusim.tracer.capture import capture_to_dir

    wl = get_workload(args.workload)
    fn, wl_args = wl.build(**_parse_sets(args.set))
    capture_to_dir(
        args.out, fn, *wl_args, name=wl.name, launches=args.launches
    )
    if args.snapshot:
        from tpusim.tracer.capture import snapshot_buffers

        paths = snapshot_buffers(
            fn, *wl_args,
            out_dir=Path(args.out) / "checkpoint_files",
            launches=args.launches,
        )
        print(f"{len(paths)} buffer snapshots in {args.out}/checkpoint_files")
    print(f"trace written to {args.out}")
    return 0


def _cmd_run(args: argparse.Namespace) -> int:
    from tpusim.harness.runner import run_suite

    rows = run_suite(
        args.suite,
        [c for c in args.configs.split(",") if c],
        args.out,
        trace_root=args.traces,
        yaml_path=args.yaml,
        capture_missing=args.capture,
        parallel=args.parallel,
        power=args.power,
        obs=args.obs,
        monitor_interval_s=args.monitor_interval,
        result_cache=args.result_cache,
    )
    failed = rows.get("__failed__", {}).get("runs", [])
    ok = {k: v for k, v in rows.items() if k != "__failed__"}
    print(f"tpusim run: {len(ok)} runs scraped to {args.out}/stats.csv"
          + (f"; FAILED: {failed}" if failed else ""))
    return 1 if failed else 0


def _cmd_correlate_ops(args: argparse.Namespace) -> int:
    """Per-op silicon correlation for one workload on the live backend
    (plot-correlation.py at HLO-instruction grain)."""
    from tpusim.harness.correl_ops import (
        correlate_workload_ops, write_correl_ops,
    )
    from tpusim.models import get_workload

    wl = get_workload(args.workload)
    fn, wl_args = wl.build()
    corr = correlate_workload_ops(
        fn, wl_args, name=wl.name, arch=args.arch, iters=args.iters,
    )
    print(f"matched {len(corr.rows)} ops "
          f"({corr.matched_time_fraction:.0%} of device time); "
          f"sync-op weighted |error| = "
          f"{corr.sync_weighted_abs_error_pct:.1f}% "
          f"(all rows {corr.weighted_abs_error_pct:.1f}%)")
    for r in corr.worst(args.top):
        print(f"  {r.name:40s} {r.opcode:16s} "
              f"sim={r.sim_ns:10.0f}ns real={r.real_ns:10.0f}ns "
              f"err={r.error_pct:+7.1f}%")
    if args.json:
        p = write_correl_ops([corr], args.json)
        print(f"report written to {p}")
    return 0


def _cmd_derive_arch(args: argparse.Namespace) -> int:
    """Cross-generation overlay derivation (docs/V5P.md)."""
    import os

    from tpusim.timing.derive import derive_overlay

    # default to the directory load_config actually resolves overlays
    # from ($TPUSIM_TUNED_DIR or <repo>/configs) — a cwd-relative write
    # from elsewhere would silently never be applied
    base = os.environ.get("TPUSIM_TUNED_DIR") or str(
        Path(__file__).resolve().parents[1] / "configs"
    )
    out = args.out or str(
        Path(base) / f"{args.dst.lower()}.derived.flags"
    )
    lines = derive_overlay(args.src, args.dst, out_path=out)
    print(f"derive-arch: {len([l for l in lines if l.startswith('-')])} "
          f"knobs {args.src} -> {args.dst}, written to {out}")
    return 0


def _cmd_loo(args: argparse.Namespace) -> int:
    """Leave-one-out validation: the out-of-sample counterpart of the
    in-sample bench headline (VERDICT r4: 'the train set is the test
    set').  Writes reports/loo.json."""
    from tpusim.harness.refine import leave_one_out, load_per_op_rows

    fixture_dir = Path(args.fixtures)
    manifest_path = fixture_dir / "manifest.json"
    if not manifest_path.exists():
        print(f"no fixture manifest at {manifest_path}", file=sys.stderr)
        return 1
    manifest = json.loads(manifest_path.read_text())
    arch = args.arch or manifest.get("arch", "v5e")
    from tpusim.harness.refine import split_held_out

    # held-out full-model fixtures are already out-of-sample by
    # construction; LOO folds cover the training workloads only
    entries, per_op_rows, _ = split_held_out(
        manifest.get("workloads", []),
        load_per_op_rows(args.per_op_artifact),
    )
    doc = leave_one_out(
        arch, entries, fixture_dir,
        per_op_rows=per_op_rows,
        max_sweeps=args.sweeps, anchor_weight=args.anchor,
    )
    for f in doc["folds"]:
        print(f"  held-out {f['workload']:24s} "
              f"err={f['held_out_err_pct']:+8.2f}%  "
              f"(train objective {f['train_objective']:.2f})")
    print(f"loo: mean |held-out error| = {doc['mean_loo_abs_err_pct']}% "
          f"(worst {doc['worst_loo_abs_err_pct']}%) over "
          f"{len(doc['folds'])} folds")
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2))
        print(f"written to {out}")
    return 0


def _cmd_correl_regen(args: argparse.Namespace) -> int:
    """Regenerate the committed per-op correlation artifact offline: the
    CURRENT model replayed against the device durations stored in the
    existing artifact.  Run after any timing-model change (the fast tier
    rejects a stale committed artifact by model-version stamp)."""
    from tpusim.harness.correl_ops import regenerate_offline

    out = args.out or args.artifact
    doc = regenerate_offline(
        args.artifact, fixture_dir=args.fixtures, arch=args.arch,
        out_path=out, workers=args.workers,
    )
    print(
        f"correl-regen: {len(doc['workloads'])} workloads, "
        f"mean sync-op weighted |error| = "
        f"{doc['mean_sync_weighted_abs_error_pct']}% "
        f"(all rows {doc['mean_weighted_abs_error_pct']}%), "
        f"model_version {doc['model_version']} "
        f"-> {out}"
    )
    # the async-observable demonstration derives purely from the per-op
    # artifact + manifest; keep it in lockstep with the regen
    try:
        from tpusim.harness.async_observable import (
            analyze_async_observable,
        )

        demo = analyze_async_observable(
            out, Path(args.fixtures) / "manifest.json",
            fixture_dir=args.fixtures, arch=args.arch,
        )
        demo_path = Path(out).parent / "async_observable.json"
        demo_path.write_text(json.dumps(demo, indent=2))
        print(
            f"correl-regen: async-observable evidence refreshed "
            f"({demo['evidence']['occupancy_impossible_rows']} "
            f"occupancy-impossible rows) -> {demo_path}"
        )
    except Exception as e:
        print(f"correl-regen: async evidence FAILED: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
    return 0


def _cmd_profile(args: argparse.Namespace) -> int:
    """Pipeline self-profiling — where does the SIMULATOR spend host
    time (the breakdown behind the ``silicon_slowdown`` headline): a
    per-phase wall-clock/peak-RSS table from the obs span tree, plus the
    top-N costliest simulated ops."""
    import time

    from tpusim.obs import Instrumentation

    t_enter = time.perf_counter()
    # sample=False: the point is the breakdown of a NORMAL simulation's
    # host time — per-op sampler feeds would skew the very table printed
    obs = Instrumentation(sample=False)
    with obs.span("init"):
        from tpusim.sim.driver import simulate_trace

    report = simulate_trace(
        args.trace, arch=args.arch, obs=obs,
        result_cache=args.result_cache,
    )

    with obs.span("report"):
        totals = report.totals
        op_rows = sorted(
            totals.per_op_cycles.items(), key=lambda kv: -kv[1]
        )[:args.top]
    total_wall = time.perf_counter() - t_enter

    arch = report.arch_config
    print(f"tpusim profile: {args.trace}")
    print(f"  arch={report.config_name} devices={report.num_devices} "
          f"kernels={len(report.kernels)} sim_cycles={report.cycles:.4g}")
    print(f"  wall={total_wall:.3f}s sim_rate={report.sim_rate_kops:.1f} "
          f"kops/s silicon_slowdown="
          f"{report.silicon_slowdown(arch.clock_hz):.3g}")
    print()
    for line in obs.profile_lines(total_wall):
        print(line)
    if args.result_cache is not None:
        # cache effectiveness (tpusim.perf): hits mean skipped engine
        # walks — the whole point of profiling a cached replay
        s = report.stats
        print(f"  result cache: {s.get('cache_hits', 0):.0f} hits, "
              f"{s.get('cache_misses', 0):.0f} misses "
              f"({s.get('cache_disk_hits', 0):.0f} from disk, "
              f"{s.get('cache_disk_errors', 0):.0f} corrupt)")
    print()
    print(f"top {len(op_rows)} costliest ops "
          f"(of {totals.op_count} simulated):")
    print(f"  {'op':40s} {'opcode':18s} {'cycles':>12s} "
          f"{'count':>8s} {'% cycles':>9s}")
    # per_op_cycles accumulates across every launch on every replayed
    # device, so normalize by total device-time, not the pod makespan
    # (with the makespan a 4-device SPMD op would print >100%)
    device_time = sum(report.device_cycles.values()) or report.cycles
    for name, cyc in op_rows:
        opcode = totals.per_op_opcode.get(name, "?")
        count = totals.per_op_count.get(name, 0.0)
        pct = 100.0 * cyc / device_time if device_time else 0.0
        print(f"  {name[:40]:40s} {opcode[:18]:18s} {cyc:12.4g} "
              f"{count:8.0f} {pct:8.2f}%")
    return 0


def _cmd_faults(args: argparse.Namespace) -> int:
    """Single-link-failure sweep: price a collective (or replay a trace)
    once per dead link and report worst-case step-time inflation — the
    "what if link (2,3,0)->(3,3,0) is down?" answer, for every link."""
    from tpusim.faults.sweep import single_link_sweep, trace_step_sweep
    from tpusim.ici.topology import torus_for
    from tpusim.timing.config import load_config

    if getattr(args, "compile_cache", None):
        # activate before the trace loads so its parse defers
        from tpusim.fastpath.store import as_compile_store

        as_compile_store(args.compile_cache)
    cfg = load_config(arch=args.arch)
    arch_name = cfg.arch.name
    topo = torus_for(args.chips, arch_name)
    if args.trace:
        result = trace_step_sweep(
            args.trace, topo, arch=args.arch,
            max_scenarios=args.max_scenarios,
            workers=args.workers, result_cache=args.result_cache,
        )
        what = f"step time ({result.unit})"
    else:
        result = single_link_sweep(
            topo, cfg.arch.ici,
            payload_bytes=args.payload_mb * 1024 * 1024,
            kind=args.kind,
            workers=args.workers,
        )
        what = f"{args.kind} ({result.unit})"
    dims = "x".join(str(d) for d in topo.dims)
    print(f"tpusim faults: single-link-failure sweep on {arch_name} "
          f"{dims} torus ({topo.num_chips} chips, "
          f"{len(result.rows)} scenarios)")
    print(f"  healthy {what}: {result.healthy:.6g}")
    worst = result.worst
    if worst is not None:
        print(f"  worst-case inflation: {worst.inflation:.3f}x at link "
              f"{worst.label()}")
    top = sorted(result.rows, key=lambda r: -r.inflation)[: args.top]
    for r in top:
        print(f"    {r.label():24s} {r.value:.6g} "
              f"({r.inflation:.3f}x)")
    degraded = sum(1 for r in result.rows if r.inflation > 1.0 + 1e-12)
    print(f"  {degraded}/{len(result.rows)} scenarios inflate the "
          f"healthy baseline")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result.to_doc(), f, indent=2)
        print(f"  sweep report written to {args.json}")
    return 0


def _cmd_cache(args: argparse.Namespace) -> int:
    """Governance front end for a disk result-cache store
    (tpusim.guard): inspect it, verify + quarantine damaged records,
    garbage-collect it down to a quota, or clear it."""
    from tpusim.guard.store import (
        clear_store, format_size, gc_store, parse_size, scan_store,
        verify_store,
    )
    from tpusim.perf.cache import DEFAULT_CACHE_DIR

    d = Path(args.dir or DEFAULT_CACHE_DIR)
    if args.action != "stats" and not d.is_dir():
        print(f"tpusim cache: no store at {d}", file=sys.stderr)
        return 1
    if args.action == "stats":
        for line in scan_store(d).lines():
            print(line)
        return 0
    if args.action == "verify":
        res = verify_store(d)
        print(f"store: {d}")
        for line in res.lines():
            print(line)
        return 0
    if args.action == "gc":
        try:
            quota = parse_size(args.quota)
        except ValueError as e:
            print(f"tpusim cache: error: {e}", file=sys.stderr)
            return 2
        if quota is None and args.max_entries is None:
            print("tpusim cache: gc needs --quota and/or --max-entries "
                  "(otherwise there is nothing to collect down to)",
                  file=sys.stderr)
            return 2
        res = gc_store(d, quota_bytes=quota, max_entries=args.max_entries)
        print(f"store: {d}")
        print(f"  deleted: {res.deleted} record(s) "
              f"({format_size(res.freed_bytes)} freed)")
        print(f"  reaped: {res.tmp_reaped} abandoned tmp file(s)")
        print(f"  remaining: {res.remaining_entries} record(s) "
              f"({format_size(res.remaining_bytes)})")
        return 0
    # clear
    removed = clear_store(d)
    print(f"store: {d}\n  removed: {removed} file(s)")
    return 0


def _cmd_campaign(args: argparse.Namespace) -> int:
    """Monte-Carlo compound-fault campaign: sample N fault scenarios
    per pod slice from a seeded spec, price each through the shared
    result cache, and report inflation distributions + the SLO
    capacity answer.  Crash-safe: re-run with --resume to continue a
    killed campaign from its last journaled scenario."""
    from tpusim.analysis import ValidationError
    from tpusim.campaign import (
        JournalError, run_campaign, run_sharded_campaign,
    )
    from tpusim.guard.cancel import CancelToken, OperationCancelled

    progress = None
    if args.verbose:
        def progress(msg: str) -> None:
            print(f"  {msg}", file=sys.stderr)
    cancel = None
    if getattr(args, "max_wall_s", None):
        cancel = CancelToken.after(args.max_wall_s)
    nodes = getattr(args, "nodes", None)
    if nodes is not None and nodes > 1 and not args.out:
        print("tpusim campaign: --nodes needs --out DIR (the per-node "
              "journal shards and merged report live there)",
              file=sys.stderr)
        return 2
    try:
        if nodes is not None and nodes > 1:
            res = run_sharded_campaign(
                args.spec,
                trace_path=args.trace,
                out_dir=args.out,
                nodes=nodes,
                resume=args.resume,
                result_cache=args.result_cache,
                workers=args.workers,
                progress=progress,
                compile_cache=args.compile_cache,
                scenario_batch=(
                    False if args.no_scenario_batch else None),
            )
        else:
            res = run_campaign(
                args.spec,
                trace_path=args.trace,
                out_dir=args.out,
                resume=args.resume,
                result_cache=args.result_cache,
                workers=args.workers,
                progress=progress,
                cancel=cancel,
                compile_cache=args.compile_cache,
                scenario_batch=(
                    False if args.no_scenario_batch else None),
            )
    except OperationCancelled as e:
        hint = (
            f"re-run with --resume --out {args.out} to continue from "
            f"the last journaled scenario" if args.out
            else "pass --out DIR to make cancelled campaigns resumable"
        )
        print(f"tpusim campaign: cancelled: {e}; {hint}", file=sys.stderr)
        return 3
    except ValidationError as e:
        print(f"tpusim campaign: spec refused:\n{e}", file=sys.stderr)
        return 1
    except JournalError as e:
        # existing-journal / foreign-resume refusals are user errors
        # with a clear next step, not tracebacks
        print(f"tpusim campaign: {e}", file=sys.stderr)
        return 1
    doc = res.doc
    s = res.stats
    print(f"tpusim campaign: {doc['campaign']!r} seed={doc['seed']} "
          f"spec={doc['spec_hash']} trace={doc['trace']}")
    print(f"  {s.priced} scenario(s) priced, {s.resumed} resumed from "
          f"journal, {s.partitioned} partitioned, {s.failed} failed "
          f"({res.wall_seconds:.2f}s)")
    for sl in doc["slices"]:
        infl = sl["inflation"]
        line = (f"  {sl['label']:12s} {sl['scenarios']} scenarios, "
                f"partition rate {sl['partition_rate']:.1%}")
        if infl is not None:
            line += (f"; inflation p50 {infl['p50']:.3f}x "
                     f"p95 {infl['p95']:.3f}x p99 {infl['p99']:.3f}x "
                     f"max {infl['max']:.3f}x")
        slo = sl.get("slo")
        if slo is not None:
            at = slo["step_ms_at_percentile"]
            shown = f"{at:.3f}ms" if at is not None else "unbounded"
            line += (f"; p{slo['percentile']:g} step {shown} vs SLO "
                     f"{slo['step_time_ms']:g}ms -> "
                     f"{'MEETS' if slo['meets'] else 'MISSES'}")
        print(line)
    cap = doc.get("capacity")
    if cap is not None:
        best = cap["smallest_meeting_slice"]
        print(f"  capacity: smallest slice meeting "
              f"{cap['slo_step_time_ms']:g}ms @ p{cap['percentile']:g} "
              f"under sampled degradation: {best or 'NONE'}")
    for k, v in s.stats_dict().items():
        print(f"  {k} = {v:.0f}")
    bs = getattr(res, "batch_stats", None)
    if bs is not None and (bs.states or bs.lanes_cached or bs.skipped):
        # only-when-active: batch accounting prints only when the
        # lane-axis warm pass actually engaged this run
        for k, v in bs.stats_dict().items():
            print(f"  {k} = {v:.0f}")
    if res.report_path is not None:
        print(f"  report written to {res.report_path}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"  report also written to {args.json}")
    return 0


def _cmd_fleet(args: argparse.Namespace) -> int:
    """Traffic-driven fleet digital twin: a seeded discrete-event
    simulation of N serving pods under an open-loop arrival process
    with a campaign-style fault stream, governed by the serve daemon's
    admission policies — goodput/MFU/p99-vs-load curves, a pods-needed
    capacity frontier, energy per served request, and per-policy loss
    attribution.  Crash-safe: re-run with --resume to continue with
    zero journaled pricing intervals re-priced."""
    from tpusim.analysis import ValidationError
    from tpusim.fleet import FleetSpecError, JournalError, run_fleet
    from tpusim.guard.cancel import CancelToken, OperationCancelled

    progress = None
    if args.verbose:
        def progress(msg: str) -> None:
            print(f"  {msg}", file=sys.stderr)
    cancel = None
    if getattr(args, "max_wall_s", None):
        cancel = CancelToken.after(args.max_wall_s)
    try:
        res = run_fleet(
            args.spec,
            trace_path=args.trace,
            out_dir=args.out,
            resume=args.resume,
            result_cache=args.result_cache,
            workers=args.workers,
            progress=progress,
            cancel=cancel,
            compile_cache=args.compile_cache,
            scenario_batch=(
                False if args.no_scenario_batch else None),
        )
    except OperationCancelled as e:
        hint = (
            f"re-run with --resume --out {args.out} to continue from "
            f"the last journaled pricing interval" if args.out
            else "pass --out DIR to make cancelled fleet runs resumable"
        )
        print(f"tpusim fleet: cancelled: {e}; {hint}", file=sys.stderr)
        return 3
    except FleetSpecError as e:
        print(f"tpusim fleet: spec refused ({e.code}): {e}",
              file=sys.stderr)
        return 1
    except ValidationError as e:
        print(f"tpusim fleet: spec refused:\n{e}", file=sys.stderr)
        return 1
    except JournalError as e:
        print(f"tpusim fleet: {e}", file=sys.stderr)
        return 1
    doc = res.doc
    s = res.stats
    print(f"tpusim fleet: {doc['fleet']!r} seed={doc['seed']} "
          f"spec={doc['spec_hash']} trace={doc['trace']}")
    print(f"  {doc['pods']} pod(s) x {doc['chips']} {doc['arch']} "
          f"chips over {doc['horizon_s']:g}s; healthy step "
          f"{doc['healthy']['step_ms']:.3f}ms "
          f"({s.states_priced} state(s) priced, {s.states_resumed} "
          f"resumed, {s.pod_losses} pod loss(es); "
          f"{res.wall_seconds:.2f}s)")
    for r in doc["curve"]:
        lat = r["latency_ms"]
        line = (f"  {r['offered_rps']:8.1f} req/s -> "
                f"{r['goodput_rps']:8.1f} goodput, "
                f"mfu {r['mfu']:.3f}")
        if lat is not None:
            line += (f", p50 {lat['p50']:.1f}ms p99 {lat['p99']:.1f}ms")
        losses = r["losses"]
        line += (f"; lost: {losses['shed']} shed, "
                 f"{losses['deadline']} deadline, "
                 f"{losses['partition']} partition, "
                 f"{losses['restart']} restart")
        if r.get("slo") is not None:
            line += f" -> {'MEETS' if r['slo']['meets'] else 'MISSES'}"
        print(line)
    frontier = doc.get("frontier")
    if frontier is not None:
        for row in frontier["table"]:
            need = row["pods_needed"]
            shown = (str(need) if need is not None
                     else f"MORE THAN {frontier['max_pods']}")
            print(f"  frontier: {row['target_rps']:g} req/s at "
                  f"p{frontier['percentile']:g} <= "
                  f"{frontier['slo_latency_ms']:g}ms needs "
                  f"{shown} pod(s)")
    for r in doc["recovery"]:
        print(f"  recovery: pod {r['pod']} lost at {r['at_s']:.1f}s, "
              f"{r['survivors']} survivor(s), re-shard "
              f"{r['chosen'] or 'none'}, recover in "
              f"{r['time_to_recover_s']:.1f}s")
    for k, v in s.stats_dict().items():
        print(f"  {k} = {v:.0f}")
    bs = getattr(res, "batch_stats", None)
    if bs is not None and (bs.states or bs.lanes_cached or bs.skipped):
        # only-when-active: batch accounting prints only when the
        # lane-axis warm pass actually engaged this run
        for k, v in bs.stats_dict().items():
            print(f"  {k} = {v:.0f}")
    if res.report_path is not None:
        print(f"  report written to {res.report_path}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"  report also written to {args.json}")
    return 0


def _cmd_advise(args: argparse.Namespace) -> int:
    """Parallelism-strategy sweep & sharding advisor: price the
    slices x strategies x meshes cross-product of one traced workload
    through the shared engine-result cache and print the ranked
    step-time / ICI-bytes / HBM-residency / watts table with the
    recommended sharding."""
    from tpusim.advise import AdviseSpecError, run_advise
    from tpusim.analysis import ValidationError

    progress = None
    if args.verbose:
        def progress(msg: str) -> None:
            print(f"  {msg}", file=sys.stderr)
    try:
        res = run_advise(
            args.spec,
            trace_path=args.trace,
            result_cache=args.result_cache,
            workers=args.workers,
            progress=progress,
            compile_cache=args.compile_cache,
        )
    except AdviseSpecError as e:
        print(f"tpusim advise: spec refused ({e.code}): {e}",
              file=sys.stderr)
        return 1
    except ValidationError as e:
        print(f"tpusim advise: spec refused:\n{e}", file=sys.stderr)
        return 1
    doc = res.doc
    cap = doc["capture"]
    print(f"tpusim advise: {doc['advise']!r} spec={doc['spec_hash']} "
          f"trace={doc['trace']}")
    print(f"  capture: {cap['chips']} chips (dp={cap['dp']} "
          f"tp={cap['tp']}), {cap['collective_sites']['tp']} tp / "
          f"{cap['collective_sites']['dp']} dp / "
          f"{cap['collective_sites']['ep']} ep collective sites")
    header = (f"  {'#':>3s} {'cell':26s} {'strategy':8s} "
              f"{'step_ms':>9s} {'ici_mb':>8s} {'coll':>5s} "
              f"{'hbm_gib':>8s} {'exp%':>6s} {'watts':>7s} "
              f"{'pf/W':>7s} flags")
    print(header)
    shown = doc["cells"][: args.top] if args.top else doc["cells"]
    for r in shown:
        flags = []
        if not r["fits_hbm"]:
            flags.append("OOM")
        if r["slo_ok"] is False:
            flags.append("SLO-MISS")
        elif r["slo_ok"] is True:
            flags.append("slo-ok")
        w = f"{r['watts']:.1f}" if r["watts"] is not None else "-"
        pw = (f"{r['perf_per_watt']:.4f}"
              if r["perf_per_watt"] is not None else "-")
        ef = r.get("exposed_comm_frac")
        ef = f"{100.0 * ef:.1f}" if ef is not None else "-"
        print(f"  {r['rank']:3d} {r['cell']:26s} {r['strategy']:8s} "
              f"{r['step_ms']:9.4f} {r['ici_bytes'] / 1e6:8.2f} "
              f"{r['collectives_per_chip']:5d} "
              f"{r['hbm_resident_gib']:8.4f} {ef:>6s} {w:>7s} {pw:>7s} "
              f"{','.join(flags) or 'ok'}")
    for s in doc["skipped"]:
        print(f"      {s['cell']:26s} skipped: {s['reason']}")
    rec = doc["recommendation"]
    if rec is not None:
        print(f"  recommendation: {rec['cell']} "
              f"({rec['strategy']}, mesh {rec['mesh']}) at "
              f"{rec['step_ms']:.4f}ms/step")
    else:
        print("  recommendation: NONE (no feasible cell)")
    for k, v in res.stats.stats_dict().items():
        print(f"  {k} = {v:.0f}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"  report written to {args.json}")
    return 0


def _cmd_serve_front(args: argparse.Namespace) -> int:
    """serve v3: the multi-acceptor front tier — N acceptor processes
    sharing the listen port via SO_REUSEPORT (fd-passing fallback),
    each with its own HTTP parse + admission, against shared hot-cache
    / disk-cache / quarantine state."""
    import tempfile

    from tpusim.serve.front import FrontSupervisor, reuse_port_available

    ephemeral_quarantine = None
    if args.state_dir:
        quarantine_dir = str(Path(args.state_dir) / "quarantine")
    else:
        # no state dir: the shared quarantine is run-scoped — reclaim
        # it after the drain or restarts would litter /tmp forever
        ephemeral_quarantine = tempfile.mkdtemp(
            prefix="tpusim-serve-quar-"
        )
        quarantine_dir = ephemeral_quarantine
    settings = {
        "trace_root": args.trace_root,
        "max_inflight": args.max_inflight,
        "queue_depth": args.queue_depth,
        "deadline_s": args.deadline_s,
        "max_request_bytes": args.max_request_bytes,
        "result_cache": args.result_cache,
        "workers": args.workers or 1,
        "workers_per_acceptor": args.serve_workers,
        "min_workers": args.serve_min_workers,
        "job_workers": max(args.job_workers, 1),
        "drain_grace_s": args.drain_grace_s,
        "state_dir": args.state_dir,
        "verbose": args.verbose,
        "disk_quota": args.cache_quota,
        "max_rss": args.max_rss,
        "max_worker_rss": args.max_worker_rss,
        "compile_cache": args.compile_cache,
        "hot_cache": args.hot_cache,
        "strict_lint": args.strict_lint,
        "trace_requests": args.trace_requests,
        "access_log": args.access_log,
        "quarantine_dir": quarantine_dir,
        "join_addr": args.join,
        "join_min_nodes": args.cluster_min_nodes,
    }
    front = FrontSupervisor(
        settings, num_acceptors=args.acceptors,
        host=args.host, port=args.port,
    )
    try:
        front.start()
    except (OSError, RuntimeError, ValueError) as e:
        print(f"tpusim serve: error: {e}", file=sys.stderr)
        return 2
    front.install_signal_handlers()
    mode = "SO_REUSEPORT" if reuse_port_available() else "fd-passing"
    hot_note = ", hot-cache on" if args.hot_cache else ""
    join_note = f", join {args.join}" if args.join else ""
    print(f"tpusim serve: listening on http://{front.host}:{front.port} "
          f"(traces: {args.trace_root or 'inline only'}; "
          f"acceptors {args.acceptors} via {mode}"
          f"{hot_note}{join_note})",
          flush=True)
    front.wait_stopped()
    if ephemeral_quarantine is not None:
        import shutil

        shutil.rmtree(ephemeral_quarantine, ignore_errors=True)
    print("tpusim serve: drained, exiting", flush=True)
    return 0


def _cmd_serve(args: argparse.Namespace) -> int:
    """Long-running simulation service (tpusim.serve): JSON API over
    HTTP with hot traces, admission control, a process-wide shared
    engine-result cache, and SIGTERM drain."""
    from tpusim.serve.daemon import ServeDaemon

    if args.acceptors and args.acceptors > 0:
        return _cmd_serve_front(args)
    try:
        daemon = ServeDaemon(
            trace_root=args.trace_root,
            host=args.host,
            port=args.port,
            max_inflight=args.max_inflight,
            queue_depth=args.queue_depth,
            deadline_s=args.deadline_s,
            max_request_bytes=args.max_request_bytes,
            result_cache=args.result_cache,
            workers=args.workers or 1,
            serve_workers=args.serve_workers,
            min_workers=args.serve_min_workers,
            # clamp at 1: job_workers=0 is the in-process test hook
            # (accept + persist jobs without draining them); a served
            # daemon must always drain its queue
            job_workers=max(args.job_workers, 1),
            drain_grace_s=args.drain_grace_s,
            state_dir=args.state_dir,
            verbose=args.verbose,
            cache_quota=args.cache_quota,
            max_rss=args.max_rss,
            max_worker_rss=args.max_worker_rss,
            compile_cache=args.compile_cache,
            hot_cache=args.hot_cache,
            strict_lint=args.strict_lint,
            trace_requests=args.trace_requests,
            access_log=args.access_log,
            cluster_join=args.join,
            cluster_min_nodes=args.cluster_min_nodes,
        )
    except ValueError as e:
        # a quota/size typo must refuse loudly, not bound nothing
        print(f"tpusim serve: error: {e}", file=sys.stderr)
        return 2
    daemon.install_signal_handlers()
    daemon.start()
    # the bound port line is the startup contract: --port 0 asks the
    # kernel for a free port, and wrappers (tests, serve-smoke, shell
    # scripts) parse this line to find it
    workers_note = (
        f", serve-workers {args.serve_workers}" if args.serve_workers
        else ""
    )
    join_note = f", join {args.join}" if args.join else ""
    print(f"tpusim serve: listening on http://{daemon.host}:{daemon.port} "
          f"(traces: {args.trace_root or 'inline only'}; "
          f"max-inflight {args.max_inflight}, queue {args.queue_depth}"
          f"{workers_note}{join_note})",
          flush=True)
    daemon.wait_stopped()
    print("tpusim serve: drained, exiting", flush=True)
    return 0


def _cmd_serve_bench(args: argparse.Namespace) -> int:
    """Loadgen for the serving daemon: replay a fixture request mix at a
    target concurrency, report p50/p95/p99 + throughput, and compare the
    warm served path against the cold one-shot CLI."""
    from tpusim.serve.bench import (
        format_acceptor_sweep, format_report, format_sweep,
        run_acceptor_sweep, run_serve_bench, run_worker_sweep,
    )

    mix = None
    if args.trace:
        mix = [
            {"trace": t, "arch": args.arch}
            for t in args.trace
        ]
    if args.acceptor_sweep:
        try:
            counts = [int(c) for c in args.acceptor_sweep.split(",") if c]
        except ValueError:
            print(f"tpusim serve-bench: --acceptor-sweep wants a comma-"
                  f"separated int list, got {args.acceptor_sweep!r}")
            return 2
        doc = run_acceptor_sweep(
            acceptor_counts=counts,
            trace_root=args.trace_root,
            concurrency=args.concurrency,
            requests=args.requests,
            mix=mix,
            hot_cache=not args.no_hot_cache,
            serve_workers=args.serve_workers,
            reps=args.reps,
            loadgen_procs=args.loadgen_procs,
        )
        print(format_acceptor_sweep(doc))
        failed = any(
            leg["error_count"] for leg in doc["acceptor_sweep"]
        )
    elif args.worker_sweep:
        try:
            counts = [int(c) for c in args.worker_sweep.split(",") if c]
        except ValueError:
            print(f"tpusim serve-bench: --worker-sweep wants a comma-"
                  f"separated int list, got {args.worker_sweep!r}")
            return 2
        doc = run_worker_sweep(
            worker_counts=counts,
            trace_root=args.trace_root,
            concurrency=args.concurrency,
            requests=args.requests,
            mix=mix,
            cli_baseline=not args.no_cli_baseline,
            reps=args.reps,
        )
        print(format_sweep(doc))
        failed = any(
            leg["error_count"] for leg in doc["worker_sweep"]
        )
    else:
        doc = run_serve_bench(
            url=args.url,
            trace_root=args.trace_root,
            concurrency=args.concurrency,
            requests=args.requests,
            mix=mix,
            cli_baseline=not args.no_cli_baseline,
            serve_workers=args.serve_workers,
            reps=args.reps,
        )
        print(format_report(doc))
        failed = bool(doc.get("error_count") or doc.get("errors"))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"report written to {args.json}")
    return 1 if failed else 0


def _cmd_lint(args: argparse.Namespace) -> int:
    """Static trace/config/schedule analyzer — the `tpusim lint` front
    end over :mod:`tpusim.analysis` (stable TLxxx codes, file:line
    anchors, text or JSON output, nonzero exit on errors)."""
    from tpusim.analysis import (
        Severity, analyze_stats_keys, analyze_trace_dir, list_code_lines,
    )
    from tpusim.analysis.diagnostics import Diagnostics

    if args.list_codes:
        for line in list_code_lines():
            print(line)
        return 0
    if args.trace is None and not args.stats_keys \
            and not args.self_audit and not args.campaign \
            and not args.advise:
        print("tpusim lint: nothing to analyze — pass a trace dir, "
              "--campaign, --advise, --stats-keys, --self-audit, or "
              "--list-codes",
              file=sys.stderr)
        return 2
    if args.trace is None and (args.faults or args.config or args.arch
                               or args.perf):
        print("tpusim lint: --faults/--config/--arch/--perf need a trace "
              "dir (the declared topology and capture meta come from it)",
              file=sys.stderr)
        return 2

    diags = Diagnostics()
    perf_docs: list | None = [] if args.perf else None
    if args.trace is not None:
        analyze_trace_dir(
            args.trace, arch=args.arch, overlays=list(args.config or []),
            faults=args.faults, diags=diags, perf=args.perf,
            perf_report=perf_docs,
        )
    if args.campaign or args.advise:
        default_chips = 1
        if args.trace is not None:
            # size the primary slice the way the runners would
            from tpusim.analysis.trace_passes import load_parsed_trace

            default_chips = max(
                load_parsed_trace(args.trace).replay_devices, 1
            )
        if args.campaign:
            from tpusim.analysis import analyze_campaign_spec

            analyze_campaign_spec(
                args.campaign, diags=diags, default_chips=default_chips,
            )
        if args.advise:
            from tpusim.analysis import analyze_advise_spec

            analyze_advise_spec(
                args.advise, diags=diags, default_chips=default_chips,
            )
    if args.stats_keys:
        analyze_stats_keys(diags=diags)
    if args.self_audit:
        from tpusim.analysis import analyze_self_audit

        analyze_self_audit(diags=diags)

    if args.format == "json":
        if perf_docs is not None:
            # perf opt-in: the same document plus the per-module
            # critical-path docs (byte-identical without --perf)
            print(json.dumps(
                {**diags.to_doc(), "perf": perf_docs}, indent=2,
            ))
        else:
            print(diags.to_json())
    else:
        for line in diags.text_lines():
            print(line)
        print(f"tpusim lint: {diags.summary()}")
    gate = diags.has_errors or (
        args.strict and diags.count(Severity.WARNING) > 0
    )
    return 1 if gate else 0


def _cmd_perf_report(args: argparse.Namespace) -> int:
    """`tpusim perf-report TRACE` — the critical-path analyzer's ranked
    exposed-collective and slack tables, one section per module, plus
    any TL5xx findings (text or the raw perf document as JSON)."""
    from tpusim.analysis import analyze_trace_dir
    from tpusim.analysis.diagnostics import Diagnostics

    diags = Diagnostics()
    perf_docs: list = []
    analyze_trace_dir(
        args.trace, arch=args.arch, overlays=list(args.config or []),
        diags=diags, perf=True, perf_report=perf_docs,
    )
    if args.module is not None:
        perf_docs = [d for d in perf_docs if d["module"] == args.module]
        if not perf_docs:
            print(f"tpusim perf-report: no module {args.module!r} in "
                  f"{args.trace}", file=sys.stderr)
            return 2

    if args.format == "json":
        print(json.dumps(
            {**diags.to_doc(), "perf": perf_docs}, indent=2,
        ))
        return 1 if diags.has_errors else 0

    top = max(args.top, 1)
    for doc in perf_docs:
        print(f"== module {doc['module']} (entry {doc['entry']}) ==")
        print(f"  critical path : {doc['critical_path_cycles']:>14.1f} cycles")
        print(f"  serial bound  : {doc['serial_cycles']:>14.1f} cycles")
        print(f"  exposed coll  : {doc['exposed_collective_cycles']:>14.1f}"
              f" of {doc['collective_cycles']:.1f} priced cycles")
        exposures = [
            {**e, "comp": cname}
            for cname, cdoc in doc["computations"].items()
            for e in cdoc["exposures"]
        ]
        exposures.sort(key=lambda e: -e["exposed_cycles"])
        if exposures:
            print(f"  {'collective':28s} {'computation':20s} "
                  f"{'exposed':>10s} {'priced':>10s} {'movable':>10s} mode")
            for e in exposures[:top]:
                mode = "sync" if e["sync"] else "async"
                print(f"  {e['op'][:28]:28s} {e['comp'][:20]:20s} "
                      f"{e['exposed_cycles']:>10.1f} "
                      f"{e['priced_cycles']:>10.1f} "
                      f"{e['movable_cycles']:>10.1f} {mode}")
        rows = [
            {**o, "comp": cname}
            for cname, cdoc in doc["computations"].items()
            for o in cdoc["ops"]
        ]
        rows.sort(key=lambda o: -o["cycles"])
        if rows:
            print(f"  {'op':28s} {'computation':20s} {'cycles':>10s} "
                  f"{'slack':>10s} {'bound':>5s} crit")
            for o in rows[:top]:
                crit = "*" if o["critical"] else ""
                print(f"  {o['op'][:28]:28s} {o['comp'][:20]:20s} "
                      f"{o['cycles']:>10.1f} {o['slack']:>10.1f} "
                      f"{o['bound']:>5s} {crit}")
        print()
    perf_lines = [
        line for d, line in zip(diags.sorted_items(), diags.text_lines())
        if d.code.startswith("TL5")
    ]
    if perf_lines:
        print("findings:")
        for line in perf_lines:
            print(f"  {line}")
    return 1 if diags.has_errors else 0


def _cmd_info(args: argparse.Namespace) -> int:
    from tpusim.trace.format import load_trace

    pod = load_trace(args.trace)
    info = {
        "meta": pod.meta,
        "modules": {
            name: {
                "computations": len(m.computations),
                "entry_ops": len(m.entry.ops) if m.entry_name else 0,
                "collectives": len(m.collectives()),
                "num_devices": m.num_devices,
            }
            for name, m in pod.modules.items()
        },
        "devices": {
            d: len(t.commands) for d, t in pod.devices.items()
        },
    }
    print(json.dumps(info, indent=2, default=str))
    return 0


def _pick_module(pod, name):
    if name:
        return pod.modules[name]
    if not pod.modules:
        raise KeyError("trace has no modules")
    return pod.modules[sorted(pod.modules)[0]]


def _cmd_debug(args: argparse.Namespace) -> int:
    from tpusim.sim.debugger import Debugger
    from tpusim.timing.config import load_config
    from tpusim.trace.format import load_trace

    pod = load_trace(args.trace)
    mod = _pick_module(pod, args.module)
    Debugger(mod, load_config(arch=args.arch)).repl()
    return 0


def _cmd_timeline(args: argparse.Namespace) -> int:
    from tpusim.sim.traceviz import write_chrome_trace
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace

    if args.obs_window_cycles and not args.counters:
        print("tpusim: error: --obs-window-cycles requires --counters",
              file=sys.stderr)
        return 2
    pod = load_trace(args.trace)
    mod = _pick_module(pod, args.module)
    cfg = load_config(arch=args.arch)
    obs = None
    if args.counters:
        from tpusim.obs import Instrumentation

        obs = Instrumentation(window_cycles=args.obs_window_cycles)
    res = Engine(cfg, record_timeline=True, obs=obs).run(mod)
    extra = None
    if obs is not None and res.samples is not None:
        from tpusim.obs import counter_track_events, window_rows

        rows = window_rows(res.samples, cfg.arch)
        extra = counter_track_events(rows, cfg.arch.clock_hz)
    write_chrome_trace(
        res, cfg.arch, args.out, process_name=mod.name, extra_events=extra
    )
    n_extra = f" + {len(extra)} counter samples" if extra else ""
    print(f"chrome trace ({len(res.timeline)} events{n_extra}) written to "
          f"{args.out}; open in chrome://tracing or ui.perfetto.dev")
    return 0


def _cmd_aerial(args: argparse.Namespace) -> int:
    from tpusim.sim.interval import (
        render_text_lanes, sample_intervals, write_interval_log,
    )
    from tpusim.timing.config import load_config
    from tpusim.timing.engine import Engine
    from tpusim.trace.format import load_trace

    pod = load_trace(args.trace)
    mod = _pick_module(pod, args.module)
    cfg = load_config(arch=args.arch)
    cap = 2_000_000
    res = Engine(cfg, record_timeline=True, max_timeline_events=cap).run(mod)
    if len(res.timeline) >= cap:
        print(f"warning: timeline capped at {cap} events; "
              "the view covers only the first part of the run",
              file=sys.stderr)
    sample = args.sample or cfg.stat_sample_cycles
    samples = sample_intervals(res, sample)
    power = None
    if args.power:
        from tpusim.power.model import power_timeline

        power = power_timeline(
            samples, cfg.arch, cfg.arch.name, dvfs_scale=cfg.dvfs_scale
        )
    if args.gz:
        meta = {"module": mod.name, "arch": cfg.arch.name,
                "sample_cycles": sample}
        if power is not None:
            meta["power_watts"] = [round(w["watts"], 2) for w in power]
        write_interval_log(samples, args.gz, meta=meta)
        print(f"interval log written to {args.gz}")
    print(render_text_lanes(samples), end="")
    if power:
        from tpusim.sim.interval import render_scalar_lane

        watts = [w["watts"] for w in power]
        avg = sum(watts) / len(watts)
        print(render_scalar_lane(
            watts, "power",
            suffix=f" avg {avg:.0f} W peak {max(watts):.0f} W",
        ), end="")
    return 0


def _cmd_tune(args: argparse.Namespace) -> int:
    from tpusim.harness.tuner import tune, tune_power, write_overlay

    import dataclasses

    if args.power:
        path = tune_power(args.arch or "v5e", out_dir=args.out)
        print(f"fitted power coefficients written to {path}")
        return 0
    result = tune(args.arch)
    print(json.dumps(dataclasses.asdict(result), indent=2))
    out = args.out
    if out == "auto":
        # the canonical location load_config applies by default — running
        # the tuner IS closing the loop (tested-cfgs, util/tuner/tuner.py)
        from pathlib import Path

        out = (
            Path(__file__).resolve().parents[1]
            / "configs" / f"{result.base_arch}.tuned.flags"
        )
        out.parent.mkdir(parents=True, exist_ok=True)
    if out:
        write_overlay(result, out)
        print(f"overlay written to {out} (load_config applies "
              f"configs/<arch>.tuned.flags automatically)")
    return 0


def _cmd_refine(args: argparse.Namespace) -> int:
    """Offline replay refinement against the committed silicon fixtures
    — no chip needed (``tune`` is the on-chip microbench pass; this is
    the joint fit on the objective bench reports)."""
    import math

    from tpusim.harness.refine import (
        load_per_op_rows, refine_arch_on_fixtures,
    )

    fixture_dir = Path(args.fixtures)
    manifest_path = fixture_dir / "manifest.json"
    if not manifest_path.exists():
        print(f"no fixture manifest at {manifest_path}", file=sys.stderr)
        return 1
    manifest = json.loads(manifest_path.read_text())
    arch = args.arch or manifest.get("arch", "v5e")
    seed = [args.seed] if args.seed else []
    # held-out full-model fixtures are validation, never training
    from tpusim.harness.refine import split_held_out

    train_entries, per_op_rows, _ = split_held_out(
        manifest.get("workloads", []),
        {} if args.no_per_op else load_per_op_rows(args.per_op_artifact),
    )
    result = refine_arch_on_fixtures(
        arch, train_entries, fixture_dir,
        base_overlays=seed, max_sweeps=args.sweeps,
        per_op_rows=per_op_rows, anchor_weight=args.anchor,
    )
    if not math.isfinite(result.start_err_pct):
        # no fixture replayed: an "overlay" of untouched preset values
        # must not masquerade as a fit
        print(
            f"no fixture workload replayed from {fixture_dir}; "
            f"nothing to refine", file=sys.stderr,
        )
        return 1
    print(f"fixture replay objective: {result.start_err_pct:.2f} -> "
          f"{result.final_err_pct:.2f} "
          f"({result.evals} evals, {result.sweeps} sweeps; "
          f"{result.replayed}/{result.total} fixtures)")
    if result.parts:
        print("  parts: " + ", ".join(
            f"{k}={v:.2f}" for k, v in sorted(result.parts.items())
        ))
    for k, v in sorted(result.changed.items()):
        print(f"  {k} -> {v:.6g}")
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        lines = result.overlay_lines(manifest.get("device_kind", ""))
        if args.seed:
            # the search ran WITH the seed's non-knob fits applied
            # (host_bandwidth, ici.link_bandwidth ...); the emitted
            # overlay must carry them or it won't reproduce the
            # reported error — same merge bench.py does
            lines += [
                ln for ln in Path(args.seed).read_text().splitlines()
                if ln.startswith("-") and not any(
                    ln.startswith(f"-arch.{k} ") for k in result.values
                )
            ]
        out.write_text("\n".join(lines) + "\n")
        print(f"overlay written to {out}")
    return 0


def _cmd_bbv(args: argparse.Namespace) -> int:
    from tpusim.tools.bbv import compute_bbv, write_simpoint_bb
    from tpusim.trace.format import load_trace

    pod = load_trace(args.trace)
    mod = _pick_module(pod, args.module)
    res = compute_bbv(mod, interval_ops=args.interval)
    print(f"{res.num_intervals} intervals x {args.interval} ops, "
          f"{len(res.dims)} opcode dims")
    if args.out:
        write_simpoint_bb(res, args.out)
        print(f"SimPoint frequency vectors written to {args.out}")
    return 0


def _cmd_occupancy(args: argparse.Namespace) -> int:
    from tpusim.timing.config import load_config
    from tpusim.tools.occupancy import occupancy_report
    from tpusim.trace.format import load_trace

    pod = load_trace(args.trace)
    mod = _pick_module(pod, args.module)
    cfg = load_config(arch=args.arch)
    report = occupancy_report(mod, cfg.arch)
    for line in report.summary_lines(limit=args.limit):
        print(line)
    return 0


def _cmd_stats_diff(args: argparse.Namespace) -> int:
    """Compare two scraped run trees — the merge-stats compare role
    (two builds / two configs over the same app list)."""
    from tpusim.harness.scrape import diff_stats, scrape_run_dirs

    old = scrape_run_dirs(args.old)
    new = scrape_run_dirs(args.new)
    diffs = diff_stats(old, new, rel_tol=args.rel_tol)
    if not diffs:
        print("no differences")
        return 0
    for run in sorted(diffs):
        if run in ("__only_old__", "__only_new__"):
            side = "only in OLD" if run == "__only_old__" else "only in NEW"
            for r in sorted(diffs[run]):
                print(f"{r}: {side}")
            continue
        for stat, (a, b) in sorted(diffs[run].items()):
            print(f"{run}: {stat} {a} -> {b}")
    return 1 if args.check else 0


def _cmd_workloads(args: argparse.Namespace) -> int:
    from tpusim.models import list_workloads

    for wl in sorted(list_workloads(), key=lambda w: (w.suite, w.name)):
        print(f"{wl.suite:10s} {wl.name:26s} devices={wl.num_devices:<3d} "
              f"{wl.description}")
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpusim")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("simulate", help="replay a stored trace")
    ps.add_argument("trace")
    ps.add_argument("--arch", default=None, help="arch preset (v4/v5e/v5p/v6e)")
    ps.add_argument("--config", action="append",
                    help="overlay flag file(s), applied in order")
    ps.add_argument("--json", default=None, help="also write stats JSON here")
    ps.add_argument("--power", action="store_true",
                    help="enable the TPUWattch power model")
    ps.add_argument("--resume-kernel", type=int, default=0,
                    help="fast-forward the first N kernel launches")
    ps.add_argument("--checkpoint-kernel", type=int, default=0,
                    help="stop the replay after N kernel launches")
    ps.add_argument("--resume-op", type=int, default=0,
                    help="fast-forward the first N entry ops inside each "
                         "module replay (sub-kernel resume)")
    ps.add_argument("--checkpoint-op", type=int, default=0,
                    help="stop each module replay after N entry ops "
                         "(sub-kernel checkpoint; drains in-flight async)")
    ps.add_argument("--network-mode", default=None,
                    choices=["analytic", "detailed"],
                    help="ICI model: closed-form schedules or per-packet "
                         "torus network sim (the -network_mode equivalent)")
    ps.add_argument("--obs-out", default=None, metavar="DIR",
                    help="enable the observability layer and write "
                         "samples.jsonl + trace.json (Perfetto counter "
                         "tracks) + metrics.prom here")
    ps.add_argument("--obs-window-cycles", type=float, default=0.0,
                    help="cycle-window size for the sampler "
                         "(0 = auto: self-coarsening to a bounded "
                         "window count)")
    ps.add_argument("--faults", default=None, metavar="SCHEDULE.json",
                    help="fault schedule (dead/degraded ICI links, chip "
                         "stragglers, HBM throttles — see "
                         "ci/faults_schema.json); stamps faults_* stats")
    ps.add_argument("--lenient-parse", action="store_true",
                    help="skip malformed HLO lines with a counted "
                         "warning instead of raising mid-file (salvage "
                         "mode for damaged captures)")
    ps.add_argument("--pricing-backend", default=None,
                    choices=["auto", "serial", "vectorized", "native"],
                    help="pin the tpusim.fastpath pricing backend (all "
                         "byte-identical; default auto = fastest "
                         "available; also via $TPUSIM_PRICING_BACKEND) "
                         "and stamp fastpath_* stats on the report")
    ps.add_argument("--workers", type=int, default=None, metavar="N",
                    help="fan module pricing over N processes "
                         "(default: $TPUSIM_WORKERS, else serial); "
                         "bit-identical to the serial replay")
    ps.add_argument("--result-cache", nargs="?", const=True, default=None,
                    metavar="DIR",
                    help="memoize engine results on disk (tpusim.perf; "
                         "default dir .tpusim_cache/): a warm re-run "
                         "prices nothing and reproduces the same stats "
                         "byte-for-byte; stamps cache_* stats")
    ps.add_argument("--cache-quota", default=None, metavar="SIZE",
                    help="bound the disk result cache (e.g. 512M, 2G); "
                         "implies --result-cache and garbage-collects "
                         "least-recently-used records past the quota "
                         "(tpusim.guard)")
    ps.add_argument("--compile-cache", nargs="?", const=True, default=None,
                    metavar="DIR",
                    help="durable compiled-module tier (tpusim.fastpath."
                         "store; default dir .tpusim_cache/, beside the "
                         "result records): compiled pricing columns "
                         "persist across processes, so a warm store "
                         "prices a cold run from mmapped columns with "
                         "zero Python IR construction; stamps "
                         "fastpath_* stats")
    ps.add_argument("--max-wall-s", type=float, default=None, metavar="S",
                    help="cooperative wall-clock budget: the replay "
                         "cancels cleanly at the next command/op "
                         "boundary once S seconds elapse (exit 3)")
    ps.add_argument("--max-rss", default=None, metavar="SIZE",
                    help="memory watchdog hard threshold (e.g. 4G): "
                         "past it the degradation ladder sheds caches "
                         "and finally cancels the run cleanly instead "
                         "of meeting the OOM-killer")
    ps.add_argument("--validate", nargs="?", const="on", default=None,
                    choices=["on", "strict"], metavar="on|strict",
                    help="pre-flight the trace/config/schedule through "
                         "the static analyzer (tpusim lint) and refuse "
                         "to replay on error-level diagnostics; "
                         "--validate=strict also refuses on warnings. "
                         "NOTE: bare --validate greedily binds a "
                         "following positional, so place it AFTER the "
                         "trace path or use the = form")
    ps.set_defaults(fn=_cmd_simulate)

    pc = sub.add_parser("capture", help="capture a registered workload")
    pc.add_argument("workload")
    pc.add_argument("out")
    pc.add_argument("--launches", type=int, default=1)
    pc.add_argument("--snapshot", action="store_true",
                    help="also dump every output buffer per launch to "
                         "<out>/checkpoint_files/ (silicon checkpoints)")
    pc.add_argument("--set", action="append", metavar="K=V",
                    help="workload builder parameter override(s)")
    pc.set_defaults(fn=_cmd_capture)

    pr = sub.add_parser(
        "run",
        help="run a benchmark suite across configs (run_simulations.py): "
             "fabricate run dirs, execute, monitor, scrape to stats.csv",
    )
    pr.add_argument("-B", "--suite", required=True,
                    help="suite name (built-in registry group or YAML)")
    pr.add_argument("-C", "--configs", required=True,
                    help="comma-separated arch[+named] list, e.g. "
                         "v5p,v5e,v5p+dcn")
    pr.add_argument("-o", "--out", default="runs",
                    help="output root (run dirs, jobs.json, stats.csv)")
    pr.add_argument("--traces", default=None,
                    help="trace root (default <out>/traces)")
    pr.add_argument("--yaml", default=None,
                    help="suite/config YAML (define-all-apps equivalent)")
    pr.add_argument("--capture", action="store_true",
                    help="capture missing traces on the live backend first")
    pr.add_argument("--parallel", type=int, default=None)
    pr.add_argument("--power", action="store_true")
    pr.add_argument("--obs", action="store_true",
                    help="write per-run obs exports (samples.jsonl, "
                         "trace.json, metrics.prom) under each run dir")
    pr.add_argument("--monitor-interval", type=float, default=10.0)
    pr.add_argument("--result-cache", default=None, metavar="DIR",
                    help="shared on-disk engine-result cache every "
                         "simulate cell mounts (repeat cells price "
                         "nothing)")
    pr.set_defaults(fn=_cmd_run)

    pco = sub.add_parser(
        "correlate-ops",
        help="per-op sim-vs-silicon correlation for a workload (live)",
    )
    pco.add_argument("workload")
    pco.add_argument("--arch", default=None)
    pco.add_argument("--iters", type=int, default=3)
    pco.add_argument("--top", type=int, default=10)
    pco.add_argument("--json", default=None, help="write correl_ops.json")
    pco.set_defaults(fn=_cmd_correlate_ops)

    pda = sub.add_parser(
        "derive-arch",
        help="derive a generation's overlay from another's calibration "
             "(transferable TensorCore knobs over published absolutes)",
    )
    pda.add_argument("--src", default="v5e")
    pda.add_argument("--dst", default="v5p")
    pda.add_argument("--out", default=None,
                     help="default: configs/<dst>.derived.flags")
    pda.set_defaults(fn=_cmd_derive_arch)

    plo = sub.add_parser(
        "loo",
        help="leave-one-out validation of the refinement procedure "
             "(offline; one preset-seeded refit per held-out workload)",
    )
    plo.add_argument("--fixtures", default="reports/silicon")
    plo.add_argument("--arch", default=None)
    plo.add_argument(
        "--per-op-artifact", default="reports/correl_ops.json",
        help="per-op artifact whose device rows join each fold's "
             "objective (held-out workload excluded)",
    )
    plo.add_argument("--sweeps", type=int, default=6)
    plo.add_argument(
        "--anchor", type=float, default=1.0,
        help="quadratic penalty on relative knob drift from the preset "
             "(physical-prior regularization; 0 disables)",
    )
    plo.add_argument("--out", default="reports/loo.json")
    plo.set_defaults(fn=_cmd_loo)

    pcr = sub.add_parser(
        "correl-regen",
        help="regenerate the per-op correlation artifact offline "
             "(current model vs the artifact's stored device durations)",
    )
    pcr.add_argument("--artifact", default="reports/correl_ops.json")
    pcr.add_argument("--fixtures", default="reports/silicon")
    pcr.add_argument("--arch", default="v5e")
    pcr.add_argument("--out", default=None,
                     help="output path (default: overwrite --artifact)")
    pcr.add_argument("--workers", type=int, default=None, metavar="N",
                     help="fan per-workload replays over N processes "
                          "(byte-identical artifact)")
    pcr.set_defaults(fn=_cmd_correl_regen)

    pp = sub.add_parser(
        "profile",
        help="self-profile one replay: per-phase wall-clock/peak-RSS "
             "table (parse/cost/engine/ici/power) + top costliest ops",
    )
    pp.add_argument("trace")
    pp.add_argument("--arch", default=None)
    pp.add_argument("--top", type=int, default=10,
                    help="how many costliest ops to print")
    pp.add_argument("--result-cache", nargs="?", const=True, default=None,
                    metavar="DIR",
                    help="profile a cached replay (tpusim.perf) and "
                         "report cache effectiveness")
    pp.set_defaults(fn=_cmd_profile)

    pfa = sub.add_parser(
        "faults",
        help="single-link-failure sweep: worst-case step-time inflation "
             "over every dead-link scenario (degraded-pod what-ifs)",
    )
    pfa.add_argument("--arch", default="v5p")
    pfa.add_argument("--chips", type=int, default=64,
                     help="pod size to sweep (default 64 = v5p 4x4x4)")
    pfa.add_argument("--kind", default="all-reduce",
                     help="collective to price per scenario "
                          "(analytic sweep)")
    pfa.add_argument("--payload-mb", type=float, default=64.0,
                     help="per-chip payload for the analytic sweep")
    pfa.add_argument("--trace", default=None,
                     help="replay this trace per scenario instead "
                          "(end-to-end step-time inflation; slower)")
    pfa.add_argument("--max-scenarios", type=int, default=16,
                     help="scenario cap for --trace sweeps")
    pfa.add_argument("--top", type=int, default=5,
                     help="how many worst links to print")
    pfa.add_argument("--json", default=None,
                     help="write the full sweep report here")
    pfa.add_argument("--workers", type=int, default=None, metavar="N",
                     help="fan per-link scenarios over N processes "
                          "(default: $TPUSIM_WORKERS, else serial); "
                          "rows merge in link order — byte-identical "
                          "to the serial sweep")
    pfa.add_argument("--result-cache", nargs="?", const=True, default=None,
                     metavar="DIR",
                     help="share one engine-result cache across the "
                          "sweep's replays (--trace sweeps; in-memory "
                          "sharing is always on, this adds the disk "
                          "tier)")
    pfa.add_argument("--compile-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="durable compiled-module tier: every sweep "
                          "scenario shares one compile, persisted "
                          "across runs (tpusim.fastpath.store)")
    pfa.set_defaults(fn=_cmd_faults)

    pca = sub.add_parser(
        "cache",
        help="govern a disk result-cache store (tpusim.guard): stats / "
             "verify (quarantine damaged records) / gc (LRU-collect to "
             "a quota) / clear",
    )
    pca.add_argument("action", choices=["stats", "verify", "gc", "clear"],
                     help="stats: one scan summary; verify: integrity "
                          "sweep quarantining corrupt/stale-format "
                          "records; gc: delete least-recently-used "
                          "records down to --quota/--max-entries; "
                          "clear: remove everything incl. quarantine")
    pca.add_argument("--dir", default=None, metavar="DIR",
                     help="store directory (default: the "
                          "--result-cache default, .tpusim_cache/)")
    pca.add_argument("--quota", default=None, metavar="SIZE",
                     help="gc: byte quota to collect down to "
                          "(e.g. 512M, 2G)")
    pca.add_argument("--max-entries", type=int, default=None, metavar="N",
                     help="gc: record-count quota to collect down to")
    pca.set_defaults(fn=_cmd_cache)

    pcm = sub.add_parser(
        "campaign",
        help="seeded Monte-Carlo compound-fault campaign: N sampled "
             "degradation scenarios per pod slice -> inflation "
             "distributions (p50/p95/p99/max), partition rate, energy "
             "deltas, and the smallest slice meeting a step-time SLO",
    )
    pcm.add_argument("spec", help="campaign spec JSON (see "
                                  "docs/ARCHITECTURE.md)")
    pcm.add_argument("--trace", required=True,
                     help="trace directory the campaign replays")
    pcm.add_argument("--out", default=None, metavar="DIR",
                     help="campaign state dir: crash-safe journal.jsonl "
                          "+ report.json (required for --resume)")
    pcm.add_argument("--resume", action="store_true",
                     help="continue a killed campaign from the last "
                          "journaled scenario in --out (completed "
                          "scenarios are never re-priced)")
    pcm.add_argument("--workers", type=int, default=None, metavar="N",
                     help="fan each replay's module pricing over N "
                          "processes (scenarios run serially so the "
                          "journal stays a true prefix)")
    pcm.add_argument("--result-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="share the engine-result cache on disk "
                          "(in-memory sharing across scenarios is "
                          "always on; this persists it across runs)")
    pcm.add_argument("--compile-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="durable compiled-module tier: a fresh "
                          "campaign over an already-compiled trace "
                          "parses and compiles nothing "
                          "(tpusim.fastpath.store)")
    pcm.add_argument("--nodes", type=int, default=None, metavar="N",
                     help="shard scenario batches across N node "
                          "processes by journal signature (requires "
                          "--out): each shard appends to its own "
                          "fsync'd journal, the coordinator merges by "
                          "(slice, index) into a report byte-identical "
                          "to a single-node run; a killed shard "
                          "resumes ELSEWHERE with --resume, re-pricing "
                          "nothing")
    pcm.add_argument("--max-wall-s", type=float, default=None, metavar="S",
                     help="cooperative wall-clock budget: the campaign "
                          "cancels at the next scenario boundary with "
                          "everything completed journaled — --resume "
                          "re-prices nothing (exit 3)")
    pcm.add_argument("--no-scenario-batch", action="store_true",
                     help="disable scenario-batched pricing (the "
                          "lane-axis batch pass that warms the result "
                          "cache per slice; report bytes are identical "
                          "either way — this only trades speed for a "
                          "pure per-state walk)")
    pcm.add_argument("--json", default=None,
                     help="also write the report document here")
    pcm.add_argument("--verbose", action="store_true",
                     help="per-scenario progress on stderr")
    pcm.set_defaults(fn=_cmd_campaign)

    pfl = sub.add_parser(
        "fleet",
        help="traffic-driven fleet digital twin: N simulated serving "
             "pods under an open-loop arrival process with a seeded "
             "fault stream and the serve daemon's admission policies "
             "-> goodput/MFU/p99-vs-load curves, a pods-needed "
             "capacity frontier, energy per request, and per-policy "
             "loss attribution",
    )
    pfl.add_argument("spec", help="fleet spec JSON (see "
                                  "docs/ARCHITECTURE.md)")
    pfl.add_argument("--trace", required=True,
                     help="trace directory the fleet serves")
    pfl.add_argument("--out", default=None, metavar="DIR",
                     help="fleet state dir: crash-safe journal.jsonl "
                          "+ report.json (required for --resume)")
    pfl.add_argument("--resume", action="store_true",
                     help="continue a killed fleet run from its "
                          "journal in --out (journaled pricing "
                          "intervals are never re-priced)")
    pfl.add_argument("--workers", type=int, default=None, metavar="N",
                     help="fan each replay's module pricing over N "
                          "processes (states price serially so the "
                          "journal stays a true prefix)")
    pfl.add_argument("--result-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="share the engine-result cache on disk "
                          "(in-memory sharing across states is "
                          "always on; this persists it across runs)")
    pfl.add_argument("--compile-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="durable compiled-module tier: a fresh "
                          "fleet run over an already-compiled trace "
                          "parses and compiles nothing "
                          "(tpusim.fastpath.store)")
    pfl.add_argument("--max-wall-s", type=float, default=None, metavar="S",
                     help="cooperative wall-clock budget: the run "
                          "cancels at the next pricing/cell boundary "
                          "with everything priced so far journaled — "
                          "--resume re-prices nothing (exit 3)")
    pfl.add_argument("--no-scenario-batch", action="store_true",
                     help="disable scenario-batched pricing (the "
                          "lane-axis batch pass that warms the result "
                          "cache per pod; report bytes are identical "
                          "either way — this only trades speed for a "
                          "pure per-state walk)")
    pfl.add_argument("--json", default=None,
                     help="also write the report document here")
    pfl.add_argument("--verbose", action="store_true",
                     help="per-state/per-cell progress on stderr")
    pfl.set_defaults(fn=_cmd_fleet)

    pad = sub.add_parser(
        "advise",
        help="parallelism-strategy sweep & sharding advisor: price the "
             "slices x strategies x meshes cross-product of one traced "
             "workload on modeled tori -> ranked step-time/ICI-bytes/"
             "HBM/watts table + recommended sharding",
    )
    pad.add_argument("spec", help="advise spec JSON (see "
                                  "docs/ARCHITECTURE.md)")
    pad.add_argument("--trace", required=True,
                     help="trace directory of the workload to advise on")
    pad.add_argument("--top", type=int, default=0,
                     help="print only the best N cells (0 = all)")
    pad.add_argument("--workers", type=int, default=None, metavar="N",
                     help="fan each cell's module pricing over N "
                          "processes (cells run serially so the report "
                          "is byte-identical)")
    pad.add_argument("--result-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="share the engine-result cache on disk "
                          "(in-memory sharing across cells is always "
                          "on; this persists it — a warm re-run prices "
                          "zero engine walks)")
    pad.add_argument("--compile-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="durable compiled-module tier: cell clones "
                          "compile once ever per (content, config) "
                          "(tpusim.fastpath.store)")
    pad.add_argument("--json", default=None,
                     help="also write the ranked report document here")
    pad.add_argument("--verbose", action="store_true",
                     help="per-cell progress on stderr")
    pad.set_defaults(fn=_cmd_advise)

    psv = sub.add_parser(
        "serve",
        help="simulation-as-a-service daemon: JSON API (simulate/lint/"
             "sweep/campaign/advise/fleet/jobs/healthz/metrics) with "
             "hot traces, admission control, shared result cache, "
             "SIGTERM drain",
    )
    psv.add_argument("--host", default="127.0.0.1")
    psv.add_argument("--port", type=int, default=8642,
                     help="listen port (0 = ask the kernel; the bound "
                          "port is printed on startup)")
    psv.add_argument("--trace-root", default=None, metavar="DIR",
                     help="directory whose subdirectories are servable "
                          "traces (requests name them; no other "
                          "filesystem paths are reachable)")
    psv.add_argument("--max-inflight", type=int, default=4,
                     help="concurrent requests actually executing")
    psv.add_argument("--queue-depth", type=int, default=16,
                     help="requests allowed to wait for a slot before "
                          "new arrivals get 429 + Retry-After")
    psv.add_argument("--deadline-s", type=float, default=30.0,
                     help="default per-request deadline (a queued "
                          "request past it gets 504; requests may "
                          "lower/raise it via deadline_ms, capped)")
    psv.add_argument("--max-request-bytes", type=int,
                     default=8 * 1024 * 1024,
                     help="request-body cap; larger bodies get 413 "
                          "before being read")
    psv.add_argument("--result-cache", nargs="?", const=True, default=None,
                     metavar="DIR",
                     help="add the disk tier to the shared engine-result "
                          "cache (default dir .tpusim_cache/); the "
                          "in-memory tier is always on — sharing across "
                          "requests is the service's point")
    psv.add_argument("--workers", type=int, default=None, metavar="N",
                     help="per-request pricing workers (default 1: "
                          "process pools and threaded serving don't mix "
                          "unless you know your start method)")
    psv.add_argument("--serve-workers", type=int, default=0, metavar="N",
                     help="serve v2: pre-forked supervised worker "
                          "processes for sync pricing — crash isolation "
                          "(one bad request costs one worker), deadline "
                          "kills, poison-request quarantine, content-"
                          "hash-affinity dispatch (default 0: the "
                          "single-process path)")
    psv.add_argument("--serve-min-workers", type=int, default=1,
                     metavar="N",
                     help="live-worker floor: below it the daemon sheds "
                          "load (503 + Retry-After) instead of queueing "
                          "into a dead pool")
    psv.add_argument("--job-workers", type=int, default=1,
                     help="threads draining the async job queue "
                          "(/v1/sweep)")
    psv.add_argument("--drain-grace-s", type=float, default=60.0,
                     help="SIGTERM drain budget before giving up on "
                          "in-flight work")
    psv.add_argument("--state-dir", default=None, metavar="DIR",
                     help="persist accepted async job specs (and "
                          "campaign journals) here: a restarted daemon "
                          "re-enqueues queued/running jobs and resumes "
                          "campaigns from their last completed scenario")
    psv.add_argument("--cache-quota", default=None, metavar="SIZE",
                     help="bound the shared disk result cache (e.g. "
                          "2G); the daemon AND every serve-worker "
                          "garbage-collect least-recently-used records "
                          "past it (tpusim.guard)")
    psv.add_argument("--compile-cache", nargs="?", const=True,
                     default=None, metavar="DIR",
                     help="durable compiled-module tier shared by the "
                          "daemon and every serve-worker: a cold first "
                          "request against a warm store prices from "
                          "mmapped columns with zero Python IR "
                          "construction (tpusim.fastpath.store)")
    psv.add_argument("--max-rss", default=None, metavar="SIZE",
                     help="daemon memory watchdog hard threshold: past "
                          "it the degradation ladder shrinks caches, "
                          "drops the compiled tier, forces lean "
                          "streaming, then sheds load (503 + "
                          "Retry-After) instead of meeting the "
                          "OOM-killer")
    psv.add_argument("--max-worker-rss", default=None, metavar="SIZE",
                     help="per-worker RSS cap (serve-workers mode): an "
                          "over-budget idle worker is restarted "
                          "deliberately between requests")
    psv.add_argument("--acceptors", type=int, default=0, metavar="N",
                     help="serve v3: N acceptor processes sharing the "
                          "listen port via SO_REUSEPORT (fd-passing "
                          "fallback; TPUSIM_NO_REUSEPORT=1 forces it) — "
                          "each runs its own HTTP parse + admission, so "
                          "no single GIL touches every request "
                          "(default 0: one daemon process)")
    psv.add_argument("--join", default=None, metavar="HOST:PORT",
                     help="serve v4: join the multi-node cluster whose "
                          "primary listens at HOST:PORT — this node "
                          "registers, heartbeats (capped-backoff, "
                          "seeded jitter), serves its consistent-hash "
                          "share of trace affinity, and forwards "
                          "misses one hop to the owner; omit on the "
                          "primary (it materializes the registry on "
                          "the first join it receives)")
    psv.add_argument("--cluster-min-nodes", type=int, default=1,
                     metavar="N",
                     help="shed compute requests (503 + Retry-After) "
                          "while fewer than N cluster members are "
                          "alive — a degraded fleet heals instead of "
                          "melting (default 1: never shed)")
    psv.add_argument("--hot-cache", nargs="?", const=True, default=None,
                     metavar="DIR",
                     help="serve v3: shared mmap hot-response cache "
                          "(default dir .tpusim_hot/) — warm repeat "
                          "simulate requests are answered straight "
                          "from the map: no dispatch, no re-pricing, "
                          "no re-serialization; invalidated by model/"
                          "format/tuned-overlay changes")
    psv.add_argument("--strict-lint", action="store_true",
                     help="refuse (422 + the diagnostics doc) any "
                          "simulate request whose trace-family lint "
                          "passes report errors OR warnings; the "
                          "verdict is cached by content hash, so the "
                          "fleet lints each distinct trace once")
    psv.add_argument("--trace-requests", action="store_true",
                     help="request-scoped tracing: every response "
                          "carries X-Tpusim-Trace, phase spans land in "
                          "a bounded flight recorder (GET /v1/debug/"
                          "traces), and /metrics grows per-route/per-"
                          "phase latency histograms; off = zero "
                          "overhead and byte-identical responses")
    psv.add_argument("--access-log", nargs="?", const=True, default=None,
                     metavar="PATH",
                     help="structured JSONL access log (route, status, "
                          "latency_ms, trace_id, cache tier, acceptor; "
                          "default path <state-dir>/access.jsonl, "
                          "size-rotated); independent of "
                          "--trace-requests")
    psv.add_argument("--verbose", action="store_true",
                     help="per-request access log on stderr")
    psv.set_defaults(fn=_cmd_serve)

    psb = sub.add_parser(
        "serve-bench",
        help="loadgen for the serve daemon: fixture request mix at a "
             "target concurrency -> p50/p95/p99 + throughput vs the "
             "cold one-shot CLI",
    )
    psb.add_argument("--url", default=None,
                     help="target an already-running daemon (default: "
                          "boot one in-process on a free port)")
    psb.add_argument("--trace-root", default=None, metavar="DIR",
                     help="trace root for the self-booted daemon "
                          "(default: the committed test fixtures)")
    psb.add_argument("--concurrency", type=int, default=8)
    psb.add_argument("--requests", type=int, default=64)
    psb.add_argument("--trace", action="append", default=None,
                     help="fixture trace name(s) for the mix (default: "
                          "llama_tiny_tp2dp2 + matmul_512)")
    psb.add_argument("--arch", default="v5p",
                     help="arch for --trace mix entries")
    psb.add_argument("--no-cli-baseline", action="store_true",
                     help="skip the cold-CLI comparison run")
    psb.add_argument("--serve-workers", type=int, default=0, metavar="N",
                     help="boot the self-hosted daemon with N supervised "
                          "worker processes (serve v2; default 0 = "
                          "single-process)")
    psb.add_argument("--worker-sweep", default=None, metavar="N,N,...",
                     help="scaling curve: one warm bench leg per worker "
                          "count (0 = single-process baseline), e.g. "
                          "'0,1,2,4'; overrides --url/--serve-workers")
    psb.add_argument("--acceptor-sweep", default=None, metavar="N,N,...",
                     help="serve v3 scaling curve: one warm leg per "
                          "acceptor count against an out-of-process "
                          "front fleet (0 = single-process baseline "
                          "added automatically), e.g. '1,2,4'; the "
                          "loadgen fans over processes so its GIL "
                          "never caps the measurement")
    psb.add_argument("--no-hot-cache", action="store_true",
                     help="acceptor-sweep legs WITHOUT the shared mmap "
                          "hot-response cache (default: on)")
    psb.add_argument("--loadgen-procs", type=int, default=None,
                     metavar="N",
                     help="loadgen processes for --acceptor-sweep "
                          "(default: min(cores, 4), at least 2)")
    psb.add_argument("--reps", type=int, default=3, metavar="N",
                     help="measured storms per leg; each leg reports its "
                          "best-throughput pass (noisy-neighbor armor; "
                          "errors from every pass still count)")
    psb.add_argument("--json", default=None,
                     help="also write the report document here")
    psb.set_defaults(fn=_cmd_serve_bench)

    pli = sub.add_parser(
        "lint",
        help="static trace/config/schedule analyzer: TLxxx diagnostics "
             "with file:line anchors, before anything is priced",
    )
    pli.add_argument("trace", nargs="?", default=None,
                     help="trace directory to analyze")
    pli.add_argument("--arch", default=None,
                     help="config preset to cross-check (default: the "
                          "arch the trace was captured on)")
    pli.add_argument("--config", action="append",
                     help="overlay flag file(s), applied like simulate's")
    pli.add_argument("--faults", default=None, metavar="SCHEDULE.json",
                     help="fault schedule to validate against the "
                          "trace's declared topology")
    pli.add_argument("--campaign", default=None, metavar="SPEC.json",
                     help="campaign spec to validate (TL21x codes: "
                          "format, candidate slices, SLO percentile, "
                          "correlated-group links); works with or "
                          "without a trace dir")
    pli.add_argument("--advise", default=None, metavar="SPEC.json",
                     help="advise spec to validate (TL22x codes: "
                          "format, unknown strategy, mesh "
                          "factorization, arch presets, SLO without "
                          "candidates); works with or without a "
                          "trace dir")
    pli.add_argument("--format", choices=["text", "json"],
                     default="text",
                     help="diagnostic output format (json is the "
                          "machine-readable document)")
    pli.add_argument("--strict", action="store_true",
                     help="exit nonzero on warnings too, not just "
                          "errors")
    pli.add_argument("--stats-keys", action="store_true",
                     help="also audit the repo's obs_/faults_/ici_ "
                          "stats-key namespaces (ownership, collisions, "
                          "schema agreement); exit 0 when the audit is "
                          "clean, 1 on any error-level finding (the "
                          "same gate as trace diagnostics)")
    pli.add_argument("--self-audit", action="store_true",
                     help="run the TL35x determinism/durability "
                          "self-audit over the repo's own sources "
                          "(unseeded RNG / wall-clock in seeded "
                          "subsystems, os.replace without "
                          "fsync-before-replace staging); exit 1 on "
                          "findings")
    pli.add_argument("--perf", action="store_true",
                     help="also run the TL50x performance passes "
                          "(critical path, slack, exposed-communication "
                          "accounting) priced with the composed config; "
                          "--format json carries the per-module "
                          "critical-path document under a 'perf' key")
    pli.add_argument("--list-codes", action="store_true",
                     help="print the diagnostic registry grouped by "
                          "family with the owning pass module, and "
                          "exit")
    pli.set_defaults(fn=_cmd_lint)

    ppr = sub.add_parser(
        "perf-report",
        help="static perf verdict for a trace: ranked exposed-collective "
             "and slack tables from the critical-path analyzer, plus the "
             "TL5xx diagnostics",
    )
    ppr.add_argument("trace", help="trace directory to analyze")
    ppr.add_argument("--arch", default=None,
                     help="config preset to price with (default: the "
                          "arch the trace was captured on)")
    ppr.add_argument("--config", action="append",
                     help="overlay flag file(s), applied like simulate's")
    ppr.add_argument("--module", default=None,
                     help="report only this module (default: all)")
    ppr.add_argument("--top", type=int, default=10,
                     help="rows per ranked table (default 10)")
    ppr.add_argument("--format", choices=["text", "json"],
                     default="text",
                     help="text tables or the raw perf document")
    ppr.set_defaults(fn=_cmd_perf_report)

    pi = sub.add_parser("info", help="describe a stored trace")
    pi.add_argument("trace")
    pi.set_defaults(fn=_cmd_info)

    pt = sub.add_parser(
        "tune", help="fit arch parameters on the local chip (tuner)"
    )
    pt.add_argument("--arch", default=None)
    pt.add_argument(
        "--out", default="auto",
        help="overlay destination; 'auto' (default) = the canonical "
             "configs/<arch>.tuned.flags that load_config applies; "
             "'' disables writing",
    )
    pt.add_argument("--power", action="store_true",
                    help="fit power coefficients instead (telemetry when "
                         "available, anchor fixtures otherwise)")
    pt.set_defaults(fn=_cmd_tune)

    pf = sub.add_parser(
        "refine",
        help="joint-fit arch knobs against committed silicon fixtures "
             "(offline; coordinate descent on the replay objective)",
    )
    pf.add_argument(
        "--fixtures", default="reports/silicon",
        help="fixture dir with manifest.json (default: reports/silicon)",
    )
    pf.add_argument("--arch", default=None)
    pf.add_argument("--seed", default=None,
                    help="overlay flag file to seed the search from")
    pf.add_argument("--sweeps", type=int, default=6)
    pf.add_argument("--out", default=None,
                    help="write the refined overlay here")
    pf.add_argument(
        "--per-op-artifact", default="reports/correl_ops.json",
        help="per-op artifact whose device rows join the objective",
    )
    pf.add_argument(
        "--no-per-op", action="store_true",
        help="fit on end-to-end totals only (the pre-round-5 objective)",
    )
    pf.add_argument(
        "--anchor", type=float, default=1.0,
        help="quadratic penalty on relative knob drift from the seed "
             "(physical-prior regularization; 0 disables)",
    )
    pf.set_defaults(fn=_cmd_refine)

    psd = sub.add_parser(
        "stats-diff",
        help="diff two scraped run trees (merge-stats compare role)",
    )
    psd.add_argument("old", help="run dir of the baseline")
    psd.add_argument("new", help="run dir of the candidate")
    psd.add_argument("--rel-tol", type=float, default=0.0,
                     help="relative tolerance for numeric stats")
    psd.add_argument("--check", action="store_true",
                     help="exit 1 when any difference is found (CI gate)")
    psd.set_defaults(fn=_cmd_stats_diff)

    pw = sub.add_parser("workloads", help="list registered workloads")
    pw.set_defaults(fn=_cmd_workloads)

    pd = sub.add_parser(
        "debug", help="single-step a trace module (gdb-style)"
    )
    pd.add_argument("trace")
    pd.add_argument("--module", default=None)
    pd.add_argument("--arch", default=None)
    pd.set_defaults(fn=_cmd_debug)

    pv = sub.add_parser(
        "timeline", help="export a module's op timeline as Chrome trace JSON"
    )
    pv.add_argument("trace")
    pv.add_argument("out")
    pv.add_argument("--module", default=None)
    pv.add_argument("--arch", default=None)
    pv.add_argument("--counters", action="store_true",
                    help="merge sampled counter tracks (mxu_util, "
                         "hbm_gbps, ...) into the trace")
    pv.add_argument("--obs-window-cycles", type=float, default=0.0)
    pv.set_defaults(fn=_cmd_timeline)

    pa = sub.add_parser(
        "aerial",
        help="interval-sampled unit-utilization time series "
             "(the AerialVision-style time-lapse view)",
    )
    pa.add_argument("trace")
    pa.add_argument("--module", default=None)
    pa.add_argument("--arch", default=None)
    pa.add_argument("--sample", type=float, default=0,
                    help="window size in cycles (default: stat_sample_cycles)")
    pa.add_argument("--gz", default=None,
                    help="also write the gzip'd JSONL interval log here")
    pa.add_argument("--power", action="store_true",
                    help="add a TPUWattch power-over-time lane")
    pa.set_defaults(fn=_cmd_aerial)

    pb = sub.add_parser(
        "bbv",
        help="per-interval opcode vectors for SimPoint phase sampling "
             "(the bbv_tool equivalent)",
    )
    pb.add_argument("trace")
    pb.add_argument("--module", default=None)
    pb.add_argument("--interval", type=int, default=1000)
    pb.add_argument("--out", default=None,
                    help="write SimPoint .bb frequency vectors here")
    pb.set_defaults(fn=_cmd_bbv)

    po = sub.add_parser(
        "occupancy",
        help="MXU tile / vmem occupancy per matmul-shaped op "
             "(the occupancy_calc_tool equivalent)",
    )
    po.add_argument("trace")
    po.add_argument("--module", default=None)
    po.add_argument("--arch", default=None)
    po.add_argument("--limit", type=int, default=10)
    po.set_defaults(fn=_cmd_occupancy)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except (KeyError, FileNotFoundError, ValueError) as e:
        print(f"tpusim: error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
