"""tpusim.advise — parallelism-strategy sweep & sharding advisor.

Answers "how should I run it" for one traced workload: sweep the
cross-product of pod slices (arch x chips) x parallelism strategies
(dp / tp / dp x tp / sp ring attention / pp pipeline / ep expert,
plus user-pinned mesh combos), price every cell through the shared
engine-result cache on a modeled torus, and emit a ranked
step-time / ICI-bytes / HBM-residency / watts table with the
recommended sharding.  Reached via ``tpusim advise`` and the async
``POST /v1/advise`` serve job.
"""

from tpusim.advise.runner import (
    ADVISE_FORMAT_VERSION,
    AdviseResult,
    AdviseStats,
    run_advise,
)
from tpusim.advise.spec import (
    AdviseSpec,
    AdviseSpecError,
    STRATEGIES,
    load_advise_spec,
    spec_hash,
)
from tpusim.advise.transform import (
    CollectiveSite,
    WorkloadProfile,
    build_cell_pod,
    build_profile,
    scaled_module,
)

__all__ = [
    "ADVISE_FORMAT_VERSION",
    "AdviseResult",
    "AdviseSpec",
    "AdviseSpecError",
    "AdviseStats",
    "CollectiveSite",
    "STRATEGIES",
    "WorkloadProfile",
    "build_cell_pod",
    "build_profile",
    "load_advise_spec",
    "run_advise",
    "scaled_module",
    "spec_hash",
]
